"""ONNX converter breadth: export→real-bytes→import round-trip numerics.

Each case builds an mx graph, exports it through the hand-written protobuf
wire format (no wheel), imports it back, and compares outputs — the
strongest self-check available offline.  Reference converter tables:
``mx2onnx/_op_translations.py`` (98 export),
``onnx2mx/_import_helper.py`` (92 import).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mod


def _roundtrip(sym, params, inputs, rtol=1e-5, atol=1e-6):
    """Export through real bytes, re-import, compare forward outputs."""
    shapes = {k: v.shape for k, v in inputs.items()}
    g = onnx_mod.export_graph(sym, params, shapes)
    data = onnx_mod.graph_to_bytes(g)
    sym2, arg2, aux2 = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))

    def run(s, p):
        binds = {k: mx.nd.array(v) for k, v in inputs.items()}
        for k, v in p.items():
            binds[k] = v if isinstance(v, mx.nd.NDArray) else mx.nd.array(v)
        aux = {k: binds.pop(k) for k in list(binds)
               if k in s.list_auxiliary_states()}
        ex = s.bind(mx.cpu(), binds, aux_states=aux)
        return [o.asnumpy() for o in ex.forward()]

    want = run(sym, params)
    got = run(sym2, {**arg2, **aux2})
    assert len(want) == len(got)
    for w, g_ in zip(want, got):
        np.testing.assert_allclose(w, g_, rtol=rtol, atol=atol)


_R = np.random.RandomState(11)
_X24 = _R.randn(2, 4).astype("float32")
_X234 = _R.randn(2, 3, 4).astype("float32")
_POS = (_R.rand(2, 4).astype("float32") + 0.5)
_UNIT = (_R.rand(2, 4).astype("float32") * 1.8 - 0.9)

_UNARY_CASES = [
    ("reciprocal", _POS), ("ceil", _X24), ("floor", _X24),
    ("sin", _X24), ("cos", _X24), ("tan", _UNIT),
    ("arcsin", _UNIT), ("arccos", _UNIT), ("arctan", _X24),
    ("sinh", _UNIT), ("cosh", _UNIT), ("square", _X24),
    ("logical_not", (_X24 > 0).astype("float32")),
    ("log_softmax", _X24), ("hard_sigmoid", _X24),
    ("sign", _X24), ("round", _X24 * 3),
]


@pytest.mark.parametrize("op,x", _UNARY_CASES,
                         ids=[c[0] for c in _UNARY_CASES])
def test_unary_roundtrip(op, x):
    data = mx.sym.var("data")
    _roundtrip(getattr(mx.sym, op)(data, name=f"{op}0"), {}, {"data": x})


_BINARY_CASES = [
    "broadcast_equal", "broadcast_greater", "broadcast_lesser",
    "broadcast_power", "_maximum", "_minimum",
]


@pytest.mark.parametrize("op", _BINARY_CASES)
def test_binary_roundtrip(op):
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    x = _R.randint(0, 3, (2, 4)).astype("float32")
    y = _R.randint(0, 3, (2, 4)).astype("float32")
    if op == "broadcast_power":
        x = np.abs(x) + 0.5
    _roundtrip(getattr(mx.sym, op)(a, b, name=f"{op}0"), {},
               {"a": x, "b": y})


@pytest.mark.parametrize("op", ["broadcast_logical_and",
                                "broadcast_logical_or",
                                "broadcast_logical_xor"])
def test_logical_roundtrip(op):
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    x = _R.randint(0, 2, (2, 4)).astype("float32")
    y = _R.randint(0, 2, (2, 4)).astype("float32")
    _roundtrip(getattr(mx.sym, op)(a, b, name=f"{op}0"), {},
               {"a": x, "b": y})


@pytest.mark.parametrize("op", ["sum", "max", "min", "prod"])
@pytest.mark.parametrize("kw", [{"axis": 1}, {"axis": (0, 2)},
                                {"axis": 1, "keepdims": True}],
                         ids=["ax1", "ax02", "keep"])
def test_reduce_roundtrip(op, kw):
    data = mx.sym.var("data")
    _roundtrip(getattr(mx.sym, op)(data, name=f"{op}0", **kw), {},
               {"data": _X234 if op != "prod" else np.abs(_X234) + 0.1})


@pytest.mark.parametrize("ordv", [1, 2])
def test_norm_roundtrip(ordv):
    data = mx.sym.var("data")
    _roundtrip(mx.sym.norm(data, ord=ordv, axis=1, name="n0"), {},
               {"data": _X234})


@pytest.mark.parametrize("op", ["argmax", "argmin"])
def test_arg_roundtrip(op):
    data = mx.sym.var("data")
    _roundtrip(getattr(mx.sym, op)(data, axis=1, name=f"{op}0"), {},
               {"data": _X234})


def test_add_n_roundtrip():
    xs = [mx.sym.var(f"x{i}") for i in range(3)]
    _roundtrip(mx.sym.add_n(*xs, name="an0"), {},
               {f"x{i}": _R.randn(2, 3).astype("float32")
                for i in range(3)})


def test_shape_size_roundtrip():
    data = mx.sym.var("data")
    _roundtrip(mx.sym.Group([mx.sym.shape_array(data, name="sh0"),
                             mx.sym.size_array(data, name="sz0")]),
               {}, {"data": _X234})


def test_squeeze_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(2, 1, 4, 1).astype("float32")
    _roundtrip(mx.sym.squeeze(data, axis=(1, 3), name="sq0"), {},
               {"data": x})


def test_broadcast_to_tile_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(2, 1, 4).astype("float32")
    _roundtrip(mx.sym.broadcast_to(data, shape=(2, 3, 4), name="bt0"), {},
               {"data": x})
    _roundtrip(mx.sym.tile(data, reps=(1, 2, 3), name="tl0"), {},
               {"data": x})


def test_depth_space_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(1, 8, 2, 2).astype("float32")
    _roundtrip(mx.sym.depth_to_space(data, block_size=2, name="d2s0"), {},
               {"data": x})
    x2 = _R.randn(1, 2, 4, 4).astype("float32")
    _roundtrip(mx.sym.space_to_depth(data, block_size=2, name="s2d0"), {},
               {"data": x2})


def test_pad_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(1, 2, 4, 4).astype("float32")
    for mode in ("constant", "edge", "reflect"):
        kw = {"constant_value": 1.5} if mode == "constant" else {}
        _roundtrip(mx.sym.pad(data, mode=mode,
                              pad_width=(0, 0, 0, 0, 1, 2, 2, 1),
                              name="pd0", **kw), {}, {"data": x})


def test_lrn_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(1, 6, 4, 4).astype("float32")
    _roundtrip(mx.sym.LRN(data, nsize=3, alpha=1e-3, beta=0.7, knorm=1.5,
                          name="lrn0"), {}, {"data": x}, rtol=1e-4)


def test_instance_norm_roundtrip():
    data = mx.sym.var("data")
    g = mx.sym.var("g0_gamma")
    b = mx.sym.var("g0_beta")
    x = _R.randn(2, 3, 5, 5).astype("float32")
    _roundtrip(mx.sym.InstanceNorm(data, g, b, eps=1e-4, name="in0"),
               {"g0_gamma": _R.rand(3).astype("float32") + 0.5,
                "g0_beta": _R.randn(3).astype("float32")},
               {"data": x}, rtol=1e-4, atol=1e-5)


def test_l2_normalization_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(2, 3, 5).astype("float32")
    _roundtrip(mx.sym.L2Normalization(data, mode="channel", name="l2n0"),
               {}, {"data": x}, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("squeeze_axis", [False, True])
def test_slice_channel_roundtrip(squeeze_axis):
    data = mx.sym.var("data")
    x = _R.randn(2, 3, 4).astype("float32")
    s = mx.sym.SliceChannel(data, num_outputs=3, axis=1,
                            squeeze_axis=squeeze_axis, name="sc0")
    _roundtrip(mx.sym.Group([s[0], s[1], s[2]]), {}, {"data": x})


def test_roi_pooling_roundtrip():
    data = mx.sym.var("data")
    rois = mx.sym.var("rois")
    x = _R.rand(1, 2, 8, 8).astype("float32")
    r = np.asarray([[0, 0, 0, 4, 4], [0, 2, 2, 7, 7]], dtype="float32")
    _roundtrip(mx.sym.ROIPooling(data, rois, pooled_size=(2, 2),
                                 spatial_scale=1.0, name="roi0"),
               {}, {"data": x, "rois": r})


def test_logistic_and_makeloss_roundtrip():
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    s = mx.sym.LogisticRegressionOutput(data, label, name="lro0")
    # label is a dropped training input — export side only keeps data
    g = onnx_mod.export_graph(s, {}, {"data": (2, 4)})
    assert [n["op_type"] for n in g["nodes"]] == ["Sigmoid"]
    sym2, arg2, aux2 = onnx_mod.import_graph(
        onnx_mod.graph_from_bytes(onnx_mod.graph_to_bytes(g)))
    ex = sym2.bind(mx.cpu(), {"data": mx.nd.array(_X24)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               1 / (1 + np.exp(-_X24)), rtol=1e-5)

    m = mx.sym.MakeLoss(mx.sym.square(data), name="ml0")
    g2 = onnx_mod.export_graph(m, {}, {"data": (2, 4)})
    assert g2["nodes"][-1]["op_type"] == "Identity"


def test_linalg_gemm2_roundtrip():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    x = _R.randn(3, 4).astype("float32")
    y = _R.randn(4, 5).astype("float32")
    s = getattr(mx.sym, "_linalg_gemm2")(a, b, alpha=2.5, name="g20")
    _roundtrip(s, {}, {"a": x, "b": y}, rtol=1e-5)


def test_power_scalar_roundtrip():
    data = mx.sym.var("data")
    s = getattr(mx.sym, "_power_scalar")(data, scalar=3.0, name="ps0")
    _roundtrip(s, {}, {"data": _POS})


def test_crop_roundtrip():
    data = mx.sym.var("data")
    x = _R.randn(1, 2, 8, 8).astype("float32")
    s = mx.sym.Crop(data, offset=(1, 2), h_w=(4, 5), name="cr0")
    _roundtrip(s, {}, {"data": x})


def test_random_ops_export_structure():
    """Numerics can't round-trip for samplers; pin the emitted/imported
    structure and output shapes instead."""
    s = getattr(mx.sym, "_random_uniform")(low=2.0, high=3.0, shape=(2, 3),
                                           name="ru0")
    g = onnx_mod.export_graph(s, {}, {})
    assert g["nodes"][0]["op_type"] == "RandomUniform"
    sym2, _, _ = onnx_mod.import_graph(
        onnx_mod.graph_from_bytes(onnx_mod.graph_to_bytes(g)))
    out = sym2.bind(mx.cpu(), {}).forward()[0].asnumpy()
    assert out.shape == (2, 3) and (out >= 2.0).all() and (out < 3.0).all()

    s = getattr(mx.sym, "_sample_multinomial")(
        mx.sym.var("p"), shape=8, name="sm0")
    g = onnx_mod.export_graph(s, {}, {"p": (2, 5)})
    assert any(n["op_type"] == "Multinomial" for n in g["nodes"])


def test_mean_n_import():
    """ONNX Mean (variadic) has no 1:1 mx op — imports as add_n/n."""
    from mxnet_tpu.contrib.onnx import protobuf as pb
    data = pb.model_to_bytes({
        "nodes": [{"op_type": "Mean", "name": "m",
                   "inputs": ["a", "b", "c"], "outputs": ["y"],
                   "attrs": {}}],
        "inputs": [{"name": n, "dtype": "float32", "shape": (2, 3)}
                   for n in "abc"],
        "outputs": [{"name": "y"}], "initializers": {}})
    sym, arg, aux = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
    xs = {n: _R.randn(2, 3).astype("float32") for n in "abc"}
    ex = sym.bind(mx.cpu(), {k: mx.nd.array(v) for k, v in xs.items()})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               (xs["a"] + xs["b"] + xs["c"]) / 3,
                               rtol=1e-6)


def test_reduce_extras_import():
    from mxnet_tpu.contrib.onnx import protobuf as pb
    x = _R.rand(2, 3, 4).astype("float32") + 0.1
    for op, ref in [
        ("ReduceLogSum", lambda a: np.log(a.sum(axis=1))),
        ("ReduceLogSumExp", lambda a: np.log(np.exp(a).sum(axis=1))),
        ("ReduceSumSquare", lambda a: (a * a).sum(axis=1)),
        ("ReduceL1", lambda a: np.abs(a).sum(axis=1)),
        ("ReduceL2", lambda a: np.sqrt((a * a).sum(axis=1))),
        ("ReduceProd", lambda a: a.prod(axis=1)),
    ]:
        data = pb.model_to_bytes({
            "nodes": [{"op_type": op, "name": "r", "inputs": ["x"],
                       "outputs": ["y"],
                       "attrs": {"axes": (1,), "keepdims": 0}}],
            "inputs": [{"name": "x", "dtype": "float32", "shape": (2, 3, 4)}],
            "outputs": [{"name": "y"}], "initializers": {}})
        sym, _, _ = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
        got = sym.bind(mx.cpu(), {"x": mx.nd.array(x)}).forward()[0]
        np.testing.assert_allclose(got.asnumpy(), ref(x), rtol=1e-5,
                                   atol=1e-6, err_msg=op)


def test_variadic_max_min_import():
    from mxnet_tpu.contrib.onnx import protobuf as pb
    xs = {n: _R.randn(2, 3).astype("float32") for n in "abc"}
    for op, ref in [("Max", np.maximum), ("Min", np.minimum)]:
        data = pb.model_to_bytes({
            "nodes": [{"op_type": op, "name": "m",
                       "inputs": ["a", "b", "c"], "outputs": ["y"],
                       "attrs": {}}],
            "inputs": [{"name": n, "dtype": "float32", "shape": (2, 3)}
                       for n in "abc"],
            "outputs": [{"name": "y"}], "initializers": {}})
        sym, _, _ = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
        ex = sym.bind(mx.cpu(), {k: mx.nd.array(v) for k, v in xs.items()})
        np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                                   ref(ref(xs["a"], xs["b"]), xs["c"]),
                                   rtol=1e-6)


def test_lp_pool_import():
    from mxnet_tpu.contrib.onnx import protobuf as pb
    x = _R.rand(1, 2, 6, 6).astype("float32")
    data = pb.model_to_bytes({
        "nodes": [{"op_type": "LpPool", "name": "lp", "inputs": ["x"],
                   "outputs": ["y"],
                   "attrs": {"kernel_shape": (2, 2), "strides": (2, 2),
                             "p": 2}}],
        "inputs": [{"name": "x", "dtype": "float32", "shape": (1, 2, 6, 6)}],
        "outputs": [{"name": "y"}], "initializers": {}})
    sym, _, _ = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
    got = sym.bind(mx.cpu(), {"x": mx.nd.array(x)}).forward()[0].asnumpy()
    want = np.sqrt((x ** 2).reshape(1, 2, 3, 2, 3, 2).sum((3, 5)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_converter_table_size():
    """Breadth pin: table sizes must not regress (reference: 98/92)."""
    from mxnet_tpu.contrib.onnx.mx2onnx import _MX2ONNX
    from mxnet_tpu.contrib.onnx.onnx2mx import _ONNX2MX
    assert len(_MX2ONNX) >= 95, len(_MX2ONNX)
    assert len(_ONNX2MX) >= 85, len(_ONNX2MX)
