"""mxnet_tpu.analysis: static checkers + runtime sanitizer (ISSUE 8).

Static side: each checker has a seeded true-positive proving it fires, a
negative showing the matching safe idiom stays quiet, fingerprint
stability, the baseline workflow, and a whole-tree gate against the
checked-in baseline.  Runtime side: planted use-after-donate (aggregated
optimizer group) and post-release shm-slot reads must raise with the
originating site named, and the clean paths must pass under
``MXNET_SANITIZE`` with zero findings.
"""
import ast
import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import core, sanitizer as san
from mxnet_tpu.optimizer import aggregate
from mxnet_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mxnet_tpu")
BASELINE = os.path.join(REPO, "ci", "analysis_baseline.txt")


def run_checker(src, checker, path="mxnet_tpu/fake.py"):
    src = textwrap.dedent(src)
    mod = core.SourceModule(path, src, ast.parse(src))
    return core._checker_table()[checker](mod)


def rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------------ donation
class TestDonationChecker:
    def test_direct_jit_donation_fires(self):
        fs = run_checker("""
            import jax
            def step(w, g):
                fn = jax.jit(lambda a, b: a - b, donate_argnums=(0,))
                out = fn(w, g)
                return out + w.sum()
            """, "donation")
        assert rules(fs) == {"use-after-donate"}
        assert fs[0].symbol == "w"
        assert "donated" in fs[0].message

    def test_rebind_suppresses(self):
        fs = run_checker("""
            import jax
            def step(w, g):
                fn = jax.jit(lambda a, b: a - b, donate_argnums=(0,))
                w = fn(w, g)
                return w.sum()
            """, "donation")
        assert fs == []

    def test_nondonating_position_ok(self):
        fs = run_checker("""
            import jax
            def step(w, g):
                fn = jax.jit(lambda a, b: a - b, donate_argnums=(0,))
                out = fn(w, g)
                return out + g.sum()     # g (arg 1) was NOT donated
            """, "donation")
        assert fs == []

    def test_factory_and_cache_laundering(self):
        # the optimizer/aggregate.py idiom: a factory returns the donated
        # jit, a dict caches it, the call site reads it back with .get
        fs = run_checker("""
            import jax
            _compiled = {}
            def build():
                return jax.jit(lambda a: a * 2, donate_argnums=(0,))
            def apply(key, w):
                fn = _compiled.get(key)
                if fn is None:
                    fn = build()
                    _compiled[key] = fn
                out = fn(w)
                return w.sum()
            """, "donation")
        assert rules(fs) == {"use-after-donate"}
        assert fs[0].scope == "apply" and fs[0].symbol == "w"

    def test_conditional_argnums_and_star_args(self):
        fs = run_checker("""
            import jax
            def go(consts, flag):
                fn = jax.jit(lambda *a: a[0],
                             donate_argnums=(0,) if flag else ())
                outs = fn(*consts)
                return len(consts)
            """, "donation")
        assert rules(fs) == {"use-after-donate"}
        assert fs[0].symbol == "consts"


# ------------------------------------------------------------------- capture
class TestCaptureChecker:
    def test_tracer_escape_and_materialize_in_jit(self):
        fs = run_checker("""
            import jax
            class M:
                def f(self, x):
                    def body(a):
                        self.saved = a
                        return a.asnumpy()
                    return jax.jit(body)(x)
            """, "capture")
        assert rules(fs) == {"tracer-escape-self", "materialize-in-jit"}

    def test_closure_mutation_fires(self):
        fs = run_checker("""
            import jax
            def outer(xs):
                leaked = []
                @jax.jit
                def body(a):
                    leaked.append(a)
                    return a
                return [body(x) for x in xs], leaked
            """, "capture")
        assert "tracer-escape-closure" in rules(fs)

    def test_method_name_collision_does_not_fire(self):
        # jax.jit(step) must not taint an unrelated METHOD named `step`
        fs = run_checker("""
            import jax
            def make():
                def step(s, x):
                    return s + x
                return jax.jit(step)
            class Trainer:
                def step(self, x):
                    self._t += 1
                    return x
            """, "capture")
        assert fs == []

    def test_registered_op_materialization(self):
        fs = run_checker("""
            from .registry import register
            @register("bad_op")
            def bad_op(x, axis=None):
                if x:
                    return float(x)
                return x.asnumpy()
            """, "capture", path="mxnet_tpu/ops/fake_ops.py")
        assert rules(fs) == {"bool-coerce-in-op", "materialize-in-op"}

    def test_registered_op_attr_branch_ok(self):
        fs = run_checker("""
            from .registry import register
            @register("good_op")
            def good_op(x, axis=None, keepdims=False):
                if keepdims:
                    return x * 2
                return x
            """, "capture", path="mxnet_tpu/ops/fake_ops.py")
        assert fs == []


# ----------------------------------------------------------------- recompile
class TestRecompileChecker:
    def test_jit_in_loop_and_per_step_attr(self):
        fs = run_checker("""
            import jax
            def train(xs):
                for i, x in enumerate(xs):
                    f = jax.jit(lambda a: a * 2)
                    invoke_op("scale", [x], {"t": i})
            """, "recompile")
        assert rules(fs) == {"jit-in-loop", "per-step-attr"}

    def test_counterish_attr_fires(self):
        fs = run_checker("""
            def step(self, x):
                return invoke_op("foo", [x], {"n": self._step_count})
            """, "recompile")
        assert rules(fs) == {"per-step-attr"}

    def test_float_cache_key(self):
        fs = run_checker("""
            def lookup(self, loss):
                return self._compiled.get(f"k{float(loss)}")
            """, "recompile")
        assert rules(fs) == {"unstable-cache-key"}

    def test_jit_outside_loop_ok(self):
        fs = run_checker("""
            import jax
            def train(xs):
                f = jax.jit(lambda a: a * 2)
                for x in xs:
                    f(x)
            """, "recompile")
        assert fs == []


# --------------------------------------------------------------------- locks
class TestLocksChecker:
    def test_unlocked_shared_attr_fires(self):
        fs = run_checker("""
            import threading
            class B:
                def start(self):
                    self._th = threading.Thread(target=self._worker_loop)
                def _worker_loop(self):
                    self.count += 1
                def poll(self):
                    self.count = 0
            """, "locks")
        assert rules(fs) == {"unlocked-shared-mutation"}
        assert fs[0].symbol == "self.count"

    def test_locked_both_sides_ok(self):
        fs = run_checker("""
            import threading
            class B:
                def start(self):
                    self._th = threading.Thread(target=self._worker_loop)
                def _worker_loop(self):
                    with self._lock:
                        self.count += 1
                def poll(self):
                    with self._lock:
                        self.count = 0
            """, "locks")
        assert fs == []

    def test_init_only_main_mutation_ok(self):
        # construct-before-start is a handshake, not a race
        fs = run_checker("""
            import threading
            class B:
                def __init__(self):
                    self.count = 0
                    self._th = threading.Thread(target=self._worker_loop)
                def _worker_loop(self):
                    self.count += 1
            """, "locks")
        assert fs == []

    def test_module_global_fires(self):
        fs = run_checker("""
            import threading
            total = 0
            def worker_body():
                global total
                total += 1
            def drain():
                global total
                total = 0
            threading.Thread(target=worker_body)
            """, "locks")
        assert rules(fs) == {"unlocked-shared-mutation"}
        assert fs[0].scope == "<module>"

    def test_transitive_worker_reach(self):
        fs = run_checker("""
            import threading
            class B:
                def start(self):
                    self._th = threading.Thread(target=self._worker_loop)
                def _worker_loop(self):
                    self._bump()
                def _bump(self):
                    self.count += 1
                def poll(self):
                    self.count = 0
            """, "locks")
        assert rules(fs) == {"unlocked-shared-mutation"}


# --------------------------------------------------------------- collectives
class TestCollectivesChecker:
    def test_divergent_branch_fires(self):
        fs = run_checker("""
            import jax
            from jax import lax
            def step(x):
                if jax.process_index() == 0:
                    return lax.psum(x, "dp")
                return x
            """, "collectives")
        assert rules(fs) == {"divergent-collective"}
        assert fs[0].symbol == "lax.psum"

    def test_taint_flows_through_reader_and_unpack(self):
        # the checkpoint _hosts() idiom: identity read in a helper, tuple-
        # unpacked at the call site, branched on later
        fs = run_checker("""
            import jax
            from jax import lax
            def _hosts():
                return jax.process_index(), 2
            def save(x):
                h, n = _hosts()
                if h == 0:
                    x = lax.all_gather(x, "dp")
                return x
            """, "collectives")
        assert rules(fs) == {"divergent-collective"}
        assert fs[0].scope == "save"

    def test_process_count_branch_is_uniform(self):
        # the num_workers > 1 degenerate-path idiom: process_count() is the
        # same value on every host, so the branch cannot diverge
        fs = run_checker("""
            import jax
            from jax import lax
            def push(x):
                if jax.process_count() > 1:
                    x = lax.psum(x, "dp")
                return x
            """, "collectives")
        assert fs == []

    def test_symmetric_branches_quiet(self):
        # both arms issue the identical collective sequence: same ops on
        # every host regardless of the divergent test (operand values may
        # differ — psum pairs by op+axis, not by value)
        fs = run_checker("""
            import time
            from jax import lax
            def f(x):
                if time.time() > 5:
                    y = lax.psum(x, "dp")
                else:
                    y = lax.psum(x * 2, "dp")
                return y
            """, "collectives")
        assert fs == []

    def test_same_op_different_axis_fires(self):
        # NOT symmetric: psum over different axes pairs against different
        # peer groups — hosts taking different arms deadlock
        fs = run_checker("""
            import jax
            from jax import lax
            def f(x):
                if jax.process_index() == 0:
                    y = lax.psum(x, "dp")
                else:
                    y = lax.psum(x, "tp")
                return y
            """, "collectives")
        assert rules(fs) == {"divergent-collective"}

    def test_nested_def_reports_once_in_inner_scope(self):
        # scope_functions yields nested defs as their own scopes; the
        # outer scope's walk must not double-report the inner's finding
        # under a second fingerprint
        fs = run_checker("""
            import jax
            from jax import lax
            def outer(xs):
                def inner(x):
                    if jax.process_index() == 0:
                        return lax.psum(x, "dp")
                    return x
                return [inner(x) for x in xs]
            """, "collectives")
        assert len(fs) == 1
        assert fs[0].scope == "outer.inner"

    def test_env_and_filesystem_divergent(self):
        fs = run_checker("""
            import os
            from jax import lax
            def f(x, path):
                if os.environ.get("ROLE") == "leader":
                    x = lax.psum(x, "dp")
                if os.path.exists(path):
                    x = lax.all_gather(x, "dp")
                return x
            """, "collectives")
        assert len(fs) == 2
        assert rules(fs) == {"divergent-collective"}

    def test_unordered_iteration_fires_sorted_quiet(self):
        fs = run_checker("""
            def sync(kv, grads):
                for k, g in grads.items():
                    kv.push(k, g)
            def sync_ok(kv, grads):
                for k, g in sorted(grads.items()):
                    kv.push(k, g)
            """, "collectives")
        assert rules(fs) == {"unordered-collective-order"}
        assert [f.scope for f in fs] == ["sync"]

    def test_set_iteration_over_collective_fires(self):
        fs = run_checker("""
            from jax import lax
            def reduce_all(xs):
                done = set(xs)
                for k in done:
                    lax.psum(k, "dp")
            """, "collectives")
        assert rules(fs) == {"unordered-collective-order"}

    def test_retry_over_collective_fires_transitively(self):
        # the kvstore bug class this PR fixed: the retried hop reaches a
        # collective two calls deep
        fs = run_checker("""
            from jax import lax
            class KV:
                def _hop(self, x):
                    return self._allreduce(x)
                def _allreduce(self, x):
                    return lax.psum(x, "dp")
                def push(self, x):
                    return self._retry.call(self._hop, x)
                def pull(self, x):
                    return self._retry.call(self._copy, x)
                def _copy(self, x):
                    return x
            """, "collectives")
        assert rules(fs) == {"retry-over-collective"}
        assert [(f.scope, f.symbol) for f in fs] == [("KV.push", "_hop")]

    def test_fault_scope_wrapping_collective_fires(self):
        fs = run_checker("""
            from jax import lax
            def drill(x, faults):
                with faults.scope("kvstore.push:fail:1"):
                    return lax.psum(x, "dp")
            """, "collectives")
        assert rules(fs) == {"retry-over-collective"}

    def test_fingerprint_stable_across_line_shifts(self):
        src = """
            import jax
            from jax import lax
            def step(x):
                if jax.process_index() == 0:
                    return lax.psum(x, "dp")
                return x
            """
        a = run_checker(src, "collectives")
        b = run_checker("# pad\n# pad\n\n" + textwrap.dedent(src),
                        "collectives")
        assert a[0].fingerprint == b[0].fingerprint
        assert a[0].line != b[0].line


# ------------------------------------------------------------------ barriers
class TestBarriersChecker:
    def test_commit_before_barrier_fires(self):
        fs = run_checker("""
            def save_sharded(self, d, step):
                self._write_host_files(d, step)
                self._commit_sharded(d, step)
                markers = self._wait_markers(d, step)
            """, "barriers")
        assert rules(fs) == {"commit-before-barrier"}

    def test_commit_without_barrier_fires(self):
        fs = run_checker("""
            def save_sharded(self, d, step):
                self._write_host_files(d, step)
                self._commit_sharded(d, step)
            """, "barriers")
        assert rules(fs) == {"commit-before-barrier"}

    def test_retry_wrapped_commit_before_barrier_fires(self):
        # the in-tree pattern: commit goes through RetryPolicy.call —
        # classification must see through the wrapper or a reordered
        # retry-wrapped commit is invisible to the rule
        fs = run_checker("""
            def save_sharded(self, d, step):
                self._retry.call(self._write_host_files, d, step)
                self._retry.call(self._commit_sharded, d, step)
                markers = self._wait_markers(d, step)
            """, "barriers")
        assert rules(fs) == {"commit-before-barrier"}

    def test_retry_wrapped_proper_order_quiet(self):
        fs = run_checker("""
            def save_sharded(self, d, step):
                self._retry.call(self._write_host_files, d, step)
                markers = self._wait_markers(d, step)
                self._retry.call(self._commit_sharded, d, step, markers)
            """, "barriers")
        assert fs == []

    def test_proper_two_phase_order_quiet(self):
        fs = run_checker("""
            def save_sharded(self, d, step):
                self._write_host_files(d, step)
                markers = self._wait_markers(d, step)
                self._commit_sharded(d, step, markers)
            """, "barriers")
        assert fs == []

    def test_single_host_commit_exempt(self):
        # no phase-1 shard/marker writes in scope: a plain single-host
        # commit needs no barrier
        fs = run_checker("""
            def save(self, step, blob):
                self._commit_step(step, blob)
            """, "barriers")
        assert fs == []

    def test_exit_between_collectives_fires(self):
        fs = run_checker("""
            import sys
            from jax import lax
            def bad(self, x):
                y = lax.psum(x, "dp")
                if self.handler.triggered:
                    sys.exit(0)
                return lax.all_gather(y, "dp")
            """, "barriers")
        assert rules(fs) == {"exit-between-collectives"}

    def test_exit_in_collective_loop_fires(self):
        fs = run_checker("""
            from jax import lax
            def bad_loop(self, xs):
                for x in xs:
                    y = lax.psum(x, "dp")
                    if self.handler.triggered:
                        raise TrainingPreempted()
            """, "barriers")
        assert rules(fs) == {"exit-between-collectives"}
        assert "back-edge" in fs[0].message

    def test_nonprocess_exit_receiver_quiet(self):
        # `.exit()` on anything but sys/os (ExitStack, pools, custom
        # scopes) is not a process exit
        fs = run_checker("""
            from jax import lax
            def f(self, x, stack):
                y = lax.psum(x, "dp")
                stack.exit()
                return lax.all_gather(y, "dp")
            """, "barriers")
        assert fs == []

    def test_bare_exit_between_collectives_fires(self):
        fs = run_checker("""
            from jax import lax
            def f(self, x):
                y = lax.psum(x, "dp")
                if self.done:
                    exit(1)
                return lax.all_gather(y, "dp")
            """, "barriers")
        assert rules(fs) == {"exit-between-collectives"}

    def test_exit_at_step_boundary_quiet(self):
        # the SPMDTrainer.step idiom: consult the flag BEFORE the scope's
        # first collective
        fs = run_checker("""
            from jax import lax
            def step(self, x):
                if self.handler.triggered:
                    raise TrainingPreempted()
                y = lax.psum(x, "dp")
                return lax.all_gather(y, "dp")
            """, "barriers")
        assert fs == []

    def test_fingerprint_stable_across_line_shifts(self):
        src = """
            def save_sharded(self, d, step):
                self._write_host_files(d, step)
                self._commit_sharded(d, step)
            """
        a = run_checker(src, "barriers")
        b = run_checker("# pad\n\n" + textwrap.dedent(src), "barriers")
        assert a[0].fingerprint == b[0].fingerprint
        assert a[0].line != b[0].line


# ------------------------------------------- locks worker-name refinement
class TestLocksWorkerNameRefinement:
    def test_consumer_called_worker_named_method_not_seeded(self):
        # the ProcessDecodePool._check_workers false positive this PR
        # killed from the baseline: a worker-NAMED method only ever
        # invoked as self.name() runs on the caller's thread
        fs = run_checker("""
            import threading
            class Pool:
                def start(self):
                    self._th = threading.Thread(target=self._loop)
                def _loop(self):
                    pass
                def _check_workers(self):
                    self._sticky = RuntimeError("dead")
                def next_batch(self):
                    self._check_workers()
                    self._sticky = None
            """, "locks")
        assert fs == []

    def test_never_called_worker_named_method_still_seeds(self):
        fs = run_checker("""
            import threading
            class Pool:
                def decode_worker(self):
                    self.count += 1
                def poll(self):
                    self.count = 0
            """, "locks")
        assert rules(fs) == {"unlocked-shared-mutation"}

    def test_spawned_and_called_method_still_seeds(self):
        # target= detection wins over the called-via-self exemption
        fs = run_checker("""
            import threading
            class Pool:
                def start(self):
                    self._th = threading.Thread(target=self._worker_loop)
                def kick(self):
                    self._worker_loop()
                def _worker_loop(self):
                    self.count += 1
                def poll(self):
                    self.count = 0
            """, "locks")
        assert rules(fs) == {"unlocked-shared-mutation"}


# ------------------------------------------------- fingerprints and baseline
class TestBaseline:
    SRC = """
        import jax
        def step(w, g):
            fn = jax.jit(lambda a, b: a - b, donate_argnums=(0,))
            out = fn(w, g)
            return out + w.sum()
        """

    def test_fingerprint_stable_across_line_shifts(self):
        a = run_checker(self.SRC, "donation")
        b = run_checker("# shifted\n# down\n\n" + textwrap.dedent(self.SRC),
                        "donation")
        assert a[0].fingerprint == b[0].fingerprint
        assert a[0].line != b[0].line

    def test_fingerprint_distinguishes_scope_and_symbol(self):
        two = run_checker(self.SRC.replace("def step", "def other"),
                          "donation")
        assert two[0].fingerprint != \
            run_checker(self.SRC, "donation")[0].fingerprint

    def test_baseline_roundtrip_and_malformed(self, tmp_path):
        f = run_checker(self.SRC, "donation")[0]
        p = tmp_path / "base.txt"
        p.write_text(core.format_baseline_line(f, "intentional: test") +
                     "\n" + "deadbeef0000  no justification here\n")
        entries, malformed = core.load_baseline(str(p))
        assert entries[f.fingerprint] == "intentional: test"
        assert len(malformed) == 1

    def test_missing_baseline_is_empty(self):
        entries, malformed = core.load_baseline("/nonexistent/file")
        assert entries == {} and malformed == []

    def test_parse_error_becomes_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        mods, errs = core.load_tree(str(bad))
        assert mods == [] and errs[0].rule == "parse-error"


class TestWholeTree:
    def test_subtree_fingerprints_match_whole_tree(self):
        # a --root scoped to one file must produce the same repo-relative
        # paths (and so fingerprints) as the whole-tree pass, or sub-tree
        # runs would break against the shared baseline
        old = os.getcwd()
        os.chdir(REPO)
        try:
            sub = core.run_checkers("mxnet_tpu/io/pipeline.py")
        finally:
            os.chdir(old)
        whole = [f for f in core.run_checkers(PKG, rel_to=REPO)
                 if f.path == "mxnet_tpu/io/pipeline.py"]
        assert {f.fingerprint for f in sub} == \
            {f.fingerprint for f in whole}
        assert all(f.path == "mxnet_tpu/io/pipeline.py" for f in sub)

    def test_tree_gates_clean_against_baseline(self):
        findings = core.run_checkers(PKG, rel_to=REPO)
        entries, malformed = core.load_baseline(BASELINE)
        assert not malformed, malformed
        new = [f for f in findings if f.fingerprint not in entries]
        assert not new, "\n".join(map(repr, new))

    @pytest.mark.slow
    def test_standalone_launcher_imports_no_jax(self):
        # tools/analyze.py asserts "jax" not in sys.modules itself
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "analyze.py"),
             "--root", PKG, "--baseline", BASELINE, "-q"],
            capture_output=True, text=True, cwd=REPO, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_cli_json_format(self, capsys):
        src = textwrap.dedent(self.__class__.SRC_BAD)
        with tempfile.NamedTemporaryFile("w", suffix=".py",
                                         delete=False) as f:
            f.write(src)
        try:
            rc = analysis.main(["--root", f.name, "--format", "json"])
        finally:
            os.unlink(f.name)
        out = capsys.readouterr().out
        import json
        doc = json.loads(out)
        assert rc == 1 and doc["new"] >= 1

    SRC_BAD = """
        import jax
        def step(w, g):
            fn = jax.jit(lambda a, b: a - b, donate_argnums=(0,))
            out = fn(w, g)
            return out + w.sum()
        """

    def test_cli_github_format(self, capsys):
        src = textwrap.dedent(self.SRC_BAD)
        with tempfile.NamedTemporaryFile("w", suffix=".py",
                                         delete=False) as f:
            f.write(src)
        try:
            rc = analysis.main(["--root", f.name, "--format", "github"])
        finally:
            os.unlink(f.name)
        out = capsys.readouterr().out
        assert rc == 1
        ann = [ln for ln in out.splitlines() if ln.startswith("::error")]
        assert len(ann) == 1
        assert "file=" in ann[0] and "line=" in ann[0]
        assert "title=donation/use-after-donate" in ann[0]

    def test_cli_text_format_byte_stable_fields(self, capsys):
        # the text format is what the baseline workflow diffs: one NEW/base
        # mark, fingerprint, checker/rule, location per finding
        src = textwrap.dedent(self.SRC_BAD)
        with tempfile.NamedTemporaryFile("w", suffix=".py",
                                         delete=False) as f:
            f.write(src)
        try:
            rc = analysis.main(["--root", f.name])
        finally:
            os.unlink(f.name)
        out = capsys.readouterr().out
        assert rc == 1
        assert out.splitlines()[0].startswith("NEW  [")
        assert out.splitlines()[-1].startswith("analysis: 1 findings")


# ----------------------------------------------------------------- sanitizer
def _agg_setup(n=4):
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    opt.aggregate_num = 16
    ws = [mx.nd.array(np.random.rand(8, 8).astype("float32"))
          for _ in range(n)]
    gs = [mx.nd.array(np.random.rand(8, 8).astype("float32"))
          for _ in range(n)]
    ss = [opt.create_state_multi_precision(i, w) for i, w in enumerate(ws)]
    return opt, ws, gs, ss


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    yield
    san.disable()
    san.reset()


class TestSanitizerDonation:
    def test_planted_use_after_donate_names_site(self):
        opt, ws, gs, ss = _agg_setup()
        stale = ws[0].detach()            # aliases the pre-update buffer
        with san.scope("donation"):
            aggregate.update_multi(opt, list(range(len(ws))), ws, gs, ss)
            with pytest.raises(san.DonatedBufferError) as ei:
                stale.asnumpy()
        assert "optimizer.aggregate group 'sgd'" in str(ei.value)
        assert san.stats()["violations"] == 1

    def test_state_alias_flagged_too(self):
        opt, ws, gs, ss = _agg_setup()
        # momentum slot handle: rebound in place, but a detached alias of
        # the OLD buffer must be flagged
        mom = ss[0] if isinstance(ss[0], mx.nd.NDArray) else ss[0][0]
        stale_state = mom.detach()
        with san.scope("donation"):
            aggregate.update_multi(opt, list(range(len(ws))), ws, gs, ss)
            with pytest.raises(san.DonatedBufferError):
                stale_state.asnumpy()

    def test_clean_aggregated_steps_zero_findings(self):
        opt, ws, gs, ss = _agg_setup()
        with san.scope("donation"):
            for _ in range(3):
                aggregate.update_multi(opt, list(range(len(ws))), ws, gs,
                                       ss)
                _ = [w.asnumpy() for w in ws]     # rebound handles: fine
        assert san.stats()["violations"] == 0
        assert san.stats()["poisoned"] > 0

    def test_engine_bulk_clean_under_sanitize(self):
        from mxnet_tpu import engine
        with san.scope("donation"):
            with engine.bulk(16):
                x = mx.nd.array(np.linspace(-1, 1, 64,
                                            dtype="float32").reshape(8, 8))
                y = x
                for _ in range(12):
                    y = y * 1.01 + 0.5
            ref = np.linspace(-1, 1, 64, dtype="float32").reshape(8, 8)
            for _ in range(12):
                ref = ref * 1.01 + 0.5
            np.testing.assert_allclose(y.asnumpy(), ref, rtol=2e-5)
        assert san.stats()["violations"] == 0

    def test_spmd_trainer_step_poisons_donated_state(self):
        from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                        make_mesh)
        net = mx.gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(),
                         FunctionalOptimizer("sgd", 1e-2),
                         make_mesh(n_devices=1, dp=1))
        x = np.random.rand(4, 8).astype("float32")
        y = np.random.rand(4, 4).astype("float32")
        with san.scope("donation"):
            loss = tr.step(x, y)
            assert np.isfinite(float(loss.asnumpy()))
            assert san.stats()["poisoned"] > 0
        assert san.stats()["violations"] == 0


def _write_rec(tmp, n=64):
    from mxnet_tpu import recordio
    rec_path = os.path.join(tmp, "d.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(rec_path, "w")
    img = (rng.rand(64, 64, 3) * 255).astype("uint8")
    for i in range(n):
        rec.write(recordio.pack_img(
            recordio.IRHeader(0, float(i % 10), i, 0), img, quality=85))
    rec.close()
    return rec_path


class TestSanitizerSlots:
    def test_post_release_slot_read_names_site(self, tmp_path):
        rec_path = _write_rec(str(tmp_path))
        with san.scope("slots"):
            it = mx.io.ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, 48, 48),
                batch_size=16, preprocess_processes=2,
                zero_copy_batches=True)
            try:
                b1 = next(it)
                _ = b1.data[0].asnumpy()          # fresh: fine
                b2 = next(it)                     # recycles b1's slot
                _ = b2.data[0].asnumpy()
                with pytest.raises(san.StaleSlotError) as ei:
                    b1.data[0].asnumpy()
            finally:
                it.close()
        assert "zero_copy_batches slot" in str(ei.value)
        assert san.stats()["violations"] == 1

    def test_clean_epoch_zero_findings(self, tmp_path):
        rec_path = _write_rec(str(tmp_path))
        with san.scope("slots"):
            it = mx.io.ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, 48, 48),
                batch_size=16, preprocess_processes=2,
                zero_copy_batches=True)
            try:
                total = 0.0
                for b in it:                      # consume before next()
                    total += float(b.data[0].asnumpy().sum())
            finally:
                it.close()
            assert total > 0
        assert san.stats()["violations"] == 0
        assert san.stats()["slot_views"] > 0

    def test_copy_mode_not_tracked(self, tmp_path):
        # default (copying) batches never register slot views
        rec_path = _write_rec(str(tmp_path), n=32)
        with san.scope("slots"):
            it = mx.io.ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, 48, 48),
                batch_size=16, preprocess_processes=2)
            try:
                b1 = next(it)
                next(it)
                _ = b1.data[0].asnumpy()          # copied: always valid
            finally:
                it.close()
        assert san.stats()["slot_views"] == 0
        assert san.stats()["violations"] == 0


class TestSanitizerConfig:
    def test_env_grammar(self):
        assert san._parse("donation,slots") == {"donation", "slots"}
        assert san._parse("1") == set(san.MODES)
        assert san._parse("") == frozenset()
        # conventional disable spellings parse to "nothing armed", they
        # must never crash `import mxnet_tpu`
        for spec in ("0", "false", "off", "none", "OFF"):
            assert san._parse(spec) == frozenset()
        with pytest.raises(ValueError):
            san._parse("bogus")

    def test_scope_restores(self):
        assert not san.active
        with san.scope("donation"):
            assert san.active and san.donation and not san.slots
            with san.scope("slots"):
                assert san.slots and not san.donation
            assert san.donation
        assert not san.active

    def test_enable_disable_additive(self):
        san.enable("donation")
        san.enable("slots")
        assert san.modes() == {"donation", "slots"}
        san.disable("donation")
        assert san.modes() == {"slots"}
        san.disable()
        assert not san.active


# --------------------------------------------------------------- fault sites
class TestFaultSites:
    def test_optimizer_apply_site(self):
        opt, ws, gs, ss = _agg_setup(n=1)
        before = ws[0].asnumpy().copy()
        with faults.scope("optimizer.apply:fail:1"):
            with pytest.raises(faults.InjectedFault):
                aggregate.update_multi(opt, [0], ws, gs, ss)
            # fails BEFORE any mutation: weights untouched
            np.testing.assert_array_equal(ws[0].asnumpy(), before)
            aggregate.update_multi(opt, [0], ws, gs, ss)   # next call passes
        assert not np.array_equal(ws[0].asnumpy(), before)

    def test_pipeline_schedule_site(self):
        import jax.numpy as jnp
        from mxnet_tpu.parallel import make_mesh, pipeline as pl
        mesh = make_mesh(n_devices=8, pp=8)
        params = jnp.ones((8, 4))
        x = jnp.ones((16, 4))
        with faults.scope("pipeline.schedule:fail:1"):
            with pytest.raises(faults.InjectedFault):
                pl.gpipe(lambda p, xx: xx * p.sum(), params, x, mesh, 4)
            out = pl.gpipe(lambda p, xx: xx * p.sum(), params, x, mesh, 4)
        assert out.shape == x.shape
