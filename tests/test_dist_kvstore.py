"""Multi-process distributed KVStore test (the reference runs the real PS
stack as local processes via the same launcher users use —
``tests/nightly/test_all.sh:55``; here the same trick over
``jax.distributed``)."""
import os
import subprocess
import sys

import pytest

# Some CPU-only jaxlib builds ship without the multiprocess collective
# backend; the workers then die inside jax.distributed.initialize with
# this exact message.  That is an environment limitation, not a
# regression in the PS stack — skip with the reason instead of failing.
_NO_MULTIPROC = "Multiprocess computations aren't implemented on the CPU"


def _launch(n, port, worker, timeout):
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # one device per worker process
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"),
         "-n", str(n), "--port", str(port),
         sys.executable, os.path.join(root, "tests", worker)],
        capture_output=True, text=True, timeout=timeout, env=env)
    if out.returncode != 0 and _NO_MULTIPROC in out.stdout + out.stderr:
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives "
                    f"({_NO_MULTIPROC!r})")
    return out


def test_dist_sync_kvstore_two_workers():
    out = _launch(2, 29731, "dist_sync_kvstore_worker.py", 420)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("WORKER_OK") == 2, out.stdout
    assert out.stdout.count("MODULE_DIST_OK") == 2, out.stdout


def test_dist_sync_matrix_four_workers():
    """The reference nightly matrix (dist_sync_kvstore.py): dense+row_sparse
    push/pull, fp16 keys, server-side optimizer, 2-bit compression with
    error feedback, and a dist_lenet-style convergence run — 4 workers."""
    out = _launch(4, 29741, "dist_matrix_worker.py", 560)
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])
    for marker in ("DENSE_OK", "RSP_OK", "RSP_ZEROS_OK", "BIG_RSP_OK",
                   "COMPR_OK", "LENET_OK", "MATRIX_OK"):
        assert out.stdout.count(marker) >= 4, (marker, out.stdout[-3000:])


def test_multihost_module_two_procs_two_devices_each():
    """Multi-host Module (VERDICT r2 missing #7): Module.fit over a
    2-process x 2-local-device topology — local SPMD dp mesh inside each
    process, dist_sync kvstore across processes, weight identity + acc."""
    out = _launch(2, 29747, "dist_multihost_module_worker.py", 420)
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])
    assert out.stdout.count("MULTIHOST_MODULE_OK") == 2, out.stdout[-3000:]
