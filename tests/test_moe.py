"""Expert-parallel MoE tests (ep mesh axis, all_to_all dispatch)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import device_mesh, moe_layer


def _expert(p, x):
    return jnp.tanh(x @ p["w"])


def _setup(E=4, d=8, b=32, seed=0):
    rng = np.random.RandomState(seed)
    gate_w = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    expert_params = {"w": jnp.asarray(rng.randn(E, d, d) * 0.3, jnp.float32)}
    x = jnp.asarray(rng.randn(b, d), jnp.float32)
    return gate_w, expert_params, x


def _dense_reference(gate_w, expert_params, x):
    probs = jax.nn.softmax(x @ gate_w, axis=-1)
    eidx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    outs = jnp.stack([_expert({"w": expert_params["w"][e]}, x)
                      for e in range(gate_w.shape[1])], axis=1)  # (B, E, D)
    sel = jnp.take_along_axis(outs, eidx[:, None, None].repeat(
        x.shape[-1], axis=2), axis=1)[:, 0]
    return sel * gate[:, None]


def test_moe_matches_dense_with_big_capacity():
    gate_w, expert_params, x = _setup()
    mesh = device_mesh({"dp": 2, "ep": 4})
    out = moe_layer(_expert, gate_w, expert_params, x, mesh,
                    capacity_factor=64.0)  # nothing drops
    ref = _dense_reference(gate_w, expert_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_moe_capacity_drops_are_bounded():
    gate_w, expert_params, x = _setup(E=8, b=64)
    mesh = device_mesh({"dp": 1, "ep": 8})
    out = moe_layer(_expert, gate_w, expert_params, x, mesh,
                    capacity_factor=0.5)  # force drops
    ref = _dense_reference(gate_w, expert_params, x)
    o, r = np.asarray(out), np.asarray(ref)
    # every token either matches the dense result or was dropped (zeros)
    matches = np.isclose(o, r, rtol=2e-4, atol=2e-4).all(axis=-1)
    zeros = (o == 0).all(axis=-1)
    assert (matches | zeros).all()
    assert zeros.any()  # capacity 0.5 must actually drop something


def test_moe_gradients_flow():
    gate_w, expert_params, x = _setup(b=16)
    mesh = device_mesh({"dp": 2, "ep": 4})

    def loss(gw, ep):
        return moe_layer(_expert, gw, ep, x, mesh, capacity_factor=8.0).sum()

    g_gate, g_exp = jax.grad(loss, argnums=(0, 1))(gate_w, expert_params)
    assert np.isfinite(np.asarray(g_gate)).all()
    assert np.isfinite(np.asarray(g_exp["w"])).all()
    assert np.abs(np.asarray(g_exp["w"])).sum() > 0


def test_moe_mismatched_gate_raises():
    gate_w, expert_params, x = _setup(E=4)
    mesh = device_mesh({"dp": 2, "ep": 4})
    import jax.numpy as jnp
    bad_gate = jnp.zeros((x.shape[-1], 16), jnp.float32)
    with pytest.raises(AssertionError):
        moe_layer(_expert, bad_gate, expert_params, x, mesh)
