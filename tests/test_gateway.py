"""serving.gateway: the HTTP front door (ISSUE 18 tentpole).

Covers route behaviour end-to-end over a real localhost socket: buffered
vs SSE-streamed ``/v1/generate`` (bitwise-identical tokens), ``/v1/infer``
through a ModelRegistry, QoS admission sheds as 429-with-Retry-After,
error→status mapping, /healthz + /metrics on the same port, and the
satellite: an atomic registry hot-swap under live concurrent HTTP
traffic with zero dropped or torn responses.
"""
import http.client
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.serving import Batcher, ModelRegistry, ModelRuntime
from mxnet_tpu.serving.decode import DecodeSession, get_decode_model
from mxnet_tpu.serving.gateway import AdmissionController, Gateway
from mxnet_tpu.telemetry import http as thttp

ITEM = (24,)
VOCAB = 96


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    thttp.stop_server()


@pytest.fixture(scope="module")
def decode_sess():
    mx.random.seed(0)
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                         page_size=8)
    yield sess
    sess.close(drain=False)


def _make_net(const=None):
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(4))
    net.initialize(mx.init.Constant(const) if const is not None else None)
    return net


def _post(port, path, body, timeout=60):
    """POST json, return (status, headers-dict, raw-bytes).  Streaming
    responses close the connection, so read() drains to EOF."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _sse_frames(raw):
    """Parse an SSE body into the list of ``data:`` payload strings."""
    out = []
    for chunk in raw.decode().split("\n\n"):
        chunk = chunk.strip()
        if chunk.startswith("data: "):
            out.append(chunk[len("data: "):])
    return out


# ---------------------------------------------------------------- admission
def test_admission_guaranteed_share_and_borrowing():
    ac = AdmissionController(capacity=4)
    ac.set_weight("a", 3.0)
    ac.set_weight("b", 1.0)
    # a's guaranteed share is 3, b's is 1
    assert all(ac.try_acquire("a") for _ in range(3))
    assert ac.try_acquire("b")
    # capacity reached and both are at/over share -> shed
    assert not ac.try_acquire("b")
    assert ac.shed == 1
    # idle capacity is borrowable once someone releases
    ac.release("a")
    assert ac.try_acquire("b")          # borrows a's idle share
    assert ac.borrowed >= 1
    snap = ac.snapshot()
    assert snap["inflight"] == {"a": 2, "b": 2}
    with pytest.raises(ValueError):
        ac.set_weight("a", 0)
    with pytest.raises(ValueError):
        AdmissionController(capacity=0)


def test_admission_floored_share_always_admits_one():
    ac = AdmissionController(capacity=2)
    ac.set_weight("big", 100.0)
    assert ac.try_acquire("big")
    assert ac.try_acquire("big")
    # tiny's proportional share rounds to 0 but floors at 1 — the
    # bounded-overshoot contract: a guarantee, not a hint
    assert ac.try_acquire("tiny")
    assert ac.inflight() == 3


# ------------------------------------------------------------- /v1/generate
def test_generate_buffered_vs_streamed_bitwise(decode_sess):
    with Gateway() as gw:
        gw.add_decode("tiny", decode_sess)
        req = {"model": "tiny", "prompt": [5, 9, 2],
               "max_new_tokens": 8, "temperature": 0.8, "seed": 11}
        st, _, raw = _post(gw.port, "/v1/generate", req)
        assert st == 200
        buffered = json.loads(raw)
        assert buffered["model"] == "tiny"
        assert len(buffered["token_ids"]) == 8
        assert buffered["finish_reason"] == "length"

        st, hdr, raw = _post(gw.port, "/v1/generate",
                             dict(req, stream=True))
        assert st == 200
        assert hdr.get("Content-Type") == "text/event-stream"
        frames = _sse_frames(raw)
        assert frames[-1] == "[DONE]"
        toks = [json.loads(f) for f in frames[:-1]]
        done = toks.pop()
        assert done["done"] is True and done["n_tokens"] == 8
        assert done["finish_reason"] == "length"
        assert [t["index"] for t in toks] == list(range(8))
        # the bitwise contract: SSE carries exactly the buffered sequence
        assert [t["token"] for t in toks] == buffered["token_ids"]


def test_generate_default_model_and_errors(decode_sess):
    with Gateway() as gw:
        gw.add_decode("tiny", decode_sess)
        # sole registered model is the default
        st, _, raw = _post(gw.port, "/v1/generate",
                           {"prompt": [1, 2], "max_new_tokens": 2})
        assert st == 200 and json.loads(raw)["model"] == "tiny"
        st, _, raw = _post(gw.port, "/v1/generate",
                           {"model": "nope", "prompt": [1]})
        assert st == 404 and json.loads(raw)["error"] == "unknown_model"
        st, _, raw = _post(gw.port, "/v1/generate",
                           {"model": "tiny", "prompt": []})
        assert st == 400
        # malformed JSON body
        conn = http.client.HTTPConnection("127.0.0.1", gw.port, timeout=30)
        try:
            conn.request("POST", "/v1/generate", b"{nope",
                         {"Content-Type": "application/json"})
            assert conn.getresponse().status == 400
        finally:
            conn.close()


def test_generate_qos_shed_is_429_with_retry_after(decode_sess):
    with Gateway(capacity=1) as gw:
        gw.add_decode("tiny", decode_sess)
        assert gw.admission.try_acquire("tiny")   # hold the only slot
        try:
            telemetry.enable()
            st, hdr, raw = _post(gw.port, "/v1/generate",
                                 {"prompt": [3], "max_new_tokens": 1})
            assert st == 429
            assert float(hdr["Retry-After"]) > 0
            assert json.loads(raw)["error"] == "qos"
            by_label = telemetry.snapshot()["counters_by_label"]
            assert any('reason="qos"' in k
                       for k in by_label.get("gateway.shed", {}))
        finally:
            gw.admission.release("tiny")


def test_streamed_shed_maps_like_buffered(decode_sess):
    # a deadline that expires before admission -> 429, both paths
    with Gateway() as gw:
        gw.add_decode("tiny", decode_sess)
        req = {"prompt": [4, 4], "max_new_tokens": 4, "deadline_ms": 0.0}
        st, hdr, raw = _post(gw.port, "/v1/generate", req)
        assert st == 429 and json.loads(raw)["error"] == "deadline"
        assert "Retry-After" in hdr
        # streamed: shed surfaces as an in-stream error frame (headers
        # are already on the wire) and the stream still terminates
        st, _, raw = _post(gw.port, "/v1/generate",
                           dict(req, stream=True))
        frames = _sse_frames(raw)
        assert frames[-1] == "[DONE]"
        payloads = [json.loads(f) for f in frames[:-1]]
        assert payloads[-1].get("error") == "deadline"
        assert not any("token" in p for p in payloads)


# ---------------------------------------------------------------- /v1/infer
def test_infer_roundtrip_and_errors():
    reg = ModelRegistry()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    reg.register("m", rt, max_latency_ms=2)
    try:
        with Gateway(registry=reg) as gw:
            x = np.random.RandomState(0).rand(*ITEM).astype("float32")
            st, _, raw = _post(gw.port, "/v1/infer",
                               {"model": "m", "inputs": x.tolist()})
            assert st == 200
            body = json.loads(raw)
            np.testing.assert_allclose(body["outputs"], rt(x),
                                       rtol=1e-5, atol=1e-6)
            st, _, _ = _post(gw.port, "/v1/infer",
                             {"model": "ghost", "inputs": [1.0]})
            assert st == 404
            st, _, raw = _post(gw.port, "/v1/infer", {"model": "m"})
            assert st == 400
            assert "inputs" in json.loads(raw)["detail"]
    finally:
        reg.close()


def test_infer_without_registry_is_404(decode_sess):
    with Gateway() as gw:
        st, _, raw = _post(gw.port, "/v1/infer",
                           {"model": "m", "inputs": [1.0]})
        assert st == 404


# ------------------------------------------------- hot swap under live fire
def test_registry_hot_swap_under_live_http_traffic():
    """ISSUE 18 satellite: swap a model's weights while HTTP clients
    hammer /v1/infer.  Every request must answer 200 with an output that
    is exactly the old or the new model's — zero drops, zero torn reads,
    and post-swap requests see the new weights."""
    reg = ModelRegistry()
    rt1 = ModelRuntime(_make_net(const=0.1), ITEM, max_batch=4, name="m")
    rt2 = ModelRuntime(_make_net(const=0.3), ITEM, max_batch=4, name="m")
    reg.register("m", rt1, max_latency_ms=1)
    x = np.random.RandomState(1).rand(*ITEM).astype("float32")
    ref1, ref2 = np.asarray(rt1(x)), np.asarray(rt2(x))
    assert not np.allclose(ref1, ref2)

    results = {}          # thread-name -> list of (status, outputs)
    errors = []
    n_threads, n_reqs = 4, 24
    body = {"model": "m", "inputs": x.tolist()}

    with Gateway(registry=reg, capacity=64) as gw:
        def client(tag):
            got = []
            try:
                for _ in range(n_reqs):
                    st, _, raw = _post(gw.port, "/v1/infer", body)
                    got.append((st, json.loads(raw).get("outputs")))
            except Exception as e:        # noqa: BLE001 — fail the test
                errors.append((tag, repr(e)))
            results[tag] = got

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        time.sleep(0.05)                    # traffic in flight
        reg.swap("m", rt2, max_latency_ms=1)
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors

        # zero dropped requests: every client got every answer
        assert all(len(results[i]) == n_reqs for i in range(n_threads))
        flat = [r for got in results.values() for r in got]
        assert all(st == 200 for st, _ in flat), \
            sorted({st for st, _ in flat})
        # zero torn responses: each output is exactly one model's answer
        n_new = 0
        for _, out in flat:
            is_old = np.allclose(out, ref1, rtol=1e-5, atol=1e-6)
            is_new = np.allclose(out, ref2, rtol=1e-5, atol=1e-6)
            assert is_old ^ is_new, out
            n_new += int(is_new)
        assert n_new > 0                    # the swap actually landed
        # and the steady state is the new weights
        st, _, raw = _post(gw.port, "/v1/infer", body)
        np.testing.assert_allclose(json.loads(raw)["outputs"], ref2,
                                   rtol=1e-5, atol=1e-6)
    reg.close()


# ---------------------------------------------------- shared-port telemetry
def test_healthz_metrics_and_routes_share_the_port(decode_sess):
    telemetry.enable()
    with Gateway() as gw:
        gw.add_decode("tiny", decode_sess, weight=2.0)
        st, raw = _get(gw.port, "/healthz")
        assert st == 200
        report = json.loads(raw)
        assert report["components"].get("gateway:gateway") is True
        _post(gw.port, "/v1/generate",
              {"prompt": [7], "max_new_tokens": 2})
        st, raw = _get(gw.port, "/metrics")
        assert st == 200
        text = raw.decode()
        assert "gateway_requests" in text or "gateway.requests" in text
        counters = telemetry.snapshot()["counters"]
        assert counters.get("gateway.requests") == 1
        assert counters.get("gateway.responses") == 1
        hists = telemetry.snapshot()["histograms"]
        assert "gateway.ttft_buffered_ms" in hists
        assert "gateway.queue_wait_ms" in hists
    # close() unmounted the routes: the port still answers, /v1 404s
    port = thttp.server_port()
    assert port is not None
    st, _, _ = _post(port, "/v1/generate", {"prompt": [1]})
    assert st == 404
    st, raw = _get(port, "/healthz")
    assert st == 200
    assert "gateway:gateway" not in json.loads(raw)["components"]


def test_unhealthy_gateway_flips_healthz(decode_sess):
    gw = Gateway()
    try:
        gw.add_decode("tiny", decode_sess)
        gw._closed = True                  # simulate a wedged front door
        st, raw = _get(gw.port, "/healthz")
        assert st == 503
        assert json.loads(raw)["components"]["gateway:gateway"] is False
        gw._closed = False
        st, _ = _get(gw.port, "/healthz")
        assert st == 200
    finally:
        gw._closed = False
        gw.close()


# ------------------------------------------- ISSUE 19: graceful degradation
def test_compute_retry_after_per_reason():
    """Every shed reason derives its Retry-After from the live state
    that caused it — not one constant that synchronizes retry storms."""
    ac = AdmissionController(capacity=10, retry_after_s=1.0)
    # breaker open: hint == the actual remaining cool-down
    assert ac.compute_retry_after("unhealthy",
                                  breaker_remaining_s=3.25) == 3.25
    assert ac.compute_retry_after("unhealthy",
                                  breaker_remaining_s=0.01) == 0.1
    assert ac.compute_retry_after("unhealthy") == 5.0     # no breaker info
    # shutdown: long — clients should fail over, not camp
    assert ac.compute_retry_after("shutdown") >= 10.0
    # owner crash: sized past an AOT-warm supervisor respawn
    assert ac.compute_retry_after("owner_unavailable") >= 2.0
    # qos: scales with gateway contention
    assert ac.compute_retry_after("qos", inflight=0) == 1.0
    assert ac.compute_retry_after("qos", inflight=10) == 2.0
    # queue pressure: scales with live queue depth
    assert ac.compute_retry_after("backpressure", queue_depth=5) == 1.5
    assert ac.compute_retry_after("deadline", queue_depth=10) == 2.0
    # kv pressure: scales with actively decoding sequences
    assert ac.compute_retry_after("kv_exhausted", active=10) == 2.0
    assert ac.compute_retry_after("kv_exhausted", active=0) == 1.0
    # unknown reasons get the base hint
    assert ac.compute_retry_after("???") == 1.0


def test_shed_headers_carry_live_retry_after(decode_sess):
    """HTTP-level: each reachable shed reason answers with the header
    computed from live state."""
    gw = Gateway(capacity=1)
    try:
        gw.add_decode("tiny", decode_sess)
        # qos: fill the only slot, then shed
        assert gw.admission.try_acquire("tiny")
        st, hdrs, raw = _post(gw.port, "/v1/generate",
                              {"model": "tiny", "prompt": [1]})
        assert st == 429
        assert json.loads(raw)["error"] == "qos"
        assert float(hdrs["Retry-After"]) == pytest.approx(
            gw.admission.compute_retry_after("qos"), abs=0.5)
        gw.admission.release("tiny")
        # shutdown: drain flips every new request to 503 + long hint
        gw.drain()
        st, hdrs, raw = _post(gw.port, "/v1/generate",
                              {"model": "tiny", "prompt": [1]})
        assert st == 503
        assert json.loads(raw)["error"] == "shutdown"
        assert float(hdrs["Retry-After"]) >= 10.0
    finally:
        gw._draining.clear()
        gw.close()


def test_drain_flips_readyz_not_healthz(decode_sess):
    """Liveness says "restart me", readiness says "route away": a drain
    must flip only readiness, or the balancer's health check kills a
    process that is finishing real work."""
    gw = Gateway()
    try:
        gw.add_decode("tiny", decode_sess)
        assert _get(gw.port, "/healthz")[0] == 200
        assert _get(gw.port, "/readyz")[0] == 200
        gw.drain()
        assert gw.draining
        st, raw = _get(gw.port, "/readyz")
        assert st == 503
        assert json.loads(raw)["components"]["gateway:gateway"] is False
        assert _get(gw.port, "/healthz")[0] == 200        # still alive
    finally:
        gw._draining.clear()
        gw.close()


def test_open_breaker_flips_readyz_not_healthz():
    """A batcher's open circuit breaker is a routing signal, not a
    liveness failure."""
    net = _make_net(0.1)
    rt = ModelRuntime(net, item_shapes=ITEM, max_batch=2)
    reg = ModelRegistry()
    reg.register("m", rt, max_latency_ms=1.0)
    gw = Gateway(registry=reg)
    try:
        assert _get(gw.port, "/readyz")[0] == 200
        b = reg.get("m")
        b._breaker_open_until = time.perf_counter() + 60.0
        st, raw = _get(gw.port, "/readyz")
        assert st == 503
        assert json.loads(raw)["components"][f"batcher:{rt.name}"] is False
        assert _get(gw.port, "/healthz")[0] == 200
        b._breaker_open_until = 0.0
        assert _get(gw.port, "/readyz")[0] == 200
    finally:
        gw.close()
        reg.close(drain=False)


def test_sse_client_disconnect_aborts_decode(decode_sess):
    """Satellite 1: the SSE reader hangs up mid-stream -> the gateway
    aborts the decode via the scheduler, the KV pages come back, and
    the eviction is accounted reason="aborted" — no leaked slots, no
    tokens decoded for nobody."""
    from mxnet_tpu.resilience import faults

    import socket as socketlib

    telemetry.enable()
    gw = Gateway()
    sock = None
    try:
        gw.add_decode("tiny", decode_sess)
        base_pages = decode_sess.stats()["pages_in_use"]
        with faults.scope("decode.step:delay:40ms"):   # slow the decode
            body = json.dumps({"model": "tiny", "prompt": [5, 9, 2],
                               "max_new_tokens": 29,
                               "stream": True}).encode()
            sock = socketlib.create_connection(("127.0.0.1", gw.port),
                                               timeout=30)
            sock.sendall(b"POST /v1/generate HTTP/1.1\r\n"
                         b"Host: x\r\nContent-Type: application/json\r\n"
                         b"Content-Length: %d\r\n\r\n" % len(body) + body)
            buf = b""
            while b"data: " not in buf:    # headers + first token frame
                chunk = sock.recv(4096)
                assert chunk, "stream closed before first token"
                buf += chunk
            assert b" 200 " in buf.split(b"\r\n", 1)[0]
            sock.close()                   # ...and vanish mid-stream
            sock = None
            # the abort lands at the next step boundary
            deadline = time.perf_counter() + 15.0
            aborted = 0
            while time.perf_counter() < deadline:
                by_label = telemetry.snapshot()["counters_by_label"]
                aborted = sum(
                    v for k, v in
                    by_label.get("decode.evictions", {}).items()
                    if 'reason="aborted"' in k)
                if aborted and \
                        decode_sess.stats()["pages_in_use"] <= base_pages:
                    break
                time.sleep(0.05)
        assert aborted >= 1
        stats = decode_sess.stats()
        assert stats["pages_in_use"] <= base_pages      # pages came back
        assert stats["active"] == 0 and stats["pending"] == 0
        counters = telemetry.snapshot()["counters"]
        assert counters.get("gateway.client_disconnects", 0) >= 1
        # the admission slot was released too
        assert gw.admission.inflight() == 0
    finally:
        if sock is not None:
            sock.close()
        gw.close()


def test_sigterm_drains_gracefully():
    """Satellite 4 (subprocess drill): SIGTERM mid-request -> the
    in-flight request completes 200, new submits shed 503 shutdown,
    and the worker exits 0."""
    import os
    import signal
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__),
                          "gateway_drain_worker.py")
    proc = subprocess.Popen([sys.executable, worker],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("PORT ")
        port = int(line.split()[1])

        results = {}

        def inflight():
            results["inflight"] = _post(
                port, "/v1/infer",
                {"model": "tiny_dense", "inputs": [0.5] * 8}, timeout=30)

        t = threading.Thread(target=inflight, daemon=True)
        t.start()
        time.sleep(0.15)                 # request is inside the batcher
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.15)                 # drain has flipped
        st, hdrs, raw = _post(port, "/v1/infer",
                              {"model": "tiny_dense",
                               "inputs": [0.5] * 8}, timeout=10)
        assert st == 503
        assert json.loads(raw)["error"] == "shutdown"
        assert float(hdrs["Retry-After"]) >= 10.0
        t.join(timeout=30)
        st, _, raw = results["inflight"]
        assert st == 200                 # in-flight work was not dropped
        assert len(json.loads(raw)["outputs"]) == 4
        out, _ = proc.communicate(timeout=30)
        assert "DRAINED" in out
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
