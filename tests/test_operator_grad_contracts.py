"""Finite-difference gradient contracts across operator families
(reference ``tests/python/unittest/test_operator.py`` strategy:
``check_numeric_gradient`` per op config, plus forward dtype sweeps).

Shapes are deliberately tiny — the FD check runs 2·size forwards per
tensor — but every config exercises a distinct attribute path of the op.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal  # noqa: F401


from mxnet_tpu.test_utils import (fd_grad_check as _grad_check,  # noqa: E402
                                  fd_rand as _rand)


# ------------------------------------------------------------- Convolution
@pytest.mark.parametrize("kernel,stride,pad,dilate,groups", [
    ((3, 3), (1, 1), (0, 0), (1, 1), 1),
    ((3, 3), (1, 1), (1, 1), (1, 1), 1),
    ((3, 3), (2, 2), (1, 1), (1, 1), 1),
    ((2, 2), (1, 1), (0, 0), (2, 2), 1),
    ((3, 3), (1, 1), (1, 1), (1, 1), 2),
    ((1, 1), (1, 1), (0, 0), (1, 1), 1),
])
def test_convolution_grad(kernel, stride, pad, dilate, groups):
    data = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data, name="c", kernel=kernel, stride=stride,
                             pad=pad, dilate=dilate, num_group=groups,
                             num_filter=4)
    loc = {"data": _rand(1, 2 * groups, 6, 6, seed=1),
           "c_weight": _rand(4, 2, *kernel, seed=2, scale=0.5),
           "c_bias": _rand(4, seed=3)}
    _grad_check(sym, loc)


@pytest.mark.parametrize("kernel,stride", [((3, 3), (1, 1)),
                                           ((2, 2), (2, 2))])
def test_convolution_no_bias_grad(kernel, stride):
    data = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data, name="c", kernel=kernel, stride=stride,
                             num_filter=3, no_bias=True)
    _grad_check(sym, {"data": _rand(1, 2, 5, 5, seed=1),
                      "c_weight": _rand(3, 2, *kernel, seed=2, scale=0.5)})


def test_convolution_1d_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data, name="c", kernel=(3,), num_filter=3)
    _grad_check(sym, {"data": _rand(2, 2, 7, seed=1),
                      "c_weight": _rand(3, 2, 3, seed=2, scale=0.5),
                      "c_bias": _rand(3, seed=3)})


@pytest.mark.parametrize("kernel,stride,pad", [
    ((3, 3), (1, 1), (0, 0)), ((2, 2), (2, 2), (0, 0)),
    ((3, 3), (2, 2), (1, 1)),
])
def test_deconvolution_grad(kernel, stride, pad):
    data = mx.sym.Variable("data")
    sym = mx.sym.Deconvolution(data, name="d", kernel=kernel, stride=stride,
                               pad=pad, num_filter=2, no_bias=True)
    _grad_check(sym, {"data": _rand(1, 3, 4, 4, seed=1),
                      "d_weight": _rand(3, 2, *kernel, seed=2, scale=0.5)})


# ----------------------------------------------------------------- Pooling
@pytest.mark.parametrize("ptype,kernel,stride,pad", [
    ("max", (2, 2), (2, 2), (0, 0)),
    ("max", (3, 3), (1, 1), (1, 1)),
    ("avg", (2, 2), (2, 2), (0, 0)),
    ("avg", (3, 3), (2, 2), (1, 1)),
    ("sum", (2, 2), (1, 1), (0, 0)),
])
def test_pooling_grad(ptype, kernel, stride, pad):
    data = mx.sym.Variable("data")
    sym = mx.sym.Pooling(data, pool_type=ptype, kernel=kernel,
                         stride=stride, pad=pad)
    if ptype == "max":
        # distinct, well-separated values so FD picks stable argmaxes
        x = np.arange(1 * 2 * 6 * 6, dtype="float32").reshape(1, 2, 6, 6)
        x += _rand(1, 2, 6, 6, seed=4, scale=0.2)
    else:
        # small centered values: FD on sums of large numbers drowns in
        # fp32 cancellation noise
        x = _rand(1, 2, 6, 6, seed=4)
    _grad_check(sym, {"data": x})


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_global_pooling_grad(ptype):
    data = mx.sym.Variable("data")
    sym = mx.sym.Pooling(data, pool_type=ptype, global_pool=True,
                         kernel=(1, 1))
    x = np.arange(2 * 2 * 4 * 4, dtype="float32").reshape(2, 2, 4, 4)
    _grad_check(sym, {"data": x})


def test_avg_pool_count_include_pad_forward():
    data = mx.sym.Variable("data")
    x = np.ones((1, 1, 2, 2), "float32")
    inc = mx.sym.Pooling(data, pool_type="avg", kernel=(2, 2), pad=(1, 1),
                         count_include_pad=True)
    exc = mx.sym.Pooling(data, pool_type="avg", kernel=(2, 2), pad=(1, 1),
                         count_include_pad=False)
    oi = inc.eval(data=mx.nd.array(x))[0].asnumpy()
    oe = exc.eval(data=mx.nd.array(x))[0].asnumpy()
    assert oi[0, 0, 0, 0] == pytest.approx(0.25)   # 1 of 4 cells real
    assert oe[0, 0, 0, 0] == pytest.approx(1.0)    # padding not counted


# --------------------------------------------------------------- BatchNorm
@pytest.mark.parametrize("fix_gamma", [True, False])
def test_batchnorm_grad(fix_gamma):
    data = mx.sym.Variable("data")
    sym = mx.sym.BatchNorm(data, name="bn", fix_gamma=fix_gamma, eps=1e-3)
    loc = {"data": _rand(4, 3, 2, 2, seed=5, scale=2.0),
           "bn_gamma": _rand(3, seed=6, shift=1.5),
           "bn_beta": _rand(3, seed=7)}
    aux = {"bn_moving_mean": np.zeros(3, "float32"),
           "bn_moving_var": np.ones(3, "float32")}
    nodes = ["data", "bn_beta"] + ([] if fix_gamma else ["bn_gamma"])
    _grad_check(sym, loc, aux=aux, grad_nodes=nodes)


def test_batchnorm_use_global_stats_forward():
    data = mx.sym.Variable("data")
    sym = mx.sym.BatchNorm(data, name="bn", use_global_stats=True,
                           fix_gamma=False, eps=0.0)
    x = _rand(2, 2, 3, 3, seed=8, scale=3.0)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=x.shape)
    ex.copy_params_from(
        {"bn_gamma": mx.nd.array([2.0, 1.0]),
         "bn_beta": mx.nd.array([0.0, 1.0])},
        {"bn_moving_mean": mx.nd.array([1.0, -1.0]),
         "bn_moving_var": mx.nd.array([4.0, 1.0])})
    out = ex.forward(is_train=True, data=mx.nd.array(x))[0].asnumpy()
    want = np.stack([(x[:, 0] - 1.0) / 2.0 * 2.0,
                     (x[:, 1] + 1.0) + 1.0], axis=1)
    assert_almost_equal(out, want, rtol=1e-4, atol=1e-5)


def test_layernorm_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.LayerNorm(data, name="ln", eps=1e-3)
    _grad_check(sym, {"data": _rand(3, 5, seed=9, scale=2.0),
                      "ln_gamma": _rand(5, seed=10, shift=1.0),
                      "ln_beta": _rand(5, seed=11)})


def test_instancenorm_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.InstanceNorm(data, name="in", eps=1e-3)
    _grad_check(sym, {"data": _rand(2, 2, 3, 3, seed=12, scale=2.0),
                      "in_gamma": _rand(2, seed=13, shift=1.0),
                      "in_beta": _rand(2, seed=14)})


def test_l2normalization_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.L2Normalization(data, eps=1e-4)
    _grad_check(sym, {"data": _rand(3, 4, seed=15, shift=0.5)})


# ----------------------------------------------------------------- softmax
@pytest.mark.parametrize("axis", [-1, 0, 1])
def test_softmax_grad(axis):
    data = mx.sym.Variable("data")
    sym = mx.sym.softmax(data, axis=axis) * mx.sym.Variable("w")
    _grad_check(sym, {"data": _rand(3, 4, seed=16, scale=2.0),
                      "w": _rand(3, 4, seed=17)}, grad_nodes=["data"])


def test_softmax_temperature_forward():
    data = mx.sym.Variable("data")
    x = _rand(2, 5, seed=18, scale=3.0)
    out = mx.sym.softmax(data, temperature=2.0).eval(
        data=mx.nd.array(x))[0].asnumpy()
    e = np.exp(x / 2.0 - (x / 2.0).max(-1, keepdims=True))
    assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-5,
                        atol=1e-6)


def test_log_softmax_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.log_softmax(data, axis=-1) * mx.sym.Variable("w")
    _grad_check(sym, {"data": _rand(3, 4, seed=19, scale=2.0),
                      "w": _rand(3, 4, seed=20)}, grad_nodes=["data"])


def test_softmax_output_backward_is_p_minus_label():
    data = mx.sym.Variable("data")
    sym = mx.sym.SoftmaxOutput(data, name="softmax")
    x = _rand(3, 4, seed=21, scale=2.0)
    y = np.array([0, 2, 3], "float32")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", data=x.shape,
                         softmax_label=y.shape)
    out = ex.forward(is_train=True, data=mx.nd.array(x),
                     softmax_label=mx.nd.array(y))[0].asnumpy()
    ex.backward()
    onehot = np.eye(4, dtype="float32")[y.astype(int)]
    assert_almost_equal(ex.grad_dict["data"].asnumpy(), out - onehot,
                        rtol=1e-4, atol=1e-5)


def test_softmax_cross_entropy_matches_manual():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    sym = mx.sym.softmax_cross_entropy(data, label)
    x = _rand(4, 5, seed=22, scale=2.0)
    y = np.array([1, 0, 4, 2], "float32")
    out = float(sym.eval(data=mx.nd.array(x),
                         label=mx.nd.array(y))[0].asnumpy())
    p = np.exp(x - x.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = -np.log(p[np.arange(4), y.astype(int)]).sum()
    assert out == pytest.approx(want, rel=1e-4)


# ------------------------------------------------------- FullyConnected/dot
@pytest.mark.parametrize("no_bias,flatten", [(False, True), (True, True),
                                             (False, False)])
def test_fully_connected_grad(no_bias, flatten):
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, name="fc", num_hidden=3,
                                no_bias=no_bias, flatten=flatten)
    loc = {"data": _rand(2, 2, 3, seed=23),
           "fc_weight": _rand(3, 6 if flatten else 3, seed=24, scale=0.5)}
    if not no_bias:
        loc["fc_bias"] = _rand(3, seed=25)
    _grad_check(sym, loc)


@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_dot_grad(ta, tb):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (4, 3) if ta else (3, 4)
    sb = (5, 4) if tb else (4, 5)
    _grad_check(sym, {"a": _rand(*sa, seed=26), "b": _rand(*sb, seed=27)})


@pytest.mark.parametrize("ta,tb", [(False, False), (True, True)])
def test_batch_dot_grad(ta, tb):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.batch_dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (2, 4, 3) if ta else (2, 3, 4)
    sb = (2, 5, 4) if tb else (2, 4, 5)
    _grad_check(sym, {"a": _rand(*sa, seed=28), "b": _rand(*sb, seed=29)})


# -------------------------------------------------------------- activations
@pytest.mark.parametrize("act", ["sigmoid", "tanh", "softrelu", "softsign"])
def test_activation_grad(act):
    data = mx.sym.Variable("data")
    sym = mx.sym.Activation(data, act_type=act)
    _grad_check(sym, {"data": _rand(3, 4, seed=30, scale=2.0)})


def test_relu_grad_away_from_kink():
    data = mx.sym.Variable("data")
    sym = mx.sym.Activation(data, act_type="relu")
    x = _rand(3, 4, seed=31, scale=2.0)
    x[np.abs(x) < 0.1] = 0.5            # keep FD off the kink
    _grad_check(sym, {"data": x})


@pytest.mark.parametrize("act,slope", [("leaky", 0.3), ("elu", 0.5)])
def test_leakyrelu_grad(act, slope):
    data = mx.sym.Variable("data")
    sym = mx.sym.LeakyReLU(data, act_type=act, slope=slope)
    x = _rand(3, 4, seed=32, scale=2.0)
    x[np.abs(x) < 0.1] = 0.5
    _grad_check(sym, {"data": x})


def test_prelu_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.LeakyReLU(data, name="pr", act_type="prelu")
    x = _rand(3, 4, seed=33, scale=2.0)
    x[np.abs(x) < 0.1] = -0.5
    _grad_check(sym, {"data": x, "pr_gamma": np.full(4, 0.3, "float32")})


@pytest.mark.parametrize("op,scale,shift", [
    ("exp", 1.0, 0.0), ("log", 0.4, 1.5), ("sqrt", 0.4, 1.5),
    ("rsqrt", 0.4, 1.5), ("cbrt", 0.4, 1.5), ("square", 1.0, 0.0),
    ("sin", 1.0, 0.0), ("cos", 1.0, 0.0), ("arctan", 1.0, 0.0),
    ("arcsinh", 1.0, 0.0), ("expm1", 1.0, 0.0), ("log1p", 0.4, 0.5),
    ("erf", 1.0, 0.0),
])
def test_unary_grad(op, scale, shift):
    data = mx.sym.Variable("data")
    sym = getattr(mx.sym, op)(data)
    _grad_check(sym, {"data": _rand(3, 4, seed=34, scale=scale,
                                    shift=shift)})


def test_clip_grad_interior():
    data = mx.sym.Variable("data")
    sym = mx.sym.clip(data, a_min=-0.8, a_max=0.8)
    x = _rand(3, 4, seed=35, scale=0.5)   # interior: gradient is identity
    _grad_check(sym, {"data": x})


# -------------------------------------------------- broadcast binary + pow
@pytest.mark.parametrize("op", ["broadcast_add", "broadcast_sub",
                                "broadcast_mul", "broadcast_div",
                                "broadcast_maximum", "broadcast_minimum",
                                "broadcast_hypot"])
def test_broadcast_binary_grad(op):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = getattr(mx.sym, op)(a, b)
    _grad_check(sym, {"a": _rand(3, 4, seed=36, shift=2.0),
                      "b": _rand(1, 4, seed=37, shift=0.7)})


def test_broadcast_power_grad():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.broadcast_power(a, b)
    _grad_check(sym, {"a": _rand(3, 4, seed=38, scale=0.3, shift=1.2),
                      "b": _rand(1, 4, seed=39, scale=0.5, shift=1.0)})


@pytest.mark.parametrize("op", ["elemwise_add", "elemwise_sub",
                                "elemwise_mul", "elemwise_div"])
def test_elemwise_binary_grad(op):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = getattr(mx.sym, op)(a, b)
    _grad_check(sym, {"a": _rand(3, 4, seed=40, shift=2.0),
                      "b": _rand(3, 4, seed=41, shift=0.8)})


# -------------------------------------------------------------- reductions
@pytest.mark.parametrize("op,axis,keepdims", [
    ("sum", None, False), ("sum", 1, True), ("sum", (0, 2), False),
    ("mean", None, False), ("mean", 1, False),
    ("prod", 1, False), ("nansum", 1, False),
])
def test_reduce_grad(op, axis, keepdims):
    data = mx.sym.Variable("data")
    kw = {"keepdims": keepdims}
    if axis is not None:
        kw["axis"] = axis
    sym = getattr(mx.sym, op)(data, **kw)
    _grad_check(sym, {"data": _rand(2, 3, 4, seed=42, shift=1.5)})


@pytest.mark.parametrize("ord", [1, 2])
def test_norm_grad(ord):
    data = mx.sym.Variable("data")
    sym = mx.sym.norm(data, ord=ord, axis=1)
    _grad_check(sym, {"data": _rand(3, 4, seed=43, shift=2.0)})


# -------------------------------------------------------- shape-manipulation
@pytest.mark.parametrize("build", [
    lambda d: mx.sym.Reshape(d, shape=(4, 6)),
    lambda d: mx.sym.transpose(d, axes=(1, 0, 2)),
    lambda d: mx.sym.Flatten(d),
    lambda d: mx.sym.expand_dims(d, axis=1),
    lambda d: mx.sym.flip(d, axis=1),
    lambda d: mx.sym.tile(d, reps=(2, 1, 1)),
    lambda d: mx.sym.repeat(d, repeats=2, axis=0),
    lambda d: mx.sym.slice(d, begin=(0, 1, 0), end=(2, 3, 2)),
    lambda d: mx.sym.slice_axis(d, axis=2, begin=1, end=3),
    lambda d: mx.sym.reverse(d, axis=0),
])
def test_shape_op_grad(build):
    data = mx.sym.Variable("data")
    sym = build(data)
    _grad_check(sym, {"data": _rand(2, 3, 4, seed=44)})


def test_concat_grad():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.Concat(a, b, dim=1)
    _grad_check(sym, {"a": _rand(2, 3, seed=45), "b": _rand(2, 2, seed=46)})


def test_stack_where_grad():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.stack(a, b, axis=1).mean() + \
        mx.sym.sum(mx.sym.where(a > 0, a * 2, b))
    _grad_check(sym, {"a": _rand(3, 4, seed=47, shift=0.6),
                      "b": _rand(3, 4, seed=48)})


# ------------------------------------------------------------- indexing ops
def test_embedding_weight_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.Embedding(data, name="e", input_dim=6, output_dim=3)
    idx = np.array([[0, 2], [5, 2]], "float32")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", data=idx.shape,
                         e_weight=(6, 3))
    w = _rand(6, 3, seed=49)
    ex.arg_dict["e_weight"][:] = w
    ex.forward(is_train=True, data=mx.nd.array(idx))
    ex.backward()
    g = ex.grad_dict["e_weight"].asnumpy()
    want = np.zeros((6, 3), "float32")
    for t in idx.ravel().astype(int):
        want[t] += 1
    assert_almost_equal(g, want, rtol=1e-5, atol=1e-6)


def test_take_grad():
    a = mx.sym.Variable("a")
    sym = mx.sym.take(a, mx.sym.Variable("idx"))
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", a=(4, 3),
                         idx=(2,))
    ex.arg_dict["a"][:] = _rand(4, 3, seed=50)
    ex.forward(is_train=True, a=ex.arg_dict["a"],
               idx=mx.nd.array([1.0, 1.0]))
    ex.backward()
    g = ex.grad_dict["a"].asnumpy()
    assert g[1].sum() == pytest.approx(6.0)   # row taken twice, dim 3
    assert g[0].sum() == 0


# -------------------------------------------------------- dtype consistency
_DTYPE_TOL = {"float16": (2e-2, 2e-2), "float32": (1e-5, 1e-6),
              "float64": (1e-5, 1e-6)}

# float64 requests run in float32 (documented deviation: no f64 units on
# TPU and the runtime keeps 32-bit defaults) — storage dtype reflects that
_EFFECTIVE = {"float16": "float16", "float32": "float32",
              "float64": "float32"}


@pytest.mark.parametrize("dtype", ["float16", "float32", "float64"])
@pytest.mark.parametrize("family", ["conv", "pool", "softmax", "fc", "bn"])
def test_forward_dtype_consistency(family, dtype):
    """Each family computes in the requested dtype and matches the fp32
    result within per-dtype tolerance (reference test_operator.py dtype
    sweeps)."""
    data = mx.sym.Variable("data")
    if family == "conv":
        sym = mx.sym.Convolution(data, name="c", kernel=(3, 3),
                                 num_filter=2, no_bias=True)
        shapes = {"data": (1, 2, 5, 5), "c_weight": (2, 2, 3, 3)}
    elif family == "pool":
        sym = mx.sym.Pooling(data, pool_type="avg", kernel=(2, 2),
                             stride=(2, 2))
        shapes = {"data": (1, 2, 4, 4)}
    elif family == "softmax":
        sym = mx.sym.softmax(data)
        shapes = {"data": (3, 4)}
    elif family == "fc":
        sym = mx.sym.FullyConnected(data, name="f", num_hidden=3,
                                    no_bias=True)
        shapes = {"data": (2, 4), "f_weight": (3, 4)}
    else:
        sym = mx.sym.BatchNorm(data, name="b", fix_gamma=False)
        shapes = {"data": (2, 2, 3, 3), "b_gamma": (2,), "b_beta": (2,)}
    vals = {k: _rand(*v, seed=51, shift=0.5) for k, v in shapes.items()}

    def run(dt):
        ex = sym.simple_bind(
            ctx=mx.cpu(), grad_req="null",
            type_dict={k: np.dtype(dt) for k in shapes}, **shapes)
        feeds = {k: mx.nd.array(v.astype(dt)) for k, v in vals.items()}
        for k, v in feeds.items():
            ex.arg_dict[k][:] = v
        out = ex.forward(is_train=False)[0]
        return out

    out = run(dtype)
    assert out.dtype == np.dtype(_EFFECTIVE[dtype])
    rtol, atol = _DTYPE_TOL[dtype]
    assert_almost_equal(out.asnumpy().astype("float32"),
                        run("float32").asnumpy(), rtol=rtol, atol=atol)


# ------------------------------------------------------------ special forms
def test_dropout_p0_and_eval_identity():
    data = mx.sym.Variable("data")
    x = _rand(3, 4, seed=52)
    out = mx.sym.Dropout(data, p=0.0).eval(
        data=mx.nd.array(x))[0].asnumpy()
    assert_almost_equal(out, x, rtol=1e-6, atol=1e-7)
    ex = mx.sym.Dropout(data, p=0.7).simple_bind(ctx=mx.cpu(),
                                                 grad_req="null",
                                                 data=x.shape)
    out = ex.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    assert_almost_equal(out, x, rtol=1e-6, atol=1e-7)  # eval mode: identity


def test_dropout_train_scales_survivors():
    data = mx.sym.Variable("data")
    p = 0.5
    ex = mx.sym.Dropout(data, p=p).simple_bind(ctx=mx.cpu(),
                                               grad_req="null",
                                               data=(64, 64))
    mx.random.seed(3)
    x = np.ones((64, 64), "float32")
    out = ex.forward(is_train=True, data=mx.nd.array(x))[0].asnumpy()
    kept = out[out != 0]
    assert kept.size > 0
    assert_almost_equal(kept, np.full_like(kept, 1 / (1 - p)), rtol=1e-5,
                        atol=1e-6)
    frac = kept.size / out.size
    assert 0.4 < frac < 0.6


def test_where_and_maximum_grad_routing():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.maximum(a, b)
    av = np.array([[1.0, -2.0], [3.0, 0.5]], "float32")
    bv = np.array([[0.0, 4.0], [1.0, 2.0]], "float32")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", a=av.shape,
                         b=bv.shape)
    ex.forward(is_train=True, a=mx.nd.array(av), b=mx.nd.array(bv))
    ex.backward()
    assert_almost_equal(ex.grad_dict["a"].asnumpy(),
                        (av > bv).astype("float32"), rtol=1e-6, atol=0)
    assert_almost_equal(ex.grad_dict["b"].asnumpy(),
                        (bv >= av).astype("float32"), rtol=1e-6, atol=0)
