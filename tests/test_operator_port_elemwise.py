"""Reference test_operator.py port, tranche 1: elementwise, scalar,
logic, and math-function cases.  Test names mirror the reference's
(tests/python/unittest/test_operator.py) one-for-one so the PARITY
inventory maps directly; bodies are written against this framework's API
and NumPy, not copied.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

_rng = np.random.RandomState


def _bind_grad(sym, **arrays):
    """Forward + ones-backward through the symbolic executor; returns
    (outputs, grads dict)."""
    args = {k: nd.array(v) for k, v in arrays.items()}
    grads = {k: nd.zeros(v.shape) for k, v in arrays.items()}
    exe = sym.bind(mx.cpu(), args, args_grad=grads)
    out = exe.forward(is_train=True)
    exe.backward(nd.ones(out[0].shape))
    return [o.asnumpy() for o in out], {k: g.asnumpy()
                                        for k, g in grads.items()}


def test_elementwise_sum():
    rng = _rng(0)
    for n in (1, 2, 4):
        arrays = {f"a{i}": rng.randn(3, 4).astype("float32")
                  for i in range(n)}
        sym = mx.sym.ElementWiseSum(*[mx.sym.Variable(f"a{i}")
                                      for i in range(n)], name="esum")
        out, grads = _bind_grad(sym, **arrays)
        assert_almost_equal(out[0], sum(arrays.values()))
        for g in grads.values():
            assert_almost_equal(g, np.ones((3, 4)))


def test_concat():
    rng = _rng(1)
    for axis in (0, 1, 2):
        parts = [rng.randn(2, 3, 4).astype("float32") for _ in range(3)]
        sym = mx.sym.Concat(*[mx.sym.Variable(f"p{i}") for i in range(3)],
                            dim=axis)
        out, grads = _bind_grad(sym, **{f"p{i}": p
                                        for i, p in enumerate(parts)})
        assert_almost_equal(out[0], np.concatenate(parts, axis=axis))
        for i in range(3):
            assert_almost_equal(grads[f"p{i}"], np.ones((2, 3, 4)))


def test_slice_channel():
    rng = _rng(2)
    x = rng.randn(2, 6, 3).astype("float32")
    outs = nd.SliceChannel(nd.array(x), num_outputs=3, axis=1)
    for i, o in enumerate(outs):
        assert_almost_equal(o.asnumpy(), x[:, 2 * i:2 * i + 2, :])
    # squeeze_axis collapses the unit axis
    outs = nd.SliceChannel(nd.array(x), num_outputs=6, axis=1,
                           squeeze_axis=True)
    assert outs[0].shape == (2, 3)


def test_swapaxes():
    rng = _rng(3)
    x = rng.randn(2, 3, 4).astype("float32")
    assert_almost_equal(nd.SwapAxis(nd.array(x), dim1=0, dim2=2).asnumpy(),
                        np.swapaxes(x, 0, 2))


def test_scalarop():
    x = _rng(4).randn(3, 4).astype("float32")
    a = nd.array(x)
    assert_almost_equal(((((4 - a) * 2.5) / 0.8) - 1.5).asnumpy(),
                        (4 - x) * 2.5 / 0.8 - 1.5, rtol=1e-5)
    # reverse subtraction / division
    assert_almost_equal((5.0 - a).asnumpy(), 5.0 - x)
    assert_almost_equal((2.0 / (a + 3)).asnumpy(), 2.0 / (x + 3),
                        rtol=1e-5)


def test_scalar_pow():
    x = np.abs(_rng(5).randn(3, 4)).astype("float32") + 0.5
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = a ** 3
    y.backward()
    assert_almost_equal(y.asnumpy(), x ** 3, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), 3 * x ** 2, rtol=1e-4)


def test_symbol_pow():
    rng = _rng(6)
    x = np.abs(rng.randn(2, 3)).astype("float32") + 0.5
    y = rng.rand(2, 3).astype("float32") + 0.5
    sym = mx.sym.Variable("x") ** mx.sym.Variable("y")
    out, grads = _bind_grad(sym, x=x, y=y)
    assert_almost_equal(out[0], x ** y, rtol=1e-5)
    assert_almost_equal(grads["x"], y * x ** (y - 1), rtol=1e-4)
    assert_almost_equal(grads["y"], x ** y * np.log(x), rtol=1e-4)


def test_pow_fn():
    x = _rng(7).rand(3, 3).astype("float32") + 0.5
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.power(2.0, a)
    y.backward()
    assert_almost_equal(y.asnumpy(), 2 ** x, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), np.log(2) * 2 ** x, rtol=1e-4)


def test_relu():
    x = _rng(8).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.relu(a)
    y.backward()
    assert_almost_equal(y.asnumpy(), np.maximum(x, 0))
    assert_almost_equal(a.grad.asnumpy(), (x > 0).astype("float32"))


def test_leaky_relu():
    x = _rng(9).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.LeakyReLU(a, act_type="leaky", slope=0.25)
    y.backward()
    assert_almost_equal(y.asnumpy(), np.where(x > 0, x, 0.25 * x))
    assert_almost_equal(a.grad.asnumpy(),
                        np.where(x > 0, 1.0, 0.25).astype("float32"))


def test_prelu():
    rng = _rng(10)
    x = rng.randn(2, 4, 3, 3).astype("float32")
    gamma = rng.rand(4).astype("float32") * 0.5
    sym = mx.sym.LeakyReLU(mx.sym.Variable("x"), mx.sym.Variable("gamma"),
                           act_type="prelu")
    out, grads = _bind_grad(sym, x=x, gamma=gamma)
    g = gamma.reshape(1, 4, 1, 1)
    assert_almost_equal(out[0], np.where(x > 0, x, g * x), rtol=1e-5)
    assert_almost_equal(grads["x"],
                        np.where(x > 0, 1.0, np.broadcast_to(g, x.shape)),
                        rtol=1e-5)
    assert_almost_equal(grads["gamma"],
                        np.where(x > 0, 0, x).sum(axis=(0, 2, 3)),
                        rtol=1e-4)


def test_selu():
    x = _rng(11).randn(4, 5).astype("float32")
    alpha, scale = 1.6732632423543772, 1.0507009873554805
    out = nd.LeakyReLU(nd.array(x), act_type="selu")
    ref = scale * np.where(x > 0, x, alpha * np.expm1(x))
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_gelu():
    x = _rng(12).randn(4, 5).astype("float32")
    out = nd.LeakyReLU(nd.array(x), act_type="gelu")
    ref = 0.5 * x * (1 + np.vectorize(np.math.erf)(x / np.sqrt(2))) \
        if hasattr(np, "math") else None
    import math
    ref = 0.5 * x * (1 + np.array([math.erf(v / math.sqrt(2))
                                   for v in x.ravel()])
                     .reshape(x.shape).astype("float32"))
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_sigmoid():
    x = _rng(13).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sigmoid(a)
    y.backward()
    s = 1 / (1 + np.exp(-x))
    assert_almost_equal(y.asnumpy(), s, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), s * (1 - s), rtol=1e-4)


def test_shape_array():
    x = nd.zeros((3, 4, 5))
    assert nd.shape_array(x).asnumpy().tolist() == [3, 4, 5]


def test_size_array():
    x = nd.zeros((3, 4, 5))
    assert int(nd.size_array(x).asnumpy().reshape(())) == 60


def test_hard_sigmoid():
    x = _rng(14).randn(3, 4).astype("float32") * 3
    out = nd.hard_sigmoid(nd.array(x), alpha=0.2, beta=0.5)
    assert_almost_equal(out.asnumpy(), np.clip(0.2 * x + 0.5, 0, 1),
                        rtol=1e-5)


def test_softsign():
    x = _rng(15).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.softsign(a)
    y.backward()
    assert_almost_equal(y.asnumpy(), x / (1 + np.abs(x)), rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), 1 / (1 + np.abs(x)) ** 2,
                        rtol=1e-4)


def test_binary_logic():
    rng = _rng(16)
    x = rng.randint(0, 3, (4, 4)).astype("float32")
    y = rng.randint(0, 3, (4, 4)).astype("float32")
    a, b = nd.array(x), nd.array(y)
    for op, ref in [(nd.broadcast_equal, x == y),
                    (nd.broadcast_not_equal, x != y),
                    (nd.broadcast_greater, x > y),
                    (nd.broadcast_greater_equal, x >= y),
                    (nd.broadcast_lesser, x < y),
                    (nd.broadcast_lesser_equal, x <= y),
                    (nd.broadcast_logical_and, (x != 0) & (y != 0)),
                    (nd.broadcast_logical_or, (x != 0) | (y != 0)),
                    (nd.broadcast_logical_xor, (x != 0) ^ (y != 0))]:
        assert_almost_equal(op(a, b).asnumpy(), ref.astype("float32"))
    # broadcasting across a unit axis
    z = rng.randint(0, 3, (1, 4)).astype("float32")
    assert_almost_equal(nd.broadcast_greater(a, nd.array(z)).asnumpy(),
                        (x > z).astype("float32"))


def test_unary_logic():
    x = np.array([[0.0, 1.5], [-2.0, 0.0]], "float32")
    assert_almost_equal(nd.logical_not(nd.array(x)).asnumpy(),
                        (x == 0).astype("float32"))


def test_binary_op_duplicate_input():
    x = _rng(17).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = a * a
    y.backward()
    assert_almost_equal(y.asnumpy(), x * x, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), 2 * x, rtol=1e-5)


def test_sign():
    x = np.array([[-2.0, 0.0, 3.5]], "float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sign(a)
    y.backward()
    assert_almost_equal(y.asnumpy(), np.sign(x))
    assert_almost_equal(a.grad.asnumpy(), np.zeros_like(x))


def test_round_ceil_floor():
    x = np.array([[-2.1, -0.5, 0.0, 0.5, 1.9, 2.5]], "float32")
    assert_almost_equal(nd.ceil(nd.array(x)).asnumpy(), np.ceil(x))
    assert_almost_equal(nd.floor(nd.array(x)).asnumpy(), np.floor(x))
    # MXNet round: half away from zero
    assert_almost_equal(nd.round(nd.array(x)).asnumpy(),
                        np.sign(x) * np.floor(np.abs(x) + 0.5))
    assert_almost_equal(nd.rint(nd.array(x)).asnumpy(), np.rint(x))
    assert_almost_equal(nd.fix(nd.array(x)).asnumpy(), np.fix(x))


def test_trunc():
    x = np.array([[-2.7, -0.2, 0.9, 3.6]], "float32")
    assert_almost_equal(nd.trunc(nd.array(x)).asnumpy(), np.trunc(x))


def test_rsqrt_cos_sin():
    x = _rng(18).rand(3, 4).astype("float32") + 0.5
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.rsqrt(a) + nd.cos(a) * nd.sin(a)
    y.backward()
    ref = 1 / np.sqrt(x) + np.cos(x) * np.sin(x)
    dref = -0.5 * x ** -1.5 + np.cos(2 * x)
    assert_almost_equal(y.asnumpy(), ref, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), dref, rtol=1e-4, atol=1e-5)


def test_maximum_minimum():
    rng = _rng(19)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    a, b = nd.array(x), nd.array(y)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        out = nd.maximum(a, b) + nd.minimum(a, b)
    out.backward()
    assert_almost_equal(out.asnumpy(), np.maximum(x, y) + np.minimum(x, y),
                        rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), np.ones_like(x))
    assert_almost_equal(b.grad.asnumpy(), np.ones_like(y))


def test_maximum_minimum_scalar():
    x = _rng(20).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = nd.maximum(a, 0.3) + nd.minimum(a, 0.7)
    out.backward()
    assert_almost_equal(out.asnumpy(),
                        np.maximum(x, 0.3) + np.minimum(x, 0.7), rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(),
                        (x > 0.3).astype("float32")
                        + (x < 0.7).astype("float32"))


def test_abs():
    x = _rng(21).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.abs(a)
    y.backward()
    assert_almost_equal(y.asnumpy(), np.abs(x))
    assert_almost_equal(a.grad.asnumpy(), np.sign(x))


@pytest.mark.parametrize("op,ref,dref", [
    ("reciprocal", lambda x: 1 / x, lambda x: -1 / x ** 2),
    ("cbrt", lambda x: np.cbrt(x), lambda x: 1 / (3 * np.cbrt(x) ** 2)),
    ("rcbrt", lambda x: 1 / np.cbrt(x),
     lambda x: -1 / (3 * x * np.cbrt(x))),
])
def test_reciprocal_cbrt_rcbrt_op(op, ref, dref):
    """reference test_reciprocal_op / test_cbrt_op / test_rcbrt_op."""
    x = _rng(22).rand(3, 4).astype("float32") + 0.5
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = getattr(nd, op)(a)
    y.backward()
    assert_almost_equal(y.asnumpy(), ref(x), rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), dref(x), rtol=1e-3, atol=1e-5)


def test_special_functions_using_scipy():
    try:
        from scipy import special as scipy_special
    except ImportError:
        pytest.skip("no scipy")
    x = _rng(23).rand(3, 4).astype("float32") + 0.3
    a = nd.array(x)
    assert_almost_equal(nd.gamma(a).asnumpy(), scipy_special.gamma(x),
                        rtol=1e-4)
    assert_almost_equal(nd.gammaln(a).asnumpy(),
                        scipy_special.gammaln(x), rtol=1e-4)
    assert_almost_equal(nd.erf(a).asnumpy(), scipy_special.erf(x),
                        rtol=1e-4)
    z = (x - 0.8) * 0.9                 # inside erfinv's (-1, 1) domain
    assert_almost_equal(nd.erfinv(nd.array(z)).asnumpy(),
                        scipy_special.erfinv(z), rtol=1e-3, atol=1e-5)


def test_mathematical():
    """The reference's big table of unary math ops, fwd + bwd."""
    rng = _rng(24)
    x01 = rng.rand(3, 4).astype("float32") * 0.8 + 0.1     # (0, 1)
    xpos = rng.rand(3, 4).astype("float32") + 0.5
    xany = rng.randn(3, 4).astype("float32")
    cases = [
        ("log", xpos, np.log, lambda x: 1 / x),
        ("log2", xpos, np.log2, lambda x: 1 / (x * np.log(2))),
        ("log10", xpos, np.log10, lambda x: 1 / (x * np.log(10))),
        ("log1p", xpos, np.log1p, lambda x: 1 / (1 + x)),
        ("exp", xany, np.exp, np.exp),
        ("expm1", xany, np.expm1, np.exp),
        ("sqrt", xpos, np.sqrt, lambda x: 0.5 / np.sqrt(x)),
        ("square", xany, np.square, lambda x: 2 * x),
        ("sin", xany, np.sin, np.cos),
        ("cos", xany, np.cos, lambda x: -np.sin(x)),
        ("tan", x01, np.tan, lambda x: 1 / np.cos(x) ** 2),
        ("arcsin", x01, np.arcsin, lambda x: 1 / np.sqrt(1 - x ** 2)),
        ("arccos", x01, np.arccos, lambda x: -1 / np.sqrt(1 - x ** 2)),
        ("arctan", xany, np.arctan, lambda x: 1 / (1 + x ** 2)),
        ("sinh", xany, np.sinh, np.cosh),
        ("cosh", xany, np.cosh, np.sinh),
        ("tanh", xany, np.tanh, lambda x: 1 - np.tanh(x) ** 2),
        ("arcsinh", xany, np.arcsinh, lambda x: 1 / np.sqrt(x ** 2 + 1)),
        ("arccosh", xpos + 1, np.arccosh,
         lambda x: 1 / np.sqrt(x ** 2 - 1)),
        ("arctanh", x01 * 0.8, np.arctanh, lambda x: 1 / (1 - x ** 2)),
        ("degrees", xany, np.degrees, lambda x: np.full_like(x, 180 / np.pi)),
        ("radians", xany, np.radians, lambda x: np.full_like(x, np.pi / 180)),
    ]
    for name, x, f, df in cases:
        a = nd.array(x)
        a.attach_grad()
        with autograd.record():
            y = getattr(nd, name)(a)
        y.backward()
        assert_almost_equal(y.asnumpy(), f(x), rtol=1e-4, atol=1e-5)
        assert_almost_equal(a.grad.asnumpy(), df(x), rtol=1e-3, atol=1e-4)


def test_clip():
    x = _rng(25).randn(3, 4).astype("float32") * 3
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.clip(a, -1.0, 1.0)
    y.backward()
    assert_almost_equal(y.asnumpy(), np.clip(x, -1, 1))
    assert_almost_equal(a.grad.asnumpy(),
                        ((x >= -1) & (x <= 1)).astype("float32"))


def test_unary_math_operators():
    """reference test_unary_math_operators: numeric-gradient pass over a
    sample of unary ops through the symbolic executor."""
    x = _rng(26).rand(3, 3).astype("float32") * 0.5 + 0.25
    for name in ("sqrt", "log", "sigmoid", "tanh", "arctan"):
        sym = getattr(mx.sym, name)(mx.sym.Variable("x"))
        check_numeric_gradient(sym, {"x": nd.array(x)}, rtol=0.05,
                               atol=1e-3)


def test_binary_math_operators():
    rng = _rng(27)
    x = rng.rand(3, 3).astype("float32") + 0.5
    y = rng.rand(3, 3).astype("float32") + 0.5
    for maker in (lambda a, b: mx.sym.hypot(a, b),
                  lambda a, b: a * b + b,
                  lambda a, b: mx.sym.pow(a, b)):
        sym = maker(mx.sym.Variable("x"), mx.sym.Variable("y"))
        check_numeric_gradient(sym, {"x": nd.array(x), "y": nd.array(y)},
                               rtol=0.05, atol=1e-3)


@pytest.mark.parametrize("op,npop", [
    ("broadcast_add", np.add), ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply), ("broadcast_div", np.divide),
    ("broadcast_mod", np.mod), ("broadcast_power", np.power),
    ("broadcast_maximum", np.maximum), ("broadcast_minimum", np.minimum),
    ("broadcast_hypot", np.hypot),
])
def test_broadcast_binary_op(op, npop):
    """reference test_broadcast_binary_op (bplus/bminus/.../bxor)."""
    rng = _rng(28)
    x = rng.rand(2, 3, 4).astype("float32") + 1.0
    for yshape in ((2, 3, 4), (1, 3, 4), (2, 1, 4), (2, 3, 1), (1, 1, 1)):
        y = rng.rand(*yshape).astype("float32") + 1.0
        got = getattr(nd, op)(nd.array(x), nd.array(y)).asnumpy()
        assert_almost_equal(got, npop(x, y).astype("float32"), rtol=1e-4)


@pytest.mark.parametrize("op,npop", [
    ("__add__", np.add), ("__sub__", np.subtract),
    ("__mul__", np.multiply), ("__truediv__", np.divide),
    ("__mod__", np.mod), ("__pow__", np.power),
    ("__ne__", np.not_equal), ("__eq__", np.equal),
])
def test_binary_op(op, npop):
    """reference test_binary_op (bplus/bminus/.../bneq on same shapes)."""
    rng = _rng(29)
    x = rng.rand(3, 4).astype("float32") + 1.0
    y = rng.rand(3, 4).astype("float32") + 1.0
    got = getattr(nd.array(x), op)(nd.array(y))
    assert_almost_equal(got.asnumpy(), npop(x, y).astype("float32"),
                        rtol=1e-4)


def test_bmod_int():
    rng = _rng(30)
    x = rng.randint(1, 100, (3, 4)).astype("int32")
    y = rng.randint(1, 10, (3, 4)).astype("int32")
    got = (nd.array(x, dtype="int32") % nd.array(y, dtype="int32"))
    assert (got.asnumpy() == x % y).all()


def test_all_finite():
    good = nd.array([[1.0, 2.0]])
    bad = nd.array([[np.nan, 1.0]])
    inf = nd.array([[np.inf, 1.0]])
    assert int(nd.all_finite(good).asscalar()) == 1
    assert int(nd.all_finite(bad).asscalar()) == 0
    assert int(nd.all_finite(inf).asscalar()) == 0
    # multi_all_finite across several arrays
    out = nd.multi_all_finite(good, bad, num_arrays=2)
    assert int(out.asscalar()) == 0


def test_cast():
    x = _rng(31).randn(3, 4).astype("float32") * 10
    x = np.abs(x)                      # uint8: stay in range
    for dst in ("float16", "float32", "int32", "uint8"):
        got = nd.Cast(nd.array(x), dtype=dst)
        assert got.dtype == np.dtype(dst)
        assert_almost_equal(np.asarray(got.asnumpy(), "float64"),
                            np.asarray(x.astype(dst), "float64"))


def test_cast_float32_to_float16():
    """Values straddling fp16 range: overflow goes inf, subnormals keep
    (reference CastStorage/CastCompute contract)."""
    import warnings as _w
    x = np.array([1e-8, 70000.0, -70000.0, 1.0009765625], "float32")
    got = nd.Cast(nd.array(x), dtype="float16").asnumpy()
    with _w.catch_warnings():
        _w.simplefilter("ignore", RuntimeWarning)   # expected overflow
        ref = x.astype("float16")
    assert got.dtype == np.float16
    assert np.isinf(got[1]) and np.isinf(got[2])
    assert_almost_equal(np.asarray(got, "float64"),
                        np.asarray(ref, "float64"))


def test_amp_multicast():
    rng = _rng(32)
    a = nd.array(rng.randn(2, 2).astype("float16"))
    b = nd.array(rng.randn(2, 2).astype("float32"))
    outs = nd.amp_multicast(a, b, num_outputs=2)
    assert outs[0].dtype == np.float32 and outs[1].dtype == np.float32
    c = nd.amp_cast(b, dtype="float16")
    assert c.dtype == np.float16


def test_blockgrad():
    x = _rng(33).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(a) * 2 + a
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.ones_like(x))  # only +a path


def test_div_sqrt_dim():
    x = _rng(34).randn(2, 3, 16).astype("float32")
    got = nd.contrib.div_sqrt_dim(nd.array(x))
    assert_almost_equal(got.asnumpy(), x / np.sqrt(16), rtol=1e-5)


def test_quadratic_function():
    """reference test_quadratic_function: the contrib quadratic op
    a*x^2 + b*x + c, fwd + bwd."""
    x = _rng(35).randn(3, 4).astype("float32")
    a_, b_, c_ = 2.0, -0.5, 1.5
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.contrib.quadratic(a, a=a_, b=b_, c=c_)
    y.backward()
    assert_almost_equal(y.asnumpy(), a_ * x ** 2 + b_ * x + c_, rtol=1e-5)
    assert_almost_equal(a.grad.asnumpy(), 2 * a_ * x + b_, rtol=1e-5)


def test_histogram():
    x = np.array([0.1, 0.5, 2.5, 2.6, 9.9, 7.3], "float32")
    cnt, edges = nd.histogram(nd.array(x), bin_cnt=10, range=(0.0, 10.0))
    ref_cnt, ref_edges = np.histogram(x, bins=10, range=(0.0, 10.0))
    assert (cnt.asnumpy().astype("int64") == ref_cnt).all()
    assert_almost_equal(edges.asnumpy(), ref_edges.astype("float32"))


def test_sequence_last():
    rng = _rng(36)
    x = rng.randn(4, 3, 5).astype("float32")      # (T, N, C)
    lens = np.array([2, 4, 1], "float32")
    got = nd.SequenceLast(nd.array(x), nd.array(lens),
                          use_sequence_length=True)
    ref = np.stack([x[int(l) - 1, i] for i, l in enumerate(lens)])
    assert_almost_equal(got.asnumpy(), ref)


def test_sequence_mask():
    rng = _rng(37)
    x = rng.randn(4, 3, 2).astype("float32")
    lens = np.array([2, 3, 1], "float32")
    got = nd.SequenceMask(nd.array(x), nd.array(lens),
                          use_sequence_length=True, value=-1.0)
    ref = x.copy()
    for i, l in enumerate(lens):
        ref[int(l):, i] = -1.0
    assert_almost_equal(got.asnumpy(), ref)


def test_sequence_reverse():
    rng = _rng(38)
    x = rng.randn(4, 3, 2).astype("float32")
    lens = np.array([2, 4, 3], "float32")
    got = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True)
    ref = x.copy()
    for i, l in enumerate(lens):
        ref[:int(l), i] = x[:int(l), i][::-1]
    assert_almost_equal(got.asnumpy(), ref)
    # no lengths: full flip on time axis
    got = nd.SequenceReverse(nd.array(x))
    assert_almost_equal(got.asnumpy(), x[::-1])
