"""ONNX converter round-trips (reference
``tests/python-pytest/onnx/test_onnxruntime*`` strategy, adapted to the
wheel-free dict graphs: export a Symbol → dict graph → import → same
outputs on the same inputs).  Only protobuf emission is wheel-gated; the
converter tables themselves are fully exercised here.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as mxonnx


def _outputs(sym, params, data, extra=None):
    shapes = {"data": data.shape}
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", **shapes)
    feed = dict(params)
    ex.copy_params_from({k: v for k, v in feed.items()
                         if k in ex.arg_dict},
                        {k: v for k, v in feed.items()
                         if k in ex.aux_dict}, allow_extra_params=True)
    return [o.asnumpy() for o in ex.forward(is_train=False, data=mx.nd.array(data))]


def _roundtrip(sym, params, data, aux=None):
    all_params = dict(params)
    all_params.update(aux or {})
    graph = mxonnx.export_graph(sym, all_params, data.shape)
    sym2, args2, auxs2 = mxonnx.import_graph(graph)
    out1 = _outputs(sym, all_params, data)
    p2 = dict(args2)
    p2.update(auxs2)
    out2 = _outputs(sym2, p2, data)
    assert len(out1) == len(out2)
    for a, b in zip(out1, out2):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    return graph


def _init_params(sym, data_shape, seed=0):
    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    params = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        params[name] = mx.nd.array(rng.randn(*shp) * 0.1)
    aux = {}
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        init = np.zeros(shp) if "mean" in name else np.abs(rng.rand(*shp)) + .5
        aux[name] = mx.nd.array(init)
    return params, aux


def test_mlp_roundtrip():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    net = mx.sym.softmax(net, name="prob", axis=-1)
    params, aux = _init_params(net, (3, 8))
    g = _roundtrip(net, params, np.random.RandomState(1).randn(3, 8)
                   .astype("float32"), aux)
    assert any(n["op_type"] == "Gemm" for n in g["nodes"])


def test_lenet_roundtrip():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, name="c1", kernel=(5, 5), num_filter=6,
                             pad=(2, 2))
    net = mx.sym.Activation(net, name="t1", act_type="tanh")
    net = mx.sym.Pooling(net, name="p1", pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, name="c2", kernel=(5, 5), num_filter=16)
    net = mx.sym.Activation(net, name="t2", act_type="tanh")
    net = mx.sym.Pooling(net, name="p2", pool_type="avg", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Flatten(net, name="fl")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params, aux = _init_params(net, (2, 1, 28, 28))
    g = _roundtrip(net, params,
                   np.random.RandomState(2).randn(2, 1, 28, 28)
                   .astype("float32"), aux)
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Conv" in ops and "MaxPool" in ops and "AveragePool" in ops
    assert "Softmax" in ops   # SoftmaxOutput exports as inference Softmax


def test_residual_conv_bn_roundtrip():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, name="c1", kernel=(3, 3), num_filter=8,
                            pad=(1, 1), no_bias=True)
    b1 = mx.sym.BatchNorm(c1, name="bn1", fix_gamma=True)
    r1 = mx.sym.Activation(b1, name="r1", act_type="relu")
    c2 = mx.sym.Convolution(r1, name="c2", kernel=(3, 3), num_filter=8,
                            pad=(1, 1), no_bias=True)
    b2 = mx.sym.BatchNorm(c2, name="bn2", fix_gamma=False)
    s = mx.sym.elemwise_add(b2, data, name="res")
    net = mx.sym.Pooling(s, name="gap", pool_type="avg", global_pool=True,
                         kernel=(1, 1))
    net = mx.sym.Flatten(net, name="fl")
    params, aux = _init_params(net, (2, 8, 8, 8))
    g = _roundtrip(net, params,
                   np.random.RandomState(3).randn(2, 8, 8, 8)
                   .astype("float32"), aux)
    ops = [n["op_type"] for n in g["nodes"]]
    assert "BatchNormalization" in ops and "GlobalAveragePool" in ops
    # fix_gamma=True exports gamma as ones
    np.testing.assert_array_equal(g["initializers"]["bn1_gamma"],
                                  np.ones(8, "float32"))


def test_embedding_gather_roundtrip():
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, name="emb", input_dim=20, output_dim=6)
    net = mx.sym.mean(emb, name="m", axis=1)
    rng = np.random.RandomState(4)
    params = {"emb_weight": mx.nd.array(rng.randn(20, 6))}
    graph = mxonnx.export_graph(net, params, (2, 5), input_dtype="int32")
    assert any(n["op_type"] == "Gather" for n in graph["nodes"])
    sym2, args2, auxs2 = mxonnx.import_graph(graph)
    x = rng.randint(0, 20, (2, 5)).astype("int32")
    ex1 = net.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 5))
    ex1.copy_params_from(params)
    o1 = ex1.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    ex2 = sym2.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 5))
    ex2.copy_params_from(args2)
    o2 = ex2.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=1e-5)


def test_unsupported_op_raises_cleanly():
    data = mx.sym.Variable("data")
    net = mx.sym.SequenceReverse(data, name="sr")
    with pytest.raises(NotImplementedError, match="no ONNX converter"):
        mxonnx.export_graph(net, {}, (4, 2, 3))


def test_protobuf_step_is_gated():
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data, name="f")
    graph = mxonnx.export_graph(net, {}, (2, 3, 4))
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False
    if have_onnx:
        pytest.skip("onnx wheel present; gating not exercised")
    with pytest.raises(ImportError, match="onnx"):
        mxonnx.graph_to_proto(graph)
    with pytest.raises(ImportError, match="onnx"):
        mxonnx.proto_to_graph("nonexistent.onnx")


def test_bn_moving_stats_import_as_aux():
    """Moving mean/var must come back as auxiliary states (not arguments)
    and be honored at inference."""
    data = mx.sym.Variable("data")
    b = mx.sym.BatchNorm(data, name="bn1")
    params = {"bn1_gamma": mx.nd.array([2.0, 3.0]),
              "bn1_beta": mx.nd.array([0.5, -0.5]),
              "bn1_moving_mean": mx.nd.array([1.0, 2.0]),
              "bn1_moving_var": mx.nd.array([4.0, 9.0])}
    g = mxonnx.export_graph(b, params, (1, 2, 2, 2))
    sym2, args2, auxs2 = mxonnx.import_graph(g)
    assert sorted(sym2.list_auxiliary_states()) == \
        ["bn1_moving_mean", "bn1_moving_var"]
    assert sorted(auxs2) == ["bn1_moving_mean", "bn1_moving_var"]
    x = np.full((1, 2, 2, 2), 3.0, "float32")
    ex = sym2.simple_bind(ctx=mx.cpu(), grad_req="null", data=(1, 2, 2, 2))
    ex.copy_params_from(args2, auxs2)
    out = ex.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    # fix_gamma=True originally → gamma exported as ones:
    # ch0: (3-1)/sqrt(4+eps)+0.5 ≈ 1.5 ; ch1: (3-2)/3 - 0.5 ≈ -0.1667
    np.testing.assert_allclose(out[0, 0, 0, 0], 1.5, atol=1e-3)
    np.testing.assert_allclose(out[0, 1, 0, 0], -1 / 6, atol=1e-3)


def test_model_zoo_resnet18_export_roundtrip(tmp_path):
    """Flagship chain (reference mx2onnx's real use): Gluon model-zoo net →
    hybridize → export (dual-file checkpoint) → load → ONNX dict →
    import → identical inference outputs."""
    mx.random.seed(0)
    net = mx.gluon.model_zoo.vision.get_model("resnet18_v1", classes=10)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 32, 32))
    net.hybridize()
    net(x)
    prefix = str(tmp_path / "r18")
    net.export(prefix)
    sym, args, auxs = (mx.sym.load(prefix + "-symbol.json"),
                       *_load_checkpoint_params(prefix))
    params = dict(args)
    params.update(auxs)
    graph = mxonnx.export_graph(sym, params, (1, 3, 32, 32))
    sym2, args2, auxs2 = mxonnx.import_graph(graph)
    xv = x.asnumpy()
    o1 = _outputs(sym, params, xv)[0]
    p2 = dict(args2)
    p2.update(auxs2)
    o2 = _outputs(sym2, p2, xv)[0]
    np.testing.assert_allclose(o1, o2, rtol=2e-4, atol=2e-5)


def _load_checkpoint_params(prefix):
    loaded = mx.nd.load(prefix + "-0000.params")
    args, auxs = {}, {}
    for k, v in loaded.items():
        (args if k.startswith("arg:") else auxs)[k.split(":", 1)[1]] = v
    return args, auxs


def test_bert_tiny_onnx_roundtrip(tmp_path):
    """Transformer coverage: BERT-tiny exports symbolically, converts to
    the ONNX dict (LayerNormalization/MatMul/Erf/GatherND/Split/...), and
    imports back with identical outputs on all four heads."""
    from mxnet_tpu.models import get_bert_model
    mx.random.seed(0)
    net = get_bert_model("bert_tiny", vocab_size=50, max_length=32,
                         dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(0)
    tok = mx.nd.array(rng.randint(0, 50, (2, 8)), dtype="int32")
    seg = mx.nd.array(rng.randint(0, 2, (2, 8)), dtype="int32")
    msk = mx.nd.ones((2, 8))
    pos = mx.nd.array(rng.randint(0, 8, (2, 3)), dtype="int32")
    net.hybridize()
    ref = [o.asnumpy() for o in net(tok, seg, msk, pos)]
    prefix = str(tmp_path / "bt")
    net.export(prefix)
    sym = mx.sym.load(prefix + "-symbol.json")
    args, auxs = _load_checkpoint_params(prefix)
    params = dict(args)
    params.update(auxs)
    ins = [a for a in sym.list_arguments() if a not in params]
    feeds = dict(zip(ins, [tok, seg, msk, pos]))
    graph = mxonnx.export_graph(sym, params,
                                {k: v.shape for k, v in feeds.items()})
    ops = {n["op_type"] for n in graph["nodes"]}
    assert {"LayerNormalization", "MatMul", "Erf",
            "GatherND", "Split"} <= ops
    sym2, args2, auxs2 = mxonnx.import_graph(graph)

    def run(s, a, x):
        ex = s.simple_bind(ctx=mx.cpu(), grad_req="null",
                           **{k: v.shape for k, v in feeds.items()})
        ex.copy_params_from(a, x, allow_extra_params=True)
        return [o.asnumpy() for o in ex.forward(is_train=False, **feeds)]
    o1 = run(sym, args, auxs)
    o2 = run(sym2, args2, auxs2)
    for a, b in zip(o1, o2):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_foreign_graph_import():
    """Import a hand-written ONNX dict (as a foreign exporter would emit):
    Pow/ReduceSum/Pad have no mx-export source here, only importers."""
    graph = {
        "nodes": [
            {"op_type": "Pad", "name": "p", "inputs": ["data"],
             "outputs": ["p"], "attrs": {"pads": (0, 1, 0, 1),
                                         "mode": "constant", "value": 2.0}},
            {"op_type": "Pow", "name": "q", "inputs": ["p", "e"],
             "outputs": ["q"], "attrs": {}},
            {"op_type": "ReduceSum", "name": "r", "inputs": ["q"],
             "outputs": ["r"], "attrs": {"axes": (1,), "keepdims": 0}},
        ],
        "inputs": [{"name": "data", "shape": (2, 3), "dtype": "float32"}],
        "outputs": [{"name": "r"}],
        "initializers": {"e": np.asarray(2.0, "float32")},
    }
    sym, args, _ = mxonnx.import_graph(graph)
    x = np.abs(np.random.RandomState(0).randn(2, 3)).astype("float32")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 3))
    ex.copy_params_from(args)
    out = ex.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    padded = np.pad(x, ((0, 0), (1, 1)), constant_values=2.0)
    np.testing.assert_allclose(out, (padded ** 2).sum(axis=1), rtol=1e-5)
