"""Subprocess entry for the two-simulated-host collective-sanitizer drills
(tests/test_divergence.py and the ci analyze stage).

Each invocation is one simulated host (``--host h/H``, the PR 9 harness
identity) running under ``MXNET_SANITIZE=collectives`` with the fingerprint
streams shared through ``--dir``.  The script runs ``--steps`` SPMD train
steps, then a sharded checkpoint save (whose commit barrier is the
cross-check sync point), then a final explicit sanitizer sync.

``--diverge-at N`` makes THIS host issue a different collective at step N —
a pipeline schedule instead of the train step, the planted SPMD bug (think:
a host-conditional branch reaching a different collective).  The clean
hosts then raise :class:`CollectiveDivergenceError` at their next sync
point instead of hanging in the barrier; the divergent host raises at its
own post-save check.  Exit codes: 0 = clean run completed, 3 =
CollectiveDivergenceError (stdout carries the message for the parent to
inspect), 4 = CollectiveStallTimeout.

``--stall-at N`` makes this host stop issuing collectives after step N
(a simulated deadlock elsewhere): its peers' watchdog must dump every
host's position and raise instead of waiting forever.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

BATCH = 16
FEATS = 8
N_CLASSES = 4


def build_trainer(seed=0):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                    make_mesh)
    mx.random.seed(seed)
    np.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="div_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu", in_units=FEATS),
                mx.gluon.nn.Dense(N_CLASSES, in_units=16))
    net.initialize()
    return SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer("sgd", 1e-2),
                       make_mesh(n_devices=4, dp=2, tp=2), nan_guard=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True,
                    help="shared dir: fingerprint streams + checkpoint")
    ap.add_argument("--host", required=True, help="h/H simulated identity")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--diverge-at", type=int, default=None)
    ap.add_argument("--stall-at", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=20.0,
                    help="watchdog + commit-barrier bound")
    args = ap.parse_args(argv)

    os.environ["MXNET_SANITIZE"] = "collectives"
    os.environ["MXNET_CKPT_HOST"] = args.host
    os.environ["MXNET_SANITIZE_DIR"] = args.dir

    import numpy as np
    import jax.numpy as jnp
    from mxnet_tpu.analysis import divergence as div
    from mxnet_tpu.analysis import sanitizer as san
    from mxnet_tpu.parallel import (CommitBarrierTimeout,
                                    SPMDCheckpointManager, pipeline)

    assert san.collectives, "MXNET_SANITIZE=collectives must arm at import"
    host, _, host_count = args.host.partition("/")
    host, host_count = int(host), int(host_count)

    tr = build_trainer()
    rng = np.random.RandomState(7)
    batches = [(rng.randn(BATCH, FEATS).astype("float32"),
                rng.randint(0, N_CLASSES, BATCH).astype("float32"))
               for _ in range(args.steps)]
    try:
        for i, (x, y) in enumerate(batches):
            if args.stall_at is not None and i >= args.stall_at:
                print(f"STALLED host={host} at step {i}", flush=True)
                return 0        # stops issuing collectives; peers' watchdog
            if args.diverge_at is not None and i == args.diverge_at:
                # the planted SPMD bug: this host issues a DIFFERENT
                # collective at the same sequence position
                from mxnet_tpu.parallel import make_mesh
                mesh = make_mesh(n_devices=8, pp=8)
                pipeline.gpipe(lambda p, xx: xx * p.sum(),
                               jnp.ones((8, 4)), jnp.ones((16, 4)), mesh, 4)
                print(f"DIVERGED host={host} at step {i}", flush=True)
            else:
                tr.step(x, y)
        mgr = SPMDCheckpointManager(args.dir, host_index=host,
                                    host_count=host_count,
                                    barrier_timeout_s=args.timeout)
        mgr.save(tr._t, tr)
        div.sync("post-save", timeout_s=args.timeout)
    except san.CollectiveDivergenceError as e:
        print(f"DIVERGENCE host={host}: {e}", flush=True)
        return 3
    except san.CollectiveStallTimeout as e:
        print(f"STALL-TIMEOUT host={host}: {e}", flush=True)
        return 4
    except CommitBarrierTimeout as e:
        # a stalled peer surfaces as the (bounded) commit-barrier timeout,
        # whose message now carries the per-host collective position dump
        print(f"STALL-TIMEOUT host={host}: {e}", flush=True)
        return 4
    print(f"CLEAN host={host} collectives={san.stats()['collectives']} "
          f"violations={san.stats()['violations']}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
