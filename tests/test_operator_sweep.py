"""Broad per-op numeric contracts vs NumPy/SciPy — the families not yet
covered by the focused operator test files (mirrors reference
``tests/python/unittest/test_operator.py``'s breadth)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

RNG = np.random.RandomState(7)


def A(*shape, scale=1.0, offset=0.0):
    return (RNG.randn(*shape) * scale + offset).astype("float32")


def close(got, want, rtol=1e-5, atol=1e-5):
    np.testing.assert_allclose(
        got.asnumpy() if hasattr(got, "asnumpy") else got,
        want, rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# unary math zoo
# ---------------------------------------------------------------------------
UNARY = [
    ("arcsin", np.arcsin, A(3, 4, scale=0.4)),
    ("arccos", np.arccos, A(3, 4, scale=0.4)),
    ("arctan", np.arctan, A(3, 4)),
    ("arcsinh", np.arcsinh, A(3, 4)),
    ("arccosh", np.arccosh, A(3, 4, scale=0.3, offset=2.0)),
    ("arctanh", np.arctanh, A(3, 4, scale=0.4)),
    ("sinh", np.sinh, A(3, 4)),
    ("cosh", np.cosh, A(3, 4)),
    ("log2", np.log2, np.abs(A(3, 4)) + 0.1),
    ("log10", np.log10, np.abs(A(3, 4)) + 0.1),
    ("cbrt", np.cbrt, A(3, 4)),
    ("rcbrt", lambda x: 1.0 / np.cbrt(x), np.abs(A(3, 4)) + 0.2),
    ("degrees", np.degrees, A(3, 4)),
    ("radians", np.radians, A(3, 4)),
    ("logical_not", lambda x: (x == 0).astype(np.float32),
     np.array([[0., 1., 2.], [-1., 0., 3.]], np.float32)),
    ("softsign", lambda x: x / (1 + np.abs(x)), A(3, 4)),
    ("ones_like", np.ones_like, A(3, 4)),
]


@pytest.mark.parametrize("name,ref,x", UNARY, ids=[u[0] for u in UNARY])
def test_unary_math(name, ref, x):
    close(getattr(nd, name)(nd.array(x)), ref(x), rtol=1e-4, atol=1e-5)


def test_erf_erfinv_gammaln():
    from scipy import special
    x = A(3, 4, scale=0.8)
    close(nd.erf(nd.array(x)), special.erf(x), rtol=1e-4)
    y = A(3, 4, scale=0.4)
    close(nd.erfinv(nd.array(y)), special.erfinv(y), rtol=1e-3, atol=1e-4)
    z = np.abs(A(3, 4)) + 0.5
    close(nd.gammaln(nd.array(z)), special.gammaln(z), rtol=1e-4, atol=1e-4)


def test_softplus_softmin_hard_sigmoid():
    x = A(3, 4)
    close(nd.softplus(nd.array(x)), np.log1p(np.exp(x)), rtol=1e-4)
    e = np.exp(-x - (-x).max(axis=-1, keepdims=True))
    close(nd.softmin(nd.array(x), axis=-1), e / e.sum(-1, keepdims=True),
          rtol=1e-4)
    close(nd.hard_sigmoid(nd.array(x)),
          np.clip(0.2 * x + 0.5, 0, 1), rtol=1e-5)


def test_smooth_l1():
    x = np.array([-2.0, -0.4, 0.0, 0.4, 2.0], np.float32)
    s = 1.0
    want = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    close(nd.smooth_l1(nd.array(x), scalar=s), want)


# ---------------------------------------------------------------------------
# broadcast binary family
# ---------------------------------------------------------------------------
BCAST = [
    ("broadcast_plus", np.add), ("broadcast_minus", np.subtract),
    ("broadcast_sub", np.subtract), ("broadcast_div", np.divide),
    ("broadcast_mod", np.mod), ("broadcast_minimum", np.minimum),
    ("broadcast_hypot", np.hypot),
    ("broadcast_equal", lambda a, b: (a == b).astype(np.float32)),
    ("broadcast_not_equal", lambda a, b: (a != b).astype(np.float32)),
    ("broadcast_greater", lambda a, b: (a > b).astype(np.float32)),
    ("broadcast_greater_equal", lambda a, b: (a >= b).astype(np.float32)),
    ("broadcast_lesser", lambda a, b: (a < b).astype(np.float32)),
    ("broadcast_lesser_equal", lambda a, b: (a <= b).astype(np.float32)),
    ("broadcast_logical_and",
     lambda a, b: ((a != 0) & (b != 0)).astype(np.float32)),
    ("broadcast_logical_or",
     lambda a, b: ((a != 0) | (b != 0)).astype(np.float32)),
    ("broadcast_logical_xor",
     lambda a, b: ((a != 0) ^ (b != 0)).astype(np.float32)),
]


@pytest.mark.parametrize("name,ref", BCAST, ids=[b[0] for b in BCAST])
def test_broadcast_binary(name, ref):
    a = np.round(A(2, 3, 4) * 2) + 3.0
    b = np.round(A(1, 3, 1) * 2) + 2.0
    close(getattr(nd, name)(nd.array(a), nd.array(b)), ref(a, b), rtol=1e-5)


def test_elemwise_family_and_minimum():
    a, b = A(3, 4), A(3, 4)
    close(nd.elemwise_add(nd.array(a), nd.array(b)), a + b)
    close(nd.elemwise_sub(nd.array(a), nd.array(b)), a - b)
    close(nd.elemwise_mul(nd.array(a), nd.array(b)), a * b)
    close(nd._minimum(nd.array(a), nd.array(b)), np.minimum(a, b))
    close(nd._hypot(nd.array(a), nd.array(b)), np.hypot(a, b))
    close(nd._logical_or(nd.array(a), nd.array(b)),
          ((a != 0) | (b != 0)).astype(np.float32))


# ---------------------------------------------------------------------------
# scalar-op family (incl. reversed variants)
# ---------------------------------------------------------------------------
SCALAR = [
    ("_minus_scalar", lambda x, s: x - s),
    ("_rminus_scalar", lambda x, s: s - x),
    ("_mul_scalar", lambda x, s: x * s),
    ("_div_scalar", lambda x, s: x / s),
    ("_rdiv_scalar", lambda x, s: s / x),
    ("_mod_scalar", lambda x, s: np.mod(x, s)),
    ("_rmod_scalar", lambda x, s: np.mod(s, x)),
    ("_power_scalar", lambda x, s: np.power(x, s)),
    ("_rpower_scalar", lambda x, s: np.power(s, x)),
    ("_maximum_scalar", lambda x, s: np.maximum(x, s)),
    ("_minimum_scalar", lambda x, s: np.minimum(x, s)),
    ("_hypot_scalar", lambda x, s: np.hypot(x, s)),
    ("_equal_scalar", lambda x, s: (x == s).astype(np.float32)),
    ("_not_equal_scalar", lambda x, s: (x != s).astype(np.float32)),
    ("_greater_scalar", lambda x, s: (x > s).astype(np.float32)),
    ("_greater_equal_scalar", lambda x, s: (x >= s).astype(np.float32)),
    ("_lesser_scalar", lambda x, s: (x < s).astype(np.float32)),
    ("_lesser_equal_scalar", lambda x, s: (x <= s).astype(np.float32)),
    ("_logical_and_scalar", lambda x, s: ((x != 0) & (s != 0)).astype(np.float32)),
    ("_logical_or_scalar", lambda x, s: ((x != 0) | (s != 0)).astype(np.float32)),
    ("_logical_xor_scalar", lambda x, s: ((x != 0) ^ (s != 0)).astype(np.float32)),
]


@pytest.mark.parametrize("name,ref", SCALAR, ids=[s[0] for s in SCALAR])
def test_scalar_ops(name, ref):
    x = np.round(A(3, 4) * 2) + 2.5
    close(getattr(nd, name)(nd.array(x), scalar=2.0), ref(x, 2.0), rtol=1e-5)


# ---------------------------------------------------------------------------
# linalg suite (reference src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------
def _spd(n):
    m = A(n, n) * 0.5
    return (m @ m.T + n * np.eye(n)).astype("float32")


def test_linalg_gemm_gemm2():
    a, b, c = A(2, 3, 4), A(2, 4, 5), A(2, 3, 5)
    close(nd.linalg.gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=3.0),
          2.0 * a @ b + 3.0 * c, rtol=1e-4)
    close(nd.linalg.gemm2(nd.array(a), nd.array(b), alpha=0.5),
          0.5 * a @ b, rtol=1e-4)
    # transpose flags
    bt = A(2, 5, 4)
    close(nd.linalg.gemm2(nd.array(a), nd.array(bt), transpose_b=True),
          np.matmul(a, np.swapaxes(bt, 1, 2)), rtol=1e-4)
    at = A(2, 4, 3)
    close(nd.linalg.gemm2(nd.array(at), nd.array(b), transpose_a=True),
          np.matmul(np.swapaxes(at, 1, 2), b), rtol=1e-4)


def test_linalg_potrf_potri_sumlogdiag():
    s = _spd(4)
    L = np.linalg.cholesky(s)
    close(nd.linalg.potrf(nd.array(s)), L, rtol=1e-4, atol=1e-4)
    close(nd.linalg.potri(nd.array(L)), np.linalg.inv(s), rtol=1e-3, atol=1e-3)
    close(nd.linalg.sumlogdiag(nd.array(L)),
          np.log(np.diag(L)).sum(), rtol=1e-4)


def test_linalg_trmm_trsm():
    Lw = np.tril(A(4, 4)) + 4 * np.eye(4, dtype=np.float32)
    b = A(4, 3)
    close(nd.linalg.trmm(nd.array(Lw), nd.array(b), alpha=1.0),
          Lw @ b, rtol=1e-4, atol=1e-4)
    close(nd.linalg.trsm(nd.array(Lw), nd.array(Lw @ b), alpha=1.0),
          b, rtol=1e-3, atol=1e-3)


def test_linalg_syrk_det_slogdet_inverse():
    a = A(3, 4)
    close(nd.linalg.syrk(nd.array(a), alpha=1.0), a @ a.T, rtol=1e-4)
    s = _spd(3)
    close(nd.linalg.det(nd.array(s)), np.linalg.det(s), rtol=1e-3)
    sign, logdet = np.linalg.slogdet(s)
    got = nd.linalg.slogdet(nd.array(s))
    close(got[0], sign, rtol=1e-4)
    close(got[1], logdet, rtol=1e-4)
    close(nd.linalg.inverse(nd.array(s)), np.linalg.inv(s), rtol=1e-3,
          atol=1e-4)


def test_linalg_gelqf_syevd():
    a = A(3, 5)
    q, l = nd.linalg.gelqf(nd.array(a))     # reference order: Q first
    qn, ln = q.asnumpy(), l.asnumpy()
    assert qn.shape == (3, 5) and ln.shape == (3, 3)
    close(ln @ qn, a, rtol=1e-3, atol=1e-4)             # A = L Q
    close(qn @ qn.T, np.eye(3), rtol=1e-3, atol=1e-4)   # Q orthonormal rows
    assert np.all(np.triu(ln, 1) == 0)                  # L lower-triangular
    s = _spd(4)
    u, lam = nd.linalg.syevd(nd.array(s))
    un, lamn = u.asnumpy(), lam.asnumpy()
    # rows of U are eigenvectors: U^T diag(lam) U == S  (reference layout)
    close(un.T @ np.diag(lamn) @ un, s, rtol=1e-3, atol=1e-3)


def test_linalg_diag_trian_helpers():
    d = np.array([1.0, 2.0, 3.0], np.float32)
    close(nd.linalg.makediag(nd.array(d)), np.diag(d))
    m = A(4, 4)
    close(nd.linalg.extractdiag(nd.array(m)), np.diag(m))
    # maketrian/extracttrian round-trip on the lower triangle
    tri = nd.linalg.extracttrian(nd.array(m))
    back = nd.linalg.maketrian(tri)
    close(back, np.tril(m), rtol=1e-5)


def test_khatri_rao():
    a, b = A(2, 3), A(4, 3)
    want = np.stack([np.kron(a[:, i], b[:, i]) for i in range(3)], axis=1)
    close(nd.khatri_rao(nd.array(a), nd.array(b)), want, rtol=1e-5)


# ---------------------------------------------------------------------------
# matrix utilities
# ---------------------------------------------------------------------------
def test_reverse_depth_space_reshape_like():
    x = A(2, 3, 4)
    close(nd.reverse(nd.array(x), axis=1), x[:, ::-1, :])
    d = A(1, 8, 2, 3)
    got = nd.depth_to_space(nd.array(d), block_size=2)
    assert got.shape == (1, 2, 4, 6)
    back = nd.space_to_depth(got, block_size=2)
    close(back, d, rtol=1e-6)
    r = A(2, 6)
    close(nd.reshape_like(nd.array(r), nd.array(A(3, 4))), r.reshape(3, 4))


def test_ravel_unravel_roundtrip():
    shape = (3, 4, 5)
    flat = np.array([0, 7, 23, 59], np.float32)
    multi = nd.unravel_index(nd.array(flat), shape=shape)
    want = np.stack(np.unravel_index(flat.astype(int), shape)).astype(np.float32)
    close(multi, want)
    back = nd.ravel_multi_index(multi, shape=shape)
    close(back, flat)


def test_nansum_nanprod_sum_axis_broadcast_axis():
    x = A(3, 4)
    x[0, 0] = np.nan
    close(nd.nansum(nd.array(x), axis=1), np.nansum(x, axis=1), rtol=1e-5)
    close(nd.nanprod(nd.array(x), axis=1), np.nanprod(x, axis=1), rtol=1e-4)
    y = A(2, 5)
    close(nd.sum_axis(nd.array(y), axis=0), y.sum(0), rtol=1e-5)
    z = A(1, 3, 1)
    close(nd.broadcast_axis(nd.array(z), axis=(0, 2), size=(2, 4)),
          np.broadcast_to(z, (2, 3, 4)))


def test_argmax_channel_cast_storage_im2col():
    x = A(4, 6)
    close(nd.argmax_channel(nd.array(x)), x.argmax(1).astype(np.float32))
    c = nd.cast_storage(nd.array(x), stype="csr")
    assert c.stype == "csr"
    close(nd.cast_storage(c, stype="default"), x)
    # im2col: 1x1 kernel is an identity reshape
    img = A(2, 3, 4, 4)
    col = nd.im2col(nd.array(img), kernel=(1, 1))
    close(col, img.reshape(2, 3, 16), rtol=1e-6)


# ---------------------------------------------------------------------------
# output heads / losses
# ---------------------------------------------------------------------------
def test_regression_outputs_forward_and_grad():
    x, lbl = A(4, 3), A(4, 3)
    close(nd.LinearRegressionOutput(nd.array(x), nd.array(lbl)), x)
    close(nd.MAERegressionOutput(nd.array(x), nd.array(lbl)), x)
    close(nd.LogisticRegressionOutput(nd.array(x), nd.array(lbl)),
          1 / (1 + np.exp(-x)), rtol=1e-5)
    # symbolic grad semantics: d(loss)/dx = (pred - label) / batch-ish scale
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    out = mx.sym.LinearRegressionOutput(data, label)
    ex = out.simple_bind(ctx=mx.cpu(), data=x.shape, label=lbl.shape,
                         grad_req="write")
    ex.arg_dict["data"][:] = x
    ex.arg_dict["label"][:] = lbl
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    # reference scale: grad_scale / num_output (features per sample)
    np.testing.assert_allclose(g, (x - lbl) / x.shape[1], rtol=1e-4,
                               atol=1e-5)


def test_svm_output_and_softmax_activation():
    x = A(4, 5)
    close(nd.SVMOutput(nd.array(x), nd.array(np.zeros(4, np.float32))), x)
    sa = nd.SoftmaxActivation(nd.array(x))
    e = np.exp(x - x.max(1, keepdims=True))
    close(sa, e / e.sum(1, keepdims=True), rtol=1e-5)


def test_pad_constant_and_edge():
    x = A(1, 1, 3, 3)
    got = nd.Pad(nd.array(x), mode="constant", constant_value=9.0,
                 pad_width=(0, 0, 0, 0, 1, 1, 2, 2))
    want = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="constant",
                  constant_values=9.0)
    close(got, want)
    got_e = nd.Pad(nd.array(x), mode="edge",
                   pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    close(got_e, np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="edge"))


def test_instance_norm_matches_numpy():
    x = A(2, 3, 4, 5)
    g, b = A(3, scale=0.5, offset=1.0), A(3, scale=0.2)
    got = nd.InstanceNorm(nd.array(x), nd.array(g), nd.array(b), eps=1e-5)
    mean = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5) * g.reshape(1, 3, 1, 1) + \
        b.reshape(1, 3, 1, 1)
    close(got, want, rtol=1e-4, atol=1e-5)


def _ctc_ref_single(logp, labels, blank):
    """Log-domain CTC forward algorithm for one sequence (T, C)."""
    ext = [blank]
    for l in labels:
        ext += [l, blank]
    S = len(ext)
    NEG = -1e30
    alpha = np.full(S, NEG)
    alpha[0] = logp[0, ext[0]]
    if S > 1:
        alpha[1] = logp[0, ext[1]]
    for t in range(1, logp.shape[0]):
        new = np.full(S, NEG)
        for s in range(S):
            best = alpha[s]
            if s >= 1:
                best = np.logaddexp(best, alpha[s - 1])
            if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                best = np.logaddexp(best, alpha[s - 2])
            new[s] = best + logp[t, ext[s]]
        alpha = new
    tail = alpha[-1]
    if S > 1:
        tail = np.logaddexp(alpha[-1], alpha[-2])
    return -tail


def test_ctc_loss_matches_forward_algorithm():
    T, B, C = 6, 2, 5
    x = A(T, B, C)
    labels = np.array([[1, 2, 0, 0], [3, 3, 4, 0]], np.float32)  # 0 padding
    got = nd.CTCLoss(nd.array(x), nd.array(labels)).asnumpy()
    logp = x - np.log(np.exp(x - x.max(-1, keepdims=True))
                      .sum(-1, keepdims=True)) - x.max(-1, keepdims=True)
    for b in range(B):
        lab = [int(v) for v in labels[b] if v != 0]
        want = _ctc_ref_single(logp[:, b], lab, blank=0)
        np.testing.assert_allclose(got[b], want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# optimizer update kernels (one-step numeric checks)
# ---------------------------------------------------------------------------
def test_signsgd_signum_updates():
    w, g = A(5), A(5)
    out = nd.signsgd_update(nd.array(w), nd.array(g), lr=0.1)
    close(out, w - 0.1 * np.sign(g), rtol=1e-6)
    mom = np.zeros(5, np.float32)
    m_nd = nd.array(mom)
    out2 = nd.signum_update(nd.array(w), nd.array(g), m_nd, lr=0.1,
                            momentum=0.9)
    new_mom = 0.9 * mom - (1 - 0.9) * g
    close(out2, w + 0.1 * np.sign(new_mom), rtol=1e-5)


def test_rmsprop_updates():
    w, g = A(5), A(5)
    n = np.zeros(5, np.float32)
    n_nd = nd.array(n)
    out = nd.rmsprop_update(nd.array(w), nd.array(g), n_nd, lr=0.1,
                            gamma1=0.9, epsilon=1e-8)
    n2 = 0.9 * n + 0.1 * g * g
    close(out, w - 0.1 * g / (np.sqrt(n2) + 1e-8), rtol=1e-4)


def test_nag_and_ftrl_and_ftml_run_and_move_weights():
    w, g = A(5), A(5)
    mom = nd.array(np.zeros(5, np.float32))
    out = nd.nag_mom_update(nd.array(w), nd.array(g), mom, lr=0.1,
                            momentum=0.9)
    assert np.abs(out.asnumpy() - w).sum() > 0
    z = nd.array(np.zeros(5, np.float32))
    n = nd.array(np.zeros(5, np.float32))
    out2 = nd.ftrl_update(nd.array(w), nd.array(g), z, n, lr=0.1)
    assert np.isfinite(out2.asnumpy()).all()
    d = nd.array(np.zeros(5, np.float32))
    v = nd.array(np.zeros(5, np.float32))
    zf = nd.array(np.zeros(5, np.float32))
    out3 = nd.ftml_update(nd.array(w), nd.array(g), d, v, zf, lr=0.1, t=1)
    assert np.isfinite(out3.asnumpy()).all()


def test_multi_and_mp_sgd_updates():
    w1, g1 = A(4), A(4)
    w2, g2 = A(3), A(3)
    outs = nd.multi_sgd_update(nd.array(w1), nd.array(g1),
                               nd.array(w2), nd.array(g2),
                               lrs=(0.1, 0.2), wds=(0.0, 0.0),
                               num_weights=2)
    close(outs[0], w1 - 0.1 * g1, rtol=1e-5)
    close(outs[1], w2 - 0.2 * g2, rtol=1e-5)
    w32 = nd.array(w1)  # fp32 master copy
    out_mp = nd.mp_sgd_update(nd.array(w1.astype(np.float16)),
                              nd.array(g1.astype(np.float16)), w32, lr=0.1)
    assert out_mp.dtype == np.float16
    close(out_mp.asnumpy().astype(np.float32), w1 - 0.1 * g1,
          rtol=1e-2, atol=1e-2)


def test_all_finite_ops():
    ok = nd.all_finite(nd.array(A(4)))
    assert ok.asnumpy().item() == 1
    bad = nd.array(np.array([1.0, np.inf], np.float32))
    assert nd.all_finite(bad).asnumpy().item() == 0
    outs = nd.multi_all_finite(nd.array(A(3)), bad, num_arrays=2)
    assert outs.asnumpy().item() == 0


def test_adamw_updates():
    w, g = A(5), A(5)
    m = nd.array(np.zeros(5, np.float32))
    v = nd.array(np.zeros(5, np.float32))
    out = nd.adamw_update(nd.array(w), nd.array(g), m, v, lr=0.1, eta=1.0,
                          wd=0.01)
    m2 = 0.1 * g
    v2 = 0.001 * g * g
    # reference adamw-inl.h:137: w -= eta*(lr*m/(sqrt(v)+eps) + wd*w)
    want = w - 1.0 * (0.1 * m2 / (np.sqrt(v2) + 1e-8) + 0.01 * w)
    close(out, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# random distribution moments
# ---------------------------------------------------------------------------
def test_random_distribution_moments():
    mx.random.seed(11)
    n = 20000
    u = nd.random.uniform(low=2.0, high=4.0, shape=(n,)).asnumpy()
    assert abs(u.mean() - 3.0) < 0.03 and u.min() >= 2.0 and u.max() <= 4.0
    g = nd.random.normal(loc=1.0, scale=2.0, shape=(n,)).asnumpy()
    assert abs(g.mean() - 1.0) < 0.06 and abs(g.std() - 2.0) < 0.06
    e = nd.random.exponential(lam=4.0, shape=(n,)).asnumpy()
    assert abs(e.mean() - 0.25) < 0.02
    p = nd.random.poisson(lam=3.0, shape=(n,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.08
    ga = nd.random.gamma(alpha=2.0, beta=3.0, shape=(n,)).asnumpy()
    assert abs(ga.mean() - 6.0) < 0.2
    nb = nd.random.negative_binomial(k=4, p=0.5, shape=(n,)).asnumpy()
    assert abs(nb.mean() - 4.0) < 0.2            # k(1-p)/p
    gnb = nd.random.generalized_negative_binomial(
        mu=2.0, alpha=0.5, shape=(n,)).asnumpy()
    assert abs(gnb.mean() - 2.0) < 0.15
    ri = nd.random.randint(low=0, high=10, shape=(n,)).asnumpy()
    assert ri.min() >= 0 and ri.max() <= 9 and abs(ri.mean() - 4.5) < 0.15


def test_random_seed_determinism():
    mx.random.seed(5)
    a = nd.random.uniform(shape=(8,)).asnumpy()
    mx.random.seed(5)
    b = nd.random.uniform(shape=(8,)).asnumpy()
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# image op family (CHW-aware, deterministic subset exact; random subset smoke)
# ---------------------------------------------------------------------------
def _img(h=8, w=10, c=3):
    return (RNG.rand(h, w, c) * 255).astype(np.uint8)


def test_image_to_tensor_normalize():
    im = _img()
    t = nd.image.to_tensor(nd.array(im))
    close(t, im.transpose(2, 0, 1).astype(np.float32) / 255.0, rtol=1e-6)
    normed = nd.image.normalize(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    close(normed, (im.transpose(2, 0, 1) / 255.0 - 0.5) / 0.2, rtol=1e-4,
          atol=1e-5)


def test_image_flips_and_crop():
    im = _img().astype(np.float32)
    close(nd.image.flip_left_right(nd.array(im)), im[:, ::-1, :])
    close(nd.image.flip_top_bottom(nd.array(im)), im[::-1, :, :])
    got = nd.image.crop(nd.array(im), x=2, y=1, width=4, height=3)
    close(got, im[1:4, 2:6, :])


def test_image_resize_shape_and_range():
    im = _img(8, 8)
    out = nd.image.resize(nd.array(im), size=(4, 4))
    assert out.shape[:2] == (4, 4)
    out2 = nd.image.resize(nd.array(im), size=(16, 12))  # (w, h) convention
    assert out2.shape[:2] == (12, 16)


def test_image_random_ops_smoke_and_deterministic_seed():
    im = nd.array(_img().astype(np.float32))
    mx.random.seed(3)
    a = nd.image.random_brightness(im, 0.3).asnumpy()
    mx.random.seed(3)
    b = nd.image.random_brightness(im, 0.3).asnumpy()
    np.testing.assert_array_equal(a, b)
    for fn, args in [(nd.image.random_contrast, (0.3,)),
                     (nd.image.random_saturation, (0.3,)),
                     (nd.image.random_hue, (0.2,)),
                     (nd.image.random_lighting, (0.1,)),
                     (nd.image.random_color_jitter, (0.2, 0.2, 0.2, 0.1)),
                     (nd.image.random_flip_left_right, ()),
                     (nd.image.random_flip_top_bottom, ())]:
        out = fn(im, *args)
        assert out.shape == im.shape
        assert np.isfinite(out.asnumpy()).all()


# ---------------------------------------------------------------------------
# contrib utilities
# ---------------------------------------------------------------------------
def test_box_iou_and_nms():
    boxes = np.array([[0, 0, 2, 2], [1, 1, 3, 3], [10, 10, 12, 12]],
                     np.float32)
    iou = nd.contrib.box_iou(nd.array(boxes), nd.array(boxes)).asnumpy()
    np.testing.assert_allclose(np.diag(iou), 1.0, rtol=1e-5)
    np.testing.assert_allclose(iou[0, 1], 1.0 / 7.0, rtol=1e-4)
    assert iou[0, 2] == 0
    dets = np.array([[0, 0.9, 0, 0, 2, 2],
                     [0, 0.8, 1, 1, 3, 3],
                     [1, 0.7, 10, 10, 12, 12]], np.float32)
    out = nd.contrib.box_nms(nd.array(dets), overlap_thresh=0.1).asnumpy()
    kept = out[out[:, 0] >= 0]
    assert len(kept) == 2  # second box suppressed by first

def test_bipartite_matching():
    score = np.array([[0.9, 0.1], [0.8, 0.7]], np.float32)
    rows, cols = nd.contrib.bipartite_matching(nd.array(score),
                                               threshold=0.05)
    r = rows.asnumpy()
    # greedy: (0,0) first (0.9), then (1,1) (0.7)
    assert r[0] == 0 and r[1] == 1


def test_boolean_mask_index_ops():
    x = A(5, 3)
    m = np.array([1, 0, 1, 0, 1], np.float32)
    got = nd.contrib.boolean_mask(nd.array(x), nd.array(m))
    close(got, x[m.astype(bool)])
    idx = nd.contrib.index_array(nd.array(A(2, 3)))
    want = np.stack(np.meshgrid(np.arange(2), np.arange(3),
                                indexing="ij"), -1)
    np.testing.assert_array_equal(idx.asnumpy(), want)
    old = A(4, 3)
    new = A(2, 3)
    out = nd.contrib.index_copy(nd.array(old),
                                nd.array(np.array([1, 3], np.float32)),
                                nd.array(new))
    want = old.copy(); want[[1, 3]] = new
    close(out, want)


def test_arange_like_and_div_sqrt_dim():
    x = A(3, 4)
    al = nd.contrib.arange_like(nd.array(x), axis=1)
    np.testing.assert_array_equal(al.asnumpy(), np.arange(4, dtype=np.float32))
    close(nd.contrib.div_sqrt_dim(nd.array(x)), x / 2.0, rtol=1e-5)


def test_getnnz_quadratic_grad():
    from mxnet_tpu.ndarray.sparse import csr_matrix
    c = csr_matrix(np.array([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32))
    assert int(nd.contrib.getnnz(c).asnumpy()[()]) == 3
    x = nd.array(A(4)); x.attach_grad()
    with mx.autograd.record():
        y = nd.contrib.quadratic(x, a=2.0, b=3.0, c=1.0)
    y.backward()
    close(y, 2 * x.asnumpy() ** 2 + 3 * x.asnumpy() + 1, rtol=1e-5)
    close(x.grad, 4 * x.asnumpy() + 3, rtol=1e-5)


def test_adaptive_avg_pool_and_bilinear_resize():
    x = A(1, 2, 4, 4)
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x), output_size=2)
    want = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    close(out, want, rtol=1e-5)
    rz = nd.contrib.BilinearResize2D(nd.array(x), height=8, width=8)
    assert rz.shape == (1, 2, 8, 8)
    # corners preserved under align_corners-style bilinear
    close(rz.asnumpy()[..., 0, 0], x[..., 0, 0], rtol=1e-5)


def test_roi_align_simple():
    # constant feature map -> pooled output equals the constant
    x = np.full((1, 1, 8, 8), 3.0, np.float32)
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = nd.contrib.ROIAlign(nd.array(x), nd.array(rois),
                              pooled_size=(2, 2), spatial_scale=1.0)
    close(out, np.full((1, 1, 2, 2), 3.0), rtol=1e-5)


def test_multibox_prior_properties():
    x = nd.array(A(1, 3, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
    a = anchors.asnumpy()[0]
    assert a.shape == (4 * 4 * 3, 4)
    # centers lie on the pixel grid (i+0.5)/4
    cx = (a[:, 0] + a[:, 2]) / 2
    assert np.allclose(sorted(set(np.round(cx, 4))),
                       [0.125, 0.375, 0.625, 0.875], atol=1e-3)


def test_fft_ifft_roundtrip_and_count_sketch():
    x = A(2, 8)
    f = nd.contrib.fft(nd.array(x))
    assert f.shape == (2, 16)
    back = nd.contrib.ifft(f) / 8.0
    close(back, x, rtol=1e-4, atol=1e-4)
    h = nd.array(np.array([0, 1, 0, 1], np.float32))
    s = nd.array(np.array([1, -1, 1, 1], np.float32))
    cs = nd.contrib.count_sketch(nd.array(A(2, 4)), h, s, out_dim=2)
    assert cs.shape == (2, 2)


def test_sparse_embedding_matches_embedding():
    w = A(10, 4)
    idx = np.array([1, 3, 7], np.float32)
    a = nd.contrib.SparseEmbedding(nd.array(idx), nd.array(w), input_dim=10,
                                   output_dim=4)
    close(a, w[idx.astype(int)])


# ---------------------------------------------------------------------------
# quantization round-trip
# ---------------------------------------------------------------------------
def test_quantize_dequantize_roundtrip():
    x = A(4, 5)
    lo = nd.array(np.array([float(x.min())], np.float32))
    hi = nd.array(np.array([float(x.max())], np.float32))
    q, qmin, qmax = nd.contrib.quantize(nd.array(x), lo, hi, out_type="int8")
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, qmin, qmax, out_type="float32")
    close(back, x, rtol=0.1, atol=0.1)


def test_quantized_fully_connected_close_to_float():
    x = np.clip(A(3, 6), -2, 2)
    w = np.clip(A(4, 6), -2, 2)
    ref = x @ w.T
    lo = lambda a: nd.array(np.array([float(a.min())], np.float32))
    hi = lambda a: nd.array(np.array([float(a.max())], np.float32))
    qx, xmin, xmax = nd.contrib.quantize_v2(nd.array(x), min_calib_range=float(x.min()),
                                            max_calib_range=float(x.max()))
    qw, wmin, wmax = nd.contrib.quantize_v2(nd.array(w), min_calib_range=float(w.min()),
                                            max_calib_range=float(w.max()))
    out, omin, omax = nd.contrib.quantized_fully_connected(
        qx, qw, xmin, xmax, wmin, wmax, num_hidden=4, no_bias=True)
    deq = nd.contrib.dequantize(out.astype(np.int8) * 0 + out, omin, omax,
                                out_type="float32") \
        if out.dtype == np.int8 else out
    got = nd.contrib.dequantize(out, omin, omax, out_type="float32").asnumpy() \
        if out.dtype == np.int8 else out.asnumpy()
    np.testing.assert_allclose(got, ref, rtol=0.15, atol=0.25)


def test_group_and_sparse_adagrad_updates():
    w, g = A(4, 3), A(4, 3)
    hist = nd.array(np.zeros((4,), np.float32))
    out = nd.contrib.group_adagrad_update(nd.array(w), nd.array(g), hist,
                                          lr=0.1)
    h2 = (g * g).mean(axis=1)
    want = w - 0.1 * g / np.sqrt(h2 + 1e-5)[:, None]
    close(out, want, rtol=1e-3, atol=1e-4)
    hist2 = nd.array(np.zeros((4, 3), np.float32))
    out2 = nd._sparse_adagrad_update(nd.array(w), nd.array(g), hist2, lr=0.1)
    assert np.isfinite(out2.asnumpy()).all()
