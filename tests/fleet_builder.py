"""Deterministic model builder for fleet drills (owner ``--spec``).

The device-owner process imports this module's :func:`build` to
construct its models — a tiny decode model (same geometry as the AOT
cold-start drill) plus a one-layer infer model behind a registry.  The
weights are seeded, so every incarnation of the owner — including every
supervisor restart — answers bitwise-identically to its predecessor;
the chaos drill's post-crash equality assertion rests on exactly this.

``build(aot_cache=...)`` re-warms from the persistent program cache, so
a restart costs program *loads*, not XLA compiles.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_empty(aot_cache=None):
    """Model-free owner for supervisor unit drills: spawn cost is the
    interpreter + framework import, no XLA compiles."""
    from mxnet_tpu.serving import ModelRegistry
    return {"registry": ModelRegistry(), "decode": {}}


def build(aot_cache=None):
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.serving import ModelRegistry, ModelRuntime
    from mxnet_tpu.serving.decode import DecodeSession, get_decode_model

    mx.random.seed(0)
    net = get_decode_model("decode_tiny", vocab_size=96, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                         page_size=8, aot_cache=aot_cache)

    mx.random.seed(1)
    dense = nn.Dense(4)
    dense.initialize()
    dense(nd.zeros((1, 8)))          # shape inference before compile
    rt = ModelRuntime(dense, item_shapes=(8,), max_batch=8,
                      aot_cache=aot_cache)
    registry = ModelRegistry()
    registry.register("tiny_dense", rt, max_latency_ms=2.0)

    return {"registry": registry, "decode": {"decode_tiny": sess}}
