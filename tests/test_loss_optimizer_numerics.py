"""Loss formula + optimizer trajectory numerics (reference
``test_loss.py`` / ``test_optimizer.py`` patterns: compare against plain
NumPy reimplementations)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


rng = np.random.RandomState(7)


def test_l1_l2_loss_formulas():
    pred = rng.randn(8, 4).astype("float32")
    label = rng.randn(8, 4).astype("float32")
    l1 = gluon.loss.L1Loss()(mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    np.testing.assert_allclose(l1, np.abs(pred - label).mean(axis=1),
                               rtol=1e-5)
    l2 = gluon.loss.L2Loss()(mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    np.testing.assert_allclose(l2, ((pred - label) ** 2).mean(axis=1) / 2,
                               rtol=1e-5)


def test_softmax_ce_loss_formula():
    pred = rng.randn(6, 5).astype("float32")
    label = rng.randint(0, 5, 6).astype("float32")
    out = gluon.loss.SoftmaxCrossEntropyLoss()(
        mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    e = np.exp(pred - pred.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    ref = -np.log(p[np.arange(6), label.astype(int)])
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_sigmoid_bce_loss_formula():
    pred = rng.randn(6, 3).astype("float32")
    label = (rng.rand(6, 3) > 0.5).astype("float32")
    out = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    ref = (np.maximum(pred, 0) - pred * label +
           np.log1p(np.exp(-np.abs(pred)))).mean(axis=1)
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_huber_loss_formula():
    pred = np.array([[0.0, 2.0]], dtype="float32")
    label = np.array([[0.5, 0.0]], dtype="float32")
    out = gluon.loss.HuberLoss(rho=1.0)(
        mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    # |0.5| <= 1 → 0.5*0.25 ; |2| > 1 → 2-0.5
    np.testing.assert_allclose(out, [(0.5 * 0.25 + 1.5) / 2], rtol=1e-5)


def test_kl_div_loss():
    pred = rng.rand(4, 6).astype("float32")
    pred /= pred.sum(axis=1, keepdims=True)
    label = rng.rand(4, 6).astype("float32")
    label /= label.sum(axis=1, keepdims=True)
    out = gluon.loss.KLDivLoss(from_logits=False)(
        mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    logp = np.log(pred)
    # reference: mean over label*(log(label) - logp)? MXNet computes
    # -sum(label * log_pred)/D + const-free form via softmax; check finite
    assert np.isfinite(out).all()


def _run_optimizer(name, np_step, steps=5, **kw):
    """Eager optimizer trajectory vs NumPy reimplementation."""
    w0 = rng.randn(6).astype("float32")
    grads = [rng.randn(6).astype("float32") for _ in range(steps)]
    opt = mx.optimizer.create(name, learning_rate=0.1, **kw)
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for g in grads:
        opt.update(0, w, mx.nd.array(g), state)
    w_ref = np_step(w0.copy(), grads)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=2e-5, atol=1e-6)


def test_sgd_momentum_trajectory():
    def ref(w, grads, lr=0.1, mom=0.9):
        v = np.zeros_like(w)
        for g in grads:
            v = mom * v - lr * g
            w = w + v
        return w
    _run_optimizer("sgd", ref, momentum=0.9, wd=0.0)


def test_adam_trajectory():
    def ref(w, grads, lr=0.1, b1=0.9, b2=0.999, eps=1e-8):
        m = np.zeros_like(w)
        v = np.zeros_like(w)
        for t, g in enumerate(grads, 1):
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            w = w - lr_t * m / (np.sqrt(v) + eps)
        return w
    _run_optimizer("adam", ref, wd=0.0)


def test_rmsprop_trajectory():
    def ref(w, grads, lr=0.1, gamma=0.9, eps=1e-8):
        n = np.zeros_like(w)
        for g in grads:
            n = gamma * n + (1 - gamma) * g * g
            w = w - lr * g / np.sqrt(n + eps)
        return w
    _run_optimizer("rmsprop", ref, gamma1=0.9, wd=0.0)


def test_weight_decay_applies():
    opt = mx.optimizer.create("sgd", learning_rate=0.1, wd=0.5)
    w = mx.nd.ones((3,))
    state = opt.create_state(0, w)
    opt.update(0, w, mx.nd.zeros((3,)), state)  # grad 0: pure decay
    np.testing.assert_allclose(w.asnumpy(), np.full(3, 1 - 0.1 * 0.5),
                               rtol=1e-6)


def test_functional_matches_eager_sgd_mom():
    """parallel.FunctionalOptimizer reproduces the eager optimizer."""
    from mxnet_tpu.parallel import FunctionalOptimizer
    import jax.numpy as jnp
    w0 = rng.randn(5).astype("float32")
    grads = [rng.randn(5).astype("float32") for _ in range(4)]
    # eager
    opt = mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9, wd=0.01)
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for g in grads:
        opt.update(0, w, mx.nd.array(g), state)
    # functional
    fo = FunctionalOptimizer("sgd", 0.05, momentum=0.9, wd=0.01)
    params = {"w": jnp.asarray(w0.copy())}
    st = fo.init_state(params)
    for g in grads:
        params, st = fo.update(params, {"w": jnp.asarray(g)}, st)
    np.testing.assert_allclose(w.asnumpy(), np.asarray(params["w"]),
                               rtol=2e-5, atol=1e-6)


# --- r4 depth: remaining loss-family formulas vs numpy (reference
# test_loss.py inventory) + sample_weight/batch_axis contracts

def test_hinge_and_squared_hinge():
    pred = np.array([[0.5], [-0.3], [2.0]], "float32")
    label = np.array([[1], [1], [-1]], "float32")
    out = mx.gluon.loss.HingeLoss()(mx.nd.array(pred), mx.nd.array(label))
    want = np.maximum(0, 1 - label * pred).mean(axis=1)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)
    out2 = mx.gluon.loss.SquaredHingeLoss()(mx.nd.array(pred),
                                            mx.nd.array(label))
    np.testing.assert_allclose(out2.asnumpy(),
                               (np.maximum(0, 1 - label * pred) ** 2)
                               .mean(axis=1), rtol=1e-5)


def test_logistic_loss_label_formats():
    pred = np.array([[0.3], [-0.7]], "float32")
    lab_pm1 = np.array([[1], [-1]], "float32")
    out = mx.gluon.loss.LogisticLoss(label_format="signed")(
        mx.nd.array(pred), mx.nd.array(lab_pm1))
    want = np.log1p(np.exp(-lab_pm1 * pred)).mean(axis=1)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)
    lab01 = np.array([[1], [0]], "float32")
    out2 = mx.gluon.loss.LogisticLoss(label_format="binary")(
        mx.nd.array(pred), mx.nd.array(lab01))
    np.testing.assert_allclose(out2.asnumpy(),
                               np.log1p(np.exp(-(2 * lab01 - 1) * pred))
                               .mean(axis=1), rtol=1e-5)


def test_triplet_loss_formula():
    rng = np.random.RandomState(0)
    a, p, n = [rng.randn(4, 6).astype("float32") for _ in range(3)]
    out = mx.gluon.loss.TripletLoss(margin=1.0)(
        mx.nd.array(a), mx.nd.array(p), mx.nd.array(n))
    want = np.maximum(
        ((a - p) ** 2).sum(1) - ((a - n) ** 2).sum(1) + 1.0, 0)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-4, atol=1e-5)


def test_poisson_nll_loss_formula():
    pred = np.array([[0.5, 1.2], [0.1, 2.0]], "float32")
    target = np.array([[1.0, 2.0], [0.0, 3.0]], "float32")
    out = mx.gluon.loss.PoissonNLLLoss(from_logits=False)(
        mx.nd.array(pred), mx.nd.array(target))
    # reference loss.py:699 returns the FULL mean (a scalar)
    want = (pred - target * np.log(pred + 1e-8)).mean()
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-4)


def test_cosine_embedding_loss_formula():
    rng = np.random.RandomState(1)
    x1 = rng.randn(3, 5).astype("float32")
    x2 = rng.randn(3, 5).astype("float32")
    lab = np.array([1, -1, 1], "float32")
    out = mx.gluon.loss.CosineEmbeddingLoss(margin=0.1)(
        mx.nd.array(x1), mx.nd.array(x2), mx.nd.array(lab))
    cos = (x1 * x2).sum(1) / (np.linalg.norm(x1, axis=1) *
                              np.linalg.norm(x2, axis=1) + 1e-12)
    want = np.where(lab == 1, 1 - cos, np.maximum(0, cos - 0.1))
    np.testing.assert_allclose(out.asnumpy().ravel(), want, rtol=1e-4,
                               atol=1e-5)


def test_sample_weight_scales_per_example():
    pred = mx.nd.array(np.array([[1.0], [1.0]], "float32"))
    lab = mx.nd.array(np.array([[0.0], [0.0]], "float32"))
    w = mx.nd.array(np.array([[1.0], [0.0]], "float32"))
    out = mx.gluon.loss.L2Loss()(pred, lab, w)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0])


def test_ctc_loss_matches_simple_case():
    """Two timesteps, vocab 3 (blank=0), target [1]: compare against the
    exact alpha recursion computed by hand."""
    logits = np.log(np.array(
        [[[0.6, 0.3, 0.1]], [[0.2, 0.7, 0.1]]], "float32"))  # (T=2,B=1,V)
    label = np.array([[1]], "float32")
    out = mx.gluon.loss.CTCLoss(layout="TNC")(
        mx.nd.array(logits), mx.nd.array(label))
    # gluon CTCLoss reserves the LAST index for blank (reference
    # loss.py:510 blank_label='last'): paths (b,1),(1,b),(1,1), b=idx 2
    p = 0.1 * 0.7 + 0.3 * 0.1 + 0.3 * 0.7
    np.testing.assert_allclose(out.asnumpy(), [-np.log(p)], rtol=1e-4)
