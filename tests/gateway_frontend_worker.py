"""Standalone gateway front-end process for the multi-front-end drill.

Builds a proxy :class:`Gateway` over an EXISTING fleet owner socket (it
does not spawn or supervise the owner — that is the parent's
supervisor's job), prints its bound HTTP port as one JSON line on
stdout, then serves until stdin closes.  Running two of these against
one socket is the scale-out topology: N stateless HTTP front doors, one
device-owning process, crash domains fully separated.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True,
                    help="AF_UNIX path of the running fleet owner")
    ap.add_argument("--capacity", type=int, default=64)
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mxnet_tpu.serving.gateway import Gateway
    gw = Gateway(owner=args.socket, capacity=args.capacity,
                 name=f"frontend-{os.getpid()}")
    print(json.dumps({"port": gw.port, "pid": os.getpid()}), flush=True)
    # serve until the parent closes our stdin (or kills us)
    while sys.stdin.readline():
        pass
    gw.close()


if __name__ == "__main__":
    main()
