"""Module API depth tranche (reference ``test_module.py`` remainder):
forward with changing shapes, monitor capture, forward dtypes, bucketing
grad_req / switch-bucket sharing, layout handling, initializer kwargs.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_forward_reshape_across_batches():
    """reference test_forward_reshape: consecutive forwards with
    DIFFERENT batch sizes / spatial shapes work without an explicit
    reshape call."""
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    for bs in (8, 4, 10):
        batch = mx.io.DataBatch(
            [mx.nd.random.uniform(shape=(bs, 6))],
            [mx.nd.zeros((bs,))])
        mod.forward(batch, is_train=False)
        assert mod.get_outputs()[0].shape == (bs, 4)


def test_module_reshape_method():
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    w0 = mod.get_params()[0]["fc_weight"].asnumpy()
    mod.reshape(data_shapes=[("data", (2, 6))],
                label_shapes=[("softmax_label", (2,))])
    batch = mx.io.DataBatch([mx.nd.ones((2, 6))], [mx.nd.zeros((2,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (2, 4)
    np.testing.assert_allclose(mod.get_params()[0]["fc_weight"].asnumpy(),
                               w0)


def test_monitor_captures_internal_tensors():
    """reference test_monitor: a Monitor installed on the module sees
    per-op tensors with finite stats."""
    seen = []
    mon = mx.monitor.Monitor(1, stat_func=lambda x: mx.nd.norm(x),
                             pattern=".*")
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.install_monitor(mon)
    mon.tic()
    batch = mx.io.DataBatch([mx.nd.random.uniform(shape=(4, 6))],
                            [mx.nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    rows = mon.toc()
    assert rows, "monitor captured nothing"
    for _, name, val in rows:
        if hasattr(val, "asscalar"):
            v = float(val.asscalar())
        else:
            import re as _re
            nums = _re.findall(r"[-+0-9.eE]+", str(val))
            v = float(nums[0]) if nums else 0.0
        assert np.isfinite(v)


@pytest.mark.parametrize("dtype", ["float32", "float16"])
def test_forward_types(dtype):
    """reference test_forward_types: the module runs end-to-end in the
    bound dtype."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc", num_hidden=3)
    mod = mx.mod.Module(out, context=mx.cpu(), label_names=None)
    mod.bind(data_shapes=[mx.io.DataDesc("data", (4, 5), dtype=dtype)],
             label_shapes=None, for_training=False)
    mod.init_params()
    batch = mx.io.DataBatch(
        [mx.nd.ones((4, 5), dtype=dtype)])
    mod.forward(batch, is_train=False)
    out_arr = mod.get_outputs()[0]
    assert out_arr.shape == (4, 3)
    assert np.isfinite(out_arr.asnumpy().astype("float64")).all()


def test_module_initializer_kwargs():
    """reference test_module_initializer: init_params honours a custom
    initializer for specific params."""
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.One())
    w = mod.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_allclose(w, np.ones_like(w))


def test_bucketing_switch_shares_params():
    """reference test_module_switch_bucket: switching buckets preserves
    the shared parameters (same arrays drive every bucket)."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(net, name="softmax"), ("data",), \
            ("softmax_label",)

    # buckets share parameters, so keep the input width fixed (the fc
    # weight shape must match across buckets) and vary the batch
    mod2 = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                  context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 10))],
              label_shapes=[("softmax_label", (4,))])
    mod2.init_params(initializer=mx.init.One())
    mod2.switch_bucket(11, data_shapes=[("data", (2, 10))],
                       label_shapes=[("softmax_label", (2,))])
    w = mod2.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_allclose(w, np.ones_like(w))
    batch = mx.io.DataBatch([mx.nd.ones((2, 10))], [mx.nd.zeros((2,))],
                            bucket_key=11)
    mod2.forward(batch, is_train=False)
    assert mod2.get_outputs()[0].shape == (2, 4)


def test_module_save_load_checkpoint_epochs(tmp_path):
    """reference test_save_load: save_checkpoint/load round-trip with
    epoch numbering and optimizer states."""
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    it = mx.io.NDArrayIter(np.random.rand(32, 6).astype("float32"),
                           np.zeros(32, "float32"), batch_size=8)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "mdl")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
    sym, arg, aux = mx.model.load_checkpoint(prefix, 2)
    assert "fc_weight" in arg
    mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True,
                              label_names=("softmax_label",))
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    w1 = mod.get_params()[0]["fc_weight"].asnumpy()
    w2 = mod2.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_allclose(w1, w2)


def test_module_input_grads_flag():
    """reference test_module_input_grads: inputs_need_grad exposes
    gradients w.r.t. data."""
    mod = mx.mod.Module(_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    batch = mx.io.DataBatch([mx.nd.random.uniform(shape=(4, 6))],
                            [mx.nd.array([0, 1, 2, 3])])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0]
    assert g.shape == (4, 6)
    assert float(mx.nd.abs(g).sum().asscalar()) > 0
