"""Reference test_ndarray.py port: names mirror
tests/python/unittest/test_ndarray.py one-for-one; cases already covered
by tests/test_ndarray.py keep their deeper variants there.
"""
import os
import pickle
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

_rng = np.random.RandomState


def test_ndarray_setitem():
    x = nd.zeros((4, 5))
    x[1] = 7.0
    ref = np.zeros((4, 5), "float32")
    ref[1] = 7
    assert_almost_equal(x.asnumpy(), ref)
    x[2, 3] = -1.0
    ref[2, 3] = -1
    assert_almost_equal(x.asnumpy(), ref)
    x[0:2, 1:3] = nd.ones((2, 2))
    ref[0:2, 1:3] = 1
    assert_almost_equal(x.asnumpy(), ref)
    # numpy-array rhs and python-list index
    x[[3]] = np.full((1, 5), 2.5, "float32")
    ref[3] = 2.5
    assert_almost_equal(x.asnumpy(), ref)
    # negative index
    x[-1, -1] = 9.0
    ref[-1, -1] = 9
    assert_almost_equal(x.asnumpy(), ref)


def test_ndarray_elementwise():
    rng = _rng(0)
    for dtype in ("float32", "float16"):
        a = rng.rand(3, 4).astype(dtype) + 0.5
        b = rng.rand(3, 4).astype(dtype) + 0.5
        na, nb = nd.array(a, dtype=dtype), nd.array(b, dtype=dtype)
        rtol = 1e-3 if dtype == "float16" else 1e-5
        assert_almost_equal((na + nb).asnumpy(), a + b, rtol=rtol)
        assert_almost_equal((na - nb).asnumpy(), a - b, rtol=rtol,
                            atol=1e-3 if dtype == "float16" else 1e-6)
        assert_almost_equal((na * nb).asnumpy(), a * b, rtol=rtol)
        assert_almost_equal((na / nb).asnumpy(), a / b, rtol=rtol)
        assert (na + nb).dtype == np.dtype(dtype)


def test_ndarray_elementwisesum():
    rng = _rng(1)
    arrs = [rng.randn(2, 3).astype("float32") for _ in range(4)]
    got = nd.ElementWiseSum(*[nd.array(a) for a in arrs])
    assert_almost_equal(got.asnumpy(), sum(arrs), rtol=1e-5)
    got = nd.add_n(*[nd.array(a) for a in arrs])
    assert_almost_equal(got.asnumpy(), sum(arrs), rtol=1e-5)


def test_ndarray_negate():
    x = _rng(2).randn(3, 4).astype("float32")
    a = nd.array(x)
    assert_almost_equal((-a).asnumpy(), -x)
    # negation leaves the original untouched
    assert_almost_equal(a.asnumpy(), x)


def test_ndarray_reshape():
    x = nd.array(np.arange(24, dtype="float32"))
    # method form with tuple, ints, and kwargs
    assert x.reshape((4, 6)).shape == (4, 6)
    assert x.reshape(4, 6).shape == (4, 6)
    assert x.reshape((-1, 6)).shape == (4, 6)
    assert x.reshape(shape=(2, 12)).shape == (2, 12)
    y = x.reshape(2, 3, 4)
    assert_almost_equal(y.asnumpy().ravel(), x.asnumpy())
    # -2/-3/-4 codes through the method
    assert y.reshape((-3, 4)).shape == (6, 4)
    assert y.reshape((0, 0, -4, 2, 2)).shape == (2, 3, 2, 2)


def test_ndarray_choose():
    rng = _rng(3)
    x = rng.randn(4, 5).astype("float32")
    idx = np.array([1, 0, 3, 4], "float32")
    got = nd.choose_element_0index(nd.array(x), nd.array(idx))
    assert_almost_equal(got.asnumpy(), x[np.arange(4), idx.astype(int)])


def test_ndarray_fill():
    rng = _rng(4)
    x = rng.randn(4, 5).astype("float32")
    idx = np.array([1, 0, 3, 4], "float32")
    vals = np.array([9.0, 8.0, 7.0, 6.0], "float32")
    got = nd.fill_element_0index(nd.array(x), nd.array(vals),
                                 nd.array(idx))
    ref = x.copy()
    ref[np.arange(4), idx.astype(int)] = vals
    assert_almost_equal(got.asnumpy(), ref)


def test_ndarray_onehot():
    idx = nd.array(np.array([2, 0, 1], "float32"))
    got = nd.one_hot(idx, depth=3)
    assert_almost_equal(got.asnumpy(),
                        np.eye(3, dtype="float32")[[2, 0, 1]])


def test_init_from_scalar():
    x = nd.array(3.5)
    assert x.shape == () and float(x.asnumpy()) == 3.5
    y = nd.array([5])
    assert y.shape == (1,)


def test_ndarray_copy():
    x = nd.array(_rng(5).randn(3, 4).astype("float32"))
    y = x.copy()
    y[0] = 0.0
    assert np.abs(x.asnumpy()[0]).sum() > 0     # deep copy


def test_ndarray_scalar():
    x = nd.zeros((2, 3))
    x[:] = 5.0
    assert (x.asnumpy() == 5).all()
    x[:] = x + 1.0
    assert (x.asnumpy() == 6).all()
    assert float((x * 0 + 2).sum().asscalar()) == 12.0


def test_ndarray_pickle():
    x = nd.array(_rng(6).randn(3, 4).astype("float32"))
    data = pickle.dumps(x)
    y = pickle.loads(data)
    assert_almost_equal(x.asnumpy(), y.asnumpy())
    # sparse round trip
    from mxnet_tpu.ndarray import sparse as sp
    s = sp.csr_matrix(np.eye(3, dtype="float32"))
    s2 = pickle.loads(pickle.dumps(s))
    assert s2.stype == "csr"
    assert_almost_equal(s2.asnumpy(), np.eye(3, dtype="float32"))


def test_ndarray_saveload():
    rng = _rng(7)
    arrays = [nd.array(rng.randn(3, 4).astype("float32")),
              nd.array(rng.randn(5).astype("float16"), dtype="float16")]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "arrs")
        # list save/load
        nd.save(path, arrays)
        loaded = nd.load(path)
        assert isinstance(loaded, list)
        for a, b in zip(arrays, loaded):
            assert a.dtype == b.dtype
            assert_almost_equal(np.asarray(a.asnumpy(), "float64"),
                                np.asarray(b.asnumpy(), "float64"))
        # dict save/load
        nd.save(path, {"w": arrays[0], "b": arrays[1]})
        loaded = nd.load(path)
        assert sorted(loaded) == ["b", "w"]
        assert_almost_equal(loaded["w"].asnumpy(), arrays[0].asnumpy())


def test_buffer_load():
    """load_buffer parses the same bytes save() wrote."""
    x = nd.array(_rng(8).randn(2, 3).astype("float32"))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "one")
        nd.save(path, [x])
        raw = open(path, "rb").read()
    loaded = nd.load_frombuffer(raw) if hasattr(nd, "load_frombuffer") \
        else nd.load_buffer(raw)
    assert_almost_equal(loaded[0].asnumpy(), x.asnumpy())


def test_ndarray_slice():
    x = _rng(9).randn(6, 4).astype("float32")
    a = nd.array(x)
    assert_almost_equal(a[2:5].asnumpy(), x[2:5])
    assert_almost_equal(a.slice(begin=(1, 0), end=(3, 2)).asnumpy(),
                        x[1:3, 0:2])
    # writes through a slice land in the base array
    a[2:4] = 0.0
    x[2:4] = 0
    assert_almost_equal(a.asnumpy(), x)


def test_ndarray_crop():
    x = _rng(10).randn(4, 5, 6).astype("float32")
    got = nd.crop(nd.array(x), begin=(1, 1, 2), end=(3, 4, 5))
    assert_almost_equal(got.asnumpy(), x[1:3, 1:4, 2:5])


def test_ndarray_concatenate():
    rng = _rng(11)
    parts = [rng.randn(2, 3).astype("float32") for _ in range(3)]
    got = nd.concatenate([nd.array(p) for p in parts], axis=0)
    assert_almost_equal(got.asnumpy(), np.concatenate(parts, axis=0))
    got = nd.concatenate([nd.array(p) for p in parts], axis=1)
    assert_almost_equal(got.asnumpy(), np.concatenate(parts, axis=1))


def test_moveaxis():
    x = _rng(12).randn(2, 3, 4).astype("float32")
    got = nd.moveaxis(nd.array(x), 0, 2)
    assert_almost_equal(got.asnumpy(), np.moveaxis(x, 0, 2))
    got = nd.moveaxis(nd.array(x), -1, 0)
    assert_almost_equal(got.asnumpy(), np.moveaxis(x, -1, 0))


def test_linspace():
    got = nd.linspace(2, 9, 7)
    assert_almost_equal(got.asnumpy(),
                        np.linspace(2, 9, 7).astype("float32"))
    got = nd.linspace(0, 1, 5, endpoint=False)
    assert_almost_equal(got.asnumpy(),
                        np.linspace(0, 1, 5, endpoint=False)
                        .astype("float32"))


@pytest.mark.parametrize("op,npop", [
    ("__eq__", np.equal), ("__ne__", np.not_equal),
    ("__gt__", np.greater), ("__ge__", np.greater_equal),
    ("__lt__", np.less), ("__le__", np.less_equal),
])
def test_ndarray_comparisons(op, npop):
    """reference test_ndarray_equal/_not_equal/_greater/... family."""
    rng = _rng(13)
    x = rng.randint(0, 3, (4, 4)).astype("float32")
    y = rng.randint(0, 3, (4, 4)).astype("float32")
    got = getattr(nd.array(x), op)(nd.array(y))
    assert_almost_equal(got.asnumpy(), npop(x, y).astype("float32"))
    # against a scalar
    got = getattr(nd.array(x), op)(1.0)
    assert_almost_equal(got.asnumpy(), npop(x, 1.0).astype("float32"))


def test_iter():
    x = nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    rows = [r.asnumpy() for r in x]
    assert len(rows) == 4
    assert_almost_equal(np.stack(rows), x.asnumpy())


def test_output():
    """out= keyword writes into the destination array."""
    x = nd.array(np.ones((3, 3), "float32"))
    out = nd.zeros((3, 3))
    nd.elemwise_add(x, x, out=out)
    assert (out.asnumpy() == 2).all()


def test_ndarray_fluent():
    """Fluent method chaining mirrors the functional ops."""
    rng = _rng(14)
    x = np.abs(rng.randn(3, 4)).astype("float32") + 0.5
    a = nd.array(x)
    assert_almost_equal(a.sqrt().asnumpy(), np.sqrt(x), rtol=1e-5)
    assert_almost_equal(a.log().exp().asnumpy(), x, rtol=1e-4)
    assert_almost_equal(a.square().asnumpy(), x * x, rtol=1e-5)
    assert_almost_equal(a.sum(axis=1).asnumpy(), x.sum(axis=1),
                        rtol=1e-5)
    assert_almost_equal(a.mean().asnumpy(), x.mean(), rtol=1e-5)
    assert_almost_equal(a.clip(0.6, 1.0).asnumpy(), np.clip(x, 0.6, 1.0))
    assert_almost_equal(a.transpose().asnumpy(), x.T)
    assert a.expand_dims(0).shape == (1, 3, 4)
    assert_almost_equal(a.flatten().asnumpy(), x.reshape(3, 4))
    assert_almost_equal(a.abs().asnumpy(), np.abs(x))
    assert_almost_equal(a.sign().asnumpy(), np.sign(x))
    assert a.argmax(axis=1).shape == (3,)
    assert_almost_equal(a.max().asnumpy(), x.max(), rtol=1e-6)
    assert_almost_equal(a.softmax().asnumpy(),
                        np.exp(x) / np.exp(x).sum(axis=-1, keepdims=True),
                        rtol=1e-4)


def test_bool_ambiguous():
    with pytest.raises(Exception):
        bool(nd.ones((2, 2)))


def test_bool():
    assert bool(nd.ones((1,)))
    assert not bool(nd.zeros((1,)))
    assert bool(nd.array([3.0]))


def test_assign_float_value_to_ndarray():
    x = nd.zeros((2, 2))
    x[0, 0] = 2.5
    assert float(x.asnumpy()[0, 0]) == 2.5
    x[:] = 1.25
    assert (x.asnumpy() == 1.25).all()


def test_assign_large_int_to_ndarray():
    x = nd.zeros((2, 2), dtype="int32")
    x[0, 0] = 2 ** 30
    assert int(x.asnumpy()[0, 0]) == 2 ** 30


def test_assign_a_row_to_ndarray():
    rng = _rng(15)
    x = nd.array(rng.randn(3, 4).astype("float32"))
    row = rng.randn(4).astype("float32")
    x[1] = nd.array(row)
    assert_almost_equal(x.asnumpy()[1], row)
    x[0] = row                      # numpy rhs
    assert_almost_equal(x.asnumpy()[0], row)


def test_ndarray_astype():
    x = nd.array(np.array([1.6, -1.6, 2.0], "float32"))
    for dtype in ("float16", "int32", "uint8", "float32"):
        y = x.astype(dtype) if dtype != "uint8" \
            else nd.array(np.array([1.6, 0.2, 2.0], "float32")) \
            .astype(dtype)
        assert y.dtype == np.dtype(dtype)
    z = x.astype("int32")
    assert z.asnumpy().tolist() == [1, -1, 2]   # truncation, not rounding
    # astype(copy=False) may return self when dtype already matches
    w = x.astype("float32", copy=False)
    assert w.dtype == np.float32


def test_ndarray_is_inf():
    x = nd.array(np.array([np.inf, -np.inf, 1.0, np.nan], "float32"))
    got = nd.contrib.isinf(x) if hasattr(nd.contrib, "isinf") \
        else nd.isinf(x)
    assert got.asnumpy().astype(bool).tolist() == [True, True, False,
                                                   False]


def test_ndarray_is_finite():
    x = nd.array(np.array([np.inf, 1.0, np.nan, -2.0], "float32"))
    got = nd.isfinite(x)
    assert got.asnumpy().astype(bool).tolist() == [False, True, False,
                                                   True]


def test_ndarray_is_nan():
    x = nd.array(np.array([np.nan, 1.0, np.inf], "float32"))
    got = nd.isnan(x)
    assert got.asnumpy().astype(bool).tolist() == [True, False, False]


def test_ndarray_nan_comparison():
    """reference mshadow maximum = (a > b ? a : b): a NaN lhs loses the
    comparison, a NaN rhs is returned — NOT ieee fmax."""
    a = nd.array(np.array([np.nan, 1.0, 2.0], "float32"))
    b = nd.array(np.array([1.0, 1.0, np.nan], "float32"))
    mx_max = nd.maximum(a, b).asnumpy()
    assert not np.isnan(mx_max[0]) and mx_max[0] == 1.0
    assert mx_max[1] == 1.0
    assert np.isnan(mx_max[2])
    eq = (a == a).asnumpy()
    assert eq[0] == 0.0             # NaN != NaN


def test_zero_from_numpy():
    z = nd.array(np.zeros((0, 4), "float32"))
    assert z.shape == (0, 4)
    assert z.asnumpy().shape == (0, 4)


def test_save_load_scalar_zero_size_ndarrays():
    arrays = [nd.array(3.0), nd.zeros((0, 3))]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "mixed")
        nd.save(path, arrays)
        loaded = nd.load(path)
    assert loaded[0].shape == () and float(loaded[0].asnumpy()) == 3.0
    assert loaded[1].shape == (0, 3)


def test_list_index_empty_and_float():
    """Empty and float list indexers cast to int like NDArray indexers
    (review regression)."""
    x = nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    assert x[[]].shape == (0, 3)
    got = x[[0.0, 1.0]]
    assert_almost_equal(got.asnumpy(), x.asnumpy())
    m = x[[True, False]]
    assert m.shape == (1, 3)
