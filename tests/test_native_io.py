"""Native C++ recordio reader tests — compares against the Python framing
implementation bit-for-bit."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, _native

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native toolchain unavailable")


def _write(tmp_path, n=50):
    frec = str(tmp_path / "n.rec")
    w = recordio.MXRecordIO(frec, "w")
    rng = np.random.RandomState(0)
    payloads = []
    for i in range(n):
        # varied sizes incl. non-multiple-of-4 to exercise padding
        p = rng.bytes(rng.randint(1, 200))
        payloads.append(p)
        w.write(p)
    w.close()
    return frec, payloads


def test_native_index_matches_python(tmp_path):
    frec, payloads = _write(tmp_path)
    offsets, lengths = _native.build_index(frec)
    assert len(offsets) == len(payloads)
    np.testing.assert_array_equal(lengths, [len(p) for p in payloads])
    # Python reader at the native offsets reproduces every payload
    r = recordio.MXRecordIO(frec, "r")
    for off, p in zip(offsets, payloads):
        r.record.seek(int(off))
        assert r.read() == p


def test_native_read_record(tmp_path):
    frec, payloads = _write(tmp_path)
    offsets, lengths = _native.build_index(frec)
    for i in (0, 7, len(payloads) - 1):
        got = _native.read_record(frec, offsets[i], lengths[i])
        assert got == payloads[i]


def test_native_read_batch(tmp_path):
    frec, payloads = _write(tmp_path)
    offsets, lengths = _native.build_index(frec)
    sel = [3, 0, 11, 11, 42]
    recs = _native.read_batch(frec, [offsets[i] for i in sel],
                              [lengths[i] for i in sel])
    for i, r in zip(sel, recs):
        assert r == payloads[i]


def test_image_record_iter_uses_native(tmp_path):
    """ImageRecordIter without .idx goes through the native scanner."""
    fidx, frec = str(tmp_path / "i.idx"), str(tmp_path / "i.rec")
    w = recordio.MXIndexedRecordIO(fidx, frec, "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = (rng.rand(20, 20, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=frec, data_shape=(3, 16, 16),
                               batch_size=4)  # no path_imgidx → scan path
    assert it._lengths is not None  # native index used
    labels = []
    for b in it:
        assert b.data[0].shape == (4, 3, 16, 16)
        labels.extend(b.label[0].asnumpy().tolist())
    assert len(labels) == 12
