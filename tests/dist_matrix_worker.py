"""4-worker dist_sync exact-value matrix (port of the reference nightly
``tests/nightly/dist_sync_kvstore.py:16-55`` semantics): dense + row_sparse
push/pull, fp16 keys, server-side optimizer, 2-bit gradient compression with
error feedback — all over real multi-process ``jax.distributed``, launched
via tools/launch.py like the reference's own launcher flow.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt

SHAPE = (2, 3)
IRREGULAR = (121, 121)
BIG = (120, 120)
RATE = 2.0


class TestOptimizer(opt.Optimizer):
    """The reference nightly's 'test' optimizer: w += rescale_grad * grad
    (``mxnet/test_utils.py`` via ``mx.optimizer.create('test', ...)``)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad


def check_diff(arr, expect, rank, msg=""):
    a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
    e = expect.asnumpy() if hasattr(expect, "asnumpy") else np.asarray(expect)
    assert np.sum(np.abs(a - e)) == 0, (rank, msg, a, e)


def test_dense(kv, rank, nw, nrepeat=3):
    for dtype in ("float32", "float16"):
        keys = ["3", "5"] if dtype == "float32" else ["4", "6"]
        shapes = [SHAPE, BIG]
        for k, s in zip(keys, shapes):
            kv.init(k, mx.nd.ones(s, dtype=dtype))
            for i in range(nrepeat):
                kv.push(k, mx.nd.ones(s, dtype=dtype) * (rank + 1))
                # server optimizer: w += rate * sum_r (r+1) each repeat
                num = (nw + 1) * nw * RATE / 2 * (i + 1) + 1
                val = mx.nd.zeros(s, dtype=dtype)
                kv.pull(k, out=val)
                check_diff(val, np.full(s, num, dtype), rank,
                           f"dense {dtype} {k}")
    print(f"DENSE_OK rank={rank}")


def test_row_sparse(kv, rank, nw, nrepeat=3):
    for dtype in ("float32", "float16"):
        k = "9" if dtype == "float32" else "10"
        kv.init(k, mx.nd.ones(SHAPE, dtype=dtype).tostype("row_sparse"))
        v = np.zeros(SHAPE, dtype)
        my_row = rank % SHAPE[0]
        v[my_row] = rank + 1
        for i in range(nrepeat):
            kv.push(k, mx.nd.array(v).tostype("row_sparse"))
            rng = np.random.RandomState(42 + rank + i)
            row_ids_np = rng.randint(SHAPE[0], size=SHAPE[0])
            val = mx.nd.sparse.zeros("row_sparse", SHAPE, dtype=dtype)
            kv.row_sparse_pull(k, out=val,
                               row_ids=mx.nd.array(row_ids_np))
            updated = np.ones(SHAPE, dtype)
            for r in range(nw):
                updated[r % SHAPE[0]] += (r + 1) * RATE * (i + 1)
            expected = np.zeros(SHAPE, dtype)
            for row in row_ids_np:
                expected[row] = updated[row]
            check_diff(val.tostype("default"), expected, rank,
                       f"rsp {dtype}")
    print(f"RSP_OK rank={rank}")


def test_row_sparse_zeros(kv, rank, nw):
    for dtype in ("float32", "float16"):
        k = "11" if dtype == "float32" else "12"
        kv.init(k, mx.nd.ones(BIG, dtype=dtype).tostype("row_sparse"))
        v = mx.nd.sparse.zeros("row_sparse", BIG, dtype=dtype)
        kv.push(k, v)
        val = mx.nd.sparse.zeros("row_sparse", BIG, dtype=dtype)
        kv.row_sparse_pull(k, out=val,
                           row_ids=mx.nd.array(np.arange(BIG[0])))
        check_diff(val.tostype("default"), np.ones(BIG, dtype), rank,
                   "rsp zeros full")
        kv.row_sparse_pull(k, out=val, row_ids=mx.nd.array([]))
        check_diff(val.tostype("default"), np.zeros(BIG, dtype), rank,
                   "rsp zeros empty")
    print(f"RSP_ZEROS_OK rank={rank}")


def test_big_row_sparse(kv, rank, nw, nrepeat=2):
    k = "97"
    kv.init(k, mx.nd.ones(IRREGULAR).tostype("row_sparse"))
    rng = np.random.RandomState(123)
    density = 0.3
    indices = np.argwhere(rng.rand(IRREGULAR[0]) < density).flatten()
    update_rows = []
    for r in range(nw):
        step = (r + 1) * 2
        update_rows.append(np.asarray(indices[::step]))
    v = np.zeros(IRREGULAR, "float32")
    for row in update_rows[rank]:
        v[row] = rank + 1
    for i in range(nrepeat):
        kv.push(k, mx.nd.array(v).tostype("row_sparse"))
        rng2 = np.random.RandomState(rank + 7 * i)
        row_ids_np = rng2.randint(IRREGULAR[0], size=IRREGULAR[0])
        val = mx.nd.sparse.zeros("row_sparse", IRREGULAR)
        kv.row_sparse_pull(k, out=val, row_ids=mx.nd.array(row_ids_np))
        updated = np.ones(IRREGULAR, "float32")
        for r in range(nw):
            for row in update_rows[r]:
                updated[row] += (r + 1) * RATE * (i + 1)
        expected = np.zeros(IRREGULAR, "float32")
        for row in row_ids_np:
            expected[row] = updated[row]
        check_diff(val.tostype("default"), expected, rank, "big rsp")
    print(f"BIG_RSP_OK rank={rank}")


def test_2bit_compression(kv, rank, nw):
    threshold = 0.5
    kv.set_gradient_compression({"type": "2bit", "threshold": threshold})
    for k, s in [("1000", SHAPE), ("1200", IRREGULAR), ("1300", BIG)]:
        kv.init(k, mx.nd.zeros(s))
        # below threshold: residual only, no update
        kv.push(k, mx.nd.ones(s) * 0.4)
        val = mx.nd.zeros(s)
        kv.pull(k, out=val)
        check_diff(val, np.zeros(s, "float32"), rank, "compr below")
        # residual tops it over the threshold on every worker
        kv.push(k, mx.nd.ones(s) * (threshold - 0.4))
        kv.pull(k, out=val)
        curval = threshold * RATE * nw
        check_diff(val, np.full(s, curval, "float32"), rank, "compr meet")
        # below again
        kv.push(k, mx.nd.ones(s) * 0.2)
        kv.pull(k, out=val)
        check_diff(val, np.full(s, curval, "float32"), rank, "compr below2")
        # exceeds with residual
        kv.push(k, mx.nd.ones(s) * (threshold - 0.2))
        kv.pull(k, out=val)
        curval += threshold * RATE * nw
        check_diff(val, np.full(s, curval, "float32"), rank, "compr meet2")
    # inactive keys: init after compression, never pushed — stay at init
    for k, s in [("1001", SHAPE), ("1301", BIG)]:
        kv.init(k, mx.nd.ones(s))
        val = mx.nd.zeros(s)
        kv.pull(k, out=val)
        check_diff(val, np.ones(s, "float32"), rank, "compr inactive")
    # random gradients, same on every worker: expected = quantize chain
    rng = np.random.RandomState(9)
    g1 = rng.uniform(-1, 1, SHAPE).astype("float32")
    g2 = rng.uniform(-1, 1, SHAPE).astype("float32")
    kv.init("1002", mx.nd.zeros(SHAPE))
    w_expect = np.zeros(SHAPE, "float32")
    residual = np.zeros(SHAPE, "float32")
    for g in (g1, g2):
        kv.push("1002", mx.nd.array(g))
        acc = residual + g
        q = np.where(acc >= threshold, threshold,
                     np.where(acc <= -threshold, -threshold, 0.0)
                     ).astype("float32")
        residual = acc - q
        w_expect += RATE * nw * q
        val = mx.nd.zeros(SHAPE)
        kv.pull("1002", out=val)
        check_diff(val, w_expect, rank, "compr random")
    print(f"COMPR_OK rank={rank}")


def test_dist_lenet(rank, nw):
    """dist_lenet-style convergence (reference
    ``tests/nightly/dist_lenet.py``): a small conv net via Module.fit over
    dist_sync; weights must stay identical across workers and learn."""
    kv = mx.kv.create("dist_sync")
    mx.random.seed(7)
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f1 = mx.sym.Flatten(p1)
    fc = mx.sym.FullyConnected(f1, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")

    rng = np.random.RandomState(1000 + rank)
    n = 64
    y = rng.randint(0, 4, n).astype("float32")
    x = np.zeros((n, 1, 12, 12), "float32")
    for j in range(n):
        q = int(y[j])
        x[j, 0, (q // 2) * 6:(q // 2) * 6 + 6,
          (q % 2) * 6:(q % 2) * 6 + 6] = 1.0
    x += rng.randn(*x.shape).astype("float32") * 0.05
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=4, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    acc = mod.score(mx.io.NDArrayIter(x, y, batch_size=16), "acc")[0][1]
    w = mod.get_params()[0]["fc_weight"].asnumpy()
    from jax.experimental import multihost_utils
    allw = np.asarray(multihost_utils.process_allgather(w))
    for r in range(nw):
        assert np.allclose(allw[r], w, atol=1e-5), \
            f"rank {rank}: lenet weights diverged from rank {r}"
    assert acc > 0.9, acc
    print(f"LENET_OK rank={rank} acc={acc:.3f}")


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == int(os.environ["JAX_NUM_PROCESSES"])
    kv.set_optimizer(TestOptimizer(rescale_grad=RATE))
    test_dense(kv, rank, nw)
    test_row_sparse(kv, rank, nw)
    test_row_sparse_zeros(kv, rank, nw)
    test_big_row_sparse(kv, rank, nw)
    test_2bit_compression(kv, rank, nw)
    kv.barrier()
    test_dist_lenet(rank, nw)
    print(f"MATRIX_OK rank={rank}")


if __name__ == "__main__":
    main()
