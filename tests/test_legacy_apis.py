"""FeedForward + im2rec + ONNX-gating tests."""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_feedforward_fit_predict(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(120, 6).astype("float32")
    y = (x.sum(axis=1) > 0).astype("float32")
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=10,
                                 optimizer="sgd", learning_rate=1.0,
                                 numpy_batch_size=30)
    model.fit(x, y)
    preds = model.predict(x)
    acc = ((preds.argmax(1) == y).mean())
    assert acc > 0.9, acc
    prefix = str(tmp_path / "ff")
    model.save(prefix, 8)
    loaded = mx.model.FeedForward.load(prefix, 8, ctx=mx.cpu())
    assert "fc1_weight" in loaded.arg_params


def test_im2rec_roundtrip(tmp_path):
    import cv2
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import im2rec

    root = tmp_path / "imgs"
    for cls in ("cats", "dogs"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            img = (np.random.rand(24, 24, 3) * 255).astype(np.uint8)
            cv2.imwrite(str(root / cls / f"{i}.png"), img)
    prefix = str(tmp_path / "data")
    im2rec.main([prefix, str(root), "--list", "--recursive"])
    assert os.path.exists(prefix + ".lst")
    im2rec.main([prefix, str(root), "--encoding", ".png"])
    assert os.path.exists(prefix + ".rec")
    ds = mx.gluon.data.vision.ImageRecordDataset(prefix + ".rec")
    assert len(ds) == 6
    img, label = ds[0]
    assert img.shape == (24, 24, 3)
    assert label in (0.0, 1.0)


def test_onnx_import_model_wheel_free():
    # import_model no longer needs the onnx wheel (hand-written wire-format
    # parser, contrib/onnx/protobuf.py) — a missing file is just a missing
    # file now
    with pytest.raises(FileNotFoundError):
        mx.contrib.onnx.import_model("x.onnx")
