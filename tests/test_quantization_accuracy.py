"""End-to-end int8 accuracy gate on a model-zoo net (VERDICT r2 missing
item: the reference proves int8 top-1 stays within ~1% of fp32 on real
models — ``example/quantization/README.md``).  No pretrained weights exist
offline, so the fixture is a quickly-trained thumbnail ResNet-18 on a
synthetic separable dataset; the assert is the same contract: quantized
top-1 within a stated tolerance of fp32 top-1, via the full calibration
driver (entropy mode).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.quantization import quantize_model


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 4, n)
    x = rng.rand(n, 3, 32, 32).astype("float32") * 0.25
    for i, c in enumerate(y):
        x[i, :, (c // 2) * 16:(c // 2) * 16 + 16,
          (c % 2) * 16:(c % 2) * 16 + 16] += 0.75
    return x, y.astype("float32")


@pytest.mark.slow
def test_model_zoo_resnet18_int8_within_tolerance():
    mx.random.seed(0)
    net = mx.gluon.model_zoo.vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize()
    x, y = _data(256)
    xin = mx.nd.array(x)
    net.hybridize()
    net(xin)

    # quick fit via the jitted SPMD train step (one compile, fast steps)
    from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                    make_mesh)
    trainer = SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                          FunctionalOptimizer("adam", 2e-3),
                          make_mesh(n_devices=1, dp=1))
    yin = mx.nd.array(y)
    for epoch in range(6):
        for i in range(0, 256, 32):
            trainer.step(xin[i:i + 32], yin[i:i + 32])
    trainer.sync_to_block()

    # export to symbol+params (the quantizer's input format)
    import tempfile, os
    d = tempfile.mkdtemp(prefix="quantacc_")
    prefix = os.path.join(d, "r18")
    net.export(prefix)
    sym = mx.sym.load(prefix + "-symbol.json")
    loaded = mx.nd.load(prefix + "-0000.params")
    arg_params = {k.split(":", 1)[1]: v for k, v in loaded.items()
                  if k.startswith("arg:")}
    aux_params = {k.split(":", 1)[1]: v for k, v in loaded.items()
                  if k.startswith("aux:")}
    sym = mx.sym.SoftmaxOutput(sym, name="softmax")

    def top1(s, arg, aux):
        mod = mx.mod.Module(s, context=mx.cpu())
        mod.bind(data_shapes=[("data", (32, 3, 32, 32))],
                 label_shapes=[("softmax_label", (32,))], for_training=False)
        mod.set_params(arg, aux, allow_missing=False)
        return mod.score(mx.io.NDArrayIter(x, y, batch_size=32),
                         "acc")[0][1]

    fp32_acc = top1(sym, arg_params, aux_params)
    assert fp32_acc > 0.9, f"fixture net failed to train ({fp32_acc})"

    calib = mx.io.NDArrayIter(x[:96], y[:96], batch_size=32)
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, calib_mode="entropy",
        calib_data=calib, num_calib_examples=96)
    int8_acc = top1(qsym, qarg, qaux)
    # the reference's published contract: ~1% degradation on real nets;
    # on this fixture allow 2 points of top-1
    assert int8_acc >= fp32_acc - 0.02, (fp32_acc, int8_acc)

    # the FAST path (r4): fused int8 lowering — offline per-channel int8
    # weights, folded BN, int8 MXU matmuls, int8 NHWC activations.  Same
    # accuracy contract as the fake-quant formulation.
    calib.reset()
    fsym, farg, faux = quantize_model(
        sym, arg_params, aux_params, calib_mode="entropy",
        calib_data=calib, num_calib_examples=96, lowering="fused_int8")
    ops = {n.op.name for n in fsym._topo() if n.op is not None}
    assert "_contrib_int8_conv_fused" in ops, ops
    assert "Convolution" not in ops, "a conv fell back to fp32"
    fused_acc = top1(fsym, farg, faux)
    assert fused_acc >= fp32_acc - 0.02, (fp32_acc, fused_acc)
