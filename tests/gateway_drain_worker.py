"""Subprocess worker for the SIGTERM graceful-drain drill (ISSUE 19
satellite).

Serves a one-model gateway whose batcher holds requests for its full
``max_latency_ms`` window, so the parent can have a request *in flight*
when it sends SIGTERM.  A :class:`PreemptionHandler` wired through
``Gateway.install_preemption`` flips the gateway to draining: the
in-flight request must complete 200, new submits must shed 503
``shutdown``, and the process must exit 0 once traffic stops.

Prints ``PORT <n>`` when serving and ``DRAINED`` after a clean drain.
"""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.resilience import PreemptionHandler
    from mxnet_tpu.serving import ModelRegistry, ModelRuntime
    from mxnet_tpu.serving.gateway import Gateway

    handler = PreemptionHandler(signals=(signal.SIGTERM,))

    mx.random.seed(1)
    dense = mx.gluon.nn.Dense(4)
    dense.initialize()
    dense(nd.zeros((1, 8)))             # shape inference before compile
    rt = ModelRuntime(dense, item_shapes=(8,), max_batch=8)
    registry = ModelRegistry()
    # a long flush window: one submitted item sits in the batch for
    # ~500ms, giving the parent room to SIGTERM around it
    registry.register("tiny_dense", rt, max_latency_ms=500.0)

    gw = Gateway(registry=registry, capacity=8)
    gw.install_preemption(handler)
    print(f"PORT {gw.port}", flush=True)

    handler.wait()                      # SIGTERM lands here
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline and gw.admission.inflight() > 0:
        time.sleep(0.02)                # in-flight requests finish
    leaked = gw.admission.inflight()
    gw.close()
    registry.close(drain=True)
    if leaked:
        print(f"LEAKED {leaked}", flush=True)
        sys.exit(3)
    print("DRAINED", flush=True)
    sys.exit(0)


if __name__ == "__main__":
    main()
