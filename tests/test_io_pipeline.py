"""Multi-process input pipeline (ISSUE 6 tentpole): shared-memory ring
decode, bitwise determinism vs the thread path, worker-death handling,
sharded readers, and the device-side augmentation prologue."""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, telemetry
from mxnet_tpu.image import DeviceAugmenter
from mxnet_tpu.io import RecordShardSampler, ShmRing
from mxnet_tpu.resilience import InjectedFault, faults


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


N_IMG, HW = 96, 64


@pytest.fixture(scope="module")
def rec_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("iopipe") / "data.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    img = (rng.rand(HW, HW, 3) * 255).astype("uint8")
    for i in range(N_IMG):
        img[i % HW, :, :] = (i * 37) % 255
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write(recordio.pack_img(header, img, quality=85))
    rec.close()
    return path


def _epoch(it, with_aug=False):
    out = []
    for b in it:
        row = [b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy(),
               b.pad]
        if with_aug:
            row += [b.augment_flip.copy(), b.augment_crop.copy()]
        out.append(row)
    return out


def _no_shm_leaks():
    if not os.path.isdir("/dev/shm"):
        return True
    return not [f for f in os.listdir("/dev/shm") if f.startswith("mxio")]


# ------------------------------------------------------------------ shm ring
def test_shm_ring_lifecycle():
    ring = ShmRing(3, 1024)
    slots = [ring.acquire() for _ in range(3)]
    assert ring.acquire() is None and ring.in_flight == 3
    v = ring.view(slots[0], (256,), np.uint32)
    v[:] = 7
    assert ring.view(slots[0], (256,), np.uint32)[100] == 7
    for s in slots:
        ring.release(s)
    assert ring.in_flight == 0
    ring.destroy()
    ring.destroy()          # idempotent
    assert _no_shm_leaks()


# -------------------------------------------------------------- determinism
def test_multiprocess_bitwise_matches_thread_path(rec_path):
    """Fixed shuffle seed → multi-process epochs are bitwise-identical to
    the single-process thread path, across two epochs (ISSUE 6 satellite)."""
    kw = dict(path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=16,
              shuffle=True, rand_mirror=True, rand_crop=True, seed=11)
    it_thread = mx.io.ImageRecordIter(**kw)
    it_mp = mx.io.ImageRecordIter(preprocess_processes=2, **kw)
    try:
        for _epoch_i in range(2):
            a = _epoch(it_thread)
            b = _epoch(it_mp)
            assert len(a) == len(b) == (N_IMG + 15) // 16
            for (da, la, pa), (db, lb, pb) in zip(a, b):
                assert pa == pb
                np.testing.assert_array_equal(la, lb)
                np.testing.assert_array_equal(da, db)
            it_thread.reset()
            it_mp.reset()
    finally:
        it_thread.close()
        it_mp.close()
    assert _no_shm_leaks()


@pytest.mark.parametrize("pattern", ["reset_before_use", "mid_epoch"])
def test_multiprocess_rng_parity_across_resets(rec_path, pattern):
    """The pool pre-draws flip/crop randomness at dispatch time; a reset
    before or mid-epoch must rewind to where the thread path's lazy draws
    would be (regression: DevicePrefetchIter resets the iterator before
    first use, which used to skip a ring's worth of draws)."""
    kw = dict(path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=16,
              shuffle=True, rand_mirror=True, rand_crop=True, seed=23)

    def run(procs):
        it = mx.io.ImageRecordIter(preprocess_processes=procs, **kw)
        try:
            if pattern == "reset_before_use":
                it.reset()
            else:
                for _ in range(2):       # consume part of the epoch...
                    next(it)
                it.reset()               # ...then abandon it
            return [b.data[0].asnumpy().copy() for b in it]
        finally:
            it.close()

    for a, b in zip(run(0), run(2)):
        np.testing.assert_array_equal(a, b)


def test_processes_zero_is_the_thread_path(rec_path):
    """``preprocess_processes=0`` must not even construct pipeline state —
    the pre-PR dispatch path, byte for byte."""
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                               batch_size=16)
    try:
        assert it._pipeline is None and it._pool is not None
        batch = next(it)
        assert batch.data[0].shape == (16, 3, 48, 48)
    finally:
        it.close()


def test_multiprocess_telemetry_counters(rec_path):
    telemetry.enable()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                               batch_size=16, preprocess_processes=2)
    try:
        n = sum(1 for _ in it)
        c = telemetry.snapshot()["counters"]
        assert c["io.record_batches"] == n
        assert c["io.staging_bytes"] > 0
        assert "io.proc_decode_wait_ms" in c
        assert "io.proc_decode_ms" in c
        gauges = telemetry.snapshot()["gauges"]
        assert any(k.startswith("io.shm_ring_occupancy") for k in gauges)
    finally:
        it.close()


# ------------------------------------------------------------- worker death
def test_worker_death_raises_bounded_not_hangs(rec_path):
    """A killed decode worker surfaces as a sticky RuntimeError within the
    bounded wait — the training loop must never hang (ISSUE 6 satellite)."""
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                               batch_size=8, preprocess_processes=2,
                               pipeline_timeout=15)
    try:
        next(it)
        os.kill(it._pipeline._procs[0].pid, 9)
        t0 = time.time()
        with pytest.raises(RuntimeError, match="died"):
            for _ in range(40):
                next(it)
        assert time.time() - t0 < 10.0, "death detection must be bounded"
        with pytest.raises(RuntimeError):
            next(it)        # sticky: keeps raising, never misreports EOF
    finally:
        it.close()
    assert _no_shm_leaks()


def test_worker_respawn_completes_epoch(rec_path):
    """``worker_respawn=True`` re-forks a dead worker (RetryPolicy backoff),
    requeues its lost batch, and the epoch completes with every batch."""
    telemetry.enable()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                               batch_size=8, preprocess_processes=2,
                               worker_respawn=True, pipeline_timeout=30)
    try:
        seen = 0
        for i, _b in enumerate(it):
            if i == 1:
                os.kill(it._pipeline._procs[1].pid, 9)
            seen += 1
        assert seen == N_IMG // 8
        assert telemetry.counter_value("io.worker_respawns") >= 1
        it.reset()
        assert sum(1 for _ in it) == seen      # next epoch is healthy too
    finally:
        it.close()
    assert _no_shm_leaks()


def test_injected_worker_crash_fault_site(rec_path):
    """``io.shm_slot`` faults hard-kill the worker process (os._exit) — the
    parent's death path and shm teardown run against a real crash."""
    with faults.scope("io.shm_slot:fail:1"):
        it = mx.io.ImageRecordIter(
            path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=8,
            preprocess_processes=2, pipeline_timeout=15)
        try:
            with pytest.raises(RuntimeError, match="died"):
                for _ in it:
                    pass
        finally:
            it.close()
    assert _no_shm_leaks()


def test_injected_spawn_fault(rec_path):
    """``io.worker_spawn`` faults fire in the parent at process start."""
    with faults.scope("io.worker_spawn:fail:1"):
        with pytest.raises(InjectedFault):
            mx.io.ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=8,
                preprocess_processes=2)
    assert _no_shm_leaks()


def test_decode_error_is_per_batch_not_sticky(rec_path, tmp_path):
    """A corrupt record raises once for ITS batch (with the worker
    traceback) and the pipeline keeps serving later batches — the thread
    path's contract, where the pool survives a bad record."""
    from mxnet_tpu.io import BatchDecodeError
    bad = str(tmp_path / "bad.rec")
    with open(rec_path, "rb") as f:
        blob = bytearray(f.read())
    blob[40:160] = b"\x5a" * 120        # stomp the first image's payload
    with open(bad, "wb") as f:
        f.write(bytes(blob))
    it = mx.io.ImageRecordIter(path_imgrec=bad, data_shape=(3, 48, 48),
                               batch_size=8, preprocess_processes=2,
                               pipeline_timeout=15)
    try:
        with pytest.raises(BatchDecodeError, match="worker"):
            next(it)                     # batch 0 carries the bad record
        rest = sum(1 for _ in it)        # the remaining batches still flow
        assert rest == N_IMG // 8 - 1
        it.reset()                       # and the next epoch works too
        with pytest.raises(BatchDecodeError):
            next(it)
        assert sum(1 for _ in it) == N_IMG // 8 - 1
    finally:
        it.close()
    assert _no_shm_leaks()


def test_device_augment_rand_crop_needs_margin(rec_path):
    """rand_crop with a canvas equal to the crop target would silently
    skip cropping on device — construction must refuse instead."""
    with pytest.raises(ValueError, match="crop margin"):
        mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                              batch_size=8, rand_crop=True,
                              device_augment=True)


# ---------------------------------------------------------- sharded readers
def test_record_shard_sampler_partitions():
    parts = [RecordShardSampler(3, i).shard(10) for i in range(3)]
    covered = sorted(sum((list(range(10))[s] for s in parts), []))
    assert covered == list(range(10))
    with pytest.raises(ValueError):
        RecordShardSampler(2, 2)


def test_record_shard_sampler_from_mesh():
    from mxnet_tpu.parallel import data_shard_info, make_mesh
    mesh = make_mesh(n_devices=1, dp=1)
    assert data_shard_info(mesh, axis="dp") == (1, 0)
    assert data_shard_info(None) == (1, 0)       # single-process fallback
    s = RecordShardSampler.from_mesh(mesh)
    assert (s.num_parts, s.part_index) == (1, 0)


def test_shard_overrides_parts(rec_path):
    """``shard=`` routes through the same contiguous (num_parts, part_index)
    split as the reference kParts handling — both pipeline modes."""
    full = mx.io.ImageRecordIter(path_imgrec=rec_path,
                                 data_shape=(3, 48, 48), batch_size=8)
    labels = [l for b in full for l in b.label[0].asnumpy()]
    full.close()
    for procs in (0, 2):
        got = []
        for part in range(2):
            it = mx.io.ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=8,
                shard=RecordShardSampler(2, part),
                preprocess_processes=procs)
            assert it.num_data == N_IMG // 2
            got.extend(l for b in it for l in b.label[0].asnumpy())
            it.close()
        assert got == labels
    assert _no_shm_leaks()


# ------------------------------------------------- device augment prologue
def test_device_augment_matches_host_augment(rec_path):
    """uint8 canvas + jitted prologue == the host-augmented batch (crop,
    mirror, normalize, widen), with ZERO steady-state compile misses."""
    telemetry.enable()
    kw = dict(path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=16,
              rand_mirror=True, rand_crop=True, resize=56, seed=5,
              mean_r=10., mean_g=20., mean_b=30.,
              std_r=2., std_g=3., std_b=4., scale=0.5)
    it_dev = mx.io.ImageRecordIter(device_augment=True,
                                   preprocess_processes=2, **kw)
    it_host = mx.io.ImageRecordIter(**kw)
    aug = it_dev.augmenter
    try:
        n = 0
        for bd, bh in zip(it_dev, it_host):
            assert bd.data[0].dtype == np.uint8
            x = aug(bd.data[0].asnumpy(), bd.augment_flip, bd.augment_crop)
            np.testing.assert_allclose(np.asarray(x),
                                       bh.data[0].asnumpy(),
                                       rtol=1e-5, atol=1e-4)
            n += 1
        assert n == N_IMG // 16
        assert aug.compile_misses == 1
        assert telemetry.counter_value("io.augment_compile_miss") == 1
        # second epoch: zero new misses (the steady-state contract)
        it_dev.reset()
        for bd in it_dev:
            aug(bd.data[0].asnumpy(), bd.augment_flip, bd.augment_crop)
        assert aug.compile_misses == 1
    finally:
        it_dev.close()
        it_host.close()
    assert _no_shm_leaks()


def test_device_augment_thread_path_matches_mp(rec_path):
    """``device_augment=True`` with procs=0 (in-process canvas decode)
    produces the same uint8 canvases as the worker path."""
    kw = dict(path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=16,
              device_augment=True, rand_mirror=True, seed=2)
    a = mx.io.ImageRecordIter(**kw)
    b = mx.io.ImageRecordIter(preprocess_processes=2, **kw)
    try:
        for ba, bb in zip(a, b):
            np.testing.assert_array_equal(ba.data[0].asnumpy(),
                                          bb.data[0].asnumpy())
            np.testing.assert_array_equal(ba.augment_flip, bb.augment_flip)
    finally:
        a.close()
        b.close()


def test_augment_prologue_fuses_into_engine_segments():
    """The prologue dispatches as a capturable op: under ``engine.bulk`` it
    lands in a fused segment with downstream eager ops (PR 5 integration)."""
    from mxnet_tpu import engine
    telemetry.enable()
    aug = DeviceAugmenter((8, 8), rand_mirror=True)
    x8 = mx.nd.array(np.random.RandomState(0).randint(
        0, 255, (2, 3, 10, 10)).astype("uint8"))
    flips = np.array([1, 0])
    crops = np.zeros((2, 2), "float32")
    ref = aug(x8, flips, crops).asnumpy() * 2.0
    c0 = telemetry.counter_value("dispatch.ops_fused") or 0
    with engine.bulk(8):
        y = aug(x8, flips, crops) * 2.0
        from mxnet_tpu.engine.recorder import LazyData
        assert type(y._data) is LazyData      # captured, not dispatched
        out = y.asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    assert (telemetry.counter_value("dispatch.ops_fused") or 0) >= c0 + 2


def test_staged_batches_survive_slot_recycling(rec_path):
    """Regression: the CPU backend's device_put zero-copy-aliases
    page-aligned host buffers, so handing out raw slot views would let a
    recycled slot corrupt batches the consumer still references.  The
    default (copying) mode must keep every staged batch intact even when
    read long after its slot was rewritten."""
    kw = dict(path_imgrec=rec_path, data_shape=(3, 48, 48), batch_size=16)
    ref_it = mx.io.ImageRecordIter(**kw)
    ref = [b.data[0].asnumpy().copy() for b in ref_it]
    ref_it.close()
    it = mx.io.ImageRecordIter(preprocess_processes=2, **kw)
    try:
        staged = [b.data[0] for b in it]      # hold EVERY batch's NDArray
        assert len(staged) == len(ref)
        for got, want in zip(staged, ref):    # read after full epoch
            np.testing.assert_array_equal(got.asnumpy(), want)
    finally:
        it.close()


def test_device_prefetch_over_multiprocess_iterator(rec_path):
    """The zero-copy staging chain end-to-end: shm slot view →
    ``DevicePrefetchIter`` double-buffered device_put → device prologue."""
    import jax
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 48, 48),
                               batch_size=16, device_augment=True,
                               rand_mirror=True, preprocess_processes=2)
    aug = it.augmenter

    def stage(b):
        return (jax.device_put(b.data[0]._data),
                jax.device_put(b.label[0]._data),
                b.augment_flip, b.augment_crop)

    pit = mx.io.DevicePrefetchIter(it, stage, depth=2)
    try:
        n = 0
        for x, y, flips, crops in pit:
            out = aug(x, flips, crops)
            assert out.shape == (16, 3, 48, 48)
            n += 1
        assert n == N_IMG // 16
    finally:
        it.close()
    assert _no_shm_leaks()
