"""Gluon layer tests, mirroring reference tests/python/unittest/test_gluon.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()
    with pytest.raises(RuntimeError):
        p.grad()


def test_parameter_dict():
    ctx = mx.current_context()
    params0 = gluon.ParameterDict("net_")
    params0.get("w0", shape=(10, 10))
    params0.get("w1", shape=(10, 10), stype="default")
    all_row_ids = nd.arange(0, 10)
    params0.initialize(ctx=ctx)
    params1 = gluon.ParameterDict("net_")
    params1.get("w0", shape=(10, 10))
    params1.get("w1", shape=(10, 10))
    assert list(params0.keys()) == ["net_w0", "net_w1"]


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4]])
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5},
                            kvstore=None)
    with autograd.record():
        x = nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()
    assert (x.grad.asnumpy() == 1).all()


def test_basic():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10, flatten=False))
    model.add(nn.Dropout(0.5))
    model.add(nn.Dense(64, activation="tanh", in_units=256),
              nn.Dense(32, in_units=64))
    model.add(nn.Activation("relu"))
    # symbol-free eager run
    model.initialize()
    x = nd.zeros((32, 2, 10))
    assert model(x).shape == (32, 32)
    # save/load params
    assert len(model.collect_params()) == 6


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_")
    inputs = mx.nd.zeros((2, 3, 10))
    assert set(model.collect_params().keys()) == {"test_weight", "test_bias"}
    model.initialize()
    outputs = model(inputs)
    assert outputs.shape == (2, 3, 128)

    model = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                     prefix="test2_")
    inputs = mx.nd.zeros((17, 2, 5, 3))
    model.initialize()
    outputs = model(inputs)
    assert outputs.shape == (17, 128)


def test_dense_deferred_shape():
    model = nn.Dense(8)
    model.initialize()
    x = nd.ones((4, 3))
    y = model(x)
    assert y.shape == (4, 8)
    assert model.weight.shape == (8, 3)


@pytest.mark.parametrize("hybridize", [False, True])
def test_conv_pool_stack(hybridize):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, kernel_size=3, padding=1, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(8, kernel_size=3),
                nn.AvgPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(10))
    net.initialize()
    if hybridize:
        net.hybridize()
    x = nd.array(np.random.randn(2, 3, 16, 16).astype(np.float32))
    y = net(x)
    assert y.shape == (2, 10)


def test_conv_groups():
    net = nn.Conv2D(8, kernel_size=3, groups=2, in_channels=4)
    net.initialize()
    x = nd.ones((1, 4, 8, 8))
    assert net(x).shape == (1, 8, 6, 6)
    assert net.weight.shape == (8, 2, 3, 3)


def test_deconv():
    net = nn.Conv2DTranspose(4, kernel_size=4, strides=2, padding=1,
                             in_channels=3)
    net.initialize()
    x = nd.ones((2, 3, 8, 8))
    y = net(x)
    assert y.shape == (2, 4, 16, 16)


def test_pool_shapes():
    x = nd.ones((2, 3, 8, 8))
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)
    p = nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True)
    assert p(x).shape == (2, 3, 4, 4)
    p = nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=False)
    assert p(x).shape == (2, 3, 3, 3)


def test_batchnorm_train_stats():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = nd.array(np.random.randn(8, 4, 3, 3).astype(np.float32) * 2 + 1)
    with autograd.record():
        y = bn(x)
    mm = bn.running_mean.data().asnumpy()
    assert np.abs(mm).sum() > 0  # moving mean moved toward batch mean
    # inference path uses running stats
    y2 = bn(x)
    assert y2.shape == x.shape


def test_layernorm():
    ln = nn.LayerNorm(in_channels=10)
    ln.initialize()
    x = nd.array(np.random.randn(4, 10).astype(np.float32))
    y = ln(x).asnumpy()
    assert np.allclose(y.mean(axis=-1), 0, atol=1e-5)
    assert np.allclose(y.std(axis=-1), 1, atol=1e-2)


def test_embedding():
    layer = nn.Embedding(10, 100)
    layer.initialize()
    x = nd.array([3, 4, 2, 0, 1])
    with autograd.record():
        y = layer(x)
        y.backward()
    assert (layer.weight.grad().asnumpy()[:5] != 0).sum() > 0
    assert (layer.weight.grad().asnumpy()[5:] == 0).all()


def test_flatten_lambda():
    fl = nn.Flatten()
    x = nd.ones((2, 3, 4))
    assert fl(x).shape == (2, 12)
    lam = nn.HybridLambda("relu")
    assert lam(nd.array([-1.0, 1.0])).asnumpy().tolist() == [0.0, 1.0]
    lam2 = nn.Lambda(lambda x: x * 2)
    assert lam2(nd.ones((2,))).asnumpy().tolist() == [2.0, 2.0]


def test_activations():
    point_to_validate = nd.array([-0.1, 0.1] * 3)

    swish = nn.Swish()
    swish.initialize()
    elu = nn.ELU()
    elu.initialize()
    selu = nn.SELU()
    selu.initialize()
    prelu = nn.PReLU()
    prelu.initialize()
    gelu = nn.GELU()
    gelu.initialize()

    def swish_test(x):
        return x * (1.0 / (1.0 + np.exp(-x)))

    for test_point, ref_point in zip(swish_test(point_to_validate.asnumpy()),
                                     swish(point_to_validate).asnumpy()):
        assert np.isclose(test_point, ref_point, atol=1e-6)

    def elu_test(x):
        return [1.0 * (np.exp(y) - 1) if y < 0 else y for y in x]

    for test_point, ref_point in zip(elu_test(point_to_validate.asnumpy()),
                                     elu(point_to_validate).asnumpy()):
        assert np.isclose(test_point, ref_point, atol=1e-6)

    out = prelu(point_to_validate).asnumpy()
    expected = [x if x >= 0 else 0.25 * x for x in point_to_validate.asnumpy()]
    assert np.allclose(out, expected, atol=1e-6)


@pytest.mark.parametrize("hybridize", [False, True])
def test_lenet_training_decreases_loss(hybridize):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(6, kernel_size=5, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(32, activation="relu"),
                nn.Dense(10))
    net.initialize()
    if hybridize:
        net.hybridize()
    x = nd.array(np.random.randn(8, 1, 16, 16).astype(np.float32))
    label = nd.array(np.arange(8) % 10)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore=None)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), label)
        autograd.backward(loss)
        trainer.step(8)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
    net.initialize()
    x = nd.ones((2, 8))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
    net2.load_parameters(f)
    assert np.allclose(net2(x).asnumpy(), y0, atol=1e-6)


def test_hybrid_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.randn(2, 8).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    assert np.allclose(y_eager, y_hybrid, atol=1e-5)


def test_hybrid_grad_matches_eager():
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="tanh", in_units=8), nn.Dense(4))
        net.initialize()
        return net

    np.random.seed(7)
    x = nd.array(np.random.randn(2, 8).astype(np.float32))

    mx.random.seed(42)
    net_a = build()
    with autograd.record():
        loss = net_a(x).sum()
    autograd.backward(loss)
    g_a = [p.grad().asnumpy() for p in net_a.collect_params().values()
           if p.grad_req != "null"]

    mx.random.seed(42)
    net_b = build()
    net_b.hybridize()
    with autograd.record():
        loss = net_b(x).sum()
    autograd.backward(loss)
    g_b = [p.grad().asnumpy() for p in net_b.collect_params().values()
           if p.grad_req != "null"]
    for a, b in zip(g_a, g_b):
        assert np.allclose(a, b, atol=1e-5)


def test_losses():
    pred = nd.array(np.random.randn(4, 5).astype(np.float32))
    label_idx = nd.array(np.array([0, 1, 2, 3]))
    label_dense = nd.array(np.random.rand(4, 5).astype(np.float32))

    l2 = gluon.loss.L2Loss()(pred, label_dense)
    ref = 0.5 * ((pred.asnumpy() - label_dense.asnumpy()) ** 2).mean(axis=1)
    assert np.allclose(l2.asnumpy(), ref, atol=1e-6)

    l1 = gluon.loss.L1Loss()(pred, label_dense)
    ref = np.abs(pred.asnumpy() - label_dense.asnumpy()).mean(axis=1)
    assert np.allclose(l1.asnumpy(), ref, atol=1e-6)

    sce = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label_idx)
    p = pred.asnumpy()
    logsm = p - p.max(axis=1, keepdims=True)
    logsm = logsm - np.log(np.exp(logsm).sum(axis=1, keepdims=True))
    ref = -logsm[np.arange(4), label_idx.asnumpy().astype(int)]
    assert np.allclose(sce.asnumpy(), ref, atol=1e-5)

    bce = gluon.loss.SigmoidBCELoss()(pred, label_dense)
    x = pred.asnumpy()
    z = label_dense.asnumpy()
    ref = (np.maximum(x, 0) - x * z + np.log1p(np.exp(-np.abs(x)))).mean(axis=1)
    assert np.allclose(bce.asnumpy(), ref, atol=1e-5)

    hinge = gluon.loss.HingeLoss()(pred, label_dense)
    assert hinge.shape == (4,)

    huber = gluon.loss.HuberLoss()(pred, label_dense)
    assert huber.shape == (4,)


def test_sequential_indexing():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8), nn.Dense(4), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(list(iter(net))) == 3


def test_block_repr_and_collect():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
    params = net.collect_params()
    names = list(params.keys())
    assert all(n.startswith("model_") for n in names)
    assert "weight" in names[0]
    r = repr(net)
    assert "Dense" in r


def test_trainer_lr_and_states(tmp_path):
    net = nn.Dense(4, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9},
                            kvstore=None)
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.25)
    assert trainer.learning_rate == 0.25
    x = nd.ones((2, 3))
    with autograd.record():
        loss = net(x).sum()
    autograd.backward(loss)
    trainer.step(2)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    trainer.load_states(f)


def test_clip_global_norm():
    arrays = [nd.ones((3,)) * 2, nd.ones((2,)) * 3]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert norm <= 1.0 + 1e-5


def test_split_and_load():
    ctx = [mx.current_context()]
    data = nd.arange(12).reshape((4, 3))
    splits = gluon.utils.split_and_load(data, ctx)
    assert len(splits) == 1
    assert splits[0].shape == (4, 3)


def test_export_symbolblock_roundtrip(tmp_path):
    """hybridize → export → SymbolBlock.imports serves identically
    (reference block.py:876 export + block.py:960 SymbolBlock)."""
    net = nn.HybridSequential(prefix="exp_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(2, 8))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "served")
    net.export(prefix)
    served = mx.gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                          prefix + "-0000.params")
    out = served(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
