"""Chip-side half of the CPU↔TPU consistency suite (the reference's
``check_consistency`` role, ``python/mxnet/test_utils.py`` — same ops on
two backends, outputs must agree).

Run WITHOUT the suite's CPU pin so ``mx.gpu(0)`` resolves to the real
accelerator; writes every op output to the npz given in argv[1].
The op batch is defined HERE so both sides import one list.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def op_batch(mx, ctx):
    """name → NDArray output, deterministic inputs, every major op family.

    Exactness: run under ``default_matmul_precision('highest')`` so the
    MXU computes fp32 (bf16 rounding would need sloppy tolerances)."""
    rng = np.random.RandomState(42)

    def A(*shape, scale=1.0):
        return mx.nd.array(rng.randn(*shape).astype("float32") * scale,
                           ctx=ctx)

    x = A(2, 3, 8, 8)
    w = A(4, 3, 3, 3, scale=0.5)
    b = A(4)
    out = {}
    out["conv"] = mx.nd.Convolution(x, w, b, kernel=(3, 3), pad=(1, 1),
                                    num_filter=4)
    out["deconv"] = mx.nd.Deconvolution(x, A(3, 4, 3, 3, scale=0.5),
                                        kernel=(3, 3), stride=(2, 2),
                                        pad=(1, 1), num_filter=4)
    out["maxpool"] = mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                                   pool_type="max")
    out["avgpool_full"] = mx.nd.Pooling(x, kernel=(3, 3), stride=(2, 2),
                                        pad=(1, 1), pool_type="avg",
                                        pooling_convention="full")
    gamma, beta = A(3, scale=0.3), A(3, scale=0.3)
    mean, var = A(3, scale=0.1), mx.nd.abs(A(3)) + 1.0
    out["bn_eval"] = mx.nd.BatchNorm(x, gamma, beta, mean, var,
                                     fix_gamma=False)
    out["fc"] = mx.nd.FullyConnected(A(4, 10), A(6, 10, scale=0.5), A(6),
                                     num_hidden=6)
    out["softmax"] = mx.nd.softmax(A(4, 7))
    out["log_softmax"] = mx.nd.log_softmax(A(4, 7))
    out["lrn"] = mx.nd.LRN(x, nsize=3, alpha=1e-3, beta=0.7)
    out["layernorm"] = mx.nd.LayerNorm(A(4, 9), A(9), A(9))
    out["dot_tn"] = mx.nd.dot(A(5, 4), A(5, 6), transpose_a=True)
    out["batch_dot"] = mx.nd.batch_dot(A(2, 3, 4), A(2, 4, 5))
    out["embedding"] = mx.nd.Embedding(
        mx.nd.array([1, 3, 0, 2], ctx=ctx), A(5, 6), input_dim=5,
        output_dim=6)
    out["take"] = mx.nd.take(A(6, 3), mx.nd.array([1, 4, 1], ctx=ctx))
    out["topk"] = mx.nd.topk(A(3, 9), k=3, ret_typ="value")
    out["sort"] = mx.nd.sort(A(3, 9), axis=1)
    out["sum_ax"] = mx.nd.sum(x, axis=(0, 2))
    out["max_ax"] = mx.nd.max(x, axis=1)
    out["norm2"] = mx.nd.norm(A(5, 5), ord=2)
    out["elem_chain"] = mx.nd.tanh(A(4, 4)) * mx.nd.sigmoid(A(4, 4)) + \
        mx.nd.relu(A(4, 4))
    out["erf_gamma"] = mx.nd.erf(A(3, 3)) + mx.nd.gammaln(
        mx.nd.abs(A(3, 3)) + 1.0)
    out["transpose"] = mx.nd.transpose(x, axes=(0, 2, 3, 1))
    out["slice"] = mx.nd.slice(x, begin=(0, 1, 2, 2), end=(2, 3, 6, 7))
    out["where"] = mx.nd.where(A(4, 4) > 0, A(4, 4), A(4, 4))
    out["leaky"] = mx.nd.LeakyReLU(A(4, 4), act_type="elu", slope=0.3)
    out["clip_sm"] = mx.nd.clip(mx.nd.smooth_l1(A(4, 4), scalar=1.5),
                                -0.8, 0.8)
    out["one_hot"] = mx.nd.one_hot(mx.nd.array([0, 2, 1], ctx=ctx), 4)
    out["grid_gen"] = mx.nd.GridGenerator(A(2, 6), transform_type="affine",
                                          target_shape=(4, 4))
    out["instance_norm"] = mx.nd.InstanceNorm(x, A(3), A(3), eps=1e-4)
    return out


def main():
    out_path = sys.argv[1]
    import jax
    import mxnet_tpu as mx

    accel = [d for d in jax.devices() if d.platform != "cpu"]
    if not accel:
        print("NO_ACCELERATOR")
        return 0
    ctx = mx.gpu(0)
    from chip_consistency_sweep import sweep_batch
    with jax.default_matmul_precision("highest"):
        outs = op_batch(mx, ctx)
        arrays = {k: v.asnumpy() for k, v in outs.items()}
        if os.environ.get("CHIP_SWEEP", "1") != "0":
            for k, v in sweep_batch(mx, ctx).items():
                arrays[f"sweep:{k}"] = v.asnumpy()
    np.savez(out_path, **arrays)
    print(f"CHIP_OK n={len(arrays)} device={accel[0].device_kind!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
