"""NDArray indexing contracts (reference
``tests/python/unittest/test_ndarray.py``: test_getitem/test_setitem/
advanced-indexing families — MXNet accepts float32 index arrays, the
historical default dtype).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _x():
    return mx.nd.array(np.arange(24).reshape(4, 6).astype("float32"))


def test_basic_slicing_matches_numpy():
    x = _x()
    n = x.asnumpy()
    for key in [slice(1, 3), slice(None, None, 2), slice(None, None, -1),
                (slice(1, 3), slice(2, 5)), (slice(None), slice(1, None, 2)),
                2, -1, (2, 3), Ellipsis, (Ellipsis, 1), None,
                (slice(None), None)]:
        np.testing.assert_array_equal(x[key].asnumpy(), n[key],
                                      err_msg=str(key))


def test_advanced_indexing_with_float_index_array():
    """Reference accepts float32 index NDArrays (the default dtype)."""
    x = _x()
    idx = mx.nd.array([0.0, 2.0, 3.0])          # float32!
    np.testing.assert_array_equal(x[idx].asnumpy(),
                                  x.asnumpy()[[0, 2, 3]])
    idx2 = mx.nd.array([1, 1, 0], dtype="int32")
    np.testing.assert_array_equal(x[idx2].asnumpy(),
                                  x.asnumpy()[[1, 1, 0]])


def test_advanced_indexing_in_tuple():
    x = _x()
    rows = mx.nd.array([0.0, 3.0])
    got = x[rows, 2].asnumpy()
    np.testing.assert_array_equal(got, x.asnumpy()[[0, 3], 2])


def test_setitem_scalar_slice_and_array():
    x = _x()
    n = x.asnumpy().copy()
    x[1:3] = 7.0
    n[1:3] = 7.0
    np.testing.assert_array_equal(x.asnumpy(), n)
    v = np.ones((2, 3), "float32") * 5
    x[0:2, 0:3] = mx.nd.array(v)
    n[0:2, 0:3] = v
    np.testing.assert_array_equal(x.asnumpy(), n)
    # broadcast setitem: row vector across the selected block
    x[:, 0:2] = mx.nd.array([[9.0, 8.0]])
    n[:, 0:2] = np.asarray([[9.0, 8.0]])
    np.testing.assert_array_equal(x.asnumpy(), n)


def test_setitem_with_float_index_array():
    x = _x()
    n = x.asnumpy().copy()
    x[mx.nd.array([0.0, 2.0])] = 1.5
    n[[0, 2]] = 1.5
    np.testing.assert_array_equal(x.asnumpy(), n)


def test_getitem_under_autograd_routes_gradient():
    x = mx.nd.array(np.arange(6, dtype="float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = x[1:4]
        (y * y).sum().backward()
    want = np.zeros(6, "float32")
    want[1:4] = 2 * np.arange(1, 4)
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-6)


def test_setitem_under_autograd_masks_gradient():
    """Writing a constant into a recorded array: the overwritten region's
    upstream gradient is cut (the write is itself a recorded op)."""
    x = mx.nd.array(np.arange(6, dtype="float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = x * 2.0
        y[0:2] = 0.0
        y.sum().backward()
    want = np.full(6, 2.0, "float32")
    want[0:2] = 0.0
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-6)


def test_getitem_returns_value_not_alias():
    x = _x()
    s = x[1:3]
    s[:] = 0.0
    # functional arrays: mutating the slice must not corrupt the base
    # (stricter than the reference's shared-memory views — documented)
    assert float(np.abs(x.asnumpy()[1:3]).sum()) > 0


def test_scalar_item_and_asscalar():
    x = _x()
    assert float(x[2, 3].asnumpy()) == 15.0
    assert x[0, 0].asscalar() == 0.0


def test_negative_and_out_of_range_int_index():
    x = _x()
    np.testing.assert_array_equal(x[-1].asnumpy(), x.asnumpy()[-1])
    with pytest.raises(Exception):
        _ = x[7]


def test_index_chain_equivalence():
    x = _x()
    np.testing.assert_array_equal(x[1][2:4].asnumpy(),
                                  x.asnumpy()[1][2:4])


def test_bool_scalar_and_mask_indexing():
    x = _x()
    n = x.asnumpy()
    # scalar bool adds an axis (numpy semantics) — must NOT be treated as
    # an integer index by the bounds checker
    np.testing.assert_array_equal(x[True].asnumpy(), n[True])
    assert x[False].shape == n[False].shape
    # explicit boolean mask array
    mask = np.zeros(4, dtype=bool)
    mask[1] = mask[3] = True
    np.testing.assert_array_equal(x[mask].asnumpy(), n[mask])


def test_fit_resume_with_extra_checkpoint_keys_stays_permissive():
    """init_params via fit(arg_params=...) ignores extra keys (reference
    behavior — only set_params validates)."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, name="fc", num_hidden=2), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 3))],
             label_shapes=[("softmax_label", (4,))])
    extra_args = {"fc_weight": mx.nd.ones((2, 3)),
                  "fc_bias": mx.nd.zeros((2,)),
                  "leftover_from_bigger_model": mx.nd.ones((5,))}
    mod.init_params(arg_params=extra_args, aux_params={},
                    allow_missing=False)          # extras tolerated here
    with pytest.raises(ValueError):
        mod.set_params(extra_args, {}, allow_extra=False)
    mod.set_params(extra_args, {}, allow_extra=True)
