"""Fault injection, retry, durable checkpoints, NaN guards, resume (ISSUE 4)."""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.parallel import (
    FunctionalOptimizer, SPMDCheckpointManager, SPMDTrainer, make_mesh,
)
from mxnet_tpu.resilience import (
    InjectedFault, ResilientTrainer, RetryPolicy, StepGuard, faults,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class _tel_scope:
    """Enable a fresh telemetry bus for the block, return snapshots."""

    def __enter__(self):
        telemetry.disable()
        telemetry.reset()
        telemetry.enable()
        return telemetry

    def __exit__(self, *exc):
        telemetry.disable()
        telemetry.reset()
        return False


# ------------------------------------------------------------------- faults
def test_fault_spec_grammar():
    parsed = faults.parse_spec(
        "checkpoint.write:fail:2, io.decode:delay:50ms:3, kv.push:flaky:0.25")
    sites = [s for s, _ in parsed]
    assert sites == ["checkpoint.write", "io.decode", "kv.push"]
    by = {s: p for s, p in parsed}
    assert by["checkpoint.write"].action == "fail"
    assert by["checkpoint.write"].count == 2
    assert by["io.decode"].action == "delay"
    assert by["io.decode"].delay == pytest.approx(0.05)
    assert by["io.decode"].count == 3
    assert by["kv.push"].prob == 0.25
    with pytest.raises(ValueError):
        faults.parse_spec("no_colon_here")
    with pytest.raises(ValueError):
        faults.parse_spec("a.b:explode")


def test_fail_policy_counts_down_and_disarms():
    faults.configure("a.b:fail:2")
    assert faults.active
    hits = 0
    for _ in range(4):
        try:
            faults.check("a.b")
        except InjectedFault as e:
            assert isinstance(e, IOError)   # retryable by default filters
            assert e.site == "a.b"
            hits += 1
    assert hits == 2
    assert not faults.active        # exhausted policies drop off entirely


def test_delay_policy_sleeps():
    faults.configure("slow.site:delay:30ms:1")
    t0 = time.perf_counter()
    faults.check("slow.site")
    assert time.perf_counter() - t0 >= 0.025
    t0 = time.perf_counter()
    faults.check("slow.site")       # count exhausted: no sleep
    assert time.perf_counter() - t0 < 0.02


def test_flaky_policy_is_seed_deterministic():
    def decisions():
        faults.configure("f.s:flaky:0.5:20")
        out = []
        for _ in range(20):
            try:
                faults.check("f.s")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = decisions(), decisions()
    assert a == b
    assert 0 < sum(a) < 20          # actually probabilistic


def test_scope_restores_previous_registry():
    faults.configure("outer.site:fail:5")
    with faults.scope("inner.site:fail:1"):
        assert list(faults.sites()) == ["inner.site"]
        with pytest.raises(InjectedFault):
            faults.check("inner.site")
    assert list(faults.sites()) == ["outer.site"]


def test_fault_injection_telemetry():
    with _tel_scope() as tel:
        faults.configure("x.y:fail:1")
        with pytest.raises(InjectedFault):
            faults.check("x.y")
        c = tel.snapshot()["counters"]
        assert c["resilience.fault_injected"] == 1


# -------------------------------------------------------------------- retry
def test_retry_recovers_and_emits_telemetry():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    with _tel_scope() as tel:
        policy = RetryPolicy(max_attempts=5, base_delay_ms=1, seed=0)
        assert policy.call(flaky, site="t.s") == "ok"
        c = tel.snapshot()["counters"]
        assert c["resilience.retry"] == 2
        assert "resilience.give_up" not in c
    assert len(calls) == 3


def test_retry_gives_up_and_reraises():
    with _tel_scope() as tel:
        policy = RetryPolicy(max_attempts=2, base_delay_ms=1)

        def always():
            raise OSError("hard down")

        with pytest.raises(OSError):
            policy.call(always, site="t.s")
        c = tel.snapshot()["counters"]
        assert c["resilience.retry"] == 1
        assert c["resilience.give_up"] == 1


def test_retry_only_retries_matching_exceptions():
    policy = RetryPolicy(max_attempts=5, base_delay_ms=1)
    calls = []

    def bug():
        calls.append(1)
        raise ValueError("logic bug, not transient")

    with pytest.raises(ValueError):
        policy.call(bug)
    assert len(calls) == 1


def test_retry_nonretryable_propagates_immediately():
    """``nonretryable`` wins over ``retryable`` — e.g. a sharded-save
    ``CommitBarrierTimeout`` (an OSError) where retrying a barrier whose
    co-writer is dead just multiplies the timeout."""
    from mxnet_tpu.parallel import CommitBarrierTimeout

    policy = RetryPolicy(max_attempts=5, base_delay_ms=1,
                         nonretryable=(CommitBarrierTimeout,),
                         sleep=lambda s: None)
    calls = []

    def barrier():
        calls.append(1)
        raise CommitBarrierTimeout("co-writer never showed")

    with pytest.raises(CommitBarrierTimeout):
        policy.call(barrier)
    assert len(calls) == 1                      # an OSError, yet no retry

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise IOError("transient")
        return "ok"

    assert policy.call(flaky) == "ok"           # plain OSError still retried


def test_retry_backoff_is_seeded_and_bounded():
    slept = []
    policy = RetryPolicy(max_attempts=4, base_delay_ms=10, max_delay_ms=25,
                         jitter=0.5, seed=7, sleep=slept.append)
    slept2 = []
    policy2 = RetryPolicy(max_attempts=4, base_delay_ms=10, max_delay_ms=25,
                          jitter=0.5, seed=7, sleep=slept2.append)

    def always():
        raise IOError("x")

    for p in (policy, policy2):
        with pytest.raises(IOError):
            p.call(always)
    assert slept == slept2                      # seeded jitter replays
    assert len(slept) == 3
    assert all(d <= 0.025 * 1.5 for d in slept)  # max_delay * (1+jitter)
    assert slept[0] >= 0.010


# --------------------------------------------------- durable checkpointing
def _trainer(seed=0, opt="adam", **kw):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu", in_units=8),
                mx.gluon.nn.Dense(4, in_units=16))
    net.initialize()
    mesh = make_mesh(dp=4, tp=2)
    return SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer(opt, 1e-2), mesh, **kw)


def _data():
    rng = np.random.RandomState(42)
    return (rng.randn(16, 8).astype("float32"),
            rng.randint(0, 4, 16).astype("float32"))


def test_midwrite_crash_recovers_previous_complete_step(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=3)
    tr.step(x, y)
    mgr.save(1, tr)
    params_at_1 = {k: np.asarray(v) for k, v in tr._state[0].items()}
    tr.step(x, y)
    faults.configure("checkpoint.write:fail:1")
    with pytest.raises(InjectedFault):
        mgr.save(2, tr)
    # the interrupted write left no committed step-2, no tmp litter after
    # the next save's GC, and step 1 restores bit-exact
    assert mgr.latest_step() == 1
    tr2 = _trainer(seed=3)
    mgr.restore(tr2)
    assert tr2._t == 1
    for k, v in params_at_1.items():
        np.testing.assert_array_equal(v, np.asarray(tr2._state[0][k]))


def test_checksum_corruption_falls_back_to_previous_step(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=3)
    for s in (1, 2):
        tr.step(x, y)
        mgr.save(s, tr)
    # flip one payload byte of the newest checkpoint
    payload = os.path.join(mgr.directory, "step_%010d" % 2, "state.bin")
    blob = bytearray(open(payload, "rb").read())
    blob[50] ^= 0xFF
    open(payload, "wb").write(bytes(blob))
    with _tel_scope() as tel:
        tr2 = _trainer(seed=3)
        mgr.restore(tr2)
        assert tr2._t == 1          # fell back to the step-1 tree
        c = tel.snapshot()["counters"]
        assert c["resilience.checkpoint_fallback"] == 1
        assert c["checkpoint.restores"] == 1


def test_corrupt_manifest_is_not_a_resume_candidate(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=3)
    for s in (1, 2):
        tr.step(x, y)
        mgr.save(s, tr)
    manifest = os.path.join(mgr.directory, "step_%010d" % 2, "manifest.json")
    open(manifest, "w").write("{ not json")
    assert mgr.latest_step() == 1
    tr2 = _trainer(seed=3)
    mgr.restore(tr2)
    assert tr2._t == 1


def test_retention_never_gcs_the_only_complete_checkpoint(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=1)
    tr.step(x, y)
    mgr.save(1, tr)
    # every later save dies mid-write; the lone complete checkpoint must
    # survive both the failures and their GC passes
    faults.configure("checkpoint.write:fail:10")
    for s in (2, 3, 4):
        tr.step(x, y)
        with pytest.raises(InjectedFault):
            mgr.save(s, tr)
    faults.clear()
    assert mgr.complete_steps() == [1]
    mgr.restore(_trainer(seed=3))


def test_retention_keeps_max_to_keep(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=2)
    for s in range(1, 5):
        tr.step(x, y)
        mgr.save(s, tr)
    assert mgr.complete_steps() == [3, 4]
    assert not [f for f in os.listdir(mgr.directory)
                if f.startswith(".tmp")]


def test_checkpoint_write_retry_recovers_transient_fault(tmp_path):
    x, y = _data()
    tr = _trainer()
    mgr = SPMDCheckpointManager(
        str(tmp_path), max_to_keep=2,
        retry=RetryPolicy(max_attempts=3, base_delay_ms=1))
    tr.step(x, y)
    with _tel_scope() as tel:
        faults.configure("checkpoint.write:fail:1")
        mgr.save(1, tr)             # first attempt dies, retry lands it
        assert mgr.latest_step() == 1
        assert tel.snapshot()["counters"]["resilience.retry"] == 1


# ---------------------------------------------------------------- StepGuard
def test_step_guard_verdicts():
    g = StepGuard(max_consecutive=3)
    assert g.observe(1.0) == "ok"
    assert g.observe(float("nan")) == "skip"
    assert g.observe(float("inf")) == "skip"
    assert g.observe(float("nan")) == "rollback"
    g.reset()
    assert g.observe(0.5) == "ok"
    assert g.bad_streak == 0
    assert g.total_bad == 3
    # finite loss but non-finite grad norm is also a bad step
    assert g.observe(1.0, grad_norm=float("nan")) == "skip"


def test_step_guard_drives_loss_scaler():
    from mxnet_tpu.contrib.amp.loss_scaler import LossScaler
    scaler = LossScaler(init_scale=1024.0, scale_factor=2.0)
    g = StepGuard(max_consecutive=5, scaler=scaler)
    with _tel_scope() as tel:
        g.observe(float("nan"))
        assert scaler.loss_scale == 512.0
        c = tel.snapshot()["counters"]
        assert c["amp.overflow"] == 1
        assert c["resilience.nan_steps"] == 1
        assert tel.snapshot()["gauges"]["amp.loss_scale"] == 512.0
    g.observe(1.0)
    assert scaler.loss_scale == 512.0


# ----------------------------------------------------------- in-jit guard
def test_nan_guard_skips_poisoned_update():
    x, y = _data()
    tr = _trainer(opt="sgd", nan_guard=True)
    tr.step(x, y)
    before = {k: np.asarray(v) for k, v in tr._state[0].items()}
    loss = tr.step(np.full_like(x, np.nan), y)
    assert not np.isfinite(float(loss.asnumpy()))
    for k, v in before.items():
        np.testing.assert_array_equal(v, np.asarray(tr._state[0][k]))
    # and a clean step afterwards still trains
    loss2 = float(tr.step(x, y).asnumpy())
    assert np.isfinite(loss2)


# ----------------------------------------------------------------- resume
def test_resilient_trainer_resumes_bitwise(tmp_path):
    x, y = _data()
    rt = ResilientTrainer(_trainer(opt="sgd"), str(tmp_path), save_every=2)
    assert rt.resumed_from is None
    for _ in range(4):
        rt.step(x, y)
    rt.flush()                      # judge the last step -> cadence save
    assert rt.manager.latest_step() == 4
    # two independent "restarted processes" resume from the same
    # checkpoint (different init seeds prove restore overwrites them);
    # save_every=100 keeps the probes from writing new checkpoints
    rt1 = ResilientTrainer(_trainer(seed=5, opt="sgd"), str(tmp_path),
                           save_every=100)
    assert rt1.resumed_from == 4 and rt1.trainer._t == 4
    cont = [float(rt1.step(x, y).asnumpy()) for _ in range(3)]
    rt2 = ResilientTrainer(_trainer(seed=9, opt="sgd"), str(tmp_path),
                           save_every=100)
    assert rt2.resumed_from == 4 and rt2.trainer._t == 4
    replay = [float(rt2.step(x, y).asnumpy()) for _ in range(3)]
    assert replay == cont           # bitwise-identical step + RNG state


def test_resilient_trainer_survives_checkpoint_failures(tmp_path):
    x, y = _data()
    with _tel_scope() as tel:
        faults.configure("checkpoint.write:fail:1")
        rt = ResilientTrainer(_trainer(opt="sgd"), str(tmp_path),
                              save_every=1)
        rt.step(x, y)
        rt.step(x, y)               # judges t=1: its save dies -> absorbed
        rt.flush()                  # judges t=2: its save lands
        assert rt.checkpoint_failures == 1
        assert rt.manager.latest_step() == 2
        assert tel.snapshot()["counters"]["resilience.checkpoint_failed"] == 1


def test_resilient_trainer_rolls_back_after_nan_streak(tmp_path):
    x, y = _data()
    nan_x = np.full_like(x, np.nan)
    rt = ResilientTrainer(_trainer(opt="sgd", nan_guard=True),
                          str(tmp_path), save_every=2,
                          guard=StepGuard(max_consecutive=3))
    for _ in range(2):
        rt.step(x, y)
    rt.flush()
    assert rt.manager.latest_step() == 2
    with _tel_scope() as tel:
        for _ in range(3):
            rt.step(nan_x, y)
        rt.flush()                  # judge the 3rd bad step -> rollback
        assert rt.rollbacks == 1
        assert rt.trainer._t == 2   # rewound to the checkpoint
        assert rt.guard.bad_streak == 0
        c = tel.snapshot()["counters"]
        assert c["resilience.rollbacks"] == 1
        assert c["resilience.nan_steps"] == 3


def test_resilient_trainer_rollback_without_checkpoint_raises(tmp_path):
    x, y = _data()
    nan_x = np.full_like(x, np.nan)
    rt = ResilientTrainer(_trainer(opt="sgd", nan_guard=True),
                          str(tmp_path), save_every=100,
                          guard=StepGuard(max_consecutive=2))
    rt.step(nan_x, y)
    rt.step(nan_x, y)
    with pytest.raises(RuntimeError):
        rt.flush()                  # 2nd bad verdict -> rollback, no ckpt


# --------------------------------------------------------------------- io
class _BoomIter(mx.io.DataIter):
    """Raises mid-epoch on the producer thread."""

    def __init__(self):
        super().__init__(batch_size=2)
        self.provide_data = [mx.io.DataDesc("data", (2, 3))]
        self.provide_label = [mx.io.DataDesc("label", (2,))]
        self._n = 0

    def reset(self):
        self._n = 0

    def next(self):
        self._n += 1
        if self._n > 2:
            raise ValueError("decode exploded")
        z = np.zeros((2, 3), "float32")
        return mx.io.DataBatch(data=[mx.nd.array(z)],
                               label=[mx.nd.array(z[:, 0])], pad=0)


def test_prefetching_iter_propagates_worker_exception():
    with _tel_scope() as tel:
        it = mx.io.PrefetchingIter(_BoomIter())
        assert it.next() is not None
        assert it.next() is not None
        with pytest.raises(ValueError, match="decode exploded"):
            it.next()               # was: hang forever on data_ready
        assert tel.snapshot()["counters"]["io.worker_error"] == 1


def test_prefetching_iter_propagates_injected_fault():
    faults.configure("io.prefetch:fail:1")
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(np.ones((8, 3), "float32"),
                          np.zeros(8, "float32"), batch_size=4))
    with pytest.raises(InjectedFault):
        it.next()


def test_device_prefetch_iter_counts_worker_error():
    with _tel_scope() as tel:
        it = mx.io.DevicePrefetchIter(_BoomIter(), stage_fn=lambda b: b)
        assert next(it) is not None
        assert next(it) is not None
        with pytest.raises(ValueError, match="decode exploded"):
            next(it)
        assert tel.snapshot()["counters"]["io.worker_error"] == 1


# ---------------------------------------------------------------- serving
def _runtime():
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    return mx.serving.ModelRuntime(net, item_shapes=(8,), max_batch=4)


def test_batcher_circuit_breaker_sheds_then_recovers():
    from mxnet_tpu.serving import Batcher, RequestRejected
    b = Batcher(_runtime(), max_latency_ms=1.0, breaker_threshold=2,
                breaker_cooldown_ms=150.0)
    req = np.zeros(8, "float32")
    with _tel_scope() as tel:
        faults.configure("serving.batch:fail:2")
        for _ in range(2):          # two consecutive failed batches
            with pytest.raises(InjectedFault):
                b.infer(req)
        assert not b.healthy
        with pytest.raises(RequestRejected) as exc:
            b.submit(req)           # breaker open: load shed, no queueing
        assert exc.value.reason == "unhealthy"
        c = tel.snapshot()["counters"]
        assert c["serving.breaker_open"] == 1
        assert c["serving.batch_failures"] == 2
        time.sleep(0.2)             # cool-down expires -> half-open
        assert b.healthy
        out = b.infer(req)          # clean probe closes the breaker
        assert out.shape == (4,)
        assert b.healthy
    b.close(drain=False)


def test_batcher_counts_worker_restarts():
    import threading
    from mxnet_tpu.serving import Batcher
    b = Batcher(_runtime(), max_latency_ms=1.0)
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    b._worker = dead                # simulate an unexpected worker death
    with _tel_scope() as tel:
        out = b.submit(np.zeros(8, "float32")).result(timeout=30)
        assert out.shape == (4,)
        assert b.worker_restarts == 1
        assert tel.snapshot()["counters"]["serving.worker_restart"] == 1
    b.close(drain=False)


def test_registry_healthy_probe():
    from mxnet_tpu.serving import ModelRegistry
    reg = ModelRegistry()
    assert not reg.healthy()        # empty registry is not ready
    reg.register("m", _runtime(), max_latency_ms=1.0)
    assert reg.healthy("m")
    assert reg.healthy()
    assert not reg.healthy("absent")
    reg.get("m")._breaker_open_until = time.perf_counter() + 60.0
    assert not reg.healthy("m")
    assert not reg.healthy()
    reg.close(drain=False)


# ---------------------------------------------------------------- kvstore
def test_kvstore_push_retry_recovers_injected_fault():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((4, 4)))
    kv.set_retry_policy(RetryPolicy(max_attempts=3, base_delay_ms=1))
    with _tel_scope() as tel:
        faults.configure("kvstore.push:fail:1")
        kv.push("w", mx.nd.ones((4, 4)))
        assert tel.snapshot()["counters"]["resilience.retry"] == 1
    out = mx.nd.zeros((4, 4))
    kv.pull("w", out=out)
    # default updater-less push ASSIGNS the reduced value into the store
    np.testing.assert_allclose(out.asnumpy(), np.ones((4, 4)))


def test_kvstore_without_retry_surfaces_fault():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((2,)))
    faults.configure("kvstore.pull:fail:1")
    with pytest.raises(InjectedFault):
        kv.pull("w", out=mx.nd.zeros((2,)))


# ----------------------------------------------------------- gluon trainer
def test_gluon_trainer_states_write_is_atomic(tmp_path):
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.1})
    fname = str(tmp_path / "states")
    trainer.save_states(fname)
    good = open(fname, "rb").read()
    faults.configure("checkpoint.write:fail:1")
    with pytest.raises(InjectedFault):
        trainer.save_states(fname)
    # the committed file is untouched by the crashed write, no temp litter
    assert open(fname, "rb").read() == good
    assert [p for p in os.listdir(tmp_path)] == ["states"]
    # with a retry policy the same transient fault is absorbed
    trainer.retry_policy = RetryPolicy(max_attempts=2, base_delay_ms=1)
    faults.configure("checkpoint.write:fail:1")
    trainer.save_states(fname)
    trainer.load_states(fname)


# ------------------------------------------------------------------ random
def test_random_state_roundtrip_is_bitwise():
    mx.random.seed(1234)
    [mx.random.next_key() for _ in range(3)]    # advance into the pool
    snap = mx.random.get_state()
    a = [np.asarray(mx.random.next_key()) for _ in range(130)]  # spans pools
    mx.random.set_state(snap)
    b = [np.asarray(mx.random.next_key()) for _ in range(130)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
