"""Multi-host Module worker: multi-device context WITHIN each process ×
``dist_sync`` kvstore ACROSS processes (VERDICT r2 missing #7 — the
reference's executor_group device slicing + kvstore_dist roles composed).

Each process runs Module.fit over a 2-device local dp mesh; gradients sum
across processes through the dist kvstore; weights must remain identical
everywhere and the model must learn.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]))

import numpy as np
import mxnet_tpu as mx


def main():
    assert len(jax.local_devices()) == 2, jax.local_devices()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    mx.random.seed(11)                       # same init on every worker
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(data, name="fc1", num_hidden=8),
                act_type="relu"),
            name="fc2", num_hidden=2),
        name="softmax")
    centers = np.asarray([[2.0] * 4, [-2.0] * 4], dtype="float32")
    rng = np.random.RandomState(500 + rank)  # a different shard per worker
    y = rng.randint(0, 2, 64).astype("float32")
    x = centers[y.astype(int)] + rng.randn(64, 4).astype("float32") * 0.3
    it = mx.io.NDArrayIter(x, y, batch_size=16)

    # TWO local devices per process: the batch shards over the local dp
    # mesh, the kvstore sums over processes
    mod = mx.mod.Module(net, context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(it, num_epoch=3, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    w = mod.get_params()[0]["fc1_weight"].asnumpy()
    from jax.experimental import multihost_utils
    allw = np.asarray(multihost_utils.process_allgather(w))
    for r in range(nw):
        assert np.allclose(allw[r], w, atol=1e-5), \
            f"rank {rank}: weights diverged from rank {r}"
    acc = mod.score(mx.io.NDArrayIter(x, y, batch_size=16), "acc")[0][1]
    assert acc > 0.9, acc
    kv.barrier()
    print(f"MULTIHOST_MODULE_OK rank={rank} acc={acc:.3f} "
          f"local_devices=2 workers={nw}")


if __name__ == "__main__":
    main()
