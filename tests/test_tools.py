"""Ops-layer tooling: parse_log, flakiness_checker, bandwidth
(reference ``tools/`` — SURVEY.md §2 layer 12 / §6 benchmark-harness row)."""
import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
TOOLS = os.path.join(REPO, "tools")


def test_parse_log_markdown_table(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Train-accuracy=0.5\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.45\n"
        "INFO:root:Epoch[0] Time cost=12.5\n"
        "INFO:root:Epoch[1] Train-accuracy=0.75\n"
        "INFO:root:Epoch[1] Time cost=11.0\n")
    data = parse_log.parse(log.read_text().splitlines(), ["accuracy"])
    table = parse_log.render(data, ["accuracy"])
    assert "| epoch |" in table and "0.750000" in table and "12.5" in table
    assert "0.450000" in table


def test_flakiness_checker_runs_target(tmp_path):
    test_file = tmp_path / "test_tiny_flake.py"
    test_file.write_text(
        "def test_always_passes():\n    assert 1 + 1 == 2\n")
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "flakiness_checker.py"),
         str(test_file) + "::test_always_passes", "-n", "2"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0/2 trials failed" in out.stdout


def test_flakiness_checker_uses_tier1_invocation():
    sys.path.insert(0, TOOLS)
    try:
        import flakiness_checker as fc
    finally:
        sys.path.pop(0)
    # trials run the tier-1 pytest flags (not the legacy nose runner)
    cmd = fc.tier1_command("tests/")
    assert "pytest" in " ".join(cmd)
    assert "not slow" in cmd
    assert "--continue-on-collection-errors" in cmd
    cmd_all = fc.tier1_command("tests/", include_slow=True)
    assert "not slow" not in cmd_all
    assert "--continue-on-collection-errors" in cmd_all
    # the interpreter's own "-m pytest" must survive the filter strip
    assert cmd_all[1:3] == ["-m", "pytest"]
    # an explicitly named test is never deselected by the marker filter
    assert "not slow" not in fc.tier1_command("tests/t.py::test_x")
    # no target = the whole tier-1 suite; dotted reference spelling maps
    assert fc.parse_args([]).test == "tests/"
    assert fc.parse_args(["test_operator.test_abs"]).test == \
        "test_operator.py::test_abs"


def test_bandwidth_measure_reduces_correctly():
    sys.path.insert(0, os.path.join(TOOLS, "bandwidth"))
    try:
        import measure
    finally:
        sys.path.pop(0)
    res = measure.run(network="squeezenet1.0", kv_store="device",
                      num_batches=2, num_classes=10, log=False)
    assert len(res) == 2
    assert all(bw > 0 and np.isfinite(t) for _b, t, bw in res)


def test_word_lm_example_learns():
    out = subprocess.run(
        [sys.executable, "example/rnn/word_lm.py", "--epochs", "3",
         "--sentences", "200"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "final train perplexity" in out.stderr or \
        "final train perplexity" in out.stdout
