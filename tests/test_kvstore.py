"""KVStore contract tests.

Mirrors reference ``tests/python/unittest/test_kvstore.py`` — init/push/pull
single and list keys, aggregation over per-device values, custom updaters,
str keys, and the type factory.
"""
import numpy as np
import pytest

import mxnet_tpu as mx

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def check_diff_to_scalar(arr, x):
    assert np.allclose(arr.asnumpy(), x), (arr.asnumpy(), x)


def test_single_kv_pair():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)
    kv.push(3, mx.nd.ones(SHAPE) * 4)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 4)


def test_list_kv_pair():
    kv = mx.kv.create("device")
    kv.init(KEYS, [mx.nd.ones(SHAPE) * k for k in KEYS])
    val = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=val)
    for v, k in zip(val, KEYS):
        check_diff_to_scalar(v, k)


def test_push_copies_value():
    """The store must not alias the caller's gradient buffer."""
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.zeros(SHAPE))
    g = mx.nd.ones(SHAPE)
    kv.push(3, g)
    g *= 5
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    check_diff_to_scalar(out, 1)


def test_row_sparse_pull_gathers_rows():
    kv = mx.kv.create("local")
    w = mx.nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    kv.init("emb", w)
    out = mx.nd.empty((2, 3))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array([1, 3]))
    assert np.allclose(out.asnumpy(), w.asnumpy()[[1, 3]])


def test_aggregator():
    """Push from multiple 'devices' sums (CommDevice::Reduce semantics)."""
    kv = mx.kv.create("device")
    kv.init(3, mx.nd.ones(SHAPE))
    num_devs = 4
    vals = [mx.nd.ones(SHAPE) for _ in range(num_devs)]
    kv.push(3, vals)
    outs = [mx.nd.empty(SHAPE) for _ in range(num_devs)]
    kv.pull(3, out=outs)
    for o in outs:
        check_diff_to_scalar(o, num_devs)


def test_updater():
    """set_updater runs at push time (reference test_updater)."""
    def updater(key, recv, local):
        local += recv

    kv = mx.kv.create("local")
    kv.set_updater(updater)
    kv.init(3, mx.nd.ones(SHAPE))
    kv.push(3, mx.nd.ones(SHAPE) * 4)
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 5)
    # repeated push accumulates through the updater
    kv.push(3, [mx.nd.ones(SHAPE)] * 4)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 9)


def test_str_keys():
    kv = mx.kv.create("local")
    kv.init("w0", mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull("w0", out=val)
    check_diff_to_scalar(val, 1)
    with pytest.raises(TypeError):
        kv.init(3, mx.nd.ones(SHAPE))


def test_get_type_and_factory():
    for t in ("local", "device", "nccl", "tpu"):
        assert mx.kv.create(t).type == t
    with pytest.raises(ValueError):
        mx.kv.create("nonsense")
    assert mx.kv.create("local").rank == 0
    assert mx.kv.create("local").num_workers == 1


def test_set_optimizer_states_roundtrip(tmp_path):
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(0, mx.nd.ones(SHAPE))
    fname = str(tmp_path / "states")
    kv.save_optimizer_states(fname, dump_optimizer=True)
    kv2 = mx.kv.create("local")
    kv2.init(0, mx.nd.ones(SHAPE))
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(fname)
    assert 0 in kv2._updater.states


def test_trainer_with_kvstore_multidevice():
    """Trainer over split_and_load replicas reduces grads through the store."""
    net = mx.gluon.nn.Dense(2, in_units=3)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1}, kvstore="device")
    x = mx.nd.ones((4, 3))
    with mx.autograd.record():
        y = net(x)
        loss = y.sum()
    loss.backward()
    trainer.step(4)
    assert net.weight.data().shape == (2, 3)


def test_gradient_compression_2bit_with_error_feedback():
    # Mirrors the reference's compressed dist_sync checks
    # (tests/nightly/dist_sync_kvstore.py): thresholding to {-t, 0, +t} and
    # residual carry-over across pushes.
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 2.0})
    kv.init(0, mx.nd.zeros((4,)))
    out = [mx.nd.zeros((4,))]

    g = mx.nd.array(np.array([0.7, -3.0, 2.5, 0.0], np.float32))
    kv.push(0, [g])
    kv.pull(0, out)
    # quantized: 0.7->0 (below t), -3.0->-2, 2.5->+2, 0->0
    np.testing.assert_allclose(out[0].asnumpy(), [0.0, -2.0, 2.0, 0.0])

    # residuals now [0.7, -1.0, 0.5, 0]; same grad again:
    # acc = [1.4, -4.0, 3.0, 0] -> q = [0, -2, 2, 0] (store replaces, no
    # updater — reference KVStoreLocal CopyFromTo semantics)
    kv.push(0, [g])
    kv.pull(0, out)
    np.testing.assert_allclose(out[0].asnumpy(), [0.0, -2.0, 2.0, 0.0])

    # third push: acc = [2.1, -5.0, 3.5, 0] -> q = [2, -2, 2, 0] — the
    # residual finally pushes the small 0.7 gradients over the threshold
    kv.push(0, [g])
    kv.pull(0, out)
    np.testing.assert_allclose(out[0].asnumpy(), [2.0, -2.0, 2.0, 0.0])


def test_gradient_compression_quantizes_after_local_reduce():
    # reference worker-side order (kvstore_dist.h): dense local reduce
    # first, THEN one quantization of the merged gradient per key
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kv.init(7, mx.nd.zeros((2,)))
    g1 = mx.nd.array(np.array([0.6, 1.2], np.float32))
    g2 = mx.nd.array(np.array([0.6, -0.4], np.float32))
    out = [mx.nd.zeros((2,))]
    kv.push(7, [g1, g2])           # merged [1.2, 0.8] -> q [1, 0], r [.2, .8]
    kv.pull(7, out)
    np.testing.assert_allclose(out[0].asnumpy(), [1.0, 0.0])
    kv.push(7, [g1, g2])           # acc [1.4, 1.6] -> q [1, 1]
    kv.pull(7, out)
    np.testing.assert_allclose(out[0].asnumpy(), [1.0, 1.0])
    assert set(kv._residuals) == {7}   # one residual per key, not per device


def test_gradient_compression_rejects_bad_params():
    kv = mx.kv.create("local")
    with pytest.raises(ValueError):
        kv.set_gradient_compression({"type": "1bit"})
    with pytest.raises(ValueError):
        kv.set_gradient_compression({"type": "2bit", "threshold": -1})


# --- r4 depth: sparse aggregation, invalid pull, init semantics
# (reference test_kvstore.py remainder)

def test_sparse_aggregator_row_sparse_push():
    """Multiple row_sparse pushes to one key aggregate by row (reference
    test_sparse_aggregator)."""
    from mxnet_tpu.ndarray import sparse
    kv = mx.kv.create("local")
    shape = (6, 3)
    kv.init("a", sparse.zeros("row_sparse", shape))
    v1 = sparse.row_sparse_array(
        (np.ones((2, 3), "float32"), np.array([0, 2])), shape=shape)
    v2 = sparse.row_sparse_array(
        (2 * np.ones((2, 3), "float32"), np.array([2, 5])), shape=shape)
    kv.push("a", [v1, v2])
    out = mx.nd.zeros(shape)
    kv.pull("a", out=out, ignore_sparse=False)
    want = v1.asnumpy() + v2.asnumpy()
    np.testing.assert_allclose(out.asnumpy(), want)
    # row_sparse_pull of a subset
    rows = mx.nd.array([2])
    sub = sparse.zeros("row_sparse", shape)
    kv.row_sparse_pull("a", out=sub, row_ids=rows)
    np.testing.assert_allclose(sub.asnumpy()[2], want[2])


def test_invalid_pull_uninitialized_key():
    kv = mx.kv.create("local")
    out = mx.nd.zeros((2, 2))
    with pytest.raises(Exception):
        kv.pull("never_initialized", out=out)


def test_double_init_keeps_first_value():
    """reference init semantics: re-initializing an existing key is
    ignored (the first value wins)."""
    kv = mx.kv.create("local")
    kv.init("k", mx.nd.ones((2, 2)))
    try:
        kv.init("k", mx.nd.full((2, 2), 7.0))
    except Exception:
        pass                               # raising loudly is also fine
    out = mx.nd.zeros((2, 2))
    kv.pull("k", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 2)))


def test_pull_into_multiple_outs():
    kv = mx.kv.create("local")
    kv.init("m", mx.nd.full((2,), 3.0))
    outs = [mx.nd.zeros((2,)), mx.nd.zeros((2,))]
    kv.pull("m", out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), [3, 3])
