"""Tests for the remaining reference operators (extra_ops.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_legacy_aliases_exist():
    for name in ("BatchNorm_v1", "Convolution_v1", "Pooling_v1",
                 "_split_v2", "_rnn_param_concat"):
        from mxnet_tpu.ops import registry
        assert registry.get(name) is not None, name


def test_upsampling_nearest_and_bilinear():
    x = mx.nd.array(np.arange(4, dtype="float32").reshape(1, 1, 2, 2))
    up = mx.nd.UpSampling(x, scale=2, sample_type="nearest")
    assert up.shape == (1, 1, 4, 4)
    np.testing.assert_array_equal(up.asnumpy()[0, 0],
                                  [[0, 0, 1, 1], [0, 0, 1, 1],
                                   [2, 2, 3, 3], [2, 2, 3, 3]])
    up2 = mx.nd.UpSampling(x, scale=2, sample_type="bilinear", num_filter=1)
    assert up2.shape == (1, 1, 4, 4)


def test_spatial_transformer_identity():
    """Identity affine θ = [1,0,0,0,1,0] reproduces the input."""
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(2, 3, 8, 8).astype("float32"))
    theta = mx.nd.array(np.tile([1, 0, 0, 0, 1, 0], (2, 1)).astype("float32"))
    out = mx.nd.SpatialTransformer(x, theta, target_shape=(8, 8),
                                   transform_type="affine",
                                   sampler_type="bilinear")
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_bilinear_sampler_shift():
    """Grid shifted fully off-image samples zeros (border padding off)."""
    x = mx.nd.ones((1, 1, 4, 4))
    grid = mx.nd.array(np.full((1, 2, 4, 4), 5.0, dtype="float32"))
    out = mx.nd.BilinearSampler(x, grid)
    assert float(out.asnumpy().sum()) == 0.0


def test_grid_generator_warp():
    flow = mx.nd.zeros((1, 2, 4, 4))
    grid = mx.nd.GridGenerator(flow, transform_type="warp")
    g = grid.asnumpy()
    assert g[0, 0, 0, 0] == -1 and g[0, 0, -1, -1] == 1


def test_make_loss_gradient():
    x = mx.nd.array(np.random.rand(4, 3).astype("float32"))
    x.attach_grad()
    with mx.autograd.record():
        loss = mx.nd.MakeLoss(x * 2, grad_scale=3.0)
    loss.backward()
    # d/dx (2x) with loss-grad 3 → 6
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((4, 3), 6.0),
                               rtol=1e-5)


def test_softmax_cross_entropy():
    data = mx.nd.array([[10.0, 0.0], [0.0, 10.0]])
    label = mx.nd.array([0.0, 1.0])
    out = mx.nd.softmax_cross_entropy(data, label)
    assert float(out.asscalar()) < 0.01


def test_index_copy_and_index_array():
    old = mx.nd.zeros((4, 2))
    new = mx.nd.ones((2, 2)) * 7
    out = mx.nd.contrib.index_copy(old, mx.nd.array([1, 3], dtype="int32"),
                                   new)
    assert out.asnumpy()[1, 0] == 7 and out.asnumpy()[0, 0] == 0
    ia = mx.nd.contrib.index_array(mx.nd.zeros((2, 3)))
    assert ia.shape == (2, 3, 2)
    assert ia.asnumpy()[1, 2].tolist() == [1, 2]


def test_arange_like():
    x = mx.nd.zeros((2, 3))
    out = mx.nd.contrib.arange_like(x)
    np.testing.assert_array_equal(out.asnumpy().ravel(), np.arange(6))
    out2 = mx.nd.contrib.arange_like(x, axis=1, start=5)
    np.testing.assert_array_equal(out2.asnumpy(), [5, 6, 7])


def test_multi_sgd_update():
    w1, w2 = mx.nd.ones((3,)), mx.nd.ones((2, 2))
    g1, g2 = mx.nd.ones((3,)), mx.nd.ones((2, 2))
    out = mx.nd.multi_sgd_update(w1, g1, w2, g2, lrs=(0.1, 0.5),
                                 wds=(0.0, 0.0), num_weights=2)
    np.testing.assert_allclose(out[0].asnumpy(), np.full(3, 0.9), rtol=1e-6)
    np.testing.assert_allclose(out[1].asnumpy(), np.full((2, 2), 0.5),
                               rtol=1e-6)
    # in-place writeback into the weight NDArrays
    np.testing.assert_allclose(w1.asnumpy(), np.full(3, 0.9), rtol=1e-6)


def test_quantized_fully_connected():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype("float32")
    w = rng.randn(16, 8).astype("float32")
    b = rng.randn(16).astype("float32")
    qx, xmn, xmx = mx.nd.contrib.quantize_v2(mx.nd.array(x), out_type="int8")
    qw, wmn, wmx = mx.nd.contrib.quantize_v2(mx.nd.array(w), out_type="int8")
    qb, bmn, bmx = mx.nd.contrib.quantize_v2(mx.nd.array(b), out_type="int8")
    qo, omn, omx = mx.nd.contrib.quantized_fully_connected(
        qx, qw, qb, xmn, xmx, wmn, wmx, bmn, bmx, num_hidden=16)
    out = mx.nd.contrib.dequantize(qo, omn, omx).asnumpy()
    ref = x @ w.T + b
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6) < 0.05


def test_sparse_retain_op():
    data = mx.nd.array(np.arange(8, dtype="float32").reshape(4, 2))
    out = mx.nd.sparse_retain(data, mx.nd.array([0, 2]))
    assert out.asnumpy()[1].sum() == 0
    np.testing.assert_array_equal(out.asnumpy()[2], [4, 5])


def test_getnnz_and_edge_id():
    m = mx.nd.array([[0, 2, 0], [1, 0, 3]])
    assert int(mx.nd.contrib.getnnz(m).asscalar()) == 3
    np.testing.assert_array_equal(
        mx.nd.contrib.getnnz(m, axis=0).asnumpy(), [1, 1, 1])
    eid = mx.nd.contrib.edge_id(m, mx.nd.array([0, 1, 0]),
                                mx.nd.array([1, 2, 0]))
    np.testing.assert_array_equal(eid.asnumpy(), [2, 3, -1])


def test_identity_attach_kl_sparse_reg():
    x = mx.nd.array(np.random.RandomState(0).randn(8, 4).astype("float32"))
    x.attach_grad()
    with mx.autograd.record():
        out = mx.nd.IdentityAttachKLSparseReg(x, sparseness_target=0.2,
                                              penalty=0.01)
        np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())
        out.sum().backward()
    g = x.grad.asnumpy()
    assert np.isfinite(g).all()
    assert np.abs(g - 1.0).max() > 1e-6  # penalty actually contributed


def test_col2im_is_transpose_of_im2col():
    x = mx.nd.array(np.arange(32, dtype="float32").reshape(1, 2, 4, 4))
    cols = mx.nd.im2col(x, kernel=(2, 2), stride=(2, 2))
    back = mx.nd.col2im(cols, output_size=(4, 4), kernel=(2, 2),
                        stride=(2, 2))
    # non-overlapping patches: exact reconstruction
    np.testing.assert_array_equal(back.asnumpy(), x.asnumpy())
    # overlapping: each pixel accumulated once per covering patch
    cols2 = mx.nd.im2col(mx.nd.ones((1, 1, 3, 3)), kernel=(2, 2),
                         stride=(1, 1))
    acc = mx.nd.col2im(cols2, output_size=(3, 3), kernel=(2, 2),
                       stride=(1, 1)).asnumpy()[0, 0]
    np.testing.assert_array_equal(acc, [[1, 2, 1], [2, 4, 2], [1, 2, 1]])


def test_multi_sum_sq_and_reset_arrays():
    a = mx.nd.ones((2, 2)) * 2
    b = mx.nd.ones((3,))
    out = mx.nd.multi_sum_sq(a, b, num_arrays=2).asnumpy()
    np.testing.assert_allclose(out, [16.0, 3.0])
    mx.nd.contrib.reset_arrays(a, b, num_arrays=2)
    assert a.asnumpy().sum() == 0 and b.asnumpy().sum() == 0


def test_bitwise_and_digamma():
    np.testing.assert_array_equal(
        mx.nd.bitwise_and(mx.nd.array([6, 5]), mx.nd.array([3, 4]))
        .asnumpy(), [2, 4])
    np.testing.assert_array_equal(
        mx.nd.bitwise_xor(mx.nd.array([6]), mx.nd.array([3])).asnumpy(), [5])
    assert abs(float(mx.nd.digamma(mx.nd.array([1.0])).asscalar())
               + 0.5772157) < 1e-5
