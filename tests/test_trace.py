"""PR 15 observability: request/step trace contexts and the merged
multi-host chrome trace, the always-on flight recorder, histogram metrics,
and the live /metrics + /healthz + /trace HTTP endpoint.

The tentpole contract test is the decode request lane: one request
submitted into a continuous batch must carry ONE trace id from
``submit()`` through queue wait, prefill, every step it rode, and its
eviction — across the client thread and the scheduler worker — and the
two-simulated-host drill must merge both hosts' streams into one timeline
with per-host lanes.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, telemetry
from mxnet_tpu.analysis import sanitizer
from mxnet_tpu.serving.decode import DecodeRuntime, DecodeScheduler, \
    get_decode_model
from mxnet_tpu.telemetry import bus, exporters, flight, http, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "trace_host_worker.py")
VOCAB = 61


@pytest.fixture(autouse=True)
def _clean_stack():
    def _reset():
        telemetry.disable()
        telemetry.reset()
        trace.disarm()
        http.stop_server()
        flight.configure(capacity=flight.DEFAULT_CAPACITY, on=True)
        flight.reset()
    _reset()
    yield
    _reset()


def _spans(name=None):
    evs = [e for e in bus.events() if e[0] == "X"]
    return [e for e in evs if e[1] == name] if name else evs


def _attrs(ev):
    return ev[6] or {}


# ------------------------------------------------------------- histograms
class TestHistograms:
    def test_observe_counts_and_bounds(self):
        telemetry.enable()
        for v in (0.5, 3.0, 3.0, 40.0):
            telemetry.observe("t.lat_ms", v)
        h = telemetry.snapshot()["histograms"]["t.lat_ms"]
        assert h["count"] == 4
        assert h["sum"] == pytest.approx(46.5)
        assert h["min"] == 0.5 and h["max"] == 40.0
        assert h["min"] <= h["p50"] <= h["p90"] <= h["p99"] <= h["max"]

    def test_cumulative_buckets_end_at_inf(self):
        telemetry.enable()
        for v in range(1, 9):
            telemetry.observe("t.h", float(v))
        rows = telemetry.histograms()["t.h"]["buckets"]
        assert rows[-1] == ("+Inf", 8)
        cums = [c for _le, c in rows]
        assert cums == sorted(cums), "bucket counts must be cumulative"

    def test_quantile_interpolates_inside_bucket(self):
        telemetry.enable()
        for _ in range(10):
            telemetry.observe("t.q", 3.0)       # lands in the (2, 4] bucket
        q = telemetry.histogram_quantile("t.q", 0.5)
        assert 2.0 <= q <= 4.0
        assert telemetry.histogram_quantile("t.missing", 0.5) is None

    def test_prometheus_bucket_series(self):
        telemetry.enable()
        telemetry.observe("decode.ttft_ms", 12.5)
        text = exporters.dump_metrics()
        assert 'mxnet_decode_ttft_ms_bucket{le="16.0"} 1' in text
        assert 'mxnet_decode_ttft_ms_bucket{le="+Inf"} 1' in text
        assert "mxnet_decode_ttft_ms_sum 12.5" in text
        assert "mxnet_decode_ttft_ms_count 1" in text

    def test_disabled_is_noop(self):
        telemetry.observe("t.off", 1.0)
        assert telemetry.histograms() == {}


# ---------------------------------------------------------- trace contexts
class TestTraceContext:
    def test_nested_spans_chain_parent_ids(self):
        telemetry.enable()
        ctx = trace.start("t.root", who="test")
        with trace.use(ctx):
            with telemetry.span("t.outer"):
                with telemetry.span("t.inner"):
                    pass
        outer, inner = _spans("t.outer")[0], _spans("t.inner")[0]
        assert _attrs(outer)["trace_id"] == ctx.trace_id
        assert _attrs(inner)["trace_id"] == ctx.trace_id
        # root context: span_id == trace_id, so outer hangs off the root
        assert _attrs(outer)["parent_id"] == ctx.trace_id
        assert _attrs(inner)["parent_id"] == _attrs(outer)["span_id"]
        # the birth instant carries the root ids
        root = [e for e in bus.events() if e[0] == "I"
                and e[1] == "t.root"][0]
        assert _attrs(root)["span_id"] == ctx.trace_id

    def test_use_none_is_noop_and_stack_restores(self):
        telemetry.enable()
        with trace.use(None):
            assert trace.current() is None
        ctx = trace.start()
        with trace.use(ctx):
            assert trace.current().trace_id == ctx.trace_id
        assert trace.current() is None

    def test_record_span_on_explicit_lane(self):
        telemetry.enable()
        ctx = trace.start()
        t0 = time.perf_counter()
        telemetry.record_span("t.ride", t0, t0 + 0.001,
                              tid=ctx.trace_id, trace=ctx, hop=1)
        ev = _spans("t.ride")[0]
        assert ev[5] == ctx.trace_id, "tid must be the request lane"
        assert _attrs(ev)["parent_id"] == ctx.span_id
        assert _attrs(ev)["hop"] == 1

    def test_child_links_cross_thread_work(self):
        telemetry.enable()
        ctx = trace.start()
        link = trace.child(ctx)
        assert link[0] == ctx.trace_id and link[2] == ctx.span_id
        out = []

        def worker():
            t0 = time.perf_counter()
            telemetry.record_span("t.remote", t0, trace=link)
            out.append(True)

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        assert out
        ev = _spans("t.remote")[0]
        assert _attrs(ev)["span_id"] == link[1]
        assert _attrs(ev)["parent_id"] == ctx.span_id


# ------------------------------------------------------------ chrome merge
class TestChromeTrace:
    def test_flow_links_and_lane_metadata(self):
        telemetry.enable()
        ctx = trace.start("t.req")
        with trace.use(ctx):
            with telemetry.span("t.work"):
                pass
        doc = trace.chrome_trace()
        evs = doc["traceEvents"]
        assert any(e.get("ph") == "M" and e["name"] == "process_name"
                   for e in evs)
        starts = [e for e in evs if e.get("ph") == "s"]
        ends = [e for e in evs if e.get("ph") == "f"]
        assert starts and ends
        assert {e["id"] for e in starts} == {e["id"] for e in ends}

    def test_two_host_streams_merge_into_one_timeline(self, tmp_path):
        d = str(tmp_path)
        telemetry.enable()
        for host in (0, 1):
            trace.configure(d, host=host, host_count=2)
            ctx = trace.start(f"t.host{host}")
            with trace.use(ctx):
                with telemetry.span("t.step", host=host):
                    pass
            trace.disarm()
            telemetry.reset()      # the stream file, not the ring, is read
        doc = trace.chrome_trace(directory=d)
        evs = doc["traceEvents"]
        lanes = {e["pid"] for e in evs
                 if e.get("ph") == "X" and e["name"] == "t.step"}
        assert lanes == {0, 1}, "one process lane per simulated host"
        names = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert {"host 0", "host 1"} <= names

    def test_host_seed_prevents_id_collisions(self, tmp_path):
        telemetry.enable()
        trace.configure(str(tmp_path), host=0, host_count=2)
        a = bus.new_id()
        trace.configure(str(tmp_path), host=1, host_count=2)
        b = bus.new_id()
        assert (a >> 48) != (b >> 48)


# --------------------------------------------------------- flight recorder
class TestFlight:
    def test_ring_wraps_keeping_newest(self):
        flight.configure(capacity=16)
        for i in range(40):
            flight.record("f.ev", value=i)
        evs = flight.events()
        assert len(evs) == 16
        assert [e[3] for e in evs] == list(range(24, 40))

    def test_disabled_records_nothing(self):
        flight.configure(on=False)
        flight.record("f.off")
        assert flight.events() == []
        flight.configure(on=True)

    def test_dump_document(self, tmp_path):
        telemetry.enable()
        telemetry.count("t.counter", 3)
        telemetry.observe("t.lat_ms", 8.0)
        flight.record("f.step", detail="d", value=7)
        sp = telemetry.span("t.open")
        sp.__enter__()
        try:
            path = flight.dump("test-reason", path=str(tmp_path / "f.json"),
                               error=ValueError("boom"))
        finally:
            sp.__exit__(None, None, None)
        with open(path) as f:
            doc = json.load(f)
        assert doc["reason"] == "test-reason"
        assert "boom" in doc["error"]
        assert any(e["name"] == "f.step" and e["value"] == 7
                   for e in doc["events"])
        assert any(s["name"] == "t.open" for s in doc["active_spans"])
        assert doc["telemetry"]["counters"]["t.counter"] == 3
        assert "t.lat_ms" in doc["telemetry"]["histograms"]

    def test_postmortem_without_dir_is_silent(self, monkeypatch):
        monkeypatch.delenv("MXNET_FLIGHT_DIR", raising=False)
        flight.record("f.pre")
        assert flight.postmortem("no-dir") is None
        assert any(e[1] == "flight.postmortem" for e in flight.events())

    def test_sanitizer_violation_auto_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_FLIGHT_DIR", str(tmp_path))

        class FakeCache:
            def generation(self, slot_id):
                return 7

        cache = FakeCache()
        with sanitizer.scope("slots"):
            sanitizer.register_kv_slot(cache, 3, "test.site")
            flight.record("decode.step", value=1)
            # clean check: no dump
            sanitizer.check_kv_slot(cache, 3, generation=7)
            assert not os.listdir(str(tmp_path))
            with pytest.raises(sanitizer.StaleKVSlotError):
                sanitizer.check_kv_slot(cache, 3, generation=5)
        dumps = [f for f in os.listdir(str(tmp_path))
                 if f.startswith("flight-")]
        assert len(dumps) == 1, "violation must leave exactly one dump"
        with open(tmp_path / dumps[0]) as f:
            doc = json.load(f)
        assert doc["reason"] == "StaleKVSlotError"
        names = [e["name"] for e in doc["events"]]
        assert "decode.step" in names, "ring history precedes the fault"
        assert "sanitizer.violation" in names


# ------------------------------------------------------- decode request lane
@pytest.fixture(scope="module")
def runtime():
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1, 2), seq_buckets=(8, 16),
                       page_size=8)
    yield rt


def _lane_events(lane):
    return [e for e in bus.events() if e[5] == lane]


class TestDecodeRequestLane:
    def test_solo_request_one_trace_submit_to_eviction(self, runtime):
        telemetry.enable()
        sched = DecodeScheduler(runtime)
        try:
            fut = sched.submit([5, 9, 2], max_new_tokens=4)
            res = fut.result(timeout=120)
        finally:
            sched.close(drain=True, timeout=30.0)
        assert len(res.token_ids) >= 1
        roots = [e for e in bus.events()
                 if e[0] == "I" and e[1] == "decode.submit"]
        assert len(roots) == 1
        lane = _attrs(roots[0])["trace_id"]
        names = [e[1] for e in _lane_events(lane)]
        for hop in ("decode.queue_wait", "decode.prefill",
                    "decode.ride_step", "decode.evict"):
            assert hop in names, f"lane missing {hop}: {names}"
        assert names.count("decode.ride_step") >= 1
        # one trace id across every hop, each hop linked into the tree
        for ev in _lane_events(lane):
            assert _attrs(ev)["trace_id"] == lane
            assert "parent_id" in _attrs(ev) or "span_id" in _attrs(ev)
        evict = [e for e in _lane_events(lane) if e[1] == "decode.evict"][0]
        assert _attrs(evict)["parent_id"] == lane, \
            "eviction must link to the submit root"

    def test_continuous_batch_keeps_per_request_trace(self, runtime):
        telemetry.enable()
        sched = DecodeScheduler(runtime)
        try:
            first = sched.submit([3, 7, 1], max_new_tokens=24)
            # wait until the first request is actually riding steps, so the
            # second genuinely joins a running batch mid-flight
            deadline = time.perf_counter() + 60
            while not _spans("decode.ride_step") and \
                    time.perf_counter() < deadline:
                time.sleep(0.001)
            second = sched.submit([8, 4], max_new_tokens=4)
            r1, r2 = first.result(timeout=120), second.result(timeout=120)
        finally:
            sched.close(drain=True, timeout=30.0)
        assert len(r1.token_ids) >= 1 and len(r2.token_ids) >= 1
        roots = [e for e in bus.events()
                 if e[0] == "I" and e[1] == "decode.submit"]
        assert len(roots) == 2
        lanes = [_attrs(r)["trace_id"] for r in roots]
        assert lanes[0] != lanes[1]
        for lane in lanes:
            names = [e[1] for e in _lane_events(lane)]
            for hop in ("decode.queue_wait", "decode.prefill",
                        "decode.ride_step", "decode.evict"):
                assert hop in names, f"lane {lane:#x} missing {hop}"
            ids = {_attrs(e)["trace_id"] for e in _lane_events(lane)}
            assert ids == {lane}, "a lane must carry exactly one trace"
        # shared steps: some ride_step spans saw batch > 1 (a mid-flight
        # join), and the hop is billed to BOTH requests' lanes
        snap = telemetry.snapshot()
        assert snap["counters"].get("decode.joins", 0) >= 1, \
            "second request never joined the running batch"
        rides = [e for e in _spans("decode.ride_step")]
        assert any(_attrs(e).get("batch", 1) > 1 for e in rides), \
            "shared steps must bill batch>1 rides to both lanes"
        hist = snap["histograms"]
        assert hist["decode.ttft_ms"]["count"] == 2
        assert hist["decode.step_ms"]["count"] >= 1


# --------------------------------------------------------- io worker lanes
N_IMG, HW = 32, 48


@pytest.fixture(scope="module")
def rec_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tracerec") / "data.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    img = (rng.rand(HW, HW, 3) * 255).astype("uint8")
    for i in range(N_IMG):
        img[i % HW, :, :] = (i * 37) % 255
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write(recordio.pack_img(header, img, quality=85))
    rec.close()
    return path


class TestIOWorkerLanes:
    def test_worker_decode_spans_parent_to_consumer_batch(self, rec_path):
        telemetry.enable()
        it = mx.io.ImageRecordIter(path_imgrec=rec_path,
                                   data_shape=(3, 32, 32), batch_size=16,
                                   preprocess_processes=2)
        n = sum(1 for _ in it)
        it.close()
        assert n >= 2
        waits = _spans("io.proc_batch_wait")
        decodes = _spans("io.worker_decode")
        assert waits and decodes, "worker decode spans must cross the shm ring"
        wait_by_seq = {_attrs(e)["seq"]: e for e in waits}
        for ev in decodes:
            a = _attrs(ev)
            # the worker's span rides a per-worker process-style lane...
            assert ev[5] == 0xD0000 + a["worker"]
            # ...and parents to the consumer-side wait for the SAME batch
            parent = wait_by_seq[a["seq"]]
            assert a["parent_id"] == _attrs(parent)["span_id"]
            assert a["trace_id"] == _attrs(parent)["trace_id"]
            assert ev[4] > 0, "worker decode must have real duration"


# ------------------------------------------------------------ http endpoint
def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class _Probe:
    def __init__(self, healthy=True):
        self.healthy = healthy


class TestHttpEndpoint:
    def test_metrics_healthz_trace_routes(self):
        telemetry.enable()
        telemetry.count("t.reqs", 2)
        telemetry.observe("t.lat_ms", 5.0)
        port = http.start_server(0)
        assert http.server_port() == port

        code, body = _get(port, "/metrics")
        assert code == 200
        assert "mxnet_t_reqs 2" in body
        assert 'mxnet_t_lat_ms_bucket{le="+Inf"} 1' in body

        code, body = _get(port, "/healthz")
        assert code == 200 and json.loads(body)["ok"] is True

        code, body = _get(port, "/trace")
        assert code == 200
        assert "traceEvents" in json.loads(body)

        code, _body = _get(port, "/nope")
        assert code == 404

    def test_healthz_flips_with_probe(self):
        port = http.start_server(0)
        probe = _Probe(healthy=True)
        http.register_health("t:probe", probe)
        try:
            assert _get(port, "/healthz")[0] == 200
            probe.healthy = False
            code, body = _get(port, "/healthz")
            assert code == 503
            assert json.loads(body)["components"]["t:probe"] is False
        finally:
            http.unregister_health("t:probe")
        assert _get(port, "/healthz")[0] == 200

    def test_batcher_registers_and_unregisters(self):
        net = mx.gluon.nn.Dense(4)
        net.initialize()
        rt = mx.serving.ModelRuntime(net, item_shapes=(8,), max_batch=2)
        b = mx.serving.Batcher(rt, start=False)
        try:
            # batchers report *readiness* (route away), not liveness
            ok, report = http.readiness()
            assert report.get(f"batcher:{rt.name}") is True and ok
            _ok, live = http.health()
            assert f"batcher:{rt.name}" not in live
        finally:
            b.close(drain=False)
        _ok, report = http.readiness()
        assert f"batcher:{rt.name}" not in report

    def test_shutdown_ordering_is_bounded(self):
        telemetry.enable()
        telemetry.start_counter_sampler(["t.reqs"], interval_ms=10)
        port = http.start_server(0)
        assert _get(port, "/metrics")[0] == 200
        t0 = time.perf_counter()
        http.stop_server()
        telemetry.stop_counter_sampler()
        assert time.perf_counter() - t0 < 5.0
        assert http.server_port() is None
        assert not telemetry.sampler_running()


# ----------------------------------------------------- two-host trace drill
def _spawn(dirpath, host, extra=()):
    env = dict(os.environ, PYTHONPATH=REPO)
    for k in ("MXNET_SANITIZE", "MXNET_CKPT_HOST", "MXNET_TELEMETRY",
              "MXNET_TRACE_DIR", "MXNET_FLIGHT_DIR"):
        env.pop(k, None)
    return subprocess.Popen(
        [sys.executable, WORKER, "--dir", dirpath, "--host", host,
         "--steps", "3", "--timeout", "60", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)


def _flight_dumps(d):
    return sorted(f for f in os.listdir(d) if f.startswith("flight-"))


class TestTwoHostDrill:
    def test_clean_run_merges_one_timeline_no_dump(self, tmp_path):
        d = str(tmp_path)
        procs = [_spawn(d, "0/2"), _spawn(d, "1/2")]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        assert [p.returncode for p in procs] == [0, 0], outs
        assert os.path.exists(os.path.join(d, "trace-0.jsonl")), outs
        assert os.path.exists(os.path.join(d, "trace-1.jsonl")), outs
        # a third process — the "driver" — merges the two streams
        doc = trace.chrome_trace(path=os.path.join(d, "merged.json"),
                                 directory=d)
        with open(os.path.join(d, "merged.json")) as f:
            reparsed = json.load(f)           # valid JSON on disk
        assert reparsed["traceEvents"]
        steps = [e for e in doc["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "trainer.step"]
        lanes = {e["pid"] for e in steps}
        assert lanes == {0, 1}, "both hosts' step spans in one timeline"
        for e in steps:
            assert "trace_id" in e["args"], "steps must carry trace roots"
        # clean run: the flight recorder stays silent
        assert _flight_dumps(d) == [], outs

    def test_planted_divergence_dumps_both_hosts(self, tmp_path):
        d = str(tmp_path)
        procs = [_spawn(d, "0/2"),
                 _spawn(d, "1/2", extra=("--diverge-at", "2"))]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        assert [p.returncode for p in procs] == [3, 3], outs
        dumps = _flight_dumps(d)
        hosts = set()
        for name in dumps:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
            assert doc["reason"] == "CollectiveDivergenceError", doc["reason"]
            assert "CollectiveDivergenceError" in doc["error"]
            hosts.add(doc["host"])
            names = [e["name"] for e in doc["events"]]
            assert "trainer.step" in names, \
                "dump must show the host's last framework beats"
            assert "collective" in names, \
                "dump must show the fingerprints leading up to the fault"
            assert "sanitizer.violation" in names
        assert hosts == {0, 1}, (dumps, outs)
