"""Subprocess entry for pod-scale checkpoint drills (test_pod_checkpoint.py
and the ci resilience stage).

Modes
-----
``shard-save``     build the deterministic trainer, run ``--steps`` steps,
                   then save through an ``SPMDCheckpointManager`` acting as
                   simulated host ``--host h/H`` of a co-writer group (all
                   workers share ``--dir``).  ``--die-at SITE`` arms a
                   one-shot fault at SITE and hard-kills the process
                   (``os._exit(9)``) when it trips — a co-writer host dying
                   mid-save, not an exception a retry could absorb.
``train-preempt``  run a ``ResilientTrainer`` loop with a
                   ``PreemptionHandler`` installed, printing one
                   ``STEP <i> <loss>`` line per step (full float precision,
                   for bitwise parity checks) — the parent SIGTERMs this
                   process mid-run and asserts a clean exit + committed
                   final checkpoint.

The trainer/batch builders are imported by the parent test for its
uninterrupted reference runs, so both sides are bitwise-comparable by
construction.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

N_CLASSES = 4
BATCH = 16
FEATS = 8


def build_trainer(seed=0, n_devices=None, dp=4, tp=2):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import FunctionalOptimizer, SPMDTrainer, make_mesh

    mx.random.seed(seed)
    np.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="pod_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu", in_units=FEATS),
                mx.gluon.nn.Dense(N_CLASSES, in_units=16))
    net.initialize()
    mesh = make_mesh(n_devices=n_devices, dp=dp, tp=tp)
    return SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer("adam", 1e-2), mesh,
                       nan_guard=True)


def make_batches(n, seed=42):
    import numpy as np
    rng = np.random.RandomState(seed)
    return [(rng.randn(BATCH, FEATS).astype("float32"),
             rng.randint(0, N_CLASSES, BATCH).astype("float32"))
            for _ in range(n)]


def reference_losses(n, seed=0):
    """Uninterrupted n-step run — the parity baseline."""
    tr = build_trainer(seed)
    return [float(tr.step(x, y).asnumpy()) for x, y in make_batches(n)]


def _mode_shard_save(args):
    from mxnet_tpu.parallel import SPMDCheckpointManager
    from mxnet_tpu.resilience import InjectedFault, faults

    host, _, host_count = args.host.partition("/")
    tr = build_trainer(args.seed)
    for x, y in make_batches(args.steps):
        tr.step(x, y)
    if args.die_at:
        faults.inject(args.die_at, "fail:1")
    mgr = SPMDCheckpointManager(args.dir, host_index=int(host),
                                host_count=int(host_count),
                                barrier_timeout_s=args.barrier_timeout)
    try:
        mgr.save(tr._t, tr, extra={"host": int(host)})
    except InjectedFault:
        # the drill: a host dying mid-save is a kill, not an exception a
        # retry could absorb
        print(f"DYING host={host} site={args.die_at}", flush=True)
        os._exit(9)
    print(f"SAVED step={tr._t} host={host}", flush=True)


def _mode_train_preempt(args):
    import time

    from mxnet_tpu.resilience import ResilientTrainer, TrainingPreempted

    rt = ResilientTrainer(build_trainer(args.seed), args.dir,
                          save_every=args.save_every, preemption=True,
                          async_save=args.async_save)
    try:
        for i, (x, y) in enumerate(make_batches(args.steps)):
            loss = float(rt.step(x, y).asnumpy())
            print(f"STEP {i} {loss!r}", flush=True)
            if args.step_delay:
                # widen the signal window: without this, post-compile steps
                # are sub-ms and a parent SIGTERMing "mid-run" can lose the
                # race to a completed run
                time.sleep(args.step_delay)
        rt.flush()
        print(f"DONE step={rt.step_count}", flush=True)
    except TrainingPreempted as e:
        print(f"PREEMPTED step={e.step} ckpt={e.checkpoint_step}",
              flush=True)
        raise      # SystemExit(0): the clean exit the scheduler expects


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True,
                    choices=["shard-save", "train-preempt"])
    ap.add_argument("--dir", required=True)
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--host", default="0/1",
                    help="simulated host identity h/H (shard-save)")
    ap.add_argument("--barrier-timeout", type=float, default=60.0)
    ap.add_argument("--die-at", default=None,
                    help="fault site that hard-kills this worker (fail:1)")
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--async-save", action="store_true")
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep per step (train-preempt: widens the "
                         "parent's SIGTERM window)")
    args = ap.parse_args(argv)
    if args.mode == "shard-save":
        _mode_shard_save(args)
    else:
        _mode_train_preempt(args)


if __name__ == "__main__":
    sys.exit(main())
