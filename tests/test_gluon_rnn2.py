"""RNN depth tranche (reference ``test_gluon_rnn.py`` remainder):
forget-bias initializer layout, zoneout shape contract, variant-length
unroll masking for every cell family, fill-shape deferred init.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_lstm_forget_bias_layout():
    """LSTMBias puts ``forget_bias`` exactly in the f-gate quarter
    (reference test_lstm_forget_bias; i/f/c/o gate order)."""
    forget_bias = 2.0
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(
        100, i2h_bias_initializer=mx.init.LSTMBias(forget_bias),
        prefix="l0_"))
    stack.add(gluon.rnn.LSTMCell(
        100, i2h_bias_initializer=mx.init.LSTMBias(forget_bias),
        prefix="l1_"))
    stack.initialize()
    stack(mx.nd.ones((32, 200)), stack.begin_state(batch_size=32))
    expected = np.hstack([np.zeros(100), forget_bias * np.ones(100),
                          np.zeros(200)])
    for name, param in stack.collect_params().items():
        if name.endswith("i2h_bias"):
            np.testing.assert_allclose(param.data().asnumpy(), expected)


def test_zoneout_shapes_and_eval_identity():
    """ZoneoutCell keeps output shapes; at inference it is the identity
    wrapper (reference test_zoneout + zoneout semantics)."""
    cell = gluon.rnn.ZoneoutCell(gluon.rnn.RNNCell(100, prefix="rnn_"),
                                 zoneout_outputs=0.5, zoneout_states=0.5)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(10, 3, 50))
    outs, states = cell.unroll(3, x, layout="NTC", merge_outputs=False)
    assert len(outs) == 3
    assert all(o.shape == (10, 100) for o in outs)
    # inference mode: zoneout is deterministic (identity mixing)
    y1, _ = cell(x[:, 0, :], cell.begin_state(batch_size=10))
    y2, _ = cell(x[:, 0, :], cell.begin_state(batch_size=10))
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-6)


@pytest.mark.parametrize("cell_fn", [
    lambda: gluon.rnn.RNNCell(20),
    lambda: gluon.rnn.LSTMCell(20),
    lambda: gluon.rnn.GRUCell(20),
    lambda: gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(20),
                                        gluon.rnn.LSTMCell(20)),
])
def test_unroll_variant_length_masks_and_matches(cell_fn):
    """reference test_rnn_unroll_variant_length: per-sequence
    valid_length unroll equals the explicit shorter unroll, and padded
    steps are zeroed."""
    cell = cell_fn()
    cell.initialize()
    batch, max_len, dim = 4, 10, 20
    valid = [3, 10, 5, 6]
    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.randn(batch, max_len, dim).astype("float32"))
    outs, states = cell.unroll(max_len, data,
                               valid_length=mx.nd.array(valid),
                               merge_outputs=True, layout="NTC")
    for i, vl in enumerate(valid):
        ele_out, ele_states = cell.unroll(
            vl, data[i:i + 1, :vl, :], merge_outputs=True, layout="NTC")
        np.testing.assert_allclose(outs.asnumpy()[i:i + 1, :vl, :],
                                   ele_out.asnumpy(), rtol=1e-4,
                                   atol=1e-4)
        if vl < max_len:
            np.testing.assert_allclose(
                outs.asnumpy()[i:i + 1, vl:, :], 0.0, atol=1e-6)
        for vs, gs in zip(states, ele_states):
            np.testing.assert_allclose(vs.asnumpy()[i:i + 1],
                                       gs.asnumpy(), rtol=1e-4,
                                       atol=1e-4)


def test_unroll_variant_length_residual_stack():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.ResidualCell(gluon.rnn.RNNCell(20)))
    stack.add(gluon.rnn.ResidualCell(gluon.rnn.RNNCell(20)))
    stack.initialize()
    rng = np.random.RandomState(1)
    data = mx.nd.array(rng.randn(4, 10, 20).astype("float32"))
    valid = mx.nd.array([3, 10, 5, 6])
    outs, _ = stack.unroll(10, data, valid_length=valid,
                           merge_outputs=True, layout="NTC")
    np.testing.assert_allclose(outs.asnumpy()[0, 3:, :], 0.0, atol=1e-6)


def test_unroll_tnc_layout_variant_length():
    cell = gluon.rnn.LSTMCell(16)
    cell.initialize()
    rng = np.random.RandomState(2)
    data = mx.nd.array(rng.randn(10, 4, 8).astype("float32"))   # TNC
    valid = [2, 7, 10, 4]
    outs, states = cell.unroll(10, data,
                               valid_length=mx.nd.array(valid),
                               merge_outputs=True, layout="TNC")
    for i, vl in enumerate(valid):
        ele_out, ele_states = cell.unroll(
            vl, data[:vl, i:i + 1, :], merge_outputs=True, layout="TNC")
        np.testing.assert_allclose(outs.asnumpy()[:vl, i:i + 1, :],
                                   ele_out.asnumpy(), rtol=1e-4,
                                   atol=1e-4)
        for vs, gs in zip(states, ele_states):
            np.testing.assert_allclose(vs.asnumpy()[i:i + 1],
                                       gs.asnumpy(), rtol=1e-4,
                                       atol=1e-4)


def test_cell_and_layer_fill_shape():
    """reference test_cell_fill_shape / test_layer_fill_shape: deferred
    input-size inference on first forward."""
    cell = gluon.rnn.LSTMCell(10)
    cell.initialize()
    out, _ = cell.unroll(3, mx.nd.ones((2, 3, 7)), merge_outputs=True)
    assert cell.i2h_weight.shape[1] == 7
    layer = gluon.rnn.LSTM(10)
    layer.initialize()
    layer(mx.nd.ones((3, 2, 7)))
    found = [p for n, p in layer.collect_params().items()
             if "i2h_weight" in n and "l0" in n]
    assert found and found[0].shape[1] == 7


def test_symbolic_variant_length_binds():
    """The valid_length path must also work symbolically (reference tail
    of test_rnn_unroll_variant_length)."""
    data = mx.sym.var("data")
    valid_length = mx.sym.var("valid_length")
    cell = gluon.rnn.RNNCell(32)
    outs, states = cell.unroll(10, data, valid_length=valid_length,
                               merge_outputs=True, layout="NTC")
    mod = mx.mod.Module(states[0], data_names=("data", "valid_length"),
                        label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10, 2)),
                          ("valid_length", (4,))], label_shapes=None)
    mod.init_params()
    mod.forward(mx.io.DataBatch([mx.nd.random.normal(0, 1, (4, 10, 2)),
                                 mx.nd.array([3, 6, 10, 2])]))
    out = mod.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()


def test_symbolic_bidirectional_variant_length_binds():
    """Symbolic bidirectional unroll with valid_length (r4 review case:
    per-step Symbol slicing must split timesteps, not graph outputs)."""
    data = mx.sym.var("data")
    valid_length = mx.sym.var("valid_length")
    cell = gluon.rnn.BidirectionalCell(gluon.rnn.RNNCell(8),
                                       gluon.rnn.RNNCell(8))
    outs, states = cell.unroll(6, data, valid_length=valid_length,
                               merge_outputs=True, layout="NTC")
    mod = mx.mod.Module(outs, data_names=("data", "valid_length"),
                        label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[("data", (3, 6, 4)),
                          ("valid_length", (3,))], label_shapes=None)
    mod.init_params()
    mod.forward(mx.io.DataBatch([mx.nd.random.normal(0, 1, (3, 6, 4)),
                                 mx.nd.array([2, 6, 4])]))
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (3, 6, 16)
    np.testing.assert_allclose(out[0, 2:, :], 0.0, atol=1e-6)


def test_mixed_initializer_still_callable():
    """Composite initializers (Mixed) used as an explicit param init must
    dispatch through __call__, not _init_weight (r4 review case)."""
    p = mx.gluon.Parameter(
        "w", shape=(2, 2),
        init=mx.init.Mixed([".*"], [mx.init.One()]))
    p.initialize()
    np.testing.assert_allclose(p.data().asnumpy(), np.ones((2, 2)))


def test_unroll_length_one():
    """length-1 unroll (r4 review case: split(num_outputs=1) returns a
    bare array; the unmerge helpers must re-wrap it)."""
    cell = gluon.rnn.LSTMCell(6)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 1, 3))
    outs, states = cell.unroll(1, x, layout="NTC", merge_outputs=False)
    assert len(outs) == 1 and outs[0].shape == (2, 6)
    outs2, _ = cell.unroll(1, x, valid_length=mx.nd.array([1, 1]),
                           merge_outputs=False, layout="NTC")
    assert len(outs2) == 1 and outs2[0].shape == (2, 6)


def test_symbol_ndarray_mix_rejected():
    """Mixing Symbol and NDArray operands fails loudly at the call site
    (r4 review case: it used to embed a live NDArray in the graph and
    die at bind with an unrelated error)."""
    with pytest.raises(TypeError, match="mix Symbol and NDArray"):
        mx.nd.broadcast_add(mx.sym.var("a"), mx.nd.ones((2, 2)))
    with pytest.raises(TypeError, match="out="):
        mx.nd.elemwise_add(mx.sym.var("a"), mx.sym.var("b"),
                           out=mx.nd.ones((2, 2)))
