"""Model zoo tests (reference
``tests/python/unittest/test_gluon_model_zoo.py``): every registered model
constructs, initializes, and produces finite logits of the right shape.

Heavy models (vgg19, densenet201, resnet152...) are exercised at the
construct-only level to keep CI time bounded; one representative per family
runs a real forward.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.vision import get_model

ALL_MODELS = [
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2",
    "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
    "alexnet", "densenet121", "densenet161", "densenet169", "densenet201",
    "squeezenet1.0", "squeezenet1.1", "inceptionv3",
    "mobilenet1.0", "mobilenet0.75", "mobilenet0.5", "mobilenet0.25",
    "mobilenetv2_1.0", "mobilenetv2_0.75", "mobilenetv2_0.5",
    "mobilenetv2_0.25",
]

FORWARD_MODELS = ["resnet18_v1", "resnet18_v2", "vgg11", "alexnet",
                  "densenet121", "squeezenet1.1", "mobilenet0.25",
                  "mobilenetv2_0.25"]


@pytest.mark.parametrize("name", ALL_MODELS)
def test_constructs(name):
    net = get_model(name, classes=7)
    assert net is not None


@pytest.mark.parametrize("name", FORWARD_MODELS)
def test_forward(name):
    net = get_model(name, classes=7)
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 224, 224))
    y = net(x)
    assert y.shape == (2, 7)
    assert np.isfinite(y.asnumpy()).all()


def test_inception_forward():
    net = get_model("inceptionv3", classes=5)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 299, 299))
    y = net(x)
    assert y.shape == (1, 5)
    assert np.isfinite(y.asnumpy()).all()


def test_hybridize_resnet():
    net = vision.resnet18_v1(classes=4)
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
    y1 = net(x)
    y2 = net(x)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-5)


def test_unknown_model_raises():
    with pytest.raises(ValueError):
        get_model("resnet1_v9")
