"""Legacy symbolic RNN tests (reference ``tests/python/unittest/test_rnn.py``
+ ``tests/python/train/test_bucketing.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_rnn_cell_unroll_symbolic():
    cell = mx.rnn.RNNCell(16, prefix="rnn_")
    data = mx.sym.Variable("data")
    outputs, states = cell.unroll(3, data, layout="NTC", merge_outputs=False)
    args = set()
    for o in outputs:
        args.update(o.list_arguments())
    assert {"rnn_i2h_weight", "rnn_h2h_weight", "rnn_i2h_bias",
            "rnn_h2h_bias", "data"} <= args


def test_lstm_cell_executes():
    cell = mx.rnn.LSTMCell(8, prefix="lstm_")
    data = mx.sym.Variable("data")
    outputs, states = cell.unroll(4, data, layout="NTC", merge_outputs=True)
    exe = outputs.simple_bind(ctx=mx.cpu(), data=(2, 4, 5))
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = np.random.RandomState(0).randn(*arr.shape) * 0.1
    exe.arg_dict["data"][:] = np.random.RandomState(1).randn(2, 4, 5)
    out = exe.forward()[0]
    assert out.shape == (2, 4, 8)
    assert np.isfinite(out.asnumpy()).all()


def test_fused_cell_matches_unfused():
    """FusedRNNCell(RNN op) vs step-wise LSTMCell with shared weights."""
    T, N, C, H = 4, 3, 5, 8
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_",
                                get_next_state=True)
    sym_f, _ = fused.unroll(T, mx.sym.Variable("data"), layout="NTC",
                            merge_outputs=True)
    exe_f = sym_f.simple_bind(ctx=mx.cpu(), data=(N, T, C))
    rng = np.random.RandomState(0)
    x = rng.randn(N, T, C).astype("float32")
    # flat param vector: W (4H, C), R (4H, H), bw, br
    W = rng.randn(4 * H, C).astype("float32") * 0.2
    R = rng.randn(4 * H, H).astype("float32") * 0.2
    bw = rng.randn(4 * H).astype("float32") * 0.1
    br = rng.randn(4 * H).astype("float32") * 0.1
    flat = np.concatenate([W.ravel(), R.ravel(), bw, br])
    exe_f.arg_dict["f_parameters"][:] = flat
    exe_f.arg_dict["data"][:] = x
    out_f = exe_f.forward()[0].asnumpy()

    cell = mx.rnn.LSTMCell(H, prefix="u_")
    sym_u, _ = cell.unroll(T, mx.sym.Variable("data"), layout="NTC",
                           merge_outputs=True)
    exe_u = sym_u.simple_bind(ctx=mx.cpu(), data=(N, T, C))
    exe_u.arg_dict["u_i2h_weight"][:] = W
    exe_u.arg_dict["u_h2h_weight"][:] = R
    exe_u.arg_dict["u_i2h_bias"][:] = bw
    exe_u.arg_dict["u_h2h_bias"][:] = br
    exe_u.arg_dict["data"][:] = x
    out_u = exe_u.forward()[0].asnumpy()
    np.testing.assert_allclose(out_f, out_u, rtol=1e-4, atol=1e-5)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 20, size=l))
                 for l in rng.randint(2, 9, size=100)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8], invalid_label=0)
    batches = list(it)
    assert len(batches) > 0
    for b in batches:
        assert b.bucket_key in (4, 8)
        assert b.data[0].shape == (8, b.bucket_key)
        # label is data shifted left
        d = b.data[0].asnumpy()
        l = b.label[0].asnumpy()
        np.testing.assert_array_equal(d[:, 1:], l[:, :-1])


def test_bucketing_training_lstm():
    """The reference's test_bucketing.py shape: char-level LM over buckets."""
    rng = np.random.RandomState(0)
    vocab = 16
    # zipf-ish marginal so there is something to learn
    p = 1.0 / np.arange(1, vocab)
    p /= p.sum()
    sentences = [list(rng.choice(np.arange(1, vocab), size=l, p=p))
                 for l in rng.randint(3, 9, size=200)]
    buckets = [4, 8]
    batch_size = 16
    it = mx.rnn.BucketSentenceIter(sentences, batch_size, buckets=buckets,
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=8,
                                 name="embed")
        cell = mx.rnn.LSTMCell(16, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, embed, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 16))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label_f = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label_f, name="softmax",
                                    use_ignore=True, ignore_label=0)
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    ppl = mx.metric.Perplexity(ignore_label=0)
    last = None
    for epoch in range(3):
        it.reset()
        ppl.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(ppl, batch.label)
        last = ppl.get()[1]
    # zipf marginal entropy ≈ exp(2.1) ≈ 8.3; uniform would be 15
    assert last < 12, last
