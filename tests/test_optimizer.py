"""Optimizer tests, mirroring reference tests/python/unittest/test_optimizer.py
(numerical update checks vs a numpy reference implementation)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt


def _run_updates(optimizer, w0, g, steps=3):
    weight = nd.array(w0.copy())
    state = optimizer.create_state(0, weight)
    for _ in range(steps):
        grad = nd.array(g.copy())
        optimizer.update(0, weight, grad, state)
    return weight.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.random.rand(4, 3).astype(np.float32)
    g = np.random.rand(4, 3).astype(np.float32)
    out = _run_updates(opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01), w0, g)

    # numpy reference (reference sgd_mom_update semantics)
    w = w0.copy()
    mom = np.zeros_like(w)
    for _ in range(3):
        gg = g + 0.01 * w
        mom = 0.9 * mom - 0.1 * gg
        w = w + mom
    assert np.allclose(out, w, atol=1e-5)


def test_sgd_no_momentum():
    w0 = np.random.rand(5).astype(np.float32)
    g = np.random.rand(5).astype(np.float32)
    out = _run_updates(opt.SGD(learning_rate=0.5), w0, g, steps=1)
    assert np.allclose(out, w0 - 0.5 * g, atol=1e-6)


def test_adam_matches_numpy():
    w0 = np.random.rand(6).astype(np.float32)
    g = np.random.rand(6).astype(np.float32)
    o = opt.Adam(learning_rate=0.01)
    out = _run_updates(o, w0, g, steps=2)

    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t in range(1, 3):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w = w - lr * m / (np.sqrt(v) + eps)
    assert np.allclose(out, w, atol=1e-5)


def test_rmsprop():
    w0 = np.random.rand(6).astype(np.float32)
    g = np.random.rand(6).astype(np.float32)
    out = _run_updates(opt.RMSProp(learning_rate=0.01), w0, g, steps=2)
    assert out.shape == w0.shape
    assert not np.allclose(out, w0)
    out_c = _run_updates(opt.RMSProp(learning_rate=0.01, centered=True),
                         w0, g, steps=2)
    assert not np.allclose(out_c, w0)


@pytest.mark.parametrize("name", ["sgd", "adam", "adagrad", "rmsprop",
                                  "adadelta", "ftrl", "adamax", "nadam",
                                  "nag", "signum", "ftml", "sgld", "dcasgd"])
def test_all_optimizers_update(name):
    np.random.seed(0)
    w0 = np.random.rand(4, 3).astype(np.float32)
    g = (np.random.rand(4, 3).astype(np.float32) - 0.5)
    o = opt.create(name)
    out = _run_updates(o, w0, g, steps=2)
    assert out.shape == w0.shape
    assert np.isfinite(out).all()
    assert not np.allclose(out, w0)


def test_multi_precision_sgd():
    w0 = np.random.rand(4).astype(np.float16)
    g = np.random.rand(4).astype(np.float16)
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    weight = nd.array(w0, dtype=np.float16)
    state = o.create_state_multi_precision(0, weight)
    assert state[0].dtype == np.float32  # master weights
    o.update_multi_precision(0, weight, nd.array(g, dtype=np.float16), state)
    assert weight.dtype == np.float16
    assert not np.allclose(weight.asnumpy(), w0)


def test_lr_wd_mult():
    o = opt.SGD(learning_rate=1.0,
                param_idx2name={0: "w0_weight", 1: "w1_bias"}, wd=0.1)
    o.set_lr_mult({"w0_weight": 0.5})
    assert o._get_lr(0) == 0.5
    assert o._get_lr(1) == 1.0
    # bias gets wd 0 by default
    assert o._get_wd(1) == 0.0
    assert o._get_wd(0) == pytest.approx(0.1)


def test_lr_scheduler():
    from mxnet_tpu import lr_scheduler
    s = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == pytest.approx(0.5)
    m = lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert m(1) == 1.0
    assert m(6) == pytest.approx(0.1)
    assert m(11) == pytest.approx(0.01)
    p = lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert p(0) == pytest.approx(1.0)
    assert p(100) == pytest.approx(0.0, abs=1e-6)
    c = lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0)
    assert c(0) == pytest.approx(1.0)
    assert c(100) == pytest.approx(0.0, abs=1e-6)
    w = lr_scheduler.FactorScheduler(step=100, base_lr=1.0,
                                     warmup_steps=10, warmup_begin_lr=0.1)
    assert w(0) == pytest.approx(0.1)
    assert w(5) == pytest.approx(0.1 + 0.9 * 0.5)


def test_scheduler_in_optimizer():
    from mxnet_tpu import lr_scheduler
    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=1.0)
    o = opt.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = nd.ones((2,))
    g = nd.ones((2,))
    for _ in range(6):
        o.update(0, w, g, None)
    assert o.learning_rate < 1.0


def test_updater_serialization():
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    u = opt.get_updater(o)
    w = nd.ones((3,))
    g = nd.ones((3,))
    u(0, g, w)
    states = u.get_states()
    u2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    u2.set_states(states)
    assert 0 in u2.states


# --- r4 depth: per-optimizer update-formula matrix vs numpy references
# (reference test_optimizer.py per-optimizer comparators with wd/
# rescale_grad/clip_gradient combinations)

def _np_sgd_mom(w, g, mom, lr, m, wd, rescale, clip):
    g = g * rescale
    if clip > 0:
        g = np.clip(g, -clip, clip)
    g = g + wd * w
    mom_new = m * mom + g
    return w - lr * mom_new, mom_new


@pytest.mark.parametrize("wd,rescale,clip", [
    (0.0, 1.0, -1.0), (0.01, 1.0, -1.0), (0.0, 0.5, -1.0),
    (0.01, 0.25, 0.5),
])
def test_sgd_momentum_full_matrix(wd, rescale, clip):
    rng = np.random.RandomState(0)
    w0 = rng.randn(6).astype("float32")
    g0 = rng.randn(6).astype("float32") * 4
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=wd,
                           rescale_grad=rescale, clip_gradient=clip)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array(w0.copy())
    want_w, want_m = w0.copy(), np.zeros(6, "float32")
    for _ in range(3):
        upd(0, mx.nd.array(g0), w)
        want_w, want_m = _np_sgd_mom(want_w, g0, 0.9, 0.1, want_m, wd,
                                     rescale, clip)
    np.testing.assert_allclose(w.asnumpy(), want_w, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.05])
def test_nag_matches_numpy(wd):
    rng = np.random.RandomState(1)
    w0 = rng.randn(5).astype("float32")
    g0 = rng.randn(5).astype("float32")
    lr, m = 0.1, 0.9
    opt = mx.optimizer.NAG(learning_rate=lr, momentum=m, wd=wd)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array(w0.copy())
    want_w, mom = w0.copy(), np.zeros(5, "float32")
    for _ in range(3):
        upd(0, mx.nd.array(g0), w)
        g = g0 + wd * want_w
        mom = m * mom + g
        want_w = want_w - lr * (g + m * mom)    # reference nag_update
        np.testing.assert_allclose(w.asnumpy(), want_w, rtol=1e-4,
                                   atol=1e-5)


def test_adagrad_matches_numpy():
    rng = np.random.RandomState(2)
    w0 = rng.randn(5).astype("float32")
    g0 = rng.randn(5).astype("float32")
    lr, eps = 0.1, 1e-7
    opt = mx.optimizer.AdaGrad(learning_rate=lr, eps=eps)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array(w0.copy())
    want_w, hist = w0.copy(), np.zeros(5, "float32")
    for _ in range(3):
        upd(0, mx.nd.array(g0), w)
        hist = hist + g0 * g0
        want_w = want_w - lr * g0 / (np.sqrt(hist) + eps)
    np.testing.assert_allclose(w.asnumpy(), want_w, rtol=1e-4, atol=1e-5)


def test_adamw_update_op_decoupled_weight_decay():
    """The contrib adamw_update op decouples wd from the gradient
    (reference src/operator/contrib/adamw.cc; the reference likewise has
    no AdamW optimizer CLASS — consumers drive the op directly): with
    zero gradients the weight shrinks by exactly eta*wd*w (reference
    adamw-inl.h:137 — wd is NOT scaled by lr, unlike torch's AdamW)."""
    w0 = np.ones(4, "float32")
    w = mx.nd.array(w0.copy())
    g = mx.nd.zeros(4)
    mean, var = mx.nd.zeros(4), mx.nd.zeros(4)
    out = mx.nd.contrib.adamw_update(
        w, g, mean, var, mx.nd.array([1.0]),   # rescale_grad tensor
        lr=0.1, eta=1.0, wd=0.5, beta1=0.9, beta2=0.999, epsilon=1e-8)
    got = out[0].asnumpy() if isinstance(out, (list, tuple)) else out.asnumpy()
    np.testing.assert_allclose(got, w0 - 1.0 * 0.5 * w0, rtol=1e-5)


def test_signum_sign_update():
    rng = np.random.RandomState(3)
    w0 = rng.randn(5).astype("float32")
    g0 = rng.randn(5).astype("float32")
    opt = mx.optimizer.create("signum", learning_rate=0.1, momentum=0.9,
                              wd=0.0)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array(w0.copy())
    upd(0, mx.nd.array(g0), w)
    mom = 0.9 * np.zeros(5) - (1 - 0.9) * g0   # reference signum momentum
    want = w0 + 0.1 * np.sign(mom)
    np.testing.assert_allclose(w.asnumpy(), want, rtol=1e-5, atol=1e-6)


def test_idx_based_wd_mult_through_updater():
    """Per-parameter wd multipliers resolve through set_wd_mult and the
    updater's idx→name mapping (reference lr/wd mult state machine)."""
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.1)
    opt.idx2name = {0: "w_weight", 1: "b_bias"}
    opt.set_wd_mult({})                         # bias gets wd 0 by default
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.ones(3)
    b = mx.nd.ones(3)
    upd(0, mx.nd.zeros(3), w)
    upd(1, mx.nd.zeros(3), b)
    # weight decays, bias does not
    assert w.asnumpy()[0] < 1.0
    np.testing.assert_allclose(b.asnumpy(), np.ones(3))
