"""Sparse-operator contracts (port of the reference
``tests/python/unittest/test_sparse_operator.py`` semantics onto the
compressed-RowSparse / dense-backed CSR layer).

Covered families: cast_storage round trips, sparse_retain fwd+bwd, dot
with csr lhs (+transposes), elemwise add/mul across stype combinations,
CSR slicing, storage-type preservation, where/abs/sign on sparse inputs,
and scipy cross-checks.
"""
import numpy as np
import pytest
import scipy.sparse as sps

import mxnet_tpu as mx
from mxnet_tpu.ndarray.sparse import CSRNDArray, RowSparseNDArray


def _rand_sparse(rng, shape, density=0.3):
    dense = rng.randn(*shape).astype("float32")
    mask = rng.rand(*shape) < density
    return dense * mask


def _rand_rsp(rng, shape, density=0.4):
    dense = rng.randn(*shape).astype("float32")
    keep = rng.rand(shape[0]) < density
    return dense * keep[:, None]


# ------------------------------------------------------------ cast_storage
@pytest.mark.parametrize("stype", ["csr", "row_sparse"])
def test_cast_storage_roundtrip(stype):
    rng = np.random.RandomState(0)
    d = _rand_sparse(rng, (7, 5))
    x = mx.nd.array(d)
    s = mx.nd.cast_storage(x, stype=stype)
    assert s.stype == stype
    np.testing.assert_array_equal(s.asnumpy(), d)
    back = mx.nd.cast_storage(s, stype="default")
    assert back.stype == "default"
    np.testing.assert_array_equal(back.asnumpy(), d)


def test_cast_storage_csr_matches_scipy():
    rng = np.random.RandomState(1)
    d = _rand_sparse(rng, (6, 9))
    c = mx.nd.array(d).tostype("csr")
    ref = sps.csr_matrix(d)
    np.testing.assert_array_equal(c.indptr.asnumpy(), ref.indptr)
    np.testing.assert_array_equal(c.indices.asnumpy(), ref.indices)
    np.testing.assert_allclose(c.data.asnumpy(), ref.data, rtol=1e-6)


# ---------------------------------------------------------- sparse_retain
def test_sparse_retain_forward():
    rng = np.random.RandomState(2)
    d = _rand_rsp(rng, (8, 4))
    x = mx.nd.array(d).tostype("row_sparse")
    rows = mx.nd.array([1, 3, 6])
    out = mx.nd.sparse_retain(x, rows)
    want = np.zeros_like(d)
    for r in (1, 3, 6):
        want[r] = d[r]
    np.testing.assert_array_equal(out.asnumpy(), want)
    assert out.stype == "row_sparse"


def test_sparse_retain_gradient():
    """Reference contract: d(retain)/d(data) keeps only retained rows."""
    rng = np.random.RandomState(3)
    d = rng.randn(6, 3).astype("float32")
    x = mx.nd.array(d)
    x.attach_grad()
    rows = mx.nd.array([0, 4])
    with mx.autograd.record():
        y = mx.nd.sparse_retain(x, rows)
        loss = (y * y).sum()
    loss.backward()
    g = x.grad.asnumpy()
    want = np.zeros_like(d)
    for r in (0, 4):
        want[r] = 2 * d[r]
    np.testing.assert_allclose(g, want, rtol=1e-5)


# ------------------------------------------------------------------- dot
@pytest.mark.parametrize("ta", [False, True])
def test_dot_csr_dense(ta):
    rng = np.random.RandomState(4)
    a = _rand_sparse(rng, (5, 7))
    b = rng.randn(*((5, 3) if ta else (7, 3))).astype("float32")
    lhs = mx.nd.array(a).tostype("csr")
    out = mx.nd.sparse.dot(lhs, mx.nd.array(b), transpose_a=ta)
    want = (a.T if ta else a) @ b
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5, atol=1e-6)


def test_dot_dense_rsp():
    rng = np.random.RandomState(5)
    a = rng.randn(4, 6).astype("float32")
    b = _rand_rsp(rng, (6, 3))
    rhs = mx.nd.array(b).tostype("row_sparse")
    out = mx.nd.sparse.dot(mx.nd.array(a), rhs)
    np.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5, atol=1e-6)


def test_dot_csr_dense_gradient():
    rng = np.random.RandomState(6)
    a = _rand_sparse(rng, (5, 7))
    b = rng.randn(7, 3).astype("float32")
    bnd = mx.nd.array(b)
    bnd.attach_grad()
    lhs = mx.nd.array(a).tostype("csr")
    with mx.autograd.record():
        out = mx.nd.sparse.dot(lhs, bnd)
        loss = out.sum()
    loss.backward()
    np.testing.assert_allclose(bnd.grad.asnumpy(),
                               a.T @ np.ones((5, 3), "float32"),
                               rtol=1e-5, atol=1e-6)


# ----------------------------------------------------- elemwise mixtures
@pytest.mark.parametrize("op,np_op", [("elemwise_add", np.add),
                                      ("elemwise_mul", np.multiply)])
@pytest.mark.parametrize("lt,rt", [("row_sparse", "row_sparse"),
                                   ("csr", "csr"),
                                   ("row_sparse", "default"),
                                   ("default", "csr")])
def test_elemwise_mixed_stypes(op, np_op, lt, rt):
    rng = np.random.RandomState(7)
    a = _rand_sparse(rng, (6, 5))
    b = _rand_sparse(rng, (6, 5))
    an = mx.nd.array(a)
    bn = mx.nd.array(b)
    if lt != "default":
        an = an.tostype(lt)
    if rt != "default":
        bn = bn.tostype(rt)
    out = getattr(mx.nd, op)(an, bn)
    np.testing.assert_allclose(out.asnumpy(), np_op(a, b), rtol=1e-6)


def test_add_n_sparse():
    rng = np.random.RandomState(8)
    arrs = [_rand_rsp(rng, (5, 4)) for _ in range(3)]
    nds = [mx.nd.array(a).tostype("row_sparse") for a in arrs]
    out = mx.nd.add_n(*nds)
    np.testing.assert_allclose(out.asnumpy(), sum(arrs), rtol=1e-6)


# --------------------------------------------------------------- slicing
def test_csr_slice():
    rng = np.random.RandomState(9)
    d = _rand_sparse(rng, (8, 6))
    c = mx.nd.array(d).tostype("csr")
    s = c[2:6]
    np.testing.assert_array_equal(s.asnumpy(), d[2:6])
    s2 = mx.nd.slice(c, begin=(1,), end=(5,))
    np.testing.assert_array_equal(s2.asnumpy(), d[1:5])


def test_rsp_retain_method():
    rng = np.random.RandomState(10)
    d = _rand_rsp(rng, (7, 3))
    r = mx.nd.array(d).tostype("row_sparse")
    kept = r.retain(mx.nd.array([0, 2, 5]))
    want = np.zeros_like(d)
    for row in (0, 2, 5):
        want[row] = d[row]
    np.testing.assert_array_equal(kept.asnumpy(), want)


# -------------------------------------------------- unary stype-preserving
@pytest.mark.parametrize("op,np_op", [("abs", np.abs), ("sign", np.sign),
                                      ("square", np.square)])
def test_unary_on_sparse(op, np_op):
    rng = np.random.RandomState(11)
    d = _rand_rsp(rng, (6, 4))
    r = mx.nd.array(d).tostype("row_sparse")
    out = getattr(mx.nd, op)(r)
    np.testing.assert_allclose(out.asnumpy(), np_op(d), rtol=1e-6)


def test_scalar_ops_on_csr():
    rng = np.random.RandomState(12)
    d = _rand_sparse(rng, (5, 5))
    c = mx.nd.array(d).tostype("csr")
    np.testing.assert_allclose((c * 3.0).asnumpy(), d * 3.0, rtol=1e-6)
    np.testing.assert_allclose((c / 2.0).asnumpy(), d / 2.0, rtol=1e-6)


# --------------------------------------------------------- where / misc
def test_where_with_sparse_condition():
    rng = np.random.RandomState(13)
    d = _rand_sparse(rng, (4, 4))
    cond = (d != 0).astype("float32")
    x = rng.randn(4, 4).astype("float32")
    y = rng.randn(4, 4).astype("float32")
    out = mx.nd.where(mx.nd.array(cond), mx.nd.array(x), mx.nd.array(y))
    np.testing.assert_array_equal(out.asnumpy(), np.where(cond != 0, x, y))


def test_norm_on_sparse():
    rng = np.random.RandomState(14)
    d = _rand_sparse(rng, (6, 6))
    c = mx.nd.array(d).tostype("csr")
    got = float(mx.nd.norm(c).asnumpy())
    assert got == pytest.approx(np.linalg.norm(d), rel=1e-5)


def test_sum_mean_on_rsp():
    rng = np.random.RandomState(15)
    d = _rand_rsp(rng, (6, 4))
    r = mx.nd.array(d).tostype("row_sparse")
    assert float(mx.nd.sum(r).asnumpy()) == pytest.approx(d.sum(), rel=1e-5)
    np.testing.assert_allclose(mx.nd.sum(r, axis=0).asnumpy(), d.sum(0),
                               rtol=1e-5)


def test_csr_scipy_dot_crosscheck():
    """dot(csr, dense) against scipy's own csr @ dense."""
    rng = np.random.RandomState(16)
    d = _rand_sparse(rng, (10, 8), density=0.2)
    b = rng.randn(8, 5).astype("float32")
    ref = sps.csr_matrix(d) @ b
    out = mx.nd.sparse.dot(mx.nd.array(d).tostype("csr"), mx.nd.array(b))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_embedding_sparse_grad_rows_match_batch():
    """Embedding(sparse_grad=True) gradient holds exactly the batch's
    unique rows (reference test_sparse_operator embedding checks)."""
    vocab, dim = 20, 6
    emb = mx.gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    idx = mx.nd.array([3, 7, 3, 11])
    with mx.autograd.record():
        out = emb(idx)
        loss = out.sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    rows = np.unique(np.asarray(g.indices.asnumpy()))
    np.testing.assert_array_equal(rows, [3, 7, 11])
    dense = g.asnumpy() if not hasattr(g, "tostype") else \
        g.tostype("default").asnumpy()
    want = np.zeros((vocab, dim), "float32")
    for i in (3, 7, 11):
        want[i] = 2.0 if i == 3 else 1.0
    np.testing.assert_allclose(dense, want, rtol=1e-6)
