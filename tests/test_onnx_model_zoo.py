"""Model-zoo family ONNX round-trips through REAL protobuf bytes.

VERDICT r2 acceptance: every model_zoo family (mobilenet, densenet,
squeezenet, inception, vgg — plus alexnet and resnet v2) must export to
real ``.onnx`` bytes and import back with identical forward outputs.
Reference flow: ``python/mxnet/contrib/onnx/mx2onnx/export_model.py`` on
the zoo models.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mod


_CASES = [
    ("squeezenet1.0", (1, 3, 224, 224)),
    ("mobilenet0.25", (1, 3, 224, 224)),
    ("mobilenetv2_0.25", (1, 3, 224, 224)),
    ("densenet121", (1, 3, 224, 224)),
    ("inceptionv3", (1, 3, 299, 299)),
    ("vgg11", (1, 3, 224, 224)),
    ("alexnet", (1, 3, 224, 224)),
    ("resnet18_v2", (1, 3, 224, 224)),
]


def _load_checkpoint_params(prefix):
    loaded = mx.nd.load(prefix + "-0000.params")
    args, auxs = {}, {}
    for k, v in loaded.items():
        (args if k.startswith("arg:") else auxs)[k.split(":", 1)[1]] = v
    return args, auxs


def _outputs(sym, params, xv):
    binds = dict(params)
    binds["data"] = mx.nd.array(xv)
    aux = {k: binds.pop(k) for k in list(binds)
           if k in sym.list_auxiliary_states()}
    args = {k: v for k, v in binds.items() if k in sym.list_arguments()}
    ex = sym.bind(mx.cpu(), args, aux_states=aux)
    return [o.asnumpy() for o in ex.forward()]


@pytest.mark.parametrize("name,shape", _CASES, ids=[c[0] for c in _CASES])
def test_model_zoo_roundtrip_real_bytes(name, shape, tmp_path):
    rng = np.random.RandomState(0)
    mx.random.seed(0)
    net = mx.gluon.model_zoo.vision.get_model(name, classes=10)
    net.initialize()
    x = mx.nd.array(rng.rand(*shape).astype("float32"))
    net.hybridize()
    net(x)
    prefix = str(tmp_path / name.replace(".", "_"))
    net.export(prefix)
    sym = mx.sym.load(prefix + "-symbol.json")
    args, auxs = _load_checkpoint_params(prefix)
    params = dict(args)
    params.update(auxs)
    want = _outputs(sym, params, x.asnumpy())[0]

    path = str(tmp_path / (name.replace(".", "_") + ".onnx"))
    onnx_mod.export_model(sym, params, shape, onnx_file_path=path)
    import os
    assert os.path.getsize(path) > 10000
    sym2, arg2, aux2 = onnx_mod.import_model(path)
    got = _outputs(sym2, {**arg2, **aux2}, x.asnumpy())[0]
    np.testing.assert_allclose(want, got, rtol=1e-4, atol=1e-4)
