"""Metric tests, mirroring reference tests/python/unittest/test_metric.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric, nd


def check_metric(m, *args, **kwargs):
    m = metric.create(m, *args, **kwargs)
    m.get_config()
    str(m)


def test_metrics_create():
    check_metric("acc", axis=0)
    check_metric("f1")
    check_metric("mcc")
    check_metric("perplexity", -1)
    check_metric("pearsonr")
    check_metric("nll_loss")
    check_metric("loss")
    composite = metric.create(["acc", "f1"])
    check_metric(composite)


def test_accuracy():
    acc = metric.Accuracy()
    pred = nd.array([[0.3, 0.7], [0, 1.], [0.4, 0.6]])
    label = nd.array([0, 1, 1])
    acc.update([label], [pred])
    name, value = acc.get()
    assert name == "accuracy"
    assert value == pytest.approx(2.0 / 3)


def test_top_k_accuracy():
    acc = metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1], [0.1, 0.1, 0.8]])
    label = nd.array([2, 1, 2])
    acc.update([label], [pred])
    _, value = acc.get()
    assert value == pytest.approx(3.0 / 3)


def test_f1_mcc():
    pred = nd.array([[0.7, 0.3], [0.2, 0.8], [0.4, 0.6], [0.9, 0.1]])
    label = nd.array([0, 1, 0, 0])
    f1 = metric.F1()
    f1.update([label], [pred])
    _, v = f1.get()
    # tp=1 fp=1 fn=0 -> precision 0.5, recall 1 -> f1 = 2/3
    assert v == pytest.approx(2.0 / 3, abs=1e-6)
    mcc = metric.MCC()
    mcc.update([label], [pred])
    _, v = mcc.get()
    assert -1.0 <= v <= 1.0


def test_mse_mae_rmse():
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([[1.5], [1.0]])
    mse = metric.MSE()
    mse.update([label], [pred])
    assert mse.get()[1] == pytest.approx((0.25 + 1.0) / 2)
    mae = metric.MAE()
    mae.update([label], [pred])
    assert mae.get()[1] == pytest.approx((0.5 + 1.0) / 2)
    rmse = metric.RMSE()
    rmse.update([label], [pred])
    assert rmse.get()[1] == pytest.approx(np.sqrt((0.25 + 1.0) / 2))


def test_perplexity():
    pred = nd.array([[0.8, 0.2], [0.2, 0.8], [0.5, 0.5]])
    label = nd.array([0, 1, 0])
    ppl = metric.Perplexity(ignore_label=None)
    ppl.update([label], [pred])
    _, v = ppl.get()
    ref = np.exp(-(np.log(0.8) + np.log(0.8) + np.log(0.5)) / 3)
    assert v == pytest.approx(ref, rel=1e-5)


def test_pearson():
    pred = nd.array([[0.7], [0.3], [0.6]])
    label = nd.array([[0.8], [0.2], [0.5]])
    p = metric.PearsonCorrelation()
    p.update([label], [pred])
    _, v = p.get()
    ref = np.corrcoef(pred.asnumpy().ravel(), label.asnumpy().ravel())[0, 1]
    assert v == pytest.approx(ref)


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [nd.array([1.0, 2.0, 3.0])])
    assert m.get()[1] == pytest.approx(2.0)


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).mean())

    m = metric.CustomMetric(feval)
    m.update([nd.array([1.0])], [nd.array([2.0])])
    assert m.get()[1] == pytest.approx(1.0)


def test_composite():
    m = metric.CompositeEvalMetric([metric.Accuracy(), metric.MAE()])
    pred = nd.array([[0.3, 0.7], [0.6, 0.4]])
    label = nd.array([1, 0])
    m.update([label], [pred])
    names, values = m.get()
    assert len(names) == 2
    assert values[0] == pytest.approx(1.0)


def test_reset():
    acc = metric.Accuracy()
    pred = nd.array([[0.3, 0.7]])
    label = nd.array([1])
    acc.update([label], [pred])
    acc.reset()
    assert acc.num_inst == 0
    name, val = acc.get()
    assert np.isnan(val)


def test_local_global_split():
    """reset_local keeps epoch totals in the global view (reference 1.5
    local/global metric split)."""
    m = mx.metric.Accuracy()
    m.update([mx.nd.array([0, 1])], [mx.nd.array([[0, 1], [0, 1]])])  # 1/2
    m.reset_local()
    m.update([mx.nd.array([1, 1])], [mx.nd.array([[0, 1], [0, 1]])])  # 2/2
    assert m.get()[1] == 1.0                 # local window: last interval
    assert m.get_global()[1] == 0.75         # epoch total: 3/4
    m.reset()
    assert np.isnan(m.get_global()[1])
