"""Metric tests, mirroring reference tests/python/unittest/test_metric.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric, nd


def check_metric(m, *args, **kwargs):
    m = metric.create(m, *args, **kwargs)
    m.get_config()
    str(m)


def test_metrics_create():
    check_metric("acc", axis=0)
    check_metric("f1")
    check_metric("mcc")
    check_metric("perplexity", -1)
    check_metric("pearsonr")
    check_metric("nll_loss")
    check_metric("loss")
    composite = metric.create(["acc", "f1"])
    check_metric(composite)


def test_accuracy():
    acc = metric.Accuracy()
    pred = nd.array([[0.3, 0.7], [0, 1.], [0.4, 0.6]])
    label = nd.array([0, 1, 1])
    acc.update([label], [pred])
    name, value = acc.get()
    assert name == "accuracy"
    assert value == pytest.approx(2.0 / 3)


def test_top_k_accuracy():
    acc = metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1], [0.1, 0.1, 0.8]])
    label = nd.array([2, 1, 2])
    acc.update([label], [pred])
    _, value = acc.get()
    assert value == pytest.approx(3.0 / 3)


def test_f1_mcc():
    pred = nd.array([[0.7, 0.3], [0.2, 0.8], [0.4, 0.6], [0.9, 0.1]])
    label = nd.array([0, 1, 0, 0])
    f1 = metric.F1()
    f1.update([label], [pred])
    _, v = f1.get()
    # tp=1 fp=1 fn=0 -> precision 0.5, recall 1 -> f1 = 2/3
    assert v == pytest.approx(2.0 / 3, abs=1e-6)
    mcc = metric.MCC()
    mcc.update([label], [pred])
    _, v = mcc.get()
    assert -1.0 <= v <= 1.0


def test_mse_mae_rmse():
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([[1.5], [1.0]])
    mse = metric.MSE()
    mse.update([label], [pred])
    assert mse.get()[1] == pytest.approx((0.25 + 1.0) / 2)
    mae = metric.MAE()
    mae.update([label], [pred])
    assert mae.get()[1] == pytest.approx((0.5 + 1.0) / 2)
    rmse = metric.RMSE()
    rmse.update([label], [pred])
    assert rmse.get()[1] == pytest.approx(np.sqrt((0.25 + 1.0) / 2))


def test_perplexity():
    pred = nd.array([[0.8, 0.2], [0.2, 0.8], [0.5, 0.5]])
    label = nd.array([0, 1, 0])
    ppl = metric.Perplexity(ignore_label=None)
    ppl.update([label], [pred])
    _, v = ppl.get()
    ref = np.exp(-(np.log(0.8) + np.log(0.8) + np.log(0.5)) / 3)
    assert v == pytest.approx(ref, rel=1e-5)


def test_pearson():
    pred = nd.array([[0.7], [0.3], [0.6]])
    label = nd.array([[0.8], [0.2], [0.5]])
    p = metric.PearsonCorrelation()
    p.update([label], [pred])
    _, v = p.get()
    ref = np.corrcoef(pred.asnumpy().ravel(), label.asnumpy().ravel())[0, 1]
    assert v == pytest.approx(ref)


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [nd.array([1.0, 2.0, 3.0])])
    assert m.get()[1] == pytest.approx(2.0)


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).mean())

    m = metric.CustomMetric(feval)
    m.update([nd.array([1.0])], [nd.array([2.0])])
    assert m.get()[1] == pytest.approx(1.0)


def test_composite():
    m = metric.CompositeEvalMetric([metric.Accuracy(), metric.MAE()])
    pred = nd.array([[0.3, 0.7], [0.6, 0.4]])
    label = nd.array([1, 0])
    m.update([label], [pred])
    names, values = m.get()
    assert len(names) == 2
    assert values[0] == pytest.approx(1.0)


def test_reset():
    acc = metric.Accuracy()
    pred = nd.array([[0.3, 0.7]])
    label = nd.array([1])
    acc.update([label], [pred])
    acc.reset()
    assert acc.num_inst == 0
    name, val = acc.get()
    assert np.isnan(val)


def test_local_global_split():
    """reset_local keeps epoch totals in the global view (reference 1.5
    local/global metric split)."""
    m = mx.metric.Accuracy()
    m.update([mx.nd.array([0, 1])], [mx.nd.array([[0, 1], [0, 1]])])  # 1/2
    m.reset_local()
    m.update([mx.nd.array([1, 1])], [mx.nd.array([[0, 1], [0, 1]])])  # 2/2
    assert m.get()[1] == 1.0                 # local window: last interval
    assert m.get_global()[1] == 0.75         # epoch total: 3/4
    m.reset()
    assert np.isnan(m.get_global()[1])


# --- r4 depth: reference test_metric.py remainder

def test_acc_2d_label_flattens():
    """reference test_acc_2d_label: labels provided as 2-D arrays are
    raveled before comparison."""
    pred = mx.nd.array([[0.3, 0.7], [0, 1.], [0.4, 0.6],
                        [0.8, 0.2], [0.3, 0.5], [0.6, 0.4]])
    label = mx.nd.array([[0, 1, 1], [1, 0, 1]])
    metric = mx.metric.create("acc")
    metric.update([label], [pred])
    _, acc = metric.get()
    want = (np.argmax(pred.asnumpy(), axis=1) ==
            label.asnumpy().ravel()).sum() / float(label.asnumpy().size)
    assert acc == want


def test_loss_update_array_or_list():
    """reference test_loss_update: update accepts a bare array or a
    list."""
    pred = mx.nd.array([[0.3, 0.7], [0, 1.], [0.4, 0.6]])
    m1 = mx.metric.create("loss")
    m2 = mx.metric.create("loss")
    m1.update(None, [pred])
    m2.update(None, pred)
    assert m1.get()[1] == m2.get()[1]


def test_single_array_input_regression_metrics():
    """reference test_single_array_input: mse/mae/rmse with bare-array
    updates."""
    pred = mx.nd.array([[1.0, 2.0, 3.0, 4.0]])
    label = pred + 0.1
    mse = mx.metric.create("mse")
    mse.update(label, pred)
    np.testing.assert_almost_equal(mse.get()[1], 0.01, decimal=5)
    mae = mx.metric.create("mae")
    mae.update(label, pred)
    np.testing.assert_almost_equal(mae.get()[1], 0.1, decimal=5)
    rmse = mx.metric.create("rmse")
    rmse.update(label, pred)
    np.testing.assert_almost_equal(rmse.get()[1], 0.1, decimal=5)


def test_nll_loss_metric():
    """reference test_nll_loss."""
    metric = mx.metric.create("nll_loss")
    pred = mx.nd.array([[0.2, 0.3, 0.5], [0.6, 0.1, 0.3]])
    label = mx.nd.array([2, 1])
    metric.update([label], [pred])
    _, loss = metric.get()
    want = -(np.log(0.5) + np.log(0.1)) / 2
    np.testing.assert_almost_equal(loss, want, decimal=5)


def test_pcc_matches_mcc_on_binary():
    """reference test_pcc: PCC reduces to MCC for binary problems."""
    pred = mx.nd.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4], [0.3, 0.7]])
    label = mx.nd.array([0, 1, 1, 1])
    pcc = mx.metric.create("pcc")
    pcc.update([label], [pred])
    mcc = mx.metric.create("mcc")
    mcc.update([label], [pred])
    np.testing.assert_almost_equal(pcc.get()[1], mcc.get()[1], decimal=6)


def test_pcc_multiclass_and_global():
    """PCC on a 3-class problem with local/global split."""
    pcc = mx.metric.create("pcc")
    pred = mx.nd.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1],
                        [0.2, 0.2, 0.6], [0.5, 0.4, 0.1]])
    label = mx.nd.array([0, 1, 2, 1])
    pcc.update([label], [pred])
    name, v = pcc.get()
    assert name == "pcc" and np.isfinite(v) and 0 < v <= 1
    pcc.reset_local()
    _, g = pcc.get_global()
    np.testing.assert_almost_equal(g, v, decimal=6)
