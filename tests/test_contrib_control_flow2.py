"""Deep control-flow tranche (VERDICT r4 item 4): ports the reference's
``tests/python/unittest/test_contrib_control_flow.py`` inventory — nested
while/foreach, gradients through control flow (incl. free-variable
captures), RNN-cell bodies, imperative↔symbolic agreement, output-format
corner cases, and subgraph-cut uniqueness — onto the lax.scan/while/cond
lowering.  Numpy references are computed inline; symbolic and imperative
paths must agree with them and each other.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _arr(shape, seed):
    return mx.nd.array(np.random.RandomState(seed).uniform(
        -1.0, 1.0, size=shape).astype("float32"))


# --------------------------------------------------------------- while_loop
def test_while_loop_forward_cases():
    """Reference test_while_loop_simple_forward's four case families."""
    # early termination by cond
    out, (ri, rs) = mx.nd.contrib.while_loop(
        cond=lambda i, s: i <= 5,
        func=lambda i, s: (None, (i + 1, s + i)),
        loop_vars=(mx.nd.array([1], dtype="int64"),
                   mx.nd.array([0], dtype="int64")),
        max_iterations=10)
    assert out is None
    assert ri.asscalar() == 6 and rs.asscalar() == 15
    # cap by max_iterations (cond always true)
    out, (ri, rs, rt) = mx.nd.contrib.while_loop(
        cond=lambda i, s, true: true,
        func=lambda i, s, true: (None, (i + 1, s + i, true)),
        loop_vars=(mx.nd.array([1], dtype="int64"),
                   mx.nd.array([0], dtype="int64"),
                   mx.nd.array([1], dtype="int64")),
        max_iterations=1000)
    assert ri.asscalar() == 1001 and rs.asscalar() == 500500
    assert rt.asscalar() == 1
    # zero iterations (cond false at entry)
    out, (ri, rs, rf) = mx.nd.contrib.while_loop(
        cond=lambda i, s, false: false,
        func=lambda i, s, false: (None, (i + 1, s + i, false)),
        loop_vars=(mx.nd.array([1], dtype="int64"),
                   mx.nd.array([0], dtype="int64"),
                   mx.nd.array([0], dtype="int64")),
        max_iterations=1000)
    assert ri.asscalar() == 1 and rs.asscalar() == 0
    # stacked outputs + final states
    out, (ri, rs) = mx.nd.contrib.while_loop(
        cond=lambda i, s: i <= 100,
        func=lambda i, s: (i, (i + 1, s + i)),
        loop_vars=(mx.nd.array([1], dtype="int64"),
                   mx.nd.array([0], dtype="int64")),
        max_iterations=1000)
    assert (out.asnumpy()[:100].ravel() == np.arange(1, 101)).all()
    assert ri.asscalar() == 101 and rs.asscalar() == 5050


@pytest.mark.parametrize("step_func", [
    lambda a, b, s: a * 1.5 + b * 2.5 - s * 3.5,
    lambda a, b, s: a * 2.5 * b + s * 0.3,
    lambda a, b, s: s * 0.3 + 2.5 * b * a,
])
@pytest.mark.parametrize("is_train", [True, False])
def test_while_loop_for_foreach_with_free_vars(step_func, is_train):
    """Reference test_while_loop_for_foreach case_1: a for-style while loop
    whose body mixes loop state with two free variables; gradients reach
    the free variables (both ND and symbolic paths, checked vs numpy)."""
    n_steps = 4
    a_np = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype("float32")
    b_np = np.random.RandomState(2).uniform(-1, 1, (2, 3)).astype("float32")
    s_np = np.random.RandomState(3).uniform(-1, 1, (2, 3)).astype("float32")

    def np_forward():
        s = s_np.copy()
        outs = []
        for _ in range(n_steps):
            s = step_func(a_np, b_np, s)
            outs.append(s.copy())
        return np.stack(outs), s

    want_out, want_s = np_forward()

    # ND path: grads via autograd
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    i0 = mx.nd.array([0], dtype="int64")
    s0 = mx.nd.array(s_np)
    if is_train:
        a.attach_grad()
        b.attach_grad()
    with mx.autograd.record(train_mode=is_train):
        out, (fi, fs) = mx.nd.contrib.while_loop(
            cond=lambda i, s: i < n_steps,
            func=lambda i, s: (step_func(a, b, s), (i + 1, step_func(a, b, s))),
            loop_vars=(i0, s0), max_iterations=n_steps)
        loss = out.sum() + fs.sum()
    np.testing.assert_allclose(out.asnumpy(), want_out, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(fs.asnumpy(), want_s, rtol=1e-5, atol=1e-5)
    if not is_train:
        return
    loss.backward()

    # numeric grad of the same scalar loss w.r.t. a
    def scalar_loss(a_v):
        s = s_np.copy()
        tot = 0.0
        for _ in range(n_steps):
            s = step_func(a_v, b_np, s)
            tot += s.sum()
        return tot + s.sum()

    eps = 1e-3
    num = np.zeros_like(a_np)
    for idx in np.ndindex(a_np.shape):
        ap, am = a_np.copy(), a_np.copy()
        ap[idx] += eps
        am[idx] -= eps
        num[idx] = (scalar_loss(ap) - scalar_loss(am)) / (2 * eps)
    np.testing.assert_allclose(a.grad.asnumpy(), num, rtol=2e-2, atol=2e-2)


def test_while_loop_nested_imp_vs_sym():
    """Reference test_while_loop_nested: inner loop scans rows of a free
    tensor; outer loop re-runs it; imp and sym agree fwd+bwd."""
    sc_np = np.random.RandomState(0).uniform(
        -1, 1, (4, 5, 3)).astype("float32")

    def run_imp(is_train):
        sc = mx.nd.array(sc_np)
        if is_train:
            sc.attach_grad()

        def inner_body(i, j, acc):
            x_ij = sc[0] * 0 + mx.nd.take(sc, j.astype("float32")
                                          .astype("int64")
                                          .reshape(())) \
                if False else mx.nd.take(sc, j.reshape(()))
            return x_ij, (i, j + 1, acc + x_ij.sum())

        def outer_body(i, j, acc):
            out, (i2, j2, acc2) = mx.nd.contrib.while_loop(
                cond=lambda i, j, acc: j < 2,
                func=inner_body, loop_vars=(i, j, acc), max_iterations=2)
            return out, (i2 + 1, j2 - 2, acc2)

        with mx.autograd.record(train_mode=is_train):
            out, (fi, fj, facc) = mx.nd.contrib.while_loop(
                cond=lambda i, j, acc: i < 2,
                func=outer_body,
                loop_vars=(mx.nd.array([0], dtype="int64"),
                           mx.nd.array([0], dtype="int64"),
                           mx.nd.array([0.0])),
                max_iterations=2)
            loss = facc.sum()
        grads = None
        if is_train:
            loss.backward()
            grads = sc.grad.asnumpy()
        return fi.asscalar(), fj.asscalar(), float(facc.asscalar()), grads

    fi, fj, facc, grads = run_imp(True)
    assert fi == 2 and fj == 0
    # each outer iter scans rows 0,1 → acc = 2*(row0+row1).sum()
    want = 2 * (sc_np[0].sum() + sc_np[1].sum())
    np.testing.assert_allclose(facc, want, rtol=1e-5)
    want_g = np.zeros_like(sc_np)
    want_g[0] = 2.0
    want_g[1] = 2.0
    np.testing.assert_allclose(grads, want_g)
    fi2, fj2, facc2, _ = run_imp(False)
    np.testing.assert_allclose(facc2, facc, rtol=1e-6)


def test_while_loop_rnn_body_grads_to_params():
    """Reference test_while_loop_rnn: an RNN-style cell as loop body; the
    eager while tape reaches the cell parameters."""
    rng = np.random.RandomState(0)
    W = mx.nd.array(rng.randn(4, 4).astype("float32") * 0.3)
    U = mx.nd.array(rng.randn(4, 4).astype("float32") * 0.3)
    seq = mx.nd.array(rng.randn(5, 2, 4).astype("float32"))
    W.attach_grad()
    U.attach_grad()
    h0 = mx.nd.zeros((2, 4))
    with mx.autograd.record():
        out, (fi, fh) = mx.nd.contrib.while_loop(
            cond=lambda i, h: i < 5,
            func=lambda i, h: (
                mx.nd.tanh(mx.nd.dot(mx.nd.take(seq, i.reshape(())), W)
                           + mx.nd.dot(h, U)),
                (i + 1,
                 mx.nd.tanh(mx.nd.dot(mx.nd.take(seq, i.reshape(())), W)
                            + mx.nd.dot(h, U)))),
            loop_vars=(mx.nd.array([0], dtype="int64"), h0),
            max_iterations=5)
        loss = fh.sum()
    loss.backward()
    # numpy forward + numeric grad spot-check on one coordinate
    def np_loss(Wv):
        h = np.zeros((2, 4), "float32")
        s = seq.asnumpy()
        for t in range(5):
            h = np.tanh(s[t] @ Wv + h @ U.asnumpy())
        return h.sum()
    eps = 1e-3
    Wn = W.asnumpy()
    for idx in [(0, 0), (2, 3)]:
        wp, wm = Wn.copy(), Wn.copy()
        wp[idx] += eps
        wm[idx] -= eps
        num = (np_loss(wp) - np_loss(wm)) / (2 * eps)
        np.testing.assert_allclose(W.grad.asnumpy()[idx], num,
                                   rtol=3e-2, atol=3e-2)
    assert float(np.abs(U.grad.asnumpy()).sum()) > 0


# ------------------------------------------------------------------ foreach
@pytest.mark.parametrize("free_in", ["out", "state", "both"])
@pytest.mark.parametrize("is_train", [True, False])
def test_foreach_free_var_placement(free_in, is_train):
    """Reference test_foreach's verify matrix: a free variable used in the
    step OUTPUT, the step STATE, or both — gradients reach it in every
    placement (the r4 capture fix; zero grads before)."""
    x_np = np.random.RandomState(0).randn(4, 2).astype("float32")
    w_np = np.random.RandomState(1).randn(2).astype("float32")
    x, w = mx.nd.array(x_np), mx.nd.array(w_np)
    if is_train:
        x.attach_grad()
        w.attach_grad()

    def step(d, states):
        s = states[0]
        if free_in == "out":
            return d * w, [s + d]
        if free_in == "state":
            return d, [s + d * w]
        return d * w, [s + d * w]

    with mx.autograd.record(train_mode=is_train):
        out, states = mx.nd.contrib.foreach(step, x, [mx.nd.zeros(2)])
        loss = out.sum() + states[0].sum()
    if not is_train:
        np_s = np.zeros(2, "float32")
        for t in range(4):
            if free_in == "out":
                np_s += x_np[t]
            elif free_in == "state":
                np_s += x_np[t] * w_np
            else:
                np_s += x_np[t] * w_np
        np.testing.assert_allclose(states[0].asnumpy(), np_s, rtol=1e-5)
        return
    loss.backward()
    colsum = x_np.sum(axis=0)
    if free_in == "out":
        want_w = colsum             # d(sum out)/dw
        want_x = np.tile(w_np + 1.0, (4, 1))
    elif free_in == "state":
        want_w = colsum
        want_x = np.tile(w_np + 1.0, (4, 1))
    else:
        want_w = 2 * colsum
        want_x = np.tile(2 * w_np, (4, 1))
    np.testing.assert_allclose(w.grad.asnumpy(), want_w, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), want_x, rtol=1e-5,
                               atol=1e-6)


def test_foreach_multiple_outputs_and_states():
    """Step returns two outputs and two states (reference verify with
    num_outputs=2/num_states=2)."""
    x = _arr((5, 3), 0)
    s1, s2 = mx.nd.zeros(3), mx.nd.ones(3)
    x.attach_grad()
    with mx.autograd.record():
        (o1, o2), (f1, f2) = mx.nd.contrib.foreach(
            lambda d, ss: ((d * 2, d + ss[1]), [ss[0] + d, ss[1] * 0.5]),
            x, [s1, s2])
        loss = o1.sum() + o2.sum() + f1.sum() + f2.sum()
    loss.backward()
    xn = x.asnumpy()
    np.testing.assert_allclose(o1.asnumpy(), xn * 2, rtol=1e-6)
    s2_t = np.ones(3, "float32")
    o2_want = []
    for t in range(5):
        o2_want.append(xn[t] + s2_t)
        s2_t = s2_t * 0.5
    np.testing.assert_allclose(o2.asnumpy(), np.stack(o2_want), rtol=1e-6)
    np.testing.assert_allclose(f1.asnumpy(), xn.sum(0), rtol=1e-5)
    # dloss/dx = 2 (o1) + 1 (o2) + 1 (f1 path) = 4 everywhere
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((5, 3), 4.0),
                               rtol=1e-5)


def test_foreach_nested():
    """Reference test_foreach_nested: foreach inside a foreach body; grads
    flow through both levels to data and a free variable."""
    x_np = np.arange(12, dtype="float32").reshape(2, 3, 2) / 10
    w_np = np.array([1.5, -0.5], dtype="float32")
    x, w = mx.nd.array(x_np), mx.nd.array(w_np)
    x.attach_grad()
    w.attach_grad()

    def inner_step(d, states):
        out = d * w
        return out, [states[0] + out]

    def outer_step(row, states):
        inner_out, inner_state = mx.nd.contrib.foreach(
            inner_step, row, [mx.nd.zeros(2)])
        return inner_out, [states[0] + inner_state[0]]

    with mx.autograd.record():
        out, states = mx.nd.contrib.foreach(outer_step, x, [mx.nd.zeros(2)])
        loss = states[0].sum()
    loss.backward()
    want_state = (x_np * w_np).sum(axis=(0, 1))
    np.testing.assert_allclose(states[0].asnumpy(), want_state, rtol=1e-5)
    np.testing.assert_allclose(out.asnumpy(), x_np * w_np, rtol=1e-5)
    np.testing.assert_allclose(w.grad.asnumpy(), x_np.sum(axis=(0, 1)),
                               rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.tile(w_np, (2, 3, 1)), rtol=1e-5)


def test_foreach_rnn_cell_params_get_grads():
    """Reference test_foreach_rnn: scanning a Gluon RNNCell trains — the
    cell parameters (free variables of the body) receive gradients."""
    cell = mx.gluon.rnn.RNNCell(8, input_size=4, prefix="fcell_")
    cell.initialize()
    x = _arr((6, 2, 4), 3)
    h0 = mx.nd.zeros((2, 8))
    params = {k: v.data() for k, v in cell.collect_params().items()}
    with mx.autograd.record():
        out, states = mx.nd.contrib.foreach(
            lambda d, s: cell(d, s), x, [h0])
        loss = out.sum()
    loss.backward()
    for name, arr in params.items():
        g = cell.collect_params()[name].grad()
        assert float(mx.nd.abs(g).sum().asscalar()) > 0, \
            f"no gradient reached {name}"
    # agrees with the explicit unroll on the same parameters
    outs2, _ = cell.unroll(6, x, begin_state=[h0], layout="TNC",
                           merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(), outs2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_foreach_state_only_and_empty_output_formats():
    """Reference test_output_format_foreach: a body may emit [] outputs
    (state-only scan) or a single output with list states."""
    x = _arr((4, 2), 1)
    out, states = mx.nd.contrib.foreach(
        lambda d, s: ([], [s[0] + d]), x, [mx.nd.zeros(2)])
    assert out == []
    np.testing.assert_allclose(states[0].asnumpy(),
                               x.asnumpy().sum(0), rtol=1e-5)
    # single out, single (non-list) state
    out, state = mx.nd.contrib.foreach(
        lambda d, s: (d * 2, s + d), x, mx.nd.zeros(2))
    assert not isinstance(state, list)
    np.testing.assert_allclose(state.asnumpy(), x.asnumpy().sum(0),
                               rtol=1e-5)


# --------------------------------------------------------------------- cond
def test_cond_grads_through_taken_branch():
    """Gradients flow through whichever branch is taken; the untaken
    branch contributes exactly zero (reference test_cond)."""
    for val, want_grad in [(3.0, 2.0), (-3.0, 1.0)]:
        x = mx.nd.array([val])
        x.attach_grad()
        with mx.autograd.record():
            out = mx.nd.contrib.cond(x.sum() > 0,
                                     lambda: x * 2, lambda: x + 1)
        out.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), [want_grad])


def test_sym_cond_inside_foreach_body():
    """Reference nesting case: a cond inside a foreach body (symbolic) —
    the subgraph cut must nest."""
    data = mx.sym.Variable("data")
    thr = mx.sym.Variable("thr")

    def step(d, states):
        gated = mx.sym.contrib.cond(
            (d.sum() > thr.sum()), lambda: d * 2, lambda: d * 0.5)
        return gated, [states[0] + gated]

    out, states = mx.sym.contrib.foreach(step, data, [mx.sym.zeros((2,))])
    g = mx.sym.Group([out, states[0]])
    ex = g.simple_bind(ctx=mx.cpu(), data=(3, 2), thr=(1,))
    ex.arg_dict["data"][:] = mx.nd.array([[2, 2], [-4, -4], [6, 6]])
    ex.arg_dict["thr"][:] = 1.0
    ex.forward()
    out_np, state_np = ex.outputs[0].asnumpy(), ex.outputs[1].asnumpy()
    np.testing.assert_allclose(out_np,
                               [[4, 4], [-2, -2], [12, 12]])
    np.testing.assert_allclose(state_np, [14, 14])


def test_sym_nested_while_in_foreach_json_roundtrip(tmp_path):
    """Two-level nesting + serialization: while_loop inside foreach body
    survives a JSON round-trip with identical execution (reference
    test_cut_subgraph_* + nested serialization)."""
    data = mx.sym.Variable("data")

    def step(d, states):
        out, (fi, acc) = mx.sym.contrib.while_loop(
            cond=lambda i, acc: i < 3,
            func=lambda i, acc: (None, (i + 1, acc + d)),
            loop_vars=(mx.sym.zeros((1,)), mx.sym.zeros((2,))),
            max_iterations=3)
        return acc, [states[0] + acc]

    out, states = mx.sym.contrib.foreach(step, data, [mx.sym.zeros((2,))])
    g = mx.sym.Group([out, states[0]])
    f = str(tmp_path / "nested-symbol.json")
    g.save(f)
    g2 = mx.sym.load(f)

    def run(sym):
        ex = sym.simple_bind(ctx=mx.cpu(), data=(4, 2))
        ex.arg_dict["data"][:] = mx.nd.arange(8).reshape(4, 2)
        ex.forward()
        return [o.asnumpy() for o in ex.outputs]

    a, b = run(g), run(g2)
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=1e-6)
    # each step accumulates 3*d; state = 3*sum(rows)
    np.testing.assert_allclose(
        a[1], 3 * np.arange(8).reshape(4, 2).sum(0), rtol=1e-6)


def test_sym_two_loops_unique_names():
    """Reference test_uniq_name/test_scope: two control-flow ops in one
    graph keep distinct subgraph variable names — binding and JSON
    round-trip don't collide."""
    data = mx.sym.Variable("data")
    o1, s1 = mx.sym.contrib.foreach(
        lambda d, s: (d * 2, [s[0] + d]), data, [mx.sym.zeros((2,))])
    o2, s2 = mx.sym.contrib.foreach(
        lambda d, s: (d * 3, [s[0] + d * d]), o1, [mx.sym.zeros((2,))])
    g = mx.sym.Group([o2, s1[0], s2[0]])
    js = g.tojson()
    g2 = mx.sym.load_json(js)
    ex = g2.simple_bind(ctx=mx.cpu(), data=(3, 2))
    ex.arg_dict["data"][:] = 1.0
    ex.forward()
    o2n, s1n, s2n = [o.asnumpy() for o in ex.outputs]
    np.testing.assert_allclose(o2n, np.full((3, 2), 6.0))
    np.testing.assert_allclose(s1n, [3.0, 3.0])
    np.testing.assert_allclose(s2n, [12.0, 12.0])


def test_sym_while_loop_grad_through_free_symbol():
    """A free symbol captured by the while body gets the summed gradient
    over active iterations only (reference while-loop grad matrix)."""
    v = mx.sym.Variable("v")
    w = mx.sym.Variable("w")
    outs, fvars = mx.sym.contrib.while_loop(
        cond=lambda i, s: i < 3,
        func=lambda i, s: (None, (i + 1, s + w * w)),
        loop_vars=(mx.sym.zeros((1,)), v),
        max_iterations=5)
    loss = mx.sym.sum(fvars[1])
    ex = loss.simple_bind(ctx=mx.cpu(), v=(2,), w=(2,), grad_req="write")
    ex.arg_dict["v"][:] = 0.0
    ex.arg_dict["w"][:] = mx.nd.array([2.0, -1.0])
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [3 * 5.0])
    ex.backward()
    # d/dw [3 * w^2] = 6w — only 3 of the 5 padded iterations are active
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), [12.0, -6.0],
                               rtol=1e-5)


def test_foreach_with_unknown_dim_raises_cleanly():
    """Reference test_foreach_with_unkown_dim: scanning needs a concrete
    leading axis — a deferred-shape symbolic bind must fail loudly, not
    produce garbage."""
    data = mx.sym.Variable("data")
    out, states = mx.sym.contrib.foreach(
        lambda d, s: (d * 2, [s[0] + d]), data, [mx.sym.zeros((2,))])
    with pytest.raises((ValueError, TypeError, RuntimeError)):
        out.simple_bind(ctx=mx.cpu())        # no data shape given
