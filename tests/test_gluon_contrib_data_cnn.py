"""gluon.contrib.data (WikiText2/103, IntervalSampler — reference
``python/mxnet/gluon/contrib/data/{text,sampler}.py``) and
gluon.contrib.cnn (DeformableConvolution layer — reference
``python/mxnet/gluon/contrib/cnn/conv_layers.py:30``)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.contrib.data import (IntervalSampler, WikiText2,
                                          WikiText103)


# ------------------------------------------------------------- sampler

def test_interval_sampler_rollover():
    """Doctest case from the reference sampler.py."""
    assert list(IntervalSampler(13, interval=3)) == \
        [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]


def test_interval_sampler_no_rollover():
    assert list(IntervalSampler(13, interval=3, rollover=False)) == \
        [0, 3, 6, 9, 12]


def test_interval_sampler_covers_all_and_len():
    s = IntervalSampler(10, interval=4)
    assert sorted(s) == list(range(10))
    assert len(s) == 10
    with pytest.raises(AssertionError):
        IntervalSampler(3, interval=5)


def test_interval_sampler_in_dataloader():
    data = gluon.data.ArrayDataset(mx.nd.arange(12).reshape(12, 1))
    loader = gluon.data.DataLoader(
        data, batch_size=4, sampler=IntervalSampler(12, interval=3))
    batches = [b.asnumpy().ravel().tolist() for b in loader]
    assert batches[0] == [0.0, 3.0, 6.0, 9.0]
    assert sorted(x for b in batches for x in b) == [float(i)
                                                    for i in range(12)]


# ---------------------------------------------------------------- text

_TRAIN = """
 = Heading =

 the quick brown fox jumps over the lazy dog
 the dog sleeps
 a fox runs
""".strip("\n")

_VALID = " the fox sleeps\n the dog runs\n"


@pytest.fixture()
def wikitext_root(tmp_path):
    (tmp_path / "wiki.train.tokens").write_text(_TRAIN, encoding="utf8")
    (tmp_path / "wiki.valid.tokens").write_text(_VALID, encoding="utf8")
    return str(tmp_path)


def test_wikitext2_windows_and_vocab(wikitext_root):
    ds = WikiText2(root=wikitext_root, segment="train", seq_len=5)
    assert len(ds) >= 2
    data, label = ds[0]
    assert data.shape == (5,) and label.shape == (5,)
    assert data.dtype == np.int32
    # label is data shifted by one token
    d_all = np.concatenate([ds[i][0].asnumpy() for i in range(len(ds))])
    l_all = np.concatenate([ds[i][1].asnumpy() for i in range(len(ds))])
    np.testing.assert_array_equal(d_all[1:], l_all[:-1])
    # vocab: built with <eos> reserved, 'the' indexed, unknown at 0
    vocab = ds.vocabulary
    assert "<eos>" in vocab.token_to_idx
    assert "the" in vocab.token_to_idx
    assert ds.frequencies["the"] == 3
    # every line break contributed an <eos>
    eos = vocab.token_to_idx["<eos>"]
    assert (np.concatenate([d_all, l_all[-1:]]) == eos).sum() >= 3


def test_wikitext2_shared_vocab_across_segments(wikitext_root):
    train = WikiText2(root=wikitext_root, segment="train", seq_len=4)
    valid = WikiText2(root=wikitext_root, segment="validation",
                      vocab=train.vocabulary, seq_len=4)
    assert valid.vocabulary is train.vocabulary
    tok = train.vocabulary.token_to_idx
    d, _ = valid[0]
    decoded = [train.vocabulary.idx_to_token[i]
               for i in d.asnumpy().tolist()]
    assert decoded[0] == "the" and tok["the"] == d.asnumpy()[0]


def test_wikitext_missing_file_raises(tmp_path):
    with pytest.raises(OSError, match="wiki.train.tokens"):
        WikiText2(root=str(tmp_path), segment="train")
    with pytest.raises(ValueError, match="segment"):
        WikiText2(root=str(tmp_path), segment="dev")


def test_wikitext103_reads_same_layout(wikitext_root):
    ds = WikiText103(root=wikitext_root, segment="train", seq_len=3)
    assert len(ds) >= 4
    d, l = ds[1]
    assert d.shape == (3,) and l.shape == (3,)


def test_wikitext_dataloader_batches(wikitext_root):
    ds = WikiText2(root=wikitext_root, segment="train", seq_len=4)
    loader = gluon.data.DataLoader(ds, batch_size=2)
    d, l = next(iter(loader))
    assert d.shape == (2, 4) and l.shape == (2, 4)


# -------------------------------------------------- DeformableConvolution

def test_deformable_layer_zero_offset_matches_conv2d():
    """Freshly-initialised offsets are zero, so the layer must equal an
    ordinary Conv2D with the same weights."""
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype("float32"))

    layer = DeformableConvolution(4, kernel_size=3, padding=1,
                                  in_channels=3)
    layer.initialize()
    out = layer(x)
    assert out.shape == (2, 4, 8, 8)

    conv = gluon.nn.Conv2D(4, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    conv.weight.set_data(layer.deformable_conv_weight.data())
    conv.bias.set_data(layer.deformable_conv_bias.data())
    np.testing.assert_allclose(out.asnumpy(), conv(x).asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_deformable_layer_matches_raw_op():
    """Layer output == offset conv + raw op invocation."""
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(1, 2, 6, 6).astype("float32"))
    layer = DeformableConvolution(3, kernel_size=3, padding=1,
                                  in_channels=2)
    layer.initialize()
    # give the offset branch non-trivial weights
    layer.offset_weight.set_data(mx.nd.array(
        0.1 * rng.randn(*layer.offset_weight.shape).astype("float32")))
    out = layer(x)

    offset = mx.nd.Convolution(
        x, layer.offset_weight.data(), layer.offset_bias.data(),
        kernel=(3, 3), stride=(1, 1), pad=(1, 1), dilate=(1, 1),
        num_filter=18, num_group=1)
    ref = mx.nd.contrib.DeformableConvolution(
        x, offset, layer.deformable_conv_weight.data(),
        layer.deformable_conv_bias.data(), kernel=(3, 3), stride=(1, 1),
        pad=(1, 1), dilate=(1, 1), num_filter=3, num_group=1,
        num_deformable_group=1)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_deformable_layer_deferred_init_and_hybridize():
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    rng = np.random.RandomState(2)
    x = mx.nd.array(rng.randn(2, 5, 7, 7).astype("float32"))
    layer = DeformableConvolution(4, kernel_size=3, padding=1)
    layer.initialize()
    eager = layer(x)                       # in_channels inferred = 5
    assert layer.deformable_conv_weight.shape == (4, 5, 3, 3)
    assert layer.offset_weight.shape == (18, 5, 3, 3)
    layer.hybridize()
    np.testing.assert_allclose(layer(x).asnumpy(), eager.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_deformable_layer_trains():
    """Offsets receive gradients and a step changes the output."""
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    from mxnet_tpu import autograd
    rng = np.random.RandomState(3)
    x = mx.nd.array(rng.randn(2, 3, 6, 6).astype("float32"))
    layer = DeformableConvolution(2, kernel_size=3, padding=1,
                                  in_channels=3,
                                  offset_weight_initializer="uniform")
    layer.initialize()
    trainer = gluon.Trainer(layer.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    with autograd.record():
        loss = (layer(x) ** 2).mean()
    loss.backward()
    g = layer.offset_weight.grad()
    assert float(mx.nd.norm(g).asscalar()) > 0
    before = layer(x).asnumpy()
    trainer.step(1)
    assert np.abs(layer(x).asnumpy() - before).max() > 0


def test_deformable_layer_param_names_match_reference():
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    layer = DeformableConvolution(2, kernel_size=1, in_channels=2)
    names = sorted(p.split("_", 1)[1] if p.startswith("deformableconvolution")
                   else p for p in layer.collect_params().keys())
    joined = " ".join(names)
    for want in ("offset_weight", "offset_bias", "deformable_conv_weight",
                 "deformable_conv_bias"):
        assert want in joined, (want, names)


def test_interval_sampler_len_no_rollover():
    """len() reports the actual yield count (fixes the reference's
    overstated __len__ with rollover=False)."""
    s = IntervalSampler(12, interval=3, rollover=False)
    assert len(s) == len(list(s)) == 4
    s13 = IntervalSampler(13, interval=3, rollover=False)
    assert len(s13) == len(list(s13)) == 5


def test_deformable_groups_and_offset_groups():
    """num_deformable_group=2: each channel half follows its own offset
    field; num_group=2: grouped weights work (op-level parity with the
    reference's deformable_convolution.cc group handling)."""
    rng = np.random.RandomState(5)
    x = mx.nd.array(rng.randn(1, 4, 8, 8).astype("float32"))
    w = mx.nd.array(np.zeros((4, 4, 1, 1), "float32"))
    for i in range(4):
        w[i, i, 0, 0] = 1.0                 # identity 1x1 conv
    # group 0 offsets: zero; group 1 offsets: shift sampling down 1 row
    offset = np.zeros((1, 2 * 2 * 1 * 1, 8, 8), "float32")
    offset[:, 2] = 1.0                      # ndg=1 slot: dy of group 1
    out = mx.nd.contrib.DeformableConvolution(
        x, mx.nd.array(offset), w, kernel=(1, 1), num_filter=4,
        num_deformable_group=2, no_bias=True)
    xn = x.asnumpy()
    # channels 0-1 unshifted, channels 2-3 sample one row down
    np.testing.assert_allclose(out.asnumpy()[0, :2], xn[0, :2], atol=1e-5)
    np.testing.assert_allclose(out.asnumpy()[0, 2:, :7], xn[0, 2:, 1:],
                               atol=1e-5)

    # grouped weights: 2 groups of 2-in/2-out == two independent convs
    wg = mx.nd.array(rng.randn(4, 2, 3, 3).astype("float32"))
    off0 = mx.nd.zeros((1, 18, 6, 6))
    outg = mx.nd.contrib.DeformableConvolution(
        x, off0, wg, kernel=(3, 3), num_filter=4, num_group=2,
        no_bias=True)
    refg = mx.nd.Convolution(x, wg, kernel=(3, 3), num_filter=4,
                             num_group=2, no_bias=True)
    np.testing.assert_allclose(outg.asnumpy(), refg.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_deformable_layer_ndg2_trains_both_offset_groups():
    """Layer with num_deformable_group=2: gradients reach the offsets of
    BOTH groups (regression: group-1 offsets used to be ignored)."""
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    from mxnet_tpu import autograd
    rng = np.random.RandomState(6)
    x = mx.nd.array(rng.randn(2, 4, 6, 6).astype("float32"))
    layer = DeformableConvolution(4, kernel_size=3, padding=1,
                                  in_channels=4, num_deformable_group=2,
                                  offset_weight_initializer="uniform")
    layer.initialize()
    with autograd.record():
        loss = (layer(x) ** 2).mean()
    loss.backward()
    g = layer.offset_weight.grad().asnumpy()     # (36, 4, 3, 3)
    assert np.abs(g[:18]).max() > 0              # group 0
    assert np.abs(g[18:]).max() > 0              # group 1
