"""Worker script for the multi-process dist kvstore test (the analog of
``tests/nightly/dist_sync_kvstore.py`` — run via tools/launch.py)."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]))

import numpy as np
import mxnet_tpu as mx


def main():
    kv = mx.kv.create("dist_sync")
    rank = kv.rank
    nw = kv.num_workers
    assert nw == int(os.environ["JAX_NUM_PROCESSES"])

    shape = (4, 3)
    kv.init(7, mx.nd.zeros(shape))
    # every worker pushes (rank+1) * ones → store should hold sum = nw(nw+1)/2
    kv.push(7, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.empty(shape)
    kv.pull(7, out=out)
    expected = nw * (nw + 1) / 2
    got = float(out.asnumpy().mean())
    assert got == expected, (got, expected)
    kv.barrier()
    print(f"WORKER_OK rank={rank} sum={got}")


if __name__ == "__main__":
    main()
