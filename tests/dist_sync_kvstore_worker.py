"""Worker script for the multi-process dist kvstore test (the analog of
``tests/nightly/dist_sync_kvstore.py`` — run via tools/launch.py)."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]))

import numpy as np
import mxnet_tpu as mx


def main():
    kv = mx.kv.create("dist_sync")
    rank = kv.rank
    nw = kv.num_workers
    assert nw == int(os.environ["JAX_NUM_PROCESSES"])

    shape = (4, 3)
    kv.init(7, mx.nd.zeros(shape))
    # every worker pushes (rank+1) * ones → store should hold sum = nw(nw+1)/2
    kv.push(7, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.empty(shape)
    kv.pull(7, out=out)
    expected = nw * (nw + 1) / 2
    got = float(out.asnumpy().mean())
    assert got == expected, (got, expected)
    kv.barrier()
    print(f"WORKER_OK rank={rank} sum={got}")

    # ---- Module.fit over dist_sync: the BASELINE config-5 API path
    # (reference example/image-classification with kvstore='dist_device_sync'
    # — each worker trains its shard, gradients sync through the kvstore,
    # weights must remain bit-identical across workers) ----
    mx.random.seed(5)                       # same init on every worker
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, name="fc", num_hidden=2),
        name="softmax")
    centers = np.asarray([[2.0] * 4, [-2.0] * 4], dtype="float32")
    rng = np.random.RandomState(100 + rank)  # a DIFFERENT shard per worker
    y = rng.randint(0, 2, 64).astype("float32")
    x = centers[y.astype(int)] + rng.randn(64, 4).astype("float32") * 0.3
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    w = mod.get_params()[0]["fc_weight"].asnumpy()
    # compare weights across workers; NOT through kvstore keys — after
    # mod.fit this store runs its server-side optimizer on every push
    # (update_on_kvstore=True, reference module.py:480), so a plain-sum
    # push no longer exists on it
    from jax.experimental import multihost_utils
    allw = np.asarray(multihost_utils.process_allgather(w))
    for r in range(nw):
        assert np.allclose(allw[r], w, atol=1e-5), \
            f"rank {rank}: weights diverged from rank {r}"
    acc = mod.score(mx.io.NDArrayIter(x, y, batch_size=16), "acc")[0][1]
    assert acc > 0.9, acc
    kv.barrier()
    print(f"MODULE_DIST_OK rank={rank} acc={acc:.3f}")


if __name__ == "__main__":
    main()
