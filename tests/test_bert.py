"""BERT model tests (BASELINE config 3: pretraining step, hybridize,
SPMD)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import get_bert_model, BERTClassifier


def _inputs(b=2, t=16, vocab=100, masked=3, seed=0):
    rng = np.random.RandomState(seed)
    tokens = mx.nd.array(rng.randint(0, vocab, (b, t)), dtype="int32")
    segments = mx.nd.array(rng.randint(0, 2, (b, t)), dtype="int32")
    mask = mx.nd.array((rng.rand(b, t) > 0.1).astype("float32"))
    positions = mx.nd.array(rng.randint(0, t, (b, masked)), dtype="int32")
    return tokens, segments, mask, positions


def test_bert_forward_shapes():
    net = get_bert_model("bert_tiny", vocab_size=100, max_length=32)
    net.initialize()
    tokens, segments, mask, positions = _inputs()
    seq, pooled, mlm, nsp = net(tokens, segments, mask, positions)
    assert seq.shape == (2, 16, 128)
    assert pooled.shape == (2, 128)
    assert mlm.shape == (2, 3, 100)
    assert nsp.shape == (2, 2)


def test_bert_hybridize_matches_eager():
    net = get_bert_model("bert_tiny", vocab_size=50, max_length=32,
                         dropout=0.0)
    net.initialize()
    tokens, segments, mask, positions = _inputs(vocab=50)
    seq_e, pooled_e, mlm_e, nsp_e = net(tokens, segments, mask, positions)
    net.hybridize()
    seq_h, pooled_h, mlm_h, nsp_h = net(tokens, segments, mask, positions)
    np.testing.assert_allclose(seq_e.asnumpy(), seq_h.asnumpy(), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(mlm_e.asnumpy(), mlm_h.asnumpy(), rtol=2e-4,
                               atol=2e-5)


def test_bert_mask_zeroes_padded_attention():
    """Fully-masked key positions must not influence outputs."""
    net = get_bert_model("bert_tiny", vocab_size=50, max_length=32,
                         dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(0)
    tok = rng.randint(0, 50, (1, 8))
    tokens = mx.nd.array(tok, dtype="int32")
    mask = mx.nd.array(np.array([[1, 1, 1, 1, 0, 0, 0, 0]], dtype="float32"))
    seq1 = net(tokens, None, mask)[0].asnumpy()
    tok2 = tok.copy()
    tok2[0, 4:] = rng.randint(0, 50, 4)  # change only padded tokens
    seq2 = net(mx.nd.array(tok2, dtype="int32"), None, mask)[0].asnumpy()
    np.testing.assert_allclose(seq1[:, :4], seq2[:, :4], rtol=1e-4,
                               atol=1e-5)


def test_bert_pretraining_step_converges():
    """MLM+NSP loss decreases over a few steps on a fixed batch."""
    vocab = 64
    net = get_bert_model("bert_tiny", vocab_size=vocab, max_length=32,
                         dropout=0.0)
    net.initialize()
    tokens, segments, mask, positions = _inputs(b=4, t=12, vocab=vocab,
                                                masked=4)
    rng = np.random.RandomState(1)
    mlm_labels = mx.nd.array(rng.randint(0, vocab, (4, 4)), dtype="float32")
    nsp_labels = mx.nd.array(rng.randint(0, 2, (4,)), dtype="float32")
    sce = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3})
    losses = []
    for _ in range(15):
        with mx.autograd.record():
            _, _, mlm, nsp = net(tokens, segments, mask, positions)
            l = sce(mlm.reshape((-1, vocab)),
                    mlm_labels.reshape((-1,))).mean() + \
                sce(nsp, nsp_labels).mean()
        l.backward()
        trainer.step(4)
        losses.append(float(l.asscalar()))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_bert_classifier():
    bert = get_bert_model("bert_tiny", vocab_size=50, max_length=32)
    net = BERTClassifier(bert, num_classes=3)
    net.initialize()
    tokens, segments, mask, _ = _inputs(vocab=50)
    out = net(tokens, segments, mask)
    assert out.shape == (2, 3)


def test_bert_spmd_train_step():
    """SPMD fused step over dp×tp mesh (the config-3 distributed path)."""
    from mxnet_tpu.parallel import SPMDTrainer, FunctionalOptimizer, make_mesh
    vocab = 32
    net = get_bert_model("bert_tiny", vocab_size=vocab, max_length=16,
                         dropout=0.0, use_decoder=False, use_classifier=False,
                         use_pooler=True)
    net.initialize()
    rng = np.random.RandomState(0)
    x = rng.randint(0, vocab, (8, 8)).astype("int32")
    y = rng.randint(0, 2, (8,)).astype("float32")

    class WithHead(mx.gluon.Block):
        def __init__(self, bert):
            super().__init__()
            self.bert = bert
            self.head = mx.gluon.nn.Dense(2)

        def forward(self, tokens):
            _, pooled = self.bert(tokens)
            return self.head(pooled)

    model = WithHead(net)
    model.initialize()
    model(mx.nd.array(x, dtype="int32"))  # materialize deferred params
    mesh = make_mesh(dp=4, tp=2)
    spmd = SPMDTrainer(model, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer("adam", 1e-3), mesh)
    l1 = float(spmd.step(x, y).asnumpy())
    l2 = float(spmd.step(x, y).asnumpy())
    assert np.isfinite(l1) and np.isfinite(l2)


def test_bert_sequence_parallel_matches_dp():
    """sp-sharded ring attention inside the fused step == plain dp run."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import SPMDTrainer, FunctionalOptimizer, make_mesh
    vocab, T = 32, 16
    rng = np.random.RandomState(0)
    x = rng.randint(0, vocab, (8, T)).astype("int32")
    y = rng.randint(0, 2, (8,)).astype("float32")

    def build():
        mx.random.seed(7)
        np.random.seed(7)
        net = get_bert_model("bert_tiny", vocab_size=vocab, max_length=T,
                             dropout=0.0, use_decoder=False,
                             use_classifier=False)

        class WithHead(mx.gluon.Block):
            def __init__(self, bert):
                super().__init__()
                self.bert = bert
                self.head = mx.gluon.nn.Dense(2)

            def forward(self, tokens):
                _, pooled = self.bert(tokens)
                return self.head(pooled)

        model = WithHead(net)
        model.initialize()
        model(mx.nd.array(x, dtype="int32"))
        return model

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    m1 = build()
    dp_tr = SPMDTrainer(m1, loss_fn, FunctionalOptimizer("sgd", 0.1),
                        make_mesh(dp=8))
    m2 = build()
    sp_tr = SPMDTrainer(m2, loss_fn, FunctionalOptimizer("sgd", 0.1),
                        make_mesh(dp=2, sp=4), sequence_parallel=True,
                        data_spec=P("dp", "sp"))
    l1 = [float(dp_tr.step(x, y).asnumpy()) for _ in range(3)]
    l2 = [float(sp_tr.step(x, y).asnumpy()) for _ in range(3)]
    np.testing.assert_allclose(l2, l1, rtol=2e-4, atol=2e-5)
    # Ulysses (all_to_all head-sharded) SP must match too — bert_tiny has 2
    # heads, so sp=2 divides them exactly
    m3 = build()
    ul_tr = SPMDTrainer(m3, loss_fn, FunctionalOptimizer("sgd", 0.1),
                        make_mesh(dp=4, sp=2), sequence_parallel=True,
                        sp_impl="ulysses", data_spec=P("dp", "sp"))
    l3 = [float(ul_tr.step(x, y).asnumpy()) for _ in range(3)]
    np.testing.assert_allclose(l3, l1, rtol=2e-4, atol=2e-5)


def test_bert_symbol_export_roundtrip(tmp_path):
    """BERT is shape-polymorphic enough to trace symbolically: hybridize →
    export (dual-file checkpoint) → load → bind → identical outputs
    (the deployment path reference users take through gluon export)."""
    mx.random.seed(0)
    net = get_bert_model("bert_tiny", vocab_size=50, max_length=32,
                         dropout=0.0)
    net.initialize()
    tokens, segments, mask, positions = _inputs(vocab=50)
    net.hybridize()
    ref = [o.asnumpy() for o in net(tokens, segments, mask, positions)]
    prefix = str(tmp_path / "bt")
    net.export(prefix)
    sym = mx.sym.load(prefix + "-symbol.json")
    loaded = mx.nd.load(prefix + "-0000.params")
    args = {k.split(":", 1)[1]: v for k, v in loaded.items()
            if k.startswith("arg:")}
    auxs = {k.split(":", 1)[1]: v for k, v in loaded.items()
            if k.startswith("aux:")}
    ins = [a for a in sym.list_arguments() if a not in args]
    feeds = dict(zip(ins, [tokens, segments, mask, positions]))
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null",
                         **{k: v.shape for k, v in feeds.items()})
    ex.copy_params_from(args, auxs, allow_extra_params=True)
    outs = [o.asnumpy() for o in ex.forward(is_train=False, **feeds)]
    for a, b in zip(ref, outs):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
