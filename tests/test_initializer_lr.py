"""Initializer + lr_scheduler tests (reference ``test_init.py`` and the
lr_scheduler unit tests inside ``test_optimizer.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _materialize(init, shape, name="fc1_weight"):
    arr = mx.nd.zeros(shape)
    init(mx.init.InitDesc(name), arr)
    return arr.asnumpy()


def test_constant_zero_one():
    assert _materialize(mx.init.Zero(), (3, 3)).sum() == 0
    assert (_materialize(mx.init.One(), (3, 3)) == 1).all()
    assert (_materialize(mx.init.Constant(2.5), (2, 2)) == 2.5).all()


def test_uniform_normal_ranges():
    u = _materialize(mx.init.Uniform(0.3), (200, 50))
    assert np.abs(u).max() <= 0.3 + 1e-6
    n = _materialize(mx.init.Normal(0.1), (200, 50))
    assert 0.05 < n.std() < 0.15


def test_xavier_magnitude():
    w = _materialize(mx.init.Xavier(factor_type="avg", magnitude=3), (64, 32))
    bound = np.sqrt(3.0 * 2 / (64 + 32))
    assert np.abs(w).max() <= bound + 1e-6
    assert np.abs(w).std() > bound / 4


def test_orthogonal_is_orthogonal():
    w = _materialize(mx.init.Orthogonal(scale=1.0), (32, 32))
    eye = w @ w.T
    np.testing.assert_allclose(eye, np.eye(32), atol=1e-4)


def test_msra_prelu():
    w = _materialize(mx.init.MSRAPrelu(), (64, 32))
    assert np.isfinite(w).all() and w.std() > 0


def test_name_based_dispatch():
    """Initializer.__call__ dispatches by name suffix (gamma→1, bias→0...)"""
    init = mx.init.Uniform(0.1)
    gamma = mx.nd.zeros((8,))
    init(mx.init.InitDesc("bn0_gamma"), gamma)
    assert (gamma.asnumpy() == 1).all()
    bias = mx.nd.ones((8,))
    init(mx.init.InitDesc("fc0_bias"), bias)
    assert (bias.asnumpy() == 0).all()


def test_mixed_initializer():
    init = mx.init.Mixed([".*bias", ".*"], [mx.init.Zero(),
                                            mx.init.Constant(3)])
    b = mx.nd.ones((4,))
    init(mx.init.InitDesc("fc_bias_custom"), b)
    # Mixed patterns apply in order; plain weight gets the constant
    w = mx.nd.zeros((4,))
    init(mx.init.InitDesc("fc_weight_custom"), w)
    assert (w.asnumpy() == 3).all()


# ------------------------------------------------------------ lr schedulers
def test_factor_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                            base_lr=1.0, stop_factor_lr=0.1)
    assert sched(1) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25
    assert sched(100) >= 0.1 / 2  # clamped near stop_factor_lr


def test_multifactor_scheduler():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[10, 20], factor=0.1,
                                                 base_lr=1.0)
    assert sched(5) == 1.0
    assert abs(sched(15) - 0.1) < 1e-9
    assert abs(sched(25) - 0.01) < 1e-9


def test_poly_and_cosine_schedulers():
    poly = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0,
                                         final_lr=0.0)
    assert poly(0) == 1.0
    assert poly(100) == 0.0
    cos = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                          final_lr=0.0)
    assert abs(cos(0) - 1.0) < 1e-6
    assert abs(cos(100)) < 1e-6
    assert 0.4 < cos(50) < 0.6


def test_warmup():
    sched = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0,
                                          warmup_steps=10,
                                          warmup_begin_lr=0.0)
    assert sched(0) < sched(5) < sched(10)
    assert abs(sched(10) - 1.0) < 0.15


def test_optimizer_uses_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5, base_lr=0.8)
    opt = mx.optimizer.SGD(lr_scheduler=sched)
    w = mx.nd.ones((2,))
    g = mx.nd.ones((2,))
    state = opt.create_state(0, w)
    opt.update(0, w, g, state)
    lr1 = float(1 - w.asnumpy()[0])  # effective lr of first step
    assert lr1 > 0


# --- r4 depth: reference test_init.py remainder

def test_variable_init_attr():
    """reference test_variable_init: a Variable's init attr drives its
    initialization through simple_bind."""
    import json
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("myweight", init=mx.init.One(),
                        shape=(10, 5))
    net = mx.sym.FullyConnected(data, weight=w, name="fc", num_hidden=10,
                                no_bias=True)
    ex = net.simple_bind(ctx=mx.cpu(), data=(3, 5))
    # simple_bind allocates zeros; init through an initializer honouring
    # the __init__ attr
    for name, arr in ex.arg_dict.items():
        desc = mx.init.InitDesc(name, {"__init__": "one"}
                                if name == "myweight" else {})
        if name != "data":
            mx.init.Uniform(0.1)(desc, arr)
    np.testing.assert_allclose(ex.arg_dict["myweight"].asnumpy(),
                               np.ones((10, 5)))


def test_bilinear_init_upsampling_kernel():
    """reference test_bilinear_init: 'upsampling*weight' params get the
    bilinear kernel by name dispatch."""
    arr = mx.nd.zeros((1, 1, 4, 4))
    mx.init.Initializer()(mx.init.InitDesc("upsampling0_weight"), arr)
    w = arr.asnumpy()[0, 0]
    want = np.array([[0.0625, 0.1875, 0.1875, 0.0625],
                     [0.1875, 0.5625, 0.5625, 0.1875],
                     [0.1875, 0.5625, 0.5625, 0.1875],
                     [0.0625, 0.1875, 0.1875, 0.0625]])
    np.testing.assert_allclose(w, want, rtol=1e-5)


def test_initializer_dumps_json_roundtrip():
    """Initializers serialize to JSON (reference Initializer.dumps)."""
    import json
    for init in (mx.init.Uniform(0.3), mx.init.Normal(0.1),
                 mx.init.Xavier(magnitude=2.5), mx.init.One()):
        s = init.dumps()
        name, kwargs = json.loads(s)
        rebuilt = mx.init.create(name, **kwargs)
        assert type(rebuilt) is type(init)


def test_constant_initializer_value():
    arr = mx.nd.zeros((3, 3))
    mx.init.Constant(2.5)._init_weight("w", arr)
    np.testing.assert_allclose(arr.asnumpy(), np.full((3, 3), 2.5))
