"""Random-stream contracts (reference ``tests/python/unittest/test_random.py``
seed/determinism family; the statistical tranche lives in
``test_random_statistics.py``)."""
import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import random as _rnd


def test_backward_key_pairing_survives_interleaved_eager_draw():
    """ADVICE r2: an eager stochastic op between an executor forward and
    its backward must not change the backward's recompute stream — the
    executor captures its forward key instead of re-querying."""
    mx.random.seed(77)
    data = mx.sym.var("data")
    d = mx.sym.Dropout(data, p=0.5, name="do")
    loss = mx.sym.MakeLoss(mx.sym.sum(d))
    x = mx.nd.ones((64,))
    ex = loss.bind(mx.cpu(), {"data": x},
                   args_grad={"data": mx.nd.zeros((64,))})
    out1 = ex.forward(is_train=True)[0].asnumpy()
    # interleaved eager draw advances the global stream
    _ = mx.nd.random.uniform(shape=(4,))
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    scale = 1.0 / 0.5
    kept = np.isclose(g, scale)
    dropped = np.isclose(g, 0.0)
    assert (kept | dropped).all()
    # backward replayed the SAME dropout mask the forward drew: the kept
    # count (scaled) reproduces the forward's sum exactly
    assert kept.sum() * scale == pytest.approx(float(out1), rel=1e-6)


def test_current_key_inside_traced_scope_is_scope_local():
    """current_key() inside a key_scope returns the scope's stream (and
    never leaks a tracer into the global eager state)."""
    mx.random.seed(3)
    k_eager_before = _rnd.current_key()
    seen = {}

    def f(key):
        with _rnd.key_scope(key):
            a = _rnd.next_key()
            seen["in_scope_last"] = _rnd.current_key() is a
        return jax.random.uniform(a)

    jax.jit(f)(jax.random.PRNGKey(0))
    assert seen["in_scope_last"]
    # global eager "last" untouched by the traced scope
    after = _rnd.current_key()
    assert np.array_equal(np.asarray(after), np.asarray(k_eager_before))
