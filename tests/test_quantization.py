"""Quantization tests (reference ``tests/python/quantization/``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as qz


def test_quantize_dequantize_roundtrip_int8():
    x = mx.nd.array(np.linspace(-3, 5, 64, dtype="float32").reshape(8, 8))
    q, mn, mx_ = mx.nd.contrib.quantize_v2(x, out_type="int8")
    assert q.dtype == np.int8
    back = mx.nd.contrib.dequantize(q, mn, mx_)
    # quantization error bounded by one step
    step = 5.0 / 127
    assert np.max(np.abs(back.asnumpy() - x.asnumpy())) <= step + 1e-6


def test_quantize_uint8_with_ranges():
    x = mx.nd.array(np.random.RandomState(0).rand(4, 4).astype("float32"))
    q, mn, mx_ = mx.nd.contrib.quantize(x, mx.nd.array([0.0]),
                                        mx.nd.array([1.0]),
                                        out_type="uint8")
    assert q.dtype == np.uint8
    back = mx.nd.contrib.dequantize(q, mn, mx_)
    assert np.max(np.abs(back.asnumpy() - x.asnumpy())) <= 1 / 255 + 1e-6


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_quantize_model_close_to_fp32():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype("float32")
    sym = _mlp()
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(x, np.zeros(64, "float32"), batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    fp32_out = mod.predict(it).asnumpy()

    qsym, qargs, qauxs = qz.quantize_model(
        sym, arg_params, aux_params, calib_mode="naive", calib_data=it,
        num_calib_examples=32)
    qmod = mx.mod.Module(qsym, context=mx.cpu())
    qmod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
              for_training=False)
    qmod.set_params(qargs, qauxs)
    int8_out = qmod.predict(it).asnumpy()
    # int8 inference tracks fp32 closely on this toy net
    assert np.max(np.abs(int8_out - fp32_out)) < 0.05
    assert (int8_out.argmax(1) == fp32_out.argmax(1)).mean() > 0.95


def test_quantize_model_excluded_layers():
    sym = _mlp()
    qsym = qz.quantize_graph(sym, {}, {}, excluded_sym_names=["fc1", "fc2"])
    names = [n.op.name for n in qsym._topo() if n.op is not None]
    assert "_contrib_quantize_v2" not in names  # everything excluded


def test_true_int8_fc_matches_fp32():
    """int8×int8→int32 kernel path (not fake-quant) tracks fp32."""
    rng = np.random.RandomState(1)
    x = rng.randn(4, 8).astype("float32")
    w = rng.randn(16, 8).astype("float32")
    qx, xmn, xmx = mx.nd.contrib.quantize_v2(mx.nd.array(x), out_type="int8")
    qw, wmn, wmx = mx.nd.contrib.quantize_v2(mx.nd.array(w), out_type="int8")
    qb, bmn, bmx = mx.nd.contrib.quantize_v2(mx.nd.zeros((16,)),
                                             out_type="int8")
    qo, omn, omx = mx.nd.contrib.quantized_fully_connected(
        qx, qw, qb, xmn, xmx, wmn, wmx, bmn, bmx, num_hidden=16,
        no_bias=True)
    out = mx.nd.contrib.dequantize(qo, omn, omx).asnumpy()
    ref = x @ w.T
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05


def test_true_int8_conv_matches_fp32():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")
    qx, xmn, xmx = mx.nd.contrib.quantize_v2(mx.nd.array(x), out_type="int8")
    qw, wmn, wmx = mx.nd.contrib.quantize_v2(mx.nd.array(w), out_type="int8")
    qb, bmn, bmx = mx.nd.contrib.quantize_v2(mx.nd.zeros((4,)),
                                             out_type="int8")
    qo, omn, omx = mx.nd.contrib.quantized_conv(
        qx, qw, qb, xmn, xmx, wmn, wmx, bmn, bmx, kernel=(3, 3),
        num_filter=4, pad=(1, 1), no_bias=True)
    out = mx.nd.contrib.dequantize(qo, omn, omx).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w),
                            mx.nd.zeros((4,)), kernel=(3, 3), num_filter=4,
                            pad=(1, 1)).asnumpy()
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.08


def test_entropy_calibration():
    """KL-threshold calibration clips outliers and stays accurate."""
    rng = np.random.RandomState(0)
    x = rng.randn(128, 8).astype("float32")
    x[0, 0] = 50.0  # a gross outlier naive calibration would absorb
    sym = _mlp()
    it = mx.io.NDArrayIter(x, np.zeros(128, "float32"), batch_size=32)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    fp32_out = mod.predict(it).asnumpy()

    qsym, qargs, qauxs = qz.quantize_model(
        sym, arg_params, aux_params, calib_mode="entropy", calib_data=it,
        num_calib_examples=128)
    qmod = mx.mod.Module(qsym, context=mx.cpu())
    qmod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
              for_training=False)
    qmod.set_params(qargs, qauxs)
    int8_out = qmod.predict(it).asnumpy()
    assert (int8_out.argmax(1) == fp32_out.argmax(1)).mean() > 0.9


def test_optimal_threshold_clips_outliers():
    rng = np.random.RandomState(0)
    vals = np.concatenate([rng.randn(100000), [60.0]])
    hist, edges = np.histogram(vals, bins=2048, range=(-60, 60))
    t = qz._optimal_threshold(hist, edges)
    assert t < 30  # the single outlier must not set the range


def test_threshold_keys_are_serializable_strings():
    """Calibration tables use stable '<name>#<dup>:<out_idx>' string keys
    (r4: the r3 id()-based keys could not be persisted and silently went
    stale across graph copies)."""
    import json
    rng = np.random.RandomState(2)
    x = rng.randn(32, 8).astype("float32")
    sym = _mlp()
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(x, np.zeros(32, "float32"), batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    th = qz._collect_thresholds(sym, arg_params, aux_params, it,
                                ["data"], 32, None, mode="naive")
    assert th and all(isinstance(k, str) for k in th)
    # round-trips through JSON and still applies to a fresh graph copy
    th2 = json.loads(json.dumps(th))
    qsym = qz.quantize_graph(_mlp(), arg_params, th2)
    names = [n.op.name for n in qsym._topo() if n.op is not None]
    assert "_contrib_quantize_v2" in names


def test_stale_threshold_table_fails_loudly():
    """A threshold table whose keys match nothing raises instead of
    silently skipping every fake-quant insertion."""
    sym = _mlp()
    with pytest.raises(ValueError, match="none of the .* threshold keys"):
        qz.quantize_graph(sym, {}, {"no_such_node:0": (0.0, 1.0)})


def test_fused_int8_lowering_mlp():
    """lower_int8_inference on the toy MLP: FC layers fuse to int8 dot
    kernels and the logits track fp32 (r4 fast path)."""
    rng = np.random.RandomState(3)
    x = rng.randn(64, 8).astype("float32")
    sym = _mlp()
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(x, np.zeros(64, "float32"), batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    fp32_out = mod.predict(it).asnumpy()

    qsym, qargs, qauxs = qz.quantize_model(
        sym, arg_params, aux_params, calib_mode="naive", calib_data=it,
        num_calib_examples=64, lowering="fused_int8")
    ops = [n.op.name for n in qsym._topo() if n.op is not None]
    assert ops.count("_contrib_int8_fc_fused") == 2, ops
    assert "FullyConnected" not in ops
    qmod = mx.mod.Module(qsym, context=mx.cpu())
    qmod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
              for_training=False)
    qmod.set_params(qargs, qauxs, allow_missing=False)
    int8_out = qmod.predict(it).asnumpy()
    assert np.max(np.abs(int8_out - fp32_out)) < 0.05
    assert (int8_out.argmax(1) == fp32_out.argmax(1)).mean() > 0.95


def test_fused_int8_lowering_convnet_residual():
    """Conv+BN+relu chains and a residual add fuse completely; numerics
    track fp32 within int8 tolerance."""
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, name="c1", kernel=(3, 3), pad=(1, 1),
                            num_filter=8, no_bias=True)
    b1 = mx.sym.BatchNorm(c1, name="b1", fix_gamma=False)
    a1 = mx.sym.Activation(b1, name="a1", act_type="relu")
    c2 = mx.sym.Convolution(a1, name="c2", kernel=(1, 1), num_filter=8,
                            no_bias=True)
    b2 = mx.sym.BatchNorm(c2, name="b2", fix_gamma=False)
    s = mx.sym.broadcast_add(b2, a1, name="res")
    out = mx.sym.Activation(s, name="a2", act_type="relu")
    sym = mx.sym.Pooling(out, name="gp", global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    sym = mx.sym.FullyConnected(sym, name="fc", num_hidden=3)

    rng = np.random.RandomState(0)
    x = rng.rand(4, 4, 8, 8).astype("float32")
    args = {"c1_weight": mx.nd.array(rng.randn(8, 4, 3, 3) * 0.3),
            "c2_weight": mx.nd.array(rng.randn(8, 8, 1, 1) * 0.3),
            "b1_gamma": mx.nd.array(1 + 0.1 * rng.randn(8)),
            "b1_beta": mx.nd.array(0.1 * rng.randn(8)),
            "b2_gamma": mx.nd.array(1 + 0.1 * rng.randn(8)),
            "b2_beta": mx.nd.array(0.1 * rng.randn(8)),
            "fc_weight": mx.nd.array(rng.randn(3, 8) * 0.3),
            "fc_bias": mx.nd.zeros(3)}
    auxs = {"b1_moving_mean": mx.nd.array(0.05 * rng.randn(8)),
            "b1_moving_var": mx.nd.array(1 + 0.1 * rng.rand(8)),
            "b2_moving_mean": mx.nd.array(0.05 * rng.randn(8)),
            "b2_moving_var": mx.nd.array(1 + 0.1 * rng.rand(8))}
    xin = mx.nd.array(x)
    ref = sym.bind(mx.cpu(), {**args, "data": xin}, aux_states=auxs) \
        .forward(is_train=False)[0].asnumpy()

    it = mx.io.NDArrayIter(x, np.zeros(4, "float32"), batch_size=4)
    qsym, qargs, qauxs = qz.quantize_model(
        sym, args, auxs, calib_mode="naive", calib_data=it,
        num_calib_examples=4, lowering="fused_int8")
    ops = [n.op.name for n in qsym._topo() if n.op is not None]
    assert ops.count("_contrib_int8_conv_fused") == 2, ops
    assert ops.count("_contrib_int8_add_act") == 1, ops
    assert "BatchNorm" not in ops and "Convolution" not in ops, ops
    got = qsym.bind(mx.cpu(), {**qargs, "data": xin}, aux_states=qauxs) \
        .forward(is_train=False)[0].asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() < 0.05 * scale + 0.02, \
        (np.abs(got - ref).max(), scale)


def test_fused_int8_lowering_global_max_pool():
    """Global *max* pool keeps the quantized state (raw int8 codes are
    scale-preserving); regression for the r4 bug where the lowering
    dequantized the codes with scale 1.0 — wrong by 1/in_scale."""
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, name="c1", kernel=(3, 3), pad=(1, 1),
                            num_filter=8, no_bias=True)
    a1 = mx.sym.Activation(c1, name="a1", act_type="relu")
    gp = mx.sym.Pooling(a1, name="gp", global_pool=True, pool_type="max",
                        kernel=(1, 1))
    sym = mx.sym.FullyConnected(gp, name="fc", num_hidden=3)

    rng = np.random.RandomState(7)
    x = rng.rand(4, 4, 8, 8).astype("float32")
    args = {"c1_weight": mx.nd.array(rng.randn(8, 4, 3, 3) * 0.3),
            "fc_weight": mx.nd.array(rng.randn(3, 8) * 0.3),
            "fc_bias": mx.nd.zeros(3)}
    xin = mx.nd.array(x)
    ref = sym.bind(mx.cpu(), {**args, "data": xin}) \
        .forward(is_train=False)[0].asnumpy()

    it = mx.io.NDArrayIter(x, np.zeros(4, "float32"), batch_size=4)
    qsym, qargs, qauxs = qz.quantize_model(
        sym, args, {}, calib_mode="naive", calib_data=it,
        num_calib_examples=4, lowering="fused_int8")
    ops = [n.op.name for n in qsym._topo() if n.op is not None]
    assert "_contrib_int8_pool" in ops, ops
    got = qsym.bind(mx.cpu(), {**qargs, "data": xin}) \
        .forward(is_train=False)[0].asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() < 0.05 * scale + 0.02, \
        (np.abs(got - ref).max(), scale)


def test_fused_int8_fc_unknown_shape_fp32_falls_back():
    """A 4-D fp32 FC input with H*W>1 and *no* data_shapes must fall back
    to fp32 (the NHWC quantize transpose cannot be matched against the
    unpermuted NCHW weight columns) — regression for silently-wrong
    flatten order."""
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, name="fc", num_hidden=3)

    rng = np.random.RandomState(11)
    x = rng.rand(2, 3, 4, 4).astype("float32")
    args = {"fc_weight": mx.nd.array(rng.randn(3, 48) * 0.2),
            "fc_bias": mx.nd.zeros(3)}
    xin = mx.nd.array(x)
    ref = sym.bind(mx.cpu(), {**args, "data": xin}) \
        .forward(is_train=False)[0].asnumpy()

    it = mx.io.NDArrayIter(x, np.zeros(2, "float32"), batch_size=2)
    th = qz._collect_thresholds(sym, args, {}, it, ("data",), 2, None,
                                mode="naive", boundaries="all")
    qsym, qargs, qauxs = qz.lower_int8_inference(
        sym, args, {}, th, data_shapes=None)
    ops = [n.op.name for n in qsym._topo() if n.op is not None]
    assert "FullyConnected" in ops, ops          # stayed fp32
    assert "_contrib_int8_fc_fused" not in ops, ops
    got = qsym.bind(mx.cpu(), {**qargs, "data": xin}) \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
