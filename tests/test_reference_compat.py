"""Cross-version artifact compatibility (the reference's
``model_backwards_compatibility_check`` role): load checkpoints produced by
stock MXNet, write checkpoints it can read back."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx

REF = "/root/reference/tests/python/unittest"


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_legacy_symbol_json_and_run():
    sym = mx.sym.load(os.path.join(REF, "save_000800.json"))
    assert sym.list_outputs() == ["softmax_output"]
    assert "batchnorm0_moving_mean" in sym.list_auxiliary_states()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(2, 10), grad_req="null")
    rng = np.random.RandomState(0)
    for k, v in exe.arg_dict.items():
        if k != "data":
            v[:] = rng.rand(*v.shape) * 0.1
    for k, v in exe.aux_dict.items():
        v[:] = 1.0 if "var" in k else 0.0
    exe.arg_dict["data"][:] = rng.rand(2, 10)
    out = exe.forward()
    np.testing.assert_allclose(out[0].asnumpy().sum(axis=1), np.ones(2),
                               rtol=1e-4)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_legacy_ndarray_v0():
    arrs = mx.nd.load(os.path.join(REF, "legacy_ndarray.v0"))
    assert len(arrs) == 6
    for a in arrs:
        assert a.shape == (128,)
        assert np.isfinite(a.asnumpy()).all()


def test_params_binary_layout(tmp_path):
    """The written file carries the dmlc list magic + V2 array records —
    the exact layout stock MXNet's MXNDArrayLoad expects."""
    path = str(tmp_path / "x.params")
    mx.nd.save(path, {"arg:w": mx.nd.ones((2, 3))})
    raw = open(path, "rb").read()
    magic, reserved = struct.unpack("<QQ", raw[:16])
    assert magic == 0x112 and reserved == 0
    (count,) = struct.unpack("<Q", raw[16:24])
    assert count == 1
    (nd_magic,) = struct.unpack("<I", raw[24:28])
    assert nd_magic == 0xF993FAC9  # NDARRAY_V2_MAGIC
    # name table at the end
    assert raw.endswith(b"arg:w")


def test_save_load_roundtrip_dtypes(tmp_path):
    path = str(tmp_path / "r.params")
    data = {"f32": mx.nd.array(np.random.rand(4, 5).astype("float32")),
            "u8": mx.nd.array(np.arange(6, dtype="uint8").reshape(2, 3),
                              dtype="uint8"),
            "scalar_shape": mx.nd.ones((1,))}
    mx.nd.save(path, data)
    back = mx.nd.load(path)
    for k in data:
        np.testing.assert_array_equal(back[k].asnumpy(),
                                      data[k].asnumpy(), err_msg=k)
    # list form (no names)
    mx.nd.save(path, [mx.nd.ones((2,)), mx.nd.zeros((3,))])
    lst = mx.nd.load(path)
    assert isinstance(lst, list) and len(lst) == 2
