"""Custom operator tests (reference ``tests/python/unittest/test_operator.py``
test_custom_op — the Sigmoid example from the docs)."""
import numpy as np
import pytest

import mxnet_tpu as mx


@mx.operator.register("mysigmoid")
class MySigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shapes):
        return in_shapes, [in_shapes[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return MySigmoid()


class MySigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = 1.0 / (1.0 + np.exp(-x))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1 - y)))


def test_custom_forward():
    x = mx.nd.array([0.0, 1.0, -1.0])
    out = mx.nd.Custom(x, op_type="mysigmoid")
    np.testing.assert_allclose(out.asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_backward():
    x = mx.nd.array(np.random.randn(4, 3).astype("float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="mysigmoid")
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5,
                               atol=1e-6)


def test_custom_unregistered_raises():
    with pytest.raises(ValueError):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="nope")


@mx.operator.register("addn2")
class AddNProp(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["a", "b"]

    def infer_shape(self, in_shapes):
        return in_shapes, [in_shapes[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return AddN()


class AddN(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] + in_data[1])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0])
        self.assign(in_grad[1], req[1], out_grad[0])


def test_custom_multi_input_grads():
    a = mx.nd.ones((3,)) * 2
    b = mx.nd.ones((3,)) * 5
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        out = mx.nd.Custom(a, b, op_type="addn2")
        out.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), np.ones(3))
    np.testing.assert_allclose(b.grad.asnumpy(), np.ones(3))
