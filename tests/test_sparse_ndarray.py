"""Sparse NDArray compat tests (reference
``tests/python/unittest/test_sparse_ndarray.py`` — dense-backed on TPU)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def test_csr_creation_from_dense():
    dense = np.array([[0, 1, 0], [2, 0, 3]], dtype="float32")
    a = sparse.csr_matrix(dense)
    assert a.stype == "csr"
    np.testing.assert_array_equal(a.asnumpy(), dense)
    np.testing.assert_array_equal(a.data.asnumpy(), [1, 2, 3])
    np.testing.assert_array_equal(a.indices.asnumpy(), [1, 0, 2])
    np.testing.assert_array_equal(a.indptr.asnumpy(), [0, 1, 3])


def test_csr_creation_from_buffers():
    a = sparse.csr_matrix((np.array([1., 2., 3.]), np.array([1, 0, 2]),
                           np.array([0, 1, 3])), shape=(2, 3))
    np.testing.assert_array_equal(a.asnumpy(),
                                  [[0, 1, 0], [2, 0, 3]])


def test_csr_scipy_roundtrip():
    import scipy.sparse as sp
    m = sp.random(5, 4, density=0.4, format="csr", dtype=np.float32,
                  random_state=0)
    a = sparse.csr_matrix(m)
    np.testing.assert_allclose(a.asnumpy(), m.todense())
    back = a.asscipy()
    np.testing.assert_allclose(np.asarray(back.todense()),
                               np.asarray(m.todense()))


def test_row_sparse():
    data = np.array([[1, 2], [3, 4]], dtype="float32")
    a = sparse.row_sparse_array((data, [1, 3]), shape=(4, 2))
    assert a.stype == "row_sparse"
    np.testing.assert_array_equal(a.indices.asnumpy(), [1, 3])
    np.testing.assert_array_equal(a.data.asnumpy(), data)
    assert a.asnumpy()[0].sum() == 0
    kept = a.retain(mx.nd.array([1]))
    assert kept.asnumpy()[3].sum() == 0
    np.testing.assert_array_equal(kept.asnumpy()[1], [1, 2])


def test_tostype_roundtrip():
    x = mx.nd.array([[0, 1], [2, 0]])
    c = x.tostype("csr")
    assert c.stype == "csr"
    d = c.tostype("default")
    assert d.stype == "default"
    np.testing.assert_array_equal(d.asnumpy(), x.asnumpy())
    r = x.tostype("row_sparse")
    assert r.stype == "row_sparse"


def test_sparse_ops_dense_backed():
    """Sparse arrays flow through ordinary operators."""
    a = sparse.csr_matrix(np.array([[0, 1], [2, 0]], dtype="float32"))
    b = mx.nd.ones((2, 2))
    out = sparse.dot(a, b)
    np.testing.assert_array_equal(out.asnumpy(), [[1, 1], [2, 2]])
    s = (a + a).asnumpy()
    np.testing.assert_array_equal(s, [[0, 2], [4, 0]])


def test_sparse_zeros_and_array():
    z = sparse.zeros("row_sparse", (3, 2))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    z2 = sparse.zeros("default", (3, 2))
    assert z2.stype == "default"
    a = sparse.array(z)
    assert a.stype == "row_sparse"


def test_rand_ndarray_sparse():
    from mxnet_tpu import test_utils as tu
    arr = tu.rand_ndarray((20, 10), stype="row_sparse", density=0.3)
    frac = (arr.asnumpy() != 0).mean()
    assert 0.05 < frac < 0.6


# --- r4 depth additions: retain, format checking, save/load, astype,
# the compressed-payload contract (reference test_sparse_ndarray.py
# remainder)

def test_row_sparse_retain_subsets_rows():
    idx = np.array([0, 2, 5], dtype="int64")
    vals = np.arange(9, dtype="float32").reshape(3, 3)
    a = sparse.row_sparse_array((vals, idx), shape=(6, 3))
    kept = a.retain(mx.nd.array([2, 5]))
    want = np.zeros((6, 3), "float32")
    want[2] = vals[1]
    want[5] = vals[2]
    np.testing.assert_allclose(kept.asnumpy(), want)


def test_csr_check_format_accepts_valid():
    rng = np.random.RandomState(5)
    d = rng.randn(4, 4).astype("float32") * (rng.rand(4, 4) < 0.5)
    sparse.csr_matrix(mx.nd.array(d)).check_format()


def test_csr_check_format_rejects_bad_indptr():
    # invalid invariants fail LOUDLY at construction (stricter than the
    # reference, which defers to check_format(full_check=True))
    with pytest.raises(ValueError, match="indptr"):
        sparse.csr_matrix(
            (np.array([1.0], "float32"), np.array([0]),
             np.array([0, 2, 1, 1, 1])), shape=(4, 4))
    with pytest.raises(ValueError, match="indices"):
        sparse.csr_matrix(
            (np.array([1.0], "float32"), np.array([9]),
             np.array([0, 1, 1, 1, 1])), shape=(4, 4))


def test_sparse_save_load_roundtrip(tmp_path):
    rng = np.random.RandomState(6)
    d = rng.randn(6, 5).astype("float32") * (rng.rand(6, 5) < 0.4)
    a = sparse.csr_matrix(mx.nd.array(d))
    idx = np.array([1, 3], dtype="int64")
    vals = np.ones((2, 5), dtype="float32")
    r = sparse.row_sparse_array((vals, idx), shape=(6, 5))
    f = str(tmp_path / "sp.nd")
    mx.nd.save(f, {"c": a, "r": r})
    loaded = mx.nd.load(f)
    np.testing.assert_allclose(loaded["c"].asnumpy(), d, rtol=1e-6)
    np.testing.assert_allclose(loaded["r"].asnumpy(), r.asnumpy())


def test_csr_astype_preserves_structure():
    rng = np.random.RandomState(4)
    d = rng.randn(5, 5).astype("float32") * (rng.rand(5, 5) < 0.4)
    a = sparse.csr_matrix(mx.nd.array(d))
    b = a.astype("float16")
    assert b.dtype == np.float16
    np.testing.assert_allclose(b.asnumpy().astype("float64"), d,
                               atol=1e-2)


def test_sparse_dot_matches_dense():
    rng = np.random.RandomState(8)
    d1 = rng.randn(5, 4).astype("float32") * (rng.rand(5, 4) < 0.3)
    d2 = rng.randn(4, 3).astype("float32")
    a = sparse.csr_matrix(mx.nd.array(d1))
    out = sparse.dot(a, mx.nd.array(d2))
    np.testing.assert_allclose(out.asnumpy(), d1 @ d2, rtol=1e-5,
                               atol=1e-5)


def test_row_sparse_compressed_memory_contract():
    """The RowSparse payload stores O(nnz_rows), not O(rows) — the r2
    'genuinely compressed' contract must not silently regress."""
    idx = np.array([7], dtype="int64")
    vals = np.ones((1, 8), dtype="float32")
    a = sparse.row_sparse_array((vals, idx), shape=(100000, 8))
    assert a.is_compressed
    assert a.data.shape[0] == 1          # payload rows == nnz rows
    assert a.shape == (100000, 8)
