"""Sparse NDArray compat tests (reference
``tests/python/unittest/test_sparse_ndarray.py`` — dense-backed on TPU)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def test_csr_creation_from_dense():
    dense = np.array([[0, 1, 0], [2, 0, 3]], dtype="float32")
    a = sparse.csr_matrix(dense)
    assert a.stype == "csr"
    np.testing.assert_array_equal(a.asnumpy(), dense)
    np.testing.assert_array_equal(a.data.asnumpy(), [1, 2, 3])
    np.testing.assert_array_equal(a.indices.asnumpy(), [1, 0, 2])
    np.testing.assert_array_equal(a.indptr.asnumpy(), [0, 1, 3])


def test_csr_creation_from_buffers():
    a = sparse.csr_matrix((np.array([1., 2., 3.]), np.array([1, 0, 2]),
                           np.array([0, 1, 3])), shape=(2, 3))
    np.testing.assert_array_equal(a.asnumpy(),
                                  [[0, 1, 0], [2, 0, 3]])


def test_csr_scipy_roundtrip():
    import scipy.sparse as sp
    m = sp.random(5, 4, density=0.4, format="csr", dtype=np.float32,
                  random_state=0)
    a = sparse.csr_matrix(m)
    np.testing.assert_allclose(a.asnumpy(), m.todense())
    back = a.asscipy()
    np.testing.assert_allclose(np.asarray(back.todense()),
                               np.asarray(m.todense()))


def test_row_sparse():
    data = np.array([[1, 2], [3, 4]], dtype="float32")
    a = sparse.row_sparse_array((data, [1, 3]), shape=(4, 2))
    assert a.stype == "row_sparse"
    np.testing.assert_array_equal(a.indices.asnumpy(), [1, 3])
    np.testing.assert_array_equal(a.data.asnumpy(), data)
    assert a.asnumpy()[0].sum() == 0
    kept = a.retain(mx.nd.array([1]))
    assert kept.asnumpy()[3].sum() == 0
    np.testing.assert_array_equal(kept.asnumpy()[1], [1, 2])


def test_tostype_roundtrip():
    x = mx.nd.array([[0, 1], [2, 0]])
    c = x.tostype("csr")
    assert c.stype == "csr"
    d = c.tostype("default")
    assert d.stype == "default"
    np.testing.assert_array_equal(d.asnumpy(), x.asnumpy())
    r = x.tostype("row_sparse")
    assert r.stype == "row_sparse"


def test_sparse_ops_dense_backed():
    """Sparse arrays flow through ordinary operators."""
    a = sparse.csr_matrix(np.array([[0, 1], [2, 0]], dtype="float32"))
    b = mx.nd.ones((2, 2))
    out = sparse.dot(a, b)
    np.testing.assert_array_equal(out.asnumpy(), [[1, 1], [2, 2]])
    s = (a + a).asnumpy()
    np.testing.assert_array_equal(s, [[0, 2], [4, 0]])


def test_sparse_zeros_and_array():
    z = sparse.zeros("row_sparse", (3, 2))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    z2 = sparse.zeros("default", (3, 2))
    assert z2.stype == "default"
    a = sparse.array(z)
    assert a.stype == "row_sparse"


def test_rand_ndarray_sparse():
    from mxnet_tpu import test_utils as tu
    arr = tu.rand_ndarray((20, 10), stype="row_sparse", density=0.3)
    frac = (arr.asnumpy() != 0).mean()
    assert 0.05 < frac < 0.6
