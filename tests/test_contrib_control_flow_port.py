"""Remaining reference test_contrib_control_flow.py families:
cut_subgraph_{foreach,while_loop,cond} (control-flow blocks embedded in
larger graphs keep working through compose/serialize/rebind), scope
(name scoping around subgraph bodies), contrib_rnn (foreach-driven RNN
cells match static unrolls, with gradients).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal

_rng = np.random.RandomState


def _run_sym(sym, arrays, grad_names=()):
    args = {k: nd.array(v) for k, v in arrays.items()}
    grads = {k: nd.zeros(arrays[k].shape) for k in grad_names}
    exe = sym.bind(mx.cpu(), args, args_grad=grads or None)
    outs = exe.forward(is_train=bool(grad_names))
    if grad_names:
        exe.backward(nd.ones(outs[0].shape))
    return [o.asnumpy() for o in outs], \
        {k: g.asnumpy() for k, g in grads.items()}


def test_cut_subgraph_foreach():
    """foreach composed INSIDE a larger graph (ops before and after),
    surviving a JSON round trip (the reference's CutGraphInputs path)."""
    rng = _rng(0)
    x = rng.randn(4, 3).astype("float32")
    init = rng.randn(3).astype("float32")

    data = mx.sym.Variable("data")
    state = mx.sym.Variable("state")
    pre = data * 2.0                       # op before the subgraph

    def body(d, s):
        out = d + s
        return out, out

    outs, states = mx.sym.contrib.foreach(body, pre, state)
    final = outs * 3.0 + states[0] if isinstance(states, list) \
        else outs * 3.0 + states          # ops after the subgraph
    final = mx.sym.Group([final]) if not isinstance(final, mx.sym.Symbol) \
        else final

    ref_scan = np.cumsum(2 * x, axis=0) + init
    want = 3 * ref_scan + ref_scan[-1]

    got, _ = _run_sym(final, {"data": x, "state": init})
    assert_almost_equal(got[0], want, rtol=1e-5)
    # JSON round trip preserves the embedded subgraph
    loaded = mx.sym.load_json(final.tojson())
    got2, _ = _run_sym(loaded, {"data": x, "state": init})
    assert_almost_equal(got2[0], want, rtol=1e-5)


def test_cut_subgraph_while_loop():
    rng = _rng(1)
    init = rng.randn(3).astype("float32")

    s = mx.sym.Variable("s")
    i = mx.sym.Variable("i")
    pre = s + 1.0

    outs, states = mx.sym.contrib.while_loop(
        cond=lambda i, s: i < 4,
        func=lambda i, s: (None, (i + 1, s * 2.0)),
        loop_vars=(i, pre), max_iterations=8)
    final = states[1] - 3.0

    want = (init + 1) * 16 - 3
    got, _ = _run_sym(final, {"s": init, "i": np.zeros((1,), "float32")})
    assert_almost_equal(got[0], want, rtol=1e-5)
    loaded = mx.sym.load_json(final.tojson())
    got2, _ = _run_sym(loaded, {"s": init,
                                "i": np.zeros((1,), "float32")})
    assert_almost_equal(got2[0], want, rtol=1e-5)


def test_cut_subgraph_cond():
    rng = _rng(2)
    x = rng.randn(3).astype("float32")

    a = mx.sym.Variable("a")
    flag = mx.sym.Variable("flag")
    pre = a * 2.0
    out = mx.sym.contrib.cond(
        mx.sym.sum(flag) > 0,
        lambda: pre + 1.0,
        lambda: pre - 1.0)
    final = out[0] * 10.0 if isinstance(out, list) else out * 10.0

    got, _ = _run_sym(final, {"a": x, "flag": np.ones((1,), "float32")})
    assert_almost_equal(got[0], 10 * (2 * x + 1), rtol=1e-5)
    got, _ = _run_sym(final, {"a": x, "flag": -np.ones((1,), "float32")})
    assert_almost_equal(got[0], 10 * (2 * x - 1), rtol=1e-5)
    loaded = mx.sym.load_json(final.tojson())
    got2, _ = _run_sym(loaded, {"a": x, "flag": np.ones((1,), "float32")})
    assert_almost_equal(got2[0], 10 * (2 * x + 1), rtol=1e-5)


def test_scope():
    """Name scoping around control-flow bodies: two foreach blocks built
    under different name managers stay distinct and re-loadable."""
    x = _rng(3).randn(3, 2).astype("float32")

    def build(tag):
        with mx.name.Prefix(f"{tag}_"):
            d = mx.sym.Variable("data")
            s = mx.sym.Variable("state")
            outs, _ = mx.sym.contrib.foreach(
                lambda dd, ss: (dd + ss, dd + ss), d, s)
            return outs

    s1, s2 = build("alpha"), build("beta")
    both = mx.sym.Group([s1 * 1.0, s2 * 1.0])
    names = [n for n in both.tojson().split('"') if "foreach" in n]
    loaded = mx.sym.load_json(both.tojson())
    got, _ = _run_sym(loaded, {"data": x, "state": np.zeros(2, "float32")})
    ref = np.cumsum(x, axis=0)
    assert_almost_equal(got[0], ref, rtol=1e-5)
    assert_almost_equal(got[1], ref, rtol=1e-5)


def test_contrib_rnn():
    """foreach driving a gluon RNN cell == the cell's static unroll,
    forward and parameter gradients (reference test_contrib_rnn)."""
    from mxnet_tpu import gluon
    rng = _rng(4)
    t, b, h = 5, 2, 4
    x = rng.randn(t, b, h).astype("float32") * 0.5

    for cell_cls in (gluon.rnn.RNNCell, gluon.rnn.GRUCell,
                     gluon.rnn.LSTMCell):
        cell = cell_cls(h, input_size=h)
        cell.initialize()
        begin = cell.begin_state(batch_size=b)

        # static unroll, step by step
        states = [s.copy() for s in begin]
        outs_ref = []
        params = list(cell.collect_params().values())
        with autograd.record():
            for step in range(t):
                o, states = cell(nd.array(x[step]), states)
                outs_ref.append(o)
            loss_ref = sum((o * o).sum() for o in outs_ref)
        loss_ref.backward()
        grads_ref = {p.name: p.grad().asnumpy().copy() for p in params}
        ref_out = np.stack([o.asnumpy() for o in outs_ref])

        # foreach-driven scan over the same cell
        for p in params:
            p.zero_grad()
        states = [s.copy() for s in begin]
        with autograd.record():
            outs, _ = nd.contrib.foreach(
                lambda d, s: cell(d, s), nd.array(x), states)
            loss = (outs * outs).sum()
        loss.backward()
        assert_almost_equal(outs.asnumpy(), ref_out, rtol=1e-4,
                            atol=1e-5)
        assert_almost_equal(float(loss.asscalar()),
                            float(loss_ref.asscalar()), rtol=1e-4)
        for p in params:
            assert_almost_equal(p.grad().asnumpy(), grads_ref[p.name],
                                rtol=1e-3, atol=1e-4), (cell_cls, p.name)
