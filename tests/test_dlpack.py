"""DLPack interop (reference ``tests/python/unittest/test_dlpack.py``):
zero-copy exchange with foreign frameworks — torch (CPU) is the live
consumer/producer available in this image.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx


def test_to_dlpack_torch_consumes():
    x = mx.nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    t = torch.utils.dlpack.from_dlpack(x.to_dlpack_for_read())
    np.testing.assert_allclose(t.numpy(), x.asnumpy())
    # protocol form: torch consumes the NDArray directly
    t2 = torch.from_dlpack(x)
    np.testing.assert_allclose(t2.numpy(), x.asnumpy())


def test_from_dlpack_torch_produces():
    t = torch.arange(8, dtype=torch.float32).reshape(2, 4) * 1.5
    a = mx.nd.from_dlpack(t)
    assert isinstance(a, mx.nd.NDArray)
    np.testing.assert_allclose(a.asnumpy(), t.numpy())
    # round-trip
    t3 = torch.from_dlpack(a)
    np.testing.assert_allclose(t3.numpy(), t.numpy())


def test_module_level_capsule_functions():
    x = mx.nd.ones((3,))
    cap = mx.nd.to_dlpack_for_read(x)
    t = torch.utils.dlpack.from_dlpack(cap)
    np.testing.assert_allclose(t.numpy(), [1, 1, 1])
    cap2 = mx.nd.to_dlpack_for_write(x)
    assert cap2 is not None
