"""Reference test_operator.py port, tranche 5: detection + misc cases —
test_op_roi_align / test_roi_align_value / test_roi_align_autograd,
test_multi_proposal_op, test_stn_valid_sampling,
test_psroipooling_with_type, test_custom_op_exc, test_correlation.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal

_rng = np.random.RandomState


def _roi_align_ref(data, rois, pooled, scale, s=2):
    """NumPy ROIAlign (average, sample grid s x s per bin) mirroring
    roi_align.cc semantics with clipped sample coords."""
    n_roi = rois.shape[0]
    c, h, w = data.shape[1:]
    ph, pw = pooled
    out = np.zeros((n_roi, c, ph, pw), "float32")
    for r in range(n_roi):
        bi = int(rois[r, 0])
        x1, y1, x2, y2 = rois[r, 1:] * scale
        rw = max(x2 - x1, 1.0)
        rh = max(y2 - y1, 1.0)
        bh, bw = rh / ph, rw / pw
        img = data[bi]
        for py in range(ph):
            for px in range(pw):
                acc = np.zeros(c, "float32")
                for sy in range(s):
                    for sx in range(s):
                        yv = np.clip(y1 + (py + (sy + 0.5) / s) * bh,
                                     0, h - 1)
                        xv = np.clip(x1 + (px + (sx + 0.5) / s) * bw,
                                     0, w - 1)
                        y0, x0 = int(yv), int(xv)
                        y1_, x1_ = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                        wy, wx = yv - y0, xv - x0
                        acc += (img[:, y0, x0] * (1 - wy) * (1 - wx)
                                + img[:, y0, x1_] * (1 - wy) * wx
                                + img[:, y1_, x0] * wy * (1 - wx)
                                + img[:, y1_, x1_] * wy * wx)
                out[r, :, py, px] = acc / (s * s)
    return out


def test_op_roi_align():
    rng = _rng(0)
    data = rng.randn(2, 3, 10, 10).astype("float32")
    rois = np.array([[0, 1, 1, 8, 8], [1, 0, 2, 6, 9]], "float32")
    got = nd.contrib.ROIAlign(nd.array(data), nd.array(rois),
                              pooled_size=(3, 3), spatial_scale=1.0,
                              sample_ratio=2)
    ref = _roi_align_ref(data, rois, (3, 3), 1.0)
    assert_almost_equal(got.asnumpy(), ref, rtol=1e-3, atol=1e-4)
    assert got.dtype == np.float32


def test_roi_align_value():
    """Spatial scale scales roi coords into feature space."""
    rng = _rng(1)
    data = rng.randn(1, 2, 8, 8).astype("float32")
    rois = np.array([[0, 4, 4, 28, 28]], "float32")   # image coords
    got = nd.contrib.ROIAlign(nd.array(data), nd.array(rois),
                              pooled_size=(2, 2), spatial_scale=0.25,
                              sample_ratio=2)
    ref = _roi_align_ref(data, rois, (2, 2), 0.25)
    assert_almost_equal(got.asnumpy(), ref, rtol=1e-3, atol=1e-4)


def test_roi_align_autograd():
    """Gradients flow to the feature map; the roi box regions receive
    nonzero gradient, far-outside regions stay zero."""
    rng = _rng(2)
    data = nd.array(rng.randn(1, 2, 12, 12).astype("float32"))
    rois = nd.array(np.array([[0, 1, 1, 5, 5]], "float32"))
    data.attach_grad()
    with autograd.record():
        out = nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                                  spatial_scale=1.0, sample_ratio=2)
        loss = out.sum()
    loss.backward()
    g = data.grad.asnumpy()
    assert np.abs(g[0, :, 1:6, 1:6]).sum() > 0
    assert np.abs(g[0, :, 9:, 9:]).sum() == 0


def test_multi_proposal_op():
    """Proposal/MultiProposal emit (batch_idx, x1, y1, x2, y2) boxes
    inside the image, ranked by score (reference test_multi_proposal_op
    contract surface)."""
    rng = _rng(3)
    n, a, h, w = 1, 3, 8, 8
    cls_prob = nd.array(rng.rand(n, 2 * a, h, w).astype("float32"))
    bbox_pred = nd.array(
        0.1 * rng.randn(n, 4 * a, h, w).astype("float32"))
    im_info = nd.array(np.array([[128.0, 128.0, 1.0]], "float32"))
    out = nd.contrib.MultiProposal(
        cls_prob, bbox_pred, im_info, feature_stride=16,
        scales=(8,), ratios=(0.5, 1, 2), rpn_pre_nms_top_n=50,
        rpn_post_nms_top_n=10, threshold=0.7, rpn_min_size=4)
    boxes = out.asnumpy() if not isinstance(out, (list, tuple)) \
        else out[0].asnumpy()
    assert boxes.shape[1] == 5
    x1, y1, x2, y2 = boxes[:, 1], boxes[:, 2], boxes[:, 3], boxes[:, 4]
    assert (x2 >= x1 - 1e-3).all() and (y2 >= y1 - 1e-3).all()
    assert (x1 >= -1e-3).all() and (y1 >= -1e-3).all()
    assert (x2 <= 128 + 1e-3).all() and (y2 <= 128 + 1e-3).all()


def test_stn_valid_sampling():
    """A shifted affine theta samples the shifted image region; samples
    falling outside pad with zeros (reference test_stn_valid_sampling
    boundary contract)."""
    x = np.zeros((1, 1, 6, 6), "float32")
    x[0, 0] = np.arange(36, dtype="float32").reshape(6, 6)
    # translate by a full image width: all but the boundary-sampling
    # first column lands outside and pads with zeros (column 0 samples
    # exactly x_src = width-1; columns 1+ are fully out of range)
    theta = np.array([[1, 0, 2.0, 0, 1, 0]], "float32")
    out = nd.SpatialTransformer(
        nd.array(x), nd.array(theta), target_shape=(6, 6),
        transform_type="affine", sampler_type="bilinear").asnumpy()
    assert np.abs(out[..., :, 1:]).sum() == 0
    assert_almost_equal(out[0, 0, :, 0], x[0, 0, :, 5], rtol=1e-4,
                        atol=1e-4)
    # identity theta reproduces the input exactly
    theta_id = np.array([[1, 0, 0, 0, 1, 0]], "float32")
    out = nd.SpatialTransformer(
        nd.array(x), nd.array(theta_id), target_shape=(6, 6),
        transform_type="affine", sampler_type="bilinear").asnumpy()
    assert_almost_equal(out, x, rtol=1e-4, atol=1e-4)


def test_psroipooling_with_type():
    """PSROIPooling: output shape contract and group-sensitive pooling
    behavior for multiple dtypes' inputs (f32 path; f16 casts)."""
    rng = _rng(4)
    od, g = 2, 3
    data = rng.randn(1, od * g * g, 12, 12).astype("float32")
    rois = np.array([[0, 0, 0, 11, 11]], "float32")
    out = nd.contrib.PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=1.0,
        output_dim=od, pooled_size=g)
    assert out.shape == (1, od, g, g)
    assert np.isfinite(out.asnumpy()).all()
    # f16 input: runs and returns finite values
    out16 = nd.contrib.PSROIPooling(
        nd.array(data.astype("float16"), dtype="float16"),
        nd.array(rois), spatial_scale=1.0, output_dim=od,
        pooled_size=g)
    assert np.isfinite(out16.asnumpy().astype("float32")).all()


def test_custom_op_exc():
    """Exceptions raised inside a CustomOp surface at the call site
    (reference test_custom_op_exc; stricter than the reference's
    deferred engine rethrow)."""
    import mxnet_tpu.operator as operator

    class BoomProp(operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Boom(operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    raise RuntimeError("custom forward boom")

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    pass
            return Boom()

    operator.register("boom_port")(BoomProp)
    with pytest.raises(Exception, match="boom"):
        nd.Custom(nd.ones((2, 2)), op_type="boom_port").asnumpy()


def test_correlation():
    """Correlation layer: zero displacement channel equals the mean of
    the elementwise product (reference test_correlation numerics core;
    infer_type seeding covered in test_infer_type.py)."""
    rng = _rng(5)
    a = rng.randn(1, 4, 6, 6).astype("float32")
    b = rng.randn(1, 4, 6, 6).astype("float32")
    out = nd.Correlation(nd.array(a), nd.array(b), kernel_size=1,
                         max_displacement=2, stride1=1, stride2=1,
                         pad_size=2, is_multiply=True)
    o = out.asnumpy()
    assert o.shape[1] == 25                      # (2*2+1)^2 channels
    center = o[0, 12]                            # zero displacement
    ref = (a[0] * b[0]).mean(axis=0)
    assert_almost_equal(center, ref, rtol=1e-4, atol=1e-5)
