"""Extended operator contract tests (mirrors more of the reference's
``tests/python/unittest/test_operator.py`` surface)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def test_order_ops():
    a = np.array([[3.0, 1.0, 2.0], [0.0, -1.0, 5.0]], dtype="float32")
    x = mx.nd.array(a)
    np.testing.assert_array_equal(mx.nd.sort(x, axis=1).asnumpy(),
                                  np.sort(a, axis=1))
    np.testing.assert_array_equal(mx.nd.argsort(x, axis=1).asnumpy(),
                                  np.argsort(a, axis=1, kind="stable"))
    np.testing.assert_array_equal(mx.nd.argmax(x, axis=1).asnumpy(),
                                  a.argmax(1))
    np.testing.assert_array_equal(mx.nd.argmin(x, axis=1).asnumpy(),
                                  a.argmin(1))
    top = mx.nd.topk(x, k=2, axis=1, ret_typ="value")
    np.testing.assert_array_equal(top.asnumpy(),
                                  -np.sort(-a, axis=1)[:, :2])


def test_clip_where_maximum():
    a = np.linspace(-3, 3, 12, dtype="float32").reshape(3, 4)
    x = mx.nd.array(a)
    np.testing.assert_allclose(mx.nd.clip(x, -1, 1).asnumpy(),
                               np.clip(a, -1, 1))
    cond = mx.nd.array((a > 0).astype("float32"))
    np.testing.assert_allclose(
        mx.nd.where(cond, x, -x).asnumpy(), np.where(a > 0, a, -a))
    np.testing.assert_allclose(mx.nd.maximum(x, 0).asnumpy(),
                               np.maximum(a, 0))


def test_one_hot_and_pick():
    idx = mx.nd.array([0, 2, 1], dtype="float32")
    oh = mx.nd.one_hot(idx, 4)
    np.testing.assert_array_equal(oh.asnumpy(),
                                  np.eye(4, dtype="float32")[[0, 2, 1]])
    data = mx.nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    picked = mx.nd.pick(data, idx, axis=1)
    np.testing.assert_array_equal(picked.asnumpy(), [0, 6, 9])


def test_stack_flip_rot():
    a = np.arange(6, dtype="float32").reshape(2, 3)
    x = mx.nd.array(a)
    st = mx.nd.stack(x, x, axis=1)
    assert st.shape == (2, 2, 3)
    np.testing.assert_array_equal(mx.nd.flip(x, axis=1).asnumpy(),
                                  a[:, ::-1])
    np.testing.assert_array_equal(mx.nd.swapaxes(x, 0, 1).asnumpy(), a.T)


def test_batch_dot_transpose_combos():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 2, 3).astype("float32")
    b = rng.randn(4, 3, 5).astype("float32")
    out = mx.nd.batch_dot(mx.nd.array(a), mx.nd.array(b))
    np.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5)
    out_t = mx.nd.batch_dot(mx.nd.array(a.transpose(0, 2, 1)),
                            mx.nd.array(b), transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), a @ b, rtol=1e-5)
    out_tb = mx.nd.batch_dot(mx.nd.array(a),
                             mx.nd.array(b.transpose(0, 2, 1)),
                             transpose_b=True)
    np.testing.assert_allclose(out_tb.asnumpy(), a @ b, rtol=1e-5)


def test_l2_normalization_and_lrn():
    rng = np.random.RandomState(0)
    a = rng.rand(2, 4).astype("float32") + 0.1
    out = mx.nd.L2Normalization(mx.nd.array(a), mode="instance")
    np.testing.assert_allclose(
        out.asnumpy(), a / np.linalg.norm(a, axis=1, keepdims=True),
        rtol=1e-5)
    x = mx.nd.array(rng.rand(1, 4, 5, 5).astype("float32"))
    lrn = mx.nd.LRN(x, nsize=3)
    assert lrn.shape == x.shape
    assert np.isfinite(lrn.asnumpy()).all()


def test_layernorm_numerics():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 6).astype("float32")
    gamma = np.ones(6, dtype="float32")
    beta = np.zeros(6, dtype="float32")
    out = mx.nd.LayerNorm(mx.nd.array(a), mx.nd.array(gamma),
                          mx.nd.array(beta))
    mu = a.mean(axis=1, keepdims=True)
    sig = a.std(axis=1, keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), (a - mu) / (sig + 1e-5),
                               rtol=1e-3, atol=1e-4)


def test_batchnorm_train_vs_eval():
    rng = np.random.RandomState(0)
    a = rng.randn(8, 3, 4, 4).astype("float32") * 2 + 1
    x = mx.nd.array(a)
    gamma = mx.nd.ones((3,))
    beta = mx.nd.zeros((3,))
    mean = mx.nd.zeros((3,))
    var = mx.nd.ones((3,))
    with mx.autograd.record():  # train mode: batch statistics
        out = mx.nd.BatchNorm(x, gamma, beta, mean, var)
    o = out.asnumpy()
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), np.zeros(3),
                               atol=1e-4)
    np.testing.assert_allclose(o.std(axis=(0, 2, 3)), np.ones(3), atol=1e-2)
    # aux moving stats were updated toward batch stats
    assert abs(float(mean.asnumpy().mean())) > 1e-3
    # eval mode: uses (updated) moving stats, not batch stats
    out_eval = mx.nd.BatchNorm(x, gamma, beta, mean, var)
    assert abs(out_eval.asnumpy().mean()) > 1e-3


def test_dropout_statistics():
    mx.random.seed(7)
    x = mx.nd.ones((1000,))
    with mx.autograd.record():
        out = mx.nd.Dropout(x, p=0.3)
    o = out.asnumpy()
    kept = (o > 0).mean()
    assert 0.6 < kept < 0.8                      # ~70% kept
    np.testing.assert_allclose(o[o > 0][0], 1 / 0.7, rtol=1e-5)
    # eval mode: identity
    np.testing.assert_array_equal(mx.nd.Dropout(x, p=0.3).asnumpy(),
                                  np.ones(1000, dtype="float32"))


def test_broadcast_like_and_expand():
    a = mx.nd.array([[1.0], [2.0]])
    b = mx.nd.zeros((2, 3))
    out = mx.nd.broadcast_like(a, b)
    assert out.shape == (2, 3)
    np.testing.assert_array_equal(out.asnumpy()[0], [1, 1, 1])
    np.testing.assert_array_equal(
        mx.nd.broadcast_to(a, shape=(2, 4)).asnumpy()[1], [2, 2, 2, 2])


def test_unary_gradients_numeric():
    """Finite-difference check over a basket of unary ops (the reference's
    check_numeric_gradient pattern)."""
    for opname in ("tanh", "sigmoid", "exp", "sqrt", "square"):
        data = mx.sym.Variable("data")
        out = mx.sym.sum(getattr(mx.sym, opname)(data))
        loc = {"data": np.random.RandomState(0).rand(4, 3).astype("float32")
               + 0.5}
        tu.check_numeric_gradient(out, loc, rtol=0.08, atol=1e-2)


def test_take_modes():
    a = np.arange(12, dtype="float32").reshape(4, 3)
    idx = mx.nd.array([1, 5], dtype="float32")  # 5 out of range
    out = mx.nd.take(mx.nd.array(a), idx, mode="clip")
    np.testing.assert_array_equal(out.asnumpy(), a[[1, 3]])
    out_wrap = mx.nd.take(mx.nd.array(a), idx, mode="wrap")
    np.testing.assert_array_equal(out_wrap.asnumpy(), a[[1, 1]])


def test_scatter_and_gather_nd():
    idx = mx.nd.array([[0, 1], [1, 0]], dtype="float32")
    data = mx.nd.array(np.arange(4, dtype="float32").reshape(2, 2))
    g = mx.nd.gather_nd(data, idx)
    np.testing.assert_array_equal(g.asnumpy(), [1, 2])
    s = mx.nd.scatter_nd(mx.nd.array([9.0, 8.0]), idx, shape=(2, 2))
    np.testing.assert_array_equal(s.asnumpy(), [[0, 9], [8, 0]])


def test_sync_batch_norm_matches_batch_norm_single_device():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 3, 5, 5).astype(np.float32))
    gamma = mx.nd.array(rng.rand(3).astype(np.float32) + 0.5)
    beta = mx.nd.array(rng.randn(3).astype(np.float32))
    mm, mv = mx.nd.zeros((3,)), mx.nd.ones((3,))
    with mx.autograd.record():
        a = mx.nd.BatchNorm(x, gamma, beta, mm.copy(), mv.copy(),
                            fix_gamma=False, eps=1e-5)
        b = mx.nd.contrib.SyncBatchNorm(x, gamma, beta, mm.copy(), mv.copy(),
                                        eps=1e-5, ndev=1)
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-5, atol=1e-6)
    # eval mode normalizes with the moving stats
    c = mx.nd.contrib.SyncBatchNorm(x, gamma, beta, mm, mv, eps=1e-5)
    assert np.isfinite(c.asnumpy()).all()


def test_sync_batch_norm_shard_map_moments_are_global():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from mxnet_tpu.ops.nn import batch_norm, sync_batch_norm
    from mxnet_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()

    rng = np.random.RandomState(0)
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    X = rng.randn(8, 3, 4, 4).astype(np.float32)
    G, B = np.ones(3, np.float32), np.zeros(3, np.float32)

    def local_bn(xs):
        out, _m, _v = sync_batch_norm(xs, jnp.asarray(G), jnp.asarray(B),
                                      jnp.zeros(3), jnp.ones(3), eps=1e-5,
                                      __training__=True)
        return out

    f = shard_map(local_bn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    got = np.asarray(f(jnp.asarray(X)))
    want, _, _ = batch_norm(jnp.asarray(X), jnp.asarray(G), jnp.asarray(B),
                            jnp.zeros(3), jnp.ones(3), eps=1e-5,
                            fix_gamma=False, __training__=True)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)


def test_batchnorm_training_variance_large_mean():
    """Single-pass BN stats must not catastrophically cancel when
    |mean| >> std (r4 / ADVICE r3: raw E[x^2]-E[x]^2 in f32 yields var~0
    for mean~1e4, std~1; the shifted-pivot form restores precision)."""
    rng = np.random.RandomState(0)
    x = (1e4 + rng.randn(8, 4, 16, 16)).astype("float32")
    with mx.autograd.record():
        out = mx.nd.BatchNorm(
            mx.nd.array(x), mx.nd.ones(4), mx.nd.zeros(4),
            mx.nd.zeros(4), mx.nd.ones(4), fix_gamma=False)
    true_var = x.var(axis=(0, 2, 3))
    got = out.asnumpy()
    expect = (x - x.mean(axis=(0, 2, 3), keepdims=True).reshape(1, 4, 1, 1)) \
        / np.sqrt(true_var.reshape(1, 4, 1, 1) + 1e-3)
    assert np.allclose(got, expect, atol=2e-2), \
        (np.abs(got - expect).max(), true_var)
