"""Sparse operator gradient contracts + density sweeps (deepens the
reference ``test_sparse_operator.py`` coverage beyond the named ports in
test_sparse_operator_port.py: grads through sparse elemwise/dot/retain,
cast_storage round trips across densities, lazy-vs-dense optimizer
equivalence on multiple configs).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.test_utils import assert_almost_equal

_rng = np.random.RandomState

DENSITIES = (0.05, 0.3, 0.8)


def _dense_with_density(rng, shape, density, row_sparse=False):
    x = rng.randn(*shape).astype("float32")
    if row_sparse:
        keep = rng.rand(shape[0]) < density
        x[~keep] = 0
    else:
        x[rng.rand(*shape) > density] = 0
    return x


@pytest.mark.parametrize("density", DENSITIES)
def test_cast_storage_density_sweep(density):
    rng = _rng(0)
    x = _dense_with_density(rng, (12, 9), density)
    d = nd.array(x)
    for stype in ("csr", "row_sparse"):
        s = nd.cast_storage(d, stype=stype)
        assert s.stype == stype
        assert_almost_equal(s.asnumpy(), x)
        back = nd.cast_storage(s, stype="default")
        assert_almost_equal(back.asnumpy(), x)
    # csr structure matches scipy at this density
    try:
        import scipy.sparse as ss
    except ImportError:
        return
    csr = sp.csr_matrix(x)
    ref = ss.csr_matrix(x)
    assert (csr.indptr.asnumpy().astype("int64") == ref.indptr).all()
    assert (csr.indices.asnumpy().astype("int64") == ref.indices).all()
    assert_almost_equal(csr.data.asnumpy(), ref.data)


@pytest.mark.parametrize("density", DENSITIES)
def test_sparse_dot_density_and_transpose(density):
    rng = _rng(1)
    a = _dense_with_density(rng, (6, 10), density)
    w = rng.randn(10, 4).astype("float32")
    a_sp = sp.csr_matrix(a)
    assert_almost_equal(nd.dot(a_sp, nd.array(w)).asnumpy(), a @ w,
                        rtol=1e-4, atol=1e-5)
    b = rng.randn(6, 3).astype("float32")
    got = nd.dot(a_sp, nd.array(b), transpose_a=True)
    assert_almost_equal(got.asnumpy(), a.T @ b, rtol=1e-4, atol=1e-5)


def test_sparse_dot_gradient():
    """d/dw (csr @ w) matches the dense computation's gradient."""
    rng = _rng(2)
    a = _dense_with_density(rng, (5, 8), 0.4)
    w = rng.randn(8, 3).astype("float32")
    a_sp = sp.csr_matrix(a)
    wv = nd.array(w)
    wv.attach_grad()
    with autograd.record():
        out = nd.dot(a_sp, wv)
        loss = (out * out).sum()
    loss.backward()
    # dense reference
    wd = nd.array(w)
    wd.attach_grad()
    with autograd.record():
        loss_d = (nd.dot(nd.array(a), wd) ** 2).sum()
    loss_d.backward()
    assert_almost_equal(wv.grad.asnumpy(), wd.grad.asnumpy(), rtol=1e-4,
                        atol=1e-5)


@pytest.mark.parametrize("op", ["elemwise_add", "elemwise_mul"])
def test_sparse_elemwise_gradient(op):
    rng = _rng(3)
    a = _dense_with_density(rng, (6, 5), 0.5, row_sparse=True)
    b = _dense_with_density(rng, (6, 5), 0.5, row_sparse=True)

    def run(make):
        x, y = make(a), make(b)
        x.attach_grad()
        y.attach_grad()
        with autograd.record():
            z = (getattr(nd, op)(x, y) * 3.0).sum()
        z.backward()
        return x.grad.asnumpy(), y.grad.asnumpy()

    gs = run(sp.row_sparse_array)
    gd = run(nd.array)
    assert_almost_equal(gs[0], gd[0], rtol=1e-5)
    assert_almost_equal(gs[1], gd[1], rtol=1e-5)


def test_sparse_retain_gradient_masks_rows():
    rng = _rng(4)
    a = _dense_with_density(rng, (8, 4), 0.9, row_sparse=True)
    # recorded path uses the dense handle (the deeper row_sparse retain
    # fwd/bwd contract lives in test_sparse_operator.py)
    x = nd.array(a)
    x.attach_grad()
    rows = nd.array(np.array([0, 3, 5], "float32"))
    with autograd.record():
        y = nd.sparse_retain(x, rows).sum()
    y.backward()
    g = x.grad.asnumpy()
    want = np.zeros_like(a)
    want[[0, 3, 5]] = 1.0
    assert_almost_equal(g, want)
    # sparse-input forward stays row_sparse and masks identically
    got = nd.sparse_retain(sp.row_sparse_array(a), rows)
    ref = np.zeros_like(a)
    ref[[0, 3, 5]] = a[[0, 3, 5]]
    assert got.stype == "row_sparse"
    assert_almost_equal(got.asnumpy(), ref)


def test_sparse_broadcast_gradients():
    rng = _rng(5)
    a = _dense_with_density(rng, (4, 6), 0.5)
    row = rng.rand(1, 6).astype("float32") + 0.5
    x = sp.csr_matrix(a)
    r = nd.array(row)
    x.attach_grad()
    r.attach_grad()
    with autograd.record():
        z = nd.broadcast_mul(x, r).sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(),
                        np.broadcast_to(row, a.shape), rtol=1e-5)
    assert_almost_equal(r.grad.asnumpy(),
                        a.sum(axis=0, keepdims=True), rtol=1e-4)


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.05}),
    ("adagrad", {"learning_rate": 0.1}),
])
def test_lazy_update_touches_only_present_rows(opt, kwargs):
    """Lazy sparse update == dense update on the touched rows; absent
    rows keep stale state (the reference lazy_update contract,
    optimizer.py lazy_update=True)."""
    rng = _rng(6)
    vocab, dim = 30, 4
    w0 = rng.randn(vocab, dim).astype("float32")
    rows = np.array([2, 7, 7, 19], "int64")
    grad_rows = rng.randn(len(rows), dim).astype("float32")

    # framework path: compressed row_sparse grad through the optimizer
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    import jax.numpy as jnp
    w = nd.array(w0.copy())
    g = RowSparseNDArray.from_rows(
        jnp.asarray(np.unique(rows).astype("int32")),
        jnp.asarray(np.stack([grad_rows[rows == r].sum(0)
                              for r in np.unique(rows)])),
        (vocab, dim))
    optimizer = mx.optimizer.create(opt, **kwargs)
    state = optimizer.create_state(0, w)
    optimizer.update(0, w, g, state)
    got = w.asnumpy()

    # dense reference on a fresh optimizer
    wd = nd.array(w0.copy())
    gd = np.zeros((vocab, dim), "float32")
    for r, gr in zip(rows, grad_rows):
        gd[r] += gr
    opt_d = mx.optimizer.create(opt, **kwargs)
    state_d = opt_d.create_state(0, wd)
    opt_d.update(0, wd, nd.array(gd), state_d)
    ref = wd.asnumpy()

    touched = np.unique(rows)
    assert_almost_equal(got[touched], ref[touched], rtol=1e-4,
                        atol=1e-5)
    untouched = np.setdiff1d(np.arange(vocab), touched)
    # lazy semantics: untouched rows unchanged (sgd) or at most the
    # dense no-grad drift (adam applies bias-corrected zero-step)
    assert_almost_equal(got[untouched], w0[untouched], rtol=1e-5,
                        atol=1e-6)


def test_sparse_sum_grad_and_dtype():
    rng = _rng(7)
    a = _dense_with_density(rng, (5, 7), 0.4)
    x = sp.csr_matrix(a)
    x.attach_grad()
    with autograd.record():
        s = nd.sum(x, axis=1).sum()
    s.backward()
    assert_almost_equal(x.grad.asnumpy(), np.ones_like(a))


def test_rsp_adoption_accumulates_across_backwards():
    """Two backwards with grad_req='add' into a row_sparse-attached grad
    accumulate (densified accumulate is acceptable; values must add)."""
    rng = _rng(8)
    w = nd.array(rng.randn(20, 3).astype("float32"))
    w.attach_grad(grad_req="add", stype="row_sparse")
    for idx in ([1, 4], [4, 9]):
        with autograd.record():
            e = nd.Embedding(nd.array(np.array(idx, "float32")), w,
                             input_dim=20, output_dim=3,
                             sparse_grad=True).sum()
        e.backward()
    g = w.grad.asnumpy()
    want = np.zeros((20, 3), "float32")
    for i in [1, 4, 4, 9]:
        want[i] += 1.0
    assert_almost_equal(g, want)


def test_csr_indexing_and_slice_consistency():
    rng = _rng(9)
    a = _dense_with_density(rng, (10, 6), 0.4)
    x = sp.csr_matrix(a)
    assert_almost_equal(x[3:7].asnumpy(), a[3:7])
    assert_almost_equal(nd.slice(x, begin=(2,), end=(9,)).asnumpy(),
                        a[2:9])
    # tostype round trip preserves values
    assert_almost_equal(x.tostype("default").asnumpy(), a)
    assert_almost_equal(x.tostype("row_sparse").asnumpy(), a)
