"""Real ONNX protobuf bytes — wire format, external validation, foreign
input-form graphs.

The reference's exporter writes ModelProto via the onnx wheel
(``mx2onnx/export_model.py``); here the wire format is hand-written
(``contrib/onnx/protobuf.py``) so ``export_model``/``import_model``
produce/consume real ``.onnx`` bytes with no wheel.  External validation:
``protoc --decode_raw`` (libprotoc) must parse the emitted bytes.
"""
import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mod
from mxnet_tpu.contrib.onnx import protobuf as pb


def _tiny_convnet():
    data = mx.sym.var("data")
    w = mx.sym.var("conv_weight")
    b = mx.sym.var("conv_bias")
    c = mx.sym.Convolution(data, w, b, kernel=(3, 3), pad=(1, 1),
                           num_filter=4, name="conv0")
    a = mx.sym.relu(c, name="relu0")
    p = mx.sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool0")
    f = mx.sym.Flatten(p, name="flat0")
    fcw = mx.sym.var("fc_weight")
    fcb = mx.sym.var("fc_bias")
    return mx.sym.FullyConnected(f, fcw, fcb, num_hidden=10, name="fc0")


def _tiny_params(rng):
    return {
        "conv_weight": mx.nd.array(rng.randn(4, 3, 3, 3).astype("float32")),
        "conv_bias": mx.nd.array(rng.randn(4).astype("float32")),
        "fc_weight": mx.nd.array(rng.randn(10, 4 * 4 * 4).astype("float32")),
        "fc_bias": mx.nd.array(rng.randn(10).astype("float32")),
    }


def _forward(sym, params, x):
    binds = dict(params)
    free = [a for a in sym.list_arguments() if a not in binds]
    assert len(free) == 1, free
    binds[free[0]] = mx.nd.array(x)
    ex = sym.bind(mx.cpu(), binds)
    return ex.forward()[0].asnumpy()


def test_export_import_through_real_bytes(tmp_path=None):
    rng = np.random.RandomState(3)
    sym = _tiny_convnet()
    params = _tiny_params(rng)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    want = _forward(sym, params, x)

    d = tempfile.mkdtemp(prefix="onnxbytes_")
    try:
        path = os.path.join(d, "tiny.onnx")
        onnx_mod.export_model(sym, params, (2, 3, 8, 8),
                              onnx_file_path=path)
        assert os.path.getsize(path) > 500
        sym2, arg2, aux2 = onnx_mod.import_model(path)
        got = _forward(sym2, {**arg2, **aux2}, x)
        np.testing.assert_allclose(want, got, rtol=1e-5, atol=1e-5)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_model_metadata_from_bytes():
    rng = np.random.RandomState(3)
    d = tempfile.mkdtemp(prefix="onnxmeta_")
    try:
        path = os.path.join(d, "tiny.onnx")
        onnx_mod.export_model(_tiny_convnet(), _tiny_params(rng),
                              (2, 3, 8, 8), onnx_file_path=path)
        meta = onnx_mod.get_model_metadata(path)
        assert meta["input_tensor_data"] == [("data", (2, 3, 8, 8))]
        assert len(meta["output_tensor_data"]) == 1
    finally:
        shutil.rmtree(d, ignore_errors=True)


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not in image")
def test_emitted_bytes_parse_with_protoc():
    """libprotoc is an independent wire-format implementation: it must
    parse our bytes, and the raw field tree must carry the expected ONNX
    schema positions (7=graph, 8=opset_import; in graph 1=node)."""
    rng = np.random.RandomState(3)
    d = tempfile.mkdtemp(prefix="onnxpc_")
    try:
        path = os.path.join(d, "tiny.onnx")
        onnx_mod.export_model(_tiny_convnet(), _tiny_params(rng),
                              (2, 3, 8, 8), onnx_file_path=path)
        with open(path, "rb") as f:
            out = subprocess.run(["protoc", "--decode_raw"], stdin=f,
                                 capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert '4: "Conv"' in out.stdout        # NodeProto.op_type field 4
        assert '4: "MaxPool"' in out.stdout
        assert '4: "Gemm"' in out.stdout or '4: "MatMul"' in out.stdout
        assert "7 {" in out.stdout              # ModelProto.graph field 7
        assert "8 {" in out.stdout              # ModelProto.opset_import
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_opset_declared_17_and_inputform_slice_clip_unsqueeze():
    """ADVICE r2 (medium): the emitted forms must be legal at the declared
    opset.  Slice/Clip/Unsqueeze must be input-form, opset 17."""
    data = mx.sym.var("data")
    s = mx.sym.slice_axis(data, axis=1, begin=1, end=3, name="sl")
    c = mx.sym.clip(s, a_min=-1.0, a_max=1.0, name="cl")
    e = mx.sym.expand_dims(c, axis=0, name="ex")
    g = onnx_mod.export_graph(e, {}, (2, 4))
    ops = {n["op_type"]: n for n in g["nodes"]}
    assert len(ops["Slice"]["inputs"]) == 4          # data+starts+ends+axes
    assert "starts" not in ops["Slice"]["attrs"]
    assert len(ops["Clip"]["inputs"]) == 3           # data+min+max
    assert "min" not in ops["Clip"]["attrs"]
    assert len(ops["Unsqueeze"]["inputs"]) == 2      # data+axes
    assert "axes" not in ops["Unsqueeze"]["attrs"]
    m = pb.bytes_to_model(onnx_mod.graph_to_bytes(g))
    assert m["opset"] == 17

    # and the round-trip back through real bytes stays exact
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4).astype("float32")
    want = _forward(e, {}, x)
    sym2, arg2, aux2 = onnx_mod.import_graph(
        onnx_mod.graph_from_bytes(onnx_mod.graph_to_bytes(g)))
    got = _forward(sym2, {**arg2, **aux2}, x)
    np.testing.assert_allclose(want, got, rtol=1e-6)


def _foreign_model(nodes, inputs, outputs, initializers):
    """Build .onnx bytes the way a foreign exporter would (input-form)."""
    return pb.model_to_bytes({"nodes": nodes, "inputs": inputs,
                              "outputs": outputs,
                              "initializers": initializers})


def test_foreign_inputform_unsqueeze_pad_reducesum():
    """Foreign opset>=13 graphs carry axes/pads as constant inputs — the
    importer must resolve them (ADVICE r2: no silent axis-0 default)."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3).astype("float32")
    data = _foreign_model(
        nodes=[
            {"op_type": "Unsqueeze", "name": "u", "inputs": ["x", "u_ax"],
             "outputs": ["ux"], "attrs": {}},
            {"op_type": "Pad", "name": "p", "inputs": ["ux", "p_pads"],
             "outputs": ["px"], "attrs": {"mode": "constant"}},
            {"op_type": "ReduceSum", "name": "r",
             "inputs": ["px", "r_ax"], "outputs": ["y"],
             "attrs": {"keepdims": 0}},
        ],
        inputs=[{"name": "x", "dtype": "float32", "shape": (2, 3)}],
        outputs=[{"name": "y"}],
        initializers={
            "u_ax": np.asarray([1], dtype=np.int64),
            "p_pads": np.asarray([0, 1, 0, 0, 0, 1], dtype=np.int64),
            "r_ax": np.asarray([2], dtype=np.int64),
        })
    sym, arg, aux = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
    got = _forward(sym, {**arg, **aux}, x)
    want = np.pad(x[:, None, :], ((0, 0), (1, 0), (0, 1))).sum(axis=2)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_foreign_inputform_slice_clip():
    rng = np.random.RandomState(1)
    x = rng.randn(3, 6).astype("float32")
    data = _foreign_model(
        nodes=[
            {"op_type": "Slice", "name": "s",
             "inputs": ["x", "st", "en", "ax"], "outputs": ["sx"],
             "attrs": {}},
            {"op_type": "Clip", "name": "c",
             "inputs": ["sx", "mn", "mx"], "outputs": ["y"], "attrs": {}},
        ],
        inputs=[{"name": "x", "dtype": "float32", "shape": (3, 6)}],
        outputs=[{"name": "y"}],
        initializers={
            "st": np.asarray([1], dtype=np.int64),
            "en": np.asarray([5], dtype=np.int64),
            "ax": np.asarray([1], dtype=np.int64),
            "mn": np.asarray(-0.5, dtype=np.float32),
            "mx": np.asarray(0.5, dtype=np.float32),
        })
    sym, arg, aux = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
    got = _forward(sym, {**arg, **aux}, x)
    np.testing.assert_allclose(got, np.clip(x[:, 1:5], -0.5, 0.5), rtol=1e-6)


def test_foreign_constant_node_becomes_initializer():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3).astype("float32")
    cval = rng.randn(2, 3).astype("float32")
    data = _foreign_model(
        nodes=[
            {"op_type": "Constant", "name": "k", "inputs": [],
             "outputs": ["kc"], "attrs": {"value": cval}},
            {"op_type": "Add", "name": "a", "inputs": ["x", "kc"],
             "outputs": ["y"], "attrs": {}},
        ],
        inputs=[{"name": "x", "dtype": "float32", "shape": (2, 3)}],
        outputs=[{"name": "y"}], initializers={})
    sym, arg, aux = onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))
    got = _forward(sym, {**arg, **aux}, x)
    np.testing.assert_allclose(got, x + cval, rtol=1e-6)


def test_dynamic_inputform_fails_loudly():
    """Axes coming from a computed tensor (not an initializer) must raise,
    never default."""
    data = _foreign_model(
        nodes=[
            {"op_type": "Shape", "name": "sh", "inputs": ["x"],
             "outputs": ["dyn"], "attrs": {}},
            {"op_type": "Unsqueeze", "name": "u", "inputs": ["x", "dyn"],
             "outputs": ["y"], "attrs": {}},
        ],
        inputs=[{"name": "x", "dtype": "float32", "shape": (2, 3)}],
        outputs=[{"name": "y"}], initializers={})
    with pytest.raises(NotImplementedError, match="dynamic"):
        onnx_mod.import_graph(onnx_mod.graph_from_bytes(data))


def test_wire_format_all_dtypes_roundtrip():
    rng = np.random.RandomState(4)
    inits = {}
    for dt in ("float32", "float64", "float16", "int32", "int64", "uint8",
               "int8", "bool"):
        a = (rng.rand(3, 2) * 4).astype(dt)
        inits[f"t_{dt}"] = a
    data = pb.model_to_bytes({"nodes": [], "inputs": [], "outputs": [],
                              "initializers": inits})
    g = pb.bytes_to_model(data)["graph"]
    for k, v in inits.items():
        np.testing.assert_array_equal(g["initializers"][k], v)
        assert g["initializers"][k].dtype == v.dtype


def test_golden_bytes_fixture_stable():
    """Schema pin: the serialized form of a fixed graph must stay
    byte-identical (field numbers / ordering / varint encoding)."""
    g = {"nodes": [{"op_type": "Relu", "name": "r", "inputs": ["x"],
                    "outputs": ["y"], "attrs": {}}],
         "inputs": [{"name": "x", "dtype": "float32", "shape": (1, 2)}],
         "outputs": [{"name": "y"}],
         "initializers": {"w": np.asarray([1.0, 2.0], dtype=np.float32)}}
    data = pb.model_to_bytes(g)
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "golden_tiny.onnx")
    if not os.path.exists(fixture):
        os.makedirs(os.path.dirname(fixture), exist_ok=True)
        with open(fixture, "wb") as f:
            f.write(data)
    with open(fixture, "rb") as f:
        assert f.read() == data, (
            "ONNX wire emission changed for an identical graph — if "
            "intentional, regenerate tests/fixtures/golden_tiny.onnx")


def test_parse_tensor_packed_dims():
    """proto3 serializers emit TensorProto.dims packed (wire type 2);
    the parser must accept both packed and unpacked forms (ADVICE r3)."""
    import numpy as np
    from mxnet_tpu.contrib.onnx import protobuf as pb
    arr = np.arange(12, dtype="float32").reshape(3, 4)
    buf = pb._tensor_proto("t", arr)
    # re-encode dims [3, 4] as one packed field-1 entry, dropping the two
    # unpacked varint entries the encoder emitted
    out = bytearray()
    packed = bytearray()
    for f, w, v in pb._iter_fields(buf):
        if f == 1:
            packed += pb._varint(v)
        elif w == 0:
            out += pb._f_varint(f, v)
        else:
            out += pb._len_delim(f, v)
    out = pb._len_delim(1, bytes(packed)) + bytes(out)
    name, parsed = pb._parse_tensor(bytes(out))
    assert name == "t"
    assert parsed.shape == (3, 4)
    assert np.array_equal(parsed, arr)
