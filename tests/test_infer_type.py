"""Symbol.infer_type: the per-op dtype pass (symbol/dtype_infer.py).

Ports the reference's infer_type coverage —
tests/python/unittest/test_infer_type.py (multi-output autograd dtype),
test_operator.py:3178 (symbol infer_type seeded from either input) — and
adds the dtype-forcing cases the pass exists for: Cast/amp_cast,
quantization graphs, Embedding, BatchNorm float16 statistics, index
outputs, creation ops, and the AMP/int8 symbols the builder's own passes
produce (reference per-op FInferType, c_api_symbolic.cc:571).
"""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_cast_forces_output_dtype():
    a = mx.sym.Variable("a")
    c = mx.sym.Cast(a, dtype="float16")
    arg_t, out_t, _ = c.infer_type(a="float32")
    assert arg_t[0] == np.float32
    assert out_t[0] == np.float16


def test_cast_chain_mixed():
    a = mx.sym.Variable("a")
    h = mx.sym.Cast(a, dtype="float16")
    y = mx.sym.Cast(h * 2.0, dtype="float64")
    arg_t, out_t, _ = y.infer_type(a="float32")
    assert arg_t[0] == np.float32
    assert out_t[0] == np.float64


def test_same_dtype_propagates_to_params():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.Activation(net, act_type="relu")
    arg_t, out_t, _ = net.infer_type(data="float16")
    names = net.list_arguments()
    assert dict(zip(names, arg_t)) == {
        "data": np.dtype("float16"), "fc_weight": np.dtype("float16"),
        "fc_bias": np.dtype("float16")}
    assert out_t[0] == np.float16


def test_seeded_from_either_input():
    """reference test_operator.py:3178 — inference seeded from a or b."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    s = mx.sym.broadcast_add(a, b)
    for dtype in ["float16", "float32", "float64"]:
        arg1, out1, _ = s.infer_type(a=dtype)
        assert arg1 == [np.dtype(dtype)] * 2 and out1[0] == np.dtype(dtype)
        arg2, out2, _ = s.infer_type(b=dtype)
        assert arg2 == [np.dtype(dtype)] * 2 and out2[0] == np.dtype(dtype)


def test_backward_unification_from_output_consumer():
    """A dtype given downstream flows backward through same-dtype ops."""
    a = mx.sym.Variable("a")
    w = mx.sym.Variable("w", dtype="float64")
    y = mx.sym.elemwise_add(a, w)
    arg_t, out_t, _ = y.infer_type()
    assert dict(zip(y.list_arguments(), arg_t))["a"] == np.float64
    assert out_t[0] == np.float64


def test_integer_index_does_not_pollute_floats():
    """ADVICE r4 (low): an int index given first must not turn float
    params/outputs integer."""
    idx = mx.sym.Variable("idx")
    emb = mx.sym.Embedding(idx, input_dim=10, output_dim=4, name="emb")
    out = mx.sym.FullyConnected(emb, num_hidden=2, name="fc")
    arg_t, out_t, _ = out.infer_type(idx="int32")
    by_name = dict(zip(out.list_arguments(), arg_t))
    assert by_name["idx"] == np.int32
    assert by_name["emb_weight"] == np.float32
    assert by_name["fc_weight"] == np.float32
    assert out_t[0] == np.float32


def test_embedding_dtype_attr():
    idx = mx.sym.Variable("idx")
    emb = mx.sym.Embedding(idx, input_dim=10, output_dim=4,
                           dtype="float16", name="emb")
    arg_t, out_t, _ = emb.infer_type(idx="int32")
    by_name = dict(zip(emb.list_arguments(), arg_t))
    assert by_name["emb_weight"] == np.float16
    assert out_t[0] == np.float16


def test_batchnorm_float16_keeps_float32_stats():
    """reference batch_norm.cc BatchNormType: fp16 data, fp32 params."""
    x = mx.sym.Variable("x")
    bn = mx.sym.BatchNorm(x, name="bn", fix_gamma=False)
    arg_t, out_t, aux_t = bn.infer_type(x="float16")
    by_name = dict(zip(bn.list_arguments(), arg_t))
    assert by_name["x"] == np.float16
    assert by_name["bn_gamma"] == np.float32
    assert by_name["bn_beta"] == np.float32
    assert all(t == np.float32 for t in aux_t)
    assert out_t[0] == np.float16
    # fp32 data keeps fp32 everywhere
    arg_t, out_t, aux_t = bn.infer_type(x="float32")
    assert all(t == np.float32 for t in arg_t + aux_t) \
        and out_t[0] == np.float32


def test_quantize_v2_graph_types():
    d = mx.sym.Variable("d")
    q = mx.sym.contrib.quantize_v2(d, min_calib_range=0.0,
                                   max_calib_range=1.0)
    _, out_t, _ = q.infer_type(d="float32")
    assert out_t[0] == np.int8
    assert out_t[1] == np.float32 and out_t[2] == np.float32


def test_quantize_dequantize_round_trip_types():
    d = mx.sym.Variable("d")
    mn = mx.sym.Variable("mn")
    mxv = mx.sym.Variable("mx")
    q = mx.sym.contrib.quantize(d, mn, mxv)
    deq = mx.sym.contrib.dequantize(q[0], q[1], q[2])
    arg_t, out_t, _ = deq.infer_type(d="float32")
    assert out_t[0] == np.float32
    _, q_out, _ = q.infer_type(d="float32")
    assert q_out[0] == np.uint8            # quantize defaults to uint8


def test_amp_converted_symbol_round_trips():
    """The builder's own AMP pass output must infer correctly."""
    from mxnet_tpu.contrib import amp
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.softmax(mx.sym.Activation(net, act_type="relu"))
    conv = amp.convert_symbol(net, target_dtype="float16")
    ops = [n.op.name for n in conv._topo() if n.op is not None]
    assert "amp_cast" in ops
    arg_t, out_t, _ = conv.infer_type(data="float32")
    assert all(t == np.float32 for t in arg_t)   # params held in fp32
    assert out_t[0] == np.float32                # cast back before softmax
    # the FC itself runs in fp16: check via internals
    internals = conv.get_internals()
    _, int_t, _ = internals.infer_type(data="float32")
    by_name = dict(zip(internals.list_outputs(), int_t))
    fc_keys = [k for k in by_name
               if k.startswith("fc") and k.endswith("_output")
               and "amp_cast" not in k]
    assert fc_keys and all(by_name[k] == np.float16 for k in fc_keys), \
        by_name
    assert any(by_name[k] == np.float16 for k in by_name
               if "amp_cast" in k), by_name


def test_topk_argsort_index_dtypes():
    a = mx.sym.Variable("a")
    _, out_t, _ = mx.sym.topk(a, k=2).infer_type(a="float16")
    assert out_t[0] == np.float32              # default index dtype
    _, out_t, _ = mx.sym.topk(a, k=2, ret_typ="value") \
        .infer_type(a="float16")
    assert out_t[0] == np.float16
    _, out_t, _ = mx.sym.topk(a, k=2, ret_typ="both", dtype="int32") \
        .infer_type(a="float16")
    assert out_t[0] == np.float16 and out_t[1] == np.int32
    _, out_t, _ = mx.sym.argsort(a, dtype="int32").infer_type(a="float64")
    assert out_t[0] == np.int32


def test_one_hot_and_creation_ops():
    idx = mx.sym.Variable("idx")
    _, out_t, _ = mx.sym.one_hot(idx, depth=4).infer_type(idx="int32")
    assert out_t[0] == np.float32
    _, out_t, _ = mx.sym.one_hot(idx, depth=4, dtype="int64") \
        .infer_type(idx="int32")
    assert out_t[0] == np.int64
    _, out_t, _ = mx.sym.zeros_like(mx.sym.Variable("z")) \
        .infer_type(z="float16")
    assert out_t[0] == np.float16


def test_where_and_take_index_inputs_free():
    cond = mx.sym.Variable("c")
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    w = mx.sym.where(cond, a, b)
    arg_t, out_t, _ = w.infer_type(c="int32", a="float16")
    by_name = dict(zip(w.list_arguments(), arg_t))
    assert by_name["c"] == np.int32 and by_name["b"] == np.float16
    assert out_t[0] == np.float16

    d = mx.sym.Variable("d")
    i = mx.sym.Variable("i")
    t = mx.sym.take(d, i)
    arg_t, out_t, _ = t.infer_type(d="float64", i="int32")
    by_name = dict(zip(t.list_arguments(), arg_t))
    assert by_name["i"] == np.int32 and out_t[0] == np.float64


def test_conflict_raises_and_partial_does_not():
    a = mx.sym.Variable("a", dtype="float16")
    b = mx.sym.Variable("b", dtype="float32")
    s = mx.sym.elemwise_add(a, b)
    with pytest.raises(ValueError):
        s.infer_type()
    arg_t, out_t, _ = s.infer_type_partial()
    assert len(arg_t) == 2     # no raise; best-effort result


def test_infer_type_partial_leaves_unknown_none():
    a = mx.sym.Variable("a")
    i = mx.sym.Variable("i")
    t = mx.sym.take(a, i)
    arg_t, out_t, _ = t.infer_type_partial(a="float16")
    by_name = dict(zip(t.list_arguments(), arg_t))
    assert by_name["a"] == np.float16
    assert by_name["i"] is None            # index stays unconstrained
    assert out_t[0] == np.float16


def test_defaults_to_float32_when_nothing_given():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    arg_t, out_t, _ = net.infer_type()
    assert all(t == np.float32 for t in arg_t) and out_t[0] == np.float32


def test_multiout_autograd_dtype():
    """reference test_infer_type.py test_infer_multiout_op: grad dtype
    follows data dtype through a multi-output op.  (The reference uses
    float64; jax runs x32, so the non-default dtype here is float16 —
    same contract.)"""
    from mxnet_tpu import autograd
    data = mx.nd.arange(16, dtype=np.float16).reshape((4, 4))
    data.attach_grad()
    with autograd.record():
        y = mx.nd.split(data, axis=0, num_outputs=2)
    y[0].backward()
    assert data.grad.dtype == np.float16


def test_cast_grad_dtype_matches():
    """reference test_infer_multiout_op2: the cast-dtype path numerically
    matches the f32 path and grads carry the cast dtype (float16 stands
    in for the reference's float64 under jax x32)."""
    from mxnet_tpu import autograd
    rng = np.random.RandomState(0)
    data32 = mx.nd.array(rng.randn(2, 3).astype(np.float32))
    data32.attach_grad()
    with autograd.record():
        t32 = mx.nd.sum(data32 * data32)
    t32.backward()
    data16 = mx.nd.Cast(data32, dtype=np.float16)
    data16.attach_grad()
    with autograd.record():
        t16 = mx.nd.sum(data16 * data16)
    t16.backward()
    assert data16.grad.dtype == np.float16
    np.testing.assert_allclose(data16.grad.asnumpy(),
                               data32.grad.asnumpy(), rtol=1e-2, atol=1e-2)


def test_shape_array_dtype():
    a = mx.sym.Variable("a")
    _, out_t, _ = mx.sym.shape_array(a).infer_type(a="float16")
    assert out_t[0] == np.int32    # jax x32 (reference: int64; documented)


def test_shared_input_slots_do_not_clobber():
    """One producer output feeding several input positions of one node
    (take(d, d)) must keep the dtype inferred through any of them."""
    d = mx.sym.Variable("d")
    w = mx.sym.Variable("w", dtype="float64")
    y = mx.sym.elemwise_add(mx.sym.take(d, d), w)
    arg_t, out_t, _ = y.infer_type()
    by_name = dict(zip(y.list_arguments(), arg_t))
    assert by_name["d"] == np.float64
    assert out_t[0] == np.float64


def test_unknown_kwarg_name_raises():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    s = mx.sym.broadcast_add(a, b)
    with pytest.raises(ValueError, match="matches no variable"):
        s.infer_type(aa="float16")


def test_moments_var_output_keeps_data_dtype():
    """moments returns both outputs in the data dtype (unlike LayerNorm's
    f32 saved stats) — the inferred type must match execution."""
    x = mx.sym.Variable("x")
    z = mx.sym.Variable("z")
    m = mx.sym.moments(x, axes=(0,))
    y = mx.sym.broadcast_add(m[1], z)
    arg_t, out_t, _ = y.infer_type(x="float16", z="float16")
    assert out_t[0] == np.float16


def test_int8_pool_avg_requant_dtype():
    """avg int8_pool emits int8 when out_scale>0, f32 otherwise — the
    rule must match ops/int8_ops.py execution."""
    d = mx.sym.Variable("d")
    s1 = mx.sym.contrib.int8_pool(d, kernel=(2, 2), pool_type="avg",
                                  in_scale=0.5, out_scale=2.0)
    _, out_t, _ = s1.infer_type(d="int8")
    assert out_t[0] == np.int8
    s2 = mx.sym.contrib.int8_pool(d, kernel=(2, 2), pool_type="avg",
                                  in_scale=0.5)
    _, out_t, _ = s2.infer_type(d="int8")
    assert out_t[0] == np.float32
