"""NDArray contract tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert a.size == 4
    assert a.ndim == 2
    z = nd.zeros((3, 4))
    assert z.asnumpy().sum() == 0
    o = nd.ones((2, 3), dtype="float16")
    assert o.dtype == np.float16
    f = nd.full((2, 2), 7)
    assert (f.asnumpy() == 7).all()
    r = nd.arange(0, 10, 2)
    assert (r.asnumpy() == np.arange(0, 10, 2)).all()


def test_default_dtype_is_float32():
    a = nd.array(np.ones((2, 2)))  # float64 numpy input
    assert a.dtype == np.float32


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((a + b).asnumpy(), a.asnumpy() + b.asnumpy())
    assert np.allclose((a - b).asnumpy(), a.asnumpy() - b.asnumpy())
    assert np.allclose((a * b).asnumpy(), a.asnumpy() * b.asnumpy())
    assert np.allclose((a / b).asnumpy(), a.asnumpy() / b.asnumpy())
    assert np.allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert np.allclose((2 + a).asnumpy(), 2 + a.asnumpy())
    assert np.allclose((2 - a).asnumpy(), 2 - a.asnumpy())
    assert np.allclose((2 / a).asnumpy(), 2 / a.asnumpy())
    assert np.allclose((-a).asnumpy(), -a.asnumpy())


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 1
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a -= 1
    assert (a.asnumpy() == 5).all()
    a /= 5
    assert (a.asnumpy() == 1).all()


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert ((a > b).asnumpy() == [0, 0, 1]).all()
    assert ((a >= b).asnumpy() == [0, 1, 1]).all()
    assert ((a == b).asnumpy() == [0, 1, 0]).all()
    assert ((a != 2).asnumpy() == [1, 0, 1]).all()
    # dtype preserved (MXNet semantics: not bool)
    assert (a > b).dtype == np.float32


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[1].shape == (3, 4)
    assert a[0, 1, 2].asscalar() == 6
    assert a[:, 1].shape == (2, 4)
    assert a[1, 0:2].shape == (2, 4)
    a[0] = 0
    assert a.asnumpy()[0].sum() == 0
    a[1, 2, 3] = 99
    assert a[1, 2, 3].asscalar() == 99


def test_reshape_transpose():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a.reshape((4, 3)).shape == (4, 3)
    assert a.reshape((-1,)).shape == (12,)
    assert a.reshape((2, -1)).shape == (2, 6)
    assert a.T.shape == (4, 3)
    assert nd.reshape(a, (0, -1)).shape == (3, 4)
    assert a.reshape((-4, 1, 3, 0)).shape == (1, 3, 4)
    b = nd.array(np.arange(24).reshape(2, 3, 4))
    assert b.transpose((2, 0, 1)).shape == (4, 2, 3)
    assert b.swapaxes(0, 2).shape == (4, 3, 2)
    assert b.flatten().shape == (2, 12)
    assert b.expand_dims(1).shape == (2, 1, 3, 4)


def test_reduce():
    a = nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    assert a.sum().asscalar() == 66
    assert np.allclose(a.sum(axis=0).asnumpy(), a.asnumpy().sum(0))
    assert np.allclose(a.mean(axis=1).asnumpy(), a.asnumpy().mean(1))
    assert a.max().asscalar() == 11
    assert a.min().asscalar() == 0
    assert a.sum(axis=0, keepdims=True).shape == (1, 4)
    # exclude semantics
    s = nd.sum(a, axis=0, exclude=True)
    assert np.allclose(s.asnumpy(), a.asnumpy().sum(1))
    assert a.argmax(axis=1).dtype == np.float32


def test_broadcast():
    a = nd.ones((1, 4))
    assert a.broadcast_to((3, 4)).shape == (3, 4)
    b = nd.ones((3, 1))
    c = nd.broadcast_add(a, b)
    assert c.shape == (3, 4)
    assert (c.asnumpy() == 2).all()


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(nd.array(np.arange(12).reshape(2, 6)), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_astype_copy():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[0] = 100
    assert a[0].asscalar() == 1.5
    d = nd.zeros((2,))
    a.copyto(d)
    assert np.allclose(d.asnumpy(), a.asnumpy())


def test_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0)


def test_wait_sync():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.asnumpy()[0, 0] == 100


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    a, b = nd.ones((2, 2)), nd.arange(0, 4)
    nd.save(fname, [a, b])
    loaded = nd.load(fname)
    assert np.allclose(loaded[0].asnumpy(), a.asnumpy())
    nd.save(fname, {"x": a, "y": b})
    d = nd.load(fname)
    assert set(d) == {"x", "y"}
    assert np.allclose(d["y"].asnumpy(), b.asnumpy())


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype("float32"))
    b = nd.array(np.random.rand(4, 5).astype("float32"))
    c = nd.dot(a, b)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    ct = nd.dot(a, b.T, transpose_b=True)
    assert np.allclose(ct.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    x = nd.array(np.random.rand(2, 3, 4).astype("float32"))
    y = nd.array(np.random.rand(2, 4, 5).astype("float32"))
    z = nd.batch_dot(x, y)
    assert z.shape == (2, 3, 5)


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert int(a) == 3
    assert a.asscalar() == 3.5
    with pytest.raises(ValueError):
        nd.ones((2,)).asscalar()


def test_take_pick_onehot():
    a = nd.array(np.arange(12).reshape(3, 4))
    t = nd.take(a, nd.array([0, 2]), axis=0)
    assert t.shape == (2, 4)
    p = nd.pick(a, nd.array([0, 1, 2]), axis=1)
    assert np.allclose(p.asnumpy(), [0, 5, 10])
    oh = nd.one_hot(nd.array([0, 2]), 3)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    assert np.allclose(idx.asnumpy(), [[0, 2], [1, 2]])
    v = nd.topk(a, k=1, ret_typ="value")
    assert np.allclose(v.asnumpy(), [[3], [5]])
    s = nd.sort(a, axis=1)
    assert np.allclose(s.asnumpy(), np.sort(a.asnumpy(), 1))
    ags = nd.argsort(a, axis=1)
    assert np.allclose(ags.asnumpy(), np.argsort(a.asnumpy(), 1))


def test_where_clip():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([-1.0, -2.0, -3.0])
    w = nd.where(cond, x, y)
    assert np.allclose(w.asnumpy(), [1, -2, 3])
    c = nd.clip(nd.array([-2.0, 0.5, 2.0]), 0.0, 1.0)
    assert np.allclose(c.asnumpy(), [0, 0.5, 1])


def test_iteration():
    a = nd.array(np.arange(6).reshape(3, 2))
    rows = [r.asnumpy() for r in a]
    assert len(rows) == 3
    assert np.allclose(rows[1], [2, 3])


# --- r4 depth additions (reference test_ndarray.py remainder)

def test_moveaxis_swapaxes():
    x = mx.nd.array(np.arange(24, dtype="float32").reshape(2, 3, 4))
    np.testing.assert_allclose(mx.nd.moveaxis(x, 0, 2).asnumpy(),
                               np.moveaxis(x.asnumpy(), 0, 2))
    np.testing.assert_allclose(mx.nd.swapaxes(x, 0, 2).asnumpy(),
                               np.swapaxes(x.asnumpy(), 0, 2))


def test_arange_variants():
    np.testing.assert_allclose(mx.nd.arange(5).asnumpy(), np.arange(5))
    np.testing.assert_allclose(mx.nd.arange(2, 10, 3).asnumpy(),
                               np.arange(2, 10, 3))
    out = mx.nd.arange(0, 4, repeat=2)
    np.testing.assert_allclose(out.asnumpy(), [0, 0, 1, 1, 2, 2, 3, 3])
    assert mx.nd.arange(3, dtype="int32").dtype == np.int32


def test_full_and_ones_like():
    f = mx.nd.full((2, 3), 7.5)
    np.testing.assert_allclose(f.asnumpy(), np.full((2, 3), 7.5))
    o = mx.nd.ones_like(f)
    np.testing.assert_allclose(o.asnumpy(), np.ones((2, 3)))
    z = mx.nd.zeros_like(f)
    np.testing.assert_allclose(z.asnumpy(), np.zeros((2, 3)))


def test_negative_step_slicing():
    x = mx.nd.array(np.arange(10, dtype="float32"))
    np.testing.assert_allclose(x[::-1].asnumpy(), np.arange(10)[::-1])
    np.testing.assert_allclose(x[8:2:-2].asnumpy(),
                               np.arange(10)[8:2:-2])


def test_copyto_and_copy_semantics():
    a = mx.nd.array(np.ones((2, 2), "float32"))
    b = mx.nd.zeros((2, 2))
    a.copyto(b)
    np.testing.assert_allclose(b.asnumpy(), np.ones((2, 2)))
    c = a.copy()
    a += 1
    np.testing.assert_allclose(c.asnumpy(), np.ones((2, 2)))  # deep copy


def test_iadd_preserves_attached_grad_buffer():
    """In-place arithmetic on a grad-attached array keeps autograd
    working (reference in-place semantics)."""
    x = mx.nd.array(np.ones(3, "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = (x * 3).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3, 3, 3])
    x += 1                        # in-place outside record
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 2, 2])


def test_tolist_asscalar_item():
    x = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]]))
    assert x[0].asnumpy().tolist() == [1.0, 2.0]
    s = mx.nd.array([42.0])
    assert s.asscalar() == 42.0
    with pytest.raises(Exception):
        x.asscalar()              # non-size-1 must refuse


def test_expand_dims_squeeze_roundtrip():
    x = mx.nd.zeros((3, 4))
    y = mx.nd.expand_dims(x, axis=0)
    assert y.shape == (1, 3, 4)
    assert mx.nd.squeeze(y, axis=0).shape == (3, 4)
    assert mx.nd.squeeze(mx.nd.zeros((1, 3, 1))).shape == (3,)


def test_size_ndim_properties():
    x = mx.nd.zeros((2, 3, 4))
    assert x.size == 24 and x.ndim == 3
    assert len(x) == 2


def test_broadcast_like_and_axis():
    a = mx.nd.array(np.arange(4, dtype="float32").reshape(1, 4))
    b = mx.nd.broadcast_like(a, mx.nd.zeros((3, 4)))
    assert b.shape == (3, 4)
    c = mx.nd.broadcast_axis(a, axis=0, size=5)
    assert c.shape == (5, 4)
    np.testing.assert_allclose(c.asnumpy()[4], a.asnumpy()[0])


def test_concatenate_alias():
    a, b = mx.nd.ones((2, 2)), mx.nd.zeros((2, 2))
    out = mx.nd.concatenate([a, b], axis=0)
    assert out.shape == (4, 2)
