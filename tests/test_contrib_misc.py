"""Estimator / launcher / rtc / text / SVRG tests."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.contrib.estimator import (
    Estimator, EarlyStoppingHandler, CheckpointHandler, LoggingHandler,
    MetricHandler,
)


def _toy(n=128, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype("float32")
    y = (x.sum(axis=1) > 0).astype("float32")
    return x, y


def test_estimator_fit_and_evaluate():
    x, y = _toy()
    net = mx.gluon.nn.Dense(2, in_units=6)
    net.initialize()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "adam",
                                             {"learning_rate": 0.05}))
    loader = mx.gluon.data.DataLoader(mx.gluon.data.ArrayDataset(x, y),
                                      batch_size=32)
    with pytest.warns(UserWarning):
        est.fit(loader, epochs=10)
    res = est.evaluate(loader)
    assert res[0][1] > 0.9, res


def test_estimator_early_stopping_and_checkpoint(tmp_path):
    x, y = _toy(64)
    net = mx.gluon.nn.Dense(2, in_units=6)
    net.initialize()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
    loader = mx.gluon.data.DataLoader(mx.gluon.data.ArrayDataset(x, y),
                                      batch_size=32)
    handlers = [EarlyStoppingHandler(est.train_metrics[0], patience=1),
                CheckpointHandler(str(tmp_path), epoch_period=1),
                MetricHandler(est.train_metrics),
                LoggingHandler(metrics=est.train_metrics)]
    est.fit(loader, epochs=5, event_handlers=handlers)
    assert any(f.endswith(".params") for f in os.listdir(tmp_path))


def test_launch_local(tmp_path):
    """tools/launch.py spawns N workers with the coordinator env."""
    script = tmp_path / "w.py"
    # per-rank files: concurrent stdout lines can interleave mid-line
    script.write_text(
        "import os\n"
        f"open(os.path.join({str(tmp_path)!r}, "
        "'rank%s' % os.environ['JAX_PROCESS_ID']), 'w').write(\n"
        "    os.environ['JAX_NUM_PROCESSES'])\n")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "launch.py"),
         "-n", "2", "--port", "29745", sys.executable, str(script)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert (tmp_path / "rank0").read_text() == "2"
    assert (tmp_path / "rank1").read_text() == "2"


def test_rtc_compat():
    with pytest.raises(NotImplementedError):
        mx.rtc.CudaModule("__global__ void k() {}")
    src = "def double_k(x_ref, o_ref):\n    o_ref[:] = x_ref[:] * 2.0\n"
    fn = mx.rtc.compile_pallas(src, "double_k", ((8, 128), "float32"))
    import jax.numpy as jnp
    out = fn(jnp.ones((8, 128), jnp.float32))
    assert float(out.sum()) == 2 * 8 * 128


def test_text_vocab_and_embedding(tmp_path):
    from mxnet_tpu.contrib import text
    counter = text.utils.count_tokens_from_str("a b b c c c\nd d d d")
    vocab = text.Vocabulary(counter, min_freq=2, reserved_tokens=["<pad>"])
    assert vocab.to_indices("d") == 2  # most frequent after unk/pad
    assert vocab.to_tokens(0) == "<unk>"
    assert len(vocab) == 5  # unk, pad, d, c, b

    emb_file = tmp_path / "emb.txt"
    emb_file.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(str(emb_file))
    v = emb.get_vecs_by_tokens(["hello", "nope"])
    np.testing.assert_allclose(v.asnumpy(), [[1, 2, 3], [0, 0, 0]])
    emb.update_token_vectors("world", mx.nd.array([9.0, 9.0, 9.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [9, 9, 9])
    with pytest.raises(KeyError):
        text.embedding.create("glove")


def test_svrg_module_converges():
    from mxnet_tpu.contrib.svrg_optimization import SVRGModule
    x, y = _toy(120)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(x, y, batch_size=30, shuffle=True)
    mod = SVRGModule(net, context=mx.cpu(), update_freq=2)
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 1.0})
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=30), "acc")
    assert score[0][1] > 0.9, score


# --- r4 depth: estimator event handlers (reference
# test_gluon_event_handler.py)

def test_estimator_resume_from_checkpoint(tmp_path):
    """reference test_resume_checkpoint: CheckpointHandler(resume_from_
    checkpoint) restarts training from the saved epoch."""
    from mxnet_tpu.gluon.contrib.estimator import CheckpointHandler
    x, y = _toy(64)
    net = mx.gluon.nn.Dense(2, in_units=6)
    net.initialize()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
    loader = mx.gluon.data.DataLoader(
        mx.gluon.data.ArrayDataset(x, y), batch_size=32)
    ck = CheckpointHandler(str(tmp_path), model_prefix="m",
                           epoch_period=1, max_checkpoints=5)
    est.fit(loader, epochs=3,
            event_handlers=[ck, MetricHandler(est.train_metrics),
                            LoggingHandler(metrics=est.train_metrics)])
    saved = [f for f in os.listdir(tmp_path) if f.endswith(".params")]
    assert len(saved) >= 2


def test_estimator_custom_handler_order():
    """reference test_custom_handler: user handlers fire at the right
    lifecycle points."""
    from mxnet_tpu.gluon.contrib.estimator.event_handler import (
        TrainBegin, EpochEnd, TrainEnd)

    events = []

    class Probe(TrainBegin, EpochEnd, TrainEnd):
        def train_begin(self, estimator, *args, **kwargs):
            events.append("begin")

        def epoch_end(self, estimator, *args, **kwargs):
            events.append("epoch")

        def train_end(self, estimator, *args, **kwargs):
            events.append("end")

    x, y = _toy(64)
    net = mx.gluon.nn.Dense(2, in_units=6)
    net.initialize()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
    loader = mx.gluon.data.DataLoader(
        mx.gluon.data.ArrayDataset(x, y), batch_size=32)
    est.fit(loader, epochs=2,
            event_handlers=[Probe(), MetricHandler(est.train_metrics),
                            LoggingHandler(metrics=est.train_metrics)])
    assert events[0] == "begin" and events[-1] == "end"
    assert events.count("epoch") == 2


def test_contrib_dataloader_iter_wraps_gluon_loader():
    """reference test_contrib_io: DataLoaderIter drives Module.fit from a
    gluon DataLoader."""
    from mxnet_tpu.contrib.io import DataLoaderIter
    x, y = _toy(100)                      # NOT divisible: exercises pad
    loader = mx.gluon.data.DataLoader(
        mx.gluon.data.ArrayDataset(x, y), batch_size=32, shuffle=False)
    it = DataLoaderIter(loader)
    assert it.batch_size == 32
    assert it.provide_data[0].shape == (32, 6)
    n = 0
    for batch in it:
        # pad contract: arrays are always full batch_size
        assert batch.data[0].shape[0] == 32
        n += 32 - (batch.pad or 0)
    assert n == 100
    it.reset()
    # drives the Module API end-to-end
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.9, acc
