"""Module API tests (reference ``tests/python/unittest/test_module.py`` and
``tests/python/train/test_mlp.py``)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp_sym(nclass=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=nclass)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=200, dim=8, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(nclass, dim) * 3
    y = rng.randint(0, nclass, n)
    x = centers[y] + rng.randn(n, dim) * 0.5
    return x.astype("float32"), y.astype("float32")


def test_module_dtype_and_shapes():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (10, 8))],
             label_shapes=[("softmax_label", (10,))])
    assert mod.data_shapes[0].shape == (10, 8)
    assert mod.label_shapes[0].shape == (10,)
    mod.init_params()
    assert mod.output_shapes[0][1] == (10, 4)


def test_module_input_grads():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (10, 8))],
             label_shapes=[("softmax_label", (10,))],
             inputs_need_grad=True)
    mod.init_params()
    x, y = _toy_data(10)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    (din,) = mod.get_input_grads()
    assert din.shape == (10, 8)
    assert np.abs(din.asnumpy()).sum() > 0


def test_module_fit_converges():
    x, y = _toy_data(240)
    it = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=12,
            optimizer="sgd", optimizer_params={"learning_rate": 0.3})
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.9, score


def test_module_predict_and_outputs():
    x, y = _toy_data(100)
    it = mx.io.NDArrayIter(x, y, batch_size=25)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (100, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(100),
                               rtol=1e-4)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_data(80)
    it = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0002.params")

    mod2 = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    out1 = mod.predict(mx.io.NDArrayIter(x, y, batch_size=20)).asnumpy()
    out2 = mod2.predict(mx.io.NDArrayIter(x, y, batch_size=20)).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-6)


def test_module_set_get_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (10, 8))],
             label_shapes=[("softmax_label", (10,))])
    mod.init_params(initializer=mx.init.Zero())
    args, auxs = mod.get_params()
    assert float(args["fc1_weight"].asnumpy().sum()) == 0.0
    args["fc1_weight"][:] = 1.0
    mod.set_params(args, auxs)
    got, _ = mod.get_params()
    assert float(got["fc1_weight"].asnumpy().mean()) == 1.0


def test_module_update_with_kvstore():
    x, y = _toy_data(80)
    it = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=3, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=20), "acc")
    assert score[0][1] > 0.8, score


def test_bucketing_module():
    """Variable-length 'sequences' via buckets (reference
    ``tests/python/train/test_bucketing.py`` shape)."""
    FEAT = 5

    def sym_gen(seq_len):
        # params are shape-invariant across buckets (like RNN cells): mean
        # over the variable-length axis, then shared dense layers
        data = mx.sym.Variable("data")
        net = mx.sym.mean(data, axis=1)
        net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
        net = mx.sym.Activation(net, name="relu1", act_type="relu")
        net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    rng = np.random.RandomState(0)

    def batch_for(seq, bs=16):
        x = rng.randn(bs, seq, FEAT).astype("float32")
        y = (x.mean(axis=(1, 2)) > 0).astype("float32")
        return mx.io.DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(y)], bucket_key=seq,
            provide_data=[mx.io.DataDesc("data", (bs, seq, FEAT))],
            provide_label=[mx.io.DataDesc("softmax_label", (bs,))])

    mod.bind(data_shapes=[("data", (16, 8, FEAT))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    fixed = [batch_for(dim) for dim in (8, 4, 6)]
    for i in range(40):
        for b in fixed:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
    # weights are shared across buckets: every bucket fits its batch
    m = mx.metric.Accuracy()
    for b in fixed:
        mod.forward(b, is_train=False)
        mod.update_metric(m, b.label)
    assert m.get()[1] > 0.9, m.get()
    # parameter arrays are literally shared (reference shared-memory pool)
    assert mod._buckets[4]._exec.arg_dict["fc1_weight"] is \
        mod._buckets[8]._exec.arg_dict["fc1_weight"]


def test_speedometer_and_callbacks(caplog):
    import logging
    caplog.set_level(logging.INFO)
    x, y = _toy_data(80)
    it = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            batch_end_callback=mx.callback.Speedometer(20, 2))
    assert any("Speed" in r.message for r in caplog.records)


# ---------------------------------------------------------------- multi-device
# TPU-native DataParallelExecutorGroup (reference executor_group.py:282-304):
# context=[c0..ck] runs ONE SPMD program with the batch sharded over a dp mesh.

def _need_cpu_devices(n):
    import jax
    if len([d for d in jax.devices() if d.platform == "cpu"]) < n:
        pytest.skip(f"needs {n} cpu devices")


def test_module_multi_device_fit_matches_single():
    _need_cpu_devices(4)
    x, y = _toy_data(240)
    mx.random.seed(7)
    ref = mx.mod.Module(_mlp_sym(), context=mx.cpu(0))
    ref.bind(data_shapes=[("data", (40, 8))],
             label_shapes=[("softmax_label", (40,))])
    ref.init_params()
    args0, auxs0 = ref.get_params()

    trained = {}
    for tag, ctxs in (("single", [mx.cpu(0)]),
                      ("multi", [mx.cpu(i) for i in range(4)])):
        it = mx.io.NDArrayIter(x, y, batch_size=40)
        mod = mx.mod.Module(_mlp_sym(), context=ctxs)
        mod.fit(it, num_epoch=3, arg_params={k: v.copy()
                                             for k, v in args0.items()},
                aux_params=dict(auxs0),
                optimizer="sgd", optimizer_params={"learning_rate": 0.2})
        trained[tag] = mod.get_params()[0]
    for k in trained["single"]:
        np.testing.assert_allclose(trained["single"][k].asnumpy(),
                                   trained["multi"][k].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_module_multi_device_actually_spans_devices():
    _need_cpu_devices(4)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(i) for i in range(4)])
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    x, yy = _toy_data(8)
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(yy)])
    mod.forward(batch, is_train=True)
    out = mod.get_outputs()[0]
    assert len(out._data.sharding.device_set) == 4
    mod.backward()
    mod.init_optimizer(optimizer="sgd")
    mod.update()   # replicated grads/params update fine


def test_module_multi_device_bad_batch_raises():
    _need_cpu_devices(4)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(i) for i in range(4)])
    with pytest.raises(ValueError, match="divisible"):
        mod.bind(data_shapes=[("data", (10, 8))],
                 label_shapes=[("softmax_label", (10,))])


def test_module_duplicate_device_raises():
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(0)])
    with pytest.raises(ValueError, match="duplicate"):
        mod.bind(data_shapes=[("data", (8, 8))],
                 label_shapes=[("softmax_label", (8,))])


# ------------------------------------------------ Sequential / Python modules
def test_sequential_module_trains():
    """Two chained symbolic stages (reference sequential_module.py):
    features → classifier, labels consumed by the last stage."""
    x, y = _toy_data(240)
    d1 = mx.sym.Variable("data")
    feat = mx.sym.Activation(mx.sym.FullyConnected(d1, name="fc1",
                                                   num_hidden=32),
                             act_type="relu")
    d2 = mx.sym.Variable("data")
    head = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(d2, name="fc2",
                                                      num_hidden=4),
                                name="softmax")
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(feat, label_names=None, context=mx.cpu()))
    seq.add(mx.mod.Module(head, context=mx.cpu()), take_labels=True,
            auto_wiring=True)
    it = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    seq.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    score = seq.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.9, score


def test_python_loss_module_chain():
    """Symbolic features + a Python loss head (reference
    python_module.py PythonLossModule)."""
    x, y = _toy_data(120, nclass=2)
    onehot = np.eye(2, dtype="float32")[y.astype(int)]
    d = mx.sym.Variable("data")
    net = mx.sym.softmax(mx.sym.FullyConnected(d, name="fc",
                                               num_hidden=2), axis=-1)
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net, label_names=None, context=mx.cpu()))
    seq.add(mx.mod.PythonLossModule(data_names=("data",),
                                    label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    seq.bind(data_shapes=[("data", (30, 8))],
             label_shapes=[("softmax_label", (30, 2))],
             inputs_need_grad=False)
    seq.init_params()
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    for _ in range(40):
        for start in range(0, 120, 30):
            b = mx.io.DataBatch(
                data=[mx.nd.array(x[start:start + 30])],
                label=[mx.nd.array(onehot[start:start + 30])])
            seq.forward(b, is_train=True)
            seq.backward()
            seq.update()
    seq.forward(mx.io.DataBatch(data=[mx.nd.array(x)],
                                label=[mx.nd.array(onehot)]),
                is_train=False)
    pred = seq.get_outputs()[0].asnumpy().argmax(axis=1)
    assert (pred == y).mean() > 0.9
