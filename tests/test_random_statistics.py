"""Distribution-level statistical contracts for the samplers (port of the
reference ``tests/python/unittest/test_random.py`` check_with_device
moment/density checks, upgraded to scipy KS / chi-square gates).

Seeded draws → deterministic; tolerances sized for n=60k samples.
"""
import numpy as np
import pytest
import scipy.stats as st

import mxnet_tpu as mx

N = 60_000


def _draw(op, n=N, **kw):
    mx.random.seed(1234)
    return getattr(mx.nd.random, op)(shape=(n,), **kw).asnumpy()


def _ks(sample, cdf, *args):
    # Kolmogorov–Smirnov against the analytic CDF; n=60k → reject only on
    # gross mismatch (p < 1e-3 would be a real distribution bug)
    stat, p = st.kstest(sample, cdf, args=args)
    assert p > 1e-3, (stat, p)


def test_uniform_moments_and_ks():
    s = _draw("uniform", low=-2.0, high=3.0)
    assert abs(s.mean() - 0.5) < 0.02
    assert abs(s.var() - 25 / 12) < 0.05
    assert s.min() >= -2.0 and s.max() < 3.0
    _ks((s + 2.0) / 5.0, "uniform")


def test_normal_moments_and_ks():
    s = _draw("normal", loc=1.5, scale=2.0)
    assert abs(s.mean() - 1.5) < 0.03
    assert abs(s.std() - 2.0) < 0.03
    _ks(s, "norm", 1.5, 2.0)


def test_gamma_moments_and_ks():
    alpha, beta = 2.5, 1.5     # mx: shape alpha, scale beta
    s = _draw("gamma", alpha=alpha, beta=beta)
    assert abs(s.mean() - alpha * beta) < 0.05
    assert abs(s.var() - alpha * beta * beta) < 0.3
    _ks(s, "gamma", alpha, 0, beta)


def test_exponential_moments_and_ks():
    lam = 2.0
    s = _draw("exponential", lam=lam)
    assert abs(s.mean() - 1 / lam) < 0.01
    _ks(s, "expon", 0, 1 / lam)


def test_poisson_moments_and_chisquare():
    lam = 3.7
    s = _draw("poisson", lam=lam)
    assert abs(s.mean() - lam) < 0.05
    assert abs(s.var() - lam) < 0.15
    kmax = int(st.poisson.ppf(0.9999, lam))
    obs = np.bincount(np.clip(s.astype(int), 0, kmax),
                      minlength=kmax + 1)
    probs = st.poisson.pmf(np.arange(kmax + 1), lam)
    probs[-1] += 1 - probs.sum()
    chi, p = st.chisquare(obs, probs * len(s))
    assert p > 1e-3, (chi, p)


def test_negative_binomial_moments():
    k, prob = 4, 0.4
    s = _draw("negative_binomial", k=k, p=prob)
    want_mean = k * (1 - prob) / prob
    want_var = k * (1 - prob) / prob ** 2
    assert abs(s.mean() - want_mean) < 0.1
    assert abs(s.var() - want_var) < 1.0
    kmax = int(st.nbinom.ppf(0.9999, k, prob))
    obs = np.bincount(np.clip(s.astype(int), 0, kmax),
                      minlength=kmax + 1)
    probs = st.nbinom.pmf(np.arange(kmax + 1), k, prob)
    probs[-1] += 1 - probs.sum()
    chi, p = st.chisquare(obs, probs * len(s))
    assert p > 1e-3, (chi, p)


def test_generalized_negative_binomial_moments():
    mu, alpha = 2.0, 0.3
    s = _draw("generalized_negative_binomial", mu=mu, alpha=alpha)
    assert abs(s.mean() - mu) < 0.05
    assert abs(s.var() - (mu + alpha * mu * mu)) < 0.25


def test_randint_uniformity():
    mx.random.seed(99)
    s = mx.nd.random.randint(low=2, high=12, shape=(N,)).asnumpy()
    assert s.min() >= 2 and s.max() <= 11
    obs = np.bincount(s.astype(int) - 2, minlength=10)
    chi, p = st.chisquare(obs)
    assert p > 1e-3, (chi, p)


def test_multinomial_frequencies():
    mx.random.seed(7)
    probs = mx.nd.array([[0.1, 0.2, 0.3, 0.4]])
    s = mx.nd.sample_multinomial(probs, shape=N).asnumpy().ravel()
    freq = np.bincount(s.astype(int), minlength=4) / len(s)
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.3, 0.4], atol=0.01)


def test_bernoulli_frequency():
    mx.random.seed(5)
    s = mx.nd.sample_bernoulli(mx.nd.array([0.3]), shape=N).asnumpy()
    assert set(np.unique(s)) <= {0.0, 1.0}
    assert abs(s.mean() - 0.3) < 0.01


def test_sample_family_per_parameter_rows():
    """sample_* take a parameter tensor: each row follows its own
    distribution (reference test_random.py sample_* checks)."""
    mx.random.seed(11)
    mu = mx.nd.array([-3.0, 0.0, 4.0])
    sig = mx.nd.array([0.5, 1.0, 2.0])
    s = mx.nd.sample_normal(mu, sig, shape=20_000).asnumpy()
    assert s.shape == (3, 20_000)
    for i, (m, sd) in enumerate([(-3, 0.5), (0, 1.0), (4, 2.0)]):
        assert abs(s[i].mean() - m) < 0.05 * max(1, abs(m))
        assert abs(s[i].std() - sd) < 0.05

    lam = mx.nd.array([1.0, 6.0])
    sp = mx.nd.sample_poisson(lam, shape=20_000).asnumpy()
    assert abs(sp[0].mean() - 1.0) < 0.05
    assert abs(sp[1].mean() - 6.0) < 0.12


def test_gamma_sample_gradient_free_and_positive():
    s = _draw("gamma", alpha=0.3, beta=2.0, n=10_000)
    assert (s >= 0).all()


def test_shuffle_is_permutation():
    mx.random.seed(21)
    x = mx.nd.arange(1000)
    y = mx.nd.shuffle(x).asnumpy()
    assert not np.array_equal(y, np.arange(1000))
    np.testing.assert_array_equal(np.sort(y), np.arange(1000))


def test_seed_reproducibility_across_all_samplers():
    outs = {}
    for trial in range(2):
        mx.random.seed(31415)
        for op, kw in [("uniform", {}), ("normal", {}),
                       ("gamma", {"alpha": 2.0}),
                       ("exponential", {}), ("poisson", {"lam": 2.0})]:
            v = getattr(mx.nd.random, op)(shape=(64,), **kw).asnumpy()
            key = (trial, op)
            outs[key] = v
    for op in ("uniform", "normal", "gamma", "exponential", "poisson"):
        np.testing.assert_array_equal(outs[(0, op)], outs[(1, op)])
