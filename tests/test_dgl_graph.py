"""DGL graph-sampling contrib ops — mirrors the reference's
``tests/python/unittest/test_dgl_graph.py`` assertions on the host-side
CSR implementations (``mxnet_tpu/ndarray/contrib_graph.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx

K5 = dict(
    data=np.arange(1, 21, dtype=np.int64),
    indices=np.array([1, 2, 3, 4, 0, 2, 3, 4, 0, 1, 3, 4,
                      0, 1, 2, 4, 0, 1, 2, 3], dtype=np.int64),
    indptr=np.array([0, 4, 8, 12, 16, 20], dtype=np.int64),
)


def _k5():
    return mx.nd.sparse.csr_matrix(
        (K5["data"], K5["indices"], K5["indptr"]), shape=(5, 5))


def _check_uniform(out, num_hops, max_num_vertices):
    sample_id, sub_csr, layer = out
    assert len(sample_id) == max_num_vertices + 1
    num_vertices = int(sample_id[-1].asnumpy()[()])
    sub_csr.check_format(full_check=True)
    indptr = sub_csr.indptr.asnumpy()
    assert (indptr[num_vertices:] == indptr[num_vertices]).all()
    for d in layer.asnumpy()[:num_vertices]:
        assert d <= num_hops
    return num_vertices


def _check_compact(csr, id_arr, num_nodes):
    compact = mx.nd.contrib.dgl_graph_compact(
        csr, id_arr, graph_sizes=num_nodes, return_mapping=False)
    assert compact.shape == (num_nodes, num_nodes)
    assert (compact.indptr.asnumpy() ==
            csr.indptr.asnumpy()[:num_nodes + 1]).all()
    sub_indices = compact.indices.asnumpy()
    indices = csr.indices.asnumpy()
    ids = id_arr.asnumpy()
    for i in range(len(sub_indices)):
        assert ids[sub_indices[i]] == indices[i]


@pytest.mark.parametrize("seed,num_hops,num_neighbor,maxv", [
    ([0, 1, 2, 3, 4], 1, 2, 5),
    ([0], 1, 1, 4),
    ([0], 2, 1, 3),
    ([0, 2, 4], 1, 2, 5),
    ([0, 4], 2, 2, 5),
])
def test_uniform_sample(seed, num_hops, num_neighbor, maxv):
    a = _k5()
    np.random.seed(42)
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, mx.nd.array(np.array(seed, dtype=np.int64)), num_args=2,
        num_hops=num_hops, num_neighbor=num_neighbor, max_num_vertices=maxv)
    assert len(out) == 3
    n = _check_uniform(out, num_hops, maxv)
    assert 0 < n < len(out[0])
    _check_compact(out[1], out[0], n)


def test_non_uniform_sample():
    a = _k5()
    prob = mx.nd.array(np.array([0.9, 0.8, 0.2, 0.4, 0.1], np.float32))
    np.random.seed(42)
    out = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        a, prob, mx.nd.array(np.array([0, 1, 2, 3, 4], dtype=np.int64)),
        num_args=3, num_hops=1, num_neighbor=2, max_num_vertices=5)
    assert len(out) == 4
    sample_id, sub_csr, sub_prob, layer = out
    n = _check_uniform([sample_id, sub_csr, layer], 1, 5)
    assert len(sub_prob) == 5
    np.testing.assert_allclose(
        sub_prob.asnumpy()[:n],
        prob.asnumpy()[sample_id.asnumpy()[:n]])


def test_sampled_edges_come_from_graph():
    # NOTE: max_num_vertices must exceed the seed count for any expansion to
    # happen — the reference's BFS loop (dgl_graph.cc SampleSubgraph) stops
    # once the vertex budget is reached, so num_seeds == max_num_vertices
    # yields an empty sub-CSR (its doc example predates that check).
    a = _k5()
    np.random.seed(0)
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, mx.nd.array(np.array([0, 1], dtype=np.int64)),
        num_args=2, num_hops=1, num_neighbor=2, max_num_vertices=5)
    sub = out[1]
    dense = sub.asnumpy()
    full = np.zeros((5, 5), np.int64)
    for r in range(5):
        s, e = K5["indptr"][r], K5["indptr"][r + 1]
        full[r, K5["indices"][s:e]] = K5["data"][s:e]
    nz = dense != 0
    assert nz.sum() > 0
    assert (dense[nz] == full[nz]).all()


def _random_graph(n, density=0.2):
    import scipy.sparse as sp
    rng = np.random.RandomState(3)
    arr = sp.random(n, n, density=density, format="coo", random_state=rng)
    arr.data = np.arange(0, len(arr.row), dtype=np.float32)
    return arr.tocsr(), mx.nd.sparse.csr_matrix(arr.tocsr()).astype(np.int64)


def test_subgraph():
    sp_g, g = _random_graph(100)
    rng = np.random.RandomState(1)
    vertices = np.unique(rng.randint(0, 100, size=(20,)))
    subgs = mx.nd.contrib.dgl_subgraph(
        g, mx.nd.array(vertices, dtype=np.int64), return_mapping=True)
    subgs[0].check_format()
    subgs[1].check_format()
    np.testing.assert_array_equal(subgs[0].indptr.asnumpy(),
                                  subgs[1].indptr.asnumpy())
    np.testing.assert_array_equal(subgs[0].indices.asnumpy(),
                                  subgs[1].indices.asnumpy())
    # new edge ids are 0..nnz-1
    np.testing.assert_array_equal(subgs[0].data.asnumpy(),
                                  np.arange(len(subgs[0].data)))
    sp_subg = subgs[1].asscipy()
    indptr = subgs[0].indptr.asnumpy()
    indices = subgs[0].indices.asnumpy()
    for subv1 in range(len(indptr) - 1):
        v1 = vertices[subv1]
        for subv2 in indices[indptr[subv1]:indptr[subv1 + 1]]:
            v2 = vertices[subv2]
            assert sp_g[v1, v2] == sp_subg[subv1, subv2]


def test_adjacency():
    _sp_g, g = _random_graph(100)
    adj = mx.nd.contrib.dgl_adjacency(g)
    assert adj.dtype == np.float32
    assert adj.shape == g.shape
    np.testing.assert_array_equal(adj.indptr.asnumpy(), g.indptr.asnumpy())
    np.testing.assert_array_equal(adj.indices.asnumpy(), g.indices.asnumpy())
    np.testing.assert_array_equal(adj.data.asnumpy(),
                                  np.ones(g.indices.shape))


def test_truncated_sample_is_always_compactable():
    # Budget-truncated walks used to emit edges to vertices outside the
    # sampled set, which graph_compact then crashed on.
    a = _k5()
    for s in range(10):
        np.random.seed(s)
        out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
            a, mx.nd.array(np.array([2], dtype=np.int64)), num_args=2,
            num_hops=1, num_neighbor=2, max_num_vertices=2)
        n = int(out[0][-1].asnumpy()[()])
        out[1].check_format(full_check=True)
        _check_compact(out[1], out[0], n)


def test_multi_seed_outputs_grouped_by_kind():
    a = _k5()
    np.random.seed(0)
    s1 = mx.nd.array(np.array([0], dtype=np.int64))
    s2 = mx.nd.array(np.array([3], dtype=np.int64))
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, s1, s2, num_args=3, num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    # reference layout: [ids0, ids1, csr0, csr1, layer0, layer1]
    assert len(out) == 6
    assert out[0].shape == (6,) and out[1].shape == (6,)
    assert out[2].shape == (5, 5) and out[3].shape == (5, 5)
    assert out[4].shape == (5,) and out[5].shape == (5,)
    assert int(out[0].asnumpy()[0]) == 0    # first sampled id of seed 0
    assert 3 in out[1].asnumpy()[:int(out[1][-1].asnumpy()[()])]


def test_graph_compact_new_edge_ids_and_mapping():
    a = _k5()
    np.random.seed(0)
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        a, mx.nd.array(np.array([0, 1], dtype=np.int64)), num_args=2,
        num_hops=1, num_neighbor=2, max_num_vertices=5)
    n = int(out[0][-1].asnumpy()[()])
    compact, mapping = mx.nd.contrib.dgl_graph_compact(
        out[1], out[0], graph_sizes=n, return_mapping=True)
    nnz = len(compact.data)
    # compacted data are new edge ids 0..nnz-1 (dgl_graph.cc sub_eids[i]=i)
    np.testing.assert_array_equal(compact.data.asnumpy(), np.arange(nnz))
    np.testing.assert_array_equal(compact.indptr.asnumpy(),
                                  mapping.indptr.asnumpy())
    np.testing.assert_array_equal(compact.indices.asnumpy(),
                                  mapping.indices.asnumpy())
    # mapping data are the sub-CSR's edge values (original graph edge ids)
    np.testing.assert_array_equal(mapping.data.asnumpy(),
                                  out[1].data.asnumpy())


def test_csr_cache_invalidated_on_inplace_write():
    a = mx.nd.sparse.csr_matrix(
        (np.array([5., 7.]), np.array([1, 2]), np.array([0, 1, 2])),
        shape=(2, 3))
    np.testing.assert_array_equal(a.data.asnumpy(), [5., 7.])
    a += 1.0
    np.testing.assert_array_equal(a.asnumpy(), [[1., 6., 1.], [1., 1., 8.]])
    np.testing.assert_array_equal(a.data.asnumpy(),
                                  [1., 6., 1., 1., 1., 8.])   # derived anew
    b = mx.nd.sparse.csr_matrix(
        (np.array([5., 7.]), np.array([1, 2]), np.array([0, 1, 2])),
        shape=(2, 3))
    b[0, 0] = 9.0
    assert b.data.asnumpy()[0] == 9.0
