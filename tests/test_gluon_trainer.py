"""Gluon Trainer depth tranche (reference
``tests/python/unittest/test_gluon_trainer.py``): step math with
momentum, lr_mult, save/load states, set_learning_rate, lr scheduler
stepping, multi-trainer guard.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_trainer_step_math_and_lr_mult():
    """reference test_trainer: sgd+momentum trajectory on grad==1, then
    lr_mult rescales the effective step."""
    x = gluon.Parameter("x", shape=(10,))
    x.initialize(init="zeros")
    trainer = gluon.Trainer([x], "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with mx.autograd.record():
        y = x.data() + 1
        y.backward()
    trainer.step(1)
    np.testing.assert_allclose(x.data().asnumpy(), np.full(10, -1.0))
    with mx.autograd.record():
        y = x.data() + 1
        y.backward()
    trainer.step(1)
    # momentum: v = 0.5*v + g = 1.5; x = -1 - 1.5 = -2.5
    np.testing.assert_allclose(x.data().asnumpy(), np.full(10, -2.5))

    x.lr_mult = 0.5
    with mx.autograd.record():
        y = x.data() + 1
        y.backward()
    trainer.step(1)
    # MXNet folds lr INTO the momentum buffer (sgd-inl.h):
    # mom = 0.5*(-1.5) - (1.0*0.5)*1 = -1.25; x = -2.5 - 1.25
    np.testing.assert_allclose(x.data().asnumpy(),
                               np.full(10, -3.75), rtol=1e-6)


def test_trainer_save_load_states(tmp_path):
    """reference test_trainer_save_load: optimizer state (momentum)
    round-trips through save_states/load_states."""
    x = gluon.Parameter("x", shape=(4,))
    x.initialize(init="zeros")
    trainer = gluon.Trainer([x], "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(3):
        with mx.autograd.record():
            (x.data() * 2).sum().backward()
        trainer.step(1)
    w_before = x.data().asnumpy().copy()
    f = str(tmp_path / "t.states")
    trainer.save_states(f)

    # continue one step, then restore and replay: identical trajectory
    with mx.autograd.record():
        (x.data() * 2).sum().backward()
    trainer.step(1)
    w_after1 = x.data().asnumpy().copy()

    x.set_data(mx.nd.array(w_before))
    trainer.load_states(f)
    with mx.autograd.record():
        (x.data() * 2).sum().backward()
    trainer.step(1)
    np.testing.assert_allclose(x.data().asnumpy(), w_after1, rtol=1e-6)


def test_trainer_learning_rate_property_and_sched():
    """reference test_trainer_lr_sched: FactorScheduler decays across
    steps; set_learning_rate overrides."""
    x = gluon.Parameter("x", shape=(4,))
    x.initialize(init="zeros")
    sched = mx.lr_scheduler.FactorScheduler(2, factor=0.1, base_lr=1.0)
    trainer = gluon.Trainer([x], "sgd",
                            {"learning_rate": 1.0, "lr_scheduler": sched})
    lr = 1.0
    for i in range(6):
        with mx.autograd.record():
            (x.data() + 1).backward()
        trainer.step(1)
        if i % 2 == 0:
            np.testing.assert_allclose(trainer.learning_rate, lr,
                                       rtol=1e-6)
            lr *= 0.1

    x2 = gluon.Parameter("x2", shape=(4,))
    x2.initialize(init="zeros")
    t2 = gluon.Trainer([x2], "sgd", {"learning_rate": 0.5})
    t2.set_learning_rate(0.05)
    assert abs(t2.learning_rate - 0.05) < 1e-9


def test_trainer_step_requires_gradients():
    """Stepping without a recorded backward must not corrupt weights
    (zero grads → weight unchanged for sgd w/o wd)."""
    x = gluon.Parameter("x", shape=(3,))
    x.initialize(init="ones")
    trainer = gluon.Trainer([x], "sgd", {"learning_rate": 0.5})
    with mx.autograd.record():
        x.data().sum().backward()
    trainer.step(1)
    w1 = x.data().asnumpy().copy()
    x.zero_grad()
    trainer.step(1)
    np.testing.assert_allclose(x.data().asnumpy(), w1)


def test_trainer_multiple_params_distinct_states():
    a = gluon.Parameter("a", shape=(2,))
    b = gluon.Parameter("b", shape=(3,))
    a.initialize(init="zeros")
    b.initialize(init="zeros")
    trainer = gluon.Trainer([a, b], "sgd",
                            {"learning_rate": 1.0, "momentum": 0.9})
    with mx.autograd.record():
        (a.data() + 1).backward()
        (b.data() * 2).sum().backward()
    trainer.step(1)
    np.testing.assert_allclose(a.data().asnumpy(), [-1, -1])
    np.testing.assert_allclose(b.data().asnumpy(), [-2, -2, -2])
