"""SSD model tests (BASELINE config 4; reference example/ssd +
multibox op contracts)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import ssd as ssd_mod


def _tiny_ssd(num_classes=3):
    # 4 scales so a 64px input keeps valid feature maps (8, 4, 2, 1)
    return ssd_mod.SSD(num_classes,
                       sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
                              (0.71, 0.79)),
                       ratios=((1, 2, 0.5),) * 4)


def test_ssd_forward_shapes():
    net = _tiny_ssd()
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    cls_pred, loc_pred, anchors = net(x)
    A = anchors.shape[1]
    assert anchors.shape == (1, A, 4)
    assert cls_pred.shape == (2, A, 4)  # 3 classes + background
    assert loc_pred.shape == (2, A * 4)
    # 4 anchors per position over 8^2+4^2+2^2+1 positions
    assert A == 4 * (64 + 16 + 4 + 1)


def test_ssd_train_step():
    net = _tiny_ssd(num_classes=2)
    net.initialize()
    loss_fn = ssd_mod.MultiBoxLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.01})
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    # one ground-truth box per image: [cls, x1, y1, x2, y2]
    labels = mx.nd.array(np.array(
        [[[0, 0.1, 0.1, 0.5, 0.5]], [[1, 0.4, 0.4, 0.9, 0.9]]],
        dtype="float32"))
    with mx.autograd.record():
        cls_pred, loc_pred, anchors = net(x)
        loss, cls_t, loc_t = loss_fn(cls_pred, loc_pred, anchors, labels)
    loss.backward()
    trainer.step(2)
    assert np.isfinite(float(loss.asscalar()))
    # at least one anchor matched per image
    assert (cls_t.asnumpy() > 0).sum() >= 2


def test_ssd_detect():
    net = _tiny_ssd(num_classes=2)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    out = ssd_mod.detect(net, x, nms_threshold=0.45)
    assert out.shape[0] == 1 and out.shape[2] == 6
    ids = out.asnumpy()[0, :, 0]
    assert ((ids >= -1) & (ids < 2)).all()


def test_ssd_300_builds():
    net = ssd_mod.ssd_300_vgg16(num_classes=20)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 300, 300))
    cls_pred, loc_pred, anchors = net(x)
    # canonical SSD-300 anchor count: 38²·4 + 19²·6 + 10²·6 + 5²·6 + 3²·4 + 1·4
    assert cls_pred.shape[1] == anchors.shape[1]
    assert cls_pred.shape[2] == 21
