"""Subprocess worker for the AOT cold-start drill (ci gateway stage and
``bench.py`` gateway config).

Each invocation is one "process restart": build + warm a DecodeSession
against an on-disk AOT program cache (or none), generate a fixed prompt,
and print one JSON line with the warm time, the token ids, and the cache
hit/miss/fallback counts.  The drill runs it twice against the same
directory — the second run must load every program (misses == 0), be
several times faster to warm, and produce bitwise-identical tokens.

Usage::

    python tests/aot_cache_worker.py            # no cache: pure cold
    python tests/aot_cache_worker.py /some/dir  # cache-backed
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    cache_dir = sys.argv[1] if len(sys.argv) > 1 and sys.argv[1] else None
    import mxnet_tpu as mx
    from mxnet_tpu.serving.decode import DecodeSession, get_decode_model

    mx.random.seed(0)
    net = get_decode_model("decode_tiny", vocab_size=96, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    t0 = time.perf_counter()
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                         page_size=8, aot_cache=cache_dir)
    warm_s = time.perf_counter() - t0
    try:
        res = sess.generate([5, 9, 2], max_new_tokens=8, temperature=0.8,
                            seed=11, timeout=120)
        pc = sess.runtime.aot_cache
        print(json.dumps({
            "warm_s": round(warm_s, 4),
            "token_ids": res.token_ids,
            "finish_reason": res.finish_reason,
            "cache": pc.stats() if pc is not None else None,
        }))
    finally:
        sess.close(drain=False)


if __name__ == "__main__":
    main()
