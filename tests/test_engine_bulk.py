"""Lazy eager dispatch with fused multi-op jit segments (ISSUE 5 tentpole):
parity fused-vs-eager, flush on every sync point, the fallback matrix,
autograd-unchanged-gradients, per-thread bulk state, zero steady-state
segment compile misses, and the engine.bulk telemetry span."""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, nd, telemetry
from mxnet_tpu.engine import recorder


@pytest.fixture(autouse=True)
def _clean_engine():
    telemetry.disable()
    telemetry.reset()
    engine.set_bulk_size(0)
    yield
    engine.set_bulk_size(0)
    telemetry.disable()
    telemetry.reset()


def _rand(shape, seed=0):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


# --------------------------------------------------------------------- parity
def _unary_chain(x):
    """Op chain with no mul→add adjacency: XLA cannot FMA-contract it, so
    fused and per-op programs are bit-identical (see docs/engine.md on
    float contraction)."""
    y = x.tanh()
    y = y.relu()
    y = y.exp()
    y = y.sigmoid()
    y = -y
    y = y.abs()
    y = y.sqrt()
    return y


def test_bitwise_parity_unary_chain():
    x = nd.array(_rand((16, 16)))
    ref = _unary_chain(x).asnumpy()
    with engine.bulk(4):
        out = _unary_chain(x)
    assert np.array_equal(ref, out.asnumpy())


def test_bitwise_parity_binary_and_reduction():
    a = nd.array(_rand((8, 12), 1))
    b = nd.array(_rand((8, 12), 2))

    def f():
        y = a + b
        y = y - 0.5
        y = nd.maximum(y, a)
        s = y.sum(axis=1)
        return s + 1.0

    ref = f().asnumpy()
    with engine.bulk(16):
        out = f()
    assert np.array_equal(ref, out.asnumpy())


def test_mul_add_chain_matches_within_contraction_tolerance():
    """A mul feeding an add inside ONE fused program may be contracted to
    an FMA by XLA (documented in docs/engine.md) — values agree to float32
    resolution, not necessarily bitwise."""
    x = nd.array(_rand((32, 32), 3))

    def f():
        y = x
        for _ in range(8):
            y = y * 1.0001
            y = y + 0.001
        return y

    ref = f().asnumpy()
    with engine.bulk(16):
        out = f()
    np.testing.assert_allclose(ref, out.asnumpy(), rtol=2e-6, atol=1e-7)


def test_multi_output_op_inside_bulk():
    x = nd.array(_rand((6, 4), 4))
    ref = nd.topk(x, k=2, ret_typ="both")
    ref = [r.asnumpy() for r in ref]
    with engine.bulk(8):
        out = nd.topk(x, k=2, ret_typ="both")
    for r, o in zip(ref, out):
        assert np.array_equal(r, o.asnumpy())


# ------------------------------------------------------------- sync points
def _pending(x):
    return type(x._data) is recorder.LazyData


def test_flush_on_every_sync_point():
    x = nd.array(_rand((4, 4)))
    syncs = [
        ("asnumpy", lambda y: y.asnumpy()),
        ("item", lambda y: y.sum().item()),
        ("wait_to_read", lambda y: y.wait_to_read()),
        ("bool", lambda y: bool(y.sum() > 0)),
        ("getitem", lambda y: y[0]),
        ("repr", lambda y: repr(y)),
        ("int", lambda y: int(y.sum())),
        ("dlpack", lambda y: y.to_dlpack_for_read()),
        ("waitall", lambda y: nd.waitall()),
    ]
    for name, sync in syncs:
        with engine.bulk(64):
            y = x * 2.0
            y = y + 1.0
            assert _pending(y), name
            sync(y)
            assert not _pending(y), f"{name} must force the flush"
            np.testing.assert_allclose(
                y.asnumpy(), x.asnumpy() * 2.0 + 1.0, rtol=1e-6)


def test_scope_exit_flushes():
    x = nd.array(_rand((4, 4)))
    with engine.bulk(64):
        y = x * 3.0
        assert _pending(y)
    assert not _pending(y)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0, rtol=1e-6)


def test_setitem_on_pending_and_mutated_input_snapshot():
    """In-place writes interleaved with pending ops: a recorded op sees the
    input VALUE at record time (immutable snapshot), like the reference
    engine's read-dependency on the pushed version."""
    x = nd.array(np.ones((4,), np.float32))
    with engine.bulk(64):
        y = x * 2.0              # records x's current buffer
        x[:] = 0.0               # rebinds x after the snapshot
        z = y + 1.0
    np.testing.assert_allclose(y.asnumpy(), 2.0)
    np.testing.assert_allclose(z.asnumpy(), 3.0)
    np.testing.assert_allclose(x.asnumpy(), 0.0)


def test_inplace_arithmetic_inside_bulk():
    x = nd.array(np.ones((8,), np.float32))
    with engine.bulk(64):
        x += 1.0
        x *= 3.0
        x -= 2.0
    np.testing.assert_allclose(x.asnumpy(), 4.0)


# ---------------------------------------------------------- fallback matrix
def test_optimizer_update_op_falls_back():
    """In-place optimizer update ops (register.py writeback) execute
    eagerly — their input rebinding needs concrete outputs now."""
    w = nd.array(np.ones((4,), np.float32))
    g = nd.array(np.full((4,), 0.5, np.float32))
    with engine.bulk(64):
        y = w * 1.0              # pending op feeding the update
        nd.sgd_update(w, g, lr=0.1, out=w)
        assert not _pending(w)
    np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.05, rtol=1e-6)
    np.testing.assert_allclose(y.asnumpy(), 1.0)


def test_sparse_operand_falls_back():
    from mxnet_tpu.ndarray import sparse as sp
    rs = sp.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([0, 2])), shape=(4, 3))
    with engine.bulk(64):
        d = rs.tostype("default")
        y = d * 2.0
    np.testing.assert_allclose(y.asnumpy()[0], 2.0)
    np.testing.assert_allclose(y.asnumpy()[1], 0.0)


def test_array_valued_attr_falls_back():
    """Ops routing tensors through attrs (unhashable) are uncapturable."""
    x = nd.array(_rand((3, 4, 5)))
    sl = nd.array(np.array([2, 3, 1], np.float32))
    ref = nd.SequenceLast(x.swapaxes(0, 1), sequence_length=sl,
                          use_sequence_length=True).asnumpy()
    with engine.bulk(64):
        out = nd.SequenceLast(x.swapaxes(0, 1), sequence_length=sl,
                              use_sequence_length=True)
    np.testing.assert_allclose(ref, out.asnumpy(), rtol=1e-6)


def test_cross_device_inputs():
    import jax
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("needs the virtual multi-device CPU mesh")
    a = nd.NDArray(jax.device_put(_rand((4,), 5), devs[0]))
    b = nd.NDArray(jax.device_put(_rand((4,), 6), devs[1]))
    ref = (a + b.as_in_context(a.context)).asnumpy()
    with engine.bulk(64):
        out = a + b.as_in_context(a.context)
    np.testing.assert_allclose(ref, out.asnumpy(), rtol=1e-6)


def test_stochastic_op_inside_bulk_uses_key_stream():
    mx.random.seed(7)
    ref = nd.random_normal(shape=(5,)).asnumpy()
    mx.random.seed(7)
    with engine.bulk(64):
        out = nd.random_normal(shape=(5,))
    np.testing.assert_allclose(ref, out.asnumpy(), rtol=1e-6)


def test_batchnorm_writeback_is_eager():
    x = nd.array(_rand((4, 3), 8))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mmean, mvar = nd.zeros((3,)), nd.ones((3,))
    with mx.autograd.train_mode():
        with engine.bulk(64):
            out = nd.BatchNorm(x, gamma, beta, mmean, mvar)
            assert not _pending(out)
    assert not np.allclose(mmean.asnumpy(), 0.0)   # aux state updated


# ------------------------------------------------------------------ autograd
def test_autograd_grads_identical_inside_bulk():
    w_np = _rand((3, 4), 9)

    def run(bulked):
        w = nd.array(w_np)
        w.attach_grad()
        if bulked:
            with engine.bulk(32):
                pre = w * 1.5            # pending before the tape starts
                with mx.autograd.record():
                    loss = ((w * 2.0 + 1.0) ** 2).sum()
                loss.backward()
        else:
            with mx.autograd.record():
                loss = ((w * 2.0 + 1.0) ** 2).sum()
            loss.backward()
        return w.grad.asnumpy(), float(loss.asnumpy())

    g_ref, l_ref = run(False)
    g_bulk, l_bulk = run(True)
    assert np.array_equal(g_ref, g_bulk)
    assert l_ref == l_bulk


def test_record_entry_flushes_pending_segment():
    x = nd.array(_rand((4,), 10))
    with engine.bulk(64):
        y = x * 2.0
        assert _pending(y)
        with mx.autograd.record():
            assert not _pending(y)       # record boundary forced the flush


# ------------------------------------------------------- per-thread state
def test_bulk_state_is_per_thread():
    engine.set_bulk_size(16)
    seen = {}

    def worker():
        seen["initial"] = engine.bulk_size()
        engine.set_bulk_size(99)
        seen["after_set"] = engine.bulk_size()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["initial"] == 0          # env default, not main's 16
    assert seen["after_set"] == 99
    assert engine.bulk_size() == 16      # worker didn't clobber main
    engine.set_bulk_size(0)


def test_cross_thread_consumption_forces_flush():
    x = nd.array(_rand((4,), 11))
    segs0, fused0 = recorder.thread_stats()
    with engine.bulk(64):
        y = x * 2.0
        assert _pending(y)
        result = {}

        def consumer():
            result["val"] = y.asnumpy()

        t = threading.Thread(target=consumer)
        t.start()
        t.join()
        # the consumer-forced flush must clear the OWNER's pending pointer
        # (else the flushed segment pins its buffers until the owner
        # records again) and attribute the stats to the owner thread
        assert recorder._tls.segment is None
        segs1, fused1 = recorder.thread_stats()
        assert (segs1 - segs0, fused1 - fused0) == (1, 1)
    np.testing.assert_allclose(result["val"], x.asnumpy() * 2.0, rtol=1e-6)


# ----------------------------------------------------- caching + telemetry
def test_zero_steady_state_compile_misses():
    telemetry.enable()
    x = nd.array(_rand((8, 8), 12))

    def loop():
        with engine.bulk(8):
            y = x
            for _ in range(8):
                y = y * 1.01
                y = y + 0.1
        y.wait_to_read()

    loop()                       # warmup compiles the segment signatures
    m0 = telemetry.counter_value("dispatch.segment_compile_miss")
    h0 = telemetry.counter_value("dispatch.segment_cache_hits")
    for _ in range(5):
        loop()
    assert telemetry.counter_value("dispatch.segment_compile_miss") == m0
    assert telemetry.counter_value("dispatch.segment_cache_hits") > h0


def test_bulk_span_reports_segments_and_fused_ops():
    telemetry.enable()
    x = nd.array(_rand((4, 4), 13))
    with engine.bulk(4):
        y = x
        for _ in range(4):
            y = y * 2.0
            y = y + 1.0
    y.wait_to_read()
    spans = [e for e in telemetry.bus.events() if e[1] == "engine.bulk"]
    attrs = spans[-1][6]
    assert attrs["size"] == 4
    assert attrs["ops_in_scope"] == 8
    assert attrs["segments"] == 2
    assert attrs["fused_ops"] == 8


def test_bulk_span_survives_mid_scope_telemetry_toggle():
    """ISSUE 5 satellite: toggling telemetry inside the scope must not
    raise or report garbage ops_in_scope."""
    x = nd.array(_rand((4,), 14))
    # off at entry, on at exit
    with engine.bulk(4):
        y = x * 2.0
        telemetry.enable()
    spans = [e for e in telemetry.bus.events() if e[1] == "engine.bulk"]
    if spans:                       # span was a noop (created while off)
        assert "ops_in_scope" not in (spans[-1][6] or {})
    # on at entry, reset mid-scope (exit counter < entry counter)
    telemetry.reset()
    telemetry.enable()
    nd.waitall()
    _ = (x * 2.0).asnumpy()         # put some ops on the counter
    with engine.bulk(4):
        y = x * 2.0
        telemetry.reset()
    spans = [e for e in telemetry.bus.events() if e[1] == "engine.bulk"]
    attrs = spans[-1][6]
    assert attrs.get("ops_in_scope", 0) >= 0
    # on at entry, off at exit: span still closes without raising
    telemetry.reset()
    telemetry.enable()
    with engine.bulk(4):
        y = x * 2.0
        telemetry.disable()
    y.wait_to_read()


def test_env_default_applies_to_new_threads(monkeypatch):
    monkeypatch.setattr(recorder, "_ENV_DEFAULT", 8)
    seen = {}

    def worker():
        seen["size"] = engine.bulk_size()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["size"] == 8


def test_set_bulk_size_returns_previous_and_flushes():
    prev = engine.set_bulk_size(32)
    assert prev == 0
    x = nd.array(_rand((4,), 15))
    y = x * 2.0
    assert _pending(y)
    assert engine.set_bulk_size(0) == 32     # flushes the pending segment
    assert not _pending(y)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 2.0, rtol=1e-6)


def test_disabled_path_records_nothing():
    telemetry.enable()
    x = nd.array(_rand((4,), 16))
    (x * 2.0).wait_to_read()
    snap = telemetry.snapshot()["counters"]
    assert snap.get("dispatch.ops_recorded", 0) == 0
    assert snap.get("dispatch.segments_flushed", 0) == 0
