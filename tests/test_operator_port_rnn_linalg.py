"""Reference test_operator.py port, tranche 4: symbolic RNN family
(test_lstm_sym / test_gru_sym / test_rnntanh_sym / test_rnnrelu_sym,
each + bidirectional + dropout), the linalg laop/gemm family, and the
introspection/monitor cases.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal

_rng = np.random.RandomState

T, B, I, H = 4, 2, 5, 6


def _rnn_sym_check(mode, bidirectional=False, p=0.0, seed=0):
    """Fused symbolic RNN runs, shapes check out, grads flow to the flat
    parameter vector, and (for p=0, unidirectional) the output matches
    the equivalent gluon cell unroll."""
    rng = _rng(seed)
    d = 2 if bidirectional else 1
    gates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    nparam = 0
    for layer in range(1):
        in_sz = I
        nparam += d * (gates * H * in_sz + gates * H * H + 2 * gates * H)
    x = rng.randn(T, B, I).astype("float32") * 0.5
    params = rng.randn(nparam).astype("float32") * 0.1
    state = np.zeros((d, B, H), "float32")

    data = mx.sym.Variable("data")
    par = mx.sym.Variable("par")
    s0 = mx.sym.Variable("s0")
    inputs = [data, par, s0]
    kwargs = {}
    if mode == "lstm":
        c0 = mx.sym.Variable("c0")
        inputs.append(c0)
    sym = mx.sym.RNN(*inputs, mode=mode, state_size=H, num_layers=1,
                     bidirectional=bidirectional, p=p, state_outputs=False,
                     **kwargs)
    arrays = {"data": x, "par": params, "s0": state}
    if mode == "lstm":
        arrays["c0"] = np.zeros((d, B, H), "float32")
    args = {k: nd.array(v) for k, v in arrays.items()}
    grads = {k: nd.zeros(v.shape) for k, v in arrays.items()}
    exe = sym.bind(mx.cpu(), args, args_grad=grads)
    out = exe.forward(is_train=True)
    assert out[0].shape == (T, B, d * H)
    exe.backward(nd.ones(out[0].shape))
    g = grads["par"].asnumpy()
    assert np.abs(g).max() > 0, "no gradient reached the RNN parameters"
    assert np.isfinite(g).all()
    return out[0].asnumpy(), params


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
def test_rnn_sym(mode):
    """reference test_lstm_sym / test_gru_sym / test_rnntanh_sym /
    test_rnnrelu_sym: the symbolic graph path and the eager op path of
    the fused RNN agree; the gluon-cell parity check lives in
    test_gluon_rnn.py (fused layer vs unrolled cells)."""
    out, params = _rnn_sym_check(mode)
    rng = _rng(0)
    x = rng.randn(T, B, I).astype("float32") * 0.5
    ref = nd.RNN(nd.array(x), nd.array(params),
                 nd.array(np.zeros((1, B, H), "float32")),
                 *([nd.array(np.zeros((1, B, H), "float32"))]
                   if mode == "lstm" else []),
                 mode=mode, state_size=H, num_layers=1,
                 state_outputs=False)
    assert_almost_equal(out, ref.asnumpy(), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
def test_rnn_bidirectional(mode):
    """reference test_lstm_bidirectional / test_gru_bidirectional /
    test_rnntanh_bidirectional / test_rnnrelu_bidirectional."""
    out, _ = _rnn_sym_check(mode, bidirectional=True, seed=1)
    assert out.shape == (T, B, 2 * H)
    # the forward half at t=0 must be independent of later inputs;
    # check by truncating the sequence
    rng = _rng(1)
    d = 2
    gates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    nparam = d * (gates * H * I + gates * H * H + 2 * gates * H)
    x = rng.randn(T, B, I).astype("float32") * 0.5
    params = rng.randn(nparam).astype("float32") * 0.1
    extra = [nd.array(np.zeros((d, B, H), "float32"))] \
        if mode == "lstm" else []
    full = nd.RNN(nd.array(x), nd.array(params),
                  nd.array(np.zeros((d, B, H), "float32")), *extra,
                  mode=mode, state_size=H, num_layers=1,
                  bidirectional=True, state_outputs=False).asnumpy()
    trunc = nd.RNN(nd.array(x[:2]), nd.array(params),
                   nd.array(np.zeros((d, B, H), "float32")), *extra,
                   mode=mode, state_size=H, num_layers=1,
                   bidirectional=True, state_outputs=False).asnumpy()
    # forward direction of step 0 agrees; backward direction differs
    assert_almost_equal(full[0, :, :H], trunc[0, :, :H], rtol=1e-4,
                        atol=1e-5)
    assert np.abs(full[0, :, H:] - trunc[0, :, H:]).max() > 1e-6


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
def test_rnn_dropout(mode):
    """reference test_lstm_dropout family: p>0 accepted; inference is
    deterministic (dropout only hits training mode / between layers)."""
    rng = _rng(2)
    gates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    nparam = (gates * H * I + gates * H * H + 2 * gates * H) \
        + (gates * H * H + gates * H * H + 2 * gates * H)
    x = rng.randn(T, B, I).astype("float32")
    params = rng.randn(nparam).astype("float32") * 0.1
    extra = [nd.array(np.zeros((2, B, H), "float32"))] \
        if mode == "lstm" else []
    o1 = nd.RNN(nd.array(x), nd.array(params),
                nd.array(np.zeros((2, B, H), "float32")), *extra,
                mode=mode, state_size=H, num_layers=2, p=0.5,
                state_outputs=False).asnumpy()
    o2 = nd.RNN(nd.array(x), nd.array(params),
                nd.array(np.zeros((2, B, H), "float32")), *extra,
                mode=mode, state_size=H, num_layers=2, p=0.5,
                state_outputs=False).asnumpy()
    assert_almost_equal(o1, o2, rtol=1e-6)   # inference: no dropout
    assert np.isfinite(o1).all()
    # training mode: inter-layer dropout is stochastic across calls
    with autograd.record(train_mode=True):
        t1 = nd.RNN(nd.array(x), nd.array(params),
                    nd.array(np.zeros((2, B, H), "float32")), *extra,
                    mode=mode, state_size=H, num_layers=2, p=0.5,
                    state_outputs=False).asnumpy()
    with autograd.record(train_mode=True):
        t2 = nd.RNN(nd.array(x), nd.array(params),
                    nd.array(np.zeros((2, B, H), "float32")), *extra,
                    mode=mode, state_size=H, num_layers=2, p=0.5,
                    state_outputs=False).asnumpy()
    assert np.abs(t1 - t2).max() > 1e-6, "training dropout not applied"


# ------------------------------------------------------------- linalg

def test_gemm():
    """reference test_gemm: gemm(+bias, alpha/beta, transposes) and
    gemm2."""
    rng = _rng(3)
    a = rng.randn(3, 4).astype("float32")
    b = rng.randn(4, 5).astype("float32")
    c = rng.randn(3, 5).astype("float32")
    got = nd.linalg.gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=0.5)
    assert_almost_equal(got.asnumpy(), 2 * (a @ b) + 0.5 * c, rtol=1e-4)
    got = nd.linalg.gemm2(nd.array(a), nd.array(b), alpha=1.5)
    assert_almost_equal(got.asnumpy(), 1.5 * (a @ b), rtol=1e-4)
    got = nd.linalg.gemm2(nd.array(a.T), nd.array(b), transpose_a=True)
    assert_almost_equal(got.asnumpy(), a @ b, rtol=1e-4)
    got = nd.linalg.gemm2(nd.array(a), nd.array(b.T), transpose_b=True)
    assert_almost_equal(got.asnumpy(), a @ b, rtol=1e-4)
    # batched
    ab = rng.randn(2, 3, 4).astype("float32")
    bb = rng.randn(2, 4, 5).astype("float32")
    got = nd.linalg.gemm2(nd.array(ab), nd.array(bb))
    assert_almost_equal(got.asnumpy(), np.einsum("bij,bjk->bik", ab, bb),
                        rtol=1e-4)


def _spd(rng, n):
    m = rng.randn(n, n).astype("float32")
    return m @ m.T + n * np.eye(n, dtype="float32")


def test_laop():
    """reference test_laop: potrf/potri/trsm/trmm round trips."""
    rng = _rng(4)
    spd = _spd(rng, 4)
    L = nd.linalg.potrf(nd.array(spd)).asnumpy()
    assert_almost_equal(L @ L.T, spd, rtol=1e-3, atol=1e-3)
    inv = nd.linalg.potri(nd.array(L)).asnumpy()
    assert_almost_equal(inv @ spd, np.eye(4), rtol=1e-2, atol=1e-2)
    # trsm solves L x = alpha * b
    bmat = rng.randn(4, 3).astype("float32")
    x = nd.linalg.trsm(nd.array(L), nd.array(bmat), alpha=1.0).asnumpy()
    assert_almost_equal(L @ x, bmat, rtol=1e-3, atol=1e-3)
    y = nd.linalg.trmm(nd.array(L), nd.array(bmat)).asnumpy()
    assert_almost_equal(y, L @ bmat, rtol=1e-4, atol=1e-4)


def test_laop_2():
    """syrk + sumlogdiag + makediag/extractdiag."""
    rng = _rng(5)
    a = rng.randn(3, 4).astype("float32")
    got = nd.linalg.syrk(nd.array(a), alpha=1.0).asnumpy()
    assert_almost_equal(got, a @ a.T, rtol=1e-4)
    got = nd.linalg.syrk(nd.array(a), transpose=True).asnumpy()
    assert_almost_equal(got, a.T @ a, rtol=1e-4)
    spd = _spd(rng, 3)
    L = np.linalg.cholesky(spd).astype("float32")
    sld = float(nd.linalg.sumlogdiag(nd.array(L)).asnumpy())
    assert_almost_equal(sld, np.log(np.diag(L)).sum(), rtol=1e-4)
    v = rng.randn(4).astype("float32")
    D = nd.linalg.makediag(nd.array(v)).asnumpy()
    assert_almost_equal(D, np.diag(v))
    back = nd.linalg.extractdiag(nd.array(D)).asnumpy()
    assert_almost_equal(back, v)


def test_laop_3():
    """gelqf: LQ decomposition reconstructs and Q is orthonormal."""
    rng = _rng(6)
    a = rng.randn(3, 5).astype("float32")
    q, l = nd.linalg.gelqf(nd.array(a))
    qn, ln = q.asnumpy(), l.asnumpy()
    assert_almost_equal(ln @ qn, a, rtol=1e-3, atol=1e-3)
    assert_almost_equal(qn @ qn.T, np.eye(3), rtol=1e-3, atol=1e-3)


def test_laop_4():
    """syevd: eigendecomposition of a symmetric matrix."""
    rng = _rng(7)
    spd = _spd(rng, 4)
    u, lam = nd.linalg.syevd(nd.array(spd))
    un, ln = u.asnumpy(), lam.asnumpy()
    # rows of U are eigenvectors: U^T diag(lam) U ... reference layout
    rec = un.T @ np.diag(ln) @ un
    assert_almost_equal(rec, spd, rtol=1e-2, atol=1e-2)


def test_laop_5():
    """det / slogdet / inverse."""
    rng = _rng(8)
    spd = _spd(rng, 3)
    d = float(nd.linalg.det(nd.array(spd)).asnumpy())
    assert_almost_equal(d, np.linalg.det(spd), rtol=1e-3)
    sign, logabs = nd.linalg.slogdet(nd.array(spd))
    assert float(sign.asnumpy()) == 1.0
    assert_almost_equal(float(logabs.asnumpy()),
                        np.log(np.linalg.det(spd)), rtol=1e-3)
    inv = nd.linalg.inverse(nd.array(spd)).asnumpy()
    assert_almost_equal(inv @ spd, np.eye(3), rtol=1e-2, atol=1e-2)


def test_laop_6():
    """Gradients through potrf/gemm2 via autograd."""
    rng = _rng(9)
    spd = _spd(rng, 3)
    a = nd.array(spd)
    a.attach_grad()
    with autograd.record():
        L = nd.linalg.potrf(a)
        out = nd.linalg.sumlogdiag(L)   # = 1/2 log det(A)
    out.backward()
    # d/dA (1/2 logdet A) = 1/2 A^{-T}; symmetrized variants accepted
    want = 0.5 * np.linalg.inv(spd).T
    got = a.grad.asnumpy()
    assert_almost_equal(got + got.T, want + want.T, rtol=1e-2,
                        atol=1e-3)


# ------------------------------------------- introspection / monitor

def test_op_output_names_monitor():
    """Monitor sees per-op output names (reference
    test_op_output_names_monitor)."""
    from mxnet_tpu.monitor import Monitor
    seen = []
    mon = Monitor(1, stat_func=lambda x: x,
                  pattern=".*", sort=True)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    mod = mx.mod.Module(act, context=mx.cpu(), label_names=None)
    mod.bind(data_shapes=[("data", (2, 4))], for_training=False)
    mod.init_params()
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(mx.io.DataBatch(data=[nd.ones((2, 4))]), is_train=False)
    names = [k for _n, k, _v in mon.toc()]   # (step, name, stat)
    joined = " ".join(str(n) for n in names)
    assert "fc" in joined and "act" in joined, joined


def test_get_all_registered_operators():
    from mxnet_tpu.ops import registry
    ops = registry.list_ops() if hasattr(registry, "list_ops") else \
        list(registry._OPS if hasattr(registry, "_OPS") else [])
    assert len(ops) > 250
    assert "Convolution" in ops and "FullyConnected" in ops


def test_get_operator_arguments():
    """Operator signatures are introspectable (reference
    mx.operator.get_operator_arguments)."""
    import inspect
    from mxnet_tpu.ops import registry
    op = registry.get("Convolution")
    sig = inspect.signature(op.fn)
    names = list(sig.parameters)
    for want in ("kernel", "stride", "pad", "num_filter"):
        assert want in names, names


def test_context_num_gpus():
    n = mx.context.num_gpus()
    assert isinstance(n, int) and n >= 0


def test_np_shape_decorator():
    """np_shape context/decorator exists and is a no-op-safe toggle
    (zero-dim shapes are always on in this build)."""
    if hasattr(mx.util, "np_shape"):
        with mx.util.np_shape(True):
            assert nd.zeros(()).shape == ()
    assert nd.zeros(()).shape == ()
