"""RCNN-family op tests (reference tests for proposal/psroi/deformable)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_proposal_shapes_and_validity():
    rng = np.random.RandomState(0)
    N, A, H, W = 1, 12, 8, 8  # 4 scales x 3 ratios
    cls_prob = mx.nd.array(rng.rand(N, 2 * A, H, W).astype("float32"))
    bbox_pred = mx.nd.array(rng.randn(N, 4 * A, H, W).astype("float32") * 0.1)
    im_info = mx.nd.array([[128.0, 128.0, 1.0]])
    rois = mx.nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                                  rpn_pre_nms_top_n=200,
                                  rpn_post_nms_top_n=50)
    assert rois.shape == (50, 5)
    r = rois.asnumpy()
    assert (r[:, 0] == 0).all()          # batch index
    assert (r[:, 1:] >= 0).all()         # clipped to image
    assert (r[:, 3] <= 128).all() and (r[:, 4] <= 128).all()
    # with scores
    rois2, scores = mx.nd.contrib.Proposal(
        cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=200,
        rpn_post_nms_top_n=50, output_score=True)
    assert scores.shape == (50, 1)


def test_psroi_pooling_uniform_input():
    """Uniform feature maps pool to the channel means regardless of bins."""
    od, p = 2, 3
    data = np.zeros((1, od * p * p, 16, 16), dtype="float32")
    for ch in range(od * p * p):
        data[0, ch] = ch
    rois = mx.nd.array([[0, 2, 2, 10, 10]], dtype="float32")
    out = mx.nd.contrib.PSROIPooling(mx.nd.array(data), rois,
                                     spatial_scale=1.0, output_dim=od,
                                     pooled_size=p)
    assert out.shape == (1, od, p, p)
    o = out.asnumpy()
    # bin (ph, pw) of output channel ch reads channel ch*9 + ph*3 + pw
    for ch in range(od):
        for ph in range(p):
            for pw in range(p):
                assert o[0, ch, ph, pw] == ch * 9 + ph * 3 + pw


def test_correlation_matches_numpy():
    rng = np.random.RandomState(0)
    a = rng.rand(1, 4, 6, 6).astype("float32")
    b = rng.rand(1, 4, 6, 6).astype("float32")
    out = mx.nd.Correlation(mx.nd.array(a), mx.nd.array(b), kernel_size=1,
                            max_displacement=1, stride1=1, stride2=1,
                            pad_size=1)
    assert out.shape == (1, 9, 6, 6)
    o = out.asnumpy()[0]
    # zero-displacement channel (index 4) = per-pixel channel-mean product
    expected_center = (a[0] * b[0]).mean(axis=0)
    np.testing.assert_allclose(o[4, :, :], expected_center, rtol=1e-5,
                               atol=1e-6)
    # displacement (dy=1, dx=0) → channel 7 compares a[y] with b[y+1]
    expected = (a[0, :, :5, :] * b[0, :, 1:, :]).mean(axis=0)
    np.testing.assert_allclose(o[7, :5, :], expected, rtol=1e-5, atol=1e-6)


def test_deformable_conv_zero_offset_matches_conv():
    """Zero offsets reduce deformable conv to ordinary convolution."""
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype("float32"))
    w = mx.nd.array(rng.randn(4, 3, 3, 3).astype("float32"))
    b = mx.nd.array(np.zeros(4, dtype="float32"))
    offset = mx.nd.zeros((2, 2 * 9, 6, 6))
    out_d = mx.nd.contrib.DeformableConvolution(
        x, offset, w, b, kernel=(3, 3), num_filter=4)
    out_c = mx.nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    np.testing.assert_allclose(out_d.asnumpy(), out_c.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_shifted_offset():
    """A constant integer offset equals sampling the shifted image."""
    rng = np.random.RandomState(0)
    x = rng.randn(1, 1, 10, 10).astype("float32")
    w = np.zeros((1, 1, 1, 1), dtype="float32")
    w[0, 0, 0, 0] = 1.0
    offset = np.zeros((1, 2, 10, 10), dtype="float32")
    offset[:, 0] = 1.0  # shift sampling down by one row
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(offset), mx.nd.array(w),
        kernel=(1, 1), num_filter=1, no_bias=True, pad=(0, 0))
    np.testing.assert_allclose(out.asnumpy()[0, 0, :9],
                               x[0, 0, 1:10], rtol=1e-5)


def _ref_deformable_psroi(data, rois, trans, scale, od, g, p, ps, spp, tstd,
                          no_trans):
    """Direct numpy port of the reference CPU kernel
    (deformable_psroi_pooling.cc DeformablePSROIPoolForwardCPU)."""
    n, c, h, w = data.shape
    ncls = 1 if no_trans else trans.shape[1] // 2
    cpc = max(od // ncls, 1)
    out = np.zeros((rois.shape[0], od, p, p), np.float32)
    for r in range(rois.shape[0]):
        b = int(rois[r, 0])
        x1 = round(rois[r, 1]) * scale - 0.5
        y1 = round(rois[r, 2]) * scale - 0.5
        x2 = (round(rois[r, 3]) + 1) * scale - 0.5
        y2 = (round(rois[r, 4]) + 1) * scale - 0.5
        rw, rh = max(x2 - x1, 0.1), max(y2 - y1, 0.1)
        bw, bh = rw / p, rh / p
        for ct in range(od):
            for ph in range(p):
                for pw in range(p):
                    pth = int(np.floor(ph / p * ps))
                    ptw = int(np.floor(pw / p * ps))
                    cls = ct // cpc
                    tx = 0.0 if no_trans else trans[r, cls * 2, pth, ptw] * tstd
                    ty = 0.0 if no_trans else trans[r, cls * 2 + 1, pth, ptw] * tstd
                    wst, hst = pw * bw + x1 + tx * rw, ph * bh + y1 + ty * rh
                    gw = min(max(int(np.floor(pw * g / p)), 0), g - 1)
                    gh = min(max(int(np.floor(ph * g / p)), 0), g - 1)
                    ch = (ct * g + gh) * g + gw
                    s, cnt = 0.0, 0
                    for ih in range(spp):
                        for iw in range(spp):
                            ww = wst + iw * (bw / spp)
                            hh = hst + ih * (bh / spp)
                            if ww < -0.5 or ww > w - 0.5 or hh < -0.5 or hh > h - 0.5:
                                continue
                            ww = min(max(ww, 0), w - 1)
                            hh = min(max(hh, 0), h - 1)
                            xl, xh = int(np.floor(ww)), int(np.ceil(ww))
                            yl, yh = int(np.floor(hh)), int(np.ceil(hh))
                            dx, dy = ww - xl, hh - yl
                            s += (1 - dx) * (1 - dy) * data[b, ch, yl, xl] + \
                                (1 - dx) * dy * data[b, ch, yh, xl] + \
                                dx * (1 - dy) * data[b, ch, yl, xh] + \
                                dx * dy * data[b, ch, yh, xh]
                            cnt += 1
                    out[r, ct, ph, pw] = 0.0 if cnt == 0 else s / cnt
    return out


def test_deformable_psroi_pooling_matches_reference_kernel():
    rng = np.random.RandomState(0)
    od, g, p, ps, spp = 2, 2, 3, 3, 2
    data = rng.randn(2, od * g * g, 12, 12).astype(np.float32)
    rois = np.array([[0, 1, 1, 8, 8], [1, 0, 2, 10, 11], [0, 3, 3, 5, 6]],
                    np.float32)
    trans = (rng.rand(3, 2 * 2, ps, ps).astype(np.float32) - 0.5)
    got = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=0.5, output_dim=od, group_size=g, pooled_size=p,
        part_size=ps, sample_per_part=spp, trans_std=0.2).asnumpy()
    want = _ref_deformable_psroi(data, rois, trans, 0.5, od, g, p, ps, spp,
                                 0.2, False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_deformable_psroi_pooling_no_trans_and_grad():
    rng = np.random.RandomState(1)
    od, g, p = 1, 2, 2
    data = mx.nd.array(rng.randn(1, od * g * g, 8, 8).astype(np.float32))
    rois = mx.nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    got = mx.nd.contrib.DeformablePSROIPooling(
        data, rois, spatial_scale=1.0, output_dim=od, group_size=g,
        pooled_size=p, sample_per_part=2, no_trans=True)
    want = _ref_deformable_psroi(data.asnumpy(), rois.asnumpy(), None, 1.0,
                                 od, g, p, p, 2, 0.0, True)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5, atol=1e-5)
    # differentiable through data (reference has a hand-written backward)
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.contrib.DeformablePSROIPooling(
            data, rois, spatial_scale=1.0, output_dim=od, group_size=g,
            pooled_size=p, sample_per_part=2, no_trans=True)
    out.backward()
    assert np.isfinite(data.grad.asnumpy()).all()
    assert np.abs(data.grad.asnumpy()).sum() > 0
