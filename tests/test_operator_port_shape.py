"""Reference test_operator.py port, tranche 2: shape manipulation and
indexing cases.  Names mirror tests/python/unittest/test_operator.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

_rng = np.random.RandomState


def test_reshape():
    """The reference's big reshape spec table: 0 (copy dim), -1 (infer),
    -2 (copy rest), -3 (merge two), -4 (split)."""
    rng = _rng(0)
    # the reference's authoritative case table (test_operator.py:2360)
    cases = [
        ((2, 3, 5, 5), (0, -1), False, (2, 75)),
        ((2, 3, 5, 5), (0, 0, -1), False, (2, 3, 25)),
        ((5, 3, 4, 5), (0, -1, 0), False, (5, 15, 4)),
        ((2, 3, 5, 4), (-1, 0, 0), False, (8, 3, 5)),
        ((2, 3, 5, 5), (0, 0, 0, 0), False, (2, 3, 5, 5)),
        ((2, 4, 5, 3), (-1, 2, 2, 1), False, (30, 2, 2, 1)),
        ((2, 3, 5, 6), (-2,), False, (2, 3, 5, 6)),
        ((2, 3, 5, 6), (6, 1, -2), False, (6, 1, 5, 6)),
        ((2, 3, 5, 6), (-3, -3), False, (6, 30)),
        ((2, 3, 5, 6), (-3, -1), False, (6, 30)),
        ((64,), (-4, 16, 4), False, (16, 4)),
        ((64,), (-4, 16, -1), False, (16, 4)),
        ((64, 1, 2, 3), (-4, 16, -1, -2), False, (16, 4, 1, 2, 3)),
        ((2, 3, 5, 5), (0, -1), True, (5, 30)),
        ((2, 3, 5, 5), (0, 0, -1), True, (3, 5, 10)),
        ((5, 3, 4, 5), (0, -1, 0), True, (3, 20, 5)),
        ((2, 3, 5, 4), (-1, 0, 0), True, (6, 5, 4)),
        ((2, 3, 4, 5), (3, -1, 0), True, (3, 8, 5)),
        ((2, 3, 5, 5), (5, 3, 0, -1), True, (5, 3, 5, 2)),
        ((2, 3, 5, 5), (0, 0, 0, 0), True, (2, 3, 5, 5)),
        ((2, 3, 5, 6), (-2,), True, (2, 3, 5, 6)),
        ((2, 3, 5, 6), (-2, 1, 30), True, (2, 3, 1, 30)),
        ((2, 3, 5, 6), (-3, -3), True, (6, 30)),
        ((64,), (16, 4, -4), True, (16, 4)),
        ((64,), (16, -1, -4), True, (16, 4)),
        ((1, 2, 3, 64), (-2, -1, 16, -4), True, (1, 2, 3, 4, 16)),
    ]
    for src_shape, spec, reverse, want in cases:
        x = rng.randn(*src_shape).astype("float32")
        got = nd.reshape(nd.array(x), shape=spec, reverse=reverse)
        assert got.shape == want, (src_shape, spec, reverse, got.shape)
        assert_almost_equal(got.asnumpy().ravel(), x.ravel())
    # legacy target_shape api
    s = mx.sym.Reshape(mx.sym.Variable("data"), target_shape=(2, 0))
    _, oshape, _ = s.infer_shape(data=(2, 3, 5, 5))
    assert oshape[0] == (2, 75)


def test_reshape_new():
    """Gradient flows through reshape unchanged."""
    x = _rng(1).randn(2, 3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = (nd.reshape(a, shape=(4, 6)) * 2).sum()
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.full_like(x, 2.0))


def test_reshape_like():
    rng = _rng(2)
    x = rng.randn(2, 12).astype("float32")
    tmpl = nd.zeros((4, 3, 2))
    got = nd.reshape_like(nd.array(x), tmpl)
    assert got.shape == (4, 3, 2)
    assert_almost_equal(got.asnumpy().ravel(), x.ravel())


def test_reshape_like_new():
    """lhs_begin/lhs_end/rhs_begin/rhs_end partial reshape."""
    # reference case table (test_operator.py:2438)
    x = _rng(3).randn(30).astype("float32")
    tmpl = nd.zeros((15, 2, 4))
    got = nd.reshape_like(nd.array(x), tmpl, lhs_begin=0, lhs_end=None,
                          rhs_begin=0, rhs_end=2)
    assert got.shape == (15, 2)
    got = nd.reshape_like(nd.array(x), tmpl, lhs_begin=None, lhs_end=1,
                          rhs_begin=None, rhs_end=2)
    assert got.shape == (15, 2)


def test_reshape_like_different_types():
    x = nd.array(_rng(4).randn(2, 6).astype("float32"))
    tmpl = nd.zeros((3, 4), dtype="int32")
    got = nd.reshape_like(x, tmpl)
    assert got.shape == (3, 4) and got.dtype == np.float32


def test_slice_like_different_types():
    x = nd.array(_rng(5).randn(5, 6).astype("float32"))
    tmpl = nd.zeros((3, 4), dtype="int32")
    got = nd.slice_like(x, tmpl)
    assert got.shape == (3, 4)


def test_reduce():
    """sum/mean/prod/max/min/nansum/nanprod over axis combos, fwd+bwd."""
    rng = _rng(6)
    x = rng.rand(2, 3, 4).astype("float32") + 0.2
    for name, ref in [("sum", np.sum), ("mean", np.mean),
                      ("prod", np.prod), ("max", np.max), ("min", np.min)]:
        for axis in (None, 0, 1, 2, (0, 2), (1, 2)):
            kw = {} if axis is None else {"axis": axis}
            got = getattr(nd, name)(nd.array(x), **kw)
            want = ref(x) if axis is None else ref(x, axis=axis)
            assert_almost_equal(got.asnumpy(), np.asarray(want,
                                                          "float32"),
                                rtol=1e-4)
            kw["keepdims"] = True
            got = getattr(nd, name)(nd.array(x), **kw)
            want = ref(x, axis=axis, keepdims=True) if axis is not None \
                else ref(x, keepdims=True)
            assert_almost_equal(got.asnumpy(),
                                np.asarray(want, "float32"), rtol=1e-4)
    # nansum / nanprod skip NaNs
    xn = x.copy()
    xn[0, 0, 0] = np.nan
    assert_almost_equal(nd.nansum(nd.array(xn), axis=0).asnumpy(),
                        np.nansum(xn, axis=0), rtol=1e-4)
    assert_almost_equal(nd.nanprod(nd.array(xn), axis=0).asnumpy(),
                        np.nanprod(xn, axis=0), rtol=1e-4)


def test_reduce_inner():
    """sum gradient broadcasts the head grad back over reduced axes."""
    x = _rng(7).rand(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sum(a, axis=1)
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.ones_like(x))
    with autograd.record():
        y = nd.max(a, axis=1)
    y.backward()
    onehot = (x == x.max(axis=1, keepdims=True)).astype("float32")
    assert_almost_equal(a.grad.asnumpy(), onehot)


def test_broadcast():
    rng = _rng(8)
    x = rng.randn(1, 3, 1).astype("float32")
    got = nd.broadcast_to(nd.array(x), shape=(2, 3, 4))
    assert_almost_equal(got.asnumpy(), np.broadcast_to(x, (2, 3, 4)))
    got = nd.broadcast_axis(nd.array(x), axis=(0, 2), size=(2, 4))
    assert_almost_equal(got.asnumpy(), np.broadcast_to(x, (2, 3, 4)))
    tmpl = nd.zeros((2, 3, 4))
    got = nd.broadcast_like(nd.array(x), tmpl)
    assert_almost_equal(got.asnumpy(), np.broadcast_to(x, (2, 3, 4)))
    # backward of broadcast = sum over broadcast axes
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.broadcast_to(a, shape=(2, 3, 4))
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.full((1, 3, 1), 8.0))


def test_transpose():
    rng = _rng(9)
    x = rng.randn(2, 3, 4).astype("float32")
    assert_almost_equal(nd.transpose(nd.array(x)).asnumpy(), x.T)
    for axes in ((0, 2, 1), (2, 0, 1), (1, 2, 0)):
        assert_almost_equal(nd.transpose(nd.array(x), axes=axes).asnumpy(),
                            np.transpose(x, axes))


def test_expand_dims():
    x = _rng(10).randn(2, 3).astype("float32")
    for axis in (0, 1, 2, -1, -2):
        got = nd.expand_dims(nd.array(x), axis=axis)
        assert_almost_equal(got.asnumpy(), np.expand_dims(x, axis))


def test_crop():
    x = _rng(11).randn(2, 3, 4).astype("float32")
    got = nd.crop(nd.array(x), begin=(0, 1, 1), end=(2, 3, 3))
    assert_almost_equal(got.asnumpy(), x[0:2, 1:3, 1:3])


def test_slice_axis():
    x = _rng(12).randn(3, 4, 5).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.slice_axis(a, axis=1, begin=1, end=3)
    y.backward()
    assert_almost_equal(y.asnumpy(), x[:, 1:3])
    want = np.zeros_like(x)
    want[:, 1:3] = 1
    assert_almost_equal(a.grad.asnumpy(), want)
    # negative begin/end
    got = nd.slice_axis(nd.array(x), axis=2, begin=-3, end=None)
    assert_almost_equal(got.asnumpy(), x[:, :, -3:])


def test_slice_like():
    rng = _rng(13)
    x = rng.randn(4, 5).astype("float32")
    tmpl = nd.zeros((2, 3))
    assert_almost_equal(nd.slice_like(nd.array(x), tmpl).asnumpy(),
                        x[:2, :3])
    # axes restricts which dims are sliced
    got = nd.slice_like(nd.array(x), tmpl, axes=(0,))
    assert_almost_equal(got.asnumpy(), x[:2, :])


def test_flip():
    x = _rng(14).randn(2, 3, 4).astype("float32")
    for axis in (0, 1, 2):
        got = nd.flip(nd.array(x), axis=axis)
        assert_almost_equal(got.asnumpy(), np.flip(x, axis))


def test_stack():
    rng = _rng(15)
    parts = [rng.randn(3, 4).astype("float32") for _ in range(3)]
    for axis in (0, 1, 2):
        got = nd.stack(*[nd.array(p) for p in parts], axis=axis)
        assert_almost_equal(got.asnumpy(), np.stack(parts, axis=axis))


def test_repeat():
    """reference test_repeat (forward/backward/numeric)."""
    x = _rng(16).randn(2, 3).astype("float32")
    # flat repeat
    got = nd.repeat(nd.array(x), repeats=2)
    assert_almost_equal(got.asnumpy(), np.repeat(x, 2))
    for axis in (0, 1):
        got = nd.repeat(nd.array(x), repeats=3, axis=axis)
        assert_almost_equal(got.asnumpy(), np.repeat(x, 3, axis=axis))
    # backward: grads accumulate across the repeats
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.repeat(a, repeats=2, axis=0)
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.full_like(x, 2.0))
    sym = mx.sym.repeat(mx.sym.Variable("x"), repeats=2, axis=1)
    check_numeric_gradient(sym, {"x": nd.array(x)}, rtol=0.05, atol=1e-3)


def test_tile():
    """reference test_tile: normal / empty reps / backward / numeric /
    invalid."""
    x = _rng(17).randn(2, 3).astype("float32")
    got = nd.tile(nd.array(x), reps=(2, 2))
    assert_almost_equal(got.asnumpy(), np.tile(x, (2, 2)))
    got = nd.tile(nd.array(x), reps=(1, 2, 3))
    assert_almost_equal(got.asnumpy(), np.tile(x, (1, 2, 3)))
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.tile(a, reps=(2, 3))
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), np.full_like(x, 6.0))
    sym = mx.sym.tile(mx.sym.Variable("x"), reps=(2, 2))
    check_numeric_gradient(sym, {"x": nd.array(x)}, rtol=0.05, atol=1e-3)


def test_reverse():
    x = _rng(18).randn(2, 3, 4).astype("float32")
    got = nd.reverse(nd.array(x), axis=(0, 2))
    assert_almost_equal(got.asnumpy(), x[::-1, :, ::-1])


def test_one_hot():
    """normal / empty indices / zero depth cases."""
    idx = np.array([1, 0, 2, 1], "float32")
    got = nd.one_hot(nd.array(idx), depth=3)
    assert_almost_equal(got.asnumpy(), np.eye(3, dtype="float32")[
        idx.astype(int)])
    got = nd.one_hot(nd.array(idx), depth=3, on_value=5.0, off_value=-1.0)
    ref = np.full((4, 3), -1.0, "float32")
    ref[np.arange(4), idx.astype(int)] = 5.0
    assert_almost_equal(got.asnumpy(), ref)
    # out-of-range indices produce all-off rows (reference contract)
    got = nd.one_hot(nd.array(np.array([3.0, 1.0], "float32")), depth=3)
    assert_almost_equal(got.asnumpy()[0], np.zeros(3, "float32"))


def test_where():
    """reference test_where: helper + numeric grad + 1-d cond."""
    rng = _rng(19)
    cond = rng.randint(0, 2, (3, 4)).astype("float32")
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    got = nd.where(nd.array(cond), nd.array(x), nd.array(y))
    assert_almost_equal(got.asnumpy(), np.where(cond, x, y))
    # gradient routes to the selected branch only
    a, b = nd.array(x), nd.array(y)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        out = nd.where(nd.array(cond), a, b)
    out.backward()
    assert_almost_equal(a.grad.asnumpy(), cond)
    assert_almost_equal(b.grad.asnumpy(), 1 - cond)
    # 1-d cond selects along the batch axis
    cond1 = np.array([1, 0, 1], "float32")
    got = nd.where(nd.array(cond1), nd.array(x), nd.array(y))
    ref = np.where(cond1[:, None].astype(bool), x, y)
    assert_almost_equal(got.asnumpy(), ref)


def test_take():
    """reference test_take: axes x clip/wrap modes, fwd + bwd."""
    rng = _rng(20)
    x = rng.randn(4, 5, 6).astype("float32")
    for axis in (0, 1, 2):
        idx = rng.randint(0, x.shape[axis], (2, 3)).astype("float32")
        got = nd.take(nd.array(x), nd.array(idx), axis=axis)
        assert_almost_equal(got.asnumpy(),
                            np.take(x, idx.astype(int), axis=axis))
    # clip mode on out-of-range
    idx = np.array([[-1, 7]], "float32")
    got = nd.take(nd.array(x), nd.array(idx), axis=0, mode="clip")
    assert_almost_equal(got.asnumpy(),
                        np.take(x, [[0, 3]], axis=0))
    got = nd.take(nd.array(x), nd.array(idx), axis=0, mode="wrap")
    assert_almost_equal(got.asnumpy(),
                        np.take(x, [[-1, 7]], axis=0, mode="wrap"))
    # backward accumulates over duplicate indices
    a = nd.array(x)
    a.attach_grad()
    dup = nd.array(np.array([0, 0, 1], "float32"))
    with autograd.record():
        y = nd.take(a, dup, axis=0)
    y.backward()
    want = np.zeros_like(x)
    want[0] = 2
    want[1] = 1
    assert_almost_equal(a.grad.asnumpy(), want)


def test_pick():
    rng = _rng(21)
    x = rng.randn(4, 5).astype("float32")
    idx = rng.randint(0, 5, (4,)).astype("float32")
    got = nd.pick(nd.array(x), nd.array(idx), axis=1)
    assert_almost_equal(got.asnumpy(), x[np.arange(4), idx.astype(int)])
    got = nd.pick(nd.array(x), nd.array(idx), axis=1, keepdims=True)
    assert got.shape == (4, 1)
    # clip mode
    got = nd.pick(nd.array(x), nd.array(np.array([9.0] * 4, "float32")),
                  axis=1, mode="clip")
    assert_almost_equal(got.asnumpy(), x[:, -1])


def test_index2d():
    """reference test_index2d = batch_take."""
    rng = _rng(22)
    x = rng.randn(6, 7).astype("float32")
    idx = rng.randint(0, 7, (6,)).astype("int32")
    got = nd.batch_take(nd.array(x), nd.array(idx, dtype="int32"))
    assert_almost_equal(got.asnumpy(), x[np.arange(6), idx])


def test_diag():
    rng = _rng(23)
    # 1-D -> matrix
    v = rng.randn(4).astype("float32")
    assert_almost_equal(nd.diag(nd.array(v)).asnumpy(), np.diag(v))
    assert_almost_equal(nd.diag(nd.array(v), k=1).asnumpy(), np.diag(v, 1))
    # 2-D -> diagonal
    m = rng.randn(4, 5).astype("float32")
    assert_almost_equal(nd.diag(nd.array(m)).asnumpy(), np.diag(m))
    assert_almost_equal(nd.diag(nd.array(m), k=-1).asnumpy(),
                        np.diag(m, -1))


def test_depthtospace():
    rng = _rng(24)
    b = 2
    x = rng.randn(1, 4 * b * b, 3, 5).astype("float32")
    got = nd.depth_to_space(nd.array(x), block_size=b)
    n, c, h, w = x.shape
    tmp = x.reshape(n, b, b, c // (b * b), h, w)
    ref = tmp.transpose(0, 3, 4, 1, 5, 2).reshape(n, c // (b * b),
                                                  h * b, w * b)
    assert_almost_equal(got.asnumpy(), ref)
    # round-trips with spacetodepth
    back = nd.space_to_depth(got, block_size=b)
    assert_almost_equal(back.asnumpy(), x)


def test_depthtospace_invalid():
    """invalid depth / space dims / block size raise."""
    x = nd.zeros((1, 5, 3, 3))
    with pytest.raises(Exception):
        nd.depth_to_space(x, block_size=2).asnumpy()
    with pytest.raises(Exception):
        nd.space_to_depth(nd.zeros((1, 4, 3, 5)), block_size=2).asnumpy()


def test_spacetodepth():
    rng = _rng(25)
    b = 2
    x = rng.randn(1, 3, 4 * b, 5 * b).astype("float32")
    got = nd.space_to_depth(nd.array(x), block_size=b)
    n, c, h, w = x.shape
    tmp = x.reshape(n, c, h // b, b, w // b, b)
    ref = tmp.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b,
                                                  h // b, w // b)
    assert_almost_equal(got.asnumpy(), ref)


def test_split_v2():
    rng = _rng(26)
    x = rng.randn(6, 4).astype("float32")
    outs = nd.split_v2(nd.array(x), indices_or_sections=3, axis=0)
    for i, o in enumerate(outs):
        assert_almost_equal(o.asnumpy(), x[2 * i:2 * i + 2])
    outs = nd.split_v2(nd.array(x), indices_or_sections=(1, 4), axis=0)
    assert_almost_equal(outs[0].asnumpy(), x[:1])
    assert_almost_equal(outs[1].asnumpy(), x[1:4])
    assert_almost_equal(outs[2].asnumpy(), x[4:])


def test_squeeze_op():
    x = _rng(27).randn(1, 3, 1, 4).astype("float32")
    assert nd.squeeze(nd.array(x)).shape == (3, 4)
    assert nd.squeeze(nd.array(x), axis=0).shape == (3, 1, 4)
    assert nd.squeeze(nd.array(x), axis=(0, 2)).shape == (3, 4)
    with pytest.raises(Exception):
        nd.squeeze(nd.array(x), axis=1).asnumpy()


def test_ravel():
    """ravel_multi_index / unravel_index round trip."""
    shape = (5, 7)
    idx = np.array([[1, 4, 0], [3, 2, 6]], "float32")   # (2, N) multi
    flat = nd.ravel_multi_index(nd.array(idx), shape=shape)
    ref = np.ravel_multi_index(idx.astype(int), shape)
    assert (flat.asnumpy().astype(int) == ref).all()
    back = nd.unravel_index(flat, shape=shape)
    assert_almost_equal(back.asnumpy(), idx)


def test_order():
    """reference test_order: sort/argsort/topk value+indices agree with
    numpy orderings."""
    rng = _rng(28)
    x = rng.randn(4, 6).astype("float32")
    assert_almost_equal(nd.sort(nd.array(x), axis=1).asnumpy(),
                        np.sort(x, axis=1))
    assert_almost_equal(nd.sort(nd.array(x), axis=1,
                                is_ascend=False).asnumpy(),
                        -np.sort(-x, axis=1))
    assert (nd.argsort(nd.array(x), axis=1).asnumpy().astype(int)
            == np.argsort(x, axis=1)).all()
    got = nd.topk(nd.array(x), k=3, axis=1, ret_typ="value")
    assert_almost_equal(got.asnumpy(), -np.sort(-x, axis=1)[:, :3])
    gi = nd.topk(nd.array(x), k=3, axis=1).asnumpy().astype(int)
    ref = np.argsort(-x, axis=1)[:, :3]
    assert (gi == ref).all()
    both = nd.topk(nd.array(x), k=2, axis=1, ret_typ="both")
    assert_almost_equal(both[0].asnumpy(), -np.sort(-x, axis=1)[:, :2])
    # mask: 1 at the top-k positions
    m = nd.topk(nd.array(x), k=2, axis=1, ret_typ="mask").asnumpy()
    assert m.sum() == 8 and m.shape == x.shape


def test_arange():
    assert_almost_equal(nd.arange(10).asnumpy(),
                        np.arange(10, dtype="float32"))
    assert_almost_equal(nd.arange(2, 10, 2).asnumpy(),
                        np.arange(2, 10, 2, dtype="float32"))
    assert_almost_equal(nd.arange(0, 10, 3, repeat=2).asnumpy(),
                        np.repeat(np.arange(0, 10, 3), 2).astype("float32"))
    got = nd.arange(5, dtype="int32")
    assert got.dtype == np.int32


def test_arange_inferstop():
    # infer_range is the deprecated legacy knob — accepted and inert
    got = nd.arange(0, 10, infer_range=True)
    assert got.shape == (10,)


def test_arange_like_without_axis():
    x = nd.zeros((2, 3))
    got = nd.contrib.arange_like(x)
    assert got.shape == (2, 3)
    got = nd.contrib.arange_like(x, axis=1)
    assert_almost_equal(got.asnumpy(), np.arange(3, dtype="float32"))


def test_init():
    """reference test_init / test_basic_val_init: zeros/ones/full."""
    assert (nd.zeros((2, 3)).asnumpy() == 0).all()
    assert (nd.ones((2, 3)).asnumpy() == 1).all()
    assert (nd.full((2, 3), 7.5).asnumpy() == 7.5).all()
    z = nd.zeros((2, 3), dtype="int32")
    assert z.dtype == np.int32
    e = nd.eye(4)
    assert_almost_equal(e.asnumpy(), np.eye(4, dtype="float32"))
    e = nd.eye(3, 5, 1)
    assert_almost_equal(e.asnumpy(), np.eye(3, 5, 1, dtype="float32"))


def test_scatter_gather_nd():
    rng = _rng(29)
    x = rng.randn(4, 5).astype("float32")
    idx = np.array([[0, 2, 3], [1, 0, 4]], "float32")   # (2, N)
    got = nd.gather_nd(nd.array(x), nd.array(idx))
    assert_almost_equal(got.asnumpy(), x[[0, 2, 3], [1, 0, 4]])
    # scatter_nd builds from data
    data = nd.array(np.array([9.0, 8.0, 7.0], "float32"))
    scat = nd.scatter_nd(data, nd.array(idx), shape=(4, 5))
    ref = np.zeros((4, 5), "float32")
    ref[[0, 2, 3], [1, 0, 4]] = [9, 8, 7]
    assert_almost_equal(scat.asnumpy(), ref)
    # gather_nd backward accumulates duplicates
    a = nd.array(x)
    a.attach_grad()
    dup = nd.array(np.array([[0, 0], [1, 1]], "float32"))
    with autograd.record():
        y = nd.gather_nd(a, dup)
    y.backward()
    want = np.zeros_like(x)
    want[0, 1] = 2
    assert_almost_equal(a.grad.asnumpy(), want)


def test_index_copy():
    x = nd.zeros((5, 3))
    t = nd.array(_rng(30).randn(2, 3).astype("float32"))
    idx = nd.array(np.array([1, 3], "float32"), dtype="int32")
    got = nd.contrib.index_copy(x, idx, t)
    ref = np.zeros((5, 3), "float32")
    ref[[1, 3]] = t.asnumpy()
    assert_almost_equal(got.asnumpy(), ref)


def test_boolean_mask():
    x = nd.array(_rng(31).randn(4, 3).astype("float32"))
    mask = nd.array(np.array([1, 0, 1, 0], "float32"))
    got = nd.contrib.boolean_mask(x, mask)
    assert_almost_equal(got.asnumpy(), x.asnumpy()[[0, 2]])


def test_slice():
    """reference test_slice (+forward_backward, begin_equals_end)."""
    x = _rng(32).randn(4, 5, 6).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.slice(a, begin=(1, 0, 2), end=(3, 4, 5))
    y.backward()
    assert_almost_equal(y.asnumpy(), x[1:3, 0:4, 2:5])
    want = np.zeros_like(x)
    want[1:3, 0:4, 2:5] = 1
    assert_almost_equal(a.grad.asnumpy(), want)
    # steps, including negative
    got = nd.slice(nd.array(x), begin=(None, None, None),
                   end=(None, None, None), step=(1, 2, -1))
    assert_almost_equal(got.asnumpy(), x[:, ::2, ::-1])
    # begin == end -> empty
    got = nd.slice(nd.array(x), begin=(1,), end=(1,))
    assert got.shape[0] == 0


def test_float16_min_max():
    x = np.array([1.0, 65504.0, -65504.0, 1e-4], "float16")
    a = nd.array(x, dtype="float16")
    assert float(nd.max(a).asnumpy()) == 65504.0
    assert float(nd.min(a).asnumpy()) == -65504.0


def test_squeeze_zero_size():
    """reference zero-size tensor handling family: creation + concat."""
    z = nd.zeros((0, 4))
    assert z.shape == (0, 4)
    c = nd.concat(z, nd.zeros((2, 4)), dim=0)
    assert c.shape == (2, 4)
    assert nd.zeros(()).shape == ()       # scalar tensor creation


def test_index_array():
    """reference test_index_array (+default/zero-dim/select_axes)."""
    x = nd.zeros((3, 2))
    got = nd.contrib.index_array(x)
    ref = np.stack(np.meshgrid(np.arange(3), np.arange(2),
                               indexing="ij"), axis=-1)
    assert (got.asnumpy().astype(int) == ref).all()
    got = nd.contrib.index_array(x, axes=(1,))
    assert (got.asnumpy().astype(int) == ref[..., 1:]).all()
    # zero-size input keeps the contract
    z = nd.contrib.index_array(nd.zeros((0, 2)))
    assert z.shape == (0, 2, 2)


def test_tile_invalid_reps():
    with pytest.raises(Exception):
        nd.tile(nd.zeros((2, 2)), reps=(-1, 2)).asnumpy()
