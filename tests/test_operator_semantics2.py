"""Tricky operator semantics vs numpy (reference ``test_operator.py``
families not yet pinned): Pad modes, bilinear UpSampling values,
GridGenerator affine grids, softmax temperature, pick keepdims, take
modes, Embedding gradient accumulation on repeated indices, LRN formula,
smooth_l1 branches.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


@pytest.mark.parametrize("mode", ["edge", "reflect"])
def test_pad_modes(mode):
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = mx.nd.Pad(mx.nd.array(x), mode=mode,
                    pad_width=(0, 0, 0, 0, 1, 1, 2, 2))
    np_mode = {"edge": "edge", "reflect": "reflect"}[mode]
    want = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), mode=np_mode)
    np.testing.assert_allclose(out.asnumpy(), want)


def test_pad_constant_value():
    x = np.ones((1, 1, 2, 2), "float32")
    out = mx.nd.Pad(mx.nd.array(x), mode="constant", constant_value=9.0,
                    pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    w = out.asnumpy()
    assert w[0, 0, 0, 0] == 9.0 and w[0, 0, 1, 1] == 1.0


def test_upsampling_nearest_values():
    x = np.array([[[[1.0, 2.0], [3.0, 4.0]]]], "float32")
    out = mx.nd.UpSampling(mx.nd.array(x), scale=2,
                           sample_type="nearest")
    want = x.repeat(2, axis=2).repeat(2, axis=3)
    np.testing.assert_allclose(out.asnumpy(), want)


def test_grid_generator_affine_identity():
    """Identity affine → a uniform [-1, 1] grid (reference
    grid_generator.cc)."""
    theta = mx.nd.array([[1.0, 0, 0, 0, 1.0, 0]])
    grid = mx.nd.GridGenerator(theta, transform_type="affine",
                               target_shape=(3, 3))
    g = grid.asnumpy()[0]
    # channel 0 = x coords, channel 1 = y coords; corners at ±1
    assert g.shape == (2, 3, 3)
    np.testing.assert_allclose(g[0][:, 0], [-1, -1, -1], atol=1e-6)
    np.testing.assert_allclose(g[0][:, 2], [1, 1, 1], atol=1e-6)
    np.testing.assert_allclose(g[1][0, :], [-1, -1, -1], atol=1e-6)
    np.testing.assert_allclose(g[1][2, :], [1, 1, 1], atol=1e-6)


def test_softmax_temperature():
    x = np.array([[1.0, 2.0, 3.0]], "float32")
    out = mx.nd.softmax(mx.nd.array(x), temperature=2.0)
    e = np.exp(x / 2.0 - (x / 2.0).max())
    np.testing.assert_allclose(out.asnumpy(), e / e.sum(), rtol=1e-5)


def test_pick_keepdims_and_modes():
    x = np.arange(12, dtype="float32").reshape(3, 4)
    idx = mx.nd.array([0, 2, 3])
    out = mx.nd.pick(mx.nd.array(x), idx, axis=1, keepdims=True)
    assert out.shape == (3, 1)
    np.testing.assert_allclose(out.asnumpy().ravel(), [0, 6, 11])


def test_take_modes():
    x = mx.nd.array(np.arange(5, dtype="float32"))
    idx = mx.nd.array([-1.0, 7.0])
    clipd = mx.nd.take(x, idx, mode="clip")
    np.testing.assert_allclose(clipd.asnumpy(), [0, 4])
    wrapped = mx.nd.take(x, idx, mode="wrap")
    np.testing.assert_allclose(wrapped.asnumpy(), [4, 2])


def test_embedding_grad_accumulates_repeated_indices():
    """Repeated lookups of one row SUM their gradients (reference
    embedding backward AddTakeGrad)."""
    w = mx.nd.array(np.zeros((4, 2), "float32"))
    w.attach_grad()
    idx = mx.nd.array([1, 1, 1, 3])
    with mx.autograd.record():
        out = mx.nd.Embedding(idx, w, input_dim=4, output_dim=2)
        out.sum().backward()
    g = w.grad.asnumpy()
    np.testing.assert_allclose(g[1], [3, 3])
    np.testing.assert_allclose(g[3], [1, 1])
    np.testing.assert_allclose(g[0], [0, 0])


def test_lrn_formula():
    """LRN vs the explicit cross-channel formula (reference lrn.cc:
    out = x / (knorm + alpha/n * sum(x^2 over window))^beta)."""
    rng = np.random.RandomState(0)
    x = rng.rand(1, 5, 2, 2).astype("float32")
    nsize, alpha, beta, knorm = 3, 1e-2, 0.75, 2.0
    out = mx.nd.LRN(mx.nd.array(x), nsize=nsize, alpha=alpha, beta=beta,
                    knorm=knorm)
    want = np.zeros_like(x)
    half = nsize // 2
    for c in range(5):
        lo, hi = max(0, c - half), min(5, c + half + 1)
        sq = (x[:, lo:hi] ** 2).sum(axis=1)
        want[:, c] = x[:, c] / (knorm + alpha / nsize * sq) ** beta
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-4, atol=1e-5)


def test_smooth_l1_branches():
    sigma = 2.0
    x = np.array([-2.0, -0.1, 0.1, 2.0], "float32")
    out = mx.nd.smooth_l1(mx.nd.array(x), scalar=sigma)
    s2 = sigma ** 2
    want = np.where(np.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                    np.abs(x) - 0.5 / s2)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)


def test_log_softmax_gradient():
    x = mx.nd.array(np.array([[1.0, 2.0, 3.0]], "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.log_softmax(x)
        y[0, 0].backward()
    # d log_softmax_0 / dx = e_0 - softmax
    sm = np.exp([1, 2, 3]) / np.exp([1, 2, 3]).sum()
    want = np.eye(3)[0] - sm
    np.testing.assert_allclose(x.grad.asnumpy()[0], want, rtol=1e-5,
                               atol=1e-6)


def test_ravel_unravel_roundtrip():
    shape = (3, 4, 5)
    idx = mx.nd.array([[0, 1, 2], [1, 2, 3], [2, 3, 4]], dtype="float32")
    flat = mx.nd.ravel_multi_index(idx, shape=shape)
    np.testing.assert_allclose(flat.asnumpy(),
                               np.ravel_multi_index(
                                   idx.asnumpy().astype("int64"), shape))
    back = mx.nd.unravel_index(flat, shape=shape)
    np.testing.assert_allclose(back.asnumpy(), idx.asnumpy())
