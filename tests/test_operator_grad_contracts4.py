"""Fourth operator-contract tranche: indexing, gathering, ordering and
layout-movement gradients (reference ``test_operator.py``:
``test_take``/``test_pick``/``test_order``/``test_gather_nd`` etc. —
``check_numeric_gradient`` per attribute path).

These families route cotangents through index maps (take/pick/gather) or
permutations (sort/topk/transpose-like) where a wrong axis or an
unaccumulated duplicate index silently corrupts training.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (fd_grad_check as _grad_check,
                                  fd_rand as _rand)


# ------------------------------------------------------------------- take
@pytest.mark.parametrize("axis", [0, 1, 2])
def test_take_grad(axis):
    data = mx.sym.Variable("data")
    idx = mx.sym.Variable("idx")
    sym = mx.sym.take(data, idx, axis=axis)
    loc = {"data": _rand(3, 4, 5, seed=1),
           "idx": np.asarray([1, 0, 2, 1], "float32")}
    _grad_check(sym, loc, grad_nodes=["data"])


def test_take_duplicate_indices_accumulate():
    """Duplicate indices must SUM their cotangents (scatter-add), not
    overwrite (reference take backward AddTakeGrad)."""
    x = mx.nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    x.attach_grad()
    idx = mx.nd.array([1, 1, 1, 2])
    with mx.autograd.record():
        y = mx.nd.take(x, idx)
        loss = y.sum()
    loss.backward()
    want = np.zeros((4, 3), "float32")
    want[1] = 3.0
    want[2] = 1.0
    np.testing.assert_array_equal(x.grad.asnumpy(), want)


@pytest.mark.parametrize("mode", ["clip", "wrap"])
def test_take_out_of_range_modes(mode):
    x = mx.nd.array(np.arange(6, dtype="float32").reshape(3, 2))
    idx = mx.nd.array([-1, 3, 4])
    out = mx.nd.take(x, idx, mode=mode).asnumpy()
    xn = x.asnumpy()
    if mode == "clip":
        want = xn[[0, 2, 2]]
    else:
        want = xn[[-1 % 3, 3 % 3, 4 % 3]]
    np.testing.assert_array_equal(out, want)


def test_batch_take_grad():
    x = mx.nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    x.attach_grad()
    idx = mx.nd.array([0, 2, 1, 0])
    with mx.autograd.record():
        y = mx.nd.batch_take(x, idx)
        (y * y).sum().backward()
    g = x.grad.asnumpy()
    want = np.zeros((4, 3), "float32")
    for r, c in enumerate([0, 2, 1, 0]):
        want[r, c] = 2 * x.asnumpy()[r, c]
    np.testing.assert_allclose(g, want, rtol=1e-5)


# -------------------------------------------------------- gather/scatter
def test_gather_nd_grad_accumulates():
    x = mx.nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    x.attach_grad()
    idx = mx.nd.array([[0, 0, 2], [1, 1, 3]])   # picks (0,1),(0,1),(2,3)
    with mx.autograd.record():
        y = mx.nd.gather_nd(x, idx)
        y.sum().backward()
    want = np.zeros((3, 4), "float32")
    want[0, 1] = 2.0
    want[2, 3] = 1.0
    np.testing.assert_array_equal(x.grad.asnumpy(), want)


def test_scatter_nd_forward_and_grad():
    # NOTE: duplicate indices are explicitly UNDEFINED for scatter_nd
    # (reference indexing_op.cc:889 "the gradient ... will not be
    # correct") — contract covers distinct targets only
    data = mx.nd.array([9.0, 8.0, 7.0])
    data.attach_grad()
    idx = mx.nd.array([[0, 3, 2]])
    with mx.autograd.record():
        y = mx.nd.scatter_nd(data, idx, shape=(4,))
        (y * mx.nd.arange(4)).sum().backward()
    np.testing.assert_array_equal(y.asnumpy(), [9.0, 0.0, 7.0, 8.0])
    np.testing.assert_array_equal(data.grad.asnumpy(), [0.0, 3.0, 2.0])


# ------------------------------------------------------------------- pick
@pytest.mark.parametrize("keepdims", [False, True])
def test_pick_grad(keepdims):
    x = mx.nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    x.attach_grad()
    idx = mx.nd.array([0, 2, 1, 1])
    with mx.autograd.record():
        y = mx.nd.pick(x, idx, axis=1, keepdims=keepdims)
        (y * y).sum().backward()
    want = np.zeros((4, 3), "float32")
    for r, c in enumerate([0, 2, 1, 1]):
        want[r, c] = 2 * x.asnumpy()[r, c]
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


# --------------------------------------------------------------- ordering
def test_sort_grad_routes_through_permutation():
    xv = np.asarray([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], "float32")
    x = mx.nd.array(xv)
    x.attach_grad()
    w = np.asarray([[1.0, 10.0, 100.0], [1.0, 10.0, 100.0]], "float32")
    with mx.autograd.record():
        y = mx.nd.sort(x, axis=1)
        (y * mx.nd.array(w)).sum().backward()
    # grad lands where each sorted element CAME from
    want = np.zeros_like(xv)
    for r in range(2):
        order = np.argsort(xv[r])
        for j, src in enumerate(order):
            want[r, src] = w[r, j]
    np.testing.assert_array_equal(x.grad.asnumpy(), want)


def test_topk_value_grad():
    xv = np.asarray([[3.0, 1.0, 2.0, 5.0]], "float32")
    x = mx.nd.array(xv)
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.topk(x, k=2, ret_typ="value", axis=1)
        y.sum().backward()
    want = np.asarray([[1.0, 0.0, 0.0, 1.0]], "float32")
    np.testing.assert_array_equal(x.grad.asnumpy(), want)


def test_argsort_matches_numpy_and_topk_indices():
    rng = np.random.RandomState(3)
    xv = rng.randn(4, 7).astype("float32")
    a = mx.nd.argsort(mx.nd.array(xv), axis=1).asnumpy()
    np.testing.assert_array_equal(a, np.argsort(xv, axis=1))
    t = mx.nd.topk(mx.nd.array(xv), k=3, axis=1).asnumpy()
    np.testing.assert_array_equal(t, np.argsort(-xv, axis=1)[:, :3])


# ------------------------------------------------------- layout movement
@pytest.mark.parametrize("op,kw", [
    ("repeat", {"repeats": 3}),
    ("repeat", {"repeats": 2, "axis": 1}),
    ("reverse", {"axis": 1}),
    ("tile", {"reps": (2, 3)}),
    ("swapaxes", {"dim1": 0, "dim2": 1}),
    ("flip", {"axis": 0}),
], ids=["repeat_flat", "repeat_ax1", "reverse", "tile", "swapaxes", "flip"])
def test_movement_grads(op, kw):
    data = mx.sym.Variable("data")
    sym = getattr(mx.sym, op)(data, **kw)
    _grad_check(sym, {"data": _rand(3, 4, seed=5)})


def test_where_grad_masks_branches():
    cond = mx.nd.array([[1.0, 0.0], [0.0, 1.0]])
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[10.0, 20.0], [30.0, 40.0]])
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        y = mx.nd.where(cond, a, b)
        (y * y).sum().backward()
    np.testing.assert_allclose(a.grad.asnumpy(),
                               [[2.0, 0.0], [0.0, 8.0]], rtol=1e-6)
    np.testing.assert_allclose(b.grad.asnumpy(),
                               [[0.0, 40.0], [60.0, 0.0]], rtol=1e-6)


# ------------------------------------------------------------------ dots
@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_dot_transpose_grads(ta, tb):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (4, 3) if ta else (3, 4)
    sb = (5, 4) if tb else (4, 5)
    _grad_check(sym, {"a": _rand(*sa, seed=6), "b": _rand(*sb, seed=7)})


@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True)])
def test_batch_dot_transpose_grads(ta, tb):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.batch_dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (2, 4, 3) if ta else (2, 3, 4)
    sb = (2, 5, 4) if tb else (2, 4, 5)
    _grad_check(sym, {"a": _rand(*sa, seed=8), "b": _rand(*sb, seed=9)})


# ---------------------------------------------------------- shape-likes
def test_broadcast_like_grad_reduces():
    a = mx.nd.array(np.ones((1, 3), "float32"))
    ref = mx.nd.zeros((4, 3))
    a.attach_grad()
    with mx.autograd.record():
        y = mx.nd.broadcast_like(a, ref)
        y.sum().backward()
    np.testing.assert_array_equal(a.grad.asnumpy(), [[4.0, 4.0, 4.0]])


@pytest.mark.parametrize("op,kw,shape", [
    ("expand_dims", {"axis": 1}, (3, 4)),
    ("squeeze", {"axis": 0}, (1, 3, 4)),
    ("reshape", {"shape": (4, 3)}, (3, 4)),
    ("reshape", {"shape": (0, -1)}, (3, 2, 2)),
], ids=["expand", "squeeze", "reshape", "reshape_special"])
def test_shape_op_grads(op, kw, shape):
    data = mx.sym.Variable("data")
    sym = getattr(mx.sym, op)(data, **kw)
    _grad_check(sym, {"data": _rand(*shape, seed=10)})


def test_clip_grad_zero_outside_range():
    x = mx.nd.array([-2.0, -0.5, 0.5, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.clip(x, -1.0, 1.0)
        (y * mx.nd.array([1.0, 2.0, 3.0, 4.0])).sum().backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), [0.0, 2.0, 3.0, 0.0])


def test_maximum_tie_gradient_split():
    """At exact ties the reference sends the full cotangent to the LHS
    (mshadow_op ge); pin that convention."""
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([1.0, 1.0])
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        y = mx.nd.broadcast_maximum(a, b)
        y.sum().backward()
    np.testing.assert_array_equal(a.grad.asnumpy(), [1.0, 1.0])
    np.testing.assert_array_equal(b.grad.asnumpy(), [0.0, 0.0])
