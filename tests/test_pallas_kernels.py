"""Pallas flash attention kernel vs the full-materialization reference
(interpret mode on the CPU mesh; the same kernel compiles for real on TPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.pallas_kernels import flash_attention, _reference


def _qkv(b=2, h=2, t=256, d=64, seed=0, dtype="float32"):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, None, 128, 128, True)
    ref = _reference(q, k, v, causal, 1.0 / np.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_flash_causal_padded_seq():
    """T not divisible by the block: causal path pads and slices back."""
    q, k, v = _qkv(t=200)
    out = flash_attention(q, k, v, True, None, 128, 128, True)
    ref = _reference(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))
    assert out.shape == (2, 2, 200, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_flash_gradients():
    q, k, v = _qkv(b=1, h=1, t=128, d=64)

    def loss_k(q, k, v):
        return flash_attention(q, k, v, True, None, 128, 128, True).sum()

    def loss_r(q, k, v):
        return _reference(q, k, v, True, 1.0 / np.sqrt(64)).sum()

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4)


def test_flash_nd_contrib_surface():
    q, k, v = _qkv(b=1, h=1, t=128, d=64)
    out = mx.nd.contrib.flash_attention(mx.nd.array(np.asarray(q)),
                                        mx.nd.array(np.asarray(k)),
                                        mx.nd.array(np.asarray(v)))
    assert out.shape == (1, 1, 128, 64)
    assert np.isfinite(out.asnumpy()).all()
