"""Symbol API tests (reference ``tests/python/unittest/test_symbol.py``)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_symbol_compose():
    data = mx.sym.Variable("data")
    net1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    net1 = mx.sym.FullyConnected(net1, name="fc2", num_hidden=100)
    assert net1.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                     "fc2_weight", "fc2_bias"]
    assert net1.list_outputs() == ["fc2_output"]


def test_symbol_internals():
    data = mx.sym.Variable("data")
    oldfc = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    net1 = mx.sym.FullyConnected(oldfc, name="fc2", num_hidden=100)
    internals = net1.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == oldfc.list_arguments()


def test_symbol_children():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    children = net.get_children()
    assert children.list_outputs() == ["data", "fc1_weight", "fc1_bias"]


def test_symbol_infer_shape():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(100, 50))
    assert dict(zip(net.list_arguments(), arg_shapes)) == {
        "data": (100, 50), "fc1_weight": (10, 50), "fc1_bias": (10,)}
    assert out_shapes == [(100, 10)]


def test_symbol_json_roundtrip():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, name="act", act_type="relu")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    js = net.tojson()
    parsed = json.loads(js)
    assert "nodes" in parsed and "heads" in parsed
    back = mx.sym.load_json(js)
    assert back.list_arguments() == net.list_arguments()
    assert back.list_outputs() == net.list_outputs()
    # numerics survive the round trip
    rng = np.random.RandomState(0)
    feed = {"data": rng.rand(3, 8).astype("float32"),
            "fc1_weight": rng.rand(16, 8).astype("float32"),
            "fc1_bias": np.zeros(16, "float32"),
            "softmax_label": np.zeros(3, "float32")}
    def run(sym):
        exe = sym.simple_bind(ctx=mx.cpu(), grad_req="null",
                              data=(3, 8), softmax_label=(3,))
        for k, v in feed.items():
            exe.arg_dict[k][:] = v
        return exe.forward()[0].asnumpy()
    np.testing.assert_allclose(run(net), run(back), rtol=1e-6)


def test_symbol_group():
    data = mx.sym.Variable("data")
    a = mx.sym.FullyConnected(data, name="fca", num_hidden=4)
    b = mx.sym.Activation(data, name="actb", act_type="tanh")
    grouped = mx.sym.Group([a, b])
    assert grouped.list_outputs() == ["fca_output", "actb_output"]
    exe = grouped.simple_bind(ctx=mx.cpu(), grad_req="null", data=(2, 3))
    outs = exe.forward()
    assert outs[0].shape == (2, 4) and outs[1].shape == (2, 3)


def test_symbol_attr():
    data = mx.sym.Variable("data", attr={"mood": "angry"})
    op = mx.sym.Convolution(data=data, name="conv", kernel=(1, 1),
                            num_filter=1, attr={"__mood__": "so so"})
    assert data.attr("mood") == "angry"
    assert op.attr("__mood__") == "so so"


def test_symbol_attr_scope():
    with mx.AttrScope(__group__="4", __data__="great"):
        data = mx.sym.Variable("data", attr={"specific": "data"})
    assert data.attr("specific") == "data"
    assert data.attr("__group__") == "4"


def test_symbol_eval():
    a = mx.sym.Variable("a")
    b = a + 2
    outs = b.eval(ctx=mx.cpu(), a=mx.nd.ones((2, 2)))
    np.testing.assert_array_equal(outs[0].asnumpy(), np.full((2, 2), 3.0))


def test_symbol_arith_and_pow():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a * 2 + b ** 2 - 3) / 2
    exe = c.simple_bind(ctx=mx.cpu(), grad_req="null", a=(2,), b=(2,))
    exe.arg_dict["a"][:] = np.array([1.0, 2.0])
    exe.arg_dict["b"][:] = np.array([3.0, 4.0])
    np.testing.assert_allclose(exe.forward()[0].asnumpy(),
                               ((np.array([1, 2]) * 2 +
                                 np.array([3, 4]) ** 2) - 3) / 2)


def test_symbol_save_load(tmp_path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc",
                                num_hidden=4)
    path = str(tmp_path / "sym.json")
    net.save(path)
    assert os.path.exists(path)
    back = mx.sym.load(path)
    assert back.list_arguments() == net.list_arguments()


def test_symbol_grad_via_bind():
    x = mx.sym.Variable("x")
    y = mx.sym.sum(x * x)
    exe = y.simple_bind(ctx=mx.cpu(), grad_req="write", x=(3,))
    exe.arg_dict["x"][:] = np.array([1.0, 2.0, 3.0])
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), [2, 4, 6])
