"""Aggregated (multi-tensor) optimizer update path (ISSUE 2 tentpole):
numerics parity with the per-parameter path, grouping/fallback rules,
state serialization compatibility, zero steady-state compile misses, and
the trainer/kvstore wiring."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, telemetry
from mxnet_tpu import optimizer as opt
from mxnet_tpu.optimizer import aggregate


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


SHAPES = [(4, 3), (7,), (2, 3, 2), (5, 5)]


def _updater_pair(name, **kwargs):
    """(per-param updater, aggregated updater) over the same config."""
    o1 = opt.create(name, **kwargs)
    o1.aggregate_num = 1            # forces the per-parameter path
    o2 = opt.create(name, **kwargs)
    assert o2.aggregate_num > 1     # default-on (env MXNET_OPTIMIZER_...)
    return opt.get_updater(o1), opt.get_updater(o2)


def _run_steps(updater, w_np, g_np, steps=3, dtype="float32"):
    ws = [nd.array(w.copy(), dtype=dtype) for w in w_np]
    idx = list(range(len(ws)))
    for _ in range(steps):
        gs = [nd.array(g.copy(), dtype=dtype) for g in g_np]
        updater(idx, gs, ws)
    return ws


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "clip_gradient": 0.1}),
    ("adam", {"learning_rate": 0.01, "wd": 0.001}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("signum", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adagrad", {"learning_rate": 0.1, "wd": 0.01}),
    ("adamax", {"learning_rate": 0.002, "wd": 0.01}),
    ("adamax", {"learning_rate": 0.002, "clip_gradient": 0.1}),
    ("nadam", {"learning_rate": 0.001, "wd": 0.01}),
    ("nadam", {"learning_rate": 0.001, "clip_gradient": 0.1,
               "schedule_decay": 0.01}),
    ("ftml", {"learning_rate": 0.01, "wd": 0.01}),
    ("ftml", {"learning_rate": 0.01, "clip_gradient": 0.1, "beta1": 0.7}),
    ("ftrl", {"learning_rate": 0.1, "wd": 0.01, "lamda1": 0.02}),
    ("ftrl", {"learning_rate": 0.1, "clip_gradient": 0.1, "beta": 0.5}),
])
def test_aggregated_matches_per_param(name, kwargs):
    np.random.seed(0)
    w_np = [np.random.rand(*s).astype(np.float32) for s in SHAPES]
    g_np = [(np.random.rand(*s).astype(np.float32) - 0.5) for s in SHAPES]
    u1, u2 = _updater_pair(name, **kwargs)
    ws1 = _run_steps(u1, w_np, g_np)
    ws2 = _run_steps(u2, w_np, g_np)
    # FTML's z update (b1*z + (1-b1)*g - sigma*w) cancels catastrophically,
    # amplifying the ulp-level rounding drift between the per-param op's
    # baked f64 python constants and the group's traced f32 scalars; every
    # other rule sits inside the tight tolerance
    rtol, atol = (2e-4, 1e-5) if name == "ftml" else (1e-5, 1e-6)
    for a, b in zip(ws1, ws2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=rtol, atol=atol)
    # optimizer state (momentum/mean/var/...) matches too
    for i in u1.states:
        l1 = aggregate._state_leaves(u1.states[i])
        l2 = aggregate._state_leaves(u2.states[i])
        assert len(l1) == len(l2)
        for s1, s2 in zip(l1, l2):
            np.testing.assert_allclose(s1.asnumpy(), s2.asnumpy(),
                                       rtol=rtol, atol=atol)


def test_nadam_m_schedule_tracks_per_param():
    """Nadam's host-side momentum schedule is mutated once per parameter
    per update on the per-param path; the aggregated extras hook must
    replicate the recurrence exactly (ISSUE 5 satellite)."""
    np.random.seed(3)
    w_np = [np.random.rand(*s).astype(np.float32) for s in SHAPES]
    g_np = [(np.random.rand(*s).astype(np.float32) - 0.5) for s in SHAPES]
    o1 = opt.create("nadam", learning_rate=0.001)
    o1.aggregate_num = 1
    o2 = opt.create("nadam", learning_rate=0.001)
    u1, u2 = opt.get_updater(o1), opt.get_updater(o2)
    ws1 = _run_steps(u1, w_np, g_np, steps=4)
    ws2 = _run_steps(u2, w_np, g_np, steps=4)
    np.testing.assert_allclose(o1.m_schedule, o2.m_schedule, rtol=1e-12)
    for a, b in zip(ws1, ws2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_nadam_mixed_precision_takes_per_param_path():
    """Nadam's m_schedule snapshots are processing-ORDER-sensitive: mixed
    fp16(mp)+fp32 params split into two groups, which would permute the
    per-param index order (members 1 and 2 would swap schedule prefixes).
    The order_sensitive guard must route the whole update per-param, so
    results match the reference exactly."""
    np.random.seed(5)
    shapes = [(4, 3), (7,), (2, 3, 2), (5, 5)]
    dtypes = ["float32", "float16", "float32", "float16"]
    w_np = [np.random.rand(*s).astype(d) for s, d in zip(shapes, dtypes)]
    g_np = [(np.random.rand(*s).astype(d) - np.asarray(0.5, d))
            for s, d in zip(shapes, dtypes)]

    def run(agg):
        o = opt.create("nadam", learning_rate=0.001, multi_precision=True)
        o.aggregate_num = 64 if agg else 1
        u = opt.get_updater(o)
        ws = [nd.array(w.copy(), dtype=w.dtype) for w in w_np]
        idx = list(range(len(ws)))
        for _ in range(3):
            gs = [nd.array(g.copy(), dtype=g.dtype) for g in g_np]
            u(idx, gs, ws)
        return o, ws

    telemetry.enable()
    o1, ws1 = run(False)
    o2, ws2 = run(True)
    assert o1.m_schedule == o2.m_schedule
    for a, b in zip(ws1, ws2):
        assert np.array_equal(a.asnumpy(), b.asnumpy())
    # the guard shows up in telemetry: every member counted as fallback
    assert telemetry.counter_value("optimizer.fallback_params") \
        >= len(shapes)


def test_ftml_t_rides_in_extras_not_recompiles():
    """FTML's per-param op bakes the step count t into its attrs (one jit
    entry per t value); the aggregated rule must hand the bias corrections
    over as traced extras, so 5 steps + an lr change compile exactly once
    (ISSUE 6 satellite)."""
    aggregate.clear_cache()
    telemetry.reset()
    telemetry.enable()
    o = opt.create("ftml", learning_rate=0.01)
    ws = [nd.array(np.ones(s, np.float32)) for s in SHAPES]
    gs = [nd.array(np.full(s, 0.1, np.float32)) for s in SHAPES]
    u = opt.get_updater(o)
    idx = list(range(len(ws)))
    for step in range(5):
        if step == 3:
            o.set_learning_rate(0.005)
        u(idx, gs, ws)
    assert telemetry.counter_value("optimizer.compile_misses") == 1
    assert telemetry.counter_value("optimizer.fallback_params") == 0


def test_adamax_nadam_zero_steady_state_misses():
    """Both new rules ride the compiled-group cache: step 1 compiles,
    later steps (and lr changes) add zero compile misses."""
    for name in ("adamax", "nadam", "ftml", "ftrl"):
        aggregate.clear_cache()   # group sigs may be warm from other tests
        telemetry.reset()
        telemetry.enable()
        o = opt.create(name)
        ws = [nd.array(np.ones(s, np.float32)) for s in SHAPES]
        gs = [nd.array(np.ones(s, np.float32)) for s in SHAPES]
        u = opt.get_updater(o)
        idx = list(range(len(ws)))
        u(idx, gs, ws)
        misses = telemetry.counter_value("optimizer.compile_misses")
        assert misses >= 1, name
        for _ in range(3):
            u(idx, gs, ws)
        o.set_learning_rate(0.5)
        u(idx, gs, ws)
        assert telemetry.counter_value("optimizer.compile_misses") \
            == misses, name
        assert telemetry.counter_value("optimizer.fallback_params") == 0, \
            name


def test_multi_precision_fp16_master_path():
    """fp16 weights + multi_precision: the aggregated path keeps the fp32
    master in the state tuple and casts back, exactly like the generic
    per-param wrap."""
    np.random.seed(1)
    w_np = [np.random.rand(*s).astype(np.float16) for s in SHAPES[:3]]
    g_np = [(np.random.rand(*s).astype(np.float16) - 0.5)
            for s in SHAPES[:3]]
    u1, u2 = _updater_pair("sgd", learning_rate=0.1, momentum=0.9,
                           wd=0.01, multi_precision=True)
    ws1 = _run_steps(u1, w_np, g_np, dtype="float16")
    ws2 = _run_steps(u2, w_np, g_np, dtype="float16")
    for a, b in zip(ws1, ws2):
        assert a.dtype == np.float16 and b.dtype == np.float16
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-3, atol=1e-3)
    # fp32 masters agree to fp32 tolerance
    for i in u1.states:
        m1, m2 = u1.states[i][0], u2.states[i][0]
        assert m1.dtype == np.float32 and m2.dtype == np.float32
        np.testing.assert_allclose(m1.asnumpy(), m2.asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_bare_fp16_falls_back():
    """fp16 without multi_precision keeps the (warning) per-param path."""
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    w = [nd.array(np.ones((3,), np.float16), dtype="float16")
         for _ in range(2)]
    g = [nd.array(np.ones((3,), np.float16), dtype="float16")
         for _ in range(2)]
    telemetry.enable()
    with pytest.warns(UserWarning):
        u = opt.get_updater(o)
        u([0, 1], g, w)
    snap = telemetry.snapshot()
    assert snap["counters"].get("optimizer.fallback_params", 0) == 2
    assert snap["counters"].get("optimizer.aggregated_params", 0) == 0


def test_unsupported_optimizer_falls_back():
    """No registered rule (e.g. AdaDelta) → per-param updates, same math."""
    np.random.seed(2)
    w_np = [np.random.rand(4, 3).astype(np.float32) for _ in range(3)]
    g_np = [np.random.rand(4, 3).astype(np.float32) for _ in range(3)]
    u1, u2 = _updater_pair("adadelta")
    ws1 = _run_steps(u1, w_np, g_np)
    ws2 = _run_steps(u2, w_np, g_np)
    for a, b in zip(ws1, ws2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6)


def test_subclass_is_not_aggregated():
    """A user subclass may override update(); exact-class match only."""

    class MySGD(opt.SGD):
        def _update_impl(self, index, weight, grad, state,
                         multi_precision=False):
            weight[:] = weight - 1.0    # nothing like SGD on purpose

    telemetry.enable()
    o = MySGD(learning_rate=0.1)
    u = opt.get_updater(o)
    ws = [nd.array(np.zeros((3,), np.float32)) for _ in range(2)]
    gs = [nd.array(np.zeros((3,), np.float32)) for _ in range(2)]
    u([0, 1], gs, ws)
    for w in ws:
        np.testing.assert_allclose(w.asnumpy(), -np.ones(3))
    assert telemetry.counter_value("optimizer.aggregated_params") == 0
    assert telemetry.counter_value("optimizer.fallback_params") == 2


def test_aggregation_size_chunks_groups():
    """MXNET_OPTIMIZER_AGGREGATION_SIZE caps tensors per dispatch."""
    telemetry.enable()
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    o.aggregate_num = 4
    n = 10
    ws = [nd.array(np.ones((3,), np.float32)) for _ in range(n)]
    gs = [nd.array(np.ones((3,), np.float32)) for _ in range(n)]
    c0 = telemetry.counter_value("optimizer.update_calls")
    u = opt.get_updater(o)
    u(list(range(n)), gs, ws)
    # 10 same-shape tensors, cap 4 -> ceil(10/4) = 3 dispatches
    assert telemetry.counter_value("optimizer.update_calls") - c0 == 3


def test_sparse_grad_falls_back():
    """Compressed row-sparse grads keep the O(nnz) lazy per-param kernels."""
    from mxnet_tpu.ndarray import sparse as sp
    telemetry.enable()
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    dense_w = nd.array(np.ones((4, 3), np.float32))
    sparse_w = nd.array(np.ones((6, 3), np.float32))
    rs = sp.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([1, 4])), shape=(6, 3))
    u = opt.get_updater(o)
    u([0, 1], [nd.array(np.ones((4, 3), np.float32)), rs],
      [dense_w, sparse_w])
    snap = telemetry.snapshot()
    assert snap["counters"].get("optimizer.fallback_params", 0) == 1
    assert snap["counters"].get("optimizer.aggregated_params", 0) == 1
    # the sparse fallback updated the touched rows and only those
    out = sparse_w.asnumpy()
    assert not np.allclose(out[1], 1.0)
    assert np.allclose(out[0], 1.0)
    # the dense member went through the aggregated path
    assert not np.allclose(dense_w.asnumpy(), 1.0)


def test_zero_compile_misses_steady_state():
    """After the first step compiles each group, later steps replay the
    cached executable: the group-signature compile-miss counter freezes
    (ISSUE 2 acceptance: zero recompiles after step 1)."""
    telemetry.enable()
    o = opt.Adam(learning_rate=0.01)
    ws = [nd.array(np.ones(s, np.float32)) for s in SHAPES]
    gs = [nd.array(np.ones(s, np.float32)) for s in SHAPES]
    u = opt.get_updater(o)
    idx = list(range(len(ws)))
    u(idx, gs, ws)
    misses_after_1 = telemetry.counter_value("optimizer.compile_misses")
    for _ in range(4):
        u(idx, gs, ws)
    assert telemetry.counter_value("optimizer.compile_misses") \
        == misses_after_1
    snap = telemetry.snapshot()
    assert snap["gauges"]["optimizer.update_groups"] >= 1
    assert snap["gauges"]["optimizer.state_bytes"] > 0
    # lr changes are traced, not baked: no recompile either
    o.set_learning_rate(0.5)
    u(idx, gs, ws)
    assert telemetry.counter_value("optimizer.compile_misses") \
        == misses_after_1


def test_group_update_spans_inside_trainer_update():
    """trainer.update gets optimizer.update_group sub-spans per group."""
    telemetry.enable()
    x = gluon.Parameter("x", shape=(4,))
    y = gluon.Parameter("y", shape=(2, 2))
    for p in (x, y):
        p.initialize(init="zeros")
    trainer = gluon.Trainer([x, y], "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        (x.data().sum() + y.data().sum()).backward()
    trainer.step(1)
    spans = telemetry.span_aggregates()
    assert "trainer.update" in spans
    assert "optimizer.update_group" in spans
    names = [e[1] for e in telemetry.bus.events()]
    assert "optimizer.update_group" in names


def _make_trainer(agg):
    net_x = gluon.Parameter("w", shape=(6, 4))
    net_x.initialize(init="ones")
    trainer = gluon.Trainer([net_x], "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "wd": 0.01})
    if not agg:
        trainer._optimizer.aggregate_num = 1
    return net_x, trainer


def _step(p, trainer):
    with mx.autograd.record():
        ((p.data() * 1.5) ** 2).sum().backward()
    trainer.step(1)


def test_trainer_save_load_states_cross_path(tmp_path):
    """States saved by the aggregated updater load into a per-param
    trainer (and vice versa) and continue the identical trajectory —
    the ser/de format is path-independent."""
    pa, ta = _make_trainer(agg=True)
    pp, tp = _make_trainer(agg=False)
    for _ in range(3):
        _step(pa, ta)
        _step(pp, tp)
    np.testing.assert_allclose(pa.data().asnumpy(), pp.data().asnumpy(),
                               rtol=1e-5, atol=1e-6)
    fa = str(tmp_path / "agg.states")
    fp = str(tmp_path / "pp.states")
    ta.save_states(fa)
    tp.save_states(fp)

    # structural equality of the serialized states
    import pickle
    sa = pickle.loads(open(fa, "rb").read())[0]
    sp_ = pickle.loads(open(fp, "rb").read())[0]
    assert sorted(sa) == sorted(sp_)
    for k in sa:
        assert type(sa[k]) is type(sp_[k])
        np.testing.assert_allclose(sa[k].asnumpy(), sp_[k].asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    # cross-load: aggregated states into the per-param trainer and
    # per-param states into the aggregated trainer; trajectories converge
    tp.load_states(fa)
    ta.load_states(fp)
    for _ in range(2):
        _step(pa, ta)
        _step(pp, tp)
    np.testing.assert_allclose(pa.data().asnumpy(), pp.data().asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_trainer_aggregated_matches_per_param_trajectory():
    pa, ta = _make_trainer(agg=True)
    pp, tp = _make_trainer(agg=False)
    for _ in range(5):
        _step(pa, ta)
        _step(pp, tp)
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pp.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_kvstore_batched_push_aggregates():
    """A multi-key push with a server-side optimizer takes ONE aggregated
    dispatch (the kvstore _updater wiring)."""
    telemetry.enable()
    kv = mx.kv.create("local")
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    kv.set_optimizer(o)
    n = 6
    for i in range(n):
        kv.init(i, nd.array(np.ones((3, 2), np.float32)))
    c0 = telemetry.counter_value("optimizer.update_calls")
    kv.push(list(range(n)),
            [nd.array(np.ones((3, 2), np.float32)) for _ in range(n)])
    assert telemetry.counter_value("optimizer.update_calls") - c0 == 1
    out = nd.array(np.zeros((3, 2), np.float32))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.1, rtol=1e-6)


def test_kvstore_custom_updater_keeps_per_key_contract():
    """set_updater with a plain function: one call per key, unchanged."""
    calls = []
    kv = mx.kv.create("local")
    for i in range(3):
        kv.init(i, nd.array(np.zeros((2,), np.float32)))
    kv.set_updater(lambda k, recv, stored: calls.append(k))
    kv.push([0, 1, 2],
            [nd.array(np.ones((2,), np.float32)) for _ in range(3)])
    assert calls == [0, 1, 2]


def test_module_update_uses_aggregated_path():
    telemetry.enable()
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc1")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (5, 6))],
             label_shapes=[("softmax_label", (5,))])
    mod.init_params()
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    batch = mx.io.DataBatch(
        data=[nd.array(np.random.rand(5, 6).astype("float32"))],
        label=[nd.array(np.zeros(5, "float32"))])
    c0 = telemetry.counter_value("optimizer.update_calls")
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    # 4 param tensors (2x weight+bias) -> grouped dispatches, not 4
    delta = telemetry.counter_value("optimizer.update_calls") - c0
    assert 1 <= delta < 4
    assert telemetry.counter_value("optimizer.aggregated_params") == 4


def test_checkpoint_spans_for_trainer_states(tmp_path):
    """checkpoint.save / checkpoint.restore spans carry bytes and the
    serialize-vs-IO split (ISSUE 2 satellite)."""
    telemetry.enable()
    p, tr = _make_trainer(agg=True)
    _step(p, tr)
    f = str(tmp_path / "t.states")
    tr.save_states(f)
    tr.load_states(f)
    spans = telemetry.span_aggregates()
    for name in ("checkpoint.save", "checkpoint.restore",
                 "checkpoint.serialize", "checkpoint.io",
                 "checkpoint.deserialize"):
        assert name in spans, (name, sorted(spans))
    evs = {e[1]: e for e in telemetry.bus.events()}
    import os
    assert evs["checkpoint.save"][6]["bytes_written"] \
        == os.path.getsize(f)
    assert evs["checkpoint.restore"][6]["bytes_read"] \
        == os.path.getsize(f)


def test_aggregate_disabled_by_env_value_one():
    """aggregate_num <= 1 (MXNET_OPTIMIZER_AGGREGATION_SIZE=1) disables
    grouping entirely."""
    telemetry.enable()
    o = opt.SGD(learning_rate=0.1)
    o.aggregate_num = 1
    u = opt.get_updater(o)
    assert not u.aggregate_updates
    ws = [nd.array(np.ones((2,), np.float32)) for _ in range(3)]
    gs = [nd.array(np.ones((2,), np.float32)) for _ in range(3)]
    u([0, 1, 2], gs, ws)
    assert telemetry.counter_value("optimizer.aggregated_params") == 0
    for w in ws:
        np.testing.assert_allclose(w.asnumpy(), 0.9, rtol=1e-6)


def test_clip_gradient_zero_is_a_noop_like_per_param():
    """clip_gradient=0.0 (or negative) never clips on the per-param path
    (truthiness / >0 gates) — the aggregated path must match, not clamp
    every gradient to zero."""
    for clip in (0.0, -1.0):
        w_np = [np.full((3,), 1.0, np.float32) for _ in range(2)]
        g_np = [np.full((3,), 0.5, np.float32) for _ in range(2)]
        u1, u2 = _updater_pair("sgd", learning_rate=0.1, momentum=0.9,
                               clip_gradient=clip)
        ws1 = _run_steps(u1, w_np, g_np, steps=2)
        ws2 = _run_steps(u2, w_np, g_np, steps=2)
        for a, b in zip(ws1, ws2):
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                       rtol=1e-6)
            assert not np.allclose(b.asnumpy(), 1.0), \
                "clip_gradient=%r froze the weights" % clip


def test_mixed_device_params_group_per_device():
    """Parameters living on different devices must not fuse into one jit
    call (committed-device conflict); each device gets its own group."""
    import jax
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("needs the virtual multi-device CPU mesh")
    telemetry.enable()
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    ws, gs = [], []
    for i in range(4):
        dev = devs[i % 2]
        ws.append(mx.nd.NDArray(jax.device_put(
            np.ones((3,), np.float32), dev)))
        gs.append(mx.nd.NDArray(jax.device_put(
            np.full((3,), 0.5, np.float32), dev)))
    u = opt.get_updater(o)
    c0 = telemetry.counter_value("optimizer.update_calls")
    u([0, 1, 2, 3], gs, ws)
    # 2 devices -> 2 groups, both aggregated
    assert telemetry.counter_value("optimizer.update_calls") - c0 == 2
    assert telemetry.counter_value("optimizer.aggregated_params") == 4
    for w in ws:
        np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.05, rtol=1e-6)


def test_updater_aggregate_updates_is_assignable():
    """Reference parity: `updater.aggregate_updates = False` disables the
    batched path without touching the optimizer."""
    o = opt.SGD(learning_rate=0.1)
    u = opt.get_updater(o)
    assert u.aggregate_updates
    u.aggregate_updates = False
    assert not u.aggregate_updates
    telemetry.enable()
    ws = [nd.array(np.ones((2,), np.float32)) for _ in range(3)]
    gs = [nd.array(np.ones((2,), np.float32)) for _ in range(3)]
    u([0, 1, 2], gs, ws)
    assert telemetry.counter_value("optimizer.aggregated_params") == 0
    for w in ws:
        np.testing.assert_allclose(w.asnumpy(), 0.9, rtol=1e-6)
    u.aggregate_updates = True
    u([0, 1, 2], gs, ws)
    assert telemetry.counter_value("optimizer.aggregated_params") == 3
