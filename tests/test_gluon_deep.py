"""Gluon deep-case tranche (VERDICT r4 item 7) — ports the remaining
``tests/python/unittest/test_gluon.py`` families: deferred-init corner
cases, hybridize cache invalidation, SymbolBlock round-trips, shared
parameters, grad_req='add', save/load with architecture edits, dtype
casts, hooks, and grad-graph changes.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


# ------------------------------------------------------------ deferred init
def test_deferred_init_basic():
    x = mx.nd.ones((5, 4, 10, 10))
    layer = nn.Conv2D(10, 2)
    layer.collect_params().initialize()
    out = layer(x)
    assert layer.weight.shape == (10, 4, 2, 2)
    assert out.shape == (5, 10, 9, 9)


def test_fill_shape_deferred_through_chain():
    """Shapes propagate through Conv→BN→Dense on first forward
    (reference test_fill_shape_deferred)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(64, kernel_size=2, padding=1),
                nn.BatchNorm(),
                nn.Dense(10))
    net.hybridize()
    net.initialize()
    net(mx.nd.ones((2, 3, 5, 7)))
    assert net[0].weight.shape[1] == 3, net[0].weight.shape
    assert net[1].gamma.shape[0] == 64, net[1].gamma.shape
    assert net[2].weight.shape[1] == 64 * 6 * 8, net[2].weight.shape


def test_fill_shape_load(tmp_path):
    """Deferred shapes also fill from loaded parameters (reference
    test_fill_shape_load)."""
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(64, kernel_size=2, padding=1),
                    nn.BatchNorm(),
                    nn.Dense(10))
        net.hybridize()
        return net

    net1 = build()
    net1.initialize()
    net1(mx.nd.ones((2, 3, 5, 7)))
    f = str(tmp_path / "net_fill.params")
    net1.save_parameters(f)

    net2 = build()
    net2.load_parameters(f)
    assert net2[0].weight.shape[1] == 3
    assert net2[1].gamma.shape[0] == 64
    assert net2[2].weight.shape[1] == 64 * 6 * 8
    # and it runs + agrees with net1
    x = mx.nd.random.uniform(shape=(2, 3, 5, 7))
    np.testing.assert_allclose(net2(x).asnumpy(), net1(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_deferred_init_error_is_actionable():
    layer = nn.Dense(10)
    layer.initialize()
    with pytest.raises(Exception) as e:
        layer.weight.data()            # not yet shaped: must fail loudly
    assert "init" in str(e.value).lower() or "shape" in str(e.value).lower()


# --------------------------------------------------- hybridize cache rules
def test_hybrid_stale_cache_add_layer():
    """Adding a child AFTER hybridize+run must invalidate the cached
    graph (reference test_hybrid_stale_cache)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(10, weight_initializer="zeros",
                         bias_initializer="ones", flatten=False))
    net.hybridize()
    net.initialize()
    assert net(mx.nd.ones((2, 3, 5))).shape == (2, 3, 10)
    net.add(nn.Flatten())
    assert net(mx.nd.ones((2, 3, 5))).shape == (2, 30)


def test_hybrid_stale_cache_replace_attr():
    net = nn.HybridSequential()
    with net.name_scope():
        net.fc1 = nn.Dense(10, weight_initializer="zeros",
                           bias_initializer="ones", flatten=False)
        net.fc2 = nn.Dense(10, weight_initializer="zeros",
                           bias_initializer="ones", flatten=False)
    net.hybridize()
    net.initialize()
    net(mx.nd.ones((2, 3, 5)))
    net.fc2 = nn.Dense(10, weight_initializer="zeros",
                       bias_initializer="ones", flatten=True)
    net.initialize()
    assert net(mx.nd.ones((2, 3, 5))).shape == (2, 10)


def test_hybrid_cache_invalidation_on_reshape():
    """A hybridized net re-traces when the input shape changes instead of
    reusing the stale executable."""
    net = nn.Dense(4, flatten=True)
    net.initialize()
    net.hybridize()
    a = net(mx.nd.ones((2, 8)))
    b = net(mx.nd.ones((5, 8)))        # new batch: must re-trace, not crash
    assert a.shape == (2, 4) and b.shape == (5, 4)


# ----------------------------------------- autograd through views (reshape)
@pytest.mark.parametrize("view", ["reshape", "slice", "at"])
def test_backward_through_view_of_conv(view):
    """reference test_reshape/test_slice/test_at: backward through a
    sliced/reshaped conv output reaches the conv parameters."""
    x = mx.nd.ones((5, 4, 10, 10))
    layer = nn.Conv2D(10, 2, in_channels=4)
    layer.collect_params().initialize()
    with mx.autograd.record():
        y = layer(x)
        if view == "reshape":
            y = y.reshape((-1,))
        elif view == "slice":
            y = y[1:3]
        else:
            y = y[1]
        y = y + 10
    y.backward()
    g = layer.weight.grad()
    assert float(mx.nd.abs(g).sum().asscalar()) > 0


# ------------------------------------------------------------- grad_req add
def test_grad_req_add_accumulates():
    data = mx.nd.random.uniform(shape=(1, 3, 8, 8))
    label = mx.nd.ones((1,))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net.hybridize()
    for v in net.collect_params().values():
        v.grad_req = "add"
    net.collect_params().zero_grad()
    with mx.autograd.record():
        l = loss(net(data), label)
    l.backward()
    g1 = net[0].weight.grad().asnumpy().copy()
    with mx.autograd.record():
        l = loss(net(data), label)
    l.backward()
    g2 = net[0].weight.grad().asnumpy()
    np.testing.assert_allclose(g1 * 2, g2, rtol=1e-5, atol=1e-6)


def test_zero_grad():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = mx.nd.ones((2, 4))
    with mx.autograd.record():
        net(x).sum().backward()
    assert float(mx.nd.abs(net.weight.grad()).sum().asscalar()) > 0
    net.collect_params().zero_grad()
    assert float(mx.nd.abs(net.weight.grad()).sum().asscalar()) == 0


# -------------------------------------------------------- shared parameters
def test_parameter_sharing_params_kwarg():
    """reference test_parameter_sharing: a block built with params=
    another block's params computes identically."""
    class Net(gluon.Block):
        def __init__(self, in_units=0, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5, in_units=in_units)
                self.dense1 = nn.Dense(5, in_units=in_units)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    net1 = Net(prefix="net1_", in_units=5)
    net2 = Net(prefix="net2_", params=net1.collect_params())
    net1.collect_params().initialize()
    x = mx.nd.random.uniform(shape=(3, 5))
    np.testing.assert_allclose(net2(x).asnumpy(), net1(x).asnumpy(),
                               rtol=1e-6)
    # training net2 moves net1's parameters (same objects)
    assert net2.dense0.weight is net1.dense0.weight or \
        net2.dense0.weight.data().asnumpy().base is not None or \
        np.shares_memory(net2.dense0.weight.data().asnumpy(),
                         net1.dense0.weight.data().asnumpy()) or True
    # value-level check: mutate through net1, net2 sees it
    net1.dense0.weight.set_data(net1.dense0.weight.data() * 0 + 1.0)
    w2 = net2.dense0.weight.data().asnumpy()
    np.testing.assert_allclose(w2, np.ones_like(w2))


def test_shared_parameter_gradients_accumulate_once_per_use():
    """A parameter used twice in one graph gets the SUM of both paths'
    gradients (weight tying)."""
    d = nn.Dense(4, in_units=4, use_bias=False, flatten=False)
    d.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    with mx.autograd.record():
        y = d(d(x)).sum()
    y.backward()
    w = d.weight.data().asnumpy()
    g = d.weight.grad().asnumpy()
    # numeric check on one coordinate
    eps = 1e-3

    def f(wv):
        h = x.asnumpy() @ wv.T
        return (h @ wv.T).sum()

    wp, wm = w.copy(), w.copy()
    wp[0, 0] += eps
    wm[0, 0] -= eps
    num = (f(wp) - f(wm)) / (2 * eps)
    np.testing.assert_allclose(g[0, 0], num, rtol=1e-2, atol=1e-2)


# ----------------------------------------------------- SymbolBlock deep use
def test_symbol_block_from_internals_with_aux(tmp_path):
    """reference test_symbol_block_save_load: a HybridBlock wrapping a
    SymbolBlock built from model-zoo INTERNALS (BN aux states included)
    round-trips through save_parameters/load_parameters."""
    class Net(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                backbone = gluon.model_zoo.vision.resnet18_v1(
                    classes=4, thumbnail=True)
                backbone.initialize()
                backbone(mx.nd.ones((1, 3, 32, 32)))
                data = mx.sym.var("data")
                out_sym = backbone(data)
                internals = out_sym.get_internals()
                names = internals.list_outputs()
                mid = [n for n in names
                       if n.endswith("_output")][len(names) // 4]
                self.backbone = gluon.SymbolBlock(
                    internals[mid], data,
                    params=backbone.collect_params())
                self.body = nn.Conv2D(3, 1)

        def hybrid_forward(self, F, x):
            return self.backbone(self.body(x))

    net1 = Net()
    net1.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 32, 32))
    y1 = net1(x)
    f = str(tmp_path / "sb.params")
    net1.save_parameters(f)

    net2 = Net()
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), y1.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_save_load_with_replaced_head(tmp_path):
    """reference test_save_load: params saved from one net load into a
    net whose head block was re-created (same names/shapes)."""
    net = gluon.model_zoo.vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    net(mx.nd.ones((1, 3, 32, 32)))
    f = str(tmp_path / "n.params")
    net.save_parameters(f)

    net2 = gluon.model_zoo.vision.resnet18_v1(classes=10, thumbnail=True)
    net2.load_parameters(f)
    x = mx.nd.random.uniform(shape=(1, 3, 32, 32))
    np.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_legacy_save_params_compat(tmp_path):
    """reference test_legacy_save_params: the deprecated
    save_params/load_params API + symbol-JSON round-trip into a
    SymbolBlock."""
    net = nn.HybridSequential(prefix="")
    with net.name_scope():
        net.add(nn.Conv2D(10, (3, 3)))
        net.add(nn.Dense(50))
    net.initialize()
    net(mx.nd.ones((1, 1, 50, 50)))
    a = net(mx.sym.var("data"))
    fj = str(tmp_path / "legacy.json")
    fp = str(tmp_path / "legacy.params")
    a.save(fj)
    with pytest.warns(DeprecationWarning):
        net.save_params(fp)
    model = gluon.SymbolBlock(
        outputs=mx.sym.load_json(open(fj).read()),
        inputs=mx.sym.var("data"))
    with pytest.warns(DeprecationWarning):
        model.load_params(fp, ctx=mx.cpu())
    x = mx.nd.random.uniform(shape=(1, 1, 50, 50))
    np.testing.assert_allclose(model(x).asnumpy(), net(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------- dtype handling
def test_cast_float64_forward_backward_under_x64():
    """float64 nets need JAX's x64 mode (off by default: TPU-native f32/
    bf16 focus) — prove the cast path works in an x64 subprocess, like
    the reference's test_dtype."""
    import subprocess, sys, os as _os
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "jax.config.update('jax_enable_x64', True);"
        "import numpy as np, mxnet_tpu as mx;"
        "from mxnet_tpu import gluon;"
        "net = gluon.model_zoo.vision.resnet18_v1(classes=4,"
        " thumbnail=True); net.initialize(); net.cast('float64');\n"
        "with mx.autograd.record():\n"
        "    y = net(mx.nd.ones((2,3,32,32), dtype='float64'))\n"
        "    y.backward()\n"
        "assert y.dtype == np.float64, y.dtype\n"
        "net.hybridize();"
        "out = net(mx.nd.ones((2,3,32,32), dtype='float64'));"
        "assert out.dtype == np.float64, out.dtype;"
        "print('X64_OK')"
    )
    env = {k: v for k, v in _os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=420,
                          env=env, cwd=_os.path.dirname(
                              _os.path.dirname(_os.path.abspath(__file__))))
    assert "X64_OK" in proc.stdout, (proc.stdout[-1500:],
                                     proc.stderr[-1500:])


def test_cast_float16_after_hybridize_retraces():
    net = gluon.model_zoo.vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize()
    net.hybridize()
    net(mx.nd.ones((2, 3, 32, 32), dtype="float32"))
    net.cast("float16")
    out = net(mx.nd.ones((2, 3, 32, 32), dtype="float16"))
    assert out.dtype == np.float16


# -------------------------------------------------------------- hooks/apply
def test_forward_hooks_fire_in_order():
    order = []
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    h1 = net[0].register_forward_pre_hook(
        lambda blk, ins: order.append("pre0"))
    h2 = net[0].register_forward_hook(
        lambda blk, ins, out: order.append("post0"))
    net(mx.nd.ones((1, 3)))
    assert order == ["pre0", "post0"]
    h1.detach()
    h2.detach()
    order.clear()
    net(mx.nd.ones((1, 3)))
    assert order == []


def test_apply_visits_every_block():
    seen = []
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.apply(lambda b: seen.append(type(b).__name__))
    assert seen.count("Dense") == 2 and "HybridSequential" in seen


# -------------------------------------------------------- grad graph change
def test_grad_graph_change():
    """reference test_grad_graph_change: a hybridized block used inside
    record() with varying downstream graph shapes keeps producing correct
    grads (no stale fused backward)."""
    net = nn.Dense(3, in_units=4, flatten=False)
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(2, 4))
    x.attach_grad()
    with mx.autograd.record():
        y = net(x).sum()
    y.backward()
    g1 = x.grad.asnumpy().copy()
    with mx.autograd.record():
        y = (net(x) * 2).sum()         # different downstream graph
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * g1, rtol=1e-5)


def test_share_inputs_outputs_identity():
    """reference test_share_inputs_outputs: a block returning its input
    unchanged must not alias away gradients."""
    class Identity(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return x

    net = Identity()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        y = net(x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones((2, 3)))


def test_sequential_indexing_and_slicing():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(5), nn.Dense(6))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    sub = net[1:]
    assert len(sub) == 2


def test_constant_parameter_blocks_gradient():
    """reference test_constant: Constant params join forward but get no
    gradient and never change under a trainer step."""
    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.c = self.params.get_constant(
                    "c", mx.nd.array([[1.0, 2.0]]))

        def hybrid_forward(self, F, x, c):
            return x + c

    net = Net()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    x = mx.nd.ones((1, 2))
    x.attach_grad()
    with mx.autograd.record():
        out = net(x).sum()
    out.backward()
    trainer.step(1)
    np.testing.assert_allclose(net.c.data().asnumpy(), [[1.0, 2.0]])


def test_bare_symbol_block_save_load_roundtrip(tmp_path):
    """A SymbolBlock with FLAT (dot-free) param names must round-trip its
    own save_parameters/load_parameters (r4 review: the legacy-format
    heuristic used to misroute this case)."""
    backbone = gluon.model_zoo.vision.resnet18_v1(classes=4,
                                                  thumbnail=True)
    backbone.initialize()
    backbone(mx.nd.ones((1, 3, 32, 32)))
    data = mx.sym.var("data")
    sb = gluon.SymbolBlock(backbone(data), data,
                           params=backbone.collect_params())
    x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
    y1 = sb(x)
    f = str(tmp_path / "bare_sb.params")
    sb.save_parameters(f)
    sb2 = gluon.SymbolBlock(backbone(data), data)
    sb2.load_parameters(f)
    np.testing.assert_allclose(sb2(x).asnumpy(), y1.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_hybrid_stale_cache_nested_child_add():
    """A structural edit in a NESTED child invalidates the hybridized
    ancestor's cached executable too (r4 review: only the mutated block's
    own cache used to be cleared)."""
    outer = nn.HybridSequential()
    inner = nn.HybridSequential()
    with inner.name_scope():
        inner.add(nn.Dense(10, weight_initializer="zeros",
                           bias_initializer="ones", flatten=False))
    with outer.name_scope():
        outer.add(inner)
    outer.hybridize()
    outer.initialize()
    assert outer(mx.nd.ones((2, 3, 5))).shape == (2, 3, 10)
    inner.add(nn.Flatten())            # nested structural change
    assert outer(mx.nd.ones((2, 3, 5))).shape == (2, 30)
