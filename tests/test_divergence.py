"""Runtime collective sanitizer (ISSUE 10): per-host fingerprint streams,
cross-checks, the watchdog, and the two-simulated-host drills.

The unit half fakes a peer by writing its stream file directly; the drill
half spawns two real subprocesses under ``MXNET_SANITIZE=collectives`` +
``MXNET_CKPT_HOST`` (the PR 9 harness) and asserts a planted divergence
raises :class:`CollectiveDivergenceError` naming BOTH hosts' next-op
fingerprints instead of hanging in the commit barrier.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import divergence as div
from mxnet_tpu.analysis import sanitizer as san

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "divergence_worker.py")


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    yield
    san.disable()
    san.reset()          # also resets the divergence stream/identity


def _peer_log(d, host):
    return os.path.join(d, f"collectives-{host}.log")


# ------------------------------------------------------------------ recording
class TestRecording:
    def test_fingerprint_fields_and_seq(self):
        with san.scope("collectives"):
            s0 = div.record("trainer.step", axis="dp", shape=(16, 8),
                            dtype="float32", site="here")
            s1 = div.record("kvstore.barrier")
        assert (s0, s1) == (0, 1)
        lines = div.stream()
        assert lines[0] == \
            "0|trainer.step|axis=dp|shape=16x8|dtype=float32 @ here"
        assert lines[1] == "1|kvstore.barrier|axis=-|shape=-|dtype=-"

    def test_detail_rides_in_fingerprint(self):
        div.record("kvstore.allreduce", shape=(4,), dtype="float32",
                   detail="key=w0")
        assert "|key=w0" in div.stream()[0]

    def test_sites_are_not_compared(self, tmp_path):
        # same op issued from differently-named call sites must NOT be a
        # divergence: the fp (before " @ ") is the contract, the site is
        # for the human reading the error
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", axis="dp", shape=(4,), dtype="f32",
                   site="host0 spelling")
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=dp|shape=4|dtype=f32 "
                    "@ host1 spelling\n")
        assert div.check("t") == {0: 1, 1: 1}

    def test_idle_sites_record_nothing(self):
        # sanitizer not armed: the SPMDTrainer hook must not record
        from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                        make_mesh)
        net = mx.gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(),
                         FunctionalOptimizer("sgd", 1e-2),
                         make_mesh(n_devices=1, dp=1))
        tr.step(np.random.rand(4, 8).astype("float32"),
                np.random.rand(4, 4).astype("float32"))
        assert div.stream() == []

    def test_clean_spmd_steps_zero_violations(self):
        from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                        make_mesh)
        net = mx.gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(),
                         FunctionalOptimizer("sgd", 1e-2),
                         make_mesh(n_devices=1, dp=1))
        x = np.random.rand(4, 8).astype("float32")
        y = np.random.rand(4, 4).astype("float32")
        with san.scope("collectives"):
            for _ in range(3):
                tr.step(x, y)
        assert san.stats()["collectives"] == 3
        assert san.stats()["violations"] == 0
        fps = [ln.split(" @ ")[0].split("|", 1)[1] for ln in div.stream()]
        assert len(set(fps)) == 1, "same step must fingerprint identically"

    def test_pipeline_and_moe_sites_record(self):
        import jax.numpy as jnp
        from mxnet_tpu.parallel import (device_mesh, make_mesh, moe_layer,
                                        pipeline)
        with san.scope("collectives"):
            mesh = make_mesh(n_devices=8, pp=8)
            pipeline.gpipe(lambda p, xx: xx * p.sum(), jnp.ones((8, 4)),
                           jnp.ones((16, 4)), mesh, 4)
            mesh_ep = device_mesh({"dp": 2, "ep": 4})
            moe_layer(lambda p, t: t @ p, jnp.ones((6, 4)),
                      jnp.ones((4, 6, 6)), jnp.ones((16, 6)), mesh_ep,
                      capacity_factor=8.0)
        kinds = [ln.split("|")[1] for ln in div.stream()]
        assert kinds == ["pipeline.gpipe", "moe.all_to_all"]

    def test_kvstore_barrier_records(self):
        kv = mx.kv.create("local")
        with san.scope("collectives"):
            kv.barrier()
        assert [ln.split("|")[1] for ln in div.stream()] == \
            ["kvstore.barrier"]


# ---------------------------------------------------------------- cross-check
class TestCrossCheck:
    def test_single_host_check_is_noop(self):
        div.record("trainer.step")
        assert div.check("t") == {0: 1}

    def test_peer_mismatch_raises_naming_both(self, tmp_path):
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", axis="dp", shape=(16, 8), dtype="f32",
                   site="SPMDTrainer.step t=0")
        div.record("kvstore.barrier", site="KVStore.barrier")
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=dp|shape=16x8|dtype=f32 @ s\n"
                    "1|moe.all_to_all|axis=ep|shape=16x8|dtype=f32 @ m\n")
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            div.check("drill")
        msg = str(ei.value)
        assert "1|kvstore.barrier|axis=-|shape=-|dtype=-" in msg
        assert "1|moe.all_to_all|axis=ep|shape=16x8|dtype=f32" in msg
        assert "host 0" in msg and "host 1" in msg
        assert ei.value.index == 1
        assert san.stats()["violations"] == 1

    def test_shorter_peer_prefix_is_clean(self, tmp_path):
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        div.record("trainer.step", shape=(4,))
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n")
        assert div.check("t")[1] == 1      # behind, but not divergent

    def test_sync_waits_for_peer(self, tmp_path):
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        import threading

        def _late_peer():
            with open(_peer_log(d, 1), "w") as f:
                f.write("0|trainer.step|axis=-|shape=4|dtype=-\n")
        t = threading.Timer(0.2, _late_peer)
        t.start()
        try:
            lengths = div.sync("t", timeout_s=10)
        finally:
            t.join()
        assert lengths == {0: 1, 1: 1}

    def test_sync_stall_dumps_every_position(self, tmp_path):
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", axis="dp", shape=(4,), dtype="f32",
                   site="s")
        with pytest.raises(san.CollectiveStallTimeout) as ei:
            div.sync("stall-drill", timeout_s=0.3)
        msg = str(ei.value)
        assert "host 0: 1 collectives" in msg
        assert "host 1: 0 collectives" in msg
        assert ei.value.behind == [1]

    def test_commit_barrier_raises_divergence_not_timeout(self, tmp_path):
        # the checkpoint wiring: host 0's marker poll cross-checks the
        # streams, so a diverged co-writer surfaces as the attributed
        # error, not as CommitBarrierTimeout
        from mxnet_tpu.parallel import (FunctionalOptimizer,
                                        SPMDCheckpointManager, SPMDTrainer,
                                        make_mesh)
        net = mx.gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(),
                         FunctionalOptimizer("sgd", 1e-2),
                         make_mesh(n_devices=1, dp=1))
        tr.step(np.random.rand(4, 8).astype("float32"),
                np.random.rand(4, 4).astype("float32"))
        d = str(tmp_path)
        ckpt = os.path.join(d, "ckpt")
        with san.scope("collectives"):
            div.configure(directory=d, host=0, host_count=2)
            div.record("trainer.step", axis="dp", shape=(4, 8),
                       dtype="float32", site="t=0")
            with open(_peer_log(d, 1), "w") as f:
                f.write("0|pipeline.gpipe|axis=pp|shape=16x4|dtype=f32 "
                        "@ planted\n")
            mgr = SPMDCheckpointManager(ckpt, host_index=0, host_count=2,
                                        barrier_timeout_s=30.0)
            with pytest.raises(san.CollectiveDivergenceError) as ei:
                mgr.save(1, tr)
        assert "pipeline.gpipe" in str(ei.value)


# -------------------------------------------------------------- config/env
class TestConfig:
    def test_env_mode_spelling(self):
        assert san._parse("collectives") == {"collectives"}
        assert san._parse("donation,collectives") == \
            {"donation", "collectives"}

    def test_host_identity_env(self, monkeypatch):
        monkeypatch.setenv("MXNET_CKPT_HOST", "1/4")
        assert div.host_identity() == (1, 4)
        monkeypatch.delenv("MXNET_CKPT_HOST")
        div.configure(host=2, host_count=3)
        assert div.host_identity() == (2, 3)

    def test_no_directory_stays_in_memory(self):
        div.configure(host=0, host_count=2)
        div.record("trainer.step")
        assert div.check("t") == {0: 1}    # no files, no peers to read

    def test_host_pin_without_count_is_honored(self, monkeypatch):
        # configure(host=) alone must pin the host component while the
        # count still resolves from the env/jax fallback chain
        monkeypatch.setenv("MXNET_CKPT_HOST", "0/4")
        div.configure(host=2)
        assert div.host_identity() == (2, 4)

    def test_single_host_past_stream_cap_never_raises(self, monkeypatch):
        # a single-process run longer than the in-memory cap must keep
        # sync()/check() as no-ops, not error out
        monkeypatch.setattr(div, "_STREAM_CAP", 8)
        for _ in range(20):
            div.record("trainer.step", shape=(4,))
        assert div.total_recorded() == 20
        assert len(div.stream()) == 8
        assert div.check("t") == {0: 20}
        assert div.sync("t", timeout_s=1) == {0: 20}

    def test_incremental_cursor_catches_late_divergence(self, tmp_path):
        # verified prefixes are consumed incrementally; a mismatch
        # appended AFTER several clean checks must still raise at the
        # right absolute index
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        with open(_peer_log(d, 1), "a") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n")
        assert div.check("t") == {0: 1, 1: 1}
        assert div.check("t") == {0: 1, 1: 1}     # idempotent re-check
        div.record("trainer.step", shape=(4,))
        with open(_peer_log(d, 1), "a") as f:
            f.write("1|moe.all_to_all|axis=ep|shape=4|dtype=-\n")
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            div.check("t")
        assert ei.value.index == 1

    def test_caught_divergence_reraises_same_index(self, tmp_path):
        # a caller that absorbs the error (e.g. an absorbed-save-failure
        # path) and re-checks must see the SAME first divergence, not a
        # shifted one — the diverging line stays pending
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        div.record("kvstore.barrier")
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n"
                    "1|moe.all_to_all|axis=ep|shape=4|dtype=-\n")
        for _ in range(2):
            with pytest.raises(san.CollectiveDivergenceError) as ei:
                div.check("t")
            assert ei.value.index == 1
            assert "moe.all_to_all" in str(ei.value)

    def test_configure_new_directory_resets_cursors(self, tmp_path):
        # byte offsets from a previous drill's directory must not be
        # applied to a new one (they would skip the new stream's prefix)
        d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
        os.makedirs(d1), os.makedirs(d2)
        div.configure(directory=d1, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        with open(_peer_log(d1, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n")
        assert div.check("t")[1] == 1
        div.configure(directory=d2)
        with open(_peer_log(d2, 1), "w") as f:
            f.write("0|pipeline.gpipe|axis=pp|shape=4|dtype=-\n")
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            div.check("t")
        assert ei.value.index == 0

    def test_cap_truncated_own_prefix_still_compared(self, tmp_path,
                                                     monkeypatch):
        # own lines scrolled off the in-memory cap are backed by the
        # on-disk own stream — a divergence in that prefix must not be
        # silently consumed
        monkeypatch.setattr(div, "_STREAM_CAP", 4)
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        for _ in range(10):
            div.record("trainer.step", shape=(4,))
        assert len(div.stream()) == 4         # memory holds only the tail
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n"
                    "1|moe.all_to_all|axis=ep|shape=4|dtype=-\n")
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            div.check("t")
        assert ei.value.index == 1            # deep inside the dropped prefix

    def test_own_disk_fallback_aligns_by_base_seq(self, tmp_path,
                                                  monkeypatch):
        # the own stream file starts at whatever seq the directory was
        # armed at: pre-arming records live nowhere durable, so their
        # indices are counted unverified (never a bogus divergence), and
        # post-arming indices must align by the file's base seq
        monkeypatch.setattr(div, "_STREAM_CAP", 2)
        d = str(tmp_path)
        div.configure(host=0, host_count=2)      # no directory yet
        div.record("trainer.step", shape=(1,))   # seq 0: memory-only
        div.record("trainer.step", shape=(2,))   # seq 1: memory-only
        div.configure(directory=d)
        for n in range(3, 7):
            div.record("trainer.step", shape=(n,))   # seqs 2..5 on disk
        assert len(div.stream()) == 2            # memory kept only a tail
        # peer agrees on everything it can prove, diverges at seq 3 —
        # which memory dropped but the own file still has, base-aligned
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=1|dtype=-\n"
                    "1|trainer.step|axis=-|shape=2|dtype=-\n"
                    "2|trainer.step|axis=-|shape=3|dtype=-\n"
                    "3|moe.all_to_all|axis=ep|shape=4|dtype=-\n")
        with pytest.raises(san.CollectiveDivergenceError) as ei:
            div.check("t")
        assert ei.value.index == 3
        # seqs 0-1 had no durable evidence: counted, not silently passed
        assert div.unverified_count() == 2

    def test_torn_tail_line_not_compared(self, tmp_path):
        # a peer caught mid-append (no trailing newline) must be re-read
        # on the next check, never compared half-written
        d = str(tmp_path)
        div.configure(directory=d, host=0, host_count=2)
        div.record("trainer.step", shape=(4,))
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|sha")       # torn
        assert div.check("t").get(1, 0) == 0
        with open(_peer_log(d, 1), "w") as f:
            f.write("0|trainer.step|axis=-|shape=4|dtype=-\n")
        assert div.check("t")[1] == 1


# ------------------------------------------------------------------- drills
def _spawn(dirpath, host, extra=()):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("MXNET_SANITIZE", None)
    env.pop("MXNET_CKPT_HOST", None)
    return subprocess.Popen(
        [sys.executable, WORKER, "--dir", dirpath, "--host", host,
         "--steps", "3", "--timeout", "60", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)


class TestTwoHostDrill:
    def test_clean_run_zero_violations(self, tmp_path):
        d = str(tmp_path)
        procs = [_spawn(d, "0/2"), _spawn(d, "1/2")]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        assert [p.returncode for p in procs] == [0, 0], outs
        assert all("violations=0" in o for o in outs), outs
        # the sharded save committed: the drill is a real 2-host step +
        # checkpoint, not just a stream echo
        from mxnet_tpu.parallel import SPMDCheckpointManager
        assert SPMDCheckpointManager(d).latest_step() == 3

    def test_planted_divergence_raises_both_hosts_named(self, tmp_path):
        d = str(tmp_path)
        procs = [_spawn(d, "0/2"),
                 _spawn(d, "1/2", extra=("--diverge-at", "2"))]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        # no hang: both processes exit with the divergence code, and the
        # error names BOTH hosts' next-op fingerprints
        assert [p.returncode for p in procs] == [3, 3], outs
        for o in outs:
            assert "CollectiveDivergenceError" in o or "DIVERGENCE" in o, o
            assert "trainer.step" in o and "pipeline.gpipe" in o, o
            assert "host 0" in o and "host 1" in o, o
        # nothing committed for the diverged step
        from mxnet_tpu.parallel import SPMDCheckpointManager
        assert SPMDCheckpointManager(d).latest_step() is None
