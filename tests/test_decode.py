"""mxnet_tpu.serving.decode: paged KV cache, 2-D prefill ladder, continuous
batching (ISSUE 11 tentpole + satellites), shared-prefix pages with
copy-on-write + int8 quantized pools (ISSUE 17).

The heart of the file is the no-recompile / bitwise-parity contract test:
a mixed-prompt-length workload with requests joining and finishing across
step boundaries must (a) take zero steady-state ``decode.compile_miss``
and (b) hand every request tokens bitwise-identical to running it solo.
ISSUE 17 adds the sharing analog: a request's tokens are bitwise-identical
whether its prefix was acquired from the shared-prefix index or prefilled
cold — in fp32 AND int8 pools.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import StaleKVSlotError, StaleSlotError, sanitizer
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.faults import InjectedFault
from mxnet_tpu.serving import RequestRejected
from mxnet_tpu.serving.decode import (DecodeRuntime, DecodeScheduler,
                                      GenerationResult, KVCacheExhausted,
                                      PagedKVCache, get_decode_model,
                                      pages_needed, seq_bucket_ladder)

VOCAB = 61


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


@pytest.fixture(scope="module")
def runtime():
    """One warmed runtime for the whole module (compiles are the cost)."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1, 2, 4), seq_buckets=(8, 16),
                       page_size=8)
    yield rt


@pytest.fixture(scope="module")
def tight_runtime():
    """Tiny KV pool (3 usable pages) for exhaustion-path tests."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    cache = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                         page_size=4, num_pages=4, max_pages_per_seq=4,
                         max_slots=2)
    rt = DecodeRuntime(net, cache=cache, batch_buckets=(1, 2),
                       seq_buckets=(8,))
    yield rt


@pytest.fixture
def sched(runtime):
    s = DecodeScheduler(runtime)
    yield s
    s.close(drain=False, timeout=10.0)
    assert runtime.cache.pages_in_use == 0, "leaked KV pages"
    assert runtime.cache.slots_in_use == 0, "leaked KV slots"


def _prompt(i, lo=1, hi=14):
    rng = np.random.RandomState(1000 + i)
    return list(rng.randint(1, VOCAB, lo + (i * 3) % (hi - lo + 1)))


# ------------------------------------------------------------- page math
def test_pages_needed():
    # written positions = prompt + max_new - 1 (last token never re-encoded)
    assert pages_needed(3, 1, 8) == 1
    assert pages_needed(8, 1, 8) == 1
    assert pages_needed(8, 2, 8) == 2
    assert pages_needed(9, 8, 8) == 2
    assert pages_needed(1, 16, 8) == 2


def test_seq_bucket_ladder():
    assert seq_bucket_ladder(64) == (8, 16, 32, 64)
    assert seq_bucket_ladder(48) == (8, 16, 32, 48)
    assert seq_bucket_ladder(8) == (8,)
    assert seq_bucket_ladder(4) == (4,)
    with pytest.raises(ValueError):
        seq_bucket_ladder(0)


# ------------------------------------------------------------- KV cache
def test_kv_cache_alloc_free_generations():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=4,
                     max_slots=3)
    assert c.usable_pages == 8 and c.context_length == 16
    a = c.alloc(3)
    b = c.alloc(4)
    assert c.pages_in_use == 7 and c.slots_in_use == 2
    assert 0 not in a.pages and 0 not in b.pages          # trash reserved
    assert not (set(a.pages) & set(b.pages))
    assert len(a.page_table) == 4 and a.page_table[3] == 0  # trash-padded
    with pytest.raises(KVCacheExhausted):
        c.alloc(2)                                         # 1 page free
    gen = c.generation(a.slot_id)
    c.free(a)
    assert c.generation(a.slot_id) == gen + 1              # bumped on free
    with pytest.raises(ValueError):
        c.free(a)                                          # double free
    c.free(b)
    assert c.pages_in_use == 0 and c.slots_in_use == 0
    with pytest.raises(ValueError):
        c.alloc(5)                                         # > max_pages_per_seq
    with pytest.raises(ValueError):
        PagedKVCache(2, 2, 16, num_pages=1)                # no room for trash


def test_kv_cache_slot_exhaustion():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2,
                     max_slots=1)
    a = c.alloc(1)
    with pytest.raises(KVCacheExhausted):
        c.alloc(1)                                         # slots, not pages
    c.free(a)
    c.alloc(1)


def test_kv_alloc_fault_injectable():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2)
    with faults.scope("decode.kv_alloc:fail"):
        with pytest.raises(InjectedFault):
            c.alloc(1)
    c.free(c.alloc(1))                                     # healthy after


def test_stale_kv_slot_sanitizer():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2)
    with sanitizer.scope("slots"):
        slot = c.alloc(1)
        c.check_slot(slot)                                 # live: fine
        c.free(slot)
        with pytest.raises(StaleKVSlotError) as ei:
            c.check_slot(slot)
        assert "decode.kv_alloc" in str(ei.value)          # site named
        assert isinstance(ei.value, StaleSlotError)        # slots family
    sanitizer.reset()
    # sanitizer off: the check is a no-op (one attribute read)
    slot = c.alloc(1)
    c.free(slot)
    c.check_slot(slot)


# ----------------------------------------------------------- runtime/ladder
def test_runtime_ladders_and_validation(runtime):
    assert runtime.batch_bucket_for(3) == 4
    assert runtime.seq_bucket_for(9) == 16
    with pytest.raises(ValueError):
        runtime.batch_bucket_for(5)
    with pytest.raises(ValueError):
        runtime.seq_bucket_for(17)
    net = runtime.block
    # cache context must fit the model's position table
    big = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                       page_size=8, num_pages=17, max_pages_per_seq=8)
    with pytest.raises(ValueError):
        DecodeRuntime(net, cache=big, warm=False)
    small = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                         page_size=8, num_pages=9, max_pages_per_seq=4,
                         max_slots=2)
    with pytest.raises(ValueError):                        # slots < max batch
        DecodeRuntime(net, cache=small, batch_buckets=(1, 4), warm=False)


def test_model_validation():
    with pytest.raises(ValueError):
        get_decode_model("decode_tiny", units=30, num_heads=4)


def test_default_cache_geometry_non_multiple_max_length():
    """Default geometry floors max_length/page_size: the derived context
    never exceeds the model's position table."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=20,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1,), seq_buckets=(8,),
                       page_size=8, warm=False)
    assert rt.cache.context_length == 16                   # 20 // 8 pages
    with pytest.raises(ValueError):
        DecodeRuntime(net, batch_buckets=(1,), page_size=32, warm=False)


# ------------------------------------------------------------- submit plane
def test_submit_validation(sched):
    with pytest.raises(ValueError):
        sched.submit([])                                   # empty
    with pytest.raises(ValueError):
        sched.submit(list(range(1, 18)))                   # > max seq bucket
    with pytest.raises(ValueError):
        sched.submit([VOCAB + 3])                          # id out of range
    with pytest.raises(ValueError):
        sched.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):
        sched.submit([1] * 16, max_new_tokens=32)          # context overflow


def test_kv_never_fits_shed(tight_runtime):
    s = DecodeScheduler(tight_runtime)
    try:
        # 4 pages needed, 3 usable: could never be admitted
        with pytest.raises(RequestRejected) as ei:
            s.submit([1] * 8, max_new_tokens=8)
        assert ei.value.reason == "kv_exhausted"
    finally:
        s.close(drain=False, timeout=10.0)


def test_kv_exhaustion_waits_then_completes(tight_runtime):
    s = DecodeScheduler(tight_runtime)
    try:
        # each needs 2 of the 3 usable pages: the second waits for the
        # first eviction, then completes — and nothing leaks
        f1 = s.submit(_prompt(1, 4, 4), max_new_tokens=5, seed=1)
        f2 = s.submit(_prompt(2, 4, 4), max_new_tokens=5, seed=2)
        assert len(f1.result(60).token_ids) == 5
        assert len(f2.result(60).token_ids) == 5
    finally:
        s.close(drain=True, timeout=30.0)
    assert tight_runtime.cache.pages_in_use == 0


# ------------------------------------------------------------ generation
def test_generate_deterministic(sched):
    r1 = sched.generate([5, 9, 2], max_new_tokens=6, seed=7, timeout=60)
    r2 = sched.generate([5, 9, 2], max_new_tokens=6, seed=7, timeout=60)
    assert isinstance(r1, GenerationResult)
    assert r1.token_ids == r2.token_ids
    assert r1.finish_reason == "length" and len(r1.token_ids) == 6
    assert r1.prompt_len == 3 and r1.ttft_ms is not None
    t1 = sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                        seed=11, timeout=60)
    t2 = sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                        seed=11, timeout=60)
    assert t1.token_ids == t2.token_ids                    # same seed
    streams = [sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                              seed=s, timeout=60).token_ids
               for s in (21, 22, 23)]
    assert len({tuple(s) for s in streams}) > 1            # seeds matter


def test_eos_stops_early(sched):
    ref = sched.generate([3, 1, 4, 1, 5], max_new_tokens=6, seed=0,
                         timeout=60).token_ids
    eos = ref[-1]
    idx = ref.index(eos)
    out = sched.generate([3, 1, 4, 1, 5], max_new_tokens=6, seed=0,
                         eos_id=eos, timeout=60)
    assert out.finish_reason == "eos"
    assert out.token_ids == ref[:idx + 1]


def test_cancelled_request_evicted(sched):
    # cancel while still queued behind a full batch: slot is never held
    blockers = [sched.submit(_prompt(i, 6, 6), max_new_tokens=16, seed=i)
                for i in range(4)]
    victim = sched.submit([1, 2], max_new_tokens=16)
    victim.cancel()
    [b.result(60) for b in blockers]
    assert victim.cancelled()


# ------------------------------------- THE no-recompile / parity contract
def test_continuous_batching_bitwise_parity_and_zero_misses(runtime):
    reqs = [dict(prompt=_prompt(i), max_new_tokens=3 + i % 6,
                 temperature=0.7 * (i % 3 == 0), seed=100 + i)
            for i in range(12)]
    s = DecodeScheduler(runtime)
    try:
        # solo reference: one request at a time (batch bucket 1)
        solo = [s.generate(timeout=120, **r).token_ids for r in reqs]
        # drop the prefix index the solo pass just populated: the
        # continuous pass must prefill cold so requests genuinely
        # overlap (full-prefix hits admit instantly and the batch can
        # drain between staggered arrivals) — and cold-vs-published is
        # exactly the parity this test exists to prove
        runtime.cache.drop_prefix_cache()
        telemetry.enable()
        telemetry.reset()
        futs = []

        def feed():
            for i, r in enumerate(reqs):
                futs.append(s.submit(**r))
                time.sleep(0.002 * (i % 4))

        t = threading.Thread(target=feed)
        t.start()
        t.join()
        cont = [f.result(120).token_ids for f in futs]
        snap = telemetry.snapshot()["counters"]
        telemetry.disable()
    finally:
        s.close(drain=False, timeout=10.0)
    for i, (a, b) in enumerate(zip(solo, cont)):
        assert a == b, f"request {i} diverged: solo={a} continuous={b}"
    assert not snap.get("decode.compile_miss"), snap
    assert snap.get("decode.joins", 0) >= 1          # genuinely continuous
    assert snap["decode.evictions"] == len(reqs)
    assert runtime.cache.pages_in_use == 0, "leaked KV pages"
    assert runtime.cache.slots_in_use == 0, "leaked KV slots"


def test_sanitizer_clean_continuous_run(runtime):
    s = DecodeScheduler(runtime)
    try:
        with sanitizer.scope("donation,slots"):
            futs = [s.submit(_prompt(i), max_new_tokens=4, seed=i)
                    for i in range(6)]
            [f.result(60) for f in futs]
            assert sanitizer.stats()["violations"] == 0
    finally:
        sanitizer.reset()
        s.close(drain=False, timeout=10.0)


def test_mesh_sharded_kv_cache_parity():
    """NamedSharding over the heads axis: the cache scales with the mesh
    without changing scheduler code, and decode output is unchanged."""
    import jax
    from jax.sharding import Mesh, NamedSharding
    from mxnet_tpu.serving.decode import DecodeSession
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("data", "model"))
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=4)
    net.initialize()
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                         page_size=8, mesh=mesh)
    try:
        assert isinstance(sess.cache.k_pages.sharding, NamedSharding)
        assert "model" in str(sess.cache.k_pages.sharding.spec)
        sharded = sess.generate([5, 9, 2], max_new_tokens=5, seed=7,
                                timeout=120).token_ids
    finally:
        sess.close(drain=False)
    plain = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                          page_size=8)
    try:
        assert plain.generate([5, 9, 2], max_new_tokens=5, seed=7,
                              timeout=120).token_ids == sharded
    finally:
        plain.close(drain=False)


# --------------------------------------------------------- shed/backpressure
def test_deadline_shed_while_waiting(sched):
    # 4 long sequences fill every batch row; a deadlined request behind
    # them expires at the next admission sweep instead of hanging
    blockers = [sched.submit(_prompt(i, 6, 6), max_new_tokens=20, seed=i)
                for i in range(4)]
    while sched.active() < 4 and not all(b.done() for b in blockers):
        time.sleep(0.001)
    late = sched.submit([1, 2, 3], max_new_tokens=4, deadline_ms=2)
    with pytest.raises(RequestRejected) as ei:
        late.result(60)
    assert ei.value.reason == "deadline"
    [b.result(120) for b in blockers]


def test_queue_backpressure_deadline(runtime):
    s = DecodeScheduler(runtime, queue_depth=1, start=False)
    try:
        s.submit([1, 2], max_new_tokens=2)
        with pytest.raises(RequestRejected) as ei:
            s.submit([3, 4], max_new_tokens=2, deadline_ms=30)
        assert ei.value.reason == "deadline"
    finally:
        s.close(drain=True, timeout=30.0)


def test_close_drain_false_rejects(runtime):
    s = DecodeScheduler(runtime, start=False)
    f = s.submit([1, 2, 3], max_new_tokens=4)
    s.close(drain=False)
    with pytest.raises(RequestRejected) as ei:
        f.result(5)
    assert ei.value.reason == "shutdown"
    with pytest.raises(RequestRejected):
        s.submit([1], max_new_tokens=1)
    assert runtime.cache.pages_in_use == 0


def test_close_drain_true_completes(runtime):
    s = DecodeScheduler(runtime, start=False)
    futs = [s.submit(_prompt(i), max_new_tokens=3, seed=i) for i in range(5)]
    s.close(drain=True, timeout=60.0)
    for f in futs:
        assert len(f.result(0).token_ids) == 3
    assert runtime.cache.pages_in_use == 0


# ------------------------------------------------------------ fault drills
def test_step_fault_fails_batch_and_recovers(runtime):
    s = DecodeScheduler(runtime, breaker_threshold=None)
    try:
        with faults.scope("decode.step:fail"):
            f = s.submit([1, 2, 3], max_new_tokens=4, seed=0)
            with pytest.raises(InjectedFault):
                f.result(60)
        assert runtime.cache.pages_in_use == 0             # slot freed
        ok = s.generate([1, 2, 3], max_new_tokens=4, seed=0, timeout=60)
        assert len(ok.token_ids) == 4                      # worker survived
        assert s.steps_failed == 1
    finally:
        s.close(drain=False, timeout=10.0)


def test_kv_alloc_fault_sheds_request_only(runtime):
    s = DecodeScheduler(runtime)
    try:
        with faults.scope("decode.kv_alloc:fail"):
            f = s.submit([1, 2], max_new_tokens=3, seed=0)
            with pytest.raises(InjectedFault):
                f.result(60)
        ok = s.generate([1, 2], max_new_tokens=3, seed=0, timeout=60)
        assert len(ok.token_ids) == 3
    finally:
        s.close(drain=False, timeout=10.0)


def test_circuit_breaker_opens_and_probes(runtime):
    s = DecodeScheduler(runtime, breaker_threshold=1,
                        breaker_cooldown_ms=150.0)
    try:
        with faults.scope("decode.step:fail"):
            f = s.submit([1, 2, 3], max_new_tokens=4)
            with pytest.raises(InjectedFault):
                f.result(60)
        assert not s.healthy
        with pytest.raises(RequestRejected) as ei:
            s.submit([1], max_new_tokens=2)
        assert ei.value.reason == "unhealthy"
        time.sleep(0.2)                                    # cooldown expires
        assert s.healthy
        assert len(s.generate([1, 2, 3], max_new_tokens=3,
                              timeout=60).token_ids) == 3
    finally:
        s.close(drain=False, timeout=10.0)


# ------------------------------------------------------------- telemetry
def test_decode_telemetry_counters(runtime):
    telemetry.enable()
    s = DecodeScheduler(runtime)
    try:
        futs = [s.submit(_prompt(i), max_new_tokens=4, seed=i)
                for i in range(5)]
        [f.result(60) for f in futs]
    finally:
        s.close(drain=False, timeout=10.0)
    snap = telemetry.snapshot()
    c = snap["counters"]
    assert c["decode.requests"] == 5
    # an admission either prefills cold or skips via a full-prefix hit
    # (the module-scoped runtime's index may already know these prompts)
    assert c.get("decode.prefills", 0) + c.get("decode.prefill_skips", 0) \
        == 5
    assert c["decode.tokens"] == 20
    assert c["decode.evictions"] == 5
    assert c["decode.ttft_ms"] > 0
    assert c.get("decode.compile_miss") in (None, 0)
    assert "decode.kv_occupancy" in snap["gauges"]
    assert "decode.kv_bytes_per_token" in snap["gauges"]


# ---------------------------------------- ISSUE 17: shared-prefix + int8
def _published_cache(**kw):
    """A small cache with one published 2-page prompt (chain + full
    entry, no tail: the prompt is page-aligned) and its donor slot."""
    cfg = dict(page_size=4, num_pages=12, max_pages_per_seq=4, max_slots=4)
    cfg.update(kw)
    c = PagedKVCache(2, 2, 16, **cfg)
    prompt = np.arange(1, 9, dtype="int32")            # 2 full pages
    donor = c.alloc(3, prompt=prompt)
    c.publish(donor, prompt, logits_row=np.zeros(7, "float32"))
    return c, prompt, donor


def test_prefix_sharing_refcounts_and_lifecycle():
    c, prompt, a = _published_cache()
    assert c.stats()["prefix_misses"] == 1
    b = c.alloc(3, prompt=prompt)                      # full hit
    assert b.shared_pages == 2
    assert b.pages[:2] == a.pages[:2]                  # acquired, not copied
    assert b.pages[2] not in a.pages
    assert b.prefix_logits is not None
    st = c.stats()
    assert st["prefix_hits"] == 1 and st["prefix_hit_rate"] == 0.5
    assert st["shared_pages"] >= 2
    # co-holder frees: shared pages survive for b AND for the index
    c.free(a)
    assert c.stats()["prefix_cached_pages"] == 2
    d = c.alloc(3, prompt=prompt)                      # still a hit
    assert d.shared_pages == 2
    c.free(b)
    c.free(d)
    # index pins keep the prefix warm with zero live slots
    assert c.pages_in_use == 0
    assert c.stats()["reclaimable_pages"] == 2
    c.drop_prefix_cache()
    assert c.stats()["prefix_cached_pages"] == 0
    assert c.stats()["reclaimable_pages"] == 0


def test_prefix_partial_chain_match_and_write_table():
    c, prompt, a = _published_cache()
    longer = np.concatenate([prompt, [9, 10, 11]]).astype("int32")
    b = c.alloc(4, prompt=longer)                      # chain match only
    assert b.shared_pages == 2 and b.prefix_logits is None
    wt = b.write_table()
    assert wt[:2] == [0, 0]                            # shared -> trash
    assert wt[2:4] == b.page_table[2:4] and 0 not in wt[2:4]
    c.free(b)
    c.free(a)


def test_prefix_cache_reclaimed_under_pressure():
    c, prompt, a = _published_cache()
    c.free(a)                                          # 2 pages pinned only
    assert c.stats()["reclaimable_pages"] == 2
    slots = [c.alloc(4), c.alloc(4)]                   # needs 8 of 11 usable
    big = c.alloc(3)                                   # forces reclaim
    assert c.stats()["prefix_cached_pages"] == 0       # index evicted LRU
    for s in slots + [big]:
        c.free(s)
    assert c.alloc(3, prompt=prompt).shared_pages == 0  # cold again
    # exhaustion message names the reclaimable count for pool sizing
    c2, _, a2 = _published_cache(num_pages=6)          # 5 usable, 3 held
    c2.free(a2)                                        # 2 pinned, 3 free... 
    c2.alloc(3)
    with pytest.raises(KVCacheExhausted) as ei:
        c2.alloc(4)                                    # > 2 free + 2 reclaim
    assert "reclaimable from the shared-prefix cache" in str(ei.value)


def _publish_and_free(c, prompt):
    """Publish ``prompt`` (chain + full entry) and leave its pages
    pinned-only; returns the donor's page list."""
    donor = c.alloc(len(prompt) // c.page_size, prompt=prompt)
    c.publish(donor, prompt, logits_row=np.zeros(7, "float32"))
    pages = list(donor.pages)
    c.free(donor)
    return pages


def test_prefix_hit_survives_reclaim_pressure():
    """Regression: a prefix-hit alloc under page pressure must never
    reclaim the pages it just matched — pre-fix the reclaimer freed the
    matched entry's pages (slot_refs still 0) and re-issued one as a
    writable fresh page, aliasing the shared prefix."""
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=8,
                     max_pages_per_seq=4, max_slots=4)
    p1 = np.arange(1, 9, dtype="int32")
    p2 = np.arange(101, 109, dtype="int32")
    _publish_and_free(c, p1)
    p2_pages = _publish_and_free(c, p2)
    blocker = c.alloc(3)                       # 0 free: hit must reclaim
    s = c.alloc(3, prompt=p2)                  # full hit on p2
    assert s.shared_pages == 2
    assert len(set(s.pages)) == len(s.pages)   # no page aliased
    assert s.pages[:2] == p2_pages[:2]         # matched pages kept intact
    # the matched entry survived reclaim (p1, the cold one, was evicted)
    assert c.stats()["prefix_cached_pages"] == 2
    c.free(s)
    c.free(blocker)
    assert c.alloc(3, prompt=p2).shared_pages == 2


def test_prefix_hit_exhausted_rolls_back():
    """When even reclaim can't free a fresh page, the hit path must roll
    back its acquisitions: the index stays intact and refcounts balance
    (pre-fix the matched pages were double-counted or freed)."""
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=6,
                     max_pages_per_seq=4, max_slots=4)
    p1 = np.arange(1, 9, dtype="int32")
    _publish_and_free(c, p1)
    blocker = c.alloc(3)                       # 0 free, 2 pinned by index
    with pytest.raises(KVCacheExhausted):
        c.alloc(3, prompt=p1)                  # hit, but no room for fresh
    assert c.stats()["prefix_cached_pages"] == 2   # index untouched
    c.free(blocker)
    s = c.alloc(3, prompt=p1)                  # retry after pressure: hit
    assert s.shared_pages == 2
    assert len(set(s.pages)) == len(s.pages)
    c.free(s)
    c.drop_prefix_cache()
    assert c.pages_in_use == 0
    assert all(r == 0 for r in c._slot_refs)   # refcounts balanced


def test_chain_eviction_unpublishes_suffix():
    """Evicting a chain link takes its whole suffix: links past a missing
    one can never match again, so leaving them pinned would strand pages
    in the index (pre-fix they held HBM invisibly)."""
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=6,
                     max_pages_per_seq=4, max_slots=4)
    prompt = np.arange(1, 13, dtype="int32")   # 3-page chain
    donor = c.alloc(3, prompt=prompt)
    c.publish(donor, prompt)                   # chain pins only, no entry
    c.free(donor)
    assert c.stats()["prefix_cached_pages"] == 3
    blocker = c.alloc(2)                       # 0 free
    s = c.alloc(1)                             # reclaim evicts the chain
    # the LRU head link went, and the rest of the chain went WITH it —
    # nothing is left pinned under unmatchable hashes
    assert c.stats()["prefix_cached_pages"] == 0
    assert c.stats()["reclaimable_pages"] == 0
    c.free(s)
    c.free(blocker)


def test_full_hit_keeps_chain_hot():
    """A full-entry hit must LRU-touch its chain hashes too: under later
    pressure the genuinely cold chain is evicted first, not the chain the
    hit just proved hot."""
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=10,
                     max_pages_per_seq=4, max_slots=4)
    p1 = np.arange(1, 9, dtype="int32")
    p2 = np.arange(101, 109, dtype="int32")
    _publish_and_free(c, p1)
    _publish_and_free(c, p2)
    hot = c.alloc(2, prompt=p1)                # full hit: p1 is hot now
    c.free(hot)
    blockers = [c.alloc(4), c.alloc(1)]        # 0 free
    trigger = c.alloc(2)                       # needs 2: evicts one chain
    c.free(trigger)
    for b in blockers:
        c.free(b)
    assert c.alloc(2, prompt=p1).shared_pages == 2   # hot chain survived
    assert c.alloc(2, prompt=p2).shared_pages == 0   # cold chain evicted


def test_stale_slot_sanitization_under_sharing():
    """The ISSUE 17 satellite: freeing one session of a shared prefix must
    NOT poison the survivor; the LAST free recycles (and poisons); a
    double free still raises."""
    c, prompt, a = _published_cache()
    with sanitizer.scope("slots"):
        b = c.alloc(3, prompt=prompt)
        c.check_slot(a)
        c.check_slot(b)
        c.free(a)                                      # co-holder leaves
        c.check_slot(b)                                # survivor is clean
        with pytest.raises(ValueError):
            c.free(a)                                  # double free raises
        c.drop_prefix_cache()                          # pins released too
        c.check_slot(b)                                # b still holds refs
        c.free(b)                                      # LAST holder: recycle
        with pytest.raises(StaleKVSlotError):
            c.check_slot(b)
        # page-level fence: a handle stamped before its page recycled
        # raises naming the page (defense in depth — the refcount
        # discipline makes this unreachable through the scheduler)
        d = c.alloc(1)
        d.page_gens[0] -= 1
        with pytest.raises(StaleKVSlotError) as ei:
            c.check_slot(d)
        assert ei.value.page == d.pages[0]
        c.free(d)
    sanitizer.reset()


def test_copy_on_write_divergence():
    """Two slots share a published prefix whose tail page is partial: each
    acquirer gets a private tail copy at admission (the CoW moment), so
    writes diverge without touching the donor's or the index's pages."""
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=12,
                     max_pages_per_seq=4, max_slots=4)
    prompt = np.arange(1, 7, dtype="int32")            # 1 full page + tail 2
    a = c.alloc(3, prompt=prompt)
    c.publish(a, prompt, logits_row=np.zeros(7, "float32"))
    before = c.cow_copies
    b = c.alloc(3, prompt=prompt)                      # full hit
    assert c.cow_copies == before + 1                  # eager tail copy
    assert b.pages[0] == a.pages[0]                    # chain page shared
    assert b.pages[1] != a.pages[1]                    # tail privatized
    # ensure_writable on the shared chain page forces a private copy
    c.ensure_writable(b, 0)
    assert b.pages[0] != a.pages[0] and b.shared_pages == 0
    # ...and on an exclusively-owned page it is a no-op
    p1 = b.pages[1]
    c.ensure_writable(b, 1)
    assert b.pages[1] == p1
    c.free(a)
    c.free(b)


def test_int8_quantize_roundtrip_row_stable():
    import jax.numpy as jnp
    from mxnet_tpu.serving.decode import kv_dequantize, kv_quantize_rows
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 7, 2, 16).astype("float32"))
    q, scale, mid = kv_quantize_rows(x)
    assert q.dtype == jnp.int8 and scale.shape == (4, 7)
    err = np.abs(np.asarray(kv_dequantize(q, scale, mid)) - np.asarray(x))
    rng_span = np.asarray(x.max(axis=(-2, -1)) - x.min(axis=(-2, -1)))
    assert (err <= rng_span[..., None, None] / 254.0 + 1e-6).all()
    # row stability: a row's codes don't depend on its neighbors
    q2, s2, m2 = kv_quantize_rows(x[1:3])
    assert (np.asarray(q2) == np.asarray(q[1:3])).all()
    assert (np.asarray(s2) == np.asarray(scale[1:3])).all()
    # all-zero rows (trash page) dequantize to exactly 0.0
    qz, sz, mz = kv_quantize_rows(jnp.zeros((1, 2, 16)))
    assert (np.asarray(kv_dequantize(qz, sz, mz)) == 0.0).all()


def test_int8_pool_geometry_doubles_admission():
    """The acceptance bar: at EQUAL pool bytes, int8 pools admit >= 2x the
    concurrent sequences of the fp32 baseline."""
    fp32 = PagedKVCache(2, 2, 16, page_size=8, num_pages=17,
                        max_pages_per_seq=4, max_slots=64)
    budget = fp32.usable_pages * fp32.page_bytes
    i8 = PagedKVCache(2, 2, 16, page_size=8,
                      num_pages=budget // (fp32.page_bytes // 3) + 1,
                      max_pages_per_seq=4, max_slots=64, kv_dtype="int8")
    assert i8.usable_pages * i8.page_bytes <= budget   # honest comparison
    assert i8.kv_bytes_per_token * 2 <= fp32.kv_bytes_per_token

    def max_admissible(cache, n_pages=2):
        held = []
        try:
            while True:
                held.append(cache.alloc(n_pages))
        except KVCacheExhausted:
            pass
        n = len(held)
        for s in held:
            cache.free(s)
        return n

    assert max_admissible(i8) >= 2 * max_admissible(fp32)


@pytest.fixture(scope="module")
def int8_session():
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    from mxnet_tpu.serving.decode import DecodeSession
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8, 16),
                         page_size=8, kv_dtype="int8")
    yield sess
    sess.close(drain=False)


def test_int8_session_deterministic_and_shared(int8_session):
    sess = int8_session
    assert sess.cache.quantized and sess.stats()["kv_dtype"] == "int8"
    p = _prompt(3, 6, 12)
    r1 = sess.generate(p, max_new_tokens=5, temperature=0.8, seed=4,
                       timeout=120)
    r2 = sess.generate(p, max_new_tokens=5, temperature=0.8, seed=4,
                       timeout=120)
    # quantization is elementwise-deterministic: the shared-vs-cold
    # bitwise contract holds in int8 too (r2 rode the prefix index)
    assert r1.token_ids == r2.token_ids
    assert sess.stats()["prefix_hits"] >= 1
    assert sess.cache.pages_in_use == 0


def test_shared_vs_cold_bitwise_across_joins(runtime):
    """The ISSUE 17 determinism bar: a request's tokens are bitwise
    identical whether its prefix was shared or cold, across continuous
    joins/evictions — checked against a prefix_sharing=False runtime."""
    sysp = _prompt(40, 10, 10)
    reqs = [dict(prompt=sysp + _prompt(50 + i, 1, 4),
                 max_new_tokens=3 + i % 4,
                 temperature=0.6 * (i % 2), seed=300 + i)
            for i in range(8)]
    # every third request repeats the bare system prompt with a fresh
    # seed: full-prefix hits that must still produce their own stream
    for i in (2, 5):
        reqs[i] = dict(prompt=sysp, max_new_tokens=4, temperature=0.9,
                       seed=400 + i)
    cold_rt = DecodeRuntime(runtime.block, batch_buckets=(1, 2, 4),
                            seq_buckets=(8, 16), page_size=8,
                            prefix_sharing=False)
    outs = {}
    for label, rt in (("shared", runtime), ("cold", cold_rt)):
        s = DecodeScheduler(rt)
        try:
            futs = []
            for i, r in enumerate(reqs):
                futs.append(s.submit(**r))
                time.sleep(0.002 * (i % 3))            # force joins
            outs[label] = [f.result(120).token_ids for f in futs]
        finally:
            s.close(drain=False, timeout=10.0)
    assert outs["shared"] == outs["cold"]
    assert cold_rt.cache.stats()["prefix_hits"] == 0   # genuinely cold
    assert runtime.cache.stats()["prefix_hits"] >= 2


def test_prefix_hit_skips_prefill_telemetry(runtime):
    telemetry.enable()
    s = DecodeScheduler(runtime)
    try:
        p = _prompt(60, 9, 9)
        s.generate(p, max_new_tokens=4, seed=1, timeout=60)
        # a successful prefix-hit admission counts as circuit-breaker
        # success exactly like a cold prefill does (max_new_tokens=1:
        # the request finishes at admission, so no decode step runs
        # that could reset the counter on the hit path's behalf)
        s._consecutive_failures = 1
        s.generate(p, max_new_tokens=1, seed=2, timeout=60)
        assert s._consecutive_failures == 0
    finally:
        s.close(drain=False, timeout=10.0)
    c = telemetry.snapshot()["counters"]
    assert c.get("decode.prefill_skips", 0) >= 1       # second skipped
    assert c.get("decode.prefix_hits", 0) >= 1
    assert c.get("decode.compile_miss") in (None, 0)   # fast path warmed


# -------------------------------------------------- fp8 KV pools (ISSUE 20)
def test_fp8_quantize_roundtrip_row_stable():
    import jax.numpy as jnp
    from mxnet_tpu.serving.decode import (kv_dequantize_fp8,
                                          kv_quantize_rows_fp8)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 7, 2, 16).astype("float32"))
    q, scale = kv_quantize_rows_fp8(x)
    assert q.dtype == jnp.float8_e4m3fn and scale.shape == (4, 7)
    xr = np.asarray(kv_dequantize_fp8(q, scale))
    xn = np.asarray(x)
    # e4m3 keeps 3 mantissa bits: relative error <= 2^-4 in the normal
    # range, absolute error bounded by the row scale in the subnormals
    err = np.abs(xr - xn)
    bound = np.abs(xn) / 16.0 + np.asarray(scale)[..., None, None] * 2e-3
    assert (err <= bound + 1e-7).all(), float((err - bound).max())
    # row stability: a row's codes don't depend on its neighbors
    q2, s2 = kv_quantize_rows_fp8(x[1:3])
    assert (np.asarray(q2).view("uint8")
            == np.asarray(q[1:3]).view("uint8")).all()
    assert (np.asarray(s2) == np.asarray(scale[1:3])).all()
    # all-zero rows (trash page) dequantize to exactly 0.0
    qz, sz = kv_quantize_rows_fp8(jnp.zeros((1, 2, 16)))
    assert (np.asarray(kv_dequantize_fp8(qz, sz)) == 0.0).all()


def test_fp8_pool_geometry_between_fp32_and_int8():
    """fp8 stores 1-byte values with ONE f32 sidecar row per pool
    (absmax scale — no midpoint), vs int8's two (scale + mid): fp8
    pages are strictly cheaper than int8 pages and far cheaper than
    fp32."""
    def mk(kvd):
        return PagedKVCache(2, 2, 16, page_size=8, num_pages=4,
                            max_pages_per_seq=2, max_slots=2,
                            kv_dtype=kvd)
    fp32, fp8, i8 = mk(None), mk("fp8_e4m3"), mk("int8")
    assert fp8.kv_bytes_per_token < fp32.kv_bytes_per_token
    assert fp8.num_sidecars == 2 and i8.num_sidecars == 4
    assert fp8.kv_bytes_per_token < i8.kv_bytes_per_token
    assert fp8.page_bytes < i8.page_bytes
    # the pools really are fp8
    import jax.numpy as jnp
    k_pool = fp8.pools[0]
    assert k_pool.dtype == jnp.float8_e4m3fn


@pytest.fixture(scope="module")
def fp8_session():
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    from mxnet_tpu.serving.decode import DecodeSession
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8, 16),
                         page_size=8, kv_dtype="fp8_e4m3")
    yield sess
    sess.close(drain=False)


def test_fp8_session_deterministic_and_shared(fp8_session):
    sess = fp8_session
    assert sess.cache.quantized and sess.stats()["kv_dtype"] == "fp8_e4m3"
    p = _prompt(3, 6, 12)
    r1 = sess.generate(p, max_new_tokens=5, temperature=0.8, seed=4,
                       timeout=120)
    r2 = sess.generate(p, max_new_tokens=5, temperature=0.8, seed=4,
                       timeout=120)
    # fp8 quantization is elementwise-deterministic: the shared-vs-cold
    # bitwise contract holds exactly like fp32/int8 (r2 rode the index)
    assert r1.token_ids == r2.token_ids
    assert sess.stats()["prefix_hits"] >= 1
    assert sess.cache.pages_in_use == 0


# ------------------------------------- speculative decoding (ISSUE 20)
from mxnet_tpu.serving.decode import (Drafter, ModelDrafter,  # noqa: E402
                                      NgramDrafter, SpecState)


@pytest.fixture(scope="module")
def spec_runtime():
    """One warmed speculative runtime (verify ladder k=3) shared by the
    whole speculative block — its own net so reference schedulers built
    on it are exactly comparable."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1, 2, 4), seq_buckets=(8, 16),
                       page_size=8, spec_buckets=(3,))
    yield rt


def _rep_prompt(i, n=9):
    """Motif-cycling prompt — the workload prompt-lookup drafting eats."""
    rng = np.random.RandomState(2000 + i)
    motif = list(rng.randint(1, VOCAB, 3))
    return (motif * ((n // 3) + 1))[:n]


def _spec_reqs(n=10):
    return [dict(prompt=_rep_prompt(i), max_new_tokens=4 + i % 5,
                 temperature=0.7 * (i % 3 == 0), seed=500 + i)
            for i in range(n)]


def _reference(spec_runtime, reqs):
    """Non-speculative streams from a drafterless scheduler on the SAME
    runtime (plain step programs, same weights)."""
    s = DecodeScheduler(spec_runtime)
    try:
        return [s.generate(timeout=120, **r).token_ids for r in reqs]
    finally:
        s.close(drain=False, timeout=10.0)


def test_spec_state_adapts_from_own_window():
    st = SpecState(2, 4)
    for _ in range(3):
        st.observe(2, 2)
    assert st.k == 2                      # needs >= 4 observations
    st.observe(2, 2)
    assert st.k == 3                      # hot window grows
    st.observe(3, 3)
    assert st.k == 4 and st.acceptance_rate == 1.0
    st.observe(4, 4)
    assert st.k == 4                      # capped at k_max
    cold = SpecState(3, 4)
    for _ in range(6):
        cold.observe(3, 0)
    assert cold.k == 1                    # shrinks, floors at 1
    cold.observe(0, 0)                    # zero-proposal rounds ignored
    assert cold.k == 1


def test_ngram_drafter_proposes_cycle_continuation():
    class R:
        prompt = np.array([5, 9, 2, 5, 9, 2, 5], "int32")
        tokens = []
    d = NgramDrafter()
    got = d.propose(R(), 3)
    assert got.tolist() == [9, 2, 5]      # continuation of latest [5]->...
    # longest suffix wins: trailing [2, 5] matches at position 2
    class R2:
        prompt = np.array([1, 2, 3, 4], "int32")
        tokens = []
    assert d.propose(R2(), 3).size == 0   # no repeat: no draft


def test_spec_continuous_and_solo_bitwise_with_zero_misses(spec_runtime):
    """THE tentpole contract: speculative streams — greedy and sampled,
    solo and continuous-batched, under donation+slots sanitizers — are
    bitwise the non-speculative streams, with zero steady-state compile
    misses and zero leaks."""
    reqs = _spec_reqs()
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    s = DecodeScheduler(spec_runtime, drafter=NgramDrafter(), spec_k=3)
    try:
        with sanitizer.scope("donation,slots"):
            solo = [s.generate(timeout=120, **r).token_ids for r in reqs]
            assert solo == ref
            spec_runtime.cache.drop_prefix_cache()
            telemetry.enable()
            telemetry.reset()
            futs = []
            for i, r in enumerate(reqs):
                futs.append(s.submit(**r))
                time.sleep(0.002 * (i % 4))
            cont = [f.result(120).token_ids for f in futs]
            assert sanitizer.stats()["violations"] == 0
        snap = telemetry.snapshot()["counters"]
        telemetry.disable()
    finally:
        sanitizer.reset()
        s.close(drain=False, timeout=10.0)
    assert cont == ref
    assert not snap.get("decode.compile_miss"), snap
    assert snap.get("decode.spec_steps", 0) >= 1
    assert snap.get("decode.spec_accepted", 0) >= 1   # drafting worked
    assert spec_runtime.cache.pages_in_use == 0
    assert spec_runtime.cache.slots_in_use == 0


def test_spec_mixed_batch_with_non_spec_rows(spec_runtime):
    """Speculating and opted-out requests share the same boundary: the
    opted-out rows ride the verify with n_draft=0 (bitwise the plain
    step) and every stream still matches the non-spec reference."""
    reqs = _spec_reqs(8)
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    s = DecodeScheduler(spec_runtime, drafter=NgramDrafter(), spec_k=3)
    try:
        futs = [s.submit(speculate=(i % 2 == 0), **r)
                for i, r in enumerate(reqs)]
        got = [f.result(120).token_ids for f in futs]
    finally:
        s.close(drain=False, timeout=10.0)
    assert got == ref


class _ScriptedDrafter(Drafter):
    """Drafts from a scripted continuation table (prompt tuple -> the
    known reference stream), optionally corrupted — the deterministic
    way to pin acceptance behavior."""

    name = "scripted"

    def __init__(self, table, corrupt=False, overshoot=False):
        self.table = table
        self.corrupt = corrupt
        self.overshoot = overshoot

    def propose(self, req, k):
        ref = self.table[tuple(int(t) for t in req.prompt)]
        done = len(req.tokens)
        if self.overshoot:
            k = k + 7          # deliberately ignore the budget cap
        cont = np.asarray(ref[done:done + k], "int32")
        if self.corrupt and cont.size:
            cont = (cont + 1) % VOCAB       # never equals the target
        return cont


def _table(reqs, ref):
    return {tuple(r["prompt"]): t for r, t in zip(reqs, ref)}


def test_spec_oracle_drafts_commit_bonus_tokens(spec_runtime):
    """All-accepted rounds commit k+1 tokens (the bonus) and finish in
    far fewer verify steps than tokens; rejected-at-position-0 rounds
    still emit exactly the target's token. Both streams stay bitwise."""
    reqs = _spec_reqs(4)
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    telemetry.enable()
    for drafter, expect_accepts in (
            (_ScriptedDrafter(_table(reqs, ref)), True),
            (_ScriptedDrafter(_table(reqs, ref), corrupt=True), False)):
        telemetry.reset()
        s = DecodeScheduler(spec_runtime, drafter=drafter, spec_k=3)
        try:
            got = [s.generate(timeout=120, **r).token_ids for r in reqs]
        finally:
            s.close(drain=False, timeout=10.0)
        assert got == ref
        snap = telemetry.snapshot()["counters"]
        if expect_accepts:
            assert snap.get("decode.spec_bonus", 0) >= 1
            assert snap["decode.spec_accepted"] > 0
        else:
            # acceptance at position 0: every draft token mismatches,
            # every verify commits exactly one target token
            assert snap.get("decode.spec_accepted", 0) == 0
            assert snap.get("decode.spec_bonus", 0) == 0
        spec_runtime.cache.drop_prefix_cache()
    telemetry.disable()


def test_spec_draft_overshoot_is_budget_capped(spec_runtime):
    """A drafter ignoring its k (longer than the remaining budget) is
    truncated by the scheduler: writes stay inside the page
    reservation, the stream is exact, nothing leaks."""
    reqs = [dict(prompt=_rep_prompt(i), max_new_tokens=3,
                 temperature=0.0, seed=900 + i) for i in range(3)]
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    s = DecodeScheduler(
        spec_runtime,
        drafter=_ScriptedDrafter(_table(reqs, ref), overshoot=True),
        spec_k=3)
    try:
        with sanitizer.scope("donation,slots"):
            got = [s.generate(timeout=120, **r).token_ids for r in reqs]
            assert sanitizer.stats()["violations"] == 0
    finally:
        sanitizer.reset()
        s.close(drain=False, timeout=10.0)
    assert got == ref
    assert all(len(t) == 3 for t in got)
    assert spec_runtime.cache.pages_in_use == 0


def test_spec_k0_budget_falls_back_to_plain_step(spec_runtime):
    """max_new_tokens=2 leaves zero draft budget after the first token
    (k <= max_new - generated - 1 = 0): the scheduler must run the
    plain step, not a degenerate verify."""
    reqs = [dict(prompt=_rep_prompt(i), max_new_tokens=2,
                 temperature=0.0, seed=950 + i) for i in range(3)]
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    telemetry.enable()
    telemetry.reset()
    s = DecodeScheduler(spec_runtime, drafter=NgramDrafter(), spec_k=3)
    try:
        got = [s.generate(timeout=120, **r).token_ids for r in reqs]
    finally:
        s.close(drain=False, timeout=10.0)
    snap = telemetry.snapshot()["counters"]
    telemetry.disable()
    assert got == ref
    assert snap.get("decode.spec_steps", 0) == 0      # plain steps only
    assert snap.get("decode.steps", 0) >= 1


def test_spec_prefix_hit_session_speculates(spec_runtime):
    """A full-prompt prefix hit (admission IS the first token) must
    still enter speculative mode for its decode steps — and stay
    bitwise with the cold non-spec stream for the same (prompt, seed)."""
    p = _rep_prompt(7)
    kw = dict(max_new_tokens=6, temperature=0.8, seed=777)
    ref = _reference(spec_runtime, [dict(prompt=p, **kw)])[0]
    spec_runtime.cache.drop_prefix_cache()
    telemetry.enable()
    telemetry.reset()
    s = DecodeScheduler(spec_runtime, drafter=NgramDrafter(), spec_k=3)
    try:
        first = s.generate(p, timeout=120, **kw).token_ids   # publishes
        hit = s.generate(p, timeout=120, **kw).token_ids     # prefix hit
    finally:
        s.close(drain=False, timeout=10.0)
    snap = telemetry.snapshot()["counters"]
    telemetry.disable()
    assert first == ref and hit == ref
    assert snap.get("decode.prefix_hits", 0) >= 1
    assert snap.get("decode.spec_steps", 0) >= 1


def test_spec_drafter_failure_degrades_not_fails(spec_runtime):
    """Any drafter exception degrades the affected boundary/request to
    plain decode — requests never fail because a draft misfired."""
    class Exploding(Drafter):
        def __init__(self):
            self.calls = 0

        def propose_batch(self, reqs, ks):
            self.calls += 1
            raise RuntimeError("draft boom")

    reqs = _spec_reqs(3)
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    d = Exploding()
    s = DecodeScheduler(spec_runtime, drafter=d, spec_k=3)
    try:
        got = [s.generate(timeout=120, **r).token_ids for r in reqs]
    finally:
        s.close(drain=False, timeout=10.0)
    assert got == ref and d.calls >= 1
    assert spec_runtime.cache.pages_in_use == 0


def test_model_drafter_self_draft_high_acceptance(spec_runtime):
    """ModelDrafter with the TARGET net as its own draft model: greedy
    requests accept every draft (the drafter computes exactly the
    target's argmax), so verify rounds commit bonus tokens — and its
    private KV cache frees every slot on detach."""
    reqs = [dict(prompt=_rep_prompt(i), max_new_tokens=7,
                 temperature=0.0, seed=600 + i) for i in range(4)]
    ref = _reference(spec_runtime, reqs)
    spec_runtime.cache.drop_prefix_cache()
    telemetry.enable()
    telemetry.reset()
    d = ModelDrafter(spec_runtime.block)
    s = DecodeScheduler(spec_runtime, drafter=d, spec_k=3)
    try:
        got = [s.generate(timeout=300, **r).token_ids for r in reqs]
    finally:
        s.close(drain=False, timeout=10.0)
    snap = telemetry.snapshot()["counters"]
    telemetry.disable()
    assert got == ref
    assert snap.get("decode.spec_bonus", 0) >= 1
    acc = snap.get("decode.spec_accepted", 0)
    prop = snap.get("decode.spec_proposed", 0)
    assert prop > 0 and acc / prop > 0.8          # greedy self-draft
    assert d.runtime.cache.stats()["pages_in_use"] == 0
    assert d.runtime.cache.stats()["slots_in_use"] == 0
    assert spec_runtime.cache.pages_in_use == 0


def test_spec_validation_errors(spec_runtime, runtime):
    with pytest.raises(ValueError, match="spec_buckets"):
        DecodeScheduler(runtime, drafter=NgramDrafter(), start=False)
    with pytest.raises(ValueError, match="spec_k"):
        DecodeScheduler(spec_runtime, drafter=NgramDrafter(), spec_k=9,
                        start=False)
    s = DecodeScheduler(spec_runtime)          # no drafter
    try:
        with pytest.raises(ValueError, match="no drafter"):
            s.submit(_rep_prompt(0), speculate=True)
    finally:
        s.close(drain=False, timeout=10.0)
    with pytest.raises(ValueError, match="unknown drafter"):
        DecodeScheduler(spec_runtime, drafter="nope", start=False)
