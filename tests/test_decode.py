"""mxnet_tpu.serving.decode: paged KV cache, 2-D prefill ladder, continuous
batching (ISSUE 11 tentpole + satellites).

The heart of the file is the no-recompile / bitwise-parity contract test:
a mixed-prompt-length workload with requests joining and finishing across
step boundaries must (a) take zero steady-state ``decode.compile_miss``
and (b) hand every request tokens bitwise-identical to running it solo.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import StaleKVSlotError, StaleSlotError, sanitizer
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.faults import InjectedFault
from mxnet_tpu.serving import RequestRejected
from mxnet_tpu.serving.decode import (DecodeRuntime, DecodeScheduler,
                                      GenerationResult, KVCacheExhausted,
                                      PagedKVCache, get_decode_model,
                                      pages_needed, seq_bucket_ladder)

VOCAB = 61


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


@pytest.fixture(scope="module")
def runtime():
    """One warmed runtime for the whole module (compiles are the cost)."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1, 2, 4), seq_buckets=(8, 16),
                       page_size=8)
    yield rt


@pytest.fixture(scope="module")
def tight_runtime():
    """Tiny KV pool (3 usable pages) for exhaustion-path tests."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=2)
    net.initialize()
    cache = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                         page_size=4, num_pages=4, max_pages_per_seq=4,
                         max_slots=2)
    rt = DecodeRuntime(net, cache=cache, batch_buckets=(1, 2),
                       seq_buckets=(8,))
    yield rt


@pytest.fixture
def sched(runtime):
    s = DecodeScheduler(runtime)
    yield s
    s.close(drain=False, timeout=10.0)
    assert runtime.cache.pages_in_use == 0, "leaked KV pages"
    assert runtime.cache.slots_in_use == 0, "leaked KV slots"


def _prompt(i, lo=1, hi=14):
    rng = np.random.RandomState(1000 + i)
    return list(rng.randint(1, VOCAB, lo + (i * 3) % (hi - lo + 1)))


# ------------------------------------------------------------- page math
def test_pages_needed():
    # written positions = prompt + max_new - 1 (last token never re-encoded)
    assert pages_needed(3, 1, 8) == 1
    assert pages_needed(8, 1, 8) == 1
    assert pages_needed(8, 2, 8) == 2
    assert pages_needed(9, 8, 8) == 2
    assert pages_needed(1, 16, 8) == 2


def test_seq_bucket_ladder():
    assert seq_bucket_ladder(64) == (8, 16, 32, 64)
    assert seq_bucket_ladder(48) == (8, 16, 32, 48)
    assert seq_bucket_ladder(8) == (8,)
    assert seq_bucket_ladder(4) == (4,)
    with pytest.raises(ValueError):
        seq_bucket_ladder(0)


# ------------------------------------------------------------- KV cache
def test_kv_cache_alloc_free_generations():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=4,
                     max_slots=3)
    assert c.usable_pages == 8 and c.context_length == 16
    a = c.alloc(3)
    b = c.alloc(4)
    assert c.pages_in_use == 7 and c.slots_in_use == 2
    assert 0 not in a.pages and 0 not in b.pages          # trash reserved
    assert not (set(a.pages) & set(b.pages))
    assert len(a.page_table) == 4 and a.page_table[3] == 0  # trash-padded
    with pytest.raises(KVCacheExhausted):
        c.alloc(2)                                         # 1 page free
    gen = c.generation(a.slot_id)
    c.free(a)
    assert c.generation(a.slot_id) == gen + 1              # bumped on free
    with pytest.raises(ValueError):
        c.free(a)                                          # double free
    c.free(b)
    assert c.pages_in_use == 0 and c.slots_in_use == 0
    with pytest.raises(ValueError):
        c.alloc(5)                                         # > max_pages_per_seq
    with pytest.raises(ValueError):
        PagedKVCache(2, 2, 16, num_pages=1)                # no room for trash


def test_kv_cache_slot_exhaustion():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2,
                     max_slots=1)
    a = c.alloc(1)
    with pytest.raises(KVCacheExhausted):
        c.alloc(1)                                         # slots, not pages
    c.free(a)
    c.alloc(1)


def test_kv_alloc_fault_injectable():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2)
    with faults.scope("decode.kv_alloc:fail"):
        with pytest.raises(InjectedFault):
            c.alloc(1)
    c.free(c.alloc(1))                                     # healthy after


def test_stale_kv_slot_sanitizer():
    c = PagedKVCache(2, 2, 16, page_size=4, num_pages=9, max_pages_per_seq=2)
    with sanitizer.scope("slots"):
        slot = c.alloc(1)
        c.check_slot(slot)                                 # live: fine
        c.free(slot)
        with pytest.raises(StaleKVSlotError) as ei:
            c.check_slot(slot)
        assert "decode.kv_alloc" in str(ei.value)          # site named
        assert isinstance(ei.value, StaleSlotError)        # slots family
    sanitizer.reset()
    # sanitizer off: the check is a no-op (one attribute read)
    slot = c.alloc(1)
    c.free(slot)
    c.check_slot(slot)


# ----------------------------------------------------------- runtime/ladder
def test_runtime_ladders_and_validation(runtime):
    assert runtime.batch_bucket_for(3) == 4
    assert runtime.seq_bucket_for(9) == 16
    with pytest.raises(ValueError):
        runtime.batch_bucket_for(5)
    with pytest.raises(ValueError):
        runtime.seq_bucket_for(17)
    net = runtime.block
    # cache context must fit the model's position table
    big = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                       page_size=8, num_pages=17, max_pages_per_seq=8)
    with pytest.raises(ValueError):
        DecodeRuntime(net, cache=big, warm=False)
    small = PagedKVCache(net.num_layers, net.num_heads, net.head_dim,
                         page_size=8, num_pages=9, max_pages_per_seq=4,
                         max_slots=2)
    with pytest.raises(ValueError):                        # slots < max batch
        DecodeRuntime(net, cache=small, batch_buckets=(1, 4), warm=False)


def test_model_validation():
    with pytest.raises(ValueError):
        get_decode_model("decode_tiny", units=30, num_heads=4)


def test_default_cache_geometry_non_multiple_max_length():
    """Default geometry floors max_length/page_size: the derived context
    never exceeds the model's position table."""
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=20,
                           units=32, num_heads=2)
    net.initialize()
    rt = DecodeRuntime(net, batch_buckets=(1,), seq_buckets=(8,),
                       page_size=8, warm=False)
    assert rt.cache.context_length == 16                   # 20 // 8 pages
    with pytest.raises(ValueError):
        DecodeRuntime(net, batch_buckets=(1,), page_size=32, warm=False)


# ------------------------------------------------------------- submit plane
def test_submit_validation(sched):
    with pytest.raises(ValueError):
        sched.submit([])                                   # empty
    with pytest.raises(ValueError):
        sched.submit(list(range(1, 18)))                   # > max seq bucket
    with pytest.raises(ValueError):
        sched.submit([VOCAB + 3])                          # id out of range
    with pytest.raises(ValueError):
        sched.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):
        sched.submit([1] * 16, max_new_tokens=32)          # context overflow


def test_kv_never_fits_shed(tight_runtime):
    s = DecodeScheduler(tight_runtime)
    try:
        # 4 pages needed, 3 usable: could never be admitted
        with pytest.raises(RequestRejected) as ei:
            s.submit([1] * 8, max_new_tokens=8)
        assert ei.value.reason == "kv_exhausted"
    finally:
        s.close(drain=False, timeout=10.0)


def test_kv_exhaustion_waits_then_completes(tight_runtime):
    s = DecodeScheduler(tight_runtime)
    try:
        # each needs 2 of the 3 usable pages: the second waits for the
        # first eviction, then completes — and nothing leaks
        f1 = s.submit(_prompt(1, 4, 4), max_new_tokens=5, seed=1)
        f2 = s.submit(_prompt(2, 4, 4), max_new_tokens=5, seed=2)
        assert len(f1.result(60).token_ids) == 5
        assert len(f2.result(60).token_ids) == 5
    finally:
        s.close(drain=True, timeout=30.0)
    assert tight_runtime.cache.pages_in_use == 0


# ------------------------------------------------------------ generation
def test_generate_deterministic(sched):
    r1 = sched.generate([5, 9, 2], max_new_tokens=6, seed=7, timeout=60)
    r2 = sched.generate([5, 9, 2], max_new_tokens=6, seed=7, timeout=60)
    assert isinstance(r1, GenerationResult)
    assert r1.token_ids == r2.token_ids
    assert r1.finish_reason == "length" and len(r1.token_ids) == 6
    assert r1.prompt_len == 3 and r1.ttft_ms is not None
    t1 = sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                        seed=11, timeout=60)
    t2 = sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                        seed=11, timeout=60)
    assert t1.token_ids == t2.token_ids                    # same seed
    streams = [sched.generate([5, 9, 2], max_new_tokens=8, temperature=0.9,
                              seed=s, timeout=60).token_ids
               for s in (21, 22, 23)]
    assert len({tuple(s) for s in streams}) > 1            # seeds matter


def test_eos_stops_early(sched):
    ref = sched.generate([3, 1, 4, 1, 5], max_new_tokens=6, seed=0,
                         timeout=60).token_ids
    eos = ref[-1]
    idx = ref.index(eos)
    out = sched.generate([3, 1, 4, 1, 5], max_new_tokens=6, seed=0,
                         eos_id=eos, timeout=60)
    assert out.finish_reason == "eos"
    assert out.token_ids == ref[:idx + 1]


def test_cancelled_request_evicted(sched):
    # cancel while still queued behind a full batch: slot is never held
    blockers = [sched.submit(_prompt(i, 6, 6), max_new_tokens=16, seed=i)
                for i in range(4)]
    victim = sched.submit([1, 2], max_new_tokens=16)
    victim.cancel()
    [b.result(60) for b in blockers]
    assert victim.cancelled()


# ------------------------------------- THE no-recompile / parity contract
def test_continuous_batching_bitwise_parity_and_zero_misses(runtime):
    reqs = [dict(prompt=_prompt(i), max_new_tokens=3 + i % 6,
                 temperature=0.7 * (i % 3 == 0), seed=100 + i)
            for i in range(12)]
    s = DecodeScheduler(runtime)
    try:
        # solo reference: one request at a time (batch bucket 1)
        solo = [s.generate(timeout=120, **r).token_ids for r in reqs]
        # continuous: staggered arrivals join the running batch
        telemetry.enable()
        telemetry.reset()
        futs = []

        def feed():
            for i, r in enumerate(reqs):
                futs.append(s.submit(**r))
                time.sleep(0.002 * (i % 4))

        t = threading.Thread(target=feed)
        t.start()
        t.join()
        cont = [f.result(120).token_ids for f in futs]
        snap = telemetry.snapshot()["counters"]
        telemetry.disable()
    finally:
        s.close(drain=False, timeout=10.0)
    for i, (a, b) in enumerate(zip(solo, cont)):
        assert a == b, f"request {i} diverged: solo={a} continuous={b}"
    assert not snap.get("decode.compile_miss"), snap
    assert snap.get("decode.joins", 0) >= 1          # genuinely continuous
    assert snap["decode.evictions"] == len(reqs)
    assert runtime.cache.pages_in_use == 0, "leaked KV pages"
    assert runtime.cache.slots_in_use == 0, "leaked KV slots"


def test_sanitizer_clean_continuous_run(runtime):
    s = DecodeScheduler(runtime)
    try:
        with sanitizer.scope("donation,slots"):
            futs = [s.submit(_prompt(i), max_new_tokens=4, seed=i)
                    for i in range(6)]
            [f.result(60) for f in futs]
            assert sanitizer.stats()["violations"] == 0
    finally:
        sanitizer.reset()
        s.close(drain=False, timeout=10.0)


def test_mesh_sharded_kv_cache_parity():
    """NamedSharding over the heads axis: the cache scales with the mesh
    without changing scheduler code, and decode output is unchanged."""
    import jax
    from jax.sharding import Mesh, NamedSharding
    from mxnet_tpu.serving.decode import DecodeSession
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("data", "model"))
    net = get_decode_model("decode_tiny", vocab_size=VOCAB, max_length=32,
                           units=32, num_heads=4)
    net.initialize()
    sess = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                         page_size=8, mesh=mesh)
    try:
        assert isinstance(sess.cache.k_pages.sharding, NamedSharding)
        assert "model" in str(sess.cache.k_pages.sharding.spec)
        sharded = sess.generate([5, 9, 2], max_new_tokens=5, seed=7,
                                timeout=120).token_ids
    finally:
        sess.close(drain=False)
    plain = DecodeSession(net, batch_buckets=(1, 2), seq_buckets=(8,),
                          page_size=8)
    try:
        assert plain.generate([5, 9, 2], max_new_tokens=5, seed=7,
                              timeout=120).token_ids == sharded
    finally:
        plain.close(drain=False)


# --------------------------------------------------------- shed/backpressure
def test_deadline_shed_while_waiting(sched):
    # 4 long sequences fill every batch row; a deadlined request behind
    # them expires at the next admission sweep instead of hanging
    blockers = [sched.submit(_prompt(i, 6, 6), max_new_tokens=20, seed=i)
                for i in range(4)]
    while sched.active() < 4 and not all(b.done() for b in blockers):
        time.sleep(0.001)
    late = sched.submit([1, 2, 3], max_new_tokens=4, deadline_ms=2)
    with pytest.raises(RequestRejected) as ei:
        late.result(60)
    assert ei.value.reason == "deadline"
    [b.result(120) for b in blockers]


def test_queue_backpressure_deadline(runtime):
    s = DecodeScheduler(runtime, queue_depth=1, start=False)
    try:
        s.submit([1, 2], max_new_tokens=2)
        with pytest.raises(RequestRejected) as ei:
            s.submit([3, 4], max_new_tokens=2, deadline_ms=30)
        assert ei.value.reason == "deadline"
    finally:
        s.close(drain=True, timeout=30.0)


def test_close_drain_false_rejects(runtime):
    s = DecodeScheduler(runtime, start=False)
    f = s.submit([1, 2, 3], max_new_tokens=4)
    s.close(drain=False)
    with pytest.raises(RequestRejected) as ei:
        f.result(5)
    assert ei.value.reason == "shutdown"
    with pytest.raises(RequestRejected):
        s.submit([1], max_new_tokens=1)
    assert runtime.cache.pages_in_use == 0


def test_close_drain_true_completes(runtime):
    s = DecodeScheduler(runtime, start=False)
    futs = [s.submit(_prompt(i), max_new_tokens=3, seed=i) for i in range(5)]
    s.close(drain=True, timeout=60.0)
    for f in futs:
        assert len(f.result(0).token_ids) == 3
    assert runtime.cache.pages_in_use == 0


# ------------------------------------------------------------ fault drills
def test_step_fault_fails_batch_and_recovers(runtime):
    s = DecodeScheduler(runtime, breaker_threshold=None)
    try:
        with faults.scope("decode.step:fail"):
            f = s.submit([1, 2, 3], max_new_tokens=4, seed=0)
            with pytest.raises(InjectedFault):
                f.result(60)
        assert runtime.cache.pages_in_use == 0             # slot freed
        ok = s.generate([1, 2, 3], max_new_tokens=4, seed=0, timeout=60)
        assert len(ok.token_ids) == 4                      # worker survived
        assert s.steps_failed == 1
    finally:
        s.close(drain=False, timeout=10.0)


def test_kv_alloc_fault_sheds_request_only(runtime):
    s = DecodeScheduler(runtime)
    try:
        with faults.scope("decode.kv_alloc:fail"):
            f = s.submit([1, 2], max_new_tokens=3, seed=0)
            with pytest.raises(InjectedFault):
                f.result(60)
        ok = s.generate([1, 2], max_new_tokens=3, seed=0, timeout=60)
        assert len(ok.token_ids) == 3
    finally:
        s.close(drain=False, timeout=10.0)


def test_circuit_breaker_opens_and_probes(runtime):
    s = DecodeScheduler(runtime, breaker_threshold=1,
                        breaker_cooldown_ms=150.0)
    try:
        with faults.scope("decode.step:fail"):
            f = s.submit([1, 2, 3], max_new_tokens=4)
            with pytest.raises(InjectedFault):
                f.result(60)
        assert not s.healthy
        with pytest.raises(RequestRejected) as ei:
            s.submit([1], max_new_tokens=2)
        assert ei.value.reason == "unhealthy"
        time.sleep(0.2)                                    # cooldown expires
        assert s.healthy
        assert len(s.generate([1, 2, 3], max_new_tokens=3,
                              timeout=60).token_ids) == 3
    finally:
        s.close(drain=False, timeout=10.0)


# ------------------------------------------------------------- telemetry
def test_decode_telemetry_counters(runtime):
    telemetry.enable()
    s = DecodeScheduler(runtime)
    try:
        futs = [s.submit(_prompt(i), max_new_tokens=4, seed=i)
                for i in range(5)]
        [f.result(60) for f in futs]
    finally:
        s.close(drain=False, timeout=10.0)
    snap = telemetry.snapshot()
    c = snap["counters"]
    assert c["decode.requests"] == 5
    assert c["decode.prefills"] == 5
    assert c["decode.tokens"] == 20
    assert c["decode.evictions"] == 5
    assert c["decode.ttft_ms"] > 0
    assert c.get("decode.compile_miss") in (None, 0)
    assert "decode.kv_occupancy" in snap["gauges"]
