"""Channel-last (NHWC/NWC) layout contracts.

The reference supports a ``layout`` parameter on Convolution / Pooling
(``src/operator/nn/convolution.cc`` param layout, NHWC weight layout
(num_filter, *kernel, C/g)) and ``axis`` on BatchNorm.  On TPU channel-last
is the MXU/VPU-native choice, so these are first-class here: every op must
produce exactly the channel-first result under a transpose.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


RTOL, ATOL = 2e-5, 2e-5


def _rng():
    return np.random.RandomState(7)


def test_conv_nhwc_matches_nchw():
    rng = _rng()
    x = rng.randn(2, 5, 9, 9).astype("float32")
    w = rng.randn(7, 5, 3, 3).astype("float32")
    b = rng.randn(7).astype("float32")
    y1 = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                           kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           num_filter=7).asnumpy()
    y2 = mx.nd.Convolution(mx.nd.array(x.transpose(0, 2, 3, 1)),
                           mx.nd.array(w.transpose(0, 2, 3, 1)),
                           mx.nd.array(b), kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), num_filter=7,
                           layout="NHWC").asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)


def test_conv_nhwc_grouped():
    rng = _rng()
    x = rng.randn(2, 10, 8, 8).astype("float32")
    w = rng.randn(6, 5, 3, 3).astype("float32")
    y1 = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                           num_filter=6, num_group=2, no_bias=True).asnumpy()
    y2 = mx.nd.Convolution(mx.nd.array(x.transpose(0, 2, 3, 1)),
                           mx.nd.array(w.transpose(0, 2, 3, 1)),
                           kernel=(3, 3), num_filter=6, num_group=2,
                           no_bias=True, layout="NHWC").asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)


def test_conv_nwc_1d():
    rng = _rng()
    x = rng.randn(2, 5, 11).astype("float32")
    w = rng.randn(4, 5, 3).astype("float32")
    y1 = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3,),
                           num_filter=4, no_bias=True, pad=(1,)).asnumpy()
    y2 = mx.nd.Convolution(mx.nd.array(x.transpose(0, 2, 1)),
                           mx.nd.array(w.transpose(0, 2, 1)), kernel=(3,),
                           num_filter=4, no_bias=True, pad=(1,),
                           layout="NWC").asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 2, 1), RTOL, ATOL)


@pytest.mark.parametrize("pool_type,conv", [("max", "valid"),
                                            ("avg", "valid"),
                                            ("max", "full"),
                                            ("avg", "full")])
def test_pooling_nhwc(pool_type, conv):
    rng = _rng()
    x = rng.randn(2, 5, 9, 9).astype("float32")
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type=pool_type,
              pooling_convention=conv)
    y1 = mx.nd.Pooling(mx.nd.array(x), **kw).asnumpy()
    y2 = mx.nd.Pooling(mx.nd.array(x.transpose(0, 2, 3, 1)),
                       layout="NHWC", **kw).asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)


def test_pooling_nhwc_global_and_exclude_pad():
    rng = _rng()
    x = rng.randn(2, 5, 6, 6).astype("float32")
    y1 = mx.nd.Pooling(mx.nd.array(x), pool_type="avg",
                       global_pool=True).asnumpy()
    y2 = mx.nd.Pooling(mx.nd.array(x.transpose(0, 2, 3, 1)), pool_type="avg",
                       global_pool=True, layout="NHWC").asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="avg",
              count_include_pad=False)
    y1 = mx.nd.Pooling(mx.nd.array(x), **kw).asnumpy()
    y2 = mx.nd.Pooling(mx.nd.array(x.transpose(0, 2, 3, 1)), layout="NHWC",
                       **kw).asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)


def test_deconv_nhwc_matches_nchw():
    rng = _rng()
    x = rng.randn(2, 4, 6, 6).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")
    y1 = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                             stride=(2, 2), pad=(1, 1),
                             num_filter=3).asnumpy()
    y2 = mx.nd.Deconvolution(mx.nd.array(x.transpose(0, 2, 3, 1)),
                             mx.nd.array(w.transpose(0, 2, 3, 1)),
                             kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                             num_filter=3, layout="NHWC").asnumpy()
    np.testing.assert_allclose(y1, y2.transpose(0, 3, 1, 2), RTOL, ATOL)


def test_gluon_conv2d_nhwc_weight_shape_and_forward():
    rng = _rng()
    net = mx.gluon.nn.Conv2D(8, 3, padding=1, layout="NHWC", use_bias=True)
    net.initialize()
    x = mx.nd.array(rng.randn(2, 6, 6, 5).astype("float32"))
    y = net(x)
    assert y.shape == (2, 6, 6, 8)
    assert net.weight.shape == (8, 3, 3, 5)   # (O, kh, kw, I)


def test_gluon_conv_nhwc_gradient():
    rng = _rng()
    net = mx.gluon.nn.Conv2D(4, 3, padding=1, layout="NHWC", use_bias=False)
    net.initialize()
    x = mx.nd.array(rng.randn(2, 5, 5, 3).astype("float32"))
    with mx.autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()
    g = net.weight.grad()
    assert g.shape == net.weight.shape
    assert np.abs(g.asnumpy()).sum() > 0


def test_resnet_nhwc_matches_nchw_model():
    rng = _rng()
    mx.random.seed(0)
    n1 = mx.gluon.model_zoo.vision.resnet18_v1(classes=10)
    n1.initialize()
    n1(mx.nd.zeros((1, 3, 32, 32)))
    mx.random.seed(0)
    n2 = mx.gluon.model_zoo.vision.resnet18_v1(classes=10, layout="NHWC")
    n2.initialize()
    n2(mx.nd.zeros((1, 32, 32, 3)))
    p1 = {k.split("_", 1)[1]: v for k, v in n1.collect_params().items()}
    p2 = {k.split("_", 1)[1]: v for k, v in n2.collect_params().items()}
    assert set(p1) == set(p2)
    for k in p2:
        a = p1[k].data().asnumpy()
        if a.ndim == 4:
            a = a.transpose(0, 2, 3, 1)
        p2[k].set_data(mx.nd.array(a))
    x = rng.randn(2, 3, 32, 32).astype("float32")
    o1 = n1(mx.nd.array(x)).asnumpy()
    o2 = n2(mx.nd.array(x.transpose(0, 2, 3, 1))).asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=2e-4, atol=2e-4)


def test_batchnorm_training_stats_onepass_numerics():
    """The fused one-pass E[x²]−E[x]² batch statistics must match numpy's
    two-pass moments (reference batch_norm.cc semantics) to fp32 accuracy."""
    rng = _rng()
    x = (rng.randn(8, 4, 5, 5) * 3 + 50).astype("float32")   # offset mean
    gamma = rng.rand(4).astype("float32") + 0.5
    beta = rng.randn(4).astype("float32")
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    want = (x - mean[None, :, None, None]) / \
        np.sqrt(var[None, :, None, None] + 1e-3) * \
        gamma[None, :, None, None] + beta[None, :, None, None]
    with mx.autograd.record(train_mode=True):
        got = mx.nd.BatchNorm(
            mx.nd.array(x), mx.nd.array(gamma), mx.nd.array(beta),
            mx.nd.zeros((4,)), mx.nd.ones((4,)), fix_gamma=False)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=2e-4, atol=2e-4)
