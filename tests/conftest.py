"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference's trick of one op suite re-run per backend
(tests/python/gpu/test_operator_gpu.py:37-45 does set_default_context +
re-import): here the suite runs on CPU with 8 virtual devices so that all
sharding/collective paths compile and execute without TPU hardware; the same
tests run unmodified on a real TPU chip.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize (TPU tunnel) sets jax_platforms="axon,cpu" via
# jax.config at interpreter start, which overrides the env var — force CPU
# through the config API so the suite never tries to claim the TPU chip.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    """Deterministic seeds per test (reference tests/python/unittest/common.py
    @with_seed): default 0, overridable via MXNET_TEST_SEED — the knob
    tools/flakiness_checker.py varies per trial."""
    import random as _pyrandom

    import mxnet_tpu as mx

    seed = int(os.environ.get("MXNET_TEST_SEED", "0"))
    np.random.seed(seed)
    mx.random.seed(seed)
    _pyrandom.seed(seed)
    yield

