"""Pod-scale checkpointing (ISSUE 9): per-shard streaming saves with a
two-phase manifest commit, async serialization, preemption-safe training,
and elastic resharded resume.

Multi-host paths run on this CPU box as *simulated* hosts: co-writer
managers share one directory, each claiming a round-robin stripe of the 8
virtual devices by id (``host_index``/``host_count`` — host 0 owns devices
0/2/4/6, host 1 owns 1/3/5/7), driven either from threads (fast unit
coverage) or real subprocesses (`pod_ckpt_worker.py`, the acceptance
drills — including a hard-killed co-writer).
"""
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import (
    CommitBarrierTimeout, SPMDCheckpointManager,
)
from mxnet_tpu.resilience import (
    InjectedFault, PreemptionHandler, ResilientTrainer, RetryPolicy,
    TrainingPreempted, faults,
)

import pod_ckpt_worker as worker

_WORKER = os.path.join(os.path.dirname(__file__), "pod_ckpt_worker.py")


def _state_leaves(trainer):
    import jax
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(trainer._state)]


def _assert_state_equal(tr_a, tr_b):
    a, b = _state_leaves(tr_a), _state_leaves(tr_b)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert tr_a._t == tr_b._t


def _sharded_save(directory, trainer, step, host_count=2, extra=None,
                  barrier_timeout=30.0, retry=None):
    """Drive a co-writer group from threads: one manager per simulated
    host, all sharing ``directory``.  Raises the first host's error."""
    mgrs = [SPMDCheckpointManager(directory, host_index=h,
                                  host_count=host_count,
                                  barrier_timeout_s=barrier_timeout,
                                  retry=retry)
            for h in range(host_count)]
    errs = {}

    def run(h):
        try:
            mgrs[h].save(step, trainer, extra=extra)
        except BaseException as e:
            errs[h] = e

    threads = [threading.Thread(target=run, args=(h,))
               for h in range(host_count)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[min(errs)]
    return mgrs[0]


# --------------------------------------------------------------- sharded
def test_sharded_layout_roundtrip_and_continue(tmp_path):
    batches = worker.make_batches(5)
    tr = worker.build_trainer(0)
    for x, y in batches[:3]:
        tr.step(x, y)
    rng_state = mx.random.get_state()
    _sharded_save(str(tmp_path), tr, 3, extra={"note": "pod"})

    d = str(tmp_path / ("step_%010d" % 3))
    names = sorted(os.listdir(d))
    assert "manifest.json" in names and "meta.bin" in names
    assert "host-0.json" in names and "host-1.json" in names
    assert any(n.startswith("shard-0-") for n in names)
    assert any(n.startswith("shard-1-") for n in names)
    import json
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["format"] == 2 and manifest["host_count"] == 2
    # every on-disk artifact is accounted for in the manifest
    assert sorted(manifest["files"]) == [n for n in names
                                         if n != "manifest.json"]

    # each host wrote only its shards: entries are disjoint, union covers
    markers = []
    for h in (0, 1):
        with open(os.path.join(d, f"host-{h}.json")) as f:
            markers.append(json.load(f))
    keys = [{(e["leaf"], tuple(tuple(p) for p in e["index"]))
             for e in m["shards"]} for m in markers]
    assert keys[0] and keys[1] and not (keys[0] & keys[1])

    # restore resumes bitwise-identically on the same topology
    tr2 = worker.build_trainer(seed=1)
    mgr = SPMDCheckpointManager(str(tmp_path))
    mgr.restore(tr2)
    assert mgr.restored_extra == {"note": "pod"}
    _assert_state_equal(tr, tr2)
    after = [float(tr.step(x, y).asnumpy()) for x, y in batches[3:]]
    mx.random.set_state(rng_state)
    resumed = [float(tr2.step(x, y).asnumpy()) for x, y in batches[3:]]
    assert resumed == after


def test_sharded_bitwise_parity_vs_single_host_format(tmp_path):
    tr = worker.build_trainer(0)
    for x, y in worker.make_batches(2):
        tr.step(x, y)
    single = SPMDCheckpointManager(str(tmp_path / "v1"))
    single.save(2, tr, extra={"fmt": 1})
    _sharded_save(str(tmp_path / "v2"), tr, 2, extra={"fmt": 1})

    tr_v1 = worker.build_trainer(seed=3)
    tr_v2 = worker.build_trainer(seed=4)
    single.restore(tr_v1)
    SPMDCheckpointManager(str(tmp_path / "v2")).restore(tr_v2)
    _assert_state_equal(tr_v1, tr_v2)
    assert single.restored_extra == {"fmt": 1}


def test_cowriter_missing_leaves_previous_restorable(tmp_path):
    """Host 0 alone (co-writer never shows up): the barrier times out, the
    step never commits, the previous checkpoint stays the resume point."""
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    _sharded_save(str(tmp_path), tr, 1)
    expect = _state_leaves(tr)

    tr.step(*worker.make_batches(2)[1])
    solo = SPMDCheckpointManager(str(tmp_path), host_index=0, host_count=2,
                                 barrier_timeout_s=0.3)
    with pytest.raises(CommitBarrierTimeout):
        solo.save(2, tr)
    assert isinstance(CommitBarrierTimeout("x"), OSError)  # retry-filterable
    # host 0's partial is on disk, but the step is not a resume candidate
    d = str(tmp_path / ("step_%010d" % 2))
    assert os.path.exists(os.path.join(d, "host-0.json"))
    assert not os.path.exists(os.path.join(d, "manifest.json"))
    assert solo.complete_steps() == [1]
    tr3 = worker.build_trainer(seed=2)
    SPMDCheckpointManager(str(tmp_path)).restore(tr3)
    for x, y in zip(_state_leaves(tr3), expect):
        np.testing.assert_array_equal(x, y)


def test_fault_site_shard_write_never_commits(tmp_path):
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    _sharded_save(str(tmp_path), tr, 1)
    tr.step(*worker.make_batches(2)[1])
    with faults.scope("ckpt.shard_write:fail:2"):
        with pytest.raises((InjectedFault, CommitBarrierTimeout)):
            _sharded_save(str(tmp_path), tr, 2, barrier_timeout=1.0)
    mgr = SPMDCheckpointManager(str(tmp_path))
    assert mgr.complete_steps() == [1]
    mgr.restore(worker.build_trainer(seed=5))   # previous still restores


def test_fault_site_commit_barrier(tmp_path):
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    with faults.scope("ckpt.commit_barrier:fail:1"):
        with pytest.raises(InjectedFault):
            _sharded_save(str(tmp_path), tr, 1)
    assert SPMDCheckpointManager(str(tmp_path)).complete_steps() == []


def test_sharded_corrupt_shard_falls_back(tmp_path):
    batches = worker.make_batches(2)
    tr = worker.build_trainer(0)
    tr.step(*batches[0])
    _sharded_save(str(tmp_path), tr, 1)
    step1 = _state_leaves(tr)
    tr.step(*batches[1])
    _sharded_save(str(tmp_path), tr, 2)

    victim = str(tmp_path / ("step_%010d" % 2) / "shard-1-0.bin")
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))

    tr2 = worker.build_trainer(seed=1)
    SPMDCheckpointManager(str(tmp_path)).restore(tr2)
    assert tr2._t == 1                       # fell back to step 1
    for x, y in zip(_state_leaves(tr2), step1):
        np.testing.assert_array_equal(x, y)


def test_partial_resave_never_invalidates_committed_bytes(tmp_path):
    """Crashed attempt -> restart re-saves the same step: a co-writer
    whose phase 1 already completed must leave its durable files AND
    marker untouched (a manifest may be committing against them), and the
    step must still commit and restore exactly."""
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    # attempt 1: host 1 finishes its phase, "host 0 dies" before writing
    m1 = SPMDCheckpointManager(str(tmp_path), host_index=1, host_count=2,
                               barrier_timeout_s=30)
    m1.save(1, tr)
    d = str(tmp_path / ("step_%010d" % 1))
    before = {n: open(os.path.join(d, n), "rb").read()
              for n in os.listdir(d)}
    assert "host-1.json" in before and "manifest.json" not in before

    # attempt 2 (the restarted run): both hosts re-save the step
    mgr = _sharded_save(str(tmp_path), tr, 1)
    assert mgr.complete_steps() == [1]
    for n, blob in before.items():   # attempt 1's bytes are untouched
        assert open(os.path.join(d, n), "rb").read() == blob, n

    tr2 = worker.build_trainer(seed=6)
    SPMDCheckpointManager(str(tmp_path)).restore(tr2)
    _assert_state_equal(tr, tr2)


def test_retry_policy_covers_sharded_write_faults(tmp_path):
    """A transient injected shard-write fault is retried away; the barrier
    timeout is excluded via ``nonretryable``."""
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    policy = RetryPolicy(max_attempts=3, base_delay_ms=1.0, jitter=0.0,
                         nonretryable=(CommitBarrierTimeout,), seed=0)
    with faults.scope("ckpt.shard_write:fail:1"):
        mgr = _sharded_save(str(tmp_path), tr, 1, retry=policy)
    assert mgr.complete_steps() == [1]


# ----------------------------------------------------------------- async
def test_async_save_parity_after_donating_steps(tmp_path):
    batches = worker.make_batches(6)
    tr = worker.build_trainer(0)
    for x, y in batches[:3]:
        tr.step(x, y)
    expect = _state_leaves(tr)               # host snapshot before async
    mgr = SPMDCheckpointManager(str(tmp_path))
    mgr.save(3, tr, extra={"async": True}, sync=False)
    for x, y in batches[3:]:                 # donates the live state
        tr.step(x, y)
    mgr.wait_for_save()
    assert not mgr.async_inflight
    assert mgr.latest_step() == 3

    tr2 = worker.build_trainer(seed=1)
    mgr.restore(tr2)
    assert mgr.restored_extra == {"async": True}
    for x, y in zip(_state_leaves(tr2), expect):
        np.testing.assert_array_equal(x, y)

    # at-most-one-inflight: back-to-back async saves all land
    mgr.save(4, tr, sync=False)
    mgr.save(5, tr, sync=False)
    mgr.wait_for_save()
    assert set(mgr.complete_steps()) >= {3, 4, 5}


def test_async_save_donation_sanitizer_clean(tmp_path):
    from mxnet_tpu.analysis import sanitizer as san

    batches = worker.make_batches(5)
    before = san.stats()["violations"]
    with san.scope("donation"):
        tr = worker.build_trainer(0)
        for x, y in batches[:2]:
            tr.step(x, y)
        mgr = SPMDCheckpointManager(str(tmp_path))
        mgr.save(2, tr, sync=False)
        for x, y in batches[2:]:
            tr.step(x, y)
        mgr.wait_for_save()
        assert san.stats()["violations"] == before
    assert mgr.latest_step() == 2


def test_fault_site_async_serialize_surfaces_on_wait(tmp_path):
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    mgr = SPMDCheckpointManager(str(tmp_path))
    with faults.scope("ckpt.async_serialize:fail:1"):
        mgr.save(1, tr, sync=False)
        with pytest.raises(InjectedFault):
            mgr.wait_for_save()
    assert mgr.latest_step() is None
    mgr.wait_for_save()                      # error is surfaced only once
    mgr.save(1, tr)                          # and a clean sync save works
    assert mgr.latest_step() == 1


def test_resilient_trainer_async_cadence_and_absorbed_failure(tmp_path):
    batches = worker.make_batches(12)
    rt = ResilientTrainer(worker.build_trainer(0), str(tmp_path),
                          save_every=5, async_save=True)
    with faults.scope("ckpt.async_serialize:fail:1"):
        for x, y in batches:
            rt.step(x, y)
        rt.flush()
    assert rt.wait_for_save()
    # the first cadence save (step 5) died in the background and was
    # absorbed; the next one landed
    assert rt.checkpoint_failures == 1
    assert rt.manager.latest_step() == 10


# ------------------------------------------------------------ preemption
def test_preemption_trigger_resilient_trainer_bitwise_resume(tmp_path):
    n = 8
    ref = worker.reference_losses(n)

    handler = PreemptionHandler(install=False)   # no real signal handlers
    rt = ResilientTrainer(worker.build_trainer(0), str(tmp_path),
                          save_every=100, preemption=handler)
    batches = worker.make_batches(n)
    first = [float(rt.step(x, y).asnumpy()) for x, y in batches[:5]]
    assert first == ref[:5]
    handler.trigger()
    with pytest.raises(TrainingPreempted) as ei:
        rt.step(*batches[5])
    assert ei.value.code == 0                # clean exit for the scheduler
    assert ei.value.step == 5 and ei.value.checkpoint_step == 5
    assert rt.manager.latest_step() == 5

    rt2 = ResilientTrainer(worker.build_trainer(9), str(tmp_path),
                           save_every=100)
    assert rt2.resumed_from == 5
    resumed = [float(rt2.step(x, y).asnumpy()) for x, y in batches[5:]]
    assert resumed == ref[5:]                # bitwise-identical resume

    # preemption=False means OFF, not a broken handler
    off = ResilientTrainer(worker.build_trainer(9),
                           str(tmp_path / "off"), preemption=False)
    assert off.preemption is None
    off.step(*batches[0])
    off.close()                              # no-op without a handler

    # preemption=True: the trainer owns the handler, close() restores
    # the pre-existing signal disposition after training
    before_h = signal.getsignal(signal.SIGTERM)
    own = ResilientTrainer(worker.build_trainer(9),
                           str(tmp_path / "own"), preemption=True)
    assert signal.getsignal(signal.SIGTERM) != before_h
    own.close()
    assert signal.getsignal(signal.SIGTERM) == before_h


def test_preemption_real_sigterm(tmp_path):
    handler = PreemptionHandler(signals=(signal.SIGTERM,))
    try:
        rt = ResilientTrainer(worker.build_trainer(0), str(tmp_path),
                              save_every=100, preemption=handler)
        batches = worker.make_batches(3)
        rt.step(*batches[0])                 # the step in flight finishes
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(TrainingPreempted):
            rt.step(*batches[1])             # the next boundary exits
        assert handler.signum == signal.SIGTERM
        assert rt.manager.latest_step() == 1
    finally:
        handler.uninstall()


def test_spmd_trainer_install_preemption(tmp_path):
    tr = worker.build_trainer(0)
    batches = worker.make_batches(2)
    tr.step(*batches[0])
    handler = PreemptionHandler(install=False)
    mgr = SPMDCheckpointManager(str(tmp_path))
    tr.install_preemption(handler, mgr)
    tr.step(*batches[1])
    handler.trigger()
    with pytest.raises(TrainingPreempted) as ei:
        tr.step(*batches[0])
    assert ei.value.code == 0
    assert mgr.latest_step() == 2
    tr2 = worker.build_trainer(seed=1)
    mgr.restore(tr2)
    _assert_state_equal(tr, tr2)


# --------------------------------------------------------------- elastic
def test_elastic_resume_sharded_4_to_2_devices(tmp_path):
    """A checkpoint written by a dp=4×tp=2 co-writer pair resumes on a
    dp=2×tp=1 mesh: bitwise-identical state, matching losses."""
    batches = worker.make_batches(5)
    tr = worker.build_trainer(0)             # 8 devices: dp=4 tp=2
    for x, y in batches[:3]:
        tr.step(x, y)
    rng_state = mx.random.get_state()
    _sharded_save(str(tmp_path), tr, 3)
    saved = _state_leaves(tr)
    after = [float(tr.step(x, y).asnumpy()) for x, y in batches[3:]]

    small = worker.build_trainer(seed=1, n_devices=2, dp=2, tp=1)
    SPMDCheckpointManager(str(tmp_path)).restore(small)
    assert small._t == 3
    for x, y in zip(_state_leaves(small), saved):
        np.testing.assert_array_equal(x, y)  # exact state on fewer devices
    mx.random.set_state(rng_state)
    resumed = [float(small.step(x, y).asnumpy()) for x, y in batches[3:]]
    np.testing.assert_allclose(resumed, after, rtol=1e-6, atol=1e-7)


def test_elastic_resume_single_host_format_2_to_8_devices(tmp_path):
    """Format-1 checkpoints reshard too (scale UP: 2 -> 8 devices)."""
    batches = worker.make_batches(3)
    small = worker.build_trainer(0, n_devices=2, dp=2, tp=1)
    for x, y in batches:
        small.step(x, y)
    mgr = SPMDCheckpointManager(str(tmp_path))
    mgr.save(3, small)
    big = worker.build_trainer(seed=1)       # 8 devices
    mgr.restore(big)
    assert big._t == 3
    for x, y in zip(_state_leaves(big), _state_leaves(small)):
        np.testing.assert_array_equal(x, y)


# -------------------------------------------------------------- gc rules
def test_gc_sharded_step_is_one_unit_and_inflight_protected(tmp_path):
    tr = worker.build_trainer(0)
    tr.step(*worker.make_batches(1)[0])
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(5, tr)
    mgr.save(10, tr)

    # a sharded write still converging at step 7 (shards + marker, no
    # manifest, fresh mtime): a save's GC must leave it alone
    inflight = str(tmp_path / ("step_%010d" % 7))
    os.makedirs(inflight)
    open(os.path.join(inflight, "shard-1-0.bin"), "wb").write(b"x" * 64)
    open(os.path.join(inflight, "host-1.json"), "w").write("{}")
    mgr.save(11, tr)
    assert os.path.isdir(inflight), "in-flight sharded commit was collected"

    # once clearly stale (a crashed co-writer's leftovers) the whole step
    # dir — shards, markers and all — goes as one unit
    old = 1.0
    os.utime(inflight, (old, old))
    mgr.save(12, tr)
    assert not os.path.isdir(inflight)

    # format-1-style incomplete litter (no shard files) keeps the PR 4
    # behavior: collected as soon as it is older than the newest complete
    stale = str(tmp_path / ("step_%010d" % 8))
    os.makedirs(stale)
    open(os.path.join(stale, "state.bin"), "wb").write(b"junk")
    mgr.save(13, tr)
    assert not os.path.isdir(stale)


# ------------------------------------------------- subprocess acceptance
def _spawn(args):
    root = os.path.dirname(os.path.dirname(os.path.abspath(_WORKER)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, _WORKER] + args,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=root, env=env)


def test_two_process_mesh_sharded_save(tmp_path):
    """The acceptance drill: a simulated 2-process mesh completes a
    sharded save where each process writes only its shards."""
    d = str(tmp_path)
    procs = [_spawn(["--mode", "shard-save", "--dir", d, "--steps", "2",
                     "--host", f"{h}/2", "--barrier-timeout", "120"])
             for h in (1, 0)]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert all("SAVED step=2" in o for o in outs), outs

    ref = worker.build_trainer(0)
    for x, y in worker.make_batches(2):
        ref.step(x, y)
    tr = worker.build_trainer(seed=1)
    SPMDCheckpointManager(d).restore(tr)
    _assert_state_equal(ref, tr)


def test_cowriter_hard_killed_between_shard_write_and_commit(tmp_path):
    """A co-writer host hard-dies (os._exit) mid-save: the step never
    commits and the previous checkpoint restores cleanly."""
    d = str(tmp_path)
    base = worker.build_trainer(0)
    base.step(*worker.make_batches(1)[0])
    SPMDCheckpointManager(d).save(1, base)
    expect = _state_leaves(base)

    killer = _spawn(["--mode", "shard-save", "--dir", d, "--steps", "2",
                     "--host", "1/2", "--die-at", "ckpt.shard_write"])
    committer = _spawn(["--mode", "shard-save", "--dir", d, "--steps", "2",
                        "--host", "0/2", "--barrier-timeout", "10"])
    k_out = killer.communicate(timeout=300)[0]
    c_out = committer.communicate(timeout=300)[0]
    assert killer.returncode == 9 and "DYING" in k_out, k_out
    assert committer.returncode != 0, c_out
    assert "CommitBarrierTimeout" in c_out, c_out

    mgr = SPMDCheckpointManager(d)
    assert mgr.complete_steps() == [1]
    tr = worker.build_trainer(seed=2)
    mgr.restore(tr)
    for x, y in zip(_state_leaves(tr), expect):
        np.testing.assert_array_equal(x, y)
