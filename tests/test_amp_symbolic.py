"""Symbolic AMP: amp_cast/amp_multicast ops + convert_symbol rewrite
(reference ``src/operator/tensor/amp_cast.cc``,
``src/nnvm/low_precision_pass.cc:257``, ``python/mxnet/contrib/amp/amp.py``),
plus the adamw/shuffle ops the round-1 registry probe flagged
(``src/operator/contrib/adamw.cc``, ``src/operator/random/shuffle_op.cc``).
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import amp


def _convnet():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, name="c1", kernel=(3, 3), num_filter=8,
                             pad=(1, 1))
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, name="r1", act_type="relu")
    net = mx.sym.elemwise_add(
        net, mx.sym.Convolution(data, name="c2", kernel=(3, 3), num_filter=8,
                                pad=(1, 1)), name="add1")
    net = mx.sym.Pooling(net, name="gp", pool_type="avg", global_pool=True,
                         kernel=(1, 1))
    net = mx.sym.Flatten(net, name="fl")
    net = mx.sym.FullyConnected(net, name="fc", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params_for(sym, data_shape, seed=0):
    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    params = {n: mx.nd.array(rng.randn(*s) * 0.1)
              for n, s in zip(sym.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    aux = {n: mx.nd.array(np.zeros(s) if "mean" in n else np.ones(s))
           for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
    return params, aux


def _run(sym, params, aux, x):
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=x.shape)
    ex.copy_params_from(params, aux, allow_extra_params=True)
    return ex.forward(is_train=False, data=mx.nd.array(x))[0]


def test_amp_cast_op():
    a = mx.nd.amp_cast(mx.nd.ones((2, 2)), dtype="bfloat16")
    assert str(a.dtype) == "bfloat16"
    b = mx.nd.amp_cast(a, dtype="float32")
    assert b.dtype == np.float32


def test_amp_multicast_widest():
    a = mx.nd.ones((2, 2))
    b = mx.nd.ones((2, 2)).astype("bfloat16")
    oa, ob = mx.nd.amp_multicast(a, b, num_outputs=2)
    assert oa.dtype == np.float32 and ob.dtype == np.float32


def test_convert_symbol_inserts_casts_and_matches_fp32():
    net = _convnet()
    conv = amp.convert_symbol(net)
    graph = json.loads(conv.tojson())
    ops = [n["op"] for n in graph["nodes"]]
    assert "amp_cast" in ops and "amp_multicast" in ops
    # lp16 casts feed Convolution/FullyConnected; softmax inputs return fp32
    params, aux = _params_for(net, (2, 3, 8, 8))
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype("float32")
    o32 = _run(net, params, aux, x).asnumpy()
    oamp = _run(conv, params, aux, x)
    assert oamp.dtype == np.float32
    np.testing.assert_allclose(o32, oamp.asnumpy(), atol=5e-2)
    assert np.abs(o32 - oamp.asnumpy()).max() > 0, \
        "casts must actually change compute"


def test_convert_symbol_excluded_names():
    net = _convnet()
    conv = amp.convert_symbol(net, excluded_sym_names=["c1", "c2", "fc"])
    graph = json.loads(conv.tojson())
    # every lp16 op excluded → no bf16 casts remain (only possible fp32 ones)
    bf16_casts = [n for n in graph["nodes"] if n["op"] == "amp_cast"
                  and n["attrs"].get("dtype") == "bfloat16"]
    assert not bf16_casts


def test_convert_symbol_conditional_fp32():
    data = mx.sym.Variable("data")
    net = mx.sym.Pooling(data, name="p1", pool_type="avg", kernel=(2, 2))
    conv = amp.convert_symbol(
        net, target_dtype_ops=["Pooling"],
        conditional_fp32_ops=[("Pooling", "pool_type", ["avg"])])
    graph = json.loads(conv.tojson())
    casts = [n for n in graph["nodes"] if n["op"] == "amp_cast"]
    assert casts and all(n["attrs"]["dtype"] == "float32" for n in casts)


def test_convert_symbol_dedups_casts():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    a = mx.sym.FullyConnected(data, w, no_bias=True, name="fa", num_hidden=4)
    b = mx.sym.FullyConnected(data, w, no_bias=True, name="fb", num_hidden=4)
    conv = amp.convert_symbol(mx.sym.Group([a, b]))
    graph = json.loads(conv.tojson())
    casts = [n for n in graph["nodes"] if n["op"] == "amp_cast"]
    assert len(casts) == 2   # one for data, one for w — shared by fa and fb


def test_converted_symbol_json_roundtrip(tmp_path):
    net = _convnet()
    conv = amp.convert_symbol(net)
    f = str(tmp_path / "amp-symbol.json")
    conv.save(f)
    loaded = mx.sym.load(f)
    params, aux = _params_for(net, (2, 3, 8, 8))
    x = np.random.RandomState(2).randn(2, 3, 8, 8).astype("float32")
    np.testing.assert_allclose(_run(conv, params, aux, x).asnumpy(),
                               _run(loaded, params, aux, x).asnumpy(),
                               rtol=1e-6)


def test_convert_model_casts_lp16_params():
    net = _convnet()
    params, aux = _params_for(net, (2, 3, 8, 8))
    _, args_cast, _ = amp.convert_model(net, params, aux)
    assert str(args_cast["fc_weight"].dtype) == "bfloat16"
    assert str(args_cast["bn1_gamma"].dtype) == "float32"
    # empty target list → no params cast (consistent with no casts inserted)
    _, args_none, _ = amp.convert_model(net, params, aux,
                                        target_dtype_ops=[])
    assert all(v.dtype == np.float32 for v in args_none.values())


def test_module_runs_converted_symbol():
    net = _convnet()
    conv = amp.convert_symbol(net)
    mod = mx.mod.Module(conv, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 3, 8, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    x = np.random.RandomState(3).randn(4, 3, 8, 8).astype("float32")
    y = np.array([0, 1, 2, 3], "float32")
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()


# ----------------------------------------------------- probe-gap ops
def test_shuffle_permutes_first_axis():
    mx.random.seed(5)
    x = mx.nd.arange(24).reshape((6, 4))
    s = mx.nd.shuffle(x)
    a, b = x.asnumpy(), s.asnumpy()
    # same rows, possibly different order
    assert sorted(map(tuple, a)) == sorted(map(tuple, b))
    seen_diff = False
    for _ in range(10):
        if not np.array_equal(mx.nd.shuffle(x).asnumpy(), a):
            seen_diff = True
            break
    assert seen_diff, "shuffle never permuted in 10 tries"


def test_adamw_update_formula():
    w = mx.nd.ones((3,)) * 2.0
    g = mx.nd.ones((3,)) * 0.5
    m = mx.nd.zeros((3,))
    v = mx.nd.zeros((3,))
    lr, b1, b2, eps, wd, eta = 0.1, 0.9, 0.999, 1e-8, 0.01, 1.0
    mx.nd.contrib.adamw_update(w, g, m, v, lr=lr, beta1=b1, beta2=b2,
                               epsilon=eps, wd=wd, eta=eta, out=w)
    m_ref = (1 - b1) * 0.5
    v_ref = (1 - b2) * 0.25
    upd = lr * m_ref / (np.sqrt(v_ref) + eps) + wd * 2.0
    np.testing.assert_allclose(w.asnumpy(), 2.0 - eta * upd, rtol=1e-5)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-6)
