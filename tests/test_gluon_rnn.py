"""Gluon RNN tests (reference ``tests/python/unittest/test_gluon_rnn.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_rnn_cell_shapes():
    cell = gluon.rnn.RNNCell(100, prefix="rnn_")
    inputs = [mx.nd.ones((10, 50)) for _ in range(3)]
    assert sorted(cell.collect_params().keys()) == \
        ["rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"]
    cell.initialize()
    outputs, _ = cell.unroll(3, inputs)
    assert [o.shape for o in outputs] == [(10, 100)] * 3


def test_lstm_cell():
    cell = gluon.rnn.LSTMCell(64, prefix="lstm_")
    cell.initialize()
    x = mx.nd.random.uniform(shape=(8, 32))
    states = cell.begin_state(8)
    out, new_states = cell(x, states)
    assert out.shape == (8, 64)
    assert len(new_states) == 2
    assert new_states[0].shape == (8, 64)
    np.testing.assert_allclose(out.asnumpy(), new_states[0].asnumpy())


def test_gru_cell_unroll_merge():
    cell = gluon.rnn.GRUCell(16, prefix="gru_")
    cell.initialize()
    x = mx.nd.random.uniform(shape=(4, 5, 8))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (4, 5, 16)
    assert states[0].shape == (4, 16)


def test_sequential_stack():
    stack = gluon.rnn.SequentialRNNCell()
    for i in range(3):
        stack.add(gluon.rnn.LSTMCell(20, prefix=f"lstm{i}_"))
    stack.initialize()
    x = [mx.nd.ones((2, 10)) for _ in range(4)]
    outputs, states = stack.unroll(4, x)
    assert outputs[-1].shape == (2, 20)
    assert len(states) == 6  # 2 per LSTM layer


def test_residual_and_dropout_cells():
    base = gluon.rnn.RNNCell(12, input_size=12, prefix="base_")
    cell = gluon.rnn.ResidualCell(base)
    cell.initialize()
    x = mx.nd.ones((3, 12))
    out, _ = cell(x, cell.begin_state(3))
    assert out.shape == (3, 12)
    d = gluon.rnn.DropoutCell(0.5)
    out2, st = d(x, [])
    assert out2.shape == x.shape


def test_bidirectional_cell():
    cell = gluon.rnn.BidirectionalCell(
        gluon.rnn.LSTMCell(10, prefix="l_"), gluon.rnn.LSTMCell(10, prefix="r_"))
    cell.initialize()
    x = [mx.nd.ones((2, 6)) for _ in range(3)]
    outputs, states = cell.unroll(3, x)
    assert [o.shape for o in outputs] == [(2, 20)] * 3
    with pytest.raises(NotImplementedError):
        cell(x[0], states)


@pytest.mark.parametrize("layer_cls,mode", [
    (gluon.rnn.RNN, "rnn"), (gluon.rnn.LSTM, "lstm"), (gluon.rnn.GRU, "gru")])
def test_fused_layers_shapes(layer_cls, mode):
    layer = layer_cls(32, num_layers=2, bidirectional=True)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(7, 4, 16))  # TNC
    out = layer(x)
    assert out.shape == (7, 4, 64)
    states = layer.begin_state(4)
    out, new_states = layer(x, states)
    assert out.shape == (7, 4, 64)
    assert new_states[0].shape == (4, 4, 32)


def test_lstm_layer_vs_cell():
    """Fused LSTM must match the step-wise LSTMCell numerically."""
    T, N, C, H = 5, 3, 8, 16
    layer = gluon.rnn.LSTM(H, input_size=C)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(H, input_size=C, prefix="c_")
    cell.initialize()
    # copy layer params into cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    x = mx.nd.random.uniform(shape=(T, N, C))
    fused = layer(x).asnumpy()
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(fused, np.swapaxes(outs.asnumpy(), 0, 1)
                               if outs.shape[0] == N else outs.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_ntc_layout():
    layer = gluon.rnn.GRU(12, layout="NTC")
    layer.initialize()
    x = mx.nd.random.uniform(shape=(4, 9, 6))
    out = layer(x)
    assert out.shape == (4, 9, 12)


def test_rnn_layer_trains():
    """A tiny sequence-sum regression learns through the fused LSTM."""
    rng = np.random.RandomState(0)
    x = rng.randn(6, 32, 4).astype("float32")  # TNC
    y = x.sum(axis=(0, 2)).astype("float32")

    class Model(gluon.Block):
        def __init__(self):
            super().__init__()
            self.rnn = gluon.rnn.LSTM(16)
            self.out = gluon.nn.Dense(1)
        def forward(self, x):
            h = self.rnn(x)
            return self.out(h[-1])
    model = Model()
    model.initialize()
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 0.02})
    loss_fn = gluon.loss.L2Loss()
    first = None
    for i in range(60):
        with mx.autograd.record():
            loss = loss_fn(model(mx.nd.array(x)), mx.nd.array(y.reshape(-1, 1)))
        loss.backward()
        trainer.step(32)
        v = float(loss.mean().asscalar())
        if first is None:
            first = v
    assert v < first * 0.5, (first, v)


def test_rnn_interlayer_dropout():
    """Dropout applies between stacked layers in train mode only."""
    layer = gluon.rnn.LSTM(16, num_layers=2, dropout=0.5)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 8))
    out_eval1 = layer(x).asnumpy()
    out_eval2 = layer(x).asnumpy()
    np.testing.assert_allclose(out_eval1, out_eval2)  # eval: deterministic
    with mx.autograd.record():
        out_tr1 = layer(x).asnumpy()
        out_tr2 = layer(x).asnumpy()
    assert np.abs(out_tr1 - out_tr2).max() > 1e-6  # train: stochastic
