"""Native libjpeg decode+augment vs the cv2 Python path.

Reference: the in-iterator OMP decode of ``src/io/iter_image_recordio_2.cc``
(rebuilt as ``src/io/jpeg_decode.cc``).  Decode must be bit-identical (both
are libjpeg); resize/augment agree to u8 rounding.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _native, recordio

pytestmark = pytest.mark.skipif(not _native.decode_available(),
                                reason="native jpeg decode unavailable")


def _jpeg(rng, h=37, w=53, quality=90):
    import cv2
    img = (rng.rand(h, w, 3) * 255).astype("uint8")
    ok, enc = cv2.imencode(".jpg", img[:, :, ::-1],
                           [cv2.IMWRITE_JPEG_QUALITY, quality])
    assert ok
    return enc.tobytes()


def test_decode_bit_identical_to_cv2():
    import cv2
    rng = np.random.RandomState(0)
    payload = _jpeg(rng)
    out = _native.decode_batch([payload] * 3, (37, 53), n_threads=2)
    ref = cv2.imdecode(np.frombuffer(payload, np.uint8), cv2.IMREAD_COLOR)
    ref = ref[:, :, ::-1].astype(np.float32).transpose(2, 0, 1)
    for i in range(3):
        np.testing.assert_array_equal(out[i], ref)


def test_resize_crop_mirror_normalize_matches_cv2():
    import cv2
    rng = np.random.RandomState(1)
    payload = _jpeg(rng)
    mean = np.array([10., 20., 30.], np.float32)
    std = np.array([2., 3., 4.], np.float32)
    out = _native.decode_batch([payload], (20, 20), resize=24,
                               mirror=np.array([1], np.uint8),
                               mean=mean, std=std, scale=0.5)
    bgr = cv2.imdecode(np.frombuffer(payload, np.uint8), cv2.IMREAD_COLOR)
    ih, iw = bgr.shape[:2]
    nh, nw = (24, int(iw * 24 / ih)) if ih < iw else (int(ih * 24 / iw), 24)
    r = cv2.resize(bgr, (nw, nh), interpolation=cv2.INTER_LINEAR)
    y0, x0 = (nh - 20) // 2, (nw - 20) // 2
    r = r[y0:y0 + 20, x0:x0 + 20][:, ::-1][:, :, ::-1].astype(np.float32)
    r = ((r - mean) / std * 0.5).transpose(2, 0, 1)
    # u8 rounding differences in bilinear, scaled by the normalization
    np.testing.assert_allclose(out[0], r, atol=0.3)


def test_iterator_native_path_matches_cv2_path():
    """ImageRecordIter end to end: same records, native vs forced-cv2
    decode, same seed → near-identical batches and identical labels."""
    rng = np.random.RandomState(2)
    d = tempfile.mkdtemp(prefix="natdec_")
    rec_path = os.path.join(d, "data.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    for i in range(8):
        img = (rng.rand(40, 40, 3) * 255).astype("uint8")
        rec.write(recordio.pack_img(recordio.IRHeader(0, float(i), i, 0),
                                    img, quality=90))
    rec.close()

    def run_epoch():
        it = mx.io.ImageRecordIter(path_imgrec=rec_path,
                                   data_shape=(3, 32, 32), batch_size=4,
                                   rand_mirror=True, rand_crop=True, seed=5,
                                   preprocess_threads=2)
        return [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]

    native = run_epoch()
    orig = _native.decode_available
    _native.decode_available = lambda: False
    try:
        cv2_path = run_epoch()
    finally:
        _native.decode_available = orig
    assert len(native) == len(cv2_path) == 2
    for (dn, ln), (dc, lc) in zip(native, cv2_path):
        np.testing.assert_array_equal(ln, lc)
        np.testing.assert_allclose(dn, dc, atol=1.5)   # u8 resize rounding


def test_corrupt_payload_falls_back_or_raises_cleanly():
    with pytest.raises(IOError):
        _native.decode_batch([b"\xff\xd8\xff" + b"junk" * 10], (8, 8))
