"""mxnet_tpu.serving: bucketed AOT runtime, dynamic batcher, registry
(ISSUE 3 tentpole + satellites)."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.serving import (Batcher, ModelRegistry, ModelRuntime,
                               RequestRejected, default_buckets)

ITEM = (12,)


@pytest.fixture(autouse=True)
def _clean_bus():
    """Every test starts with a fresh, disabled bus and leaves it that way."""
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _make_net(const=None):
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(4))
    net.initialize(mx.init.Constant(const) if const is not None else None)
    return net


def _reqs(n, shape=ITEM, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.rand(*shape).astype("float32") for _ in range(n)]


# ------------------------------------------------------------------ buckets
def test_default_buckets():
    assert default_buckets(1) == (1,)
    assert default_buckets(8) == (1, 2, 4, 8)
    # a non-power-of-two cap is itself the top bucket
    assert default_buckets(12) == (1, 2, 4, 8, 12)
    with pytest.raises(ValueError):
        default_buckets(0)


def test_bucket_for_and_validation():
    rt = ModelRuntime(_make_net(), ITEM, max_batch=8, warm=False)
    assert [rt.bucket_for(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        rt.bucket_for(9)
    with pytest.raises(ValueError):
        rt._normalize(np.zeros((3, 7), "float32"))    # wrong item shape
    with pytest.raises(ValueError):
        rt._normalize((np.zeros(ITEM), np.zeros(ITEM)))  # wrong arity
    with pytest.raises(ValueError):
        ModelRuntime(_make_net(), ITEM, max_batch=8, buckets=(1, 2),
                     warm=False)  # ladder must end at max_batch


# ----------------------------------------------------------------- numerics
def test_padded_numerics_parity():
    """A padded bucket run returns exactly what an unpadded forward would."""
    net = _make_net()
    rt = ModelRuntime(net, ITEM, max_batch=8)
    for n in (1, 3, 5, 8):
        reqs = _reqs(n, seed=n)
        outs = rt.run_batch([rt._normalize(r) for r in reqs])
        assert len(outs) == n
        direct = net(mx.nd.array(np.stack(reqs))).asnumpy()
        np.testing.assert_allclose(np.stack(outs), direct, rtol=1e-5,
                                   atol=1e-6)


def test_single_call_convenience():
    net = _make_net()
    rt = ModelRuntime(net, ITEM, max_batch=4)
    x = _reqs(1)[0]
    out = rt(x)
    direct = net(mx.nd.array(x[None])).asnumpy()[0]
    np.testing.assert_allclose(out, direct, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------ compile-miss contract
def test_warmup_compiles_buckets_then_zero_steady_misses():
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=8)
    snap = telemetry.snapshot()
    assert snap["counters"]["serving.warmup_compiles"] == 4
    recompiles_after_warm = snap["counters"].get("cachedop.recompiles", 0)
    b = Batcher(rt, max_latency_ms=2)
    futs = []
    for n in (1, 3, 8, 5, 2, 7):
        futs += [b.submit(r) for r in _reqs(n, seed=n)]
    for f in futs:
        f.result(timeout=30)
    b.close()
    snap = telemetry.snapshot()
    # every size hit a warmed bucket: no serving miss, no XLA retrace
    assert snap["counters"].get("serving.compile_miss", 0) == 0
    assert snap["counters"].get("cachedop.recompiles", 0) == \
        recompiles_after_warm
    assert snap["counters"]["serving.batch_items"] == 26
    # queue-wait spans landed (cross-thread record_span path)
    assert "serving.queue_wait" in snap["spans"]
    assert "serving.run" in snap["spans"]


def test_unwarmed_shape_counts_as_miss():
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=8, warm=False)
    rt.run_batch([rt._normalize(r) for r in _reqs(3)])
    assert telemetry.counter_value("serving.compile_miss") == 1
    # second batch at the same bucket replays the now-compiled executable
    rt.run_batch([rt._normalize(r) for r in _reqs(4)])
    assert telemetry.counter_value("serving.compile_miss") == 1


def test_training_trace_is_not_an_inference_warmup():
    """The CachedOp cache is keyed by autograd mode: a shape traced only
    under training replays NOTHING at inference, so it must still count as
    a serving.compile_miss (compiled_signatures(training=False) filter)."""
    net = _make_net()
    net.hybridize()
    with mx.autograd.record():
        net(mx.nd.array(np.zeros((4,) + ITEM, "float32")))
    sigs = net.compiled_signatures()
    assert sigs and not net.compiled_signatures(training=False)
    telemetry.enable()
    rt = ModelRuntime(net, ITEM, max_batch=4, buckets=(4,), warm=False)
    rt.run_batch([rt._normalize(r) for r in _reqs(3)])
    assert telemetry.counter_value("serving.compile_miss") == 1


# ------------------------------------------------------------------ batcher
def test_timeout_flush_serves_lone_request():
    """An idle server answers a single request within the latency budget —
    the timer flush, not max_batch, closes the batch."""
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=8)
    b = Batcher(rt, max_latency_ms=20)
    t0 = time.perf_counter()
    out = b.submit(_reqs(1)[0]).result(timeout=30)
    took = time.perf_counter() - t0
    assert out.shape == (4,)
    assert took < 10.0
    snap = telemetry.snapshot()
    assert snap["counters"]["serving.batches"] == 1
    assert snap["counters"]["serving.batch_items"] == 1
    assert snap["counters"].get("serving.padded_items", 0) == 0  # bucket 1
    b.close()


def test_max_batch_flush_coalesces():
    """Queued requests coalesce into full buckets when the worker starts."""
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, max_latency_ms=200, queue_depth=64, start=False)
    futs = [b.submit(r) for r in _reqs(8)]
    assert b.pending() == 8
    b.start()
    outs = [f.result(timeout=30) for f in futs]
    assert len(outs) == 8
    snap = telemetry.snapshot()
    # two full buckets, no padding, well under the 200ms timer
    assert snap["counters"]["serving.batches"] == 2
    assert snap["counters"]["serving.batch_items"] == 8
    assert snap["counters"].get("serving.padded_items", 0) == 0
    b.close()


def test_deadline_rejection_when_queue_full():
    """A deadlined submit() against a full queue REJECTS at the deadline
    instead of hanging (the load-shedding acceptance criterion)."""
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, queue_depth=2, start=False)
    b.submit(_reqs(1)[0])
    b.submit(_reqs(1)[0])
    t0 = time.perf_counter()
    with pytest.raises(RequestRejected) as ei:
        b.submit(_reqs(1)[0], deadline_ms=60)
    took = time.perf_counter() - t0
    assert ei.value.reason == "deadline"
    assert 0.04 < took < 5.0
    by_label = telemetry.snapshot()["counters_by_label"]
    assert any('reason="deadline"' in k
               for k in by_label["serving.rejections"])
    b.close(drain=True)     # the two queued requests still get served


def test_deadline_expired_while_queued_is_shed():
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, start=False)
    fut = b.submit(_reqs(1)[0], deadline_ms=10)
    time.sleep(0.05)
    b.start()
    with pytest.raises(RequestRejected) as ei:
        fut.result(timeout=30)
    assert ei.value.reason == "deadline"
    b.close()


def test_backpressure_blocks_then_completes():
    """Deadline-less submits on a full queue block (backpressure) but make
    progress as the worker drains — nothing is dropped."""
    rt = ModelRuntime(_make_net(), ITEM, max_batch=2)
    b = Batcher(rt, max_latency_ms=1, queue_depth=2)
    futs = [b.submit(r) for r in _reqs(12)]
    outs = [f.result(timeout=60) for f in futs]
    assert len(outs) == 12
    b.close()


def test_worker_survives_model_crash():
    """A model exception fails that batch's futures; later requests run."""
    telemetry.enable()
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, max_latency_ms=2)
    real = rt.run_batch
    boom = {"armed": True}

    def flaky(rows):
        if boom.pop("armed", False):
            raise RuntimeError("model exploded")
        return real(rows)

    rt.run_batch = flaky
    with pytest.raises(RuntimeError, match="model exploded"):
        b.submit(_reqs(1)[0]).result(timeout=30)
    out = b.submit(_reqs(1)[0]).result(timeout=30)   # worker still alive
    assert out.shape == (4,)
    assert b.batches_failed == 1
    assert telemetry.counter_value("serving.batch_failures") == 1
    b.close()


def test_dead_worker_respawns_on_submit():
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, max_latency_ms=2)
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    b._worker = dead        # simulate an unexpectedly dead worker thread
    out = b.submit(_reqs(1)[0]).result(timeout=30)
    assert out.shape == (4,)
    assert b._worker.is_alive() or b.pending() == 0
    b.close()


def test_submit_after_close_rejects():
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt)
    b.close()
    with pytest.raises(RequestRejected) as ei:
        b.submit(_reqs(1)[0])
    assert ei.value.reason == "shutdown"


def test_close_without_drain_rejects_queue():
    rt = ModelRuntime(_make_net(), ITEM, max_batch=4)
    b = Batcher(rt, start=False)
    futs = [b.submit(r) for r in _reqs(3)]
    b.close(drain=False)
    for f in futs:
        with pytest.raises(RequestRejected) as ei:
            f.result(timeout=5)
        assert ei.value.reason == "shutdown"


# ----------------------------------------------------------------- registry
def test_registry_swap_routes_and_drains():
    telemetry.enable()
    reg = ModelRegistry()
    rt1 = ModelRuntime(_make_net(const=0.1), ITEM, max_batch=4, name="m")
    rt2 = ModelRuntime(_make_net(const=0.3), ITEM, max_batch=4, name="m")
    old = reg.register("m", rt1, max_latency_ms=2)
    with pytest.raises(ValueError):
        reg.register("m", rt2)          # no silent shadowing
    x = _reqs(1)[0]
    out1 = reg.infer("m", x)
    reg.swap("m", rt2, max_latency_ms=2)
    out2 = reg.infer("m", x)
    assert not np.allclose(out1, out2)  # new weights answer
    np.testing.assert_allclose(out2, rt2(x), rtol=1e-5, atol=1e-6)
    # the old batcher was drained and closed by the swap
    with pytest.raises(RequestRejected):
        old.submit(x)
    assert telemetry.counter_value("serving.model_swaps") == 1
    assert reg.names() == ["m"]
    reg.unregister("m")
    with pytest.raises(KeyError):
        reg.get("m")
    assert "m" not in reg


def test_registry_close_all():
    reg = ModelRegistry()
    reg.register("a", ModelRuntime(_make_net(), ITEM, max_batch=2))
    reg.register("b", ModelRuntime(_make_net(), ITEM, max_batch=2))
    assert reg.names() == ["a", "b"]
    reg.close()
    assert reg.names() == []


# ------------------------------------------------------------- import paths
def test_from_exported_parity(tmp_path):
    net = _make_net()
    net.hybridize()
    net(mx.nd.array(np.zeros((2,) + ITEM, "float32")))
    prefix = str(tmp_path / "m")
    net.export(prefix)
    rt = ModelRuntime.from_exported(prefix + "-symbol.json", "data",
                                    prefix + "-0000.params", ITEM,
                                    max_batch=4)
    x = _reqs(3, seed=7)
    outs = rt.run_batch([rt._normalize(r) for r in x])
    direct = net(mx.nd.array(np.stack(x))).asnumpy()
    np.testing.assert_allclose(np.stack(outs), direct, rtol=1e-5, atol=1e-6)


def test_multi_input_model():
    class TwoIn(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.proj = mx.gluon.nn.Dense(4)

        def hybrid_forward(self, F, a, b):
            return self.proj(a) + b

    net = TwoIn()
    net.initialize()
    rt = ModelRuntime(net, item_shapes=((6,), (4,)), max_batch=4)
    b = Batcher(rt, max_latency_ms=2)
    rng = np.random.RandomState(3)
    pairs = [(rng.rand(6).astype("float32"), rng.rand(4).astype("float32"))
             for _ in range(5)]
    outs = [b.submit(p).result(timeout=30) for p in pairs]
    direct = net(mx.nd.array(np.stack([a for a, _ in pairs])),
                 mx.nd.array(np.stack([c for _, c in pairs]))).asnumpy()
    np.testing.assert_allclose(np.stack(outs), direct, rtol=1e-5, atol=1e-6)
    b.close()


# ------------------------------------------------- block.py entry-point API
def test_compile_for_requires_hybridize():
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    with pytest.raises(RuntimeError, match="hybridize"):
        net.compile_for(mx.nd.ones((1, 8)))


def test_compiled_signatures_membership():
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    assert net.compiled_signatures() == frozenset()
    sig = net.compile_for(mx.nd.ones((2, 8)))
    assert sig == (((2, 8),), ("float32",))
    assert sig in net.compiled_signatures()
    assert (((4, 8),), ("float32",)) not in net.compiled_signatures()


def test_record_span_cross_thread():
    telemetry.enable()
    t0 = time.perf_counter()
    time.sleep(0.01)
    telemetry.record_span("serving.queue_wait", t0, model="t")
    agg = telemetry.span_aggregates()
    assert agg["serving.queue_wait"][0] == 1
    assert agg["serving.queue_wait"][1] >= 0.01
    (ev,) = [e for e in telemetry.trace_events()
             if e["name"] == "serving.queue_wait"]
    assert ev["ph"] == "X" and ev["dur"] >= 1e4   # >= 10ms in us
