"""Executor API depth tranche (reference
``tests/python/unittest/test_executor.py``): binary fwd/bwd bind matrix
across ranks, dot gradients at random shapes, Executor.reshape sharing.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _check_bind_with_uniform(ufunc, gfunc, dim, sf=None, lshape=None,
                             rshape=None, rng=None):
    """reference check_bind_with_uniform: bind lhs/rhs, forward+backward,
    compare against the analytic numpy fwd/grad."""
    rng = rng or np.random.RandomState(0)
    shape = lshape or tuple(rng.randint(1, 8, size=dim))
    lhs = mx.sym.Variable("lhs")
    rhs = mx.sym.Variable("rhs")
    ret = sf(lhs, rhs) if sf is not None else ufunc(lhs, rhs)

    lhs_arr = mx.nd.array(rng.uniform(-1, 1, lshape or shape)
                          .astype("float32") + 2.0)
    rhs_arr = mx.nd.array(rng.uniform(-1, 1, rshape or shape)
                          .astype("float32") + 2.0)
    lhs_grad = mx.nd.zeros((lshape or shape))
    rhs_grad = mx.nd.zeros((rshape or shape))
    ex = ret.bind(mx.cpu(), args=[lhs_arr, rhs_arr],
                  args_grad=[lhs_grad, rhs_grad])
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    want = ufunc(lhs_arr.asnumpy(), rhs_arr.asnumpy())
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    out_grad = mx.nd.array(np.ones(out.shape, "float32"))
    ex.backward([out_grad])
    lg, rg = gfunc(out_grad.asnumpy(), lhs_arr.asnumpy(),
                   rhs_arr.asnumpy())
    np.testing.assert_allclose(lhs_grad.asnumpy(), lg, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(rhs_grad.asnumpy(), rg, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("dim", [1, 2, 3])
def test_bind_binary_matrix(dim):
    rng = np.random.RandomState(dim)
    _check_bind_with_uniform(lambda x, y: x + y,
                             lambda g, x, y: (g, g), dim, rng=rng)
    _check_bind_with_uniform(lambda x, y: x - y,
                             lambda g, x, y: (g, -g), dim, rng=rng)
    _check_bind_with_uniform(lambda x, y: x * y,
                             lambda g, x, y: (y * g, x * g), dim, rng=rng)
    _check_bind_with_uniform(lambda x, y: x / y,
                             lambda g, x, y: (g / y, -x * g / (y ** 2)),
                             dim, rng=rng)


@pytest.mark.parametrize("dim", [1, 2])
def test_bind_minmax_matrix(dim):
    rng = np.random.RandomState(10 + dim)
    _check_bind_with_uniform(lambda x, y: np.maximum(x, y),
                             lambda g, x, y: (g * (x >= y), g * (y > x)),
                             dim, sf=mx.sym.maximum, rng=rng)
    _check_bind_with_uniform(lambda x, y: np.minimum(x, y),
                             lambda g, x, y: (g * (x <= y), g * (y < x)),
                             dim, sf=mx.sym.minimum, rng=rng)


def test_dot_random_shapes():
    rng = np.random.RandomState(3)
    for _ in range(5):
        s = tuple(rng.randint(1, 50, size=3))
        _check_bind_with_uniform(
            lambda x, y: np.dot(x, y),
            lambda g, x, y: (np.dot(g, y.T), np.dot(x.T, g)), 2,
            lshape=(s[0], s[1]), rshape=(s[1], s[2]), sf=mx.sym.dot,
            rng=rng)
    # 1-D inner product
    s = int(rng.randint(1, 50))
    _check_bind_with_uniform(
        lambda x, y: np.dot(x, y),
        lambda g, x, y: (g * y, g * x), 1,
        lshape=(s,), rshape=(s,), sf=mx.sym.dot, rng=rng)


def test_executor_reshape_shares_weights():
    """reference test_reshape: reshaped executor shares parameter arrays
    with the base executor but gets fresh data buffers."""
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4)
    exe = y.simple_bind(mx.cpu(), x=(5, 4), grad_req="null")
    exe.arg_arrays[0][:] = 1
    exe.arg_arrays[1][:] = mx.nd.ones((4, 4))
    exe.arg_arrays[2][:] = 0

    new_exe = exe.reshape(x=(3, 4))
    new_exe.forward(is_train=False)
    assert np.all(new_exe.outputs[0].asnumpy() == 4)

    # weight update through one executor is visible in the other
    exe.arg_arrays[1][:] = 2.0
    new_exe.forward(is_train=False)
    assert np.all(new_exe.outputs[0].asnumpy() == 8)

    # base executor still works at its own shape
    exe.forward(is_train=False)
    assert exe.outputs[0].shape == (5, 4)
    assert np.all(exe.outputs[0].asnumpy() == 8)


def test_executor_outputs_listing_and_grad_dict():
    a = mx.sym.Variable("a")
    out = mx.sym.Group([a * 2, a + 1])
    ex = out.simple_bind(mx.cpu(), a=(2, 2), grad_req="write")
    ex.arg_dict["a"][:] = 1.0
    ex.forward(is_train=True)
    assert len(ex.outputs) == 2
    ex.backward([mx.nd.ones((2, 2)), mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(),
                               np.full((2, 2), 3.0))


def test_executor_reshape_guards_and_dtype():
    """Up-sizing without allow_up_sizing and rank changes without
    partial_shaping raise (reference contract); dtypes survive reshape."""
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4)
    exe = y.simple_bind(mx.cpu(), x=(5, 4), grad_req="null",
                        type_dict={"x": "float16"})
    with pytest.raises(ValueError, match="allow_up_sizing"):
        exe.reshape(x=(9, 4))
    with pytest.raises(ValueError, match="partial_shaping"):
        exe.reshape(x=(5, 2, 2))
    bigger = exe.reshape(allow_up_sizing=True, x=(9, 4))
    assert bigger.arg_dict["x"].shape == (9, 4)
    assert bigger.arg_dict["x"].dtype == np.float16
