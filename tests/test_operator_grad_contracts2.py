"""Second finite-difference/semantics tranche (reference
``tests/python/unittest/test_operator.py`` families not covered by
``test_operator_grad_contracts.py``): pad, LRN, sequence ops, pick/take
variants, ordering, spatial ops, and shape-polymorphic helpers.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal  # noqa: F401


from mxnet_tpu.test_utils import (fd_grad_check as _grad_check,  # noqa: E402
                                  fd_rand as _rand)


# ---------------------------------------------------------------------- pad
@pytest.mark.parametrize("mode", ["constant", "edge", "reflect"])
def test_pad_grad(mode):
    data = mx.sym.Variable("data")
    sym = mx.sym.pad(data, mode=mode,
                     pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    _grad_check(sym, {"data": _rand(1, 2, 3, 3, seed=1)})


def test_pad_constant_value_forward():
    data = mx.sym.Variable("data")
    sym = mx.sym.pad(data, mode="constant", constant_value=7.0,
                     pad_width=(0, 0, 0, 0, 1, 0, 0, 0))
    out = sym.eval(data=mx.nd.ones((1, 1, 2, 2)))[0].asnumpy()
    assert out[0, 0, 0, 0] == 7.0 and out[0, 0, 1, 0] == 1.0


# ---------------------------------------------------------------------- LRN
def test_lrn_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.LRN(data, nsize=3, alpha=1e-2, beta=0.5)
    _grad_check(sym, {"data": _rand(1, 4, 3, 3, seed=2, shift=1.0)})


# -------------------------------------------------------------- sequence ops
def test_sequence_mask_semantics():
    data = mx.sym.Variable("data")
    slen = mx.sym.Variable("len")
    sym = mx.sym.SequenceMask(data, slen, use_sequence_length=True,
                              value=-9.0)
    x = _rand(4, 2, 3, seed=3)                  # (T, batch, feat)
    ln = np.array([2.0, 4.0], "float32")
    out = sym.eval(data=mx.nd.array(x), len=mx.nd.array(ln))[0].asnumpy()
    np.testing.assert_allclose(out[:2, 0], x[:2, 0])
    assert (out[2:, 0] == -9.0).all()
    np.testing.assert_allclose(out[:, 1], x[:, 1])


def test_sequence_last_and_reverse():
    data = mx.sym.Variable("data")
    slen = mx.sym.Variable("len")
    x = _rand(4, 2, 3, seed=4)
    ln = np.array([2.0, 4.0], "float32")
    last = mx.sym.SequenceLast(data, slen, use_sequence_length=True)
    out = last.eval(data=mx.nd.array(x), len=mx.nd.array(ln))[0].asnumpy()
    np.testing.assert_allclose(out[0], x[1, 0])
    np.testing.assert_allclose(out[1], x[3, 1])
    rev = mx.sym.SequenceReverse(data, slen, use_sequence_length=True)
    out = rev.eval(data=mx.nd.array(x), len=mx.nd.array(ln))[0].asnumpy()
    np.testing.assert_allclose(out[0, 0], x[1, 0])   # first 2 reversed
    np.testing.assert_allclose(out[2, 0], x[2, 0])   # tail untouched
    np.testing.assert_allclose(out[0, 1], x[3, 1])   # full reverse


def test_sequence_mask_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.SequenceMask(data, mx.sym.Variable("len"),
                              use_sequence_length=True)
    _grad_check(sym, {"data": _rand(3, 2, 2, seed=5),
                      "len": np.array([2.0, 3.0], "float32")},
                grad_nodes=["data"])


# ------------------------------------------------------------- pick and take
def test_pick_grad_and_modes():
    data = mx.sym.Variable("data")
    idx = mx.sym.Variable("idx")
    sym = mx.sym.pick(data, idx, axis=1)
    x = _rand(3, 4, seed=6)
    iv = np.array([0.0, 3.0, 1.0], "float32")
    out = sym.eval(data=mx.nd.array(x), idx=mx.nd.array(iv))[0].asnumpy()
    np.testing.assert_allclose(out, x[np.arange(3), iv.astype(int)])
    _grad_check(sym, {"data": x, "idx": iv}, grad_nodes=["data"])


@pytest.mark.parametrize("mode", ["clip", "wrap"])
def test_take_modes(mode):
    data = mx.sym.Variable("data")
    idx = mx.sym.Variable("idx")
    sym = mx.sym.take(data, idx, mode=mode)
    x = _rand(4, 2, seed=7)
    iv = np.array([-1.0, 5.0], "float32")
    out = sym.eval(data=mx.nd.array(x), idx=mx.nd.array(iv))[0].asnumpy()
    if mode == "clip":
        np.testing.assert_allclose(out, x[[0, 3]])
    else:
        np.testing.assert_allclose(out, x[[-1 % 4, 5 % 4]])


def test_batch_take_forward():
    a = mx.sym.Variable("a")
    idx = mx.sym.Variable("idx")
    sym = mx.sym.batch_take(a, idx)
    x = _rand(3, 4, seed=8)
    iv = np.array([1.0, 0.0, 3.0], "float32")
    out = sym.eval(a=mx.nd.array(x), idx=mx.nd.array(iv))[0].asnumpy()
    np.testing.assert_allclose(out, x[np.arange(3), iv.astype(int)])


def test_gather_nd_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.gather_nd(data, mx.sym.Variable("idx"))
    x = _rand(3, 4, seed=9)
    iv = np.array([[0, 2, 1], [1, 3, 0]], "float32")
    _grad_check(sym, {"data": x, "idx": iv}, grad_nodes=["data"])


# ------------------------------------------------------------------ ordering
def test_sort_argsort_topk():
    data = mx.sym.Variable("data")
    x = np.array([[3.0, 1.0, 2.0], [0.0, -1.0, 5.0]], "float32")
    out = mx.sym.sort(data, axis=-1).eval(data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, np.sort(x, -1))
    out = mx.sym.argsort(data, axis=-1, is_ascend=False).eval(
        data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, np.argsort(-x, -1))
    val, ind = mx.sym.topk(data, k=2, ret_typ="both", axis=-1).eval(
        data=mx.nd.array(x))
    np.testing.assert_allclose(val.asnumpy()[0], [3.0, 2.0])
    np.testing.assert_allclose(ind.asnumpy()[0], [0.0, 2.0])


def test_argmax_argmin_keepdims():
    data = mx.sym.Variable("data")
    x = _rand(3, 5, seed=10)
    out = mx.sym.argmax(data, axis=1, keepdims=True).eval(
        data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out[:, 0], np.argmax(x, 1))
    out = mx.sym.argmin(data, axis=0).eval(data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, np.argmin(x, 0))


# ------------------------------------------------------------- spatial/misc
def test_swapaxes_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.SwapAxis(data, dim1=0, dim2=2)
    _grad_check(sym, {"data": _rand(2, 3, 4, seed=11)})


def test_depth_space_roundtrip():
    data = mx.sym.Variable("data")
    x = _rand(1, 8, 2, 2, seed=12)
    d2s = mx.sym.depth_to_space(data, block_size=2)
    s2d = mx.sym.space_to_depth(d2s, block_size=2)
    out = s2d.eval(data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-6)


def test_upsampling_nearest_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.UpSampling(data, scale=2, sample_type="nearest")
    x = _rand(1, 2, 3, 3, seed=13)
    out = sym.eval(data=mx.nd.array(x))[0].asnumpy()
    assert out.shape == (1, 2, 6, 6)
    np.testing.assert_allclose(out[0, 0, :2, :2], np.full((2, 2),
                                                          x[0, 0, 0, 0]))
    _grad_check(sym, {"data": x})


def test_slice_like_and_broadcast_like():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.slice_like(a, b).eval(
        a=mx.nd.ones((4, 5)), b=mx.nd.zeros((2, 3)))[0]
    assert out.shape == (2, 3)
    out = mx.sym.broadcast_like(a, b).eval(
        a=mx.nd.ones((1, 3)), b=mx.nd.zeros((4, 3)))[0]
    assert out.shape == (4, 3)


def test_shape_array_and_size_array():
    data = mx.sym.Variable("data")
    out = mx.sym.shape_array(data).eval(
        data=mx.nd.ones((2, 3, 5)))[0].asnumpy()
    np.testing.assert_array_equal(out, [2, 3, 5])
    out = mx.sym.size_array(data).eval(data=mx.nd.ones((2, 3)))[0].asnumpy()
    np.testing.assert_array_equal(out.ravel(), [6])


def test_one_hot_and_diag():
    idx = mx.sym.Variable("idx")
    out = mx.sym.one_hot(idx, depth=4, on_value=2.0, off_value=-1.0).eval(
        idx=mx.nd.array([1.0, 3.0]))[0].asnumpy()
    want = np.full((2, 4), -1.0, "float32")
    want[0, 1] = want[1, 3] = 2.0
    np.testing.assert_allclose(out, want)
    data = mx.sym.Variable("data")
    out = mx.sym.diag(data).eval(
        data=mx.nd.array(np.arange(9).reshape(3, 3)))[0].asnumpy()
    np.testing.assert_allclose(out, [0, 4, 8])


# --------------------------------------------------------------- RNN fused op
@pytest.mark.parametrize("mode", ["rnn_tanh", "gru", "lstm"])
def test_fused_rnn_matches_cell_math(mode):
    """Fused RNN op forward is finite, shape-correct, and differentiable
    (reference rnn.cc; exact cell math is covered in test_gluon_rnn)."""
    T, B, I, H = 3, 2, 4, 5
    data = mx.sym.Variable("data")
    params = mx.sym.Variable("params")
    state = mx.sym.Variable("state")
    ngates = {"rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]
    psize = ngates * H * (I + H + 2)
    inputs = {"data": _rand(T, B, I, seed=14),
              "params": _rand(psize, seed=15, scale=0.2),
              "state": np.zeros((1, B, H), "float32")}
    if mode == "lstm":
        cell = mx.sym.Variable("cell")
        sym = mx.sym.RNN(data, params, state, cell, state_size=H,
                         num_layers=1, mode=mode)
        inputs["cell"] = np.zeros((1, B, H), "float32")
    else:
        sym = mx.sym.RNN(data, params, state, state_size=H, num_layers=1,
                         mode=mode)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write",
                         **{k: v.shape for k, v in inputs.items()})
    for k, v in inputs.items():
        ex.arg_dict[k][:] = v
    out = ex.forward(is_train=True)[0]
    assert out.shape == (T, B, H)
    assert np.isfinite(out.asnumpy()).all()
    ex.backward()
    g = ex.grad_dict["params"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ------------------------------------------------------------ CTC loss shape
def test_ctc_loss_positive_and_differentiable():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    sym = mx.sym.CTCLoss(data, label)
    T, B, C = 6, 2, 5
    x = _rand(T, B, C, seed=16, scale=2.0)
    y = np.array([[1, 2, 0, 0], [3, 1, 2, 0]], "float32")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", data=x.shape,
                         label=y.shape)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["label"][:] = y
    out = ex.forward(is_train=True)[0].asnumpy()
    assert out.shape == (B,) and (out > 0).all()
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ----------------------------------------------------- dot with sparse lhs
def test_sparse_dot_csr_dense():
    lhs = mx.nd.sparse.csr_matrix(
        (np.array([1.0, 2.0, 3.0], "float32"), np.array([0, 2, 1]),
         np.array([0, 2, 3])), shape=(2, 3))
    rhs = mx.nd.array(_rand(3, 4, seed=17))
    out = mx.nd.sparse.dot(lhs, rhs).asnumpy()
    np.testing.assert_allclose(out, lhs.asnumpy() @ rhs.asnumpy(),
                               rtol=1e-5)
