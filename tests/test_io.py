"""Data IO tests.

Mirrors reference ``tests/python/unittest/test_io.py`` (NDArrayIter pad/
discard/roll_over, CSVIter) and ``test_recordio.py`` (framing round-trip,
indexed access, IRHeader pack/unpack).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


# ----------------------------------------------------------------- recordio
def test_recordio_roundtrip(tmp_path):
    frec = str(tmp_path / "test.rec")
    N = 255
    writer = recordio.MXRecordIO(frec, "w")
    for i in range(N):
        writer.write(bytes(str(i), "utf-8"))
    del writer
    reader = recordio.MXRecordIO(frec, "r")
    for i in range(N):
        res = reader.read()
        assert res == bytes(str(i), "utf-8")
    assert reader.read() is None


def test_indexed_recordio(tmp_path):
    fidx = str(tmp_path / "test.idx")
    frec = str(tmp_path / "test.rec")
    N = 255
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    for i in range(N):
        writer.write_idx(i, bytes(str(i), "utf-8"))
    writer.close()
    reader = recordio.MXIndexedRecordIO(fidx, frec, "r")
    keys = list(reader.keys)
    assert sorted(keys) == list(range(N))
    for i in np.random.permutation(N)[:50]:
        assert reader.read_idx(int(i)) == bytes(str(i), "utf-8")


def test_irheader_pack_unpack():
    # scalar label
    h = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload" and h2.label == 3.0 and h2.id == 7
    # vector label sets flag
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 9, 0)
    h2, payload = recordio.unpack(recordio.pack(h, b"x"))
    assert h2.flag == 3
    np.testing.assert_allclose(h2.label, [1, 2, 3])


def test_pack_img_unpack_img():
    img = (np.random.rand(32, 24, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 5.0, 1, 0), img, quality=100,
                          img_fmt=".png")
    header, img2 = recordio.unpack_img(s)
    assert header.label == 5.0
    np.testing.assert_array_equal(img, img2)


# ---------------------------------------------------------------- NDArrayIter
def test_ndarrayiter():
    data = np.ones([1000, 2, 2])
    labels = np.ones([1000, 1])
    for i in range(1000):
        data[i] = i / 100
        labels[i] = i / 100
    it = mx.io.NDArrayIter(data, labels, 128, True,
                           last_batch_handle="pad")
    batch_count = 0
    labels_copy = []
    for batch in it:
        labels_copy.append(batch.label[0].asnumpy())
        batch_count += 1
    assert batch_count == 8
    # shuffled but complete (pad wraps)
    all_labels = np.concatenate(labels_copy).ravel()[:1000]
    assert len(all_labels) == 1000


def test_ndarrayiter_discard():
    data = np.arange(100).reshape(100, 1)
    it = mx.io.NDArrayIter(data, batch_size=30, shuffle=False,
                           last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[2].data[0].asnumpy().ravel(),
                                  np.arange(60, 90))
    it.reset()
    assert len(list(it)) == 3


def test_ndarrayiter_pad():
    data = np.arange(10).reshape(10, 1)
    it = mx.io.NDArrayIter(data, batch_size=4, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[2].pad == 2
    np.testing.assert_array_equal(batches[2].data[0].asnumpy().ravel(),
                                  [8, 9, 0, 1])


def test_ndarrayiter_dict_and_provide():
    data = {"a": np.zeros((10, 2)), "b": np.zeros((10, 3))}
    it = mx.io.NDArrayIter(data, np.zeros(10), batch_size=5)
    descs = it.provide_data
    assert sorted(d.name for d in descs) == ["a", "b"]
    assert it.provide_label[0].shape == (5,)


# -------------------------------------------------------------------- CSVIter
def test_csviter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    arr = np.random.rand(30, 4)
    lab = np.arange(30)
    np.savetxt(data_path, arr, delimiter=",")
    np.savetxt(label_path, lab, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(4,),
                       label_csv=label_path, label_shape=(1,), batch_size=10)
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), arr[:10],
                               rtol=1e-5)
    # string-typed shape like reference scripts pass
    it2 = mx.io.CSVIter(data_csv=data_path, data_shape="(4,)", batch_size=10)
    assert next(iter(it2)).data[0].shape == (10, 4)


# ------------------------------------------------------------ ImageRecordIter
def _write_img_rec(tmp_path, n=24, hw=(40, 36)):
    import cv2  # noqa: F401
    fidx = str(tmp_path / "img.idx")
    frec = str(tmp_path / "img.rec")
    w = recordio.MXIndexedRecordIO(fidx, frec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(hw[0], hw[1], 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 10), i, 0), img, img_fmt=".png"))
    w.close()
    return frec, fidx


def test_image_record_iter(tmp_path):
    frec, fidx = _write_img_rec(tmp_path)
    it = mx.io.ImageRecordIter(
        path_imgrec=frec, path_imgidx=fidx, data_shape=(3, 32, 32),
        batch_size=8, shuffle=True, rand_mirror=True, rand_crop=True,
        mean_r=123.0, mean_g=117.0, mean_b=104.0, preprocess_threads=2)
    count = 0
    labels = []
    for batch in it:
        assert batch.data[0].shape == (8, 3, 32, 32)
        labels.extend(batch.label[0].asnumpy().tolist())
        count += 1
    assert count == 3
    assert sorted(set(int(l) for l in labels)) == list(range(10))
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter_decode_telemetry(tmp_path):
    """ImageRecordIter exports its internal decode-pool waits (ROADMAP io.*
    item): io.decode_wait_ms counter (decoder-labeled) + io.decode_batch /
    io.read_records spans + io.record_batches progress."""
    from mxnet_tpu import telemetry
    frec, fidx = _write_img_rec(tmp_path, n=8)
    telemetry.reset()
    telemetry.enable()
    try:
        it = mx.io.ImageRecordIter(
            path_imgrec=frec, path_imgidx=fidx, data_shape=(3, 32, 32),
            batch_size=4, preprocess_threads=2)
        n = len(list(it))
        assert n == 2
        snap = telemetry.snapshot()
        assert snap["counters"]["io.record_batches"] == n
        assert snap["counters"]["io.decode_wait_ms"] >= 0
        assert any(k.startswith('{decoder="') for k in
                   snap["counters_by_label"]["io.decode_wait_ms"])
        assert snap["spans"]["io.decode_batch"]["calls"] == n
        assert snap["spans"]["io.read_records"]["calls"] == n
    finally:
        telemetry.disable()
        telemetry.reset()


def test_image_record_dataset(tmp_path):
    frec, _ = _write_img_rec(tmp_path, n=6)
    ds = mx.gluon.data.vision.ImageRecordDataset(frec)
    assert len(ds) == 6
    img, label = ds[3]
    assert img.shape == (40, 36, 3)
    assert label == 3.0


# ------------------------------------------------------------- gluon.data
def test_array_dataset_and_loader():
    X = np.random.uniform(size=(16, 3))
    y = np.arange(16, dtype="float32")
    ds = mx.gluon.data.ArrayDataset(X, y)
    assert len(ds) == 16
    loader = mx.gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[1][0].asnumpy(), X[4:8], rtol=1e-5)
    np.testing.assert_allclose(batches[1][1].asnumpy(), y[4:8])


def test_dataloader_last_batch():
    X = np.random.uniform(size=(10, 2))
    ds = mx.gluon.data.ArrayDataset(X)
    assert len(list(mx.gluon.data.DataLoader(ds, 4, last_batch="keep"))) == 3
    assert len(list(mx.gluon.data.DataLoader(ds, 4, last_batch="discard"))) == 2
    loader = mx.gluon.data.DataLoader(ds, 4, last_batch="rollover")
    assert len(list(loader)) == 2
    assert len(list(loader)) == 3  # rolled-over remainder joins next epoch


def test_dataset_transform_and_filter():
    ds = mx.gluon.data.SimpleDataset(list(range(10)))
    doubled = ds.transform(lambda x: 2 * x)
    assert doubled[3] == 6
    evens = ds.filter(lambda x: x % 2 == 0)
    assert len(evens) == 5
    taken = ds.take(3)
    assert len(taken) == 3


def test_samplers():
    s = mx.gluon.data.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    r = mx.gluon.data.RandomSampler(100)
    assert sorted(list(r)) == list(range(100))
    b = mx.gluon.data.BatchSampler(s, 2, "keep")
    assert list(b) == [[0, 1], [2, 3], [4]]
    assert len(b) == 3


def test_transforms_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms
    img = mx.nd.array((np.random.rand(36, 36, 3) * 255).astype("uint8"),
                      dtype="uint8")
    fn = transforms.Compose([
        transforms.Resize(32),
        transforms.CenterCrop(28),
        transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2)),
    ])
    out = fn(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32


def test_transforms_random():
    from mxnet_tpu.gluon.data.vision import transforms
    img = mx.nd.array((np.random.rand(32, 32, 3) * 255).astype("float32"))
    for t in (transforms.RandomFlipLeftRight(),
              transforms.RandomBrightness(0.3),
              transforms.RandomContrast(0.3),
              transforms.RandomSaturation(0.3),
              transforms.RandomColorJitter(0.1, 0.1, 0.1, 0.1),
              transforms.RandomLighting(0.1),
              transforms.RandomResizedCrop(16)):
        out = t(img)
        assert np.isfinite(out.asnumpy()).all(), type(t).__name__


def test_dataloader_multiworker():
    X = np.random.uniform(size=(32, 3)).astype("float32")
    y = np.arange(32, dtype="float32")
    ds = mx.gluon.data.ArrayDataset(X, y)
    loader = mx.gluon.data.DataLoader(ds, batch_size=8, num_workers=2,
                                      thread_pool=True)
    batches = list(loader)
    assert len(batches) == 4
    got = np.concatenate([b[1].asnumpy() for b in batches])
    np.testing.assert_allclose(np.sort(got), y)


def test_image_det_record_iter(tmp_path):
    """io.ImageDetRecordIter parses packed detection labels from .rec
    (reference iter_image_det_recordio.cc format)."""
    import cv2
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "det.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        # packed label: [header_width=2, obj_width=5, cls,x1,y1,x2,y2]*2
        label = [2, 5,
                 float(i % 3), 0.1, 0.1, 0.5, 0.5,
                 float((i + 1) % 3), 0.4, 0.4, 0.9, 0.9]
        header = recordio.IRHeader(len(label), label, i, 0)
        rec.write(recordio.pack_img(header, img, quality=90))
    rec.close()
    it = mx.io.ImageDetRecordIter(path_imgrec=rec_path,
                                  data_shape=(3, 32, 32), batch_size=4,
                                  label_pad_width=12)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 32, 32)
    lab = batch.label[0].asnumpy()
    assert lab.shape[0] == 4 and lab.shape[2] == 5
    # first object of record 0: class 0 at (.1,.1,.5,.5)
    np.testing.assert_allclose(lab[0, 0], [0, 0.1, 0.1, 0.5, 0.5],
                               atol=1e-6)
    # padding rows are -1
    assert (lab[0, 2:] == -1).all()


def test_test_utils_download_local(tmp_path):
    src = tmp_path / "weights.bin"
    src.write_bytes(b"abc123")
    out = mx.test_utils.download("file://" + str(src),
                                 dirname=str(tmp_path / "dl"))
    assert open(out, "rb").read() == b"abc123"
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="egress"):
        mx.test_utils.download("http://example.com/x.bin",
                               fname=str(tmp_path / "nope.bin"))


# --- r4 depth: gluon.data remainder (reference test_gluon_data.py —
# multi-worker loaders, batchify of structures, interval sampler,
# dataset compositions)

def test_dataloader_num_workers_matches_single_process():
    X = np.arange(64, dtype="float32").reshape(16, 4)
    y = np.arange(16, dtype="float32")
    ds = mx.gluon.data.ArrayDataset(mx.nd.array(X), mx.nd.array(y))
    single = [b[0].asnumpy() for b in
              mx.gluon.data.DataLoader(ds, 4, shuffle=False,
                                       num_workers=0)]
    multi = [b[0].asnumpy() for b in
             mx.gluon.data.DataLoader(ds, 4, shuffle=False,
                                      num_workers=2)]
    assert len(single) == len(multi) == 4
    for a, b in zip(single, multi):
        np.testing.assert_allclose(a, b)


def test_dataloader_batchify_tuple_structures():
    """Default batchify stacks each element of a tuple sample
    independently (reference default_batchify_fn)."""
    class PairDataset(mx.gluon.data.Dataset):
        def __len__(self):
            return 6

        def __getitem__(self, i):
            return (np.full((2,), i, "float32"),
                    np.float32(i * 10))

    loader = mx.gluon.data.DataLoader(PairDataset(), batch_size=3,
                                      shuffle=False)
    batches = list(loader)
    a, b = batches[0]
    assert a.shape == (3, 2) and b.shape == (3,)
    np.testing.assert_allclose(b.asnumpy(), [0, 10, 20])


def test_interval_sampler_and_batch_sampler():
    from mxnet_tpu.gluon.data import sampler as S
    seq = list(S.SequentialSampler(6))
    assert seq == [0, 1, 2, 3, 4, 5]
    rnd = list(S.RandomSampler(6))
    assert sorted(rnd) == seq
    bs = list(S.BatchSampler(S.SequentialSampler(7), 3,
                             last_batch="discard"))
    assert bs == [[0, 1, 2], [3, 4, 5]]
    bs_keep = list(S.BatchSampler(S.SequentialSampler(7), 3,
                                  last_batch="keep"))
    assert bs_keep[-1] == [6]
    bs_roll = list(S.BatchSampler(S.SequentialSampler(7), 3,
                                  last_batch="rollover"))
    assert bs_roll == [[0, 1, 2], [3, 4, 5]]   # 6 rolls to next epoch


def test_simple_dataset_take():
    ds = mx.gluon.data.SimpleDataset(list(range(10)))
    t = ds.take(4)
    assert len(t) == 4 and t[3] == 3


def test_transform_first_only_touches_data():
    X = np.ones((4, 2), "float32")
    y = np.arange(4, dtype="float32")
    ds = mx.gluon.data.ArrayDataset(mx.nd.array(X), mx.nd.array(y))
    t = ds.transform_first(lambda x: x * 5)
    data, label = t[1]
    np.testing.assert_allclose(data.asnumpy(), X[1] * 5)
    assert float(label.asscalar()) == 1.0


# --- r4 depth: vision transforms semantics (reference
# test_gluon_data_vision.py)

def test_to_tensor_and_normalize_values():
    from mxnet_tpu.gluon.data.vision import transforms
    img = mx.nd.array((np.arange(24).reshape(4, 2, 3) * 10)
                      .astype("uint8"))
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 4, 2)           # HWC -> CHW
    np.testing.assert_allclose(
        t.asnumpy(), img.asnumpy().transpose(2, 0, 1) / 255.0,
        rtol=1e-5)
    norm = transforms.Normalize(mean=(0.5, 0.5, 0.5),
                                std=(0.2, 0.2, 0.2))(t)
    np.testing.assert_allclose(norm.asnumpy(),
                               (t.asnumpy() - 0.5) / 0.2, rtol=1e-5)


def test_center_crop_and_resize_geometry():
    from mxnet_tpu.gluon.data.vision import transforms
    img = mx.nd.array(np.arange(30 * 40 * 3).reshape(30, 40, 3)
                      .astype("uint8") % 255)
    out = transforms.CenterCrop((20, 10))(img)     # (w, h)
    assert out.shape == (10, 20, 3)
    r = transforms.Resize(16)(img)
    assert r.shape[2] == 3 and min(r.shape[:2]) == 16


def test_random_flip_transforms_preserve_content():
    from mxnet_tpu.gluon.data.vision import transforms
    mx.random.seed(7)
    img = mx.nd.array(np.arange(12).reshape(2, 2, 3).astype("float32"))
    lr = transforms.RandomFlipLeftRight()
    outs = {tuple(lr(img).asnumpy().ravel()) for _ in range(20)}
    want = {tuple(img.asnumpy().ravel()),
            tuple(img.asnumpy()[:, ::-1].ravel())}
    assert outs <= want and len(outs) == 2     # both variants occur


def test_color_jitter_stays_in_range():
    from mxnet_tpu.gluon.data.vision import transforms
    mx.random.seed(1)
    img = mx.nd.array(np.random.RandomState(0).rand(8, 8, 3)
                      .astype("float32"))
    jit = transforms.RandomColorJitter(brightness=0.2, contrast=0.2,
                                       saturation=0.2)
    out = jit(img)
    assert out.shape == img.shape
    assert np.isfinite(out.asnumpy()).all()


def test_compose_in_dataloader_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms
    rng = np.random.RandomState(0)
    imgs = (rng.rand(8, 12, 12, 3) * 255).astype("uint8")
    labels = np.arange(8).astype("float32")
    ds = mx.gluon.data.ArrayDataset(mx.nd.array(imgs),
                                    mx.nd.array(labels))
    fn = transforms.Compose([transforms.Resize(8),
                             transforms.ToTensor()])
    loader = mx.gluon.data.DataLoader(ds.transform_first(fn),
                                      batch_size=4, shuffle=False)
    batches = list(loader)
    assert batches[0][0].shape == (4, 3, 8, 8)
    np.testing.assert_allclose(batches[0][1].asnumpy(), [0, 1, 2, 3])


def test_device_prefetch_iter_orders_and_overlaps():
    """DevicePrefetchIter: staged payloads arrive in order, one-ahead, and
    reset() restarts cleanly (reference src/io/iter_prefetcher.h role)."""
    import threading as _threading
    import time as _time
    from mxnet_tpu.io import DevicePrefetchIter

    x = np.arange(40, dtype="float32").reshape(10, 4)
    y = np.arange(10, dtype="float32")
    it = mx.io.NDArrayIter(x, y, batch_size=2)
    staged_on = []

    def stage(b):
        staged_on.append(_threading.current_thread().name)
        return b.data[0].asnumpy(), b.label[0].asnumpy()

    pit = DevicePrefetchIter(it, stage, depth=2)
    seen = []
    for xb, yb in pit:
        seen.append(yb.tolist())
        _time.sleep(0.01)        # consumer slower than stager => overlap
    assert seen == [[0, 1], [2, 3], [4, 5], [6, 7], [8, 9]]
    assert all(n != _threading.main_thread().name for n in staged_on)
    # epoch 2 after implicit reset via __iter__
    seen2 = [yb.tolist() for _, yb in pit]
    assert seen2 == seen


def test_device_prefetch_iter_propagates_errors():
    from mxnet_tpu.io import DevicePrefetchIter
    it = mx.io.NDArrayIter(np.zeros((4, 2), "float32"),
                           np.zeros(4, "float32"), batch_size=2)

    def bad_stage(b):
        raise RuntimeError("stage boom")

    pit = DevicePrefetchIter(it, bad_stage)
    with pytest.raises(RuntimeError, match="stage boom"):
        next(iter(pit))


def test_device_prefetch_iter_mid_epoch_reset():
    from mxnet_tpu.io import DevicePrefetchIter
    x = np.arange(24, dtype="float32").reshape(12, 2)
    it = mx.io.NDArrayIter(x, np.arange(12, dtype="float32"), batch_size=3)
    pit = DevicePrefetchIter(it, lambda b: b.label[0].asnumpy(), depth=1)
    first = next(iter(pit))
    assert first.tolist() == [0, 1, 2]
    pit.reset()
    again = next(pit)
    assert again.tolist() == [0, 1, 2]


def test_device_prefetch_iter_exhaustion_reraises():
    """After an epoch ends (or errors), further next() calls keep raising
    instead of deadlocking on the empty queue."""
    from mxnet_tpu.io import DevicePrefetchIter
    it = mx.io.NDArrayIter(np.zeros((4, 2), "float32"),
                           np.zeros(4, "float32"), batch_size=2)
    pit = DevicePrefetchIter(it, lambda b: b.label[0].asnumpy())
    list(pit)
    with pytest.raises(StopIteration):
        next(pit)
    assert next(iter([]), "sentinel") == "sentinel"   # contract shape
    assert next(pit, "default") == "default"          # no deadlock
    # error path: exhausted-by-error also keeps raising
    pit2 = DevicePrefetchIter(it, lambda b: 1 / 0)
    with pytest.raises(ZeroDivisionError):
        next(iter(pit2))
    with pytest.raises(StopIteration):
        next(pit2)
