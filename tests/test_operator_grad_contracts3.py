"""Third contract tranche: spatial/detection legacy ops (reference
``tests/python/unittest/test_operator.py`` ROIPooling/BilinearSampler/
SpatialTransformer/GridGenerator families).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (fd_grad_check as _grad_check,  # noqa: E402
                                  fd_rand as _rand)


def test_roi_pooling_forward_semantics():
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    sym = mx.sym.ROIPooling(data, rois, pooled_size=(2, 2),
                            spatial_scale=1.0)
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    r = np.array([[0, 0, 0, 3, 3]], "float32")   # whole image
    out = sym.eval(data=mx.nd.array(x),
                   rois=mx.nd.array(r))[0].asnumpy()
    # 2x2 max pool over the 4x4 region
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_bilinear_sampler_identity_grid():
    data = mx.sym.Variable("data")
    grid = mx.sym.Variable("grid")
    sym = mx.sym.BilinearSampler(data, grid)
    x = _rand(1, 1, 4, 4, seed=1)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    g = np.stack([xs, ys])[None].astype("float32")   # identity sampling
    out = sym.eval(data=mx.nd.array(x), grid=mx.nd.array(g))[0].asnumpy()
    np.testing.assert_allclose(out, x, atol=1e-5)


def test_bilinear_sampler_grad():
    data = mx.sym.Variable("data")
    grid = mx.sym.Variable("grid")
    sym = mx.sym.BilinearSampler(data, grid)
    ys, xs = np.meshgrid(np.linspace(-0.8, 0.8, 3),
                         np.linspace(-0.8, 0.8, 3), indexing="ij")
    g = np.stack([xs, ys])[None].astype("float32")
    _grad_check(sym, {"data": _rand(1, 1, 4, 4, seed=2), "grid": g},
                grad_nodes=["data"])


def test_spatial_transformer_identity():
    data = mx.sym.Variable("data")
    loc = mx.sym.Variable("loc")
    sym = mx.sym.SpatialTransformer(data, loc, target_shape=(4, 4),
                                    transform_type="affine",
                                    sampler_type="bilinear")
    x = _rand(1, 1, 4, 4, seed=3)
    theta = np.array([[1, 0, 0, 0, 1, 0]], "float32")   # identity affine
    out = sym.eval(data=mx.nd.array(x), loc=mx.nd.array(theta))[0].asnumpy()
    np.testing.assert_allclose(out, x, atol=1e-4)


def test_grid_generator_affine():
    loc = mx.sym.Variable("loc")
    sym = mx.sym.GridGenerator(loc, transform_type="affine",
                               target_shape=(3, 3))
    theta = np.array([[1, 0, 0, 0, 1, 0]], "float32")
    out = sym.eval(loc=mx.nd.array(theta))[0].asnumpy()
    assert out.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(out[0, 0, 0], [-1, 0, 1], atol=1e-5)
    np.testing.assert_allclose(out[0, 1, :, 0], [-1, 0, 1], atol=1e-5)


def test_multibox_prior_layout():
    data = mx.sym.Variable("data")
    sym = mx.sym.contrib.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    out = sym.eval(data=mx.nd.zeros((1, 3, 2, 2)))[0].asnumpy()
    assert out.shape == (1, 4, 4)
    # center of the first cell is (0.25, 0.25) with extent 0.5
    np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-5)
