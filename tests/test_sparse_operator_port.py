"""Reference test_sparse_operator.py port: names mirror
tests/python/unittest/test_sparse_operator.py one-for-one (cases already
covered by tests/test_sparse_operator.py keep their deeper variants
there; this file carries the reference-named contracts).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.test_utils import assert_almost_equal, rand_ndarray

_rng = np.random.RandomState


def _rand_csr(rng, shape, density=0.3):
    dense = rng.randn(*shape).astype("float32")
    dense[rng.rand(*shape) > density] = 0
    return sp.csr_matrix(dense), dense


def _rand_rsp(rng, shape, density=0.3):
    dense = rng.randn(*shape).astype("float32")
    keep = rng.rand(shape[0]) < density
    dense[~keep] = 0
    return sp.row_sparse_array(dense), dense


def test_elemwise_binary_ops():
    """add/sub/mul/div across stype combinations keep values right and
    report a sensible output stype."""
    rng = _rng(0)
    a_sp, a = _rand_csr(rng, (6, 8))
    b_sp, b = _rand_csr(rng, (6, 8))
    for op, ref in [(nd.elemwise_add, a + b), (nd.elemwise_sub, a - b),
                    (nd.elemwise_mul, a * b)]:
        got = op(a_sp, b_sp)
        assert_almost_equal(got.asnumpy(), ref, rtol=1e-5, atol=1e-6)
    # rsp + rsp
    ar_sp, ar = _rand_rsp(rng, (6, 4))
    br_sp, br = _rand_rsp(rng, (6, 4))
    assert_almost_equal(nd.elemwise_add(ar_sp, br_sp).asnumpy(), ar + br,
                        rtol=1e-5)
    # sparse + dense falls back to dense
    d = rng.randn(6, 8).astype("float32")
    got = nd.elemwise_add(a_sp, nd.array(d))
    assert_almost_equal(got.asnumpy(), a + d, rtol=1e-5)


def test_elemwise_csr_same_zeros():
    """csr ± csr with identical sparsity patterns keeps exact zeros."""
    rng = _rng(1)
    a_sp, a = _rand_csr(rng, (5, 7), density=0.2)
    got = nd.elemwise_sub(a_sp, a_sp)
    assert np.abs(got.asnumpy()).sum() == 0


def test_sparse_mathematical_core():
    """Zero-preserving unary math on sparse inputs operates on values
    and keeps zeros (reference's sqrt/abs/sign/... core table)."""
    rng = _rng(2)
    a_sp, a = _rand_csr(rng, (5, 6))
    pos = sp.csr_matrix(np.abs(a))
    for name, ref in [("abs", np.abs(a)), ("sign", np.sign(a)),
                      ("sqrt", np.sqrt(np.abs(a))),
                      ("square", np.square(a)),
                      ("sin", np.sin(a)), ("tanh", np.tanh(a)),
                      ("arcsinh", np.arcsinh(a)),
                      ("expm1", np.expm1(a)), ("log1p", np.log1p(np.abs(a)))]:
        x = pos if name in ("sqrt", "log1p") else a_sp
        got = getattr(nd, name)(x)
        assert_almost_equal(got.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_elemwise_add_ex():
    rng = _rng(3)
    shapes = [(4, 5), (3, 3)]
    for shape in shapes:
        a_sp, a = _rand_rsp(rng, shape)
        b_sp, b = _rand_rsp(rng, shape)
        got = nd.elemwise_add(a_sp, b_sp)
        assert_almost_equal(got.asnumpy(), a + b, rtol=1e-5)
        # grads flow through sparse adds
        x, y = sp.row_sparse_array(a), sp.row_sparse_array(b)
        x.attach_grad()
        y.attach_grad()
        with autograd.record():
            z = nd.elemwise_add(x, y).sum()
        z.backward()
        assert_almost_equal(x.grad.asnumpy(), np.ones(shape), rtol=1e-6)


def test_cast_storage_ex():
    """dense<->csr<->row_sparse round trips preserve values."""
    rng = _rng(4)
    dense = rng.randn(6, 5).astype("float32")
    dense[rng.rand(6, 5) > 0.4] = 0
    d = nd.array(dense)
    for stype in ("csr", "row_sparse"):
        s = nd.cast_storage(d, stype=stype)
        assert s.stype == stype
        assert_almost_equal(s.asnumpy(), dense)
        back = nd.cast_storage(s, stype="default")
        assert back.stype == "default"
        assert_almost_equal(back.asnumpy(), dense)


def test_sparse_dot():
    rng = _rng(5)
    a_sp, a = _rand_csr(rng, (4, 6))
    w = rng.randn(6, 5).astype("float32")
    got = nd.dot(a_sp, nd.array(w))
    assert_almost_equal(got.asnumpy(), a @ w, rtol=1e-4)
    # transpose_a: csr.T @ dense -> row_sparse in the reference; values
    # must match regardless of output storage
    got = nd.dot(a_sp, nd.array(rng.randn(4, 3).astype("float32")),
                 transpose_a=True)
    assert got.shape == (6, 3)


def test_sparse_dot_determinism():
    rng = _rng(6)
    a_sp, _ = _rand_csr(rng, (8, 16))
    w = nd.array(rng.randn(16, 4).astype("float32"))
    r1 = nd.dot(a_sp, w).asnumpy()
    r2 = nd.dot(a_sp, w).asnumpy()
    assert (r1 == r2).all()


def test_sparse_slice():
    rng = _rng(7)
    a_sp, a = _rand_csr(rng, (8, 6))
    got = nd.slice(a_sp, begin=(2,), end=(6,))
    assert_almost_equal(got.asnumpy(), a[2:6])


def test_sparse_retain():
    rng = _rng(8)
    a_sp, a = _rand_rsp(rng, (8, 4), density=0.8)
    rows = nd.array(np.array([1, 3, 6], "float32"))
    got = nd.sparse_retain(a_sp, rows)
    ref = np.zeros_like(a)
    ref[[1, 3, 6]] = a[[1, 3, 6]]
    assert_almost_equal(got.asnumpy(), ref)
    assert got.stype == "row_sparse"


def test_sparse_unary_with_numerics():
    """negation/relu-style unaries with gradients on sparse inputs."""
    rng = _rng(9)
    a_sp, a = _rand_rsp(rng, (6, 4), density=0.9)
    x = sp.row_sparse_array(a)
    x.attach_grad()
    with autograd.record():
        y = nd.relu(x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), (a > 0).astype("float32"))


def test_sparse_nd_zeros():
    for stype in ("csr", "row_sparse"):
        z = sp.zeros(stype, (4, 5))
        assert z.stype == stype and z.shape == (4, 5)
        assert np.abs(z.asnumpy()).sum() == 0


def test_sparse_nd_zeros_like():
    rng = _rng(10)
    a_sp, _ = _rand_csr(rng, (4, 5))
    z = nd.zeros_like(a_sp)
    assert np.abs(z.asnumpy()).sum() == 0 and z.shape == (4, 5)


def test_sparse_axis_operations():
    """sum/mean along axes on sparse inputs."""
    rng = _rng(11)
    a_sp, a = _rand_csr(rng, (5, 7))
    assert_almost_equal(nd.sum(a_sp, axis=0).asnumpy(), a.sum(axis=0),
                        rtol=1e-4)
    assert_almost_equal(nd.sum(a_sp, axis=1).asnumpy(), a.sum(axis=1),
                        rtol=1e-4)
    assert_almost_equal(nd.mean(a_sp, axis=1).asnumpy(), a.mean(axis=1),
                        rtol=1e-4)


def test_sparse_square_sum():
    rng = _rng(12)
    a_sp, a = _rand_rsp(rng, (6, 4))
    got = nd._internal._square_sum(a_sp, axis=1) \
        if hasattr(nd, "_internal") and \
        hasattr(nd._internal, "_square_sum") else \
        nd.sum(nd.square(a_sp), axis=1)
    assert_almost_equal(got.asnumpy(), (a ** 2).sum(axis=1), rtol=1e-4)


def test_sparse_storage_fallback():
    """Ops without sparse kernels transparently densify — values stay
    right and no error escapes."""
    rng = _rng(13)
    a_sp, a = _rand_csr(rng, (4, 6))
    got = nd.softmax(a_sp)
    e = np.exp(a - a.max(axis=-1, keepdims=True))
    assert_almost_equal(got.asnumpy(), e / e.sum(axis=-1, keepdims=True),
                        rtol=1e-4)


def test_sparse_elementwise_sum():
    rng = _rng(14)
    arrays = []
    dense_sum = np.zeros((5, 4), "float32")
    for _ in range(3):
        s, d = _rand_rsp(rng, (5, 4))
        arrays.append(s)
        dense_sum += d
    got = nd.add_n(*arrays)
    assert_almost_equal(got.asnumpy(), dense_sum, rtol=1e-5)


def test_contrib_sparse_embedding():
    """contrib.SparseEmbedding-style: sparse_grad Embedding keeps a
    compressed row_sparse gradient."""
    rng = _rng(15)
    w = nd.array(rng.randn(40, 6).astype("float32"))
    w.attach_grad(stype="row_sparse")
    idx = nd.array(np.array([3, 7, 7, 20], "float32"))
    with autograd.record():
        e = nd.Embedding(idx, w, input_dim=40, output_dim=6,
                         sparse_grad=True)
        loss = (e * e).sum()
    loss.backward()
    g = w.grad
    assert g.stype == "row_sparse" and g.is_compressed()
    assert sorted(g.indices.asnumpy().tolist()) == [3, 7, 20]


def test_sparse_embedding():
    """Dense-grad embedding and sparse-grad embedding agree on values."""
    rng = _rng(16)
    table = rng.randn(30, 5).astype("float32")
    idx = np.array([1, 5, 5, 29], "float32")
    out_d = nd.Embedding(nd.array(idx), nd.array(table), input_dim=30,
                         output_dim=5)
    out_s = nd.Embedding(nd.array(idx), nd.array(table), input_dim=30,
                         output_dim=5, sparse_grad=True)
    assert_almost_equal(out_d.asnumpy(), out_s.asnumpy())
    assert_almost_equal(out_d.asnumpy(), table[idx.astype(int)])


def test_sparse_broadcast_add_sub():
    rng = _rng(17)
    a_sp, a = _rand_csr(rng, (4, 6))
    row = rng.randn(1, 6).astype("float32")
    assert_almost_equal(nd.broadcast_add(a_sp, nd.array(row)).asnumpy(),
                        a + row, rtol=1e-5)
    assert_almost_equal(nd.broadcast_sub(a_sp, nd.array(row)).asnumpy(),
                        a - row, rtol=1e-5)


def test_sparse_broadcast_mul_div():
    rng = _rng(18)
    a_sp, a = _rand_csr(rng, (4, 6))
    row = rng.rand(1, 6).astype("float32") + 0.5
    assert_almost_equal(nd.broadcast_mul(a_sp, nd.array(row)).asnumpy(),
                        a * row, rtol=1e-5)
    assert_almost_equal(nd.broadcast_div(a_sp, nd.array(row)).asnumpy(),
                        a / row, rtol=1e-5)


def test_scatter_ops():
    """_scatter_set_nd-style updates used by the sparse optimizers:
    writes land only on the addressed rows."""
    rng = _rng(19)
    w = nd.array(np.zeros((6, 3), "float32"))
    rows = np.array([1, 4], "float32")
    vals = rng.randn(2, 3).astype("float32")
    out = nd.contrib.index_copy(w, nd.array(rows, dtype="int32"),
                                nd.array(vals))
    ref = np.zeros((6, 3), "float32")
    ref[[1, 4]] = vals
    assert_almost_equal(out.asnumpy(), ref)


def test_batchnorm_fallback():
    """BatchNorm on a sparse input densifies and matches dense BN."""
    rng = _rng(20)
    a_sp, a = _rand_rsp(rng, (8, 4), density=0.9)
    gamma = nd.ones(4)
    beta = nd.zeros(4)
    mm = nd.zeros(4)
    mv = nd.ones(4)
    got = nd.BatchNorm(a_sp, gamma, beta, mm, mv, use_global_stats=True,
                       fix_gamma=False, eps=1e-3)
    ref = nd.BatchNorm(nd.array(a), gamma, beta, mm, mv,
                       use_global_stats=True, fix_gamma=False, eps=1e-3)
    assert_almost_equal(got.asnumpy(), ref.asnumpy(), rtol=1e-5)


def test_sparse_nd_where():
    rng = _rng(21)
    cond_sp, cond = _rand_csr(rng, (4, 5), density=0.4)
    x = rng.randn(4, 5).astype("float32")
    y = rng.randn(4, 5).astype("float32")
    got = nd.where(cond_sp, nd.array(x), nd.array(y))
    assert_almost_equal(got.asnumpy(), np.where(cond != 0, x, y))


def test_sparse_quadratic_function():
    rng = _rng(22)
    a_sp, a = _rand_csr(rng, (4, 5))
    got = nd.contrib.quadratic(a_sp, a=2.0, b=0.0, c=0.0)
    assert_almost_equal(got.asnumpy(), 2 * a ** 2, rtol=1e-5)
    # with c != 0 the zeros stop being zeros — dense result, right values
    got = nd.contrib.quadratic(a_sp, a=1.0, b=1.0, c=3.0)
    assert_almost_equal(got.asnumpy(), a ** 2 + a + 3, rtol=1e-5)


def test_reshape_backward_fallback():
    """Gradient flows through reshape of a sparse input (dense grad)."""
    rng = _rng(23)
    a_sp, a = _rand_rsp(rng, (4, 6), density=0.9)
    x = sp.row_sparse_array(a)
    x.attach_grad()
    with autograd.record():
        y = (nd.reshape(x, shape=(2, 12)) * 2).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), np.full((4, 6), 2.0))
