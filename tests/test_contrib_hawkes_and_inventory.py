"""Hawkes-process log likelihood, gradient multiplier, and the internal
op-name inventory (same-shape logic ops, slice-assign, scatter, samplers).

Expected values for hawkesll come from the reference's own test
(`tests/python/unittest/test_contrib_hawkesll.py`), evaluated against its
C++ kernels — exact-parity fixtures.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_hawkesll_univariate_output():
    T, N, K = 4, 4, 3
    mu = nd.array(np.tile(np.array([1.5, 2.0, 3.0], np.float32), (N, 1)))
    alpha = nd.array(np.array([0.2, 0.3, 0.4], np.float32))
    beta = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    lags = nd.array(np.array([[6, 7, 8, 9], [1, 2, 3, 4],
                              [3, 4, 5, 6], [8, 9, 10, 11]], np.float32))
    marks = nd.zeros((N, T)).astype(np.int32)
    states = nd.zeros((N, K))
    valid_length = nd.array(np.array([1, 2, 3, 4], np.float32))
    max_time = nd.ones((N,)) * 100.0
    ll, out_state = nd.contrib.hawkesll(
        mu, alpha, beta, states, lags, marks, valid_length, max_time)
    np.testing.assert_allclose(
        ll.asnumpy(),
        [-649.79453489, -649.57118596, -649.38025115, -649.17811484],
        rtol=1e-5)
    assert out_state.shape == (N, K)


def test_hawkesll_multivariate_output():
    N, K = 2, 3
    mu = np.array([1.5, 2.0, 3.0], np.float32)
    alpha = nd.array(np.array([0.2, 0.3, 0.4], np.float32))
    beta = nd.array(np.array([2.0, 2.0, 2.0], np.float32))
    lags = nd.array(np.array([[6, 7, 8, 9, 3, 2, 5, 1, 7],
                              [1, 2, 3, 4, 2, 1, 2, 1, 4]], np.float32))
    marks = nd.array(np.array([[0, 1, 2, 1, 0, 2, 1, 0, 2],
                               [1, 2, 0, 0, 0, 2, 2, 1, 0]])).astype(np.int32)
    valid_length = nd.array(np.array([7, 9], np.float32))
    max_time = nd.ones((N,)) * 100.0
    ll, _ = nd.contrib.hawkesll(nd.array(np.tile(mu, (N, 1))), alpha, beta,
                                nd.zeros((N, K)), lags, marks,
                                valid_length, max_time)
    np.testing.assert_allclose(ll.asnumpy(), [-647.01240372, -646.28617272],
                               rtol=1e-5)


def test_hawkesll_backward():
    N, K = 2, 3
    mu = nd.array(np.array([1.5, 2.0, 3.0], np.float32))
    alpha = nd.array(np.array([0.2, 0.3, 0.4], np.float32))
    beta = nd.array(np.array([2.0, 2.0, 2.0], np.float32))
    lags = nd.array(np.array([[6, 7, 8, 9, 3, 2, 5, 1, 7],
                              [1, 2, 3, 4, 2, 1, 2, 1, 4]], np.float32))
    marks = nd.array(np.array([[0, 0, 0, 1, 0, 0, 1, 2, 0],
                               [1, 2, 0, 0, 0, 2, 2, 1, 0]])).astype(np.int32)
    valid_length = nd.array(np.array([9, 9], np.float32))
    max_time = nd.ones((N,)) * 100.0
    mu.attach_grad(); alpha.attach_grad(); beta.attach_grad()
    with mx.autograd.record():
        ll, _ = nd.contrib.hawkesll(mu.tile((N, 1)), alpha, beta,
                                    nd.zeros((N, K)), lags, marks,
                                    valid_length, max_time)
    ll.backward()
    np.testing.assert_allclose(
        mu.grad.asnumpy(), [-193.33987481, -198.0, -198.66828681], rtol=1e-5)
    np.testing.assert_allclose(
        alpha.grad.asnumpy(), [-9.95093892, -4.0, -3.98784892], rtol=1e-5)
    np.testing.assert_allclose(
        beta.grad.asnumpy(),
        [-1.49052169e-02, -5.87469511e-09, -7.29065224e-03],
        rtol=1e-4, atol=1e-10)


def test_hawkesll_padded_steps_do_not_poison_gradients():
    # Regression: a padded (invalid) step whose mark has zero baseline used to
    # produce log(0) in the masked where-branch, whose inf cotangent NaN'd
    # every parameter's gradient through the scan carry.
    N, K = 1, 3
    mu = nd.array(np.array([1.5, 2.0, 0.0], np.float32))
    alpha = nd.array(np.array([0.2, 0.3, 0.4], np.float32))
    beta = nd.array(np.array([1.0, 1.0, 1.0], np.float32))
    lags = nd.array(np.array([[1, 2, 1, 1]], np.float32))
    marks = nd.array(np.array([[0, 1, 2, 2]])).astype(np.int32)  # padding = mark 2
    valid_length = nd.array(np.array([2], np.float32))
    max_time = nd.array(np.array([10.0], np.float32))
    mu.attach_grad(); alpha.attach_grad(); beta.attach_grad()
    with mx.autograd.record():
        ll, _ = nd.contrib.hawkesll(mu.reshape((1, K)), alpha, beta,
                                    nd.zeros((N, K)), lags, marks,
                                    valid_length, max_time)
    ll.backward()
    assert np.isfinite(ll.asnumpy()).all()
    for p in (mu, alpha, beta):
        assert np.isfinite(p.grad.asnumpy()).all(), p.grad.asnumpy()


def test_gradientmultiplier_identity_forward_scaled_backward():
    x = nd.array(np.array([1., 2., 3.], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.contrib.gradientmultiplier(x, scalar=-0.5)
    y.backward()
    np.testing.assert_array_equal(y.asnumpy(), [1., 2., 3.])
    np.testing.assert_array_equal(x.grad.asnumpy(), [-0.5, -0.5, -0.5])
    np.testing.assert_array_equal(
        nd.contrib.backward_gradientmultiplier(x, scalar=2.0).asnumpy(),
        [2., 4., 6.])


def test_internal_logic_and_mod_ops():
    a = nd.array(np.array([[1., 2.], [3., 4.]]))
    b = nd.array(np.array([[1., 0.], [3., 5.]]))
    np.testing.assert_array_equal(nd._equal(a, b).asnumpy(), [[1, 0], [1, 0]])
    np.testing.assert_array_equal(nd._not_equal(a, b).asnumpy(), [[0, 1], [0, 1]])
    np.testing.assert_array_equal(nd._greater(a, b).asnumpy(), [[0, 1], [0, 0]])
    np.testing.assert_array_equal(nd._lesser_equal(a, b).asnumpy(), [[1, 0], [1, 1]])
    np.testing.assert_array_equal(nd._logical_and(a, b).asnumpy(), [[1, 0], [1, 1]])
    np.testing.assert_array_equal(nd._logical_xor(a, b).asnumpy(), [[0, 1], [0, 0]])
    np.testing.assert_array_equal(nd._mod(a, nd.array(np.array([[2., 2.], [2., 3.]]))).asnumpy(),
                                  [[1, 0], [1, 1]])
    np.testing.assert_array_equal(nd._grad_add(a, b).asnumpy(), [[2, 2], [6, 9]])
    np.testing.assert_array_equal(nd._copyto(a).asnumpy(), a.asnumpy())


def test_slice_assign_ops():
    x = nd.zeros((4, 4))
    y = nd._slice_assign(x, nd.ones((2, 2)), begin=(1, 1), end=(3, 3))
    want = np.zeros((4, 4)); want[1:3, 1:3] = 1
    np.testing.assert_array_equal(y.asnumpy(), want)
    z = nd._slice_assign_scalar(x, scalar=7, begin=(0,), end=(2,))
    want = np.zeros((4, 4)); want[0:2] = 7
    np.testing.assert_array_equal(z.asnumpy(), want)
    np.testing.assert_array_equal(
        nd._scatter_plus_scalar(nd.ones((2, 2)), scalar=2).asnumpy(),
        np.full((2, 2), 3.0))
    np.testing.assert_array_equal(
        nd._scatter_elemwise_div(nd.ones((2,)) * 6, nd.ones((2,)) * 3).asnumpy(),
        [2., 2.])


def test_square_sum():
    a = nd.array(np.array([[1., 2.], [3., 4.]]))
    np.testing.assert_array_equal(nd._square_sum(a, axis=1).asnumpy(), [5., 25.])
    np.testing.assert_array_equal(nd._square_sum(a).asnumpy(), 30.)


def test_array_parameter_samplers():
    mx.random.seed(7)
    lam = nd.array(np.array([1.0, 50.0], np.float32))
    p = nd._sample_poisson(lam, shape=(3000,))
    assert p.shape == (2, 3000)
    m = p.asnumpy().mean(axis=1)
    assert abs(m[0] - 1.0) < 0.2 and abs(m[1] - 50.0) < 2.0
    e = nd._sample_exponential(lam, shape=(3000,))
    me = e.asnumpy().mean(axis=1)
    assert abs(me[0] - 1.0) < 0.1 and abs(me[1] - 0.02) < 0.01
    nb = nd._sample_negative_binomial(nd.array(np.array([5.0], np.float32)),
                                      nd.array(np.array([0.5], np.float32)),
                                      shape=(4000,))
    assert abs(nb.asnumpy().mean() - 5.0) < 0.5  # mean = k(1-p)/p = 5
    gnb = nd._sample_generalized_negative_binomial(
        nd.array(np.array([4.0], np.float32)),
        nd.array(np.array([0.25], np.float32)), shape=(4000,))
    assert abs(gnb.asnumpy().mean() - 4.0) < 0.5
