"""SPMD sharded checkpoint/resume tests (SURVEY.md §5.4)."""
import json
import os
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import (
    FunctionalOptimizer, SPMDTrainer, make_mesh,
    save_spmd_checkpoint, load_spmd_checkpoint, SPMDCheckpointManager,
)


def _trainer(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu", in_units=8),
                mx.gluon.nn.Dense(4, in_units=16))
    net.initialize()
    mesh = make_mesh(dp=4, tp=2)
    return SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer("adam", 1e-2), mesh), net


def _data():
    rng = np.random.RandomState(42)
    return (rng.randn(16, 8).astype("float32"),
            rng.randint(0, 4, 16).astype("float32"))


def test_checkpoint_roundtrip_resumes_identically(tmp_path):
    x, y = _data()
    tr1, _ = _trainer()
    for _ in range(3):
        tr1.step(x, y)
    save_spmd_checkpoint(str(tmp_path / "ckpt"), tr1)
    after_ckpt = [float(tr1.step(x, y).asnumpy()) for _ in range(3)]

    tr2, _ = _trainer(seed=1)  # different init — must be overwritten
    load_spmd_checkpoint(str(tmp_path / "ckpt"), tr2)
    assert tr2._t == 3
    resumed = [float(tr2.step(x, y).asnumpy()) for _ in range(3)]
    np.testing.assert_allclose(resumed, after_ckpt, rtol=1e-5, atol=1e-6)


def test_checkpoint_manager_rotation(tmp_path):
    x, y = _data()
    tr, _ = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for step in range(4):
        tr.step(x, y)
        mgr.save(step, tr)
    assert mgr.latest_step() == 3
    tr2, _ = _trainer(seed=2)
    mgr2 = SPMDCheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    mgr2.restore(tr2)
    assert tr2._t == 4
    # restored params match the saved trainer's
    for k in tr._state[0]:
        np.testing.assert_allclose(np.asarray(tr._state[0][k]),
                                   np.asarray(tr2._state[0][k]), rtol=1e-6)


def test_manager_layout_is_checksummed_manifest(tmp_path):
    """The durable on-disk format (ISSUE 4): one directory per committed
    step with a manifest recording size + crc32 of the payload, and the
    ``extra`` dict riding along through restore."""
    x, y = _data()
    tr, _ = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=2)
    tr.step(x, y)
    mgr.save(1, tr, extra={"note": "hello"})
    d = os.path.join(str(tmp_path), "step_%010d" % 1)
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    with open(os.path.join(d, "state.bin"), "rb") as f:
        blob = f.read()
    meta = manifest["files"]["state.bin"]
    assert manifest["step"] == 1
    assert meta["size"] == len(blob)
    assert meta["crc32"] == zlib.crc32(blob)
    tr2, _ = _trainer(seed=1)
    mgr.restore(tr2)
    assert mgr.restored_extra == {"note": "hello"}


def test_manager_empty_directory(tmp_path):
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=2)
    assert mgr.latest_step() is None
    assert mgr.complete_steps() == []
    tr, _ = _trainer()
    with pytest.raises(FileNotFoundError):
        mgr.restore(tr)


def test_manager_restore_specific_step(tmp_path):
    x, y = _data()
    tr, _ = _trainer()
    mgr = SPMDCheckpointManager(str(tmp_path), max_to_keep=5)
    for s in (1, 2, 3):
        tr.step(x, y)
        mgr.save(s, tr)
    tr2, _ = _trainer(seed=1)
    mgr.restore(tr2, step=2)
    assert tr2._t == 2


def test_checkpoint_telemetry_spans(tmp_path):
    """save/restore land as checkpoint.* spans with bytes and the
    serialize-vs-IO split (ISSUE 2 satellite)."""
    from mxnet_tpu import telemetry
    telemetry.disable()
    telemetry.reset()
    telemetry.enable()
    try:
        x, y = _data()
        tr, _ = _trainer()
        tr.step(x, y)
        save_spmd_checkpoint(str(tmp_path / "ckpt"), tr)
        load_spmd_checkpoint(str(tmp_path / "ckpt"), tr)
        spans = telemetry.span_aggregates()
        for name in ("checkpoint.save", "checkpoint.restore",
                     "checkpoint.serialize", "checkpoint.io",
                     "checkpoint.deserialize"):
            assert name in spans, (name, sorted(spans))
        snap = telemetry.snapshot()
        c = snap["counters"]
        assert c["checkpoint.saves"] == 1
        assert c["checkpoint.restores"] == 1
        assert c["checkpoint.bytes_written"] > 0
        assert c["checkpoint.bytes_read"] == c["checkpoint.bytes_written"]
        evs = {e[1]: e for e in telemetry.bus.events()}
        assert evs["checkpoint.save"][6]["bytes_written"] > 0
        assert evs["checkpoint.restore"][6]["bytes_read"] > 0
    finally:
        telemetry.disable()
        telemetry.reset()
