"""Symbol API depth tranche (reference
``tests/python/unittest/test_symbol.py``): compose, copy/pickle,
internals/children, infer_type, fluent methods, zero-prop, grouping,
same-name children.
"""
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    net = mx.sym.Activation(net, name="act1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    return net


def test_symbol_basic_listing():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"]
    assert net.list_outputs() == ["fc2_output"]
    assert net.name == "fc2"


def test_symbol_compose_call():
    """reference test_symbol_compose: calling a symbol re-binds its
    variable inputs."""
    data = mx.sym.Variable("data")
    net1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    net1 = mx.sym.FullyConnected(net1, name="fc2", num_hidden=100)

    net2 = mx.sym.FullyConnected(mx.sym.Variable("data2"), name="fc3",
                                 num_hidden=10)
    net2 = mx.sym.Activation(net2, act_type="relu")
    net2 = mx.sym.FullyConnected(net2, name="fc4", num_hidden=20)
    composed = net2(data2=net1, name="composed")
    args = composed.list_arguments()
    assert "fc1_weight" in args and "fc4_bias" in args
    assert "data2" not in args          # replaced by net1's graph
    # the composed graph runs
    ex = composed.simple_bind(ctx=mx.cpu(), data=(2, 8))
    ex.forward()
    assert ex.outputs[0].shape == (2, 20)


def test_symbol_copy_independent():
    net = _mlp()
    c = net.__copy__() if hasattr(net, "__copy__") else pickle.loads(
        pickle.dumps(net))
    assert c.list_arguments() == net.list_arguments()
    assert c.tojson() == net.tojson()


def test_symbol_pickle_roundtrip():
    net = _mlp()
    s = pickle.dumps(net)
    net2 = pickle.loads(s)
    assert net2.tojson() == net.tojson()
    ex = net2.simple_bind(ctx=mx.cpu(), data=(2, 6))
    ex.forward()
    assert ex.outputs[0].shape == (2, 4)


def test_symbol_internals_and_children():
    net = _mlp()
    internals = net.get_internals()
    outs = internals.list_outputs()
    assert "fc1_output" in outs and "act1_output" in outs
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    children = net.get_children()
    assert "act1_output" in children.list_outputs()
    # grandchildren
    gc = children.get_children() if hasattr(children, "get_children") \
        else None


def test_symbol_infer_type():
    data = mx.sym.Variable("data")
    f32 = mx.sym.FullyConnected(data, name="fc1", num_hidden=3)
    arg_types, out_types, aux_types = f32.infer_type(data="float32")
    assert all(t == np.float32 for t in arg_types)
    assert out_types[0] == np.float32


def test_symbol_infer_shape_backward_inference():
    """reference test_symbol_infer_shape: shapes flow from the OUTPUT
    side too (partial inference given an intermediate)."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc", num_hidden=12)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(7, 5))
    assert out_shapes == [(7, 12)]
    d = dict(zip(out.list_arguments(), arg_shapes))
    assert d["fc_weight"] == (12, 5) and d["fc_bias"] == (12,)


def test_symbol_fluent_methods():
    """reference test_symbol_fluent: tensor methods exist on symbols and
    compute identically to their nd twins."""
    x_np = np.random.RandomState(0).rand(2, 3, 4).astype("float32") + 0.5
    checks = [
        ("reshape", lambda s: s.reshape((2, 12)),
         lambda a: a.reshape(2, 12)),
        ("transpose", lambda s: s.transpose((1, 0, 2)),
         lambda a: a.transpose(1, 0, 2)),
        ("sum", lambda s: s.sum(axis=1), lambda a: a.sum(axis=1)),
        ("mean", lambda s: s.mean(axis=0), lambda a: a.mean(axis=0)),
        ("max", lambda s: s.max(axis=2), lambda a: a.max(axis=2)),
        ("log", lambda s: s.log(), lambda a: np.log(a)),
        ("sqrt", lambda s: s.sqrt(), lambda a: np.sqrt(a)),
        ("square", lambda s: s.square(), lambda a: a * a),
        ("flatten", lambda s: s.flatten(), lambda a: a.reshape(2, 12)),
        ("expand_dims", lambda s: s.expand_dims(axis=0),
         lambda a: a[None]),
        ("clip", lambda s: s.clip(0.6, 1.0),
         lambda a: np.clip(a, 0.6, 1.0)),
        ("abs", lambda s: s.abs(), lambda a: np.abs(a)),
    ]
    for nm, sym_fn, np_fn in checks:
        v = mx.sym.Variable("x")
        try:
            out = sym_fn(v)
        except AttributeError:
            pytest.fail(f"Symbol lacks fluent method {nm}")
        ex = out.simple_bind(ctx=mx.cpu(), x=x_np.shape)
        ex.arg_dict["x"][:] = mx.nd.array(x_np)
        ex.forward()
        np.testing.assert_allclose(ex.outputs[0].asnumpy(), np_fn(x_np),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"fluent {nm}")


def test_blockgrad_stops_gradient():
    x = mx.sym.Variable("x")
    y = mx.sym.BlockGrad(x * 2) + x
    ex = y.simple_bind(ctx=mx.cpu(), x=(3,), grad_req="write")
    ex.arg_dict["x"][:] = 1.0
    ex.forward(is_train=True)
    ex.backward()
    # only the un-blocked path contributes
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), [1, 1, 1])


def test_zero_prop_unused_input_gets_zero_grad():
    """reference test_zero_prop: an argument that doesn't reach the loss
    gets zero gradient, not garbage."""
    x = mx.sym.Variable("x")
    u = mx.sym.Variable("unused")
    y = mx.sym.sum(x * 3)
    g = mx.sym.Group([y, mx.sym.BlockGrad(u)])
    ex = g.simple_bind(ctx=mx.cpu(), x=(2, 2), unused=(2, 2),
                       grad_req="write")
    ex.arg_dict["x"][:] = 1.0
    ex.arg_dict["unused"][:] = 5.0
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones(()), mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               np.full((2, 2), 3.0))
    np.testing.assert_allclose(ex.grad_dict["unused"].asnumpy(),
                               np.zeros((2, 2)))


def test_children_same_name():
    """reference test_children_same_name: two uses of one symbol keep a
    consistent graph."""
    a = mx.sym.Variable("data")
    b = a + a
    for c in b.get_children():
        assert c.list_outputs()[0] == "data"


def test_group_and_multi_output_indexing():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    g = mx.sym.Group([a * 2, b + 1])
    assert len(g.list_outputs()) == 2
    first = g[0]
    ex = first.simple_bind(ctx=mx.cpu(), a=(2,))
    ex.arg_dict["a"][:] = 3.0
    ex.forward()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [6, 6])


def test_symbol_attr_round_trip():
    with mx.AttrScope(ctx_group="dev1"):
        v = mx.sym.Variable("v", lr_mult=2.0)
    assert v.attr("ctx_group") == "dev1"
    assert float(v.attr("lr_mult")) == 2.0
    net = mx.sym.FullyConnected(v, name="fc", num_hidden=2)
    d = net.attr_dict()
    assert d["v"]["ctx_group"] == "dev1"
    assert d["fc"]["num_hidden"] == "2"


def test_compose_rejects_grouped_operand():
    net = mx.sym.sqrt(mx.sym.Variable("x"))
    g = mx.sym.Group([mx.sym.Variable("a") * 2,
                      mx.sym.Variable("b") + 1])
    with pytest.raises(ValueError, match="grouped"):
        net(x=g)


def test_compose_renames_head():
    net = mx.sym.FullyConnected(mx.sym.Variable("d"), name="fc",
                                num_hidden=2)
    composed = net(d=mx.sym.Variable("other") * 2, name="composed")
    assert composed.name == "composed"
    assert composed.list_outputs() == ["composed_output"]


def test_symbol_numpy_mix_rejected():
    with pytest.raises(TypeError, match="mix Symbol"):
        mx.nd.broadcast_add(mx.sym.var("a"), np.ones((2, 2)))


# --- r4: reference test_attr.py family

def test_attr_scope_precedence_and_pickle():
    """reference test_attr_basic: explicit attrs beat the enclosing
    scope; attrs survive pickling."""
    with mx.AttrScope(group="4", data="great"):
        data = mx.sym.Variable("data", attr={"dtype": "data",
                                             "group": "1"}, lr_mult=1)
        gdata = mx.sym.Variable("data2")
    assert gdata.attr("group") == "4"
    assert data.attr("group") == "1"
    assert str(data.attr("lr_mult")) == "1"
    d2 = pickle.loads(pickle.dumps(data))
    assert d2.attr("dtype") == data.attr("dtype")


def test_attr_scope_applies_to_ops_and_nests():
    """reference test_operator: scopes attach to op nodes and nest."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(__data__="great"):
        fc1 = mx.sym.Activation(data, act_type="relu")
        with mx.AttrScope(__init_bias__="0.0"):
            fc2 = mx.sym.FullyConnected(fc1, num_hidden=10, name="fc2")
    assert fc1.attr("__data__") == "great"
    assert fc2.attr("__data__") == "great"
    assert fc2.attr("__init_bias__") == "0.0"
    fc2copy = pickle.loads(pickle.dumps(fc2))
    assert fc2copy.tojson() == fc2.tojson()


def test_attr_dict_collects_per_node():
    """reference test_attr_dict: attr_dict exposes variable attrs and op
    hyperparameters per node."""
    data = mx.sym.Variable("data", attr={"mood": "angry"})
    op = mx.sym.Convolution(data=data, name="conv", kernel=(1, 1),
                            num_filter=1)
    d = op.attr_dict()
    assert d["data"]["mood"] == "angry"
    assert d["conv"]["num_filter"] == "1"
    assert d["conv"]["kernel"] == "(1, 1)"


# --- r4: reference test_infer_shape.py family

def test_mlp2_infer_shape_full():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc1", num_hidden=1000)
    out = mx.sym.Activation(out, act_type="relu")
    out = mx.sym.FullyConnected(out, name="fc2", num_hidden=10)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(100, 100))
    d = dict(zip(out.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (1000, 100)
    assert d["fc1_bias"] == (1000,)
    assert d["fc2_weight"] == (10, 1000)
    assert out_shapes == [(100, 10)]


def test_infer_shape_error_is_loud():
    """reference test_mlp2_infer_error: inconsistent shapes raise."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc1", num_hidden=1000)
    out = mx.sym.elemwise_add(out, mx.sym.Variable("extra"))
    with pytest.raises(Exception):
        out.infer_shape(data=(100, 100), extra=(50, 50))


def test_incomplete_infer_partial():
    """reference test_incomplete_infer_*: infer_shape_partial returns
    what it can without raising."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc", num_hidden=8)
    res = out.infer_shape_partial()
    assert res is not None              # no exception with nothing known


def test_conv_infer_shape_chain():
    """reference test_incomplete_infer_convolution analog with full
    input: conv weight/bias shapes derive from data."""
    data = mx.sym.Variable("data")
    out = mx.sym.Convolution(data, name="conv", kernel=(3, 3),
                             num_filter=6, pad=(1, 1))
    arg_shapes, out_shapes, _ = out.infer_shape(data=(2, 5, 9, 9))
    d = dict(zip(out.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (6, 5, 3, 3)
    assert d["conv_bias"] == (6,)
    assert out_shapes == [(2, 6, 9, 9)]


def test_fc_infer_type_f16():
    """reference test_fc_infer_type: dtype propagates through FC."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc", num_hidden=3)
    arg_types, out_types, _ = out.infer_type(data="float16")
    d = dict(zip(out.list_arguments(), arg_types))
    assert out_types[0] == np.float16
