"""serving.fleet: crash-supervised device-owner + fault-tolerant RPC
(ISSUE 19 tentpole).

Layered coverage: frame codec (crc, magic, size cap, restricted
unpickler), client/server RPC semantics over a real AF_UNIX socket
(deadline propagation, typed error mapping, streaming, cancel,
heartbeats), transport fault sites (``fleet.rpc_send`` redial), and the
supervisor (spawn readiness, SIGKILL auto-restart with generation bump,
``fleet.owner_spawn`` retry under backoff).  The full chaos drill —
200 concurrent HTTP requests across two owner kills — lives in the CI
``fleet`` stage, not here.
"""
import os
import pickle
import signal
import socket
import threading
import time
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.retry import RetryPolicy
from mxnet_tpu.serving.batcher import RequestRejected
from mxnet_tpu.serving.fleet import (FrameError, OwnerClient, OwnerGone,
                                     RemoteError, RPCServer)
from mxnet_tpu.serving.fleet import transport as T


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    faults.clear()
    yield
    telemetry.disable()
    telemetry.reset()
    faults.clear()


# ------------------------------------------------------------ frame codec
def _pair():
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    return a, b


def test_frame_roundtrip_all_kinds():
    a, b = _pair()
    try:
        for kind in (T.REQ, T.RES, T.STREAM, T.PING, T.PONG, T.CANCEL):
            payload = {"id": kind, "blob": np.arange(kind + 1.0),
                       "nested": {"k": [1, 2, 3]}}
            T.send_frame(a, kind, payload)
            got_kind, got = T.recv_frame(b)
            assert got_kind == kind
            assert got["id"] == kind
            np.testing.assert_array_equal(got["blob"], payload["blob"])
            assert got["nested"] == payload["nested"]
    finally:
        a.close()
        b.close()


def test_frame_crc_mismatch_rejected():
    a, b = _pair()
    try:
        data = pickle.dumps({"x": 1})
        bad_crc = (zlib.crc32(data) ^ 0xdead) & 0xffffffff
        frame = T._HEADER.pack(T._MAGIC, T.RES, len(data), bad_crc)
        a.sendall(frame + data)
        with pytest.raises(FrameError, match="crc"):
            T.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_frame_bad_magic_rejected():
    a, b = _pair()
    try:
        a.sendall(T._HEADER.pack(b"NOPE", T.RES, 0, 0))
        with pytest.raises(FrameError, match="magic"):
            T.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_frame_oversize_rejected():
    a, b = _pair()
    try:
        a.sendall(T._HEADER.pack(T._MAGIC, T.RES, T.MAX_FRAME + 1, 0))
        with pytest.raises(FrameError, match="exceeds"):
            T.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_frame_eof_is_owner_gone():
    a, b = _pair()
    a.close()
    try:
        with pytest.raises(OwnerGone):
            T.recv_frame(b)
    finally:
        b.close()


def test_restricted_unpickler_blocks_foreign_classes():
    # any non-numpy/builtins class is refused — even this framework's own
    evil = pickle.dumps(RetryPolicy())
    with pytest.raises(pickle.UnpicklingError, match="forbidden"):
        T._loads(evil)
    # the allowed surface (numpy + builtins) round-trips
    ok = T._loads(T._dumps({"a": np.float32(2.5), "b": [1, "x"]}))
    assert ok["a"] == np.float32(2.5)


# ----------------------------------------------------- RPC client / server
class EchoService:
    """Duck-typed service capturing what the wire delivered."""

    def __init__(self):
        self.seen = []            # (method, params, deadline_ms, trace)
        self.cancelled = []
        self.release = threading.Event()

    def pong(self):
        return {"pid": os.getpid(), "generation": 7}

    def cancel(self, key):
        self.cancelled.append(key)
        self.release.set()

    def handle(self, method, params, deadline_ms, trace, emit,
               register_cancel):
        self.seen.append((method, dict(params), deadline_ms, trace))
        if method == "echo":
            return {"echo": params}
        if method == "boom_key":
            raise KeyError("no such model")
        if method == "boom_value":
            raise ValueError("bad arg")
        if method == "boom_reject":
            raise RequestRejected("backpressure", "queue full")
        if method == "boom_bug":
            raise RuntimeError("owner bug")
        if method == "slow":
            self.release.wait(timeout=10.0)
            return {"done": True}
        if method == "stream":
            register_cancel("req-key")
            for i in range(int(params["n"])):
                emit({"token": i * 10, "index": i})
            return {"count": int(params["n"])}
        if method == "stream_cancel":
            register_cancel("req-key")
            emit({"token": 0, "index": 0})
            self.release.wait(timeout=10.0)
            return {"count": 1, "cancelled": bool(self.cancelled)}
        raise KeyError(method)


@pytest.fixture()
def rpc(tmp_path):
    path = str(tmp_path / "owner.sock")
    svc = EchoService()
    server = RPCServer(path, svc)
    client = OwnerClient(path, retry=RetryPolicy(
        max_attempts=4, base_delay_ms=10.0, max_delay_ms=50.0, seed=0))
    yield svc, server, client, path
    client.close()
    server.close()


def test_rpc_roundtrip_and_deadline_propagation(rpc):
    svc, _server, client, _ = rpc
    out = client.call("echo", {"x": 1}, deadline_ms=1234.5)
    assert out == {"echo": {"x": 1}}
    method, params, deadline, _trace = svc.seen[0]
    assert method == "echo" and params == {"x": 1}
    assert deadline == pytest.approx(1234.5)   # rode the wire


def test_rpc_trace_context_rides_frames(rpc):
    svc, _server, client, _ = rpc

    class Ctx:
        trace_id, span_id = 0xabc, 0xdef

    client.call("echo", {}, trace=Ctx())
    assert tuple(svc.seen[0][3]) == (0xabc, 0xdef)


def test_rpc_typed_error_mapping(rpc):
    _svc, _server, client, _ = rpc
    with pytest.raises(KeyError):
        client.call("boom_key")
    with pytest.raises(ValueError, match="bad arg"):
        client.call("boom_value")
    with pytest.raises(RequestRejected) as ei:
        client.call("boom_reject")
    assert ei.value.reason == "backpressure"
    with pytest.raises(RemoteError, match="owner bug"):
        client.call("boom_bug")
    # the server survives every one of those
    assert client.call("echo", {"ok": 1}) == {"echo": {"ok": 1}}


def test_rpc_streaming_and_terminal_result(rpc):
    _svc, _server, client, _ = rpc
    stream = client.stream("stream", {"n": 4}, deadline_ms=10_000)
    frames = list(stream)
    assert [f["token"] for f in frames] == [0, 10, 20, 30]
    assert stream.result() == {"count": 4}


def test_rpc_stream_cancel_routes_to_service(rpc):
    svc, _server, client, _ = rpc
    stream = client.stream("stream_cancel", {}, timeout=10.0)
    first = next(iter(stream))
    assert first["token"] == 0
    stream.cancel()
    assert svc.release.wait(timeout=5.0)
    assert stream.result()["cancelled"] is True
    assert svc.cancelled == ["req-key"]


def test_rpc_ping_heartbeat(rpc):
    _svc, _server, client, _ = rpc
    pong = client.ping(timeout=2.0)
    assert pong["pid"] == os.getpid() and pong["generation"] == 7


def test_rpc_heartbeat_answers_while_request_runs(rpc):
    svc, _server, client, _ = rpc
    done = {}

    def slow():
        done["r"] = client.call("slow", timeout=10.0)

    t = threading.Thread(target=slow, daemon=True)
    t.start()
    time.sleep(0.1)
    assert client.ping(timeout=2.0)["generation"] == 7   # not head-blocked
    svc.release.set()
    t.join(timeout=5.0)
    assert done["r"] == {"done": True}


def test_rpc_call_timeout(rpc):
    _svc, _server, client, _ = rpc
    with pytest.raises(TimeoutError):
        client.call("slow", timeout=0.2)


def test_server_death_fails_outstanding_calls_with_owner_gone(rpc):
    svc, server, client, _ = rpc
    errs = []

    def slow():
        try:
            client.call("slow", timeout=10.0)
        except Exception as e:          # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=slow, daemon=True)
    t.start()
    time.sleep(0.1)
    server.close()
    t.join(timeout=5.0)
    assert len(errs) == 1 and isinstance(errs[0], OwnerGone)
    svc.release.set()


def test_rpc_send_fault_tears_call_next_call_redials(rpc, tmp_path):
    _svc, _server, client, _ = rpc
    telemetry.enable()
    client.call("echo", {"warm": 1})          # established connection
    with faults.scope("fleet.rpc_send:fail:1"):
        # a torn send is OwnerGone for THIS call — retrying an
        # idempotent request is the caller's (gateway's) decision
        with pytest.raises(OwnerGone):
            client.call("echo", {"x": 2})
    out = client.call("echo", {"x": 3})       # next call redials
    assert out == {"echo": {"x": 3}}
    assert client.reconnects >= 1
    snap = telemetry.snapshot()["counters"]
    assert snap.get("fleet.transport_failures", 0) >= 1
    assert snap.get("fleet.reconnects", 0) >= 1


def test_client_without_retry_raises_on_dead_socket(tmp_path):
    client = OwnerClient(str(tmp_path / "nothing.sock"),
                         retry=RetryPolicy(max_attempts=1))
    with pytest.raises(OSError):
        client.call("echo", {})
    client.close()


def test_stale_socket_file_is_replaced(tmp_path):
    path = str(tmp_path / "stale.sock")
    left = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    left.bind(path)                     # a SIGKILLed predecessor's leavings
    left.close()
    svc = EchoService()
    server = RPCServer(path, svc)
    client = OwnerClient(path)
    try:
        assert client.call("echo", {"a": 1}) == {"echo": {"a": 1}}
    finally:
        client.close()
        server.close()
    assert not os.path.exists(path)     # close() unlinks


# -------------------------------------------------------------- supervisor
EMPTY_SPEC = "tests.fleet_builder:build_empty"


def _fast_supervisor(tmp_path, **kw):
    from mxnet_tpu.serving.fleet import Supervisor
    kw.setdefault("heartbeat_s", 0.2)
    kw.setdefault("backoff", RetryPolicy(max_attempts=4, base_delay_ms=20.0,
                                         max_delay_ms=100.0, seed=0))
    kw.setdefault("stable_s", 0.5)
    return Supervisor(EMPTY_SPEC, str(tmp_path / "owner.sock"), **kw)


def test_supervisor_spawn_ping_stats_stop(tmp_path):
    sup = _fast_supervisor(tmp_path)
    sup.start()
    try:
        assert sup.alive
        cli = sup.client()
        pong = cli.ping(timeout=5.0)
        assert pong["pid"] == sup.owner_pid
        assert pong["generation"] == 0
        stats = cli.call("stats", timeout=10.0)
        assert stats["pid"] == sup.owner_pid
        assert stats["infer_models"] == []
        cli.close()
    finally:
        sup.stop()
    assert not sup.alive
    assert not os.path.exists(sup.socket_path)


def test_supervisor_restarts_after_sigkill(tmp_path):
    telemetry.enable()
    sup = _fast_supervisor(tmp_path)
    sup.start()
    try:
        pid0 = sup.owner_pid
        os.kill(pid0, signal.SIGKILL)
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and sup.restarts < 1:
            time.sleep(0.05)
        assert sup.restarts == 1
        assert sup.generation == 1
        # the replacement answers, with a new pid and the bumped generation
        cli = sup.client()
        pong = cli.ping(timeout=10.0)
        assert pong["pid"] == sup.owner_pid != pid0
        assert pong["generation"] == 1
        cli.close()
        snap = telemetry.snapshot()["counters"]
        assert snap.get("fleet.owner_restarts", 0) >= 1
    finally:
        sup.stop()


def test_supervisor_owner_spawn_fault_retried(tmp_path):
    faults.inject("fleet.owner_spawn", "fail:1")
    sup = _fast_supervisor(tmp_path)
    try:
        sup.start()                     # first spawn injected dead, retried
        assert sup.alive
        cli = sup.client()
        assert cli.ping(timeout=5.0)["generation"] == 0
        cli.close()
    finally:
        sup.stop()


def test_supervisor_spawn_gives_up_after_budget(tmp_path):
    from mxnet_tpu.serving.fleet import Supervisor
    faults.inject("fleet.owner_spawn", "fail:10")
    sup = Supervisor(EMPTY_SPEC, str(tmp_path / "owner.sock"),
                     backoff=RetryPolicy(max_attempts=2, base_delay_ms=5.0,
                                         seed=0))
    with pytest.raises(faults.InjectedFault):
        sup.start()
    sup.stop()


# ------------------------------------------------- multi-front-end drill
@pytest.mark.slow
def test_two_gateway_frontends_share_one_owner(tmp_path):
    """The scale-out topology: two gateway *processes* (separate HTTP
    front doors, separate crash domains) proxy one supervised device
    owner over its unix socket.  Both answer 200 with bitwise-identical
    tokens, keep answering after the owner is SIGKILLed and respawned
    (each front end redials the socket on its next call — no front-end
    restart, no lost port), and the fleet socket is the ONLY thing the
    front ends share."""
    import http.client
    import json
    import subprocess
    import sys

    from mxnet_tpu.serving.fleet import Supervisor

    sup = Supervisor("tests.fleet_builder:build",
                     str(tmp_path / "owner.sock"),
                     aot_cache=str(tmp_path / "aot"), heartbeat_s=0.3)
    sup.start()
    procs, ports = [], []

    def post(port, body, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/v1/generate", json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    body = {"model": "decode_tiny", "prompt": [5, 9, 2],
            "max_new_tokens": 6, "temperature": 0.8, "seed": 11,
            "deadline_ms": 60000}
    try:
        for _ in range(2):
            p = subprocess.Popen(
                [sys.executable,
                 os.path.join(os.path.dirname(__file__),
                              "gateway_frontend_worker.py"),
                 "--socket", sup.socket_path],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            procs.append(p)
            hello = json.loads(p.stdout.readline())
            ports.append(hello["port"])
        assert ports[0] != ports[1]
        ref = None
        for port in ports:
            st, raw = post(port, body)
            assert st == 200, (port, st, raw)
            toks = json.loads(raw)["token_ids"]
            ref = toks if ref is None else ref
            assert toks == ref, (port, toks, ref)
        pid0 = sup.owner_pid
        os.kill(pid0, signal.SIGKILL)
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline and sup.restarts < 1:
            time.sleep(0.05)
        assert sup.restarts >= 1 and sup.owner_pid != pid0
        # both front ends keep serving the SAME bitwise stream through
        # the replacement owner — no front-end process was touched.
        # While the replacement binds its socket the documented
        # degradation is 503 owner_unavailable (+ Retry-After), never a
        # 5xx crash or a dead port — so: retry until 200, tolerating
        # ONLY 503 in between.
        for port in ports:
            deadline = time.perf_counter() + 60.0
            while True:
                st, raw = post(port, body)
                if st == 200:
                    break
                assert st == 503, (port, st, raw)
                assert time.perf_counter() < deadline, (port, raw)
                time.sleep(0.2)
            assert json.loads(raw)["token_ids"] == ref
    finally:
        for p in procs:
            try:
                p.stdin.close()
                p.wait(timeout=10)
            except Exception:
                p.kill()
        sup.stop()
    assert not os.path.exists(sup.socket_path)
