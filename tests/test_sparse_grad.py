"""Row-sparse gradient path (reference ``src/operator/optimizer_op.cc``
sparse kernels, ``python/mxnet/optimizer/optimizer.py`` lazy_update,
``include/mxnet/kvstore.h:213`` RowSparsePull, and
``tests/python/train/test_sparse_fm.py``-style embedding training).

The capability under test is asymptotic, not just numeric: gradients for
``Embedding(sparse_grad=True)`` must be O(batch·dim) compressed rows, the
lazy optimizers must touch only present rows (absent rows keep stale
momentum), and ``row_sparse_pull`` must return only the requested rows.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray.sparse import RowSparseNDArray

VOCAB, DIM = 50000, 16


def _embed(vocab=VOCAB, dim=DIM):
    emb = mx.gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    emb(mx.nd.zeros((1, 1), dtype="int32"))   # materialize deferred init
    return emb


def test_row_sparse_ctor_is_compressed():
    rs = mx.nd.sparse.row_sparse_array(
        (np.ones((2, 4), "float32"), [1, 5]), shape=(10000, 4))
    assert rs.is_compressed()
    np.testing.assert_array_equal(rs.indices.asnumpy(), [1, 5])
    assert rs.data.shape == (2, 4)
    # dense materialization is lazy and correct
    d = rs.asnumpy()
    assert d.shape == (10000, 4) and d[1].sum() == 4 and d[2].sum() == 0


def test_embedding_sparse_grad_memory_is_o_batch():
    emb = _embed()
    x = mx.nd.array([[3, 17, 3], [99, 4096, 17]], dtype="int32")
    with mx.autograd.record():
        emb(x).sum().backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray) and g.is_compressed()
    rows, vals = g._rs
    # O(batch·dim): 6 token slots, never (VOCAB, DIM)
    assert vals.shape == (6, DIM)
    assert g._dense is None, "gradient must not densify"
    # duplicates are summed into one row
    got = dict(zip(np.asarray(g.indices.asnumpy()).tolist(),
                   np.asarray(g.data.asnumpy())[:, 0].tolist()))
    assert got[3] == pytest.approx(2.0)
    assert got[17] == pytest.approx(2.0)
    assert got[4096] == pytest.approx(1.0)
    assert sorted(got) == [3, 17, 99, 4096]


def test_lazy_sgd_momentum_absent_rows_stay_stale():
    """Reference SGDMomLazyUpdateRspImpl: a row absent from the batch keeps
    its momentum *unchanged* (no decay applied) and its weight frozen."""
    emb = _embed(vocab=100, dim=4)
    tr = mx.gluon.Trainer(emb.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9})
    # step 1 touches rows {1, 2}
    with mx.autograd.record():
        emb(mx.nd.array([[1, 2]], dtype="int32")).sum().backward()
    tr.step(1)
    state = tr._updaters[0].states
    mom = next(iter(state.values()))
    mom = mom[0] if isinstance(mom, (list, tuple)) else mom
    mom1 = mom.asnumpy().copy()
    w1 = emb.weight.data().asnumpy().copy()
    assert np.abs(mom1[1]).sum() > 0 and np.abs(mom1[2]).sum() > 0
    # step 2 touches only row {2}: row 1 must be completely frozen
    with mx.autograd.record():
        emb(mx.nd.array([[2]], dtype="int32")).sum().backward()
    tr.step(1)
    mom2 = mom.asnumpy()
    w2 = emb.weight.data().asnumpy()
    np.testing.assert_array_equal(mom2[1], mom1[1])   # stale momentum kept
    np.testing.assert_array_equal(w2[1], w1[1])       # weight frozen
    assert np.abs(mom2[2] - mom1[2]).sum() > 0        # present row updated


def test_lazy_sgd_matches_rowwise_formula():
    emb = _embed(vocab=30, dim=4)
    lr, momentum, wd = 0.1, 0.9, 0.01
    tr = mx.gluon.Trainer(emb.collect_params(), "sgd",
                          {"learning_rate": lr, "momentum": momentum,
                           "wd": wd})
    w0 = emb.weight.data().asnumpy().copy()
    x = mx.nd.array([[5, 9]], dtype="int32")
    with mx.autograd.record():
        emb(x).sum().backward()
    tr.step(1)
    w1 = emb.weight.data().asnumpy()
    for r in (5, 9):
        g = np.ones(4, "float32") + wd * w0[r]   # rescale=1 (batch 1)
        expect = w0[r] + (momentum * 0 - lr * g)
        np.testing.assert_allclose(w1[r], expect, rtol=1e-6)
    untouched = [r for r in range(30) if r not in (5, 9)]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])


@pytest.mark.parametrize("optname,kw", [
    ("adagrad", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
])
def test_lazy_adagrad_adam_touch_only_present_rows(optname, kw):
    emb = _embed(vocab=64, dim=4)
    tr = mx.gluon.Trainer(emb.collect_params(), optname, dict(kw))
    w0 = emb.weight.data().asnumpy().copy()
    with mx.autograd.record():
        emb(mx.nd.array([[7, 13]], dtype="int32")).sum().backward()
    tr.step(1)
    w1 = emb.weight.data().asnumpy()
    changed = np.nonzero(np.abs(w1 - w0).sum(axis=1))[0].tolist()
    assert sorted(changed) == [7, 13]


def test_sparse_embedding_model_trains():
    """Sparse-FM-style workload: bag-of-tokens embedding + linear head
    learns a separable toy problem with lazy sparse updates only."""
    vocab, dim, nclass = 10000, 8, 3
    rng = np.random.RandomState(0)
    # class c ≡ tokens drawn from a distinct, far-apart vocab region
    xs = np.stack([rng.randint(c * 3000, c * 3000 + 50, size=4)
                   for c in rng.randint(0, nclass, 200).tolist()])
    ys = (xs[:, 0] // 3000).astype("float32")

    net = mx.gluon.nn.Sequential()
    emb = mx.gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    net.add(emb)
    net.add(mx.gluon.nn.Lambda(lambda x: x.mean(axis=1)))
    net.add(mx.gluon.nn.Dense(nclass))
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "adam",
                          {"learning_rate": 0.05})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    first = last = None
    for _ in range(30):
        with mx.autograd.record():
            loss = loss_fn(net(mx.nd.array(xs, dtype="int32")),
                           mx.nd.array(ys))
        loss.backward()
        tr.step(len(xs))
        v = float(loss.mean().asscalar())
        first = v if first is None else first
        last = v
    assert last < first * 0.3, (first, last)
    g = emb.weight.grad()
    assert g.is_compressed(), "training must keep gradients compressed"


def test_hybridized_embedding_falls_back_dense_correctly():
    """Under hybridize the fused jit produces dense grads; writing them into
    the row-sparse buffer must densify it (correctness over sparsity)."""
    emb = _embed(vocab=50, dim=4)
    emb.hybridize()
    x = mx.nd.array([[1, 2]], dtype="int32")
    with mx.autograd.record():
        emb(x).sum().backward()
    g = emb.weight.grad()
    gd = g.asnumpy()
    assert gd[1].sum() == 4 and gd[3].sum() == 0


def test_kvstore_row_sparse_pull_compressed():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.arange(40).reshape((10, 4)))
    out = mx.nd.sparse.zeros("row_sparse", (10, 4))
    kv.row_sparse_pull(3, out=out, row_ids=mx.nd.array([2, 5]))
    assert out.is_compressed()
    np.testing.assert_array_equal(out.indices.asnumpy(), [2, 5])
    np.testing.assert_allclose(out.data.asnumpy(),
                               np.arange(40).reshape(10, 4)[[2, 5]])


def test_retain_and_zero_grad_compressed():
    rs = mx.nd.sparse.row_sparse_array(
        (np.arange(8, dtype="float32").reshape(2, 4), [3, 7]), shape=(20, 4))
    kept = rs.retain(mx.nd.array([3, 11]))
    assert kept.is_compressed()
    np.testing.assert_array_equal(kept.indices.asnumpy(), [3])
    emb = _embed(vocab=40, dim=4)
    with mx.autograd.record():
        emb(mx.nd.array([[1]], dtype="int32")).sum().backward()
    p = emb.weight
    assert p.grad().indices.shape[0] == 1
    p.zero_grad()
    assert p.grad().is_compressed() and p.grad().indices.shape[0] == 0


def test_observing_grad_does_not_change_semantics():
    """asnumpy() on a compressed gradient caches a dense view but must NOT
    flip it to dense storage — lazy updates stay lazy after logging."""
    emb = _embed(vocab=100, dim=4)
    tr = mx.gluon.Trainer(emb.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        emb(mx.nd.array([[1, 2]], dtype="int32")).sum().backward()
    g = emb.weight.grad()
    _ = g.asnumpy()                      # a logging read
    assert g.is_compressed()
    w0 = emb.weight.data().asnumpy().copy()
    tr.step(1)
    changed = np.nonzero(np.abs(emb.weight.data().asnumpy() - w0)
                         .sum(axis=1))[0].tolist()
    assert sorted(changed) == [1, 2], "lazy update must survive observation"


def test_attach_grad_stype_row_sparse():
    """Raw-NDArray sparse-grad contract (reference ndarray.py:2158):
    attach_grad(stype='row_sparse') yields a compressed row_sparse grad
    with O(nnz) rows after an Embedding(sparse_grad=True) backward."""
    rng = np.random.RandomState(0)
    w = mx.nd.array(rng.randn(50, 4).astype("float32"))
    w.attach_grad(stype="row_sparse")
    idx = mx.nd.array([1, 3, 3], dtype="int32")
    with autograd.record():
        e = mx.nd.Embedding(idx, w, input_dim=50, output_dim=4,
                            sparse_grad=True)
        loss = e.sum()
    loss.backward()
    g = w.grad
    assert g.stype == "row_sparse"
    assert g.is_compressed()                      # O(nnz), not (50, 4)
    np.testing.assert_array_equal(np.sort(g.indices.asnumpy()), [1, 3])
    assert g.data.shape == (2, 4)
    ref = np.zeros((50, 4), "float32")
    ref[1] += 1.0
    ref[3] += 2.0
    np.testing.assert_allclose(g.asnumpy(), ref)


def test_attach_grad_stype_default_and_invalid():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(stype="default")
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0])
    with pytest.raises(ValueError):
        mx.nd.array([1.0]).attach_grad(stype="block_sparse")


def test_attach_grad_stype_dense_backward_densifies():
    """A dense backward into a row_sparse-attached grad still produces
    correct values (the buffer adopts a dense-equivalent result)."""
    w = mx.nd.array(np.ones((6, 2), "float32"))
    w.attach_grad(stype="row_sparse")
    with autograd.record():
        loss = (w * 3.0).sum()
    loss.backward()
    assert w.grad.stype == "row_sparse"
    np.testing.assert_allclose(w.grad.asnumpy(), np.full((6, 2), 3.0))
