"""Module behavior contracts, tranche 2 (reference
``tests/python/unittest/test_module.py`` families: input grads, reshape,
set_params validation, checkpoint resume incl. optimizer state, dtype,
forward-shape change re-bind).
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_input_grads():
    """inputs_need_grad routes dL/ddata out of the module (reference
    test_module.py:test_module_input_grads)."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params(mx.init.Xavier())
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 6))],
                            label=[mx.nd.array([0, 1, 0, 1])])
    mod.forward(batch, is_train=True)
    mod.backward()
    [dgrad] = mod.get_input_grads()
    assert dgrad.shape == (4, 6)
    assert float(np.abs(dgrad.asnumpy()).sum()) > 0


def test_module_reshape_keeps_params():
    """reshape to a new batch size without re-init (reference
    test_module_reshape)."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    w_before = mod.get_params()[0]["fc1_weight"].asnumpy()
    mod.reshape(data_shapes=[("data", (16, 6))],
                label_shapes=[("softmax_label", (16,))])
    batch = mx.io.DataBatch(data=[mx.nd.ones((16, 6))],
                            label=[mx.nd.zeros((16,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (16, 2)
    np.testing.assert_array_equal(
        mod.get_params()[0]["fc1_weight"].asnumpy(), w_before)


def test_set_params_validates_names():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Xavier())
    arg, aux = mod.get_params()
    bad = dict(arg)
    bad["not_a_param"] = mx.nd.ones((1,))
    with pytest.raises(Exception):
        mod.set_params(bad, aux, allow_extra=False)
    mod.set_params(bad, aux, allow_extra=True)      # tolerated when asked
    missing = dict(arg)
    missing.pop("fc1_weight")
    with pytest.raises(Exception):
        mod.set_params(missing, aux, allow_missing=False)
    mod.set_params(missing, aux, allow_missing=True)


def test_checkpoint_resume_continues_optimizer_state():
    """save_checkpoint + load(load_optimizer_states): momentum carries
    across the restart — trajectories with and without a restart match
    (reference test_module.py save/load family)."""
    rng = np.random.RandomState(0)
    x = rng.randn(64, 6).astype("float32")
    y = rng.randint(0, 2, 64).astype("float32")

    def make_it():
        return mx.io.NDArrayIter(x, y, batch_size=16)

    def fit(num_epoch, resume_from=None, save_to=None):
        mx.random.seed(7)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        it = make_it()
        kw = {}
        if resume_from is not None:
            sym, arg, aux = mx.model.load_checkpoint(*resume_from)
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label)
            mod.set_params(arg, aux)
            kw["arg_params"], kw["aux_params"] = arg, aux
            kw["begin_epoch"] = resume_from[1]
        mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                initializer=mx.init.Xavier(), force_init=False, **kw)
        if save_to is not None:
            mod.save_checkpoint(save_to, num_epoch,
                                save_optimizer_states=True)
        return mod

    d = tempfile.mkdtemp(prefix="modresume_")
    prefix = os.path.join(d, "ck")
    # straight run: 4 epochs
    m_straight = fit(4)
    w_straight = m_straight.get_params()[0]["fc1_weight"].asnumpy()
    # split run: 2 epochs, checkpoint (incl. optimizer state), resume via
    # Module.load(load_optimizer_states=True) for 2 more — momentum
    # carries across the restart so the trajectory MATCHES the straight run
    fit(2, save_to=prefix)
    mx.random.seed(7)
    mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
    it = make_it()
    mod2.fit(it, num_epoch=4, begin_epoch=2, optimizer="sgd",
             optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    w_resumed = mod2.get_params()[0]["fc1_weight"].asnumpy()
    np.testing.assert_allclose(w_resumed, w_straight, rtol=1e-4, atol=1e-5)


def test_module_fp16_dtype_forward():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=3)
    mod = mx.mod.Module(mx.sym.MakeLoss(mx.sym.sum(net)),
                        label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (2, 4), np.float16)],
             for_training=False)
    mod.init_params(mx.init.One())
    batch = mx.io.DataBatch(data=[mx.nd.ones((2, 4), dtype="float16")],
                            label=None)
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert np.isfinite(out.asnumpy()).all()


def test_backward_without_training_bind_raises():
    """for_training=False bind + backward = loud error (reference
    executor contract: no grad arrays were allocated)."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    batch = mx.io.DataBatch(data=[mx.nd.ones((2, 6))],
                            label=[mx.nd.zeros((2,))])
    mod.forward(batch, is_train=False)
    with pytest.raises(Exception):
        mod.backward()
