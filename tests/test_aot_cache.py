"""serving.aot: persistent AOT program cache — round trip, bitwise
contract, and the poisoning matrix (ISSUE 18 satellite: corrupt /
truncated / wrong-version entries must fall back to a fresh compile with
a ``gateway.aot_cache_fallback`` counter, never crash or serve stale)."""
import json
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.resilience import faults
from mxnet_tpu.serving import aot
from mxnet_tpu.serving.aot import (AOT_FORMAT, _MAGIC, ProgramCache,
                                   model_signature)

_M = len(_MAGIC)

ITEM = (24,)


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    faults.clear()


def _make_net():
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential(prefix="aotnet_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    return net


def _cache(tmp_path, net, salt=""):
    return ProgramCache(str(tmp_path), model_signature(net, salt=salt))


# ------------------------------------------------------------- model keys
def test_model_signature_stable_and_salted():
    a, b = _make_net(), _make_net()
    assert model_signature(a) == model_signature(b)
    assert model_signature(a) != model_signature(a, salt="geometry-v2")


def test_model_signature_tracks_param_shapes():
    a = _make_net()
    mx.random.seed(0)
    b = mx.gluon.nn.HybridSequential(prefix="aotnet_")
    with b.name_scope():
        b.add(mx.gluon.nn.Dense(32, activation="relu"))   # different width
        b.add(mx.gluon.nn.Dense(4))
    b.initialize()
    b.hybridize()
    assert model_signature(a) != model_signature(b)


# ------------------------------------------------------------- round trip
def test_compile_for_round_trip_bitwise(tmp_path):
    x = nd.array(np.random.RandomState(0).rand(4, *ITEM).astype("float32"))
    net1 = _make_net()
    c1 = _cache(tmp_path, net1)
    sig1 = net1.compile_for(x, cache=c1)
    assert c1.stores == 1 and c1.misses == 1
    y1 = net1(x).asnumpy()

    # "restarted process": same model rebuilt, loads instead of compiling
    net2 = _make_net()
    c2 = _cache(tmp_path, net2)
    sig2 = net2.compile_for(x, cache=c2)
    assert (c2.hits, c2.misses, c2.fallbacks) == (1, 0, 0)
    assert sig1 == sig2
    assert net2._cached_op._aot, "AOT executable not installed"
    y2 = net2(x).asnumpy()
    assert (y1 == y2).all(), "warm-cache outputs must be bitwise identical"


def test_compile_grid_through_cache(tmp_path):
    def make_example(b):
        return [nd.array(np.zeros((b,) + ITEM, "float32"))]

    net1 = _make_net()
    c1 = _cache(tmp_path, net1)
    sigs1 = net1.compile_grid(make_example, [1, 2, 4], cache=c1)
    assert c1.stores == 3
    net2 = _make_net()
    c2 = _cache(tmp_path, net2)
    sigs2 = net2.compile_grid(make_example, [1, 2, 4], cache=c2)
    assert c2.hits == 3 and c2.misses == 0
    assert sigs1 == sigs2
    # signatures registered as compiled — serving's zero-recompile check
    assert sigs2[2] in net2.compiled_signatures(training=False)


def test_aot_hit_skips_recompile_telemetry(tmp_path):
    x = nd.array(np.zeros((2,) + ITEM, "float32"))
    net1 = _make_net()
    net1.compile_for(x, cache=_cache(tmp_path, net1))
    net2 = _make_net()
    net2.compile_for(x, cache=_cache(tmp_path, net2))
    telemetry.enable()
    net2(x)
    counters = telemetry.snapshot()["counters"]
    assert not any(k.startswith("cachedop.recompiles")
                   for k in counters), counters


def test_load_or_build(tmp_path):
    import jax
    import jax.numpy as jnp
    pc = ProgramCache(str(tmp_path), "m1")
    fn = jax.jit(lambda a: jnp.sin(a) * 2)
    x = np.linspace(0, 1, 7, dtype="float32")
    built, meta, loaded = pc.load_or_build("sin2", fn, (x,),
                                           extra={"k": [1, 2]})
    assert not loaded and pc.stores == 1
    hit, meta2, loaded2 = pc.load_or_build("sin2", fn, (x,))
    assert loaded2 and meta2 == {"k": [1, 2]}
    assert (np.asarray(built(x)) == np.asarray(hit(x))).all()


# ------------------------------------------------------- poisoning matrix
def _seed_entry(tmp_path):
    import jax
    import jax.numpy as jnp
    pc = ProgramCache(str(tmp_path), "victim")
    fn = jax.jit(lambda a: a + 1)
    x = np.zeros((3,), "float32")
    pc.load_or_build("prog", fn, (x,))
    return pc, pc.path("prog"), fn, x


def _fallback_reasons():
    by_label = telemetry.snapshot()["counters_by_label"]
    return by_label.get("gateway.aot_cache_fallback", {})


@pytest.mark.parametrize("poison,reason", [
    (lambda raw: raw[:len(raw) // 2], "truncated"),
    (lambda raw: b"GARBAGE!" + raw[8:], "bad_magic"),
    (lambda raw: raw[:-20] + bytes(20), "crc"),
    (lambda raw: raw[:10], "truncated"),
    (lambda raw: b"", "bad_magic"),
])
def test_poisoned_entry_falls_back(tmp_path, poison, reason):
    pc, path, fn, x = _seed_entry(tmp_path)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(poison(raw))
    telemetry.enable()
    fresh = ProgramCache(str(tmp_path), "victim")
    out, meta, loaded = fresh.load_or_build("prog", fn, (x,))
    assert not loaded and fresh.fallbacks == 1
    assert (np.asarray(out(x)) == 1).all()     # fresh compile still works
    assert any(f'reason="{reason}"' in k for k in _fallback_reasons()), \
        _fallback_reasons()


def _rewrite_header(path, **patch):
    raw = open(path, "rb").read()
    magic = raw[:_M]
    (hlen,) = struct.unpack("<I", raw[_M:_M + 4])
    header = json.loads(raw[_M + 4:_M + 4 + hlen].decode())
    header.update(patch)
    blob = raw[_M + 4 + hlen:]
    hjson = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(magic + struct.pack("<I", len(hjson)) + hjson + blob)


@pytest.mark.parametrize("patch,reason", [
    ({"format": AOT_FORMAT + 1}, "format_version"),
    ({"jaxlib": "0.0.0"}, "env_jaxlib"),
    ({"backend": "tpu-v9"}, "env_backend"),
    ({"model_key": "someone-else"}, "model_key"),
    ({"name": "other-prog"}, "entry_name"),
])
def test_version_and_identity_mismatch_falls_back(tmp_path, patch, reason):
    pc, path, fn, x = _seed_entry(tmp_path)
    _rewrite_header(path, **patch)
    telemetry.enable()
    fresh = ProgramCache(str(tmp_path), "victim")
    out, meta, loaded = fresh.load_or_build("prog", fn, (x,))
    assert not loaded and fresh.fallbacks == 1
    assert (np.asarray(out(x)) == 1).all()
    assert any(f'reason="{reason}"' in k for k in _fallback_reasons()), \
        _fallback_reasons()


def test_malicious_pickle_refused(tmp_path):
    """A crc-consistent entry whose blob references a module outside the
    jax/numpy allowlist must fall back, not execute."""
    import pickle
    pc, path, fn, x = _seed_entry(tmp_path)
    evil = pickle.dumps((os.system, "echo pwned"))
    raw = open(path, "rb").read()
    (hlen,) = struct.unpack("<I", raw[_M:_M + 4])
    header = json.loads(raw[_M + 4:_M + 4 + hlen].decode())
    import zlib
    header["payload_len"] = len(evil)
    header["crc32"] = zlib.crc32(evil) & 0xffffffff
    hjson = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(raw[:_M] + struct.pack("<I", len(hjson)) + hjson + evil)
    telemetry.enable()
    fresh = ProgramCache(str(tmp_path), "victim")
    assert fresh.load("prog") is None
    assert any('reason="unpickle"' in k for k in _fallback_reasons()), \
        _fallback_reasons()


def test_missing_entry_is_plain_miss(tmp_path):
    pc = ProgramCache(str(tmp_path), "empty")
    telemetry.enable()
    assert pc.load("never-stored") is None
    assert pc.fallbacks == 0 and pc.misses == 1
    counters = telemetry.snapshot()["counters"]
    assert not any(k.startswith("gateway.aot_cache_fallback")
                   for k in counters)


def test_store_failure_is_nonfatal(tmp_path):
    """A failed commit (injected at the aot.write durable site) warns and
    returns False — serving never dies because a cache write did."""
    import jax
    import jax.numpy as jnp
    pc = ProgramCache(str(tmp_path), "m")
    fn = jax.jit(lambda a: a * 3)
    x = np.ones((2,), "float32")
    telemetry.enable()
    with faults.scope("aot.write:fail:1"):
        out, meta, loaded = pc.load_or_build("p", fn, (x,))
    assert not loaded
    assert (np.asarray(out(x)) == 3).all()     # the compile still served
    assert pc.entries() == []                  # nothing torn on disk
    counters = telemetry.snapshot()["counters"]
    assert counters.get("gateway.aot_cache_store_failures") == 1


def test_env_keyed_directories(tmp_path):
    pc = ProgramCache(str(tmp_path), "m")
    import jax
    assert f"aot-v{AOT_FORMAT}" in pc.dir
    assert jax.__version__ in pc.dir
    assert pc.dir.endswith("m")


def test_as_program_cache_passthrough(tmp_path):
    net = _make_net()
    pc = ProgramCache(str(tmp_path), "m")
    assert aot.as_program_cache(None, net) is None
    assert aot.as_program_cache(pc, net) is pc
    derived = aot.as_program_cache(str(tmp_path), net, salt="s")
    assert isinstance(derived, ProgramCache)
    assert derived.model_key == model_signature(net, salt="s")
