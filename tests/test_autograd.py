"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py
and test_higher_order_grad.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array(np.random.rand(3, 4).astype("float32"))
    w = nd.array(np.random.rand(5, 4).astype("float32"))
    x.attach_grad(); w.attach_grad()
    with autograd.record():
        y = nd.FullyConnected(x, w, no_bias=True, num_hidden=5)
        loss = (y * y).sum()
    loss.backward()
    expect_w = 2 * (x.asnumpy().T @ (x.asnumpy() @ w.asnumpy().T)).T
    assert np.allclose(w.grad.asnumpy(), expect_w, atol=1e-4)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert np.allclose(x.grad.asnumpy(), [30.0, 60.0])


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 4 * x.asnumpy())


def test_pause_and_training_flags():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
            z = x * 2  # not recorded
        y = x * 3
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0])
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * x * x
    y.backward()
    assert np.allclose(g.asnumpy(), [12.0])


def test_multiple_heads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y1 = x * 2
        y2 = x * 3
    autograd.backward([y1, y2])
    assert np.allclose(x.grad.asnumpy(), [5.0, 5.0])


def test_grad_function():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    dx = autograd.grad(y, x)
    assert np.allclose(dx.asnumpy(), [6.0])
    # x.grad untouched by grad()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_higher_order():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x).sum()
        g1 = autograd.grad(y, x, create_graph=True)
        z = g1.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), -np.sin(x.asnumpy()), atol=1e-5)


def test_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            x, = self.saved_tensors
            return 2 * x * dy

    x = nd.array([1.0, 4.0])
    x.attach_grad()
    with autograd.record():
        y = Square()(x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [4.0])


def test_stop_gradient_op():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * 3) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_softmax_output_loss_grad():
    x = nd.array(np.random.rand(4, 3).astype("float32"))
    label = nd.array([0, 1, 2, 1])
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    sm = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(1, keepdims=True)
    oh = np.eye(3, dtype="float32")[label.asnumpy().astype(int)]
    assert np.allclose(x.grad.asnumpy(), sm - oh, atol=1e-5)


def test_dropout_modes():
    x = nd.ones((100, 100))
    # predict mode: identity
    y = nd.Dropout(x, p=0.5)
    assert np.allclose(y.asnumpy(), x.asnumpy())
    with autograd.record():
        z = nd.Dropout(x, p=0.5)
    frac = (z.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_batchnorm_moving_stats_update():
    x = nd.array(np.random.randn(8, 4, 5, 5).astype("float32") * 3 + 1)
    gamma, beta = nd.ones((4,)), nd.zeros((4,))
    mm, mv = nd.zeros((4,)), nd.ones((4,))
    with autograd.record():
        y = nd.BatchNorm(x, gamma, beta, mm, mv, momentum=0.5, fix_gamma=False)
    # moving stats were updated toward batch stats
    assert not np.allclose(mm.asnumpy(), 0)
    # normalized output in training mode
    assert abs(y.asnumpy().mean()) < 0.1
    # inference mode uses moving stats
    y2 = nd.BatchNorm(x, gamma, beta, nd.zeros((4,)), nd.ones((4,)),
                      fix_gamma=False, eps=1e-10)
    assert np.allclose(y2.asnumpy(), x.asnumpy(), atol=1e-3)
