"""Gluon behavior contracts, tranche 2 (reference
``tests/python/unittest/test_gluon.py`` families not yet pinned:
parameter sharing/tying, Constant params, save/load variants,
SymbolBlock.imports, grad_req setattr, deferred-init errors, cast,
apply/children, Sequential indexing, name uniqueness, summary).
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_parameter_sharing_ties_weights():
    """reference test_gluon.py test_parameter_sharing: blocks built with
    params=other.collect_params() train as ONE set of weights."""
    a = gluon.nn.Dense(4, in_units=3, prefix="tied_")
    b = gluon.nn.Dense(4, in_units=3, prefix="tied_",
                       params=a.collect_params())
    a.initialize()
    x = mx.nd.ones((2, 3))
    np.testing.assert_array_equal(a(x).asnumpy(), b(x).asnumpy())
    # updating through a is visible through b
    a.weight.set_data(mx.nd.ones((4, 3)) * 2)
    np.testing.assert_array_equal(b(x).asnumpy(), a(x).asnumpy())
    assert a.weight is b.weight or \
        a.weight.data() is b.weight.data()


def test_constant_parameter_receives_no_gradient():
    class WithConst(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.const = self.params.get_constant(
                    "const", np.asarray([[1.0, 2.0], [3.0, 4.0]],
                                        "float32"))
                self.dense = gluon.nn.Dense(2, in_units=2)

        def hybrid_forward(self, F, x, const):
            return self.dense(x) + F.dot(x, const)

    net = WithConst()
    net.initialize()
    x = mx.nd.ones((3, 2))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    # constant took part in forward but holds no/zero grad
    g = net.const.grad() if net.const.grad_req != "null" else None
    assert g is None or float(np.abs(g.asnumpy()).sum()) == 0.0
    assert float(np.abs(net.dense.weight.grad().asnumpy()).sum()) > 0


def test_save_load_parameters_roundtrip_and_flags():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(5, in_units=3), gluon.nn.Dense(2, in_units=5))
    net.initialize()
    x = mx.nd.ones((1, 3))
    want = net(x).asnumpy()
    d = tempfile.mkdtemp(prefix="gluonsl_")
    path = os.path.join(d, "p.params")
    net.save_parameters(path)

    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(5, in_units=3), gluon.nn.Dense(2, in_units=5))
    net2.load_parameters(path)
    np.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)

    # ignore_extra: loading into a net with FEWER params
    net3 = gluon.nn.HybridSequential()
    net3.add(gluon.nn.Dense(5, in_units=3))
    with pytest.raises(Exception):
        net3.load_parameters(path)        # extra keys must raise by default
    net3.load_parameters(path, ignore_extra=True)

    # allow_missing: loading into a net with MORE params
    net4 = gluon.nn.HybridSequential()
    net4.add(gluon.nn.Dense(5, in_units=3), gluon.nn.Dense(2, in_units=5),
             gluon.nn.Dense(7, in_units=2))
    with pytest.raises(Exception):
        net4.load_parameters(path)
    net4.collect_params().initialize()
    net4.load_parameters(path, allow_missing=True)


def test_symbolblock_imports_runs_exported_model():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="relu", in_units=3),
            gluon.nn.Dense(2, in_units=4))
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 3))
    want = net(x).asnumpy()
    d = tempfile.mkdtemp(prefix="symblk_")
    prefix = os.path.join(d, "m")
    net.export(prefix)
    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params")
    np.testing.assert_allclose(sb(x).asnumpy(), want, rtol=1e-6)


def test_grad_req_setattr_disables_gradients():
    net = gluon.nn.Dense(3, in_units=2)
    net.initialize()
    net.bias.grad_req = "null"        # freeze ONLY the bias
    x = mx.nd.ones((2, 2))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    assert float(np.abs(net.weight.grad().asnumpy()).sum()) > 0
    with pytest.raises(Exception):
        net.bias.grad()               # no gradient buffer for null req
    # freezing everything makes backward a loud error (stricter than the
    # reference's silent no-op — documented eager error semantics)
    net.weight.grad_req = "null"
    with mx.autograd.record():
        loss = net(x).sum()
    with pytest.raises(ValueError):
        loss.backward()


def test_deferred_init_access_raises():
    net = gluon.nn.Dense(3)           # in_units unknown
    net.initialize()
    from mxnet_tpu.gluon.parameter import DeferredInitializationError
    with pytest.raises(DeferredInitializationError):
        net.weight.data()
    net(mx.nd.ones((2, 5)))           # materializes
    assert net.weight.shape == (3, 5)


def test_uninitialized_forward_raises():
    net = gluon.nn.Dense(3, in_units=2)
    with pytest.raises(Exception):
        net(mx.nd.ones((1, 2)))


def test_block_cast_changes_param_dtype():
    net = gluon.nn.Dense(3, in_units=2)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().dtype == np.float16
    out = net(mx.nd.ones((2, 2), dtype="float16"))
    assert out.dtype == np.float16


def test_apply_and_children_iteration():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4), gluon.nn.Dense(2))
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert seen.count("Dense") == 2
    assert len(list(net)) == 2
    assert isinstance(net[1], gluon.nn.Dense)


def test_sequential_prefix_uniqueness():
    a = gluon.nn.Dense(2)
    b = gluon.nn.Dense(2)
    assert a.prefix != b.prefix
    names = set(a.collect_params()) & set(b.collect_params())
    assert not names, names


def test_summary_prints_shapes():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    import io as _io
    import contextlib
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        net.summary(mx.nd.ones((3, 5)))
    text = buf.getvalue()
    assert "Dense" in text
    # total parameter count = 5*4+4 + 4*2+2 = 34
    assert "34" in text, text


def test_hybridize_then_unhybridized_numerics_match():
    mx.random.seed(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="tanh"), gluon.nn.Dense(3))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 6).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize(static_alloc=True, static_shape=True)   # flags accepted
    np.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-6)


def test_parameter_reset_ctx_and_list_ctx():
    net = gluon.nn.Dense(2, in_units=2)
    net.initialize(ctx=mx.cpu(0))
    assert net.weight.list_ctx() == [mx.cpu(0)]
    net.collect_params().reset_ctx(mx.cpu(0))
    out = net(mx.nd.ones((1, 2)))
    assert out.shape == (1, 2)


def test_zero_grad_clears_accumulated():
    net = gluon.nn.Dense(2, in_units=2)
    net.initialize()
    x = mx.nd.ones((1, 2))
    with mx.autograd.record():
        net(x).sum().backward()
    assert float(np.abs(net.weight.grad().asnumpy()).sum()) > 0
    net.collect_params().zero_grad()
    assert float(np.abs(net.weight.grad().asnumpy()).sum()) == 0


def test_lambda_blocks():
    """reference test_gluon.py test_lambda: Lambda + HybridLambda."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.HybridLambda("tanh"),
            gluon.nn.Lambda(lambda x: x * 2))
    x = mx.nd.array([[0.5, -0.5]])
    np.testing.assert_allclose(net(x).asnumpy(), np.tanh([[0.5, -0.5]]) * 2,
                               rtol=1e-6)


def test_multi_input_hybrid_block_with_none():
    class Two(gluon.HybridBlock):
        def hybrid_forward(self, F, a, b=None):
            return a * 2 if b is None else a + b

    net = Two()
    net.hybridize()
    a = mx.nd.ones((2, 2))
    b = mx.nd.ones((2, 2)) * 2            # a+b=3 ≠ a*2=2: the two traces
    np.testing.assert_array_equal(net(a).asnumpy(), np.full((2, 2), 2.0))
    np.testing.assert_array_equal(net(a, b).asnumpy(), np.full((2, 2), 3.0))
