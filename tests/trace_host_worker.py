"""Subprocess entry for the two-simulated-host *tracing* drills
(tests/test_trace.py and the ci trace stage).

Each invocation is one simulated host (``--host h/H``) running with the
full observability stack armed through the environment alone —
``MXNET_TELEMETRY=1`` (bus), ``MXNET_TRACE_DIR`` (per-host event stream
for the merged chrome trace), ``MXNET_FLIGHT_DIR`` (post-mortem dumps),
and ``MXNET_SANITIZE=collectives`` + ``MXNET_SANITIZE_DIR`` (the PR 10
cross-check whose violation funnel triggers the flight dump).

The script runs ``--steps`` SPMD train steps (each minting a step trace
context that streams to ``trace-<h>.jsonl``), then a sharded checkpoint
save and a final sanitizer sync.  A clean run exits 0 and must leave NO
flight dump; ``--diverge-at N`` plants the PR 10 divergence (this host
issues a pipeline schedule where its peer issues a train step), which
must exit 3 AND leave a ``flight-<h>-*.json`` post-mortem naming this
host's last ring events.  Exit 4 = stall timeout.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

BATCH = 16
FEATS = 8
N_CLASSES = 4


def build_trainer(seed=0):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import (FunctionalOptimizer, SPMDTrainer,
                                    make_mesh)
    mx.random.seed(seed)
    np.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="trc_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu", in_units=FEATS),
                mx.gluon.nn.Dense(N_CLASSES, in_units=16))
    net.initialize()
    return SPMDTrainer(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                       FunctionalOptimizer("sgd", 1e-2),
                       make_mesh(n_devices=4, dp=2, tp=2), nan_guard=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True,
                    help="shared dir: trace streams + flight dumps + "
                         "fingerprint streams + checkpoint")
    ap.add_argument("--host", required=True, help="h/H simulated identity")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--diverge-at", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=20.0)
    args = ap.parse_args(argv)

    # the whole stack arms from env, BEFORE any mxnet_tpu numerics import —
    # exactly how a production launcher would opt a pod in
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ["MXNET_TRACE_DIR"] = args.dir
    os.environ["MXNET_FLIGHT_DIR"] = args.dir
    os.environ["MXNET_SANITIZE"] = "collectives"
    os.environ["MXNET_CKPT_HOST"] = args.host
    os.environ["MXNET_SANITIZE_DIR"] = args.dir

    import numpy as np
    import jax.numpy as jnp
    from mxnet_tpu import telemetry
    from mxnet_tpu.analysis import divergence as div
    from mxnet_tpu.analysis import sanitizer as san
    from mxnet_tpu.parallel import (CommitBarrierTimeout,
                                    SPMDCheckpointManager, pipeline)
    from mxnet_tpu.telemetry import flight, trace

    assert telemetry.is_enabled(), "MXNET_TELEMETRY=1 must arm the bus"
    assert trace.trace_dir() == args.dir, "MXNET_TRACE_DIR must arm streaming"
    assert flight.enabled, "flight recorder is on by default"
    host, _, host_count = args.host.partition("/")
    host, host_count = int(host), int(host_count)

    tr = build_trainer()
    rng = np.random.RandomState(7)
    batches = [(rng.randn(BATCH, FEATS).astype("float32"),
                rng.randint(0, N_CLASSES, BATCH).astype("float32"))
               for _ in range(args.steps)]
    try:
        for i, (x, y) in enumerate(batches):
            if args.diverge_at is not None and i == args.diverge_at:
                from mxnet_tpu.parallel import make_mesh
                mesh = make_mesh(n_devices=8, pp=8)
                pipeline.gpipe(lambda p, xx: xx * p.sum(),
                               jnp.ones((8, 4)), jnp.ones((16, 4)), mesh, 4)
                print(f"DIVERGED host={host} at step {i}", flush=True)
            else:
                tr.step(x, y)
        mgr = SPMDCheckpointManager(args.dir, host_index=host,
                                    host_count=host_count,
                                    barrier_timeout_s=args.timeout)
        mgr.save(tr._t, tr)
        div.sync("post-save", timeout_s=args.timeout)
    except san.CollectiveDivergenceError as e:
        # sanitizer._violation already wrote the flight dump before raising
        print(f"DIVERGENCE host={host}: {e}", flush=True)
        print(f"FLIGHT-DUMP host={host}: {flight.last_dump_path()}",
              flush=True)
        return 3
    except (san.CollectiveStallTimeout, CommitBarrierTimeout) as e:
        print(f"STALL-TIMEOUT host={host}: {e}", flush=True)
        return 4
    print(f"CLEAN host={host} steps={tr._t} "
          f"events={telemetry.snapshot()['n_events']} "
          f"violations={san.stats()['violations']}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
