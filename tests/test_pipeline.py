"""Pipeline parallelism tests (GPipe schedule over the pp mesh axis)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import device_mesh, gpipe


def _stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked(n_stage, d, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(n_stage, d, d) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.randn(n_stage, d) * 0.1, jnp.float32)}


def _sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = _stage({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_gpipe_matches_sequential(n_micro):
    n_stage, d, batch = 4, 16, 8
    mesh = device_mesh({"dp": 2, "pp": 4})
    params = _stacked(n_stage, d)
    x = jnp.asarray(np.random.RandomState(1).randn(batch, d), jnp.float32)
    out = gpipe(_stage, params, x, mesh, n_micro)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_gpipe_eight_stages():
    mesh = device_mesh({"pp": 8})
    params = _stacked(8, 8)
    x = jnp.ones((4, 8), jnp.float32) * 0.1
    out = gpipe(_stage, params, x, mesh, 2)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_gpipe_gradients_match():
    n_stage, d, batch = 4, 8, 8
    mesh = device_mesh({"dp": 2, "pp": 4})
    params = _stacked(n_stage, d)
    x = jnp.asarray(np.random.RandomState(2).randn(batch, d), jnp.float32)

    def loss_pipe(p):
        return gpipe(_stage, p, x, mesh, 2).sum()

    def loss_seq(p):
        return _sequential(p, x).sum()

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    np.testing.assert_allclose(np.asarray(gp["w"]), np.asarray(gs["w"]),
                               rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gp["b"]), np.asarray(gs["b"]),
                               rtol=5e-4, atol=5e-5)


def test_gpipe_batch_divisibility_check():
    mesh = device_mesh({"pp": 8})
    params = _stacked(8, 4)
    with pytest.raises(AssertionError):
        gpipe(_stage, params, jnp.ones((5, 4)), mesh, 2)


def _loss(yp, yt):
    return jnp.mean((yp - yt) ** 2)


@pytest.mark.parametrize("n_micro", [4, 8])  # covers stash = N and stash = 2S
def test_1f1b_matches_sequential_grad(n_micro):
    from mxnet_tpu.parallel import pipeline_train_1f1b
    n_stage, d, mb = 4, 16, 2
    mesh = device_mesh({"pp": n_stage}, devices=jax.devices()[:n_stage])
    params = _stacked(n_stage, d)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n_micro * mb, d), jnp.float32)
    y = jnp.asarray(rng.randn(n_micro * mb, d), jnp.float32)

    def ref(params, x):
        return jnp.mean((_sequential(params, x) - y) ** 2)

    want_loss, want_grads = jax.value_and_grad(ref)(params, x)
    want_dx = jax.grad(lambda xx: ref(params, xx))(x)
    loss, grads, dx = jax.jit(lambda p, xx, yy: pipeline_train_1f1b(
        _stage, _loss, p, xx, yy, mesh, n_micro))(params, x, y)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=1e-4, atol=1e-6)


def test_1f1b_sgd_step_converges():
    from mxnet_tpu.parallel import pipeline_train_1f1b
    n_stage, d, mb, n_micro = 4, 8, 2, 4
    mesh = device_mesh({"pp": n_stage}, devices=jax.devices()[:n_stage])
    params = _stacked(n_stage, d, seed=3)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(n_micro * mb, d), jnp.float32)
    y = jnp.asarray(rng.randn(n_micro * mb, d) * 0.1, jnp.float32)

    @jax.jit
    def step(params):
        loss, grads, _ = pipeline_train_1f1b(_stage, _loss, params, x, y,
                                             mesh, n_micro)
        new = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
        return loss, new

    losses = []
    for _ in range(20):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_1f1b_log_loss_no_nan_from_warmup_ticks():
    # Regression: warmup ticks evaluate the loss VJP on garbage activations;
    # with a log-style loss those are non-finite and multiplicative masking
    # (NaN * 0 = NaN) used to poison every stage's gradients.
    from mxnet_tpu.parallel import pipeline_train_1f1b
    n_stage, d, mb, n_micro = 4, 8, 2, 4
    mesh = device_mesh({"pp": n_stage}, devices=jax.devices()[:n_stage])
    params = _stacked(n_stage, d, seed=5)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(n_micro * mb, d), jnp.float32)
    y = jnp.asarray(rng.rand(n_micro * mb, d), jnp.float32)

    def log_loss(yp, yt):
        return -jnp.mean(yt * jnp.log(jnp.abs(yp)))  # -inf at yp == 0

    loss, grads, dx = jax.jit(lambda p, xx, yy: pipeline_train_1f1b(
        _stage, log_loss, p, xx, yy, mesh, n_micro))(params, x, y)

    def ref(params):
        return log_loss(_sequential(params, x), y)

    want_loss, want_grads = jax.value_and_grad(ref)(params)
    assert np.isfinite(np.asarray(grads["w"])).all()
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(want_grads["w"]),
                               rtol=1e-4, atol=1e-6)


# ------------------------------------------------- interleaved (virtual) 1F1B
from mxnet_tpu.parallel import gpipe_interleaved
from mxnet_tpu.parallel.pipeline import _simulate_interleaved


def _mesh(n):
    return device_mesh({"pp": n}, devices=jax.devices()[:n])


@pytest.mark.parametrize("v,n_micro", [(1, 4), (2, 4), (2, 3), (3, 5)])
def test_interleaved_matches_sequential(v, n_micro):
    S, d = 4, 6
    mesh = _mesh(S)
    params = _stacked(S * v, d, seed=7)     # per-stage DISTINCT params
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n_micro * 2, d), jnp.float32)
    out = gpipe_interleaved(_stage, params, x, mesh, n_micro, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=2e-5, atol=2e-6)


def test_interleaved_gradients_match_sequential():
    S, v, d = 4, 2, 5
    mesh = _mesh(S)
    params = _stacked(S * v, d, seed=9)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, d), jnp.float32)

    def loss(p):
        return jnp.sum(gpipe_interleaved(_stage, p, x, mesh, 4, v) ** 2)

    def loss_ref(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g1 = jax.grad(loss)(params)
    g2 = jax.grad(loss_ref)(params)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g1["b"]), np.asarray(g2["b"]),
                               rtol=1e-4, atol=1e-6)


def test_interleaved_v1_equals_gpipe():
    S, d = 4, 6
    mesh = _mesh(S)
    params = _stacked(S, d, seed=3)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, d), jnp.float32)
    a = gpipe_interleaved(_stage, params, x, mesh, 4, 1)
    b = gpipe(_stage, params, x, mesh, 4)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_interleaved_schedule_is_near_ideal():
    """Work conservation + tick bound: every stage-visit happens exactly
    once, and the schedule finishes within one chunk-round of the perfect
    pipelining bound of N*v + (S-1) ticks."""
    S, N = 4, 8
    proc_i, _, _, n_slots = _simulate_interleaved(S, 2, N)
    total_slots_i = sum(1 for row in proc_i for e in row if e is not None)
    assert total_slots_i == S * 2 * N        # every stage-visit happens once
    assert len(proc_i) <= N * 2 + 2 * S
    # LIFO slot reuse keeps the activation buffer at true peak concurrency
    assert n_slots <= 3


def test_interleaved_odd_batches_and_slots():
    S, v, d = 2, 3, 4
    mesh = _mesh(S)
    params = _stacked(S * v, d, seed=11)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, d), jnp.float32)
    out = gpipe_interleaved(_stage, params, x, mesh, 3, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=2e-5, atol=2e-6)


# ------------------------------------------- heterogeneous stage functions
def _hetero_stage(p, x, k):
    """Per-stage distinct ARCHITECTURE: even stages tanh, odd stages
    leaky-relu — selected by the traced logical stage index."""
    h = x @ p["w"] + p["b"]
    return jax.lax.switch(k % 2, [jnp.tanh,
                                  lambda z: jnp.where(z > 0, z, 0.2 * z)], h)


def _hetero_sequential(params, x):
    for i in range(params["w"].shape[0]):
        h = x @ params["w"][i] + params["b"][i]
        x = np.tanh(h) if i % 2 == 0 else np.where(h > 0, h, 0.2 * h)
    return x


def test_heterogeneous_stages_gpipe():
    S, d = 4, 5
    mesh = _mesh(S)
    params = _stacked(S, d, seed=21)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, d), jnp.float32)
    out = gpipe(_hetero_stage, params, x, mesh, 4)
    np.testing.assert_allclose(np.asarray(out),
                               _hetero_sequential(params, np.asarray(x)),
                               rtol=2e-5, atol=2e-6)


def test_heterogeneous_stages_interleaved():
    S, v, d = 2, 2, 5
    mesh = _mesh(S)
    params = _stacked(S * v, d, seed=22)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(6, d), jnp.float32)
    out = gpipe_interleaved(_hetero_stage, params, x, mesh, 3, v)
    np.testing.assert_allclose(np.asarray(out),
                               _hetero_sequential(params, np.asarray(x)),
                               rtol=2e-5, atol=2e-6)


def test_heterogeneous_stages_1f1b_grads():
    from mxnet_tpu.parallel import pipeline_train_1f1b
    S, d = 4, 4
    mesh = _mesh(S)
    params = _stacked(S, d, seed=23)
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(4, d), jnp.float32)
    y = jnp.asarray(rng.randn(4, d), jnp.float32)
    mse = lambda yp, yt: jnp.mean((yp - yt) ** 2)  # noqa: E731
    loss, grads, dx = pipeline_train_1f1b(_hetero_stage, mse, params, x, y,
                                          mesh, n_microbatches=2)

    def ref_of(p, xx):
        out = xx
        for i in range(S):
            h = out @ p["w"][i] + p["b"][i]
            out = jnp.tanh(h) if i % 2 == 0 else jnp.where(h > 0, h, 0.2 * h)
        return mse(out, y)

    want_loss, want_grads = jax.value_and_grad(ref_of)(params, x)
    want_dx = jax.grad(lambda xx: ref_of(params, xx))(x)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=1e-4, atol=1e-6)


def test_defaulted_third_param_is_not_stage_idx():
    """A homogeneous stage_fn with a defaulted third parameter must keep
    its default — only 3 required positionals opt into the stage index."""
    from mxnet_tpu.parallel.pipeline import _stage_caller
    seen = {}

    def stage(p, x, train=False):
        seen["train"] = train
        return x

    call = _stage_caller(stage)
    call({}, jnp.ones(2), 5)
    assert seen["train"] is False
